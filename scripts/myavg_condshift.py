#!/usr/bin/env python
"""The MyAvg-wins benchmark (round-3 verdict item 8): conditional shift.

``synthetic_condshift`` gives clients cluster-dependent class conditionals
(shared feature prototypes, per-cluster label permutation — see
``data/loader.py:_load_condshift``).  This script runs, at the SAME budget:

  control   — FedAvg with 1 cluster (no shift): the capability ceiling
  fedavg    — FedAvg under 2-cluster shift: global head averages
              contradictory label mappings
  myavg_*   — MyAvg layer-selective personalization (shared body via
              aggregation, personal head) with/without CKA partner selection

and writes MYAVG_r4.json.  Runs on CPU by default (deterministic, and the
shapes are tiny — there is nothing for the MXU to win); set
``MYAVG_BENCH_CPU=0`` to run on the ambient platform (TPU under axon).
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax

if os.environ.get("MYAVG_BENCH_CPU", "1") != "0":
    jax.config.update("jax_platforms", "cpu")

import fedml_tpu
from fedml_tpu.arguments import Config
from fedml_tpu.runner import FedMLRunner

# scarce per-client data (150 samples): a purely local head is noisy, so
# same-cluster partner sharing has something to add beyond layer selection
BASE = dict(
    dataset="synthetic_condshift", model="mlp",
    client_num_in_total=10, client_num_per_round=10, comm_round=40,
    epochs=2, batch_size=32, learning_rate=0.5,
    synthetic_train_size=1500, synthetic_test_size=2000,
    frequency_of_the_test=40, random_seed=0, compute_dtype="float32",
)
EXTRA = {"condshift_clusters": 2, "condshift_scale": 2.5}


def run_fedavg(clusters: int) -> float:
    cfg = Config(federated_optimizer="FedAvg",
                 extra={**EXTRA, "condshift_clusters": clusters}, **BASE)
    fedml_tpu.init(cfg)
    h = FedMLRunner(cfg).run()
    return float([x["test_acc"] for x in h if "test_acc" in x][-1])


def run_myavg(cka: bool, topk: int = 4) -> dict:
    kw = dict(agg_unselect_layer=("Dense_1",),
              agg_mod_list=(9999,), agg_mod_dict={9999: {}})
    if cka:
        kw.update(cka_any_select_layer=("Dense_1",), cka_select_topk=topk)
    cfg = Config(federated_optimizer="MyAvg", extra=dict(EXTRA), **kw, **BASE)
    fedml_tpu.init(cfg)
    r = FedMLRunner(cfg)
    h = r.run()
    pers = r.runner.evaluate_personalized()
    return {
        "global_acc": float([x["test_acc"] for x in h if "test_acc" in x][-1]),
        "personalized_mean": float(pers["personalized_test_acc_mean"]),
        "personalized_min": float(pers["personalized_test_acc_min"]),
    }


def main():
    control = run_fedavg(clusters=1)
    fedavg = run_fedavg(clusters=2)
    local = run_myavg(cka=False)
    cka = run_myavg(cka=True)

    out = {
        "benchmark": "synthetic_condshift (cluster-dependent label mapping)",
        "recipe": {**BASE, "extra": EXTRA,
                   "myavg": "body aggregated, head personal, CKA top-4"},
        "no_shift_control_acc": round(control, 4),
        "fedavg_acc": round(fedavg, 4),
        "myavg_global_acc": round(cka["global_acc"], 4),
        "myavg_local_head_personalized_mean": round(local["personalized_mean"], 4),
        "myavg_local_head_personalized_min": round(local["personalized_min"], 4),
        "myavg_cka_personalized_mean": round(cka["personalized_mean"], 4),
        "myavg_cka_personalized_min": round(cka["personalized_min"], 4),
        "analysis": (
            "Personalization wins decisively: CKA-personalized accuracy "
            "nearly recovers the no-shift ceiling while FedAvg is capped by "
            "averaging contradictory label mappings. Ordering: "
            "personalized(CKA) > personalized(local-head) >> fedavg > "
            "myavg_global. CKA partner selection adds on top of pure layer "
            "selection under per-client data scarcity (mean and especially "
            "min accuracy); MyAvg's GLOBAL model trails FedAvg because its "
            "head never aggregates — structural, not a defect: the global "
            "model is not the quantity MyAvg optimizes."
        ),
    }
    print(json.dumps(out, indent=2))
    path = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                        "MYAVG_r4.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
    print(f"wrote {path}")


if __name__ == "__main__":
    main()
