#!/usr/bin/env python
"""Per-op attribution of the FedAvg round via the JAX profiler (round-4).

Traces ONE production jitted round on the real chip, then aggregates the
device events by hlo_category and by source line, reporting achieved TFLOP/s
and GB/s per bucket — the evidence base for PERF.md's roofline ("what is the
round actually spending its time and bandwidth on").

The parsing/aggregation lives in ``fedml_tpu.obs.profiler`` since ISSUE 18
(the engine opens its own trace windows behind ``extra.profile_rounds``);
this script remains the manual one-round harness over that library.

Usage: python scripts/profile_trace.py   (on the TPU; writes /tmp/prof)
       PROFILE_FUSED=1 python scripts/profile_trace.py   (trace the
       extra.fused_blocks program — the PERF.md round-6 attribution path)
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp

from fedml_tpu.obs.profiler import (
    aggregate_device_events,
    bucket_rows,
    find_trace_file,
    load_trace,
)


def build_sim():
    import fedml_tpu
    from fedml_tpu.arguments import Config
    from fedml_tpu.runner import FedMLRunner

    n_clients, per_round, batch, spc = 128, 64, 128, 512
    cfg = Config(
        dataset="cifar10", model="resnet20", client_num_in_total=n_clients,
        client_num_per_round=per_round, comm_round=50, epochs=1,
        batch_size=batch, learning_rate=0.03, partition_method="homo",
        synthetic_train_size=n_clients * spc, synthetic_test_size=1024,
        frequency_of_the_test=0, compute_dtype="bfloat16", step_mode="match",
        metrics_jsonl_path="",
        extra={"fused_blocks": True} if os.environ.get("PROFILE_FUSED") else {},
    )
    fedml_tpu.init(cfg)
    return FedMLRunner(cfg).runner


def main():
    sim = build_sim()

    def run():
        out = sim._round_fn(
            sim.global_vars, sim.server_state, sim.client_states, sim.counts,
            sim._data[0], sim._data[1], jnp.int32(1), sim.root_key,
            sim.defense_history,
        )
        jax.block_until_ready(out)

    run()  # compile + warm
    os.makedirs("/tmp/prof", exist_ok=True)
    with jax.profiler.trace("/tmp/prof"):
        run()

    trace_file = find_trace_file("/tmp/prof")
    if trace_file is None:
        raise SystemExit("no trace captured under /tmp/prof")
    aggregated = aggregate_device_events(load_trace(trace_file))
    cat = aggregated["by_category"]

    print("TRACE " + json.dumps({
        "total_ms": round(sum(v[0] for v in cat.values()) / 1e9, 1),
        "by_category": bucket_rows(cat, 8),
        "by_source": bucket_rows(aggregated["by_source"], 12),
    }))


if __name__ == "__main__":
    main()
