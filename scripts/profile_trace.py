#!/usr/bin/env python
"""Per-op attribution of the FedAvg round via the JAX profiler (round-4).

Traces ONE production jitted round on the real chip, then aggregates the
device events by hlo_category and by source line, reporting achieved TFLOP/s
and GB/s per bucket — the evidence base for PERF.md's roofline ("what is the
round actually spending its time and bandwidth on").

Usage: python scripts/profile_trace.py   (on the TPU; writes /tmp/prof)
       PROFILE_FUSED=1 python scripts/profile_trace.py   (trace the
       extra.fused_blocks program — the PERF.md round-6 attribution path)
"""
import collections
import glob
import gzip
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp


def build_sim():
    import fedml_tpu
    from fedml_tpu.arguments import Config
    from fedml_tpu.runner import FedMLRunner

    n_clients, per_round, batch, spc = 128, 64, 128, 512
    cfg = Config(
        dataset="cifar10", model="resnet20", client_num_in_total=n_clients,
        client_num_per_round=per_round, comm_round=50, epochs=1,
        batch_size=batch, learning_rate=0.03, partition_method="homo",
        synthetic_train_size=n_clients * spc, synthetic_test_size=1024,
        frequency_of_the_test=0, compute_dtype="bfloat16", step_mode="match",
        metrics_jsonl_path="",
        extra={"fused_blocks": True} if os.environ.get("PROFILE_FUSED") else {},
    )
    fedml_tpu.init(cfg)
    return FedMLRunner(cfg).runner


def main():
    sim = build_sim()

    def run():
        out = sim._round_fn(
            sim.global_vars, sim.server_state, sim.client_states, sim.counts,
            sim._data[0], sim._data[1], jnp.int32(1), sim.root_key,
            sim.defense_history,
        )
        jax.block_until_ready(out)

    run()  # compile + warm
    os.makedirs("/tmp/prof", exist_ok=True)
    with jax.profiler.trace("/tmp/prof"):
        run()

    latest = max(glob.glob("/tmp/prof/plugins/profile/*/"), key=os.path.getmtime)
    trace_file = glob.glob(os.path.join(latest, "*.trace.json.gz"))[0]
    with gzip.open(trace_file) as f:
        tr = json.load(f)

    pids = {e["pid"]: e["args"].get("name", "")
            for e in tr.get("traceEvents", [])
            if e.get("ph") == "M" and e.get("name") == "process_name"}
    dev_pids = {p for p, n in pids.items() if "TPU" in n or "device" in n.lower()}

    cat = collections.defaultdict(lambda: [0, 0, 0, 0])   # ps, flops, bytes, n
    src = collections.defaultdict(lambda: [0, 0, 0, 0])
    for e in tr.get("traceEvents", []):
        a = e.get("args") or {}
        if e.get("ph") == "X" and e.get("pid") in dev_pids and "hlo_category" in a:
            c = a["hlo_category"]
            if c == "while":
                continue
            d = int(a.get("device_duration_ps", 0))
            fl = int(a.get("model_flops", 0) or 0)
            by = int(a.get("raw_bytes_accessed", 0) or 0)
            for bucket, key in ((cat, c), (src, a.get("source", "?"))):
                bucket[key][0] += d
                bucket[key][1] += fl
                bucket[key][2] += by
                bucket[key][3] += 1

    def rows(bucket, top):
        out = []
        for k, (d, fl, by, n) in sorted(bucket.items(), key=lambda kv: -kv[1][0])[:top]:
            out.append({
                "key": k, "ms": round(d / 1e9, 2), "n": n,
                "tflops": round(fl / (d / 1e12) / 1e12, 2) if d else 0,
                "gbps": round(by / (d / 1e12) / 1e9, 1) if d else 0,
            })
        return out

    print("TRACE " + json.dumps({
        "total_ms": round(sum(v[0] for v in cat.values()) / 1e9, 1),
        "by_category": rows(cat, 8),
        "by_source": rows(src, 12),
    }))


if __name__ == "__main__":
    main()
