#!/usr/bin/env python
"""Microbenchmark the client-vmapped ResNet-20 conv regime on the real chip.

Small repeated jit calls with identical inputs mis-time over the tunneled
device (impossible >100% MFU observed), so every probe here runs its op in a
jitted lax.scan CHAIN of `reps` iterations whose input depends on the previous
output — the device must execute them sequentially, and one dispatch covers
the whole chain.  Per-op time = chain time / reps.

Times, for each ResNet-20 stage shape at n=64 clients x batch 128:
  conv_g    — grouped conv (feature_group_count=n): the vmapped-model form
  mm_eq     — batched matmul over im2col-SHAPED operands.  NOTE: this
              materializes the (M, 9*cin) patch matrix, i.e. 9x the input
              traffic of a direct conv, and uses square K=N=9*cin (chain
              shape stability) — a reference point for the im2col-matmul
              bandwidth regime, NOT a lane-equivalent conv ceiling.  The
              ceiling argument lives in PERF.md (trace rate + roofline).
  bn_relu   — conv_g + train-mode batch-norm + relu (the fused stage cost)
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp


def chain_time(op, x0, reps=20):
    """Run x -> op(x) `reps` times inside one jitted scan; return s/op."""

    @jax.jit
    def chained(x):
        def body(c, _):
            return op(c), ()
        out, _ = jax.lax.scan(body, x, None, length=reps)
        return out

    out = chained(x0)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    out = chained(x0)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / reps


def main():
    n, b = 64, 128
    stages = [(32, 32, 16, 16), (16, 16, 32, 32), (8, 8, 64, 64)]
    dev = jax.devices()[0]
    from fedml_tpu.ops import flops as flopslib

    peak = flopslib.device_peak_flops(dev)
    report = {"device": str(getattr(dev, "device_kind", dev.platform)),
              "n_clients": n, "batch": b, "peak_tflops": peak / 1e12}

    for (h, w, cin, cout) in stages:
        assert cin == cout
        key = jax.random.PRNGKey(0)
        xg = jax.random.normal(key, (b, h, w, n * cin), jnp.bfloat16)
        wg = jax.random.normal(key, (3, 3, cin, n * cout), jnp.bfloat16) * 0.05
        scale = jnp.ones((n * cout,), jnp.float32)
        bias = jnp.zeros((n * cout,), jnp.float32)

        def conv_only(x):
            y = jax.lax.conv_general_dilated(
                x, wg, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"),
                feature_group_count=n, preferred_element_type=jnp.bfloat16)
            # renormalize so the chain doesn't overflow; cost counted in all probes
            return y * jax.lax.rsqrt(jnp.float32(9 * cin)).astype(jnp.bfloat16)

        def conv_bn_relu(x):
            y = jax.lax.conv_general_dilated(
                x, wg, (1, 1), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"),
                feature_group_count=n, preferred_element_type=jnp.bfloat16)
            yf = y.astype(jnp.float32)
            mean = yf.mean(axis=(0, 1, 2), keepdims=True)
            var = yf.var(axis=(0, 1, 2), keepdims=True)
            out = (yf - mean) * jax.lax.rsqrt(var + 1e-5) * scale + bias
            return jax.nn.relu(out).astype(jnp.bfloat16)

        A = jax.random.normal(key, (n, b * h * w, 9 * cin), jnp.bfloat16) * 0.05
        Bm = jax.random.normal(key, (n, 9 * cin, 9 * cin), jnp.bfloat16) * 0.05

        def mm_eq(a):
            # square K=N=9*cin keeps the chain shape-stable; flops scaled below
            return jnp.einsum("nik,nko->nio", a, Bm,
                              preferred_element_type=jnp.bfloat16)

        fl_conv = 2 * 9 * cin * cout * h * w * b * n
        fl_mm = 2 * (b * h * w) * (9 * cin) * (9 * cin) * n
        t_g = chain_time(conv_only, xg)
        t_bn = chain_time(conv_bn_relu, xg)
        t_m = chain_time(mm_eq, A)
        report[f"s{h}x{w}x{cin}"] = {
            "conv_grouped_ms": t_g * 1e3, "conv_grouped_mfu": fl_conv / t_g / peak,
            "conv_bn_relu_ms": t_bn * 1e3, "bn_relu_overhead_ms": (t_bn - t_g) * 1e3,
            "mm_eq_ms": t_m * 1e3, "mm_eq_mfu": fl_mm / t_m / peak,
        }
    print("GROUPEDCONV " + json.dumps(report))


if __name__ == "__main__":
    main()
