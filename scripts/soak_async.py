#!/usr/bin/env python
"""10k-client buffered-async soak (ISSUE 8) — CLI over
``fedml_tpu.cross_silo.async_soak.run_soak``.

Drives one real AsyncFedMLServerManager (in-proc backend, real wire bytes)
with an event-scheduled simulated fleet: skewed lognormal latencies, injected
upload drops, staleness-decayed folds, K-arrival virtual rounds.  Prints the
accounting JSON (versions/s, staleness histogram, fold-lag p50/p95, peak
buffered updates, drop/retry accounting) and exits non-zero if the soak
stalls, leaks buffered updates (peak > 2), or loses a drop unaccounted.

    JAX_PLATFORMS=cpu python scripts/soak_async.py --clients 10000 \
        --concurrency 1024 --buffer-k 64 --versions 20

Fault-tolerance modes: ``--kill-recover`` (ISSUE 10: in-process server
hard-kill + journal recovery under seeded chaos on both legs) and
``--procs N`` (ISSUE 13: real OS processes over TCP, seeded SIGKILLs of
the server and clients, journal-recovered completion with the extended
client-side accounting identity).
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--clients", type=int, default=10000)
    p.add_argument("--concurrency", type=int, default=1024)
    p.add_argument("--buffer-k", type=int, default=64)
    p.add_argument("--versions", type=int, default=20)
    p.add_argument("--staleness-exponent", type=float, default=0.5)
    p.add_argument("--drop-prob", type=float, default=0.02)
    p.add_argument("--latency-mean-s", type=float, default=0.005)
    p.add_argument("--latency-sigma", type=float, default=1.0)
    p.add_argument("--redispatch-timeout-s", type=float, default=2.0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--timeout-s", type=float, default=600.0)
    p.add_argument("--kill-recover", action="store_true",
                   help="ISSUE-10 mode: run with the recovery journal + "
                        "seeded chaos (BOTH legs: dispatch and upload), "
                        "HARD-KILL the server mid-run, restart it, and "
                        "assert the recovery invariants (monotone version, "
                        "zero unaccounted losses, duplicates deduped)")
    p.add_argument("--journal-dir", default=None,
                   help="journal directory for --kill-recover (default: a "
                        "fresh temp dir, removed afterwards)")
    p.add_argument("--procs", type=int, default=0, metavar="N",
                   help="ISSUE-13 mode: REAL OS processes over the TCP "
                        "backend — 1 server + N clients, seeded SIGKILLs of "
                        "the server and clients mid-run, every party "
                        "journal-recovered and the run driven to completion "
                        "(client/server counts from --clients etc. are "
                        "ignored; the multiproc soak sizes itself)")
    p.add_argument("--chaos", action="store_true",
                   help="with --procs: thread the default seeded chaos_* "
                        "fault mix into every worker's cfg, so drop/delay/"
                        "duplicate/corrupt faults ride the REAL TCP "
                        "transport in the same run as the genuine SIGKILLs "
                        "(ISSUE 14 satellite; the accounting identity must "
                        "still close)")
    args = p.parse_args()

    if args.procs:
        from fedml_tpu.cross_silo.async_soak import (
            DEFAULT_CHAOS_FLAGS, run_multiproc_kill_soak,
        )

        res = run_multiproc_kill_soak(
            n_clients=args.procs, timeout_s=args.timeout_s, seed=args.seed,
            chaos=dict(DEFAULT_CHAOS_FLAGS) if args.chaos else None)
        print(json.dumps(res, indent=2))
        failures = []
        if not res["completed"]:
            failures.append("run did not complete")
        if not res["monotone"]:
            failures.append("server version not monotone through the SIGKILL")
        if res["server_kills"] < 1 or res["client_kills"] < 2:
            failures.append(
                f"kill schedule under-delivered (server {res['server_kills']}, "
                f"clients {res['client_kills']})")
        if res["unaccounted"] != 0:
            failures.append(
                f"{res['unaccounted']} client restarts unaccounted")
        if failures:
            print("SOAK FAILED: " + "; ".join(failures), file=sys.stderr)
            return 1
        return 0

    if args.kill_recover:
        from fedml_tpu.cross_silo.async_soak import run_kill_recover_soak

        res = run_kill_recover_soak(
            n_clients=args.clients, concurrency=args.concurrency,
            buffer_k=args.buffer_k, versions=args.versions,
            staleness_exponent=args.staleness_exponent,
            drop_prob=args.drop_prob, latency_mean_s=args.latency_mean_s,
            latency_sigma=args.latency_sigma,
            redispatch_timeout_s=args.redispatch_timeout_s, seed=args.seed,
            journal_dir=args.journal_dir, timeout_s=args.timeout_s,
        )
        print(json.dumps(res, indent=2))
        failures = []
        if res["versions"] < args.versions:
            failures.append(f"only {res['versions']}/{args.versions} versions closed")
        if not res["monotone"]:
            failures.append("server version not monotone through the restart")
        if res["unaccounted"] != 0:
            failures.append(f"{res['unaccounted']} losses unaccounted")
        if res["peak_buffered_updates"] > 2:
            failures.append(f"peak buffered updates {res['peak_buffered_updates']} > 2")
        if failures:
            print("SOAK FAILED: " + "; ".join(failures), file=sys.stderr)
            return 1
        return 0

    from fedml_tpu.cross_silo.async_soak import run_soak

    res = run_soak(
        n_clients=args.clients, concurrency=args.concurrency,
        buffer_k=args.buffer_k, versions=args.versions,
        staleness_exponent=args.staleness_exponent, drop_prob=args.drop_prob,
        latency_mean_s=args.latency_mean_s, latency_sigma=args.latency_sigma,
        redispatch_timeout_s=args.redispatch_timeout_s, seed=args.seed,
        timeout_s=args.timeout_s,
    )
    print(json.dumps(res, indent=2))
    failures = []
    if res["versions"] < args.versions:
        failures.append(f"only {res['versions']}/{args.versions} versions closed")
    if res["peak_buffered_updates"] > 2:
        failures.append(f"peak buffered updates {res['peak_buffered_updates']} > 2")
    if res["unaccounted_drops"] != 0:
        failures.append(f"{res['unaccounted_drops']} drops unaccounted")
    if failures:
        print("SOAK FAILED: " + "; ".join(failures), file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
