"""MyAvg (CKA personalized) at north-star recipe scale on the hard benchmark.

Runs the fedml_config_7_m5top3 recipe shape with the MyAgg-7 optimizer on
synthetic_hard and records global + personalized accuracy per eval round,
comparable to the FedAvg curves in CURVE_r3.json.

Usage: python scripts/myavg_recipe.py [out.json] [rounds]
"""
import json
import sys
import time

import fedml_tpu
from fedml_tpu.arguments import Config
from fedml_tpu.runner import FedMLRunner


def main():
    out = sys.argv[1] if len(sys.argv) > 1 else "MYAVG_r3.json"
    rounds = int(sys.argv[2]) if len(sys.argv) > 2 else 100
    cfg = Config(
        dataset="synthetic_hard",
        model="resnet20",
        norm="group",  # ACCURACY.md: prefer GN under non-IID
        federated_optimizer="MyAgg-7",
        client_num_in_total=5,
        client_num_per_round=5,
        comm_round=rounds,
        epochs=5,
        batch_size=32,
        learning_rate=0.03,
        weight_decay=0.001,
        partition_method="hetero",
        partition_alpha=0.5,
        frequency_of_the_test=4,
        random_seed=0,
        synthetic_train_size=20000,
        synthetic_test_size=4000,
        # the reference recipe's agg_args mapped to FLAX leaf paths (resnet20
        # stage 3 = BasicBlock_6..8, head = Dense_0 — MyAvgSimulator refuses
        # substrings that match no leaf): default rounds share the early/body
        # convs; every 5th round aggregates everything; CKA personalization
        # on stage 3 + head
        agg_unselect_layer=("Dense_0", "BasicBlock_6", "BasicBlock_7", "BasicBlock_8"),
        agg_mod_list=(5,),
        agg_mod_dict={5: {}},
        cka_any_select_layer=("Dense_0", "BasicBlock_6", "BasicBlock_7", "BasicBlock_8"),
        cka_select_topk=3,
    )
    fedml_tpu.init(cfg)
    t0 = time.time()
    runner = FedMLRunner(cfg)
    hist = runner.run()
    curve = [
        (h["round"], h.get("test_acc"), h.get("personalized_test_acc_mean"))
        for h in hist if "test_acc" in h
    ]
    res = {
        "recipe": "MyAgg-7, resnet20-GN, 5 clients, hetero a=0.5, batch 32, lr 0.03",
        "curve_round_global_personalized": curve,
        "final_global": curve[-1][1],
        "final_personalized": curve[-1][2],
        "wall_s": round(time.time() - t0, 1),
    }
    with open(out, "w") as f:
        json.dump(res, f, indent=1)
    print(json.dumps({k: v for k, v in res.items() if k != "curve_round_global_personalized"}))


if __name__ == "__main__":
    main()
