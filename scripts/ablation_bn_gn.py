"""BN-vs-GN ablation at north-star recipe scale on the hard benchmark.

SURVEY.md 7 hard-part 3: BatchNorm under non-IID is where FedAvg accuracy
collapses; this runs the fedml_config_7 recipe shape (5 clients, Dirichlet
alpha=0.5, 100 rounds x 5 epochs, batch 32, SGD lr 0.03) on synthetic_hard
with resnet20 (BN) and resnet20 norm=group, recording both curves.

Usage: python scripts/ablation_bn_gn.py [out.json] [rounds]
"""
import json
import sys
import time

import fedml_tpu
from fedml_tpu.arguments import Config
from fedml_tpu.runner import FedMLRunner


def run(norm: str, rounds: int):
    cfg = Config(
        dataset="synthetic_hard",
        model="resnet20",
        norm=norm,
        client_num_in_total=5,
        client_num_per_round=5,
        comm_round=rounds,
        epochs=5,
        batch_size=32,
        learning_rate=0.03,
        weight_decay=0.001,
        partition_method="hetero",
        partition_alpha=0.5,
        frequency_of_the_test=4,
        random_seed=0,
        synthetic_train_size=20000,
        synthetic_test_size=4000,
    )
    fedml_tpu.init(cfg)
    t0 = time.time()
    hist = FedMLRunner(cfg).run()
    curve = [(h["round"], h["test_acc"]) for h in hist if "test_acc" in h]
    return {"norm": norm, "curve": curve, "wall_s": round(time.time() - t0, 1),
            "final_acc": curve[-1][1] if curve else None}


if __name__ == "__main__":
    out = sys.argv[1] if len(sys.argv) > 1 else "CURVE_r3.json"
    rounds = int(sys.argv[2]) if len(sys.argv) > 2 else 100
    results = {
        "dataset": "synthetic_hard (low-SNR cluster mixture, Bayes ~1.0)",
        "recipe": "5 clients, hetero alpha=0.5, 100x5 epochs, batch 32, sgd lr 0.03",
        "runs": [run("batch", rounds), run("group", rounds)],
    }
    with open(out, "w") as f:
        json.dump(results, f, indent=1)
    print(json.dumps({k: (v if k != "runs" else [
        {kk: r[kk] for kk in ("norm", "final_acc", "wall_s")} for r in v
    ]) for k, v in results.items()}))
