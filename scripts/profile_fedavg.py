#!/usr/bin/env python
"""Attribute FedAvg round time on the real chip (VERDICT r2 'what's weak' #1).

Times, separately:
  full      — the production jitted round (MeshSimulator._round_fn)
  clients   — ONLY the vmapped client_update (local SGD) with the same shapes
  fwd       — forward pass only (loss) over the same batch stream
  conv_mm   — a batched-matmul stand-in with the MXU-lane-equivalent shapes of
              every ResNet-20 conv (what the chip could do if the round were
              nothing but its convs at their native channel widths)
  wide_mm   — the same FLOPs issued as 128-lane matmuls (the MXU headline)

Prints a JSON breakdown; run on the real TPU (no args).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def timeit(fn, *args, iters=5):
    out = fn(*args)
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def main():
    import fedml_tpu
    from fedml_tpu.arguments import Config
    from fedml_tpu.ops import flops as flopslib
    from fedml_tpu.runner import FedMLRunner

    n_clients, per_round, batch, spc = 128, 64, 128, 512
    cfg = Config(
        dataset="cifar10", model="resnet20",
        client_num_in_total=n_clients, client_num_per_round=per_round,
        comm_round=50, epochs=1, batch_size=batch, learning_rate=0.03,
        partition_method="homo",
        synthetic_train_size=n_clients * spc, synthetic_test_size=1024,
        frequency_of_the_test=0, compute_dtype="bfloat16", step_mode="match",
        metrics_jsonl_path="",
    )
    fedml_tpu.init(cfg)
    sim = FedMLRunner(cfg).runner
    dev = jax.devices()[0]
    peak = flopslib.device_peak_flops(dev)

    steps_per_client = -(-spc // batch)
    samples_round = per_round * steps_per_client * batch
    flops_sample = flopslib.resnet20_cifar_train_flops_per_sample()
    flops_round = samples_round * flops_sample

    report = {"device": str(getattr(dev, "device_kind", dev.platform)),
              "peak_tflops": peak / 1e12,
              "samples_per_round": samples_round,
              "flops_per_sample_g": flops_sample / 1e9}

    # -- full round --------------------------------------------------------
    def full():
        return sim._round_fn(
            sim.global_vars, sim.server_state, sim.client_states,
            sim.counts, sim._data[0], sim._data[1],
            jnp.int32(1), sim.root_key, sim.defense_history,
        )[0]

    t_full = timeit(full)
    report["full_round_s"] = t_full
    report["full_mfu"] = flops_round / t_full / peak

    # -- clients only ------------------------------------------------------
    algo = sim.algorithm
    from fedml_tpu.core import rng as rnglib

    sampled = rnglib.sample_clients(sim.root_key, 1, n_clients, per_round)
    xs = jnp.take(sim._data[0], sampled, axis=0)
    ys = jnp.take(sim._data[1], sampled, axis=0)
    cnts = jnp.take(sim.counts, sampled)
    rkey = rnglib.round_key(sim.root_key, 1)
    keys = jax.vmap(lambda i: rnglib.client_key(rkey, i))(sampled)

    @jax.jit
    def clients_only(gv, xs, ys, cnts, keys):
        def one(x, y, cnt, k):
            out = algo.client_update(gv, None, sim.server_state, x, y, cnt, k)
            return out.contribution
        return jax.vmap(one)(xs, ys, cnts, keys)

    t_cli = timeit(clients_only, sim.global_vars, xs, ys, cnts, keys)
    report["clients_only_s"] = t_cli
    report["clients_mfu"] = flops_round / t_cli / peak
    report["non_client_overhead_s"] = t_full - t_cli

    # -- forward only ------------------------------------------------------
    from fedml_tpu.fl import losses

    model = sim.model

    @jax.jit
    def fwd_only(gv, xs, ys):
        def one(x, y):
            def batch_loss(carry, i):
                xb = jax.lax.dynamic_slice_in_dim(x, i * batch, batch)
                yb = jax.lax.dynamic_slice_in_dim(y, i * batch, batch)
                logits, _ = model.apply(gv, xb, train=True, mutable=["batch_stats"])
                return carry + losses.cross_entropy(logits, yb).mean(), None
            tot, _ = jax.lax.scan(batch_loss, 0.0, jnp.arange(steps_per_client))
            return tot
        return jax.vmap(one)(xs, ys)

    t_fwd = timeit(fwd_only, sim.global_vars, xs, ys)
    report["fwd_only_s"] = t_fwd

    # -- conv-shape batched matmuls ---------------------------------------
    # every ResNet-20 conv as (im2col) matmul: M = b*H*W, K = 3*3*Cin, N = Cout
    convs = [(32, 3, 16, 1)] + [(32, 16, 16, 12)] + [(16, 16, 32, 1), (16, 32, 32, 11)] \
        + [(8, 32, 64, 1), (8, 64, 64, 11)]
    B = per_round  # client dim rides as the matmul batch

    def make_mm(sp, cin, cout, b=batch):
        m = b * sp * sp
        k = 9 * cin
        x = jnp.ones((B, m, k), jnp.bfloat16)
        w = jnp.ones((B, k, cout), jnp.bfloat16)
        return x, w

    mats = [(make_mm(sp, cin, cout), reps) for sp, cin, cout, reps in convs]

    @jax.jit
    def conv_mm(mats_flat):
        acc = 0.0
        for (x, w), reps in mats_flat:
            y = jnp.einsum("bmk,bkn->bmn", x, w, preferred_element_type=jnp.float32)
            acc = acc + y.mean() * reps
        return acc

    t_mm = timeit(conv_mm, mats)
    conv_flops = sum(2 * B * (b := batch) * sp * sp * 9 * cin * cout * reps
                     for sp, cin, cout, reps in convs)
    # fwd only; train ~= 3x fwd conv flops
    report["conv_mm_s"] = t_mm
    report["conv_mm_tflops"] = conv_flops / t_mm / 1e12
    report["conv_mm_mfu"] = conv_flops / t_mm / peak

    # -- wide matmul reference --------------------------------------------
    M = 8192
    x = jnp.ones((M, 4096), jnp.bfloat16)
    w = jnp.ones((4096, 4096), jnp.bfloat16)

    @jax.jit
    def wide(x, w):
        return (x @ w).mean()

    t_wide = timeit(wide, x, w)
    report["wide_mm_tflops"] = 2 * M * 4096 * 4096 / t_wide / 1e12
    report["wide_mm_mfu"] = 2 * M * 4096 * 4096 / t_wide / peak

    print("PROFILE " + json.dumps({k: (round(v, 5) if isinstance(v, float) else v)
                                   for k, v in report.items()}))


if __name__ == "__main__":
    main()
