// LightSecAgg finite-field kernels — C++ mirror of the Python field math.
//
// Capability parity with the reference's only real native compute,
// android/fedmlsdk/MobileNN/src/security/LightSecAgg.cpp:1-134 (modInverse,
// modDivide, gen_Lagrange_coeffs, mask encode/decode), re-derived for the
// fedml_tpu field layout (trust/secagg/field.py): prime M31 = 2^31 - 1,
// int64 arithmetic so products never overflow, Fermat inverses.
//
// Conformance is asserted against the Python implementation by
// tests/test_native_client.py (same alphas/betas/mask/noise in, same
// coefficients / encoded shares / decoded mask out).

#pragma once

#include <cstdint>
#include <stdexcept>
#include <vector>

namespace lsa {

constexpr int64_t kPrime = (int64_t{1} << 31) - 1;  // M31, matches field.py

inline int64_t mod_pow(int64_t base, int64_t exp, int64_t p = kPrime) {
  int64_t result = 1;
  base %= p;
  if (base < 0) base += p;
  while (exp > 0) {
    if (exp & 1) result = (__int128)result * base % p;
    base = (__int128)base * base % p;
    exp >>= 1;
  }
  return result;
}

// Fermat inverse (p prime) — reference modInverse uses extended Euclid on
// 32-bit ints; Fermat keeps the code branch-free and matches field.py.
inline int64_t mod_inverse(int64_t a, int64_t p = kPrime) {
  a %= p;
  if (a < 0) a += p;
  if (a == 0) throw std::domain_error("mod_inverse(0)");
  return mod_pow(a, p - 2, p);
}

// (len(eval), len(interp)) Lagrange basis coefficients over F_p —
// coeff[i][j] = prod_{k != j} (e_i - t_k) / (t_j - t_k)  (mod p).
// Mirrors field.py gen_lagrange_coeffs / reference gen_Lagrange_coeffs.
inline std::vector<std::vector<int64_t>> gen_lagrange_coeffs(
    const std::vector<int64_t>& eval_points,
    const std::vector<int64_t>& interp_points, int64_t p = kPrime) {
  const size_t ne = eval_points.size(), nt = interp_points.size();
  std::vector<std::vector<int64_t>> out(ne, std::vector<int64_t>(nt, 0));
  for (size_t j = 0; j < nt; ++j) {
    int64_t den = 1;
    for (size_t k = 0; k < nt; ++k) {
      if (k == j) continue;
      int64_t d = (interp_points[j] - interp_points[k]) % p;
      if (d < 0) d += p;
      den = (__int128)den * d % p;
    }
    const int64_t den_inv = mod_inverse(den, p);
    for (size_t i = 0; i < ne; ++i) {
      int64_t num = 1;
      for (size_t k = 0; k < nt; ++k) {
        if (k == j) continue;
        int64_t d = (eval_points[i] - interp_points[k]) % p;
        if (d < 0) d += p;
        num = (__int128)num * d % p;
      }
      out[i][j] = (__int128)num * den_inv % p;
    }
  }
  return out;
}

// Encode a padded mask (length divisible by (u - t)) plus t noise chunks into
// n per-client shares: shares = W @ [chunks; noise] (mod p), W the (n, u)
// Lagrange matrix from betas to alphas.  Noise is an explicit argument (the
// Python side draws it from its own RNG) so the kernel is deterministic and
// conformance-testable.
inline std::vector<std::vector<int64_t>> encode_mask(
    const std::vector<int64_t>& mask, const std::vector<int64_t>& noise,
    int n, int t, int u, int64_t p = kPrime) {
  const int k = u - t;
  if (mask.size() % k != 0) throw std::invalid_argument("mask not padded to u-t");
  const size_t s = mask.size() / k;
  if (noise.size() != (size_t)t * s) throw std::invalid_argument("noise must be t*s");
  std::vector<int64_t> alphas(u), betas(n);
  for (int i = 0; i < u; ++i) alphas[i] = i + 1;
  for (int i = 0; i < n; ++i) betas[i] = u + 1 + i;
  auto W = gen_lagrange_coeffs(betas, alphas, p);  // (n, u)
  std::vector<std::vector<int64_t>> out(n, std::vector<int64_t>(s, 0));
  for (int row = 0; row < n; ++row) {
    for (int j = 0; j < u; ++j) {
      const int64_t w = W[row][j];
      const int64_t* chunk = (j < k) ? &mask[(size_t)j * s] : &noise[(size_t)(j - k) * s];
      for (size_t c = 0; c < s; ++c) {
        out[row][c] = (out[row][c] + (__int128)w * chunk[c]) % p;
      }
    }
  }
  return out;
}

// Server-side one-shot decode: interpolate the sum of masks from >= u
// survivors' aggregated shares.  survivors are 0-based client indices;
// agg_shares[i] is survivor i's aggregate (length s).  Returns d_pad values.
inline std::vector<int64_t> decode_aggregate_mask(
    const std::vector<int>& survivors,
    const std::vector<std::vector<int64_t>>& agg_shares,
    int t, int u, size_t d_pad, int64_t p = kPrime) {
  if ((int)survivors.size() < u) throw std::invalid_argument("need >= u survivors");
  const int k = u - t;
  const size_t s = agg_shares.at(0).size();
  std::vector<int64_t> alphas(k), eval_pts(u);
  for (int i = 0; i < k; ++i) alphas[i] = i + 1;
  for (int i = 0; i < u; ++i) eval_pts[i] = u + 1 + survivors[i];
  auto W = gen_lagrange_coeffs(alphas, eval_pts, p);  // (k, u)
  std::vector<int64_t> out((size_t)k * s, 0);
  for (int row = 0; row < k; ++row) {
    for (int col = 0; col < u; ++col) {
      const int64_t w = W[row][col];
      const auto& share = agg_shares[col];
      for (size_t c = 0; c < s; ++c) {
        int64_t& o = out[(size_t)row * s + c];
        o = (o + (__int128)w * share[c]) % p;
      }
    }
  }
  out.resize(d_pad);
  return out;
}

}  // namespace lsa
