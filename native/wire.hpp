// Pytree wire format + cross-silo Message framing — C++ side.
//
// Speaks exactly the bytes of fedml_tpu/comm/wire.py and comm/message.py:
//
//   Message  = [4B LE control_len][control JSON][pytree blob]
//   blob     = [4B LE header_len][header JSON][raw LE buffers...]
//   header   = {"version":1, "treedef":skel, "leaves":[{dtype,shape,nbytes}]}
//
// and the TCP transport framing of comm/tcp_backend.py:
//
//   frame    = [8B LE frame_len][Message bytes]
//
// Capability parity: the reference's C++ mobile client serializes models with
// MNN buffers + MQTT (android/fedmlsdk/MobileNN/src/train/FedMLMNNTrainer.cpp);
// here the contract is the language-neutral pytree layout, designed for this
// exact purpose (SURVEY.md §7 hard part 6).
//
// The client never rebuilds the treedef: replies carry the SAME tensor
// skeleton as the incoming global model, so the received header JSON is
// reused verbatim and only leaf buffers are swapped.

#pragma once

#include <cstdint>
#include <cstring>
#include <map>
#include <memory>
#include <stdexcept>
#include <string>
#include <vector>

namespace wire {

// ---------------------------------------------------------------------------
// Minimal JSON parser (objects/arrays/strings/numbers/bools/null) — enough
// for the wire headers, which Python emits with json.dumps(separators=(",",":"))
// ---------------------------------------------------------------------------
struct Json {
  enum Type { Null, Bool, Int, Dbl, Str, Arr, Obj } type = Null;
  bool b = false;
  int64_t i = 0;
  double d = 0.0;
  std::string s;
  std::vector<Json> arr;
  std::map<std::string, Json> obj;

  const Json& at(const std::string& key) const {
    auto it = obj.find(key);
    if (it == obj.end()) throw std::out_of_range("json key: " + key);
    return it->second;
  }
  bool has(const std::string& key) const { return obj.count(key) > 0; }
  int64_t as_int() const { return type == Dbl ? (int64_t)d : i; }
};

class JsonParser {
 public:
  explicit JsonParser(const std::string& text) : t_(text) {}
  Json parse() {
    Json v = value();
    ws();
    if (pos_ != t_.size()) throw std::runtime_error("trailing json");
    return v;
  }

 private:
  const std::string& t_;
  size_t pos_ = 0;

  void ws() { while (pos_ < t_.size() && isspace((unsigned char)t_[pos_])) ++pos_; }
  char peek() { ws(); if (pos_ >= t_.size()) throw std::runtime_error("eof"); return t_[pos_]; }
  char next() { char c = peek(); ++pos_; return c; }
  void expect(char c) { if (next() != c) throw std::runtime_error(std::string("expected ") + c); }

  Json value() {
    switch (peek()) {
      case '{': return object();
      case '[': return array();
      case '"': { Json v; v.type = Json::Str; v.s = string(); return v; }
      case 't': lit("true"); { Json v; v.type = Json::Bool; v.b = true; return v; }
      case 'f': lit("false"); { Json v; v.type = Json::Bool; v.b = false; return v; }
      case 'n': lit("null"); return Json{};
      default: return number();
    }
  }
  void lit(const char* w) { ws(); size_t n = strlen(w);
    if (t_.compare(pos_, n, w) != 0) throw std::runtime_error("bad literal");
    pos_ += n; }
  Json object() {
    expect('{'); Json v; v.type = Json::Obj;
    if (peek() == '}') { ++pos_; return v; }
    while (true) {
      std::string k = string();
      expect(':');
      v.obj[k] = value();
      char c = next();
      if (c == '}') return v;
      if (c != ',') throw std::runtime_error("bad object");
    }
  }
  Json array() {
    expect('['); Json v; v.type = Json::Arr;
    if (peek() == ']') { ++pos_; return v; }
    while (true) {
      v.arr.push_back(value());
      char c = next();
      if (c == ']') return v;
      if (c != ',') throw std::runtime_error("bad array");
    }
  }
  std::string string() {
    expect('"'); std::string out;
    while (true) {
      if (pos_ >= t_.size()) throw std::runtime_error("eof in string");
      char c = t_[pos_++];
      if (c == '"') return out;
      if (c == '\\') {
        char e = t_[pos_++];
        switch (e) {
          case 'n': out += '\n'; break; case 't': out += '\t'; break;
          case 'r': out += '\r'; break; case 'b': out += '\b'; break;
          case 'f': out += '\f'; break; case '/': out += '/'; break;
          case '"': out += '"'; break; case '\\': out += '\\'; break;
          case 'u': { // basic BMP escape
            unsigned cp = std::stoul(t_.substr(pos_, 4), nullptr, 16); pos_ += 4;
            if (cp < 0x80) out += (char)cp;
            else if (cp < 0x800) { out += (char)(0xC0 | (cp >> 6)); out += (char)(0x80 | (cp & 0x3F)); }
            else { out += (char)(0xE0 | (cp >> 12)); out += (char)(0x80 | ((cp >> 6) & 0x3F)); out += (char)(0x80 | (cp & 0x3F)); }
            break; }
          default: throw std::runtime_error("bad escape");
        }
      } else {
        out += c;
      }
    }
  }
  Json number() {
    ws();
    size_t start = pos_;
    if (t_[pos_] == '-') ++pos_;
    bool is_int = true;
    while (pos_ < t_.size() && (isdigit((unsigned char)t_[pos_]) || strchr(".eE+-", t_[pos_]))) {
      if (t_[pos_] == '.' || t_[pos_] == 'e' || t_[pos_] == 'E') is_int = false;
      ++pos_;
    }
    Json v;
    std::string tok = t_.substr(start, pos_ - start);
    if (is_int) { v.type = Json::Int; v.i = std::stoll(tok); }
    else { v.type = Json::Dbl; v.d = std::stod(tok); }
    return v;
  }
};

// ---------------------------------------------------------------------------
// Message frame codec
// ---------------------------------------------------------------------------
struct Leaf {
  std::string dtype;        // numpy dtype str, e.g. "<f4"
  std::vector<int64_t> shape;
  size_t nbytes = 0;
  size_t offset = 0;        // into the original frame buffer region
};

struct DecodedMessage {
  Json control;              // msg_type/sender/receiver/round_idx/...
  std::string header_json;   // the blob header, verbatim (reused in replies)
  std::vector<Leaf> leaves;
  std::vector<uint8_t> buffers;  // concatenated raw leaf bytes
};

inline uint32_t read_u32(const uint8_t* p) {
  uint32_t v; memcpy(&v, p, 4); return v;  // little-endian hosts only
}

inline DecodedMessage decode_message(const std::vector<uint8_t>& frame) {
  // Every length prefix is validated against the remaining frame bytes before
  // any iterator arithmetic: a truncated or corrupt frame must throw, not read
  // out of bounds.
  if (frame.size() < 4) throw std::runtime_error("short frame");
  const uint32_t clen = read_u32(frame.data());
  if ((size_t)clen > frame.size() - 4) throw std::runtime_error("control length exceeds frame");
  std::string control_json(frame.begin() + 4, frame.begin() + 4 + clen);
  size_t off = 4 + (size_t)clen;
  if (frame.size() - off < 4) throw std::runtime_error("truncated header length");
  const uint32_t hlen = read_u32(frame.data() + off);
  if ((size_t)hlen > frame.size() - off - 4) throw std::runtime_error("header length exceeds frame");
  std::string header_json(frame.begin() + off + 4, frame.begin() + off + 4 + hlen);
  off += 4 + (size_t)hlen;

  DecodedMessage out;
  out.control = JsonParser(control_json).parse();
  out.header_json = header_json;
  Json header = JsonParser(header_json).parse();
  if (header.at("version").as_int() != 1) throw std::runtime_error("wire version");
  const size_t buf_bytes = frame.size() - off;
  size_t rel = 0;
  for (const Json& spec : header.at("leaves").arr) {
    Leaf leaf;
    leaf.dtype = spec.at("dtype").s;
    for (const Json& dim : spec.at("shape").arr) leaf.shape.push_back(dim.as_int());
    // a hostile header can claim negative/huge nbytes; without these checks
    // (size_t) wrap makes offset+nbytes a wild pointer downstream
    const int64_t declared = spec.at("nbytes").as_int();
    if (declared < 0 || (uint64_t)declared > buf_bytes - rel)
      throw std::runtime_error("leaf nbytes exceeds buffer region");
    // nbytes must also agree with shape x itemsize: consumers size their
    // reads/writes from the SHAPE (e.g. the trainer's d*c kernel loop), so a
    // frame whose shape promises more elements than its bytes deliver would
    // still be a heap overrun. dtype strings end in the itemsize ("<f4").
    uint64_t elems = 1;
    for (int64_t dim : leaf.shape) {
      if (dim < 0) throw std::runtime_error("negative dim");
      if (dim != 0 && elems > UINT64_MAX / (uint64_t)dim)
        throw std::runtime_error("shape product overflow");
      elems *= (uint64_t)dim;
    }
    uint64_t itemsize = 0;
    for (char ch : leaf.dtype) {
      if (ch >= '0' && ch <= '9') itemsize = itemsize * 10 + (uint64_t)(ch - '0');
    }
    if (itemsize == 0 || itemsize > 16) throw std::runtime_error("bad dtype itemsize");
    if (elems > UINT64_MAX / itemsize)
      throw std::runtime_error("shape byte size overflow");
    if (elems * itemsize != (uint64_t)declared)
      throw std::runtime_error("nbytes != shape product * itemsize");
    leaf.nbytes = (size_t)declared;
    leaf.offset = rel;
    rel += leaf.nbytes;
    out.leaves.push_back(std::move(leaf));
  }
  out.buffers.assign(frame.begin() + off, frame.end());
  if (out.buffers.size() != rel) throw std::runtime_error("buffer size mismatch");
  return out;
}

// Build a reply whose tensor skeleton equals the incoming one (header JSON
// reused verbatim); control is a flat JSON object the caller provides.
inline std::vector<uint8_t> encode_message(const std::string& control_json,
                                           const std::string& header_json,
                                           const std::vector<uint8_t>& buffers) {
  std::vector<uint8_t> out;
  auto put_u32 = [&out](uint32_t v) {
    uint8_t b[4]; memcpy(b, &v, 4); out.insert(out.end(), b, b + 4);
  };
  put_u32((uint32_t)control_json.size());
  out.insert(out.end(), control_json.begin(), control_json.end());
  put_u32((uint32_t)header_json.size());
  out.insert(out.end(), header_json.begin(), header_json.end());
  out.insert(out.end(), buffers.begin(), buffers.end());
  return out;
}

}  // namespace wire
