// fedml_native — C++ federated-learning client + field-kernel CLI.
//
// Capability parity with the reference's mobile C++ client
// (android/fedmlsdk/MobileNN/src/train/FedMLMNNTrainer.cpp: on-device
// training driven by a Python server) translated to TPU-world terms
// (SURVEY.md §2.13): a non-Python process that speaks the pytree wire format
// over the TCP transport, joins the cross-silo FedAvg protocol, trains a
// softmax-regression model on its local shard with plain C++ loops, and
// uploads weights + sample count.  Message-type integers match
// fedml_tpu/cross_silo/message_define.py.
//
// Modes:
//   fedml_native client --rank R --base-port P --data FILE
//       [--host H --lr 0.1 --epochs 1 --batch 16]
//   fedml_native fieldtest N T U S   (LightSecAgg kernel conformance; reads
//       mask/noise ints on stdin, prints COEFFS/SHARES/DECODED — compared
//       bit-exactly against trust/secagg by tests/test_native_client.py)
//
// Build: make -C native   (g++ -O2 -std=c++17, no external deps)

#include <arpa/inet.h>
#include <netinet/in.h>
#include <sys/socket.h>
#include <unistd.h>

#include <algorithm>
#include <cmath>
#include <cstdio>
#include <cstring>
#include <fstream>
#include <iostream>
#include <random>
#include <sstream>
#include <string>
#include <vector>

#include "lightsecagg.hpp"
#include "wire.hpp"

// message_define.py parity
enum MsgType {
  kInitConfig = 1,
  kSyncModel = 2,
  kSendModel = 3,
  kClientStatus = 5,
  kCheckStatus = 6,
  kFinish = 7,
  kFinished = 8,
};

// ---------------------------------------------------------------------------
// TCP framing (comm/tcp_backend.py: [8B LE length][Message bytes])
// ---------------------------------------------------------------------------
static bool read_exact(int fd, uint8_t* buf, size_t n) {
  size_t got = 0;
  while (got < n) {
    ssize_t r = read(fd, buf + got, n - got);
    if (r <= 0) return false;
    got += (size_t)r;
  }
  return true;
}

// Mirror of tcp_backend.py's MAX_FRAME_BYTES: refuse absurd length prefixes
// before allocating, so a corrupt/hostile peer cannot OOM the client.
static constexpr uint64_t kMaxFrameBytes = 1ull << 30;  // 1 GB

static bool read_frame(int fd, std::vector<uint8_t>* out) {
  uint64_t len = 0;
  if (!read_exact(fd, (uint8_t*)&len, 8)) return false;
  if (len > kMaxFrameBytes) {
    fprintf(stderr, "frame length %llu exceeds cap\n", (unsigned long long)len);
    return false;
  }
  out->resize(len);
  return read_exact(fd, out->data(), len);
}

// best_effort: a terminal ack may race the server's listener teardown
// (the server closes right after broadcasting FINISH; its Python twin
// treats the FINISHED ack as bookkeeping only) — such a send must not
// fail the client.
static void send_frame_to(const std::string& host, int port, const std::vector<uint8_t>& payload,
                          bool best_effort = false) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) { if (best_effort) return; perror("socket"); exit(1); }
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons((uint16_t)port);
  inet_pton(AF_INET, host.c_str(), &addr.sin_addr);
  if (connect(fd, (sockaddr*)&addr, sizeof(addr)) != 0) {
    if (best_effort) { close(fd); return; }
    perror("connect"); exit(1);
  }
  // mirror the transport's MAX_FRAME_BYTES on the send side (also bounds
  // the 8 + size arithmetic for the compiler's overflow analysis)
  if (payload.size() > (1ull << 30)) {
    fprintf(stderr, "frame of %zu bytes exceeds 1 GB cap\n", payload.size());
    if (best_effort) { close(fd); return; }
    exit(1);
  }
  uint64_t len = payload.size();
  std::vector<uint8_t> framed(8 + payload.size());
  memcpy(framed.data(), &len, 8);
  memcpy(framed.data() + 8, payload.data(), payload.size());
  size_t sent = 0;
  while (sent < framed.size()) {
    // MSG_NOSIGNAL: a peer that closed mid-race (the best_effort case)
    // must surface as EPIPE, not a process-killing SIGPIPE
    ssize_t w = send(fd, framed.data() + sent, framed.size() - sent, MSG_NOSIGNAL);
    if (w <= 0) {
      if (best_effort) { close(fd); return; }
      perror("send"); exit(1);
    }
    sent += (size_t)w;
  }
  close(fd);
}

// ---------------------------------------------------------------------------
// Local shard: [u32 n][u32 d][u32 c][f32 x n*d][i32 y n]
// ---------------------------------------------------------------------------
struct Shard {
  uint32_t n = 0, d = 0, c = 0;
  std::vector<float> x;
  std::vector<int32_t> y;
};

static Shard load_shard(const std::string& path) {
  std::ifstream f(path, std::ios::binary);
  if (!f) { fprintf(stderr, "cannot open %s\n", path.c_str()); exit(1); }
  Shard s;
  f.read((char*)&s.n, 4); f.read((char*)&s.d, 4); f.read((char*)&s.c, 4);
  s.x.resize((size_t)s.n * s.d);
  s.y.resize(s.n);
  f.read((char*)s.x.data(), (std::streamsize)s.x.size() * 4);
  f.read((char*)s.y.data(), (std::streamsize)s.y.size() * 4);
  if (!f) { fprintf(stderr, "short shard file %s\n", path.c_str()); exit(1); }
  return s;
}

// ---------------------------------------------------------------------------
// Softmax-regression local SGD (the on-device trainer role of
// FedMLMNNTrainer.cpp, for the lr model: kernel (d, c) + bias (c))
// ---------------------------------------------------------------------------
static void train_softmax(const Shard& s, float* kernel, float* bias,
                          float lr, int epochs, int batch, uint32_t seed) {
  const uint32_t n = s.n, d = s.d, c = s.c;
  std::mt19937 rng(seed);
  std::vector<uint32_t> order(n);
  for (uint32_t i = 0; i < n; ++i) order[i] = i;
  std::vector<float> logits(c), probs(c), gk((size_t)d * c), gb(c);
  for (int e = 0; e < epochs; ++e) {
    std::shuffle(order.begin(), order.end(), rng);
    for (uint32_t start = 0; start < n; start += (uint32_t)batch) {
      const uint32_t end = std::min(n, start + (uint32_t)batch);
      const float inv_b = 1.0f / (float)(end - start);
      std::fill(gk.begin(), gk.end(), 0.0f);
      std::fill(gb.begin(), gb.end(), 0.0f);
      for (uint32_t bi = start; bi < end; ++bi) {
        const float* xi = &s.x[(size_t)order[bi] * d];
        const int32_t yi = s.y[order[bi]];
        for (uint32_t j = 0; j < c; ++j) {
          float acc = bias[j];
          for (uint32_t k = 0; k < d; ++k) acc += xi[k] * kernel[(size_t)k * c + j];
          logits[j] = acc;
        }
        float mx = logits[0];
        for (uint32_t j = 1; j < c; ++j) mx = std::max(mx, logits[j]);
        float z = 0.0f;
        for (uint32_t j = 0; j < c; ++j) { probs[j] = std::exp(logits[j] - mx); z += probs[j]; }
        for (uint32_t j = 0; j < c; ++j) {
          const float g = probs[j] / z - (j == (uint32_t)yi ? 1.0f : 0.0f);
          gb[j] += g;
          for (uint32_t k = 0; k < d; ++k) gk[(size_t)k * c + j] += g * xi[k];
        }
      }
      for (size_t i = 0; i < gk.size(); ++i) kernel[i] -= lr * inv_b * gk[i];
      for (uint32_t j = 0; j < c; ++j) bias[j] -= lr * inv_b * gb[j];
    }
  }
}

// ---------------------------------------------------------------------------
// Client protocol
// ---------------------------------------------------------------------------
struct Args {
  int rank = 1;
  int base_port = 9690;
  std::string host = "127.0.0.1";
  std::string data;
  float lr = 0.1f;
  int epochs = 1;
  int batch = 16;
};

static std::string control_json(int msg_type, int sender, int receiver,
                                const std::string& extra_fields) {
  std::ostringstream os;
  os << "{\"msg_type\":" << msg_type << ",\"sender\":" << sender
     << ",\"receiver\":" << receiver;
  if (!extra_fields.empty()) os << "," << extra_fields;
  os << "}";
  return os.str();
}

static const std::string kEmptyBlobHeader =
    "{\"version\":1,\"treedef\":{\"d\":{}},\"leaves\":[]}";

static int run_client(const Args& a) {
  Shard shard = load_shard(a.data);
  // listen on base_port + rank
  int lfd = socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(lfd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = INADDR_ANY;
  addr.sin_port = htons((uint16_t)(a.base_port + a.rank));
  if (bind(lfd, (sockaddr*)&addr, sizeof(addr)) != 0) { perror("bind"); return 1; }
  listen(lfd, 16);
  fprintf(stderr, "[native-client %d] listening on %d\n", a.rank, a.base_port + a.rank);

  bool done = false;
  while (!done) {
    int cfd = accept(lfd, nullptr, nullptr);
    if (cfd < 0) break;
    std::vector<uint8_t> frame;
    while (read_frame(cfd, &frame)) {
      wire::DecodedMessage msg = wire::decode_message(frame);
      const int msg_type = (int)msg.control.at("msg_type").as_int();
      if (msg_type == kCheckStatus) {
        auto reply = wire::encode_message(
            control_json(kClientStatus, a.rank, 0,
                         "\"client_status\":\"ONLINE\",\"client_os\":\"cpp\""),
            kEmptyBlobHeader, {});
        send_frame_to(a.host, a.base_port + 0, reply);
      } else if (msg_type == kInitConfig || msg_type == kSyncModel) {
        const int64_t round_idx = msg.control.at("round_idx").as_int();
        // locate the lr model's leaves generically: 2-D f32 -> kernel,
        // 1-D f32 -> bias (shape validated against the shard)
        float* kernel = nullptr;
        float* bias = nullptr;
        for (const wire::Leaf& leaf : msg.leaves) {
          if (leaf.dtype != "<f4") continue;
          float* buf = (float*)(msg.buffers.data() + leaf.offset);
          if (leaf.shape.size() == 2 && leaf.shape[0] == (int64_t)shard.d &&
              leaf.shape[1] == (int64_t)shard.c) kernel = buf;
          if (leaf.shape.size() == 1 && leaf.shape[0] == (int64_t)shard.c) bias = buf;
        }
        if (!kernel || !bias) { fprintf(stderr, "model shape mismatch\n"); return 1; }
        train_softmax(shard, kernel, bias, a.lr, a.epochs, a.batch,
                      (uint32_t)(round_idx * 1000 + a.rank));
        std::ostringstream extra;
        extra << "\"num_samples\":" << shard.n << ",\"round_idx\":" << round_idx;
        auto reply = wire::encode_message(
            control_json(kSendModel, a.rank, 0, extra.str()),
            msg.header_json, msg.buffers);  // same skeleton, trained buffers
        send_frame_to(a.host, a.base_port + 0, reply);
        fprintf(stderr, "[native-client %d] trained round %lld (n=%u)\n",
                a.rank, (long long)round_idx, shard.n);
      } else if (msg_type == kFinish) {
        auto reply = wire::encode_message(
            control_json(kFinished, a.rank, 0, ""), kEmptyBlobHeader, {});
        send_frame_to(a.host, a.base_port + 0, reply, /*best_effort=*/true);
        done = true;
        break;
      }
    }
    close(cfd);
  }
  close(lfd);
  fprintf(stderr, "[native-client %d] finished\n", a.rank);
  return 0;
}

// ---------------------------------------------------------------------------
// fieldtest: LightSecAgg kernel conformance (deterministic, no RNG)
// ---------------------------------------------------------------------------
static int run_fieldtest(int n, int t, int u, int s) {
  const int k = u - t;
  std::vector<int64_t> mask((size_t)k * s), noise((size_t)t * s);
  for (auto& v : mask) std::cin >> v;
  for (auto& v : noise) std::cin >> v;

  std::vector<int64_t> alphas(u), betas(n);
  for (int i = 0; i < u; ++i) alphas[i] = i + 1;
  for (int i = 0; i < n; ++i) betas[i] = u + 1 + i;
  auto W = lsa::gen_lagrange_coeffs(betas, alphas);
  printf("COEFFS\n");
  for (auto& row : W) {
    for (size_t j = 0; j < row.size(); ++j) printf("%lld%c", (long long)row[j], j + 1 == row.size() ? '\n' : ' ');
  }

  auto shares = lsa::encode_mask(mask, noise, n, t, u);
  printf("SHARES\n");
  for (auto& row : shares) {
    for (size_t j = 0; j < row.size(); ++j) printf("%lld%c", (long long)row[j], j + 1 == row.size() ? '\n' : ' ');
  }

  // single-mask scenario: survivors 0..u-1 aggregate just this mask's shares;
  // decoding must reproduce the mask
  std::vector<int> survivors(u);
  for (int i = 0; i < u; ++i) survivors[i] = i;
  std::vector<std::vector<int64_t>> agg;
  for (int i = 0; i < u; ++i) agg.push_back(shares[i]);
  auto decoded = lsa::decode_aggregate_mask(survivors, agg, t, u, mask.size());
  printf("DECODED\n");
  for (size_t j = 0; j < decoded.size(); ++j) printf("%lld%c", (long long)decoded[j], j + 1 == decoded.size() ? '\n' : ' ');
  // also print a mod-inverse table for spot conformance
  printf("INVERSES\n");
  for (int64_t v : {int64_t{2}, int64_t{3}, int64_t{65537}, int64_t{123456789}, lsa::kPrime - 1}) {
    printf("%lld %lld\n", (long long)v, (long long)lsa::mod_inverse(v));
  }
  return 0;
}

int main(int argc, char** argv) {
  if (argc < 2) { fprintf(stderr, "usage: %s client|fieldtest ...\n", argv[0]); return 2; }
  std::string mode = argv[1];
  if (mode == "fieldtest") {
    if (argc != 6) { fprintf(stderr, "fieldtest N T U S\n"); return 2; }
    return run_fieldtest(atoi(argv[2]), atoi(argv[3]), atoi(argv[4]), atoi(argv[5]));
  }
  Args a;
  for (int i = 2; i + 1 < argc; i += 2) {
    std::string k = argv[i], v = argv[i + 1];
    if (k == "--rank") a.rank = atoi(v.c_str());
    else if (k == "--base-port") a.base_port = atoi(v.c_str());
    else if (k == "--host") a.host = v;
    else if (k == "--data") a.data = v;
    else if (k == "--lr") a.lr = (float)atof(v.c_str());
    else if (k == "--epochs") a.epochs = atoi(v.c_str());
    else if (k == "--batch") a.batch = atoi(v.c_str());
  }
  if (a.data.empty()) { fprintf(stderr, "--data required\n"); return 2; }
  return run_client(a);
}
