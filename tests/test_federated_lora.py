"""Federated LoRA on the fast path (ISSUE 12).

The LoRA exchange must ride every cross-silo fast path: streaming/associative
folds (bitwise-equal to exact at staleness 0), compressed delta uploads with
the per-tree low-rank compression floor and EF residual carry, the trust gate
(secure-agg/FHE/defense configurations force exact buffer-all mode), the
pjit-sharded server fold (bitwise-equal to the host fold on the 8-device CPU
mesh), and the buffered-async server end to end with real silo trainers.
"""

import numpy as np
import pytest

from .conftest import tiny_config


def _lora_cfg(**kw):
    base = dict(
        training_type="cross_cloud",
        dataset="shakespeare",
        model="transformer",
        client_num_in_total=2,
        client_num_per_round=2,
        comm_round=2,
        epochs=1,
        batch_size=4,
        learning_rate=0.01,
        synthetic_train_size=128,
        synthetic_test_size=32,
        frequency_of_the_test=1,
        extra={"unitedllm": True, "lora_r": 4},
    )
    extra = kw.pop("extra", {})
    base.update(kw)
    merged = dict(base["extra"])
    merged.update(extra)
    base["extra"] = merged
    return tiny_config(**base)


def _make_lora_agg(extra=None, **kw):
    import fedml_tpu
    from fedml_tpu.data import loader
    from fedml_tpu.llm.unitedllm import LoRAAggregator

    cfg = _lora_cfg(extra=extra or {}, **kw)
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    return cfg, LoRAAggregator(cfg, ds)


def _upload_msg(cid, params):
    from fedml_tpu.comm.message import Message
    from fedml_tpu.cross_silo import message_define as md

    msg = Message(md.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, cid, 0)
    msg.add_params(md.MSG_ARG_KEY_MODEL_PARAMS, params)
    # decode(encode) produces the lazy tensor frame the fold path consumes
    return Message.decode(msg.encode())


def _perturbed(tree, seed):
    import jax

    r = np.random.RandomState(seed)
    return jax.tree_util.tree_map(
        lambda x: np.asarray(x, np.float32) + r.randn(*np.shape(x)).astype(np.float32),
        jax.device_get(tree))


def _leaves(tree):
    import jax

    return jax.tree_util.tree_leaves(jax.device_get(tree))


# ---------------------------------------------------------------------------
# streaming == exact, bitwise at staleness 0
# ---------------------------------------------------------------------------

def test_lora_streaming_matches_exact_bitwise(eight_devices):
    """LoRA adapter folds (streaming accumulator) vs the exact buffer-all
    aggregate, BITWISE: 2 silos with equal power-of-two sample counts make
    every weighted-mean step an exact f32 scaling, so any accumulator
    deviation shows up as a bit flip."""
    _, exact = _make_lora_agg()
    _, stream = _make_lora_agg(extra={"streaming_aggregation": True})
    assert not exact.stream_mode  # flags unset: exact path, unchanged default
    assert stream.stream_mode     # the LoRA opt-in (ISSUE 12 tentpole)

    base = exact.global_vars
    for cid in (1, 2):
        params = _perturbed(base, cid)
        exact.add_local_trained_result(cid, params, 64.0)
        assert stream.ingest_streaming(cid, _upload_msg(cid, params), 64.0,
                                       is_delta=False)
    assert stream.peak_buffered_updates <= 2
    a = exact.aggregate(0)
    b = stream.aggregate(0)
    for x, y in zip(_leaves(a), _leaves(b)):
        np.testing.assert_array_equal(x, y)


def test_lora_async_tau0_fold_matches_sync_bitwise(eight_devices):
    """The async fold at staleness 0 (scale = literal 1.0) is bitwise the
    synchronous streaming fold on the adapter tree."""
    from fedml_tpu.cross_silo.async_server import staleness_scale

    _, sync = _make_lora_agg(extra={"streaming_aggregation": True})
    _, asy = _make_lora_agg(extra={"streaming_aggregation": True})
    base = sync.global_vars
    for cid in (1, 2, 3):
        params = _perturbed(base, cid)
        assert sync.ingest_streaming(cid, _upload_msg(cid, params),
                                     16.0 + cid, is_delta=False)
        assert asy.fold(cid, _upload_msg(cid, params), 16.0 + cid,
                        is_delta=False, scale=staleness_scale(0, 0.5))
    for x, y in zip(_leaves(sync.aggregate(0)), _leaves(asy.aggregate(0))):
        np.testing.assert_array_equal(x, y)


# ---------------------------------------------------------------------------
# delta uploads + compression on low-rank factors
# ---------------------------------------------------------------------------

def test_lora_delta_uploads_match_full(eight_devices):
    """Adapter DELTA folds (is_delta=True, the compressed-upload shape)
    reconstruct the same aggregate as full-adapter folds across rounds."""
    import jax

    _, full = _make_lora_agg(extra={"streaming_aggregation": True})
    _, delt = _make_lora_agg(extra={"streaming_aggregation": True})
    for rnd in range(2):
        base = jax.device_get(full.global_vars)
        for cid in (1, 2):
            params = _perturbed(base, 10 * rnd + cid)
            delta = jax.tree_util.tree_map(
                lambda n, g: (np.asarray(n, np.float32)
                              - np.asarray(g, np.float32)), params, base)
            assert full.ingest_streaming(cid, _upload_msg(cid, params), 64.0,
                                         is_delta=False)
            assert delt.ingest_streaming(cid, _upload_msg(cid, delta), 64.0,
                                         is_delta=True)
        for x, y in zip(_leaves(full.aggregate(rnd)), _leaves(delt.aggregate(rnd))):
            np.testing.assert_allclose(x, y, rtol=1e-5, atol=1e-5)


def test_lora_qsgd8_quantize_then_fold_error_bound(eight_devices):
    """Quantize-then-fold on low-rank factors: with the per-tree compression
    floor every adapter leaf rides qsgd8, and the folded aggregate stays
    within one quantization step (block amax / 127) of the uncompressed
    fold — the error bound that makes compressed deltas usable."""
    import jax

    from fedml_tpu.comm import codecs, wire

    # q/k/v targets only: every rank-8 factor is exactly (128, 8)/(8, 128) —
    # 1024 elements, one qsgd8 block, BELOW the model-scale floor but above
    # the low-rank floor (the per-tree override is what makes them compress)
    lr_extra = {"streaming_aggregation": True, "lora_r": 8,
                "lora_targets": r".*attn/w[qkv]/kernel"}
    cfg, plain = _make_lora_agg(extra=dict(lr_extra))
    _, quant = _make_lora_agg(extra=dict(lr_extra))
    base = jax.device_get(plain.global_vars)
    leaf_sizes = [np.asarray(l).size for l in jax.tree_util.tree_leaves(base)]
    assert all(s >= codecs.LOW_RANK_MIN_COMPRESS_ELEMS for s in leaf_sizes)
    assert all(s < codecs.DEFAULT_MIN_COMPRESS_ELEMS + 1 for s in leaf_sizes)

    max_step = 0.0
    for cid in (1, 2):
        delta = jax.tree_util.tree_map(
            lambda g: np.random.RandomState(cid).randn(*np.shape(g)).astype(np.float32),
            base)
        comp, _, stats = codecs.compress_pytree(
            delta, "qsgd8", key=jax.random.PRNGKey(cid),
            min_elems=codecs.LOW_RANK_MIN_COMPRESS_ELEMS)
        n_comp = sum(isinstance(l, wire.CompressedLeaf)
                     for l in jax.tree_util.tree_leaves(
                         comp, is_leaf=lambda x: isinstance(x, wire.CompressedLeaf)))
        assert n_comp == len(leaf_sizes)  # EVERY rank-r factor compressed
        assert stats["ratio"] >= 3.5, stats
        max_step = max(max_step, max(
            np.abs(np.asarray(l)).max() / 127.0
            for l in jax.tree_util.tree_leaves(delta)))
        assert plain.ingest_streaming(cid, _upload_msg(cid, delta), 64.0,
                                      is_delta=True)
        assert quant.ingest_streaming(cid, _upload_msg(cid, comp), 64.0,
                                      is_delta=True)
    for x, y in zip(_leaves(plain.aggregate(0)), _leaves(quant.aggregate(0))):
        np.testing.assert_allclose(x, y, atol=max_step + 1e-6)


def test_lora_topk_ef_residual_carries_across_rounds(eight_devices):
    """top-k with error feedback on the adapter tree: each round's decoded
    upload plus its residual equals the residual-corrected delta, leaf-
    aligned across rounds (the invariant that makes EF converge)."""
    import jax

    from fedml_tpu.comm import codecs, wire

    cfg, agg = _make_lora_agg(extra={"lora_r": 8})
    base = jax.device_get(agg.global_vars)
    residuals = None
    prev_residuals = None
    for rnd in range(3):
        delta = jax.tree_util.tree_map(
            lambda g: np.random.RandomState(100 + rnd).randn(*np.shape(g)).astype(np.float32),
            base)
        comp, residuals, _ = codecs.compress_pytree(
            delta, "topk", key=jax.random.PRNGKey(rnd), residuals=residuals,
            ratio=0.05, min_elems=codecs.LOW_RANK_MIN_COMPRESS_ELEMS)
        decoded = wire.decode_pytree(wire.encode_pytree(comp))
        d_leaves = jax.tree_util.tree_leaves(delta)
        out_leaves = jax.tree_util.tree_leaves(decoded)
        for i, (d, o) in enumerate(zip(d_leaves, out_leaves)):
            corrected = d.reshape(-1)
            if prev_residuals is not None and prev_residuals[i] is not None:
                corrected = corrected + prev_residuals[i]
            if residuals[i] is None:
                # below-floor leaf rode raw: exact, no EF state
                np.testing.assert_array_equal(np.asarray(o).reshape(-1),
                                              corrected)
                continue
            np.testing.assert_allclose(
                np.asarray(o).reshape(-1) + residuals[i], corrected,
                rtol=1e-6, atol=1e-6)
        prev_residuals = residuals


def test_lora_client_low_rank_compression_floor(eight_devices):
    """The client manager picks up the trainer's per-tree
    comm_compress_min_elems (adapters compress under the model-scale
    default), and an EXPLICIT comm_compress_min_size flag still wins."""
    import fedml_tpu
    import jax

    from fedml_tpu.comm import codecs, wire
    from fedml_tpu.comm.inproc import InProcRouter
    from fedml_tpu.data import loader
    from fedml_tpu.llm.unitedllm import build_unitedllm_client

    cfg = _lora_cfg(run_id="lora_minsz", extra={"comm_compression": "qsgd8",
                                                "lora_r": 8})
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    InProcRouter.reset("lora_minsz")
    client = build_unitedllm_client(cfg, ds, rank=1, backend="INPROC")
    try:
        from fedml_tpu.llm import lora as lora_lib

        assert client._comm_min_elems == codecs.LOW_RANK_MIN_COMPRESS_ELEMS
        lora0 = _perturbed(lora_lib.init_lora(
            client.trainer.base_params, 8, jax.random.PRNGKey(0)), 1)
        new = _perturbed(lora0, 2)
        payload, is_delta = client._maybe_compress(new, lora0, 0)
        assert is_delta
        comp_leaves = [l for l in jax.tree_util.tree_leaves(
            payload, is_leaf=lambda x: isinstance(x, wire.CompressedLeaf))
            if isinstance(l, wire.CompressedLeaf)]
        assert comp_leaves, "no adapter leaf compressed under the per-tree floor"
    finally:
        client.finish()

    # explicit flag beats the trainer override
    cfg2 = _lora_cfg(run_id="lora_minsz2",
                     extra={"comm_compression": "qsgd8", "lora_r": 8,
                            "comm_compress_min_size": 10 ** 9})
    fedml_tpu.init(cfg2)
    InProcRouter.reset("lora_minsz2")
    client2 = build_unitedllm_client(cfg2, ds, rank=1, backend="INPROC")
    try:
        assert client2._comm_min_elems == 10 ** 9
    finally:
        client2.finish()


# ---------------------------------------------------------------------------
# trust gate: secure-agg/FHE/defense configurations force exact mode
# ---------------------------------------------------------------------------

def test_lora_trust_pipeline_forces_exact_mode(eight_devices):
    """The PR-4 gate regression (ISSUE 12 satellite): a configured trust
    pipeline must pin LoRA aggregation to the exact buffer-all path even
    when compression/streaming flags ask for the associative fold."""
    cfg, agg = _make_lora_agg(
        extra={"comm_compression": "qsgd8", "streaming_aggregation": True},
        enable_defense=True, defense_type="norm_diff_clipping",
        norm_bound=5.0)
    assert agg.trust is not None and agg.trust.active
    assert not agg.stream_mode
    assert not agg.fold(1, _upload_msg(1, _perturbed(agg.global_vars, 1)),
                        64.0, False)


def test_secure_aggregators_never_stream(eight_devices):
    """Secure-agg/FHE aggregators carry masked/ciphertext uploads that are
    not foldable f32 trees: stream_mode must stay off whatever the comm
    flags say (the explicit hardening the LoRA opt-in must not bypass)."""
    import fedml_tpu
    from fedml_tpu.cross_silo.lightsecagg import LSAAggregator
    from fedml_tpu.cross_silo.secagg_shamir import SAAggregator
    from fedml_tpu.data import loader
    from fedml_tpu.data.dataset import pad_eval_set
    from fedml_tpu.models import model_hub

    cfg = tiny_config(client_num_in_total=4, client_num_per_round=4,
                      extra={"comm_compression": "qsgd8",
                             "streaming_aggregation": True})
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)
    test_arrays = pad_eval_set(ds.test_x, ds.test_y, 32)
    for cls in (SAAggregator, LSAAggregator):
        agg = cls(cfg, model, ds.train_x[: cfg.batch_size], test_arrays)
        assert not agg.stream_mode, cls.__name__
        assert not agg._shard_fold, cls.__name__


# ---------------------------------------------------------------------------
# sharded fold == host fold, bitwise
# ---------------------------------------------------------------------------

def test_sharded_fold_matches_host_fold_bitwise(eight_devices):
    """extra.server_shard_fold: the NamedSharding'd device fold (including a
    delta contribution and the on-device finalize) is BITWISE the host numpy
    fold on the 8-device CPU mesh."""
    import jax

    _, host = _make_lora_agg(extra={"streaming_aggregation": True,
                                    "lora_r": 8})
    _, shard = _make_lora_agg(extra={"streaming_aggregation": True,
                                     "lora_r": 8, "server_shard_fold": True})
    assert not host._shard_fold and shard._shard_fold
    base = jax.device_get(host.global_vars)
    for cid, w in ((1, 16.0), (2, 32.0), (3, 37.0)):
        params = _perturbed(base, cid)
        is_delta = cid == 3  # exercise the finalize add-back on both paths
        payload = params if not is_delta else jax.tree_util.tree_map(
            lambda n, g: np.asarray(n, np.float32) - np.asarray(g, np.float32),
            params, base)
        assert host.ingest_streaming(cid, _upload_msg(cid, payload), w,
                                     is_delta=is_delta)
        assert shard.ingest_streaming(cid, _upload_msg(cid, payload), w,
                                      is_delta=is_delta)
    assert shard._stream_acc.kind == "sharded"
    # the accumulator leaves really live under NamedShardings on the mesh
    sharded_any = any(
        not s.sharding.is_fully_replicated for s in shard._stream_acc._sums)
    assert sharded_any, "no accumulator leaf actually sharded"
    a = host.aggregate(0)
    b = shard.aggregate(0)
    for x, y in zip(_leaves(a), _leaves(b)):
        np.testing.assert_array_equal(x, y)
    # the finalized global inherits the shardings (stays device-resident)
    assert any(
        hasattr(l, "sharding") and not l.sharding.is_fully_replicated
        for l in jax.tree_util.tree_leaves(b))


def test_sharded_fold_journal_roundtrip(eight_devices):
    """export/restore_stream_state round-trips the sharded accumulator's
    partial sums through the journal's host-array form."""
    import jax

    _, shard = _make_lora_agg(extra={"streaming_aggregation": True,
                                     "server_shard_fold": True})
    base = jax.device_get(shard.global_vars)
    assert shard.ingest_streaming(1, _upload_msg(1, _perturbed(base, 1)),
                                  64.0, is_delta=False)
    proto, arrays = shard.export_stream_state()
    assert proto["stream_folded"] == 1 and arrays

    _, restored = _make_lora_agg(extra={"streaming_aggregation": True,
                                        "server_shard_fold": True})
    restored.restore_stream_state(proto, arrays)
    assert restored._stream_acc is not None
    assert restored._stream_acc.kind == "sharded"
    assert restored.ingest_streaming(2, _upload_msg(2, _perturbed(base, 2)),
                                     64.0, is_delta=False)
    assert shard.ingest_streaming(2, _upload_msg(2, _perturbed(base, 2)),
                                  64.0, is_delta=False)
    for x, y in zip(_leaves(shard.aggregate(0)), _leaves(restored.aggregate(0))):
        np.testing.assert_array_equal(x, y)


# ---------------------------------------------------------------------------
# e2e: async LoRA with real silo trainers
# ---------------------------------------------------------------------------

def test_async_lora_e2e_inproc(eight_devices):
    """Buffered-async LoRA end to end: real silo trainers over the in-proc
    fabric, compressed delta uploads folding with staleness decay, virtual
    rounds closing at K arrivals, peak buffered <= 2."""
    import fedml_tpu
    from fedml_tpu.data import loader
    from fedml_tpu.llm.unitedllm import run_unitedllm_process_group

    cfg = _lora_cfg(
        run_id="lora_async", comm_round=2, batch_size=2,
        synthetic_train_size=64, synthetic_test_size=16,
        extra={"comm_compression": "qsgd8", "lora_r": 8,
               "async_aggregation": True, "async_buffer_k": 2,
               "async_staleness_exponent": 0.5,
               "async_redispatch_timeout_s": 10.0})
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    history, server = run_unitedllm_process_group(cfg, ds, backend="INPROC",
                                                  timeout=240.0)
    assert len(history) == 2
    assert server.aggregator.stream_mode
    assert server.aggregator.peak_buffered_updates <= 2
    assert np.isfinite(history[-1]["test_loss"])
    summary = server.async_summary()
    assert summary["server_version"] == 2
    assert summary["arrivals"] >= 4
