"""LLM stack tests: transformer, ring attention, sharding rules, LoRA,
pjit trainer, FedLLM.

Ring attention is verified EXACTLY against dense attention on the 8-device
mesh — the correctness bar for the long-context path (SURVEY.md §5 gap the
TPU build fills).
"""

import numpy as np
import pytest


def test_transformer_forward_and_loss(eight_devices):
    import jax
    import jax.numpy as jnp
    from fedml_tpu.models.transformer import Transformer, TransformerConfig

    cfg = TransformerConfig.tiny(vocab_size=256)
    model = Transformer(cfg)
    tokens = jnp.zeros((2, 64), jnp.int32)
    params = model.init({"params": jax.random.PRNGKey(0)}, tokens)["params"]
    logits = model.apply({"params": params}, tokens)
    assert logits.shape == (2, 64, 256)
    # lm_head runs in cfg.logits_dtype (bf16 default keeps the vocab matmul
    # on the MXU fast path); f32 must still be selectable for eval paths
    assert logits.dtype == cfg.logits_dtype
    f32_cfg = type(cfg)(**{**cfg.__dict__, "logits_dtype": jnp.float32})
    l32 = Transformer(f32_cfg).apply({"params": params}, tokens)
    assert l32.dtype == jnp.float32


def test_causality(eight_devices):
    """Future tokens must not affect past logits."""
    import jax
    import jax.numpy as jnp
    from fedml_tpu.models.transformer import Transformer, TransformerConfig

    cfg = TransformerConfig.tiny(vocab_size=64)
    model = Transformer(cfg)
    k = jax.random.PRNGKey(0)
    t1 = jax.random.randint(k, (1, 32), 0, 64)
    t2 = t1.at[:, 20:].set(jax.random.randint(jax.random.fold_in(k, 1), (1, 12), 0, 64))
    params = model.init({"params": k}, t1)["params"]
    l1 = model.apply({"params": params}, t1)
    l2 = model.apply({"params": params}, t2)
    np.testing.assert_allclose(l1[:, :20], l2[:, :20], atol=2e-2)


def test_ring_attention_matches_dense(eight_devices):
    import jax
    import jax.numpy as jnp
    from fedml_tpu.ops.ring_attention import dense_attention, ring_attention
    from fedml_tpu.parallel.mesh import make_mesh

    mesh = make_mesh(("sp",), (8,))
    k = jax.random.PRNGKey(0)
    b, s, h, d = 2, 64, 4, 16
    q = jax.random.normal(k, (b, s, h, d), jnp.float32)
    kk = jax.random.normal(jax.random.fold_in(k, 1), (b, s, h, d), jnp.float32)
    v = jax.random.normal(jax.random.fold_in(k, 2), (b, s, h, d), jnp.float32)
    for causal in (True, False):
        ref = dense_attention(q, kk, v, causal=causal)
        out = ring_attention(q, kk, v, mesh, axis="sp", causal=causal)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5)


def test_transformer_with_ring_attention(eight_devices):
    """Full model forward with seq sharded over 8 devices == unsharded."""
    import jax
    import jax.numpy as jnp
    from fedml_tpu.models.transformer import Transformer, TransformerConfig
    from fedml_tpu.parallel.mesh import make_mesh

    cfg = TransformerConfig.tiny(vocab_size=128)
    cfg = type(cfg)(**{**cfg.__dict__, "dtype": jnp.float32, "remat": False,
                       "logits_dtype": jnp.float32})
    mesh = make_mesh(("sp",), (8,))
    tokens = jax.random.randint(jax.random.PRNGKey(0), (2, 128), 0, 128)
    plain = Transformer(cfg)
    params = plain.init({"params": jax.random.PRNGKey(1)}, tokens)["params"]
    ref = plain.apply({"params": params}, tokens)
    ringed = Transformer(cfg, mesh=mesh, seq_axis="sp")
    out = ringed.apply({"params": params}, tokens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=3e-4, rtol=1e-4)


def test_sharding_rules(eight_devices):
    import jax
    import jax.numpy as jnp
    from fedml_tpu.models.transformer import Transformer, TransformerConfig
    from fedml_tpu.parallel.mesh import make_mesh
    from fedml_tpu.parallel import sharding

    cfg = TransformerConfig.tiny(vocab_size=128)
    mesh = make_mesh(("data", "model"), (2, 4))
    model = Transformer(cfg)
    tokens = jnp.zeros((2, 32), jnp.int32)
    params = model.init({"params": jax.random.PRNGKey(0)}, tokens)["params"]
    sharded = sharding.shard_params(params, mesh)
    # wq kernel must actually be sharded over the model axis
    wq = sharded["layer_0"]["attn"]["wq"]["kernel"]
    assert len(wq.sharding.device_set) > 1, wq.sharding
    # norms replicated
    scale = sharded["final_norm"]["scale"]
    assert scale.sharding.is_fully_replicated


def test_llm_trainer_dp_tp(eight_devices):
    """pjit train step over a 2x4 (data, model) mesh: loss decreases."""
    import jax
    from fedml_tpu.llm.train import LLMTrainArgs, LLMTrainer
    from fedml_tpu.models.transformer import TransformerConfig
    from fedml_tpu.parallel.mesh import make_mesh

    cfg = TransformerConfig.tiny(vocab_size=64)
    args = LLMTrainArgs(batch_size=4, seq_len=32, total_steps=12, learning_rate=1e-2, warmup_steps=2)
    mesh = make_mesh(("data", "model"), (2, 4))
    tr = LLMTrainer(cfg, args, mesh=mesh)

    # learnable synthetic stream: next token = (token + 1) % vocab
    import jax.numpy as jnp

    def batches():
        k = jax.random.PRNGKey(0)
        while True:
            k = jax.random.fold_in(k, 1)
            start = jax.random.randint(k, (args.batch_size, 1), 0, 64)
            seq = (start + jnp.arange(args.seq_len + 1)[None]) % 64
            yield seq[:, :-1], seq[:, 1:]

    hist = tr.fit(batches(), steps=12)
    assert hist[-1]["loss"] < hist[0]["loss"] * 0.7, [h["loss"] for h in hist]


def test_lora_merge_and_fedllm(eight_devices):
    import jax
    import jax.numpy as jnp
    import fedml_tpu
    from fedml_tpu.llm import lora as lora_lib
    from fedml_tpu.llm.fedllm import FedLLMSimulator
    from fedml_tpu.models.transformer import Transformer, TransformerConfig
    from fedml_tpu.arguments import Config
    from fedml_tpu.data import loader

    # lora zero-init => merge is identity
    cfg = TransformerConfig.tiny(vocab_size=64)
    model = Transformer(cfg)
    tokens = jnp.zeros((1, 16), jnp.int32)
    params = model.init({"params": jax.random.PRNGKey(0)}, tokens)["params"]
    lora = lora_lib.init_lora(params, rank=4, key=jax.random.PRNGKey(1))
    merged = lora_lib.merge(params, lora)
    np.testing.assert_allclose(
        np.asarray(merged["layer_0"]["attn"]["wq"]["kernel"]),
        np.asarray(params["layer_0"]["attn"]["wq"]["kernel"]),
    )
    assert lora_lib.lora_size(lora) < 0.2 * sum(p.size for p in jax.tree_util.tree_leaves(params))

    # end-to-end federated LoRA on the synthetic markov text task
    fcfg = Config(
        dataset="shakespeare", model="rnn", client_num_in_total=4, client_num_per_round=2,
        comm_round=3, epochs=1, batch_size=8, learning_rate=5e-3,
        synthetic_train_size=256, synthetic_test_size=64,
        partition_method="homo", frequency_of_the_test=3,
    )
    fedml_tpu.init(fcfg)
    ds = loader.load(fcfg)
    sim = FedLLMSimulator(fcfg, ds, tcfg=TransformerConfig.tiny(vocab_size=ds.class_num))
    hist = sim.run()
    assert np.isfinite(hist[-1]["test_ppl"])
    assert hist[-1]["train_loss"] < hist[0]["train_loss"] * 1.05


def test_fedllm_checkpoint_resume_parity(eight_devices, tmp_path):
    """2 rounds + checkpoint + fresh-simulator resume for 2 more == 4
    straight rounds, bit-for-bit on the adapter tree (the FedLLM
    PauseResumeCallback parity: round_idx + adapters + RNG are the state)."""
    import jax
    import numpy as np
    import fedml_tpu
    from fedml_tpu.arguments import Config
    from fedml_tpu.data import loader
    from fedml_tpu.llm.fedllm import FedLLMSimulator
    from fedml_tpu.models.transformer import TransformerConfig

    def cfg(**kw):
        base = dict(
            dataset="shakespeare", model="rnn", client_num_in_total=4,
            client_num_per_round=2, comm_round=4, epochs=1, batch_size=8,
            learning_rate=5e-3, synthetic_train_size=256, synthetic_test_size=64,
            partition_method="homo", frequency_of_the_test=0,
        )
        base.update(kw)
        return Config(**base)

    straight_cfg = cfg()
    fedml_tpu.init(straight_cfg)
    ds = loader.load(straight_cfg)
    tcfg = TransformerConfig.tiny(vocab_size=ds.class_num)
    straight = FedLLMSimulator(straight_cfg, ds, tcfg=tcfg)
    straight.run()

    ck = str(tmp_path / "fedllm-ck")
    first = FedLLMSimulator(cfg(comm_round=2, checkpoint_dir=ck,
                                checkpoint_every_rounds=1), ds, tcfg=tcfg)
    first.run()
    resumed = FedLLMSimulator(cfg(checkpoint_dir=ck, resume=True), ds, tcfg=tcfg)
    hist = resumed.run()
    assert [h["round"] for h in hist] == [2, 3]  # resumed mid-run

    a = jax.tree_util.tree_leaves(jax.device_get(straight.global_lora))
    b = jax.tree_util.tree_leaves(jax.device_get(resumed.global_lora))
    for x, y in zip(a, b):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), rtol=1e-6, atol=1e-7)
