"""Test harness: 8 virtual CPU devices so mesh sharding is exercised without
TPU hardware (SURVEY.md §4 takeaway: real in-proc transport fakes + virtual
multi-device tests instead of the reference's loopback process emulation)."""

import os

# Force CPU with 8 virtual devices (the ambient sitecustomize pins
# jax_platforms to the real TPU via jax.config; tests must not depend on
# hardware, so override both the env var and the config before any backend
# initialization).
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

# Runtime lock sanitizer (ISSUE 9): FEDML_TPU_LOCKSAN=1 swaps threading.Lock
# for an instrumented wrapper BEFORE any fedml_tpu module creates a lock, so
# the whole suite records the lock-order graph and a report dumps at exit.
# Strict no-op when the env var is unset (the sanitizer module is stdlib-only
# and its import creates no locks).
import sys as _sys

_sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
from fedml_tpu.analysis.sanitizer import maybe_install_from_env

maybe_install_from_env()

# Runtime trace sanitizer (ISSUE 20): FEDML_TPU_TRACESAN=1 activates the
# transfer/compile guard (jax.transfer_guard around steady-state rounds +
# a jax.monitoring compile listener) before any round code runs.  Strict
# no-op when the env var is unset — install() is the only path that
# imports jax from the module.
from fedml_tpu.analysis.tracesan import maybe_install_from_env as _tracesan_env

_tracesan_env()

import jax

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the suite is dominated by XLA compiles (the
# CNN zoo alone re-compiles ~20 models); caching them across runs cuts the
# 1-core wall clock severalfold.  The setup (host-CPU-fingerprinted dir at
# the repo root — see the module for the SIGILL rationale) is shared with
# the __graft_entry__ multichip dryrun and bench.py via core/cache.py, so
# all three warm the same cache.
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from fedml_tpu.core.cache import setup_persistent_cache

setup_persistent_cache()

import numpy as np
import pytest


def pytest_configure(config):
    # tier-1 runs `-m 'not slow'`; `locksan` (ISSUE 11 satellite) is the
    # runtime lock-sanitizer gate's collection marker — mark any threaded
    # e2e with @pytest.mark.locksan and test_sanitizer's gate re-runs it
    # under FEDML_TPU_LOCKSAN=1 without hard-coding test ids
    config.addinivalue_line(
        "markers", "slow: excluded from the tier-1 `-m 'not slow'` run")
    config.addinivalue_line(
        "markers",
        "locksan: threaded e2e included in the runtime lock-sanitizer gate "
        "(test_sanitizer re-runs `-m locksan` under FEDML_TPU_LOCKSAN=1)")
    config.addinivalue_line(
        "markers",
        "tracesan: steady-state round e2e included in the runtime trace-"
        "sanitizer gate (test_tracesan re-runs `-m tracesan` under "
        "FEDML_TPU_TRACESAN=1)")


@pytest.fixture(scope="session")
def eight_devices():
    import jax

    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


def tiny_config(**overrides):
    from fedml_tpu.arguments import Config

    base = dict(
        dataset="synthetic",
        model="lr",
        client_num_in_total=8,
        client_num_per_round=4,
        comm_round=2,
        epochs=1,
        batch_size=16,
        learning_rate=0.1,
        synthetic_train_size=640,
        synthetic_test_size=160,
        partition_method="homo",
        frequency_of_the_test=1,
        compute_dtype="float32",
        random_seed=0,
    )
    base.update(overrides)
    return Config(**base)


@pytest.fixture
def make_tiny_config():
    return tiny_config
