"""Test harness: 8 virtual CPU devices so mesh sharding is exercised without
TPU hardware (SURVEY.md §4 takeaway: real in-proc transport fakes + virtual
multi-device tests instead of the reference's loopback process emulation)."""

import os

# Force CPU with 8 virtual devices (the ambient sitecustomize pins
# jax_platforms to the real TPU via jax.config; tests must not depend on
# hardware, so override both the env var and the config before any backend
# initialization).
os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")

# Persistent compilation cache: the suite is dominated by XLA compiles (the
# CNN zoo alone re-compiles ~20 models); caching them across runs cuts the
# 1-core wall clock severalfold.  Keyed per repo checkout AND per host CPU
# fingerprint: XLA:CPU AOT entries compiled on a host with different machine
# features load with "could lead to SIGILL" warnings and occasionally abort
# the process mid-suite (observed: Fatal Python error: Aborted inside a
# jitted round) — a cache written on another machine must never be read.
import hashlib as _hashlib
import platform as _platform

_cpu_flags = _platform.machine() + _platform.processor()
try:
    _seen = set()
    with open("/proc/cpuinfo") as _f:
        for _line in _f:
            # x86 says "flags", aarch64 says "Features"; model lines cover
            # hosts with neither.  First occurrence of each key (cpuinfo
            # repeats per core) — the feature list is the actual contract.
            _key = _line.split(":", 1)[0].strip()
            if _key in ("flags", "Features", "model name", "CPU part") and _key not in _seen:
                _seen.add(_key)
                _cpu_flags += _line.strip()
except OSError:
    pass
_host_tag = _hashlib.sha1(_cpu_flags.encode()).hexdigest()[:12]
_cache_dir = os.path.join(os.path.dirname(__file__), "..", f".jax_cache-{_host_tag}")
jax.config.update("jax_compilation_cache_dir", os.path.abspath(_cache_dir))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

import numpy as np
import pytest


@pytest.fixture(scope="session")
def eight_devices():
    import jax

    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


def tiny_config(**overrides):
    from fedml_tpu.arguments import Config

    base = dict(
        dataset="synthetic",
        model="lr",
        client_num_in_total=8,
        client_num_per_round=4,
        comm_round=2,
        epochs=1,
        batch_size=16,
        learning_rate=0.1,
        synthetic_train_size=640,
        synthetic_test_size=160,
        partition_method="homo",
        frequency_of_the_test=1,
        compute_dtype="float32",
        random_seed=0,
    )
    base.update(overrides)
    return Config(**base)


@pytest.fixture
def make_tiny_config():
    return tiny_config
