"""Master/worker deploy protocol e2e (reference model_scheduler
master/worker protocol managers): placement across workers, readiness
aggregation, routed inference with failover, scale and undeploy commands —
all over the comm plane."""

import time

import numpy as np
import pytest

from .conftest import tiny_config


@pytest.fixture
def lr_card(tmp_path, eight_devices):
    import jax
    import jax.numpy as jnp

    import fedml_tpu
    from fedml_tpu.models import model_hub
    from fedml_tpu.serving.deploy import ModelCard, save_params_card

    cfg = tiny_config()
    fedml_tpu.init(cfg)
    model = model_hub.create(cfg, 10)
    variables = model.init({"params": jax.random.PRNGKey(0)}, jnp.zeros((1, 32)), train=True)
    path = save_params_card(variables, str(tmp_path / "lr.wire"))
    return ModelCard(name="lr-proto", version="v1", model="lr", classes=10, params_path=path)


def test_master_worker_deploy_protocol(tmp_path, lr_card, eight_devices):
    import fedml_tpu
    from fedml_tpu.comm.inproc import InProcRouter
    from fedml_tpu.serving.deploy_protocol import DeployMasterManager, DeployWorkerManager

    cfg = tiny_config(run_id="deploy-proto")
    cfg = __import__("dataclasses").replace(cfg, backend="INPROC")
    fedml_tpu.init(cfg)
    InProcRouter.reset("deploy-proto")

    master = DeployMasterManager(cfg, backend="INPROC")
    master.run_in_thread()
    workers = [
        DeployWorkerManager(cfg, rank=r, workdir=str(tmp_path), backend="INPROC",
                            capacity=2)
        for r in (1, 2)
    ]
    for w in workers:
        w.run_in_thread()
        w.start()
    try:
        master.wait_workers(2, timeout=30)

        # deploy 1 replica, then scale UP to 3: the scale must spread onto a
        # worker that never saw the original DEPLOY (the card rides the
        # SCALE message) and split across capacity-2 workers
        placement = master.deploy("demo", lr_card, replicas=1)
        assert sum(placement.values()) == 1, placement
        assert master.wait_ready("demo", replicas=1, timeout=180)
        placement = master.scale("demo", 3)
        assert sum(placement.values()) == 3 and len(placement) == 2, placement
        assert master.wait_ready("demo", replicas=3, timeout=180)

        out = master.predict("demo", {"inputs": np.zeros((2, 32)).tolist()})
        assert len(out["outputs"]) == 2 and len(out["outputs"][0]) == 10

        # kill one replica process on worker 1: its local scheduler restarts
        # it and the master's routing table re-converges via status reports
        ep = workers[0].sched.endpoints["demo"]
        victim = next(iter(ep.procs.values()))
        victim.kill()
        # a replacement replica is a fresh jax subprocess: boot alone can
        # take ~60s on the loaded 1-core CI box
        deadline = time.time() + 240
        recovered = False
        while time.time() < deadline and not recovered:
            # assert on the OBSERVED condition: readiness reports are
            # periodic snapshots, so re-querying after the loop could catch
            # a transient probe dip and flake
            if len(master.ready_targets("demo")) >= 3:
                try:
                    master.predict("demo", {"inputs": np.zeros((1, 32)).tolist()})
                    recovered = True
                except RuntimeError:
                    pass
            time.sleep(0.2)
        assert recovered, master.ready_targets("demo")

        # over-capacity requests are refused up front
        with pytest.raises(RuntimeError, match="capacity exhausted"):
            master.deploy("too-big", lr_card, replicas=99)

        # scale down to 1 replica total
        master.scale("demo", 1)
        deadline = time.time() + 60
        while time.time() < deadline and len(master.ready_targets("demo")) != 1:
            time.sleep(0.2)
        assert len(master.ready_targets("demo")) == 1

        master.undeploy("demo")
        deadline = time.time() + 60
        while time.time() < deadline and any(
            w.sched.endpoints for w in workers
        ):
            time.sleep(0.2)
        assert all(not w.sched.endpoints for w in workers)
    finally:
        master.shutdown_workers()
        for w in workers:
            w.stop()
        master.finish()
