"""FHE aggregation tests (VERDICT item 7, reference core/fhe/fhe_agg.py).

Properties: RLWE encrypt/decrypt roundtrip, homomorphic addition, and the
end-to-end cross-silo guarantee — encrypted-path model ≈ plaintext-path model
while the server never holds an individual plaintext update.
"""

import numpy as np
import pytest

from .conftest import tiny_config


def test_rlwe_roundtrip_and_homomorphic_add():
    from fedml_tpu.trust.fhe.rlwe import RLWECipher, RLWEParams, add_ciphertexts, scale_ciphertext

    cipher = RLWECipher(RLWEParams(n=256), key_seed=42)
    rng = np.random.RandomState(0)
    x = rng.uniform(-3, 3, size=500)
    blocks = cipher.encrypt_vector(x)
    back = cipher.decrypt_vector(blocks, len(x))
    np.testing.assert_allclose(back, x, atol=2e-4)  # 16-bit fixed point

    # sum of 5 ciphertexts decrypts to the sum of plaintexts
    vecs = [rng.uniform(-2, 2, size=500) for _ in range(5)]
    # independent encryptors sharing the key (separate encryption randomness)
    encs = [RLWECipher(RLWEParams(n=256), key_seed=42) for _ in range(5)]
    summed = add_ciphertexts([e.encrypt_vector(v) for e, v in zip(encs, vecs)],
                             cipher.params.q)
    np.testing.assert_allclose(
        cipher.decrypt_vector(summed, 500), np.sum(vecs, axis=0), atol=2e-3
    )

    # integer scalar multiply
    tripled = scale_ciphertext(blocks, 3, cipher.params.q)
    np.testing.assert_allclose(cipher.decrypt_vector(tripled, len(x)), 3 * x, atol=1e-3)

    # a different key seed cannot decrypt
    wrong = RLWECipher(RLWEParams(n=256), key_seed=43)
    garbage = wrong.decrypt_vector(blocks, len(x))
    assert np.mean(np.abs(garbage - x)) > 100.0


def _fhe_config(**kw):
    base = dict(
        client_num_in_total=4,
        client_num_per_round=4,
        comm_round=2,
        epochs=1,
        batch_size=16,
        synthetic_train_size=256,
        synthetic_test_size=64,
        training_type="cross_silo",
        enable_fhe=True,
        frequency_of_the_test=1,
    )
    base.update(kw)
    return tiny_config(**base)


def test_fhe_cross_silo_matches_plaintext(eight_devices):
    import fedml_tpu
    from fedml_tpu.cross_silo import run_in_process_group
    from fedml_tpu.cross_silo.fhe import FHEAggregator, run_fhe_process_group
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub

    cfg = _fhe_config(run_id="fhe1")
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)

    # spy: every model payload reaching the server must be int64 ciphertext
    seen = []
    orig = FHEAggregator.add_local_trained_result

    def spy(self, client_idx, blocks, sample_num):
        seen.append(np.asarray(blocks))
        orig(self, client_idx, blocks, sample_num)

    FHEAggregator.add_local_trained_result = spy
    try:
        history, server = run_fhe_process_group(cfg, ds, model, timeout=240.0)
    finally:
        FHEAggregator.add_local_trained_result = orig

    assert len(history) == cfg.comm_round
    assert len(seen) == cfg.comm_round * cfg.client_num_in_total
    for arr in seen:
        assert arr.dtype == np.int64 and arr.ndim == 3 and arr.shape[1] == 2

    cfg2 = _fhe_config(run_id="fhe1p", enable_fhe=False)
    plain_history = run_in_process_group(cfg2, ds, model, timeout=120.0)
    for h_fhe, h_plain in zip(history, plain_history):
        assert abs(h_fhe["test_acc"] - h_plain["test_acc"]) < 0.05, (h_fhe, h_plain)


def test_fhe_flag_guards(eight_devices):
    import fedml_tpu
    from fedml_tpu.runner import FedMLRunner

    with pytest.raises(NotImplementedError, match="cross-silo"):
        FedMLRunner(_fhe_config(training_type="simulation"))

    # FHE + SecAgg together is refused loudly
    from fedml_tpu.cross_silo.fhe import check_fhe_compatible

    with pytest.raises(NotImplementedError, match="enable_secagg"):
        check_fhe_compatible(_fhe_config(enable_secagg=True))
