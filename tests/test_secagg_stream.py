"""Streaming secure aggregation (ISSUE 15).

The headline is a MEMORY claim with an integer proof: masked uploads fold
one at a time into a field accumulator (peak buffered <= 2 at any cohort
size), and because the masking ring makes every sum exact, the streamed
masked total unmasks to BITWISE the buffer-all protocol's result — no FMA
tolerance anywhere.  The suite pins:

1. the ring/pack/quantize primitives (trust/secagg/stream.py),
2. the streaming fold + dropout recovery at finalize, incl. the Shamir
   threshold boundary (t+1 reveals reconstruct, t fail loudly),
3. the real 4-client Shamir protocol: stream == legacy bitwise, dropouts
   before upload / after upload (no reveal) / during finalize,
4. quantize-then-mask (qsgd8 grid in a cohort-sized ring) composing with
   the wire, and central DP landing exactly once at finalize (Pallas path),
5. the trust-pipeline gate relaxation: CDP-only pipelines stream bitwise,
   while defense/LDP/FHE/SA/LSA configurations still pin exact mode,
6. the ISSUE-15 lint satellite: the secagg modules hold zero legacy
   statement-position ``extra`` idioms (regression-pinned).
"""

import numpy as np
import pytest

from .conftest import tiny_config


def _sa_config(**kw):
    base = dict(
        client_num_in_total=4,
        client_num_per_round=4,
        comm_round=2,
        epochs=1,
        batch_size=16,
        synthetic_train_size=256,
        synthetic_test_size=64,
        training_type="cross_silo",
        enable_secagg=True,
        frequency_of_the_test=0,
        extra={"secagg_method": "shamir", "secagg_stream": True},
    )
    extra = kw.pop("extra", {})
    base.update(kw)
    merged = dict(base["extra"])
    merged.update(extra)
    base["extra"] = merged
    return tiny_config(**base)


def _run_sa(cfg, **kw):
    import fedml_tpu
    from fedml_tpu.cross_silo.secagg_shamir import run_shamir_secagg_process_group
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub

    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)
    return run_shamir_secagg_process_group(cfg, ds, model, timeout=120.0, **kw)


def _leaves_equal(a, b) -> bool:
    import jax

    la = jax.tree_util.tree_leaves(jax.device_get(a))
    lb = jax.tree_util.tree_leaves(jax.device_get(b))
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y)) for x, y in zip(la, lb))


# -- 1) primitives ------------------------------------------------------------

def test_pack_ring_roundtrip_all_widths():
    from fedml_tpu.trust.secagg import stream as st

    rng = np.random.default_rng(0)
    for bits, per_elem in ((8, 1), (11, 2), (16, 2), (23, 3), (24, 3),
                           (31, 4), (32, 4)):
        v = rng.integers(0, (1 << bits) - 1, 777, dtype=np.int64)
        packed = st.pack_ring(v, bits)
        assert packed.nbytes == 777 * per_elem, bits
        out = st.unpack_ring(packed, bits, 777)
        assert np.array_equal(v, out), bits
    with pytest.raises(ValueError):
        st.unpack_ring(st.pack_ring(rng.integers(0, 255, 10), 8), 8, 11)
    with pytest.raises(ValueError):
        st.pack_ring(rng.integers(0, 7, 4), 40)


def test_ring_sizing_and_meta():
    from fedml_tpu.trust.secagg import stream as st

    r4 = st.ring_for("qsgd8", 4, q_bits=16, q8_frac_bits=7)
    assert r4.bits == 11 and r4.wire_nbytes(1) == 2  # u16 at a 4-cohort
    r10k = st.ring_for("qsgd8", 10_000, q_bits=16, q8_frac_bits=7)
    assert r10k.bits == 23 and r10k.wire_nbytes(1) == 3  # packed 3-byte
    dense = st.ring_for(None, 10_000, q_bits=16, q8_frac_bits=7)
    assert dense.bits == 31 and dense.wire_nbytes(1) == 4  # u32, prime field
    from fedml_tpu.trust.secagg.field import DEFAULT_PRIME

    assert dense.modulus == DEFAULT_PRIME
    meta = r4.meta(100)
    assert r4.matches(meta) and not r10k.matches(meta) and not dense.matches(meta)
    # topk has no masked composition: unknown codecs are refused loudly
    with pytest.raises(ValueError):
        st.MaskedRing("topk", 4, 7)


def test_stochastic_int8_quantizer_unbiased_and_clipped():
    from fedml_tpu.trust.secagg import stream as st

    x = np.random.default_rng(1).normal(0, 0.1, 4096).astype(np.float32)
    qs = np.stack([st.quantize_stochastic_int8(x, 7, [s, 3]) for s in range(64)])
    assert qs.min() >= -127 and qs.max() <= 127
    err = np.abs(qs.mean(0) / 128.0 - np.clip(x, -127 / 128, 127 / 128))
    assert err.max() < 0.02, err.max()
    # determinism: same seed -> same draw
    assert np.array_equal(st.quantize_stochastic_int8(x, 7, [9, 9]),
                          st.quantize_stochastic_int8(x, 7, [9, 9]))
    # clipping engages on out-of-grid values
    big = np.asarray([10.0, -10.0], np.float32)
    assert np.array_equal(st.quantize_stochastic_int8(big, 7, 0),
                          np.asarray([127, -127]))


def test_field_accumulator_lazy_reduction_exact():
    from fedml_tpu.parallel.stream_fold import FieldStreamAccumulator

    p = 2**23
    acc = FieldStreamAccumulator([np.zeros(64, np.int64)], p)
    rng = np.random.default_rng(2)
    expect = np.zeros(64, np.int64)
    for _ in range(300):
        v = rng.integers(0, p, 64, dtype=np.int64)
        acc.fold_leaf(0, v)
        expect = (expect + v) % p
    assert np.array_equal(acc.host_sums()[0], expect)
    # restart from a journaled sum
    acc2 = FieldStreamAccumulator([np.zeros(64, np.int64)], p,
                                  sums=acc.host_sums())
    acc2.fold_leaf(0, np.ones(64, np.int64))
    assert np.array_equal(acc2.host_sums()[0], (expect + 1) % p)


def test_streaming_masked_sum_exact_with_dropouts():
    """Fold-one-at-a-time == batch sum, with clients dropping BEFORE upload
    (orphaned pair masks cancelled from seeds) — the integer identity."""
    from fedml_tpu.trust.secagg import stream as st

    n, d = 8, 300
    ring = st.ring_for("qsgd8", n, q_bits=16, q8_frac_bits=7)
    drop_before = {5, 7}
    q = {u: st.quantize_stochastic_int8(
        np.random.default_rng(u).normal(0, 0.05, d).astype(np.float32),
        ring.frac_bits, u) for u in range(1, n + 1)}
    self_seed = {u: 1000 + u for u in range(1, n + 1)}
    pair = {(u, v): 7000 + min(u, v) * 100 + max(u, v)
            for u in range(1, n + 1) for v in range(1, n + 1) if u != v}
    msum = st.StreamingMaskedSum(d, ring)
    for u in range(1, n + 1):
        if u in drop_before:
            continue
        peers = {v: pair[(u, v)] for v in range(1, n + 1) if v != u}
        msum.fold(st.mask_vector(np.mod(q[u], ring.modulus), u, peers,
                                 self_seed[u], ring.modulus))
    survivors = [u for u in range(1, n + 1) if u not in drop_before]
    total = msum.finalize(
        {u: self_seed[u] for u in survivors},
        {(i, j): pair[(i, j)] for i in drop_before for j in survivors})
    assert np.array_equal(total, sum(q[u] for u in survivors))
    assert msum.peak_buffered <= 2
    # a masked upload alone is field noise, not the plaintext
    assert not np.array_equal(msum.masked_total() % ring.modulus,
                              sum(q[u] for u in survivors) % ring.modulus)


def test_pallas_noise_kernel_matches_reference():
    import jax
    import jax.numpy as jnp

    from fedml_tpu.ops.pallas import noise as nz

    x = jnp.asarray(np.random.default_rng(0).normal(0, 1, 2500).astype(np.float32))
    k = jax.random.PRNGKey(7)
    out = nz.apply_gaussian_noise(x, k, 0.25, interpret=True)
    ref = nz.apply_gaussian_noise_reference(x, k, 0.25)
    assert out.shape == x.shape
    assert bool(jnp.all(out == ref))
    # sigma=0 is the identity
    assert bool(jnp.all(nz.apply_gaussian_noise(x, k, 0.0, interpret=True) == x))


# -- 2) threshold boundary ----------------------------------------------------

def test_shamir_threshold_boundary_t_plus_one_vs_t(eight_devices):
    """The hard decode bound at finalize: with exactly T+1 reveals the
    streamed round reconstructs; with T it must fail loudly (never a wrong
    silent aggregate)."""
    import fedml_tpu
    from fedml_tpu.cross_silo.secagg_shamir import (
        SAAggregator, derive_round_seed, shamir_secagg_params,
    )
    from fedml_tpu.data import loader
    from fedml_tpu.data.dataset import pad_eval_set
    from fedml_tpu.models import model_hub
    from fedml_tpu.trust.secagg import stream as st
    from fedml_tpu.trust.secagg.shamir import shamir_share

    cfg = _sa_config(run_id="sas_thr", extra={"secagg_privacy_t": 2})
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)
    t, _ = shamir_secagg_params(cfg)
    assert t == 2

    def build_round(n_reveals):
        agg = SAAggregator(cfg, model, ds.train_x[:16],
                           pad_eval_set(ds.test_x, ds.test_y, 32))
        assert agg.field_stream
        rng_np = np.random.RandomState(3)
        b = {u: 500 + u for u in range(1, 5)}
        shares = {u: shamir_share(b[u], 4, t + 1, rng_np) for u in b}
        for u in range(1, 5):
            xf = np.mod(np.full(agg.model_dim, u, np.int64), agg.ring.modulus)
            seed = derive_round_seed(b[u], 0)
            masked = st.mask_vector(xf, u, {}, seed, agg.ring.modulus)
            agg.add_masked_upload(u, st.pack_ring(masked, agg.ring.bits), 1.0,
                                  dict(agg.ring.meta(agg.model_dim), delta=False))
        for v in range(1, n_reveals + 1):
            agg.add_reveal(v, {str(u): shares[u][v - 1][1] for u in b}, {})
        return agg

    ok = build_round(t + 1)
    ok.aggregate(0)  # reconstructs
    assert ok.peak_buffered_updates <= 2
    short = build_round(t)
    with pytest.raises(RuntimeError, match="not enough b-shares"):
        short.aggregate(0)


# -- 3) the real protocol -----------------------------------------------------

def test_stream_dense_bitwise_vs_legacy(eight_devices):
    """Mod-field exactness: a streamed run's final global is BITWISE the
    buffer-all run's, even though each run drew fresh OS-entropy masks —
    the masks cancel exactly."""
    import jax

    h_s, srv_s = _run_sa(_sa_config(run_id="sas1"))
    h_l, srv_l = _run_sa(_sa_config(run_id="sas1l", extra={"secagg_stream": False}))
    assert len(h_s) == len(h_l) == 2
    assert srv_s.aggregator.field_stream and not srv_l.aggregator.field_stream
    assert srv_s.aggregator.peak_buffered_updates <= 2
    # legacy buffers the whole cohort
    assert srv_l.aggregator.peak_buffered_updates >= 4
    assert _leaves_equal(srv_s.aggregator.global_vars,
                         srv_l.aggregator.global_vars)
    _ = jax  # keep the import for device_get inside _leaves_equal


def test_stream_qsgd8_quantize_then_mask(eight_devices):
    """comm_compression=qsgd8 and SecAgg STACK: masked int8-grid deltas on
    the u16 ring wire (4-cohort), 2x under the dense f32 equivalent, and
    the run still learns."""
    from fedml_tpu.comm import codecs

    before = codecs.PAYLOAD_BYTES.value(codec="secagg_qsgd8")
    raw_before = codecs.PAYLOAD_RAW_BYTES.value(codec="secagg_qsgd8")
    cfg = _sa_config(run_id="sas2", frequency_of_the_test=1,
                     extra={"comm_compression": "qsgd8"})
    h, srv = _run_sa(cfg)
    assert srv.aggregator.ring.codec == "qsgd8"
    assert srv.aggregator.ring.bits == 11  # 8 value bits + 2 carry + 1 sign
    assert srv.aggregator.peak_buffered_updates <= 2
    assert h[-1]["test_acc"] > 0.4, h
    wire = codecs.PAYLOAD_BYTES.value(codec="secagg_qsgd8") - before
    raw = codecs.PAYLOAD_RAW_BYTES.value(codec="secagg_qsgd8") - raw_before
    assert wire > 0 and raw / wire >= 1.9, (raw, wire)


def test_stream_dropout_before_upload_bitwise(eight_devices):
    """Client 4 completes setup but never uploads: the streamed round
    reconstructs s_sk_4 from the reveals and cancels its orphaned pair
    masks from SEEDS at finalize (never re-buffering) — bitwise the legacy
    dropout round."""
    extra = {"straggler_timeout_s": 2.0, "straggler_quorum_frac": 0.5,
             "secagg_privacy_t": 2}
    h_s, srv_s = _run_sa(_sa_config(run_id="sas3", comm_round=1, extra=extra),
                         drop_ranks=frozenset({4}))
    h_l, srv_l = _run_sa(
        _sa_config(run_id="sas3l", comm_round=1,
                   extra=dict(extra, secagg_stream=False)),
        drop_ranks=frozenset({4}))
    assert len(h_s) == len(h_l) == 1
    assert 4 in srv_s.aggregator.compromised
    assert srv_s.aggregator.peak_buffered_updates <= 2
    assert _leaves_equal(srv_s.aggregator.global_vars,
                         srv_l.aggregator.global_vars)


def test_stream_dropout_after_upload_and_during_finalize(eight_devices):
    """Client 4 uploads its masked model, then vanishes BEFORE the reveal
    phase (drops during finalize): the reveal-phase straggler timeout
    proceeds with the T+1 surviving reveals, client 4's self-mask is
    reconstructed from its PEERS' b-shares, and its upload stays in the
    aggregate — bitwise the full-participation run."""
    import fedml_tpu
    from fedml_tpu.comm.inproc import InProcRouter
    from fedml_tpu.cross_silo.secagg_shamir import build_sa_client, build_sa_server
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub

    extra = {"straggler_timeout_s": 2.0, "straggler_quorum_frac": 0.5,
             "secagg_privacy_t": 2}
    cfg = _sa_config(run_id="sas4", comm_round=1, extra=extra)
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)
    InProcRouter.reset(str(cfg.run_id))
    clients = [build_sa_client(cfg, ds, model, rank=r, backend="INPROC")
               for r in range(1, 5)]
    # rank 4 trains + uploads, then never answers the ACTIVE_SET request
    clients[3].handle_message_active_set = lambda msg: None
    for c in clients:
        c.run_in_thread()
    server = build_sa_server(cfg, ds, model, backend="INPROC")
    try:
        history = server.run_until_done(timeout=120.0)
    finally:
        for c in clients:
            c.finish()
    assert len(history) == 1
    assert server.aggregator.peak_buffered_updates <= 2
    # all four uploads are in the sum: equals the no-dropout legacy run
    _, srv_full = _run_sa(_sa_config(run_id="sas4l", comm_round=1,
                                     extra=dict(extra, secagg_stream=False)))
    assert _leaves_equal(server.aggregator.global_vars,
                         srv_full.aggregator.global_vars)


# -- 4) central DP at finalize ------------------------------------------------

def test_central_dp_exactly_once_at_finalize(eight_devices):
    """enable_dp + cdp composes with secagg_stream (LDP stays refused): the
    noise lands once, deterministically from the round key, via the Pallas
    noise path — pinned against the manual clip+noise of the no-DP run's
    aggregate."""
    import jax
    import jax.flatten_util
    import jax.numpy as jnp

    from fedml_tpu.core import rng as rnglib
    from fedml_tpu.ops.pallas import noise as nz
    from fedml_tpu.trust.dp.dp import clip_by_norm, gaussian_sigma

    dp_kw = dict(enable_dp=True, dp_solution_type="cdp",
                 mechanism_type="gaussian", epsilon=50.0, delta=1e-5,
                 sensitivity=0.01, clipping_norm=1.0)
    h_dp, srv_dp = _run_sa(_sa_config(run_id="sas5", comm_round=1, **dp_kw))
    h_dp2, srv_dp2 = _run_sa(_sa_config(run_id="sas5b", comm_round=1, **dp_kw))
    h_plain, srv_plain = _run_sa(_sa_config(run_id="sas5p", comm_round=1))
    # deterministic: two DP runs agree bitwise; and DP actually changed it
    assert _leaves_equal(srv_dp.aggregator.global_vars,
                         srv_dp2.aggregator.global_vars)
    assert not _leaves_equal(srv_dp.aggregator.global_vars,
                             srv_plain.aggregator.global_vars)
    # manual expectation from the no-DP aggregate (noise applied ONCE);
    # the initial global is deterministic from random_seed — no run needed
    import fedml_tpu
    from fedml_tpu.cross_silo.secagg_shamir import build_sa_server
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub

    icfg = _sa_config(run_id="sas5i", comm_round=1)
    fedml_tpu.init(icfg)
    ids = loader.load(icfg)
    init_srv = build_sa_server(icfg, ids, model_hub.create(icfg, ids.class_num),
                               backend="INPROC")
    init_flat, _ = jax.flatten_util.ravel_pytree(init_srv.aggregator.global_vars)
    init_srv.finish()
    agg_flat, _ = jax.flatten_util.ravel_pytree(srv_plain.aggregator.global_vars)
    delta = clip_by_norm(jnp.asarray(agg_flat) - jnp.asarray(init_flat), 1.0)
    key = jax.random.fold_in(rnglib.round_key(rnglib.root_key(0), 0), 0xCD9)
    sigma = gaussian_sigma(50.0, 1e-5, 0.01)
    expect = nz.apply_gaussian_noise(jnp.asarray(init_flat) + delta, key, sigma,
                                     interpret=True)
    got, _ = jax.flatten_util.ravel_pytree(srv_dp.aggregator.global_vars)
    assert np.array_equal(np.asarray(got), np.asarray(expect, np.float32))


def test_ldp_with_secagg_still_refused():
    from fedml_tpu.cross_silo.secagg_shamir import shamir_secagg_params

    cfg = _sa_config(run_id="sas6", enable_dp=True, dp_solution_type="ldp")
    with pytest.raises(NotImplementedError, match="enable_dp"):
        shamir_secagg_params(cfg)
    # and cdp WITHOUT the streaming fold keeps the historical refusal
    cfg2 = _sa_config(run_id="sas6b", enable_dp=True, dp_solution_type="cdp",
                      extra={"secagg_stream": False})
    with pytest.raises(NotImplementedError, match="enable_dp"):
        shamir_secagg_params(cfg2)


# -- 5) trust gate: stream where sound, exact everywhere else -----------------

def _plain_aggregator(run_id, trust=True, **kw):
    import fedml_tpu
    from fedml_tpu.cross_silo.server import FedMLAggregator
    from fedml_tpu.data import loader
    from fedml_tpu.data.dataset import pad_eval_set
    from fedml_tpu.models import model_hub
    from fedml_tpu.trust.pipeline import build_trust_pipeline

    base = dict(client_num_in_total=2, client_num_per_round=2, comm_round=1,
                epochs=1, batch_size=16, synthetic_train_size=128,
                synthetic_test_size=64, training_type="cross_silo",
                frequency_of_the_test=0, run_id=run_id)
    base.update(kw)
    cfg = tiny_config(**base)
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)
    tp = build_trust_pipeline(cfg) if trust else None
    return FedMLAggregator(cfg, model, ds.train_x[:16],
                           pad_eval_set(ds.test_x, ds.test_y, 32), trust=tp), ds


def _feed_two(agg, base):
    from fedml_tpu.comm.message import Message
    from fedml_tpu.cross_silo import message_define as md

    import jax

    for cid in (1, 2):
        rs = np.random.RandomState(cid)
        params = jax.tree_util.tree_map(
            lambda x: np.asarray(x, np.float32)
            + rs.randn(*np.shape(x)).astype(np.float32), base)
        if agg.stream_mode:
            m = Message(md.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, cid, 0)
            m.add_params(md.MSG_ARG_KEY_MODEL_PARAMS, params)
            assert agg.ingest_streaming(cid, Message.decode(m.encode()), 64.0,
                                        is_delta=False)
        else:
            agg.add_local_trained_result(cid, params, 64.0)


def test_cdp_trust_streams_bitwise_sync_and_async_flags(eight_devices):
    """The gate relaxation: a CDP-only trust pipeline no longer forces
    exact mode — under either the sync streaming flag or the async flag the
    fold engages, and the finalized (clipped+noised) global is BITWISE the
    exact buffer-all CDP result."""
    import jax

    dp = dict(enable_dp=True, dp_solution_type="cdp", mechanism_type="gaussian",
              epsilon=100.0, delta=1e-5, sensitivity=0.01, clipping_norm=1.0)
    stream, _ = _plain_aggregator("tg1", extra={"streaming_aggregation": True}, **dp)
    async_agg, _ = _plain_aggregator("tg2", extra={"async_aggregation": True}, **dp)
    exact, _ = _plain_aggregator("tg3", **dp)
    assert stream.stream_mode and async_agg.stream_mode
    assert not exact.stream_mode
    base = jax.device_get(exact.global_vars)
    _feed_two(stream, base)
    _feed_two(exact, base)
    assert stream._stream_folded == 2 and exact._stream_folded == 0
    assert _leaves_equal(stream.aggregate(0), exact.aggregate(0))


def test_defense_ldp_fhe_salsa_still_exact(eight_devices):
    """Regression pins (ISSUE 15 satellite): every configuration that needs
    the stacked per-client matrix still takes the buffer-all path exactly
    as before the PR — the fold NEVER engages."""
    import fedml_tpu

    # defense-configured: stacked matrix needed -> exact
    dfn, _ = _plain_aggregator(
        "tg4", enable_defense=True, defense_type="norm_diff_clipping",
        extra={"streaming_aggregation": True})
    assert not dfn.stream_mode
    # LDP: per-client noise -> exact
    ldp, _ = _plain_aggregator(
        "tg5", enable_dp=True, dp_solution_type="ldp",
        extra={"streaming_aggregation": True})
    assert not ldp.stream_mode
    # FHE aggregator: ciphertext stacks -> pinned exact whatever the flags
    from fedml_tpu.cross_silo.fhe import FHEAggregator
    from fedml_tpu.data import loader as dloader
    from fedml_tpu.data.dataset import pad_eval_set
    from fedml_tpu.models import model_hub

    fcfg = tiny_config(
        client_num_in_total=2, client_num_per_round=2, comm_round=1,
        training_type="cross_silo", enable_fhe=True, run_id="tg6",
        extra={"streaming_aggregation": True, "comm_compression": "qsgd8",
               "fhe_ring_dim": 256})
    fedml_tpu.init(fcfg)
    fds = dloader.load(fcfg)
    fmodel = model_hub.create(fcfg, fds.class_num)
    fhe = FHEAggregator(fcfg, fmodel, fds.train_x[:16],
                        pad_eval_set(fds.test_x, fds.test_y, 32))
    assert not fhe.stream_mode
    assert fhe.fold(1, object(), 1.0, False) is False
    # SA/LSA keep the base f32 fold pinned off (their own field fold is
    # separate machinery behind secagg_stream)
    from fedml_tpu.cross_silo.secagg_shamir import SAAggregator

    scfg = _sa_config(run_id="tg7", extra={"comm_compression": "qsgd8"})
    fedml_tpu.init(scfg)
    sds = dloader.load(scfg)
    smodel = model_hub.create(scfg, sds.class_num)
    sa = SAAggregator(scfg, smodel, sds.train_x[:16],
                      pad_eval_set(sds.test_x, sds.test_y, 32))
    assert not sa.stream_mode and sa.field_stream


def test_lsa_stream_bitwise_vs_legacy(eight_devices):
    """LightSecAgg rides the same field fold: the O(cohort * d) masked-model
    buffer streams (peak <= 2), the aggregate-mask decode is untouched, and
    the final global is bitwise the buffer-all run's."""
    import fedml_tpu
    from fedml_tpu.cross_silo.lightsecagg import run_lightsecagg_process_group
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub

    def lsa_cfg(run_id, stream):
        return tiny_config(
            client_num_in_total=4, client_num_per_round=4, comm_round=1,
            epochs=1, batch_size=16, synthetic_train_size=256,
            synthetic_test_size=64, training_type="cross_silo",
            enable_secagg=True, frequency_of_the_test=0, run_id=run_id,
            extra={"secagg_stream": stream})

    cfg = lsa_cfg("lsa_s", True)
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)
    h_s, srv_s = run_lightsecagg_process_group(cfg, ds, model, timeout=120.0)
    cfg_l = lsa_cfg("lsa_l", False)
    fedml_tpu.init(cfg_l)
    h_l, srv_l = run_lightsecagg_process_group(cfg_l, ds, model, timeout=120.0)
    assert len(h_s) == len(h_l) == 1
    assert srv_s.aggregator.peak_buffered_updates <= 2
    assert srv_l.aggregator.peak_buffered_updates >= 4
    assert _leaves_equal(srv_s.aggregator.global_vars,
                         srv_l.aggregator.global_vars)


# -- 6) soak + satellites -----------------------------------------------------

def test_secagg_soak_smoke():
    from fedml_tpu.cross_silo.secagg_soak import run_secagg_stream_soak

    res = run_secagg_stream_soak(cohort=128, dim=1024, rounds=1,
                                 drop_before_frac=0.02, drop_after_frac=0.02)
    assert res["bitwise_identity"] and res["peak_buffered"] <= 2
    assert res["dropped_before"] >= 2 and res["dropped_after"] >= 2
    assert res["bytes_per_round"] < res["bytes_per_round_dense_mask"]
    assert res["bytes_per_round_dense_mask"] < res["bytes_per_round_legacy_int64"]
    dense = run_secagg_stream_soak(cohort=64, dim=512, rounds=1, codec="dense")
    assert dense["bitwise_identity"] and dense["peak_buffered"] <= 2


def test_secagg_modules_hold_no_legacy_extra_idioms():
    """ISSUE-15 lint satellite, regression-pinned: the secagg modules carry
    ZERO statement-position ``extra`` setdefault/subscript/``in`` sites (the
    reported-only class lint --fix never auto-rewrites) and zero rewritable
    legacy reads."""
    import os

    from fedml_tpu.analysis.fix import fix_source

    pkg = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    for mod in ("lightsecagg.py", "secagg_shamir.py", "secagg_soak.py"):
        path = os.path.join(pkg, "fedml_tpu", "cross_silo", mod)
        with open(path) as f:
            src = f.read()
        _, rewrites, skipped = fix_source(src, f"cross_silo/{mod}")
        assert rewrites == 0, (mod, rewrites)
        assert skipped == [], (mod, skipped)
