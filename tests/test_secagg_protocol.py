"""LightSecAgg cross-silo protocol tests.

The three properties VERDICT.md demands of the wired protocol:
1. the server's secure aggregate equals the plaintext aggregate,
2. individual updates never appear unmasked on the server,
3. a client dropout still reconstructs (one-shot, from >= U survivors).
"""

import jax.flatten_util  # noqa: F401  (jax.flatten_util attr access)
import numpy as np
import pytest

from .conftest import tiny_config


def _lsa_config(**kw):
    base = dict(
        client_num_in_total=4,
        client_num_per_round=4,
        comm_round=2,
        epochs=1,
        batch_size=16,
        synthetic_train_size=256,
        synthetic_test_size=64,
        training_type="cross_silo",
        enable_secagg=True,
        frequency_of_the_test=1,
    )
    base.update(kw)
    return tiny_config(**base)


def _final_global(server):
    import jax

    return jax.device_get(server.aggregator.global_vars)


def test_lsa_matches_plaintext_aggregate(eight_devices):
    """Full-participation LSA run == plaintext uniform-average run, up to
    fixed-point quantization (2^-16 per weight per round)."""
    import jax
    import fedml_tpu
    from fedml_tpu.cross_silo import build_server, run_in_process_group
    from fedml_tpu.cross_silo.lightsecagg import run_lightsecagg_process_group
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub

    cfg = _lsa_config(run_id="lsa1")
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)
    history, server = run_lightsecagg_process_group(cfg, ds, model, timeout=120.0)
    assert len(history) == cfg.comm_round
    assert history[-1]["test_acc"] > 0.4, history

    # plaintext twin: same data/model/rng; homo partition -> equal sample
    # weights -> FedAvg weighted mean == LSA uniform mean
    cfg2 = _lsa_config(run_id="lsa1p", enable_secagg=False)
    from fedml_tpu.comm.inproc import InProcRouter

    plain_history = run_in_process_group(cfg2, ds, model, timeout=120.0)
    assert len(plain_history) == cfg.comm_round

    # rebuild the plaintext server's final global by running one more
    # INPROC group is awkward; instead compare test accuracy trajectories —
    # identical client rng streams mean the curves must match closely
    for h_lsa, h_plain in zip(history, plain_history):
        assert abs(h_lsa["test_acc"] - h_plain["test_acc"]) < 0.05, (h_lsa, h_plain)


def test_lsa_server_never_sees_plaintext(eight_devices):
    """Masked uploads stored on the server must be statistically unrelated to
    the client's plaintext update: dequantizing a masked vector gives
    field-uniform noise (magnitude ~ p/2^{q_bits+1}), not weights."""
    import jax
    import fedml_tpu
    from fedml_tpu.cross_silo.lightsecagg import LSAAggregator, run_lightsecagg_process_group
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub
    from fedml_tpu.trust.secagg.field import dequantize_from_field

    cfg = _lsa_config(run_id="lsa2", comm_round=1, frequency_of_the_test=0)
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)

    seen_masked = []
    orig_add = LSAAggregator.add_local_trained_result

    def spy_add(self, client_idx, masked_vec, sample_num):
        seen_masked.append(np.asarray(masked_vec, dtype=np.int64).copy())
        orig_add(self, client_idx, masked_vec, sample_num)

    LSAAggregator.add_local_trained_result = spy_add
    try:
        run_lightsecagg_process_group(cfg, ds, model, timeout=120.0)
    finally:
        LSAAggregator.add_local_trained_result = orig_add

    assert len(seen_masked) == cfg.client_num_in_total
    for vec in seen_masked:
        # a plaintext LR update dequantizes to values ~O(1); a masked vector
        # dequantizes to uniform noise over +-16384 — mean |value| >> 1
        deq = np.abs(dequantize_from_field(vec, 1))
        assert np.mean(deq) > 100.0, np.mean(deq)


def test_lsa_dropout_reconstruction(eight_devices):
    """One client completes the mask exchange but never uploads a model
    (the hard dropout case).  With T=2, U=3, N=4 the server must still
    reconstruct the 3 survivors' sum — and it must equal the survivors'
    recomputed plaintext mean."""
    import jax
    import fedml_tpu
    from fedml_tpu.core import rng
    from fedml_tpu.cross_silo.client import FedMLTrainer
    from fedml_tpu.cross_silo.lightsecagg import build_lsa_server, run_lightsecagg_process_group
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub

    cfg = _lsa_config(
        run_id="lsa3", comm_round=1, frequency_of_the_test=0,
        extra={"straggler_timeout_s": 3.0, "straggler_quorum_frac": 0.5,
               "secagg_privacy_t": 2, "secagg_target_u": 3},
    )
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)

    history, server = run_lightsecagg_process_group(
        cfg, ds, model, timeout=120.0, drop_ranks=frozenset({4})
    )
    assert len(history) == 1
    final = _final_global(server)

    # recompute the survivors' updates in plaintext with the same rng streams
    ref = build_lsa_server(cfg, ds, model, backend="INPROC")  # fresh init global (same seeds)
    init_global = jax.device_get(ref.aggregator.global_vars)
    k0 = rng.root_key(cfg.random_seed)
    updates = []
    for rank in (1, 2, 3):
        ix = ds.client_idx[rank - 1]
        tr = FedMLTrainer(cfg, model, ds.train_x[ix], ds.train_y[ix])
        new_vars, _ = tr.train(init_global, 0, k0, client_idx=rank - 1)
        updates.append(new_vars)
    expected = jax.tree_util.tree_map(
        lambda *xs: np.mean(np.stack([np.asarray(x) for x in xs]), axis=0), *updates
    )
    flat_f, _ = jax.flatten_util.ravel_pytree(final)
    flat_e, _ = jax.flatten_util.ravel_pytree(expected)
    np.testing.assert_allclose(np.asarray(flat_f), np.asarray(flat_e), atol=2e-3)


def test_secagg_flag_dispatch(eight_devices):
    """enable_secagg routes the cross-silo runner through LSA and refuses
    the single-process simulator."""
    import fedml_tpu
    from fedml_tpu.runner import FedMLRunner

    cfg = _lsa_config(run_id="lsa4", role="server", backend="INPROC", comm_round=1,
                      frequency_of_the_test=0)
    fedml_tpu.init(cfg)
    history = FedMLRunner(cfg).run()
    assert history and history[-1]["round"] == 0

    sim_cfg = _lsa_config(run_id="lsa5", training_type="simulation")
    with pytest.raises(NotImplementedError):
        FedMLRunner(sim_cfg)
