"""LightSecAgg cross-silo protocol tests.

The three properties VERDICT.md demands of the wired protocol:
1. the server's secure aggregate equals the plaintext aggregate,
2. individual updates never appear unmasked on the server,
3. a client dropout still reconstructs (one-shot, from >= U survivors).
"""

import jax.flatten_util  # noqa: F401  (jax.flatten_util attr access)
import numpy as np
import pytest

from .conftest import tiny_config


def _lsa_config(**kw):
    base = dict(
        client_num_in_total=4,
        client_num_per_round=4,
        comm_round=2,
        epochs=1,
        batch_size=16,
        synthetic_train_size=256,
        synthetic_test_size=64,
        training_type="cross_silo",
        enable_secagg=True,
        frequency_of_the_test=1,
    )
    base.update(kw)
    return tiny_config(**base)


def _final_global(server):
    import jax

    return jax.device_get(server.aggregator.global_vars)


def test_lsa_matches_plaintext_aggregate(eight_devices):
    """Full-participation LSA run == plaintext uniform-average run, up to
    fixed-point quantization (2^-16 per weight per round)."""
    import jax
    import fedml_tpu
    from fedml_tpu.cross_silo import build_server, run_in_process_group
    from fedml_tpu.cross_silo.lightsecagg import run_lightsecagg_process_group
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub

    cfg = _lsa_config(run_id="lsa1")
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)
    history, server = run_lightsecagg_process_group(cfg, ds, model, timeout=120.0)
    assert len(history) == cfg.comm_round
    assert history[-1]["test_acc"] > 0.4, history

    # plaintext twin: same data/model/rng; homo partition -> equal sample
    # weights -> FedAvg weighted mean == LSA uniform mean
    cfg2 = _lsa_config(run_id="lsa1p", enable_secagg=False)
    from fedml_tpu.comm.inproc import InProcRouter

    plain_history = run_in_process_group(cfg2, ds, model, timeout=120.0)
    assert len(plain_history) == cfg.comm_round

    # rebuild the plaintext server's final global by running one more
    # INPROC group is awkward; instead compare test accuracy trajectories —
    # identical client rng streams mean the curves must match closely
    for h_lsa, h_plain in zip(history, plain_history):
        assert abs(h_lsa["test_acc"] - h_plain["test_acc"]) < 0.05, (h_lsa, h_plain)


def test_lsa_server_never_sees_plaintext(eight_devices):
    """Masked uploads stored on the server must be statistically unrelated to
    the client's plaintext update: dequantizing a masked vector gives
    field-uniform noise (magnitude ~ p/2^{q_bits+1}), not weights."""
    import jax
    import fedml_tpu
    from fedml_tpu.cross_silo.lightsecagg import LSAAggregator, run_lightsecagg_process_group
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub
    from fedml_tpu.trust.secagg.field import dequantize_from_field

    cfg = _lsa_config(run_id="lsa2", comm_round=1, frequency_of_the_test=0)
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)

    seen_masked = []
    orig_add = LSAAggregator.add_local_trained_result

    def spy_add(self, client_idx, masked_vec, sample_num):
        seen_masked.append(np.asarray(masked_vec, dtype=np.int64).copy())
        orig_add(self, client_idx, masked_vec, sample_num)

    LSAAggregator.add_local_trained_result = spy_add
    try:
        run_lightsecagg_process_group(cfg, ds, model, timeout=120.0)
    finally:
        LSAAggregator.add_local_trained_result = orig_add

    assert len(seen_masked) == cfg.client_num_in_total
    for vec in seen_masked:
        # a plaintext LR update dequantizes to values ~O(1); a masked vector
        # dequantizes to uniform noise over +-16384 — mean |value| >> 1
        deq = np.abs(dequantize_from_field(vec, 1))
        assert np.mean(deq) > 100.0, np.mean(deq)


def test_lsa_dropout_reconstruction(eight_devices):
    """One client completes the mask exchange but never uploads a model
    (the hard dropout case).  With T=2, U=3, N=4 the server must still
    reconstruct the 3 survivors' sum — and it must equal the survivors'
    recomputed plaintext mean."""
    import jax
    import fedml_tpu
    from fedml_tpu.core import rng
    from fedml_tpu.cross_silo.client import FedMLTrainer
    from fedml_tpu.cross_silo.lightsecagg import build_lsa_server, run_lightsecagg_process_group
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub

    cfg = _lsa_config(
        run_id="lsa3", comm_round=1, frequency_of_the_test=0,
        extra={"straggler_timeout_s": 3.0, "straggler_quorum_frac": 0.5,
               "secagg_privacy_t": 2, "secagg_target_u": 3},
    )
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)

    history, server = run_lightsecagg_process_group(
        cfg, ds, model, timeout=120.0, drop_ranks=frozenset({4})
    )
    assert len(history) == 1
    final = _final_global(server)

    # recompute the survivors' updates in plaintext with the same rng streams
    ref = build_lsa_server(cfg, ds, model, backend="INPROC")  # fresh init global (same seeds)
    init_global = jax.device_get(ref.aggregator.global_vars)
    k0 = rng.root_key(cfg.random_seed)
    updates = []
    for rank in (1, 2, 3):
        ix = ds.client_idx[rank - 1]
        tr = FedMLTrainer(cfg, model, ds.train_x[ix], ds.train_y[ix])
        new_vars, _ = tr.train(init_global, 0, k0, client_idx=rank - 1)
        updates.append(new_vars)
    expected = jax.tree_util.tree_map(
        lambda *xs: np.mean(np.stack([np.asarray(x) for x in xs]), axis=0), *updates
    )
    flat_f, _ = jax.flatten_util.ravel_pytree(final)
    flat_e, _ = jax.flatten_util.ravel_pytree(expected)
    np.testing.assert_allclose(np.asarray(flat_f), np.asarray(flat_e), atol=2e-3)


def test_ring_pack_roundtrip_and_wire_bytes():
    """ISSUE 17 satellite: the masked upload rides the wire ring-packed
    (u32, 4 B/elem) instead of raw int64 (8 B/elem).  Packing must be an
    exact roundtrip — unpack restores the int64 bits, so the field math
    downstream is unchanged."""
    from fedml_tpu.comm.message import Message
    from fedml_tpu.cross_silo import message_define as md
    from fedml_tpu.cross_silo.lightsecagg import MSG_ARG_KEY_MASKED_RING
    from fedml_tpu.trust.secagg.stream import (
        DENSE_RING_BITS, pack_ring, unpack_ring)

    rs = np.random.RandomState(11)
    vec = rs.randint(0, 2**DENSE_RING_BITS - 1, size=1337, dtype=np.int64)
    packed = pack_ring(vec, DENSE_RING_BITS)
    assert packed.dtype == np.uint32 and packed.nbytes == 4 * vec.size
    np.testing.assert_array_equal(
        unpack_ring(packed, DENSE_RING_BITS, vec.size), vec)

    def frame_bytes(payload, with_meta):
        m = Message(md.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, 1, 0)
        m.add_params(md.MSG_ARG_KEY_MODEL_PARAMS, payload)
        if with_meta:
            m.add_params(MSG_ARG_KEY_MASKED_RING,
                         {"ring_bits": DENSE_RING_BITS, "length": vec.size})
        m.add_params(md.MSG_ARG_KEY_NUM_SAMPLES, 16.0)
        m.add_params(md.MSG_ARG_KEY_ROUND_INDEX, 0)
        return len(m.encode())

    legacy, ring = frame_bytes(vec, False), frame_bytes(packed, True)
    # ~2x on the dominant tensor section (header/meta overhead is O(1))
    assert ring < 0.6 * legacy, (ring, legacy)


def test_lsa_packed_wire_bitwise_matches_legacy_int64(eight_devices):
    """End to end on the real protocol: (a) every model upload arrives
    ring-packed (u32 + control meta); (b) a run whose clients speak the
    LEGACY raw-int64 wire (no meta) is still accepted by the server and
    produces the BITWISE-identical final global — masks differ between runs
    (os.urandom) but cancel exactly in the field aggregate, and unpack is
    exact, so the dequantized finals must match bit for bit."""
    import fedml_tpu
    from fedml_tpu.cross_silo import message_define as md
    from fedml_tpu.cross_silo import lightsecagg as lsa
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub

    wire_seen = []
    orig_handle = lsa.LSAServerManager.handle_message_receive_model

    def spy_handle(self, msg):
        wire_seen.append((
            np.asarray(msg.get(md.MSG_ARG_KEY_MODEL_PARAMS)).dtype,
            msg.get_control(lsa.MSG_ARG_KEY_MASKED_RING) is not None,
        ))
        orig_handle(self, msg)

    orig_send = lsa.LSAClientManager.send_message

    def legacy_send(self, msg):
        # simulate an old client: unpack back to raw int64 and strip the
        # ring meta before the frame hits the wire
        meta = msg.get_control(lsa.MSG_ARG_KEY_MASKED_RING)
        if meta is not None:
            msg.msg_params[md.MSG_ARG_KEY_MODEL_PARAMS] = lsa.unpack_ring(
                np.asarray(msg.get(md.MSG_ARG_KEY_MODEL_PARAMS)),
                int(meta["ring_bits"]), int(meta["length"]))
            msg.msg_params.pop(lsa.MSG_ARG_KEY_MASKED_RING)
        orig_send(self, msg)

    def run(run_id, legacy):
        cfg = _lsa_config(run_id=run_id, comm_round=1,
                          frequency_of_the_test=0)
        fedml_tpu.init(cfg)
        ds = loader.load(cfg)
        model = model_hub.create(cfg, ds.class_num)
        wire_seen.clear()
        lsa.LSAServerManager.handle_message_receive_model = spy_handle
        if legacy:
            lsa.LSAClientManager.send_message = legacy_send
        try:
            _, server = lsa.run_lightsecagg_process_group(
                cfg, ds, model, timeout=120.0)
        finally:
            lsa.LSAServerManager.handle_message_receive_model = orig_handle
            lsa.LSAClientManager.send_message = orig_send
        assert len(wire_seen) == cfg.client_num_in_total
        for dtype, has_meta in wire_seen:
            if legacy:
                assert dtype == np.int64 and not has_meta
            else:
                assert dtype == np.uint32 and has_meta
        import jax

        return [np.asarray(l) for l in
                jax.tree_util.tree_leaves(_final_global(server))]

    packed = run("lsa_ring", legacy=False)
    legacy = run("lsa_ring_legacy", legacy=True)
    for a, b in zip(packed, legacy):
        np.testing.assert_array_equal(a, b)


def test_secagg_flag_dispatch(eight_devices):
    """enable_secagg routes the cross-silo runner through LSA and refuses
    the single-process simulator."""
    import fedml_tpu
    from fedml_tpu.runner import FedMLRunner

    cfg = _lsa_config(run_id="lsa4", role="server", backend="INPROC", comm_round=1,
                      frequency_of_the_test=0)
    fedml_tpu.init(cfg)
    history = FedMLRunner(cfg).run()
    assert history and history[-1]["round"] == 0

    sim_cfg = _lsa_config(run_id="lsa5", training_type="simulation")
    with pytest.raises(NotImplementedError):
        FedMLRunner(sim_cfg)
