"""Runtime trace sanitizer (ISSUE 20): transfer-guarded steady-state rounds,
compile attribution, annotated host boundaries — and the tier-1 gate that
runs the flagship round loop + the async fold path under the guard and
requires zero disallowed transfers and zero post-warmup recompiles."""

import contextlib
import json
import os
import subprocess
import sys
from pathlib import Path

import numpy as np
import pytest

from fedml_tpu.analysis import tracesan
from fedml_tpu.analysis.tracesan import (
    ENV_FLAG,
    ENV_REPORT,
    active,
    install,
    maybe_install_from_env,
    uninstall,
)

from .conftest import tiny_config

REPO_ROOT = Path(__file__).resolve().parent.parent


@pytest.fixture
def san():
    """An installed sanitizer, torn down afterwards (never leaks into the
    rest of the suite)."""
    was_active = active()
    s = install()
    yield s
    if was_active is None:
        uninstall()


def _load(cfg):
    import fedml_tpu
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub

    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)
    return ds, model


def _upload_msg(rank, params, n_samples=16.0, version=0):
    from fedml_tpu.comm.message import Message
    from fedml_tpu.cross_silo import message_define as md

    msg = Message(md.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, rank, 0)
    msg.add_params(md.MSG_ARG_KEY_MODEL_PARAMS, params)
    msg.add_params(md.MSG_ARG_KEY_NUM_SAMPLES, float(n_samples))
    msg.add_params(md.MSG_ARG_KEY_ROUND_INDEX, int(version))
    return Message.decode(msg.encode())


# -- gating --------------------------------------------------------------------

def test_env_unset_is_a_strict_noop(monkeypatch):
    monkeypatch.delenv(ENV_FLAG, raising=False)
    assert maybe_install_from_env() is None
    assert active() is None
    assert isinstance(tracesan.round_guard(3), contextlib.nullcontext)
    assert isinstance(tracesan.allow("x"), contextlib.nullcontext)


def test_module_import_is_jax_free():
    """The default path must not even import jax from the module: the env
    check plus null context managers are the entire unset behavior."""
    code = (
        "import sys\n"
        "import fedml_tpu.analysis.tracesan as t\n"
        "assert 'jax' not in sys.modules, 'module import pulled in jax'\n"
        "import contextlib\n"
        "assert isinstance(t.round_guard(2), contextlib.nullcontext)\n"
        "assert isinstance(t.allow('s'), contextlib.nullcontext)\n"
        "assert 'jax' not in sys.modules, 'inactive cms pulled in jax'\n"
        "print('NOOP_OK')\n"
    )
    res = subprocess.run([sys.executable, "-c", code], cwd=str(REPO_ROOT),
                         capture_output=True, text=True, timeout=120,
                         env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert res.returncode == 0, res.stderr[-3000:]
    assert "NOOP_OK" in res.stdout


# -- guard semantics -----------------------------------------------------------

def test_round_guard_blocks_implicit_transfers(san):
    import jax
    import jax.numpy as jnp

    # warmup round (below the default warmup_rounds=1): transfers legal
    with san.round_guard(0):
        jnp.sin(np.ones(3)).block_until_ready()
    # steady round: the same implicit h2d must raise AND be recorded
    with pytest.raises(jax.errors.JaxRuntimeError, match="isallowed"):
        with san.round_guard(5):
            jnp.sin(np.ones(4)).block_until_ready()
    rep = san.report()
    assert rep["guarded_rounds"] >= 1
    kinds = [v["kind"] for v in rep["violations"]]
    assert "disallowed_transfer" in kinds
    viol = next(v for v in rep["violations"] if v["kind"] == "disallowed_transfer")
    assert viol["round"] == 5
    # after the guard exits the process is back to normal
    jnp.sin(np.ones(5)).block_until_ready()


def test_allow_reopens_the_guard_and_counts(san):
    import jax.numpy as jnp

    with san.round_guard(7):
        with tracesan.allow("test_boundary"):
            jnp.asarray(np.arange(6.0)).block_until_ready()
        with tracesan.allow("test_boundary"):
            jnp.asarray(np.arange(6.0) + 1.0).block_until_ready()
    rep = san.report()
    assert rep["allowed_sites"]["test_boundary"] == 2
    assert [v for v in rep["violations"] if v["kind"] == "disallowed_transfer"] == []


def test_explicit_device_get_stays_legal_under_guard(san):
    import jax
    import jax.numpy as jnp

    x = jnp.arange(8.0)
    with san.round_guard(3):
        host = jax.device_get(x)  # explicit: the guard's whole point
    assert host.shape == (8,)
    assert san.report()["guarded_rounds"] >= 1


def test_steady_compile_is_attributed_and_flagged(san):
    import jax
    import jax.numpy as jnp

    # the persistent compilation cache only absorbs big programs; still,
    # force a REAL backend compile so the monitoring event is guaranteed
    x = jnp.arange(11.0)  # staged (and its arange compiled) outside the guard
    jax.config.update("jax_enable_compilation_cache", False)
    try:
        with san.round_guard(4):
            # no host operands (a python literal would itself trip the
            # guard): x*x's first compile is the steady-phase event
            jnp.arctan(x * x).block_until_ready()
    finally:
        jax.config.update("jax_enable_compilation_cache", True)
    rep = san.report()
    steady = [v for v in rep["violations"] if v["kind"] == "steady_compile"]
    assert steady, f"no steady compile recorded: {rep['compiles']}"
    assert steady[0]["round"] == 4
    assert rep["compiles"].get("steady", 0) >= 1
    # attribution: the innermost fedml_tpu frame is this test's caller chain
    # (no package frame on the stack -> '<outside-package>' is acceptable)
    assert steady[0]["site"]


def test_install_is_idempotent_and_uninstall_deactivates():
    was = active()
    s1 = install()
    s2 = install()
    assert s1 is s2
    if was is None:
        uninstall()
        assert active() is None
        assert isinstance(tracesan.round_guard(1), contextlib.nullcontext)


# -- env-gated end-to-end (subprocess): conftest-style install + report dump ---

def test_env_gated_install_and_report_dump(tmp_path):
    report = tmp_path / "tracesan.json"
    code = (
        "import numpy as np\n"
        "from fedml_tpu.analysis.tracesan import maybe_install_from_env, active\n"
        "san = maybe_install_from_env()\n"
        "assert san is not None and active() is san\n"
        "import jax, jax.numpy as jnp\n"
        "from fedml_tpu.analysis import tracesan\n"
        "x = jnp.arange(8.0)\n"
        "with tracesan.round_guard(0):\n"
        "    jnp.sum(x).block_until_ready()\n"
        "with tracesan.round_guard(3):\n"
        "    with tracesan.allow('smoke'):\n"
        "        jnp.asarray(np.ones(3)).block_until_ready()\n"
        "try:\n"
        "    with tracesan.round_guard(4):\n"
        "        jnp.add(x, np.ones(8)).block_until_ready()\n"
        "except jax.errors.JaxRuntimeError:\n"
        "    pass\n"
        "else:\n"
        "    raise SystemExit('implicit transfer was not blocked')\n"
        "print('RUN_OK')\n"
    )
    env = {**os.environ, ENV_FLAG: "1", ENV_REPORT: str(report),
           "JAX_PLATFORMS": "cpu"}
    res = subprocess.run([sys.executable, "-c", code], cwd=str(REPO_ROOT),
                         capture_output=True, text=True, timeout=300, env=env)
    assert res.returncode == 0, res.stdout[-2000:] + res.stderr[-3000:]
    assert "RUN_OK" in res.stdout
    assert report.exists(), "report was not dumped at interpreter exit"
    rep = json.loads(report.read_text())
    assert rep["guarded_rounds"] == 2
    assert rep["allowed_sites"] == {"smoke": 1}
    assert sum(rep["compiles"].values()) >= 1, "compile listener saw nothing"
    kinds = [v["kind"] for v in rep["violations"]]
    assert "disallowed_transfer" in kinds


def test_tracesan_marker_is_registered_and_populated():
    """`-m tracesan` must collect the gate — an empty selection would pass
    vacuously and silently disarm it."""
    res = subprocess.run(
        [sys.executable, "-m", "pytest", "tests/test_tracesan.py",
         "-m", "tracesan", "--collect-only", "-q",
         "-p", "no:cacheprovider", "-p", "no:randomly"],
        cwd=str(REPO_ROOT), env={**os.environ, "JAX_PLATFORMS": "cpu"},
        capture_output=True, text=True, timeout=300,
    )
    assert res.returncode == 0, res.stdout[-2000:] + res.stderr[-1000:]
    collected = [l for l in res.stdout.splitlines() if "::" in l]
    assert len(collected) >= 1, "tracesan marker collects nothing"


# -- the tier-1 gate: flagship round loop + async fold under the guard ---------

@pytest.mark.tracesan
def test_tracesan_gate_flagship_rounds_and_async_fold(eight_devices):
    """≥3 steady-state FedAvg rounds (the compiled mesh chunk path) plus the
    buffered-async streamed fold, all under ``transfer_guard('disallow')``:
    zero disallowed transfers, zero post-warmup recompiles.  A violation
    here means the hot path regressed — fix the staging/annotation, do not
    relax this test."""
    import jax

    was_active = active()
    san = install()
    try:
        from fedml_tpu.sim.engine import MeshSimulator

        cfg = tiny_config(comm_round=4)
        ds, model = _load(cfg)
        sim = MeshSimulator(cfg, ds, model)
        out = []
        for _ in range(4):  # round 0 warms up; rounds 1-3 run guarded
            out.extend(sim.run_rounds(1))
        assert len(out) == 4 and all(np.isfinite(list(m.values())).all()
                                     for m in out)

        # async-server fold path: decode real wire frames into the streamed
        # accumulator.  Round 0 fold warms the per-leaf programs; the
        # steady-round folds must then be transfer-silent outside the
        # annotated fold_ingest boundary.
        from fedml_tpu.cross_silo import build_aggregator

        cfg2 = tiny_config(extra={"streaming_aggregation": True})
        ds2, model2 = _load(cfg2)
        agg = build_aggregator(cfg2, ds2, model2)
        assert agg.stream_mode
        base = jax.device_get(agg.global_vars)
        msgs = {cid: _upload_msg(cid, base) for cid in (1, 2, 3, 4)}
        with san.round_guard(0):
            assert agg.fold(1, msgs[1], 16.0, False)
        with san.round_guard(5):
            for cid in (2, 3, 4):
                assert agg.fold(cid, msgs[cid], 16.0, False)
        agg.aggregate(0)

        rep = san.report()
        assert rep["violations"] == [], (
            "trace-hygiene violations in the flagship round loop:\n"
            + json.dumps(rep["violations"], indent=1))
        assert rep["guarded_rounds"] >= 4, rep  # 3 sim rounds + 1 fold round
        assert rep["compiles"].get("steady", 0) == 0, rep["compiles"]
        # non-vacuity: the annotated boundaries actually fired
        assert rep["allowed_sites"].get("round_metrics", 0) >= 3, rep
        assert rep["allowed_sites"].get("fold_ingest", 0) >= 3, rep
    finally:
        if was_active is None:
            uninstall()


def test_default_path_is_bitwise_pinned(eight_devices):
    """Training with the sanitizer installed must be BITWISE the default
    run: the guard observes, it never reorders or re-places a computation
    on the guarded path."""
    import jax

    from fedml_tpu.sim.engine import MeshSimulator

    def run(with_san):
        cfg = tiny_config(comm_round=2)
        ds, model = _load(cfg)
        if with_san:
            install()
        try:
            sim = MeshSimulator(cfg, ds, model)
            sim.run_rounds(1)
            sim.run_rounds(1)
            return jax.device_get(sim.global_vars)
        finally:
            if with_san:
                uninstall()

    was_active = active()
    if was_active is not None:
        uninstall()
    try:
        plain = run(False)
        guarded = run(True)
    finally:
        if was_active is not None:
            install()
    for a, b in zip(jax.tree_util.tree_leaves(plain),
                    jax.tree_util.tree_leaves(guarded)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
