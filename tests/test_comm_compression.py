"""Compressed streaming cross-silo rounds (ISSUE 4).

Covers the wire-v2 codec layer (raw/qsgd8/topk roundtrips + error bounds +
EF residual), v1 back-compat and bit-identical default bytes, the zero-copy
fast path (views on decode, bounded peak on encode), the chunked stream
decoder, streaming-accumulator vs batch-aggregate parity, and the e2e
in-proc cross-silo run with ``extra.comm_compression=qsgd8``.
"""

import json
import struct
import tracemalloc

import numpy as np
import pytest

from .conftest import tiny_config


def _old_v1_encode(tree):
    """The pre-ISSUE-4 encoder, verbatim — the bit-compat oracle."""
    from fedml_tpu.comm import wire

    leaves = []
    skel = wire._build_skeleton(tree, leaves)
    arrs = [np.asarray(l) for l in leaves]
    header = {
        "version": 1,
        "treedef": skel,
        "leaves": [
            {"dtype": a.dtype.str, "shape": list(a.shape), "nbytes": int(a.nbytes)}
            for a in arrs
        ],
    }
    hbytes = json.dumps(header, separators=(",", ":")).encode("utf-8")
    parts = [struct.pack("<I", len(hbytes)), hbytes]
    for a in arrs:
        parts.append(np.ascontiguousarray(a).tobytes())
    return b"".join(parts)


def _tree():
    r = np.random.RandomState(0)
    return {
        "params": {"w": r.randn(3000).astype(np.float32),
                   "b": r.randn(16).astype(np.float32)},
        "meta": [np.int32(7), np.array([1.5], np.float64)],
        "t": (np.ones((2, 2), np.float16),),
    }


# ---------------------------------------------------------------------------
# wire v1 back-compat + bit-identical default bytes
# ---------------------------------------------------------------------------

def test_default_encode_bit_identical_to_v1():
    """Compression off -> today's bytes, bit for bit (message level too)."""
    from fedml_tpu.comm import wire
    from fedml_tpu.comm.message import Message

    tree = _tree()
    assert wire.encode_pytree(tree) == _old_v1_encode(tree)

    msg = Message(3, 2, 0)
    msg.add_params("model_params", {"w": np.arange(64, dtype=np.float32)})
    msg.add_params("num_samples", 64.0)
    control = {k: v for k, v in msg.msg_params.items()
               if not isinstance(v, dict)}
    cbytes = json.dumps(control, separators=(",", ":")).encode("utf-8")
    expected = (len(cbytes).to_bytes(4, "little") + cbytes
                + _old_v1_encode({"model_params": {"w": np.arange(64, dtype=np.float32)}}))
    assert msg.encode() == expected


def test_wire_v1_frames_still_decode():
    from fedml_tpu.comm import wire

    tree = _tree()
    data = _old_v1_encode(tree)
    out = wire.decode_pytree(data)
    np.testing.assert_array_equal(out["params"]["w"], tree["params"]["w"])
    assert out["meta"][0] == 7
    assert isinstance(out["t"], tuple)


def test_wire_rejects_corrupt_frames():
    from fedml_tpu.comm import wire

    data = wire.encode_pytree({"a": np.zeros(8, np.float32)})
    with pytest.raises(ValueError, match="unsupported wire version"):
        wire.decode_pytree(data.replace(b'"version":1', b'"version":9'))
    with pytest.raises(ValueError, match="length mismatch"):
        wire.decode_pytree(data[:-4])  # truncated payload
    # unknown codec in a v2 spec (same-length name keeps the framing valid)
    comp, _, _ = _compress({"x": np.zeros(2048, np.float32)}, "qsgd8")
    v2 = wire.encode_pytree(comp)
    with pytest.raises(ValueError, match="unknown wire codec"):
        wire.decode_pytree(v2.replace(b'"codec":"qsgd8"', b'"codec":"qsgd9"'))


# ---------------------------------------------------------------------------
# codec roundtrips
# ---------------------------------------------------------------------------

def _compress(tree, codec, **kw):
    import jax

    from fedml_tpu.comm import codecs

    kw.setdefault("key", jax.random.PRNGKey(0))
    return codecs.compress_pytree(tree, codec, **kw)


def test_qsgd8_roundtrip_error_bound():
    """Block-scaled stochastic int8: elementwise error <= one quantization
    step (block amax / 127); small and integer leaves ride raw exactly."""
    from fedml_tpu.comm import wire

    tree = _tree()
    comp, res, stats = _compress(tree, "qsgd8")
    assert res is None or all(r is None for r in res)  # unbiased: no EF state
    out = wire.decode_pytree(wire.encode_pytree(comp))
    w = tree["params"]["w"]
    err = np.abs(out["params"]["w"] - w).max()
    assert err <= np.abs(w).max() / 127.0 + 1e-6, err
    np.testing.assert_array_equal(out["params"]["b"], tree["params"]["b"])  # raw
    assert out["meta"][0] == 7
    assert stats["ratio"] > 3.0, stats


def test_topk_roundtrip_and_error_feedback():
    """Sparse top-k: decoded == ef_top_k's dense mask, and the residual is
    exactly what was dropped (corrected = sent + residual)."""
    from fedml_tpu.comm import wire

    vec = np.random.RandomState(1).randn(4096).astype(np.float32)
    tree = {"w": vec}
    comp, res, _ = _compress(tree, "topk", ratio=0.05)
    out = wire.decode_pytree(wire.encode_pytree(comp))
    k = max(1, int(0.05 * vec.size))
    assert int((out["w"] != 0).sum()) == k
    # the k kept entries are the largest-|.| ones and exact
    kept = np.argsort(-np.abs(vec))[:k]
    np.testing.assert_allclose(np.sort(out["w"][kept]), np.sort(vec[kept]), rtol=1e-6)
    # EF invariant: sent + residual == corrected (== vec, round 0)
    np.testing.assert_allclose(out["w"] + res[0], vec, rtol=1e-6, atol=1e-7)
    # round 2: the residual is carried and folded in
    comp2, res2, _ = _compress(tree, "topk", ratio=0.05, residuals=res)
    out2 = wire.decode_pytree(wire.encode_pytree(comp2))
    np.testing.assert_allclose(out2["w"] + res2[0], vec + res[0], rtol=1e-6, atol=1e-7)


def test_compressed_leaf_dense_matches_wire_decode():
    from fedml_tpu.comm import wire

    comp, _, _ = _compress({"w": np.random.RandomState(2).randn(2048).astype(np.float32)}, "qsgd8")
    via_wire = wire.decode_pytree(wire.encode_pytree(comp))["w"]
    np.testing.assert_array_equal(comp["w"].dense(), via_wire)


# ---------------------------------------------------------------------------
# zero-copy fast path
# ---------------------------------------------------------------------------

def test_decode_returns_views_not_copies():
    from fedml_tpu.comm import wire

    tree = {"w": np.arange(4096, dtype=np.float32)}
    data = wire.encode_pytree(tree)
    out = wire.decode_pytree(data)
    # raw leaves alias the receive buffer: no ownership, read-only
    assert not out["w"].flags.owndata
    assert not out["w"].flags.writeable
    np.testing.assert_array_equal(out["w"], tree["w"])


def test_encode_memory_peak_bounded():
    """The old encoder duplicated every leaf (tobytes) AND held parts + the
    joined blob (~2x payload above the output).  The writev path's peak must
    stay ~1x: the single output allocation plus change."""
    from fedml_tpu.comm import wire

    payload = 8 << 20  # one 8 MB leaf
    tree = {"w": np.zeros(payload // 4, np.float32)}
    wire.encode_pytree(tree)  # warm allocator paths outside the measurement
    tracemalloc.start()
    data = wire.encode_pytree(tree)
    _, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    assert len(data) >= payload
    assert peak < payload * 1.5, f"encode peak {peak} vs payload {payload}"
    # decode of raw leaves allocates ~nothing (views into data)
    tracemalloc.start()
    out = wire.decode_pytree(data)
    _, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    assert peak < payload * 0.25, f"decode peak {peak} vs payload {payload}"
    del out


def test_chunked_encode_and_stream_decoder():
    from fedml_tpu.comm import wire

    tree = _tree()
    comp, _, _ = _compress(tree, "qsgd8")
    chunk_bytes = 1 << 10
    chunks = list(wire.encode_pytree_chunks(comp, chunk_bytes=chunk_bytes))
    assert len(chunks) > 3  # the big leaf actually streams
    assert all(len(bytes(c)) <= chunk_bytes + 512 for c in chunks)
    dec = wire.PytreeStreamDecoder()
    seen = []
    for c in chunks:
        seen += dec.feed(c)
    assert dec.complete
    whole = wire.decode_pytree(b"".join(bytes(c) for c in chunks))
    np.testing.assert_array_equal(dec.result()["params"]["w"], whole["params"]["w"])
    assert len(seen) == len(dec.header["leaves"])


# ---------------------------------------------------------------------------
# streaming accumulator vs batch aggregate
# ---------------------------------------------------------------------------

def _make_aggregator(extra=None):
    import fedml_tpu
    from fedml_tpu.cross_silo.server import FedMLAggregator
    from fedml_tpu.data import loader
    from fedml_tpu.data.dataset import pad_eval_set
    from fedml_tpu.models import model_hub

    cfg = tiny_config()
    cfg.extra = dict(extra or {})
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)
    test_arrays = pad_eval_set(ds.test_x, ds.test_y, 32)
    agg = FedMLAggregator(cfg, model, ds.train_x[: cfg.batch_size], test_arrays)
    return cfg, agg


def _fake_clients(agg, n=3, seed=3):
    import jax

    r = np.random.RandomState(seed)
    base = jax.device_get(agg.global_vars)
    out = {}
    for cid in range(1, n + 1):
        out[cid] = (jax.tree_util.tree_map(
            lambda x: np.asarray(x) + r.randn(*np.shape(x)).astype(np.float32)
            if np.asarray(x).dtype.kind == "f" else np.asarray(x), base),
            float(32 * cid))
    return out


def test_exact_path_is_reference_bit_exact():
    """Compression off -> buffer-all + tree_weighted_mean, bitwise equal to
    the reference computation (the regression guard for default behavior)."""
    import jax
    import jax.numpy as jnp

    from fedml_tpu.core import pytree as pt

    _, agg = _make_aggregator()
    assert not agg.stream_mode
    clients = _fake_clients(agg)
    for cid, (params, w) in clients.items():
        agg.add_local_trained_result(cid, params, w)
    assert agg.received_count() == 3
    ids = sorted(clients)
    stacked = pt.tree_stack([jax.tree_util.tree_map(jnp.asarray, clients[i][0]) for i in ids])
    weights = jnp.asarray([clients[i][1] for i in ids], jnp.float32)
    expected = pt.tree_weighted_mean(stacked, weights)
    got = agg.aggregate(0)
    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(got)),
                    jax.tree_util.tree_leaves(jax.device_get(expected))):
        np.testing.assert_array_equal(a, b)


def test_streaming_accumulator_matches_batch_aggregate():
    """Streaming fold (via real encoded messages) == batch aggregate."""
    import jax

    from fedml_tpu.comm.message import Message
    from fedml_tpu.cross_silo import message_define as md

    _, agg_exact = _make_aggregator()
    _, agg_stream = _make_aggregator(extra={"streaming_aggregation": True})
    assert agg_stream.stream_mode
    clients = _fake_clients(agg_exact)
    for cid, (params, w) in clients.items():
        agg_exact.add_local_trained_result(cid, params, w)
        msg = Message(md.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, cid, 0)
        msg.add_params(md.MSG_ARG_KEY_MODEL_PARAMS, params)
        decoded = Message.decode(msg.encode())
        assert agg_stream.ingest_streaming(cid, decoded, w, is_delta=False)
    assert agg_stream.received_count() == 3
    assert agg_stream.peak_buffered_updates <= 2
    exact = agg_exact.aggregate(0)
    stream = agg_stream.aggregate(0)
    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(exact)),
                    jax.tree_util.tree_leaves(jax.device_get(stream))):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6)


def test_streaming_delta_uploads_match_full_uploads():
    """w*(global+delta) folds == full-model folds: the delta path's add-back
    bookkeeping (stream_w_delta) reconstructs the same aggregate."""
    import jax

    from fedml_tpu.comm.message import Message

    _, agg_full = _make_aggregator(extra={"streaming_aggregation": True})
    _, agg_delta = _make_aggregator(extra={"streaming_aggregation": True})
    base = jax.device_get(agg_full.global_vars)
    clients = _fake_clients(agg_full)
    for cid, (params, w) in clients.items():
        assert agg_full.ingest_streaming(
            cid, Message.decode(_model_msg(params).encode()), w, is_delta=False)
        delta = jax.tree_util.tree_map(
            lambda n, g: (np.asarray(n, np.float32) - np.asarray(g, np.float32)).astype(np.asarray(n).dtype),
            params, base)
        assert agg_delta.ingest_streaming(
            cid, Message.decode(_model_msg(delta).encode()), w, is_delta=True)
    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(agg_full.aggregate(0))),
                    jax.tree_util.tree_leaves(jax.device_get(agg_delta.aggregate(0)))):
        np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5)


def _model_msg(params):
    from fedml_tpu.comm.message import Message
    from fedml_tpu.cross_silo import message_define as md

    msg = Message(md.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, 1, 0)
    msg.add_params(md.MSG_ARG_KEY_MODEL_PARAMS, params)
    return msg


# ---------------------------------------------------------------------------
# e2e: compressed in-proc cross-silo round
# ---------------------------------------------------------------------------

def test_cross_silo_e2e_qsgd8(eight_devices):
    """Full protocol with extra.comm_compression=qsgd8: finite accuracy, the
    acceptance's >= 3.5x payload reduction, and peak buffered updates <= 2
    regardless of clients-per-round (4 here)."""
    import fedml_tpu
    from fedml_tpu.comm import codecs
    from fedml_tpu.comm.inproc import InProcRouter
    from fedml_tpu.cross_silo import build_client, build_server
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub

    cfg = tiny_config(training_type="cross_silo", model="mlp",
                      client_num_in_total=4, client_num_per_round=4,
                      comm_round=2, run_id="cs_comp", learning_rate=0.3,
                      frequency_of_the_test=1)
    cfg.extra = {"comm_compression": "qsgd8", "mlp_hidden": 512}
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)
    InProcRouter.reset("cs_comp")
    raw0 = codecs.PAYLOAD_RAW_BYTES.value(codec="qsgd8")
    wire0 = codecs.PAYLOAD_BYTES.value(codec="qsgd8")
    clients = [build_client(cfg, ds, model, rank=r, backend="INPROC")
               for r in range(1, 5)]
    for c in clients:
        c.run_in_thread()
    server = build_server(cfg, ds, model, backend="INPROC")
    assert server.aggregator.stream_mode
    try:
        history = server.run_until_done(timeout=120.0)
    finally:
        for c in clients:
            c.finish()
    assert len(history) == 2
    assert np.isfinite(history[-1]["test_acc"])
    assert history[-1]["test_acc"] > 0.3, history
    raw_b = codecs.PAYLOAD_RAW_BYTES.value(codec="qsgd8") - raw0
    wire_b = codecs.PAYLOAD_BYTES.value(codec="qsgd8") - wire0
    assert raw_b > 0 and wire_b > 0
    assert raw_b / wire_b >= 3.5, (raw_b, wire_b)
    assert server.aggregator.peak_buffered_updates <= 2


def test_cross_silo_compression_off_unchanged(eight_devices):
    """Flag unset: stream mode off, uploads are full models over v1 bytes,
    and the run matches the uncompressed protocol exactly."""
    import fedml_tpu
    from fedml_tpu.comm.inproc import InProcRouter
    from fedml_tpu.cross_silo import build_client, build_server
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub

    cfg = tiny_config(training_type="cross_silo", client_num_in_total=2,
                      client_num_per_round=2, comm_round=1, run_id="cs_raw",
                      frequency_of_the_test=1)
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)
    InProcRouter.reset("cs_raw")
    captured = []
    router = InProcRouter.get("cs_raw")

    from fedml_tpu.cross_silo import message_define as md

    orig_route = router.route

    def spy(msg):
        if msg.get_type() == md.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER:
            captured.append(msg)
        orig_route(msg)

    router.route = spy
    clients = [build_client(cfg, ds, model, rank=r, backend="INPROC") for r in (1, 2)]
    for c in clients:
        c.run_in_thread()
    server = build_server(cfg, ds, model, backend="INPROC")
    assert not server.aggregator.stream_mode
    try:
        history = server.run_until_done(timeout=120.0)
    finally:
        for c in clients:
            c.finish()
    assert len(history) == 1 and np.isfinite(history[0]["test_acc"])
    assert captured, "no model uploads observed"
    for msg in captured:
        assert msg.get(md.MSG_ARG_KEY_MODEL_IS_DELTA, None) is None
        # the upload's wire bytes are exactly the v1 encoding of its params
        tensors = {md.MSG_ARG_KEY_MODEL_PARAMS: msg.get(md.MSG_ARG_KEY_MODEL_PARAMS)}
        blob = msg.encode()
        clen = int.from_bytes(blob[:4], "little")
        assert blob[4 + clen:] == _old_v1_encode(tensors)
