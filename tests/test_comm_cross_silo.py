"""Comm layer + cross-silo protocol tests.

Replaces the reference's process-emulation smoke tests (SURVEY.md §4:
background processes over a public broker) with hermetic in-proc fabric
tests, plus real-gRPC loopback and injected-failure straggler tests the
reference never had (SURVEY.md §7 hard part 4).
"""

import threading
import time

import numpy as np
import pytest

from .conftest import tiny_config


# ---------------------------------------------------------------------------
# wire format
# ---------------------------------------------------------------------------

def test_wire_roundtrip():
    from fedml_tpu.comm import wire

    tree = {
        "params": {"w": np.arange(12, dtype=np.float32).reshape(3, 4), "b": np.zeros(4, np.float32)},
        "meta": [np.int32(7), np.array([1.5], np.float64)],
        "t": (np.ones((2, 2), np.float16),),
    }
    data = wire.encode_pytree(tree)
    out = wire.decode_pytree(data)
    np.testing.assert_array_equal(out["params"]["w"], tree["params"]["w"])
    np.testing.assert_array_equal(out["t"][0], tree["t"][0])
    assert out["meta"][0] == 7
    assert isinstance(out["t"], tuple)
    # no pickle anywhere: bytes must start with the JSON header
    assert b"treedef" in data[:200]


def test_wire_rejects_bad_version():
    from fedml_tpu.comm import wire

    data = bytearray(wire.encode_pytree({"a": np.zeros(2)}))
    # corrupt the version field
    bad = data.replace(b'"version":1', b'"version":9')
    with pytest.raises(ValueError, match="unsupported wire version"):
        wire.decode_pytree(bytes(bad))


def test_message_roundtrip():
    from fedml_tpu.comm.message import Message

    msg = Message(3, sender_id=2, receiver_id=0)
    msg.add_params("model_params", {"w": np.ones((4, 4), np.float32)})
    msg.add_params("num_samples", 123.0)
    out = Message.decode(msg.encode())
    assert out.get_type() == 3
    assert out.get_sender_id() == 2
    assert out.get("num_samples") == 123.0
    np.testing.assert_array_equal(out.get("model_params")["w"], np.ones((4, 4)))


# ---------------------------------------------------------------------------
# backends
# ---------------------------------------------------------------------------

def _echo_pair(manager_cls, make):
    """Start two endpoints; send 0 -> 1; assert delivery."""
    from fedml_tpu.comm.message import Message

    received = []

    class Obs:
        def receive_message(self, t, m):
            received.append((t, m))

    a, b = make()
    b.add_observer(Obs())
    t = threading.Thread(target=b.handle_receive_message, daemon=True)
    t.start()
    msg = Message(5, 0, 1)
    msg.add_params("x", np.arange(8, dtype=np.float32))
    a.send_message(msg)
    deadline = time.time() + 5
    while not received and time.time() < deadline:
        time.sleep(0.01)
    b.stop_receive_message()
    assert received, "message never delivered"
    assert received[0][0] == 5
    np.testing.assert_array_equal(received[0][1].get("x"), np.arange(8, dtype=np.float32))


def test_inproc_backend():
    from fedml_tpu.comm.inproc import InProcCommManager, InProcRouter

    InProcRouter.reset("t1")
    _echo_pair(None, lambda: (InProcCommManager("t1", 0), InProcCommManager("t1", 1)))


def test_grpc_backend_loopback():
    from fedml_tpu.comm.grpc_backend import GRPCCommManager

    base = 18890
    a = GRPCCommManager("127.0.0.1", base + 0, 0, base_port=base)
    b = GRPCCommManager("127.0.0.1", base + 1, 1, base_port=base)
    try:
        _echo_pair(None, lambda: (a, b))
    finally:
        a.stop_receive_message()


def test_mqtt_s3_backend_offloads_payload():
    from fedml_tpu.comm.mqtt_s3 import InMemoryObjectStore, MqttS3CommManager

    a = MqttS3CommManager("m1", 0)
    b = MqttS3CommManager("m1", 1)
    _echo_pair(None, lambda: (a, b))
    # a big tensor must have gone through the object store, not the topic
    from fedml_tpu.comm.message import Message

    received = []

    class Obs:
        def receive_message(self, t, m):
            received.append(m)

    b.add_observer(Obs())
    t = threading.Thread(target=b.handle_receive_message, daemon=True)
    t.start()
    big = Message(2, 0, 1)
    big.add_params("model_params", {"w": np.zeros((64, 1024), np.float32)})  # 256 KB
    a.send_message(big)
    deadline = time.time() + 5
    while not received and time.time() < deadline:
        time.sleep(0.01)
    b.stop_receive_message()
    assert received
    store = InMemoryObjectStore.get_store("m1")
    assert len(store.blobs) >= 1, "large payload should be offloaded to the store"


def test_mqtt_last_will_liveness():
    from fedml_tpu.comm.mqtt_s3 import InMemoryBroker, MqttS3CommManager

    statuses = []
    a = MqttS3CommManager("m2", 0)
    a.subscribe_status(lambda s: statuses.append(s))
    b = MqttS3CommManager("m2", 1)  # publishes ONLINE
    InMemoryBroker.get("m2").disconnect_ungraceful(b.client_id)
    assert {"ID": 1, "status": "ONLINE"} in statuses
    assert {"ID": 1, "status": "OFFLINE"} in statuses


# ---------------------------------------------------------------------------
# cross-silo end-to-end
# ---------------------------------------------------------------------------

def _cs_config(**kw):
    base = dict(
        training_type="cross_silo",
        client_num_in_total=4,
        client_num_per_round=4,
        comm_round=3,
        learning_rate=0.3,
        frequency_of_the_test=1,
    )
    base.update(kw)
    return tiny_config(**base)


@pytest.mark.locksan
def test_cross_silo_full_protocol(eight_devices):
    import fedml_tpu
    from fedml_tpu.cross_silo import run_in_process_group
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub

    cfg = _cs_config(run_id="cs1")
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)
    history = run_in_process_group(cfg, ds, model, timeout=120.0)
    assert len(history) == 3
    accs = [h["test_acc"] for h in history if "test_acc" in h]
    assert accs[-1] > 0.4, accs


def test_chunked_broadcast_leg_direct(eight_devices):
    """ISSUE 11 satellite (PR-8 carry-over): the server->client BROADCAST
    leg ships as chunk frames over the in-proc fabric when
    extra.comm_chunk_bytes is set — the receiver's assembler reassembles a
    bitwise-identical model message."""
    import threading
    import time as _time

    from fedml_tpu.comm.inproc import InProcCommManager, InProcRouter
    from fedml_tpu.comm.message import Message

    InProcRouter.reset("chunk-bcast")
    server_end = InProcCommManager("chunk-bcast", 0, chunk_bytes=1024)
    client_end = InProcCommManager("chunk-bcast", 1, chunk_bytes=1024)
    received = []

    class Obs:
        def receive_message(self, t, m):
            received.append(m)

    client_end.add_observer(Obs())
    t = threading.Thread(target=client_end.handle_receive_message, daemon=True)
    t.start()
    try:
        # a model broadcast shape: rank 0 -> rank 1, payload >> chunk bound
        bcast = Message(2, 0, 1)
        w = np.arange(64 * 64, dtype=np.float32).reshape(64, 64)
        bcast.add_params("model_params", {"w": w})
        bcast.add_params("round_idx", 3)
        server_end.send_message(bcast)
        deadline = _time.time() + 10
        while not received and _time.time() < deadline:
            _time.sleep(0.01)
    finally:
        client_end.stop_receive_message()
        server_end.stop_receive_message()
        InProcRouter.reset("chunk-bcast")
    assert received, "chunked broadcast never reassembled"
    msg = received[0]
    assert msg.get("round_idx") == 3
    np.testing.assert_array_equal(msg.get("model_params")["w"], w)


def test_chunked_e2e_parity_both_legs(eight_devices):
    """Full sync protocol with extra.comm_chunk_bytes vs without: chunk
    frames flow (both broadcast and upload legs cross the bound), the
    history matches, and the final global model is BITWISE the unchunked
    run's — chunking is transport framing, never semantics."""
    import jax

    import fedml_tpu
    from fedml_tpu.comm.base import CHUNK_FRAMES
    from fedml_tpu.comm.inproc import InProcRouter
    from fedml_tpu.cross_silo import build_client, build_server
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub

    def run(run_id, chunk_bytes):
        extra = {"comm_chunk_bytes": chunk_bytes} if chunk_bytes else {}
        cfg = _cs_config(run_id=run_id, comm_round=2, client_num_in_total=2,
                         client_num_per_round=2, frequency_of_the_test=0,
                         extra=extra)
        fedml_tpu.init(cfg)
        ds = loader.load(cfg)
        model = model_hub.create(cfg, ds.class_num)
        InProcRouter.reset(run_id)
        clients = [build_client(cfg, ds, model, rank=r, backend="INPROC")
                   for r in (1, 2)]
        for c in clients:
            c.run_in_thread()
        server = build_server(cfg, ds, model, backend="INPROC")
        try:
            history = server.run_until_done(timeout=120.0)
        finally:
            for c in clients:
                c.finish()
        return history, jax.device_get(server.aggregator.global_vars)

    plain_hist, plain_vars = run("chk_off", 0)
    frames0 = CHUNK_FRAMES.value()
    chunk_hist, chunk_vars = run("chk_on", 1024)
    frames = CHUNK_FRAMES.value() - frames0
    # both legs chunk: 2 clients x 2 rounds of broadcasts AND uploads, each
    # several frames — far more than the uploads alone would produce
    assert frames > 2 * 2 * 2, f"only {frames} chunk frames flowed"
    assert [h["round"] for h in plain_hist] == [h["round"] for h in chunk_hist]
    for a, b in zip(jax.tree_util.tree_leaves(plain_vars),
                    jax.tree_util.tree_leaves(chunk_vars)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_data_silo_selection(eight_devices):
    """Reference fedml_aggregator.data_silo_selection parity: identity when
    silo count == client count, round-seeded assignment otherwise."""
    import fedml_tpu
    from fedml_tpu.cross_silo import build_server
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub

    cfg = _cs_config(run_id="cs-dss")
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)
    agg = build_server(cfg, ds, model, backend="INPROC").aggregator
    assert agg.data_silo_selection(0, 4, 4) == [0, 1, 2, 3]
    sel = agg.data_silo_selection(3, 30, 6)
    assert len(sel) == 6 and len(set(sel)) == 6  # distinct (no replacement)
    assert all(0 <= s < 30 for s in sel)
    assert sel == agg.data_silo_selection(3, 30, 6)  # round-deterministic
    # round-seeded: the assignment must actually vary across rounds
    assert any(agg.data_silo_selection(r, 30, 6) != sel for r in range(4, 10))
    # bit-parity with the reference's seeded draw
    np.random.seed(3)
    assert sel == np.random.choice(30, 6, replace=False).tolist()
    # more clients than silos is rejected (upstream assert)
    with pytest.raises(ValueError, match="must be"):
        agg.data_silo_selection(0, 2, 6)


def test_cross_silo_via_runner(eight_devices):
    import fedml_tpu
    from fedml_tpu.runner import FedMLRunner

    cfg = _cs_config(run_id="cs2", role="server", backend="INPROC")
    fedml_tpu.init(cfg)
    history = FedMLRunner(cfg).run()
    assert history and history[-1]["round"] == 2


def test_server_schedule_calibrates_from_protocol_counts(eight_devices):
    """VERDICT 'what's weak' #5: the server must derive steps_per_epoch from
    the sample counts clients report in the protocol, not from the
    synthetic_train_size config guess."""
    import jax
    import fedml_tpu
    from fedml_tpu.cross_silo.server import FedMLAggregator
    from fedml_tpu.data import loader
    from fedml_tpu.data.dataset import pad_eval_set
    from fedml_tpu.models import model_hub

    # config claims 10000 samples/client; clients will report 64
    cfg = tiny_config(synthetic_train_size=640, batch_size=16)
    cfg.extra = dict(cfg.extra or {})
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)
    test_arrays = pad_eval_set(ds.test_x, ds.test_y, 32)
    cfg.synthetic_train_size = 160000  # mislead the provisional guess
    agg = FedMLAggregator(cfg, model, ds.train_x[: cfg.batch_size], test_arrays)
    provisional = agg.hp.steps_per_epoch
    assert provisional == 160000 // 8 // 16  # the wrong guess

    params = jax.device_get(agg.global_vars)
    for cid in (1, 2):
        agg.add_local_trained_result(cid, params, 64.0)
    agg.aggregate(0)
    assert agg.hp.steps_per_epoch == 4  # ceil(64 / 16): the protocol truth


def test_cross_silo_straggler_bounded_wait(eight_devices):
    """A dead client must NOT stall the round when bounded wait is on —
    the mid-round straggler gap called out in SURVEY.md §5."""
    import fedml_tpu
    from fedml_tpu.comm.inproc import InProcRouter
    from fedml_tpu.cross_silo import build_client, build_server
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub
    from fedml_tpu.cross_silo import message_define as md

    cfg = _cs_config(run_id="cs3", comm_round=2)
    cfg.extra = {"straggler_timeout_s": 1.0, "straggler_quorum_frac": 0.5}
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)
    InProcRouter.reset("cs3")
    router = InProcRouter.get("cs3")
    # drop all model uploads from client 4 (it answers status, then goes dark)
    router.drop_rule = lambda m: (
        m.get_sender_id() == 4 and m.get_type() == md.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER
    )
    clients = [build_client(cfg, ds, model, rank=r, backend="INPROC") for r in range(1, 5)]
    for c in clients:
        c.run_in_thread()
    server = build_server(cfg, ds, model, backend="INPROC")
    history = server.run_until_done(timeout=60.0)
    for c in clients:
        c.finish()
    assert len(history) == 2, "rounds must complete despite the dead client"


def test_cross_silo_over_grpc(eight_devices):
    """Full protocol over real gRPC loopback (the reference's perf-critical
    backend, here with the polyglot wire format)."""
    import fedml_tpu
    from fedml_tpu.cross_silo import build_client, build_server
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub

    cfg = _cs_config(run_id="cs4", client_num_in_total=2, client_num_per_round=2, comm_round=2)
    cfg.extra = {"grpc_base_port": 19200}
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)
    clients = [build_client(cfg, ds, model, rank=r, backend="GRPC") for r in (1, 2)]
    for c in clients:
        c.run_in_thread()
    server = build_server(cfg, ds, model, backend="GRPC")
    try:
        history = server.run_until_done(timeout=120.0)
    finally:
        for c in clients:
            c.finish()
    assert len(history) == 2


# ---------------------------------------------------------------------------
# checkpoint / resume
# ---------------------------------------------------------------------------

def test_checkpoint_resume(eight_devices, tmp_path):
    """Kill-and-resume must reproduce the uninterrupted run exactly."""
    import jax
    import fedml_tpu
    from fedml_tpu.runner import FedMLRunner

    ck = str(tmp_path / "ckpt")
    # uninterrupted 4-round run
    cfg_a = tiny_config(comm_round=4, client_num_per_round=4)
    fedml_tpu.init(cfg_a)
    ra = FedMLRunner(cfg_a)
    ra.run()
    # run 2 rounds, checkpoint, "crash", resume for rounds 3-4
    cfg_b = tiny_config(comm_round=2, client_num_per_round=4,
                        checkpoint_dir=ck, checkpoint_every_rounds=1)
    fedml_tpu.init(cfg_b)
    rb = FedMLRunner(cfg_b)
    rb.run()
    cfg_c = tiny_config(comm_round=4, client_num_per_round=4,
                        checkpoint_dir=ck, resume=True)
    fedml_tpu.init(cfg_c)
    rc = FedMLRunner(cfg_c)
    assert rc.runner.try_resume()
    assert rc.runner.round_idx == 2
    rc.runner.run()
    a = jax.tree_util.tree_leaves(jax.device_get(ra.runner.global_vars))
    c = jax.tree_util.tree_leaves(jax.device_get(rc.runner.global_vars))
    for x, y in zip(a, c):
        np.testing.assert_allclose(x, y, rtol=1e-5, atol=1e-6)


def test_mqtt_real_adapters_interface_conformance():
    """The paho/boto3 adapters implement the exact broker/store interfaces the
    MqttS3CommManager consumes; without the libs installed they must raise a
    clear ImportError naming the missing dependency (never fail at first
    use), and with a stub client the S3 store must round-trip."""
    import pytest as _pt

    from fedml_tpu.comm import mqtt_real
    from fedml_tpu.comm.mqtt_s3 import InMemoryBroker, InMemoryObjectStore

    # interface parity: same method surface as the in-memory fakes
    for meth in ("publish", "subscribe", "set_will"):
        assert hasattr(mqtt_real.PahoMqttBroker, meth) and hasattr(InMemoryBroker, meth)
    for meth in ("put", "get"):
        assert hasattr(mqtt_real.S3ObjectStore, meth) and hasattr(InMemoryObjectStore, meth)

    if mqtt_real._paho is None:
        with _pt.raises(ImportError, match="paho-mqtt"):
            mqtt_real.PahoMqttBroker("localhost")
    if mqtt_real._boto3 is None:
        with _pt.raises(ImportError, match="boto3"):
            mqtt_real.S3ObjectStore(bucket="b")

    class StubS3:
        def __init__(self):
            self.blobs = {}

        def put_object(self, Bucket, Key, Body):
            self.blobs[(Bucket, Key)] = Body

        def get_object(self, Bucket, Key):
            import io

            return {"Body": io.BytesIO(self.blobs[(Bucket, Key)])}

    store = mqtt_real.S3ObjectStore(bucket="b", client=StubS3())
    store.put("k1", b"payload")
    assert store.get("k1") == b"payload"


def test_blockchain_backend_echo_and_cross_silo(eight_devices):
    """Web3/Theta backends: messages as ledger transactions (reference
    web3_comm_manager shape); a full cross-silo round runs over the chain."""
    from fedml_tpu.comm.blockchain import BlockchainCommManager, InMemoryLedger

    InMemoryLedger.reset("bc1")
    _echo_pair(None, lambda: (BlockchainCommManager("bc1", 0), BlockchainCommManager("bc1", 1)))

    # one FL round over the chain via the comm-manager dispatch
    import fedml_tpu
    from fedml_tpu.cross_silo import run_in_process_group
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub

    cfg = _cs_config(run_id="bc2", comm_round=1, client_num_in_total=2,
                     client_num_per_round=2, frequency_of_the_test=1)
    fedml_tpu.init(cfg)
    InMemoryLedger.reset("bc2")
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)
    history = run_in_process_group(cfg, ds, model, backend="WEB3", timeout=120.0)
    assert len(history) == 1 and "test_acc" in history[0]


def test_intra_silo_dp_numerics_match(eight_devices):
    """Row: the reference's DDP-in-silo. A silo with 8 local devices shards
    its local shard over a data mesh axis; the SPMD run must match the
    unsharded run's numerics exactly (DDP changes partitioning, not math)."""
    import jax
    import jax.numpy as jnp
    import fedml_tpu
    from fedml_tpu.core import rng
    from fedml_tpu.cross_silo.client import FedMLTrainer
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub

    cfg = tiny_config(batch_size=16)
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)
    ix = ds.client_idx[0]
    k0 = rng.root_key(cfg.random_seed)
    variables = model.init({"params": jax.random.PRNGKey(1)},
                           jnp.asarray(ds.train_x[:2]), train=True)

    dp = FedMLTrainer(cfg, model, ds.train_x[ix], ds.train_y[ix])
    assert dp.dp_active

    # the COMPUTE must be partitioned, not just the at-rest arrays: the
    # per-device dot operates on batch/n_local = 16/8 = 2 rows
    hlo = dp._train.lower(variables, dp.x, dp.y, dp.count, k0, None).compile().as_text()
    assert "f32[2,60]" in hlo or "f32[2,10]" in hlo, "per-step batch is not sharded"

    cfg_off = tiny_config(batch_size=16, extra={"silo_dp": False})
    plain = FedMLTrainer(cfg_off, model, ds.train_x[ix], ds.train_y[ix])
    assert not plain.dp_active

    # indivisible batch size must refuse DP loudly rather than fake it
    cfg_odd = tiny_config(batch_size=15)
    odd = FedMLTrainer(cfg_odd, model, ds.train_x[ix], ds.train_y[ix])
    assert not odd.dp_active

    out_dp, n_dp = dp.train(variables, 0, k0, client_idx=0)
    out_plain, n_plain = plain.train(variables, 0, k0, client_idx=0)
    assert n_dp == n_plain
    for a, b in zip(jax.tree_util.tree_leaves(out_dp), jax.tree_util.tree_leaves(out_plain)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-5)
