"""Streaming inference + persisted request metrics (VERDICT round-2 item 9).

- POST /predict with stream=true returns newline-delimited JSON chunks
  (reference fedml_inference_runner.py StreamingResponse path).
- The gateway forwards streams and records latency; request telemetry is
  persisted into the deploy DB every reconcile sweep.
"""

import json
import urllib.request

import numpy as np
import pytest


def _post(port, body, stream=False):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/predict",
        data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json"},
    )
    resp = urllib.request.urlopen(req, timeout=10)
    if not stream:
        return json.loads(resp.read())
    with resp:
        return [json.loads(l) for l in resp if l.strip()]


@pytest.fixture
def runner():
    from fedml_tpu.serving.inference import FedMLInferenceRunner, FedMLPredictor

    class TokenPredictor(FedMLPredictor):
        def predict(self, request):
            return {"outputs": request["inputs"]}

        def predict_stream(self, request):
            for i, tok in enumerate(request["inputs"]):
                yield {"index": i, "token": tok}

    r = FedMLInferenceRunner(TokenPredictor(), port=0)
    r.run(block=False)
    yield r
    r.stop()


def test_stream_route_yields_chunks(runner):
    chunks = _post(runner.port, {"inputs": ["a", "b", "c"], "stream": True}, stream=True)
    assert chunks == [
        {"index": 0, "token": "a"},
        {"index": 1, "token": "b"},
        {"index": 2, "token": "c"},
    ]
    # non-stream requests still get the plain JSON response
    out = _post(runner.port, {"inputs": ["a"]})
    assert out == {"outputs": ["a"]}


def test_stream_early_failure_is_clean_400():
    from fedml_tpu.serving.inference import FedMLInferenceRunner, FedMLPredictor

    class Broken(FedMLPredictor):
        def predict_stream(self, request):
            raise ValueError("boom")
            yield  # pragma: no cover

    r = FedMLInferenceRunner(Broken(), port=0)
    r.run(block=False)
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(r.port, {"stream": True}, stream=True)
        assert ei.value.code == 400
        assert "boom" in ei.value.read().decode()
    finally:
        r.stop()


def test_default_predict_stream_falls_back_to_predict():
    from fedml_tpu.serving.inference import FedMLPredictor

    class P(FedMLPredictor):
        def predict(self, request):
            return {"x": 1}

    assert list(P().predict_stream({})) == [{"x": 1}]


def test_jax_predictor_streams_per_row(eight_devices):
    import flax.linen as nn
    import jax

    from fedml_tpu.serving.inference import JaxPredictor

    class M(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            return nn.Dense(3)(x)

    m = M()
    v = m.init(jax.random.PRNGKey(0), np.zeros((1, 4), np.float32))
    p = JaxPredictor(m, v, max_batch=8)
    chunks = list(p.predict_stream({"inputs": np.ones((2, 4)).tolist()}))
    assert [c["index"] for c in chunks] == [0, 1]
    assert len(chunks[0]["outputs"]) == 3


def test_gateway_stream_and_persisted_stats(tmp_path):
    """End-to-end through the deploy scheduler: streaming predict via the
    gateway, latency EWM recorded, stats persisted to the DB by reconcile."""
    import jax

    import fedml_tpu
    from tests.conftest import tiny_config
    from fedml_tpu.models import model_hub
    from fedml_tpu.serving.deploy import ModelCard, ModelDeployScheduler, save_params_card

    cfg = tiny_config()
    fedml_tpu.init(cfg)
    model = model_hub.create(cfg, 10)
    variables = model.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        np.zeros((1, 32), np.float32), train=True,
    )
    path = str(tmp_path / "m.wire")
    save_params_card(variables, path)
    card = ModelCard(name="lr-s", version="v1", model="lr", classes=10, params_path=path)

    sched = ModelDeployScheduler(str(tmp_path / "db.sqlite"), reconcile_interval_s=0.3)
    sched.cards.register(card)
    try:
        sched.deploy("demo", "lr-s", replicas=1)
        sched.run_in_thread()
        assert sched.wait_ready("demo", replicas=1, timeout=180)

        chunks = list(sched.predict_stream("demo", {"inputs": np.zeros((3, 32)).tolist()}))
        assert [c["index"] for c in chunks] == [0, 1, 2]
        assert len(chunks[0]["outputs"]) == 10
        sched.predict("demo", {"inputs": np.zeros((1, 32)).tolist()})

        ep = sched.endpoints["demo"]
        assert ep.latency_ms_ewm is not None and ep.latency_ms_ewm > 0
        # reconcile persists the telemetry
        deadline = 20
        import time as _t

        stats = None
        for _ in range(int(deadline / 0.2)):
            stats = sched.db.stats("demo")
            if stats is not None and stats["requests"] >= 2:
                break
            _t.sleep(0.2)
        assert stats is not None and stats["requests"] >= 2, stats
        assert stats["latency_ms_ewm"] > 0
    finally:
        sched.stop()


def test_none_chunk_is_streamed_not_dropped():
    """A predictor whose first yielded chunk is a literal None must stream
    'null' — None is not the empty-stream sentinel (round-3 advisor)."""
    from fedml_tpu.serving.inference import FedMLInferenceRunner, FedMLPredictor

    class NonePredictor(FedMLPredictor):
        def predict_stream(self, request):
            yield None
            yield {"x": 1}

    r = FedMLInferenceRunner(NonePredictor(), port=0)
    r.run(block=False)
    try:
        chunks = _post(r.port, {"stream": True}, stream=True)
        assert chunks == [None, {"x": 1}]
    finally:
        r.stop()


def test_abandoned_gateway_stream_releases_inflight(tmp_path):
    """predict_stream counts as inflight the moment the response opens, and an
    abandoned (never-iterated or half-read) stream releases its slot and its
    socket at close() — not at GC (round-3 advisor)."""
    import jax

    import fedml_tpu
    from tests.conftest import tiny_config
    from fedml_tpu.models import model_hub
    from fedml_tpu.serving.deploy import ModelCard, ModelDeployScheduler, save_params_card

    cfg = tiny_config()
    fedml_tpu.init(cfg)
    model = model_hub.create(cfg, 10)
    variables = model.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        np.zeros((1, 32), np.float32), train=True,
    )
    path = str(tmp_path / "m.wire")
    save_params_card(variables, path)
    sched = ModelDeployScheduler(str(tmp_path / "db.sqlite"), reconcile_interval_s=30)
    sched.cards.register(ModelCard(name="lr-s", version="v1", model="lr",
                                   classes=10, params_path=path))
    try:
        sched.deploy("demo", "lr-s", replicas=1)
        assert sched.wait_ready("demo", replicas=1, timeout=180)
        ep = sched.endpoints["demo"]

        # never iterated: inflight counted on open, released on close()
        h = sched.predict_stream("demo", {"inputs": np.zeros((2, 32)).tolist()})
        assert ep.inflight == 1
        h.close()
        assert ep.inflight == 0

        # half-read then abandoned
        h2 = sched.predict_stream("demo", {"inputs": np.zeros((3, 32)).tolist()})
        assert next(h2)["index"] == 0
        assert ep.inflight == 1
        h2.close()
        assert ep.inflight == 0
        assert ep.latency_ms_ewm is not None

        # fully drained stream still accounts exactly once
        assert len(list(sched.predict_stream(
            "demo", {"inputs": np.zeros((2, 32)).tolist()}))) == 2
        assert ep.inflight == 0
    finally:
        sched.stop()


def test_file_response_for_non_json_accept(tmp_path):
    """A non-JSON Accept header routes to predict_file and serves the file
    bytes with the requested content type (reference FileResponse path);
    JSON-only predictors yield a clean 400."""
    from fedml_tpu.serving.inference import FedMLInferenceRunner, FedMLPredictor

    art = tmp_path / "out.bin"
    art.write_bytes(b"\x89artifact")

    class FilePredictor(FedMLPredictor):
        def predict(self, request):
            return {"ok": True}

        def predict_file(self, request, accept):
            return str(art)

    r = FedMLInferenceRunner(FilePredictor(), port=0)
    r.run(block=False)
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{r.port}/predict", data=json.dumps({}).encode(),
            headers={"Content-Type": "application/json",
                     "Accept": "application/octet-stream"},
        )
        with urllib.request.urlopen(req, timeout=10) as resp:
            assert resp.headers["Content-Type"] == "application/octet-stream"
            assert resp.read() == b"\x89artifact"
        # JSON accept still hits predict()
        out = _post(r.port, {})
        assert out == {"ok": True}
    finally:
        r.stop()

    class JsonOnly(FedMLPredictor):
        def predict(self, request):
            return {"ok": True}

    r2 = FedMLInferenceRunner(JsonOnly(), port=0)
    r2.run(block=False)
    try:
        req = urllib.request.Request(
            f"http://127.0.0.1:{r2.port}/predict", data=json.dumps({}).encode(),
            headers={"Content-Type": "application/json", "Accept": "image/png"},
        )
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 400
    finally:
        r2.stop()
