"""Simulation-platform breadth: decentralized, hierarchical, async, SplitNN,
FedGKT, VFL — each runs end-to-end through the runner dispatch and learns.

Covers SURVEY.md §2.14 strategies P5, P7, P8, P9, P10, P11.
"""

import numpy as np
import pytest

from .conftest import tiny_config


def _run(**kw):
    import fedml_tpu

    return fedml_tpu.run_simulation(tiny_config(**kw))


def test_decentralized_dsgd(eight_devices):
    h = _run(federated_optimizer="decentralized_fl", comm_round=8,
             learning_rate=0.3, frequency_of_the_test=4)
    accs = [m["test_acc"] for m in h if "test_acc" in m]
    assert accs[-1] > 0.3, accs
    # consensus distance must be finite and shrinking-ish
    cds = [m["consensus_dist"] for m in h if "consensus_dist" in m]
    assert np.isfinite(cds).all()


def test_decentralized_pushsum(eight_devices):
    import fedml_tpu

    cfg = tiny_config(federated_optimizer="decentralized_fl", comm_round=8,
                      learning_rate=0.3, frequency_of_the_test=8)
    cfg.extra = {"decentralized_mode": "pushsum", "topology_neighbor_num": 2}
    h = fedml_tpu.run_simulation(cfg)
    accs = [m["test_acc"] for m in h if "test_acc" in m]
    assert accs[-1] > 0.3, accs


def test_ring_gossip_ppermute_matches_dense_matmul(eight_devices):
    """The ppermute halo-exchange ring mix must equal ring_topology(n) @ P —
    the dense-matmul reference — leaf for leaf; and the ring mode must learn
    end-to-end through the runner dispatch."""
    import jax
    import jax.numpy as jnp

    import fedml_tpu
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub
    from fedml_tpu.parallel import topology as topo
    from fedml_tpu.sim.decentralized import DecentralizedSimulator

    cfg = tiny_config(federated_optimizer="decentralized_fl", comm_round=2,
                      client_num_in_total=16, learning_rate=0.3)
    cfg.extra = {"decentralized_mode": "ring"}
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)
    sim = DecentralizedSimulator(cfg, ds, model, mode="ring")
    n = ds.n_clients
    mix = jax.jit(sim._make_ring_mix(n))

    # parity: random stacked tree through both mixers
    key = jax.random.PRNGKey(0)
    tree = {
        "w": jax.random.normal(key, (n, 5, 3)),
        "b": jax.random.normal(jax.random.fold_in(key, 1), (n, 7)),
    }
    tree = fedml_tpu.parallel.mesh.shard_leading_axis(tree, sim.mesh)
    W = jnp.asarray(topo.ring_topology(n))
    got = mix(tree)
    for k in tree:
        want = jnp.tensordot(W, tree[k], axes=([1], [0]))
        np.testing.assert_allclose(np.asarray(got[k]), np.asarray(want), atol=1e-5)

    # and the full round learns
    h = sim.run_round()
    assert np.isfinite(h["train_loss"])


def test_pushsum_mixing_recovers_uniform_average():
    """Pure PushSum iteration on a directed (column-stochastic) topology must
    converge to the UNIFORM average of the initial values — regression for the
    row-stochastic matrix that degenerated to a stationary-weighted consensus."""
    from fedml_tpu.parallel import topology as topo

    n = 6
    W = topo.column_stochastic(topo.asymmetric_topology(n, 2, seed=3))
    np.testing.assert_allclose(W.sum(axis=0), np.ones(n), atol=1e-6)
    x = np.arange(1.0, n + 1.0)  # distinct initial values
    w = np.ones(n)
    for _ in range(200):
        x = W @ x
        w = W @ w
    ratio = x / w
    np.testing.assert_allclose(ratio, np.full(n, np.mean(np.arange(1.0, n + 1.0))), atol=1e-5)


def test_hierarchical_respects_client_num_per_round(eight_devices):
    """client_num_per_round < n must still learn (sampled sub-rounds) and the
    sampled trajectory must differ from full participation (regression:
    client_num_per_round used to be silently ignored)."""
    kw = dict(federated_optimizer="HierarchicalFL", comm_round=4, group_num=2,
              group_comm_round=2, learning_rate=0.3, frequency_of_the_test=4)
    h_sampled = _run(**kw, client_num_per_round=4)
    accs = [m["test_acc"] for m in h_sampled if "test_acc" in m]
    assert accs[-1] > 0.3, accs
    h_full = _run(**kw, client_num_per_round=8)
    sampled_losses = [m["train_loss"] for m in h_sampled]
    full_losses = [m["train_loss"] for m in h_full]
    assert sampled_losses != full_losses, "sampling had no effect on trajectory"


def test_hierarchical_fl(eight_devices):
    h = _run(federated_optimizer="HierarchicalFL", comm_round=4, group_num=2,
             group_comm_round=2, learning_rate=0.3, frequency_of_the_test=2)
    accs = [m["test_acc"] for m in h if "test_acc" in m]
    assert accs[-1] > 0.35, accs


def test_async_fedavg(eight_devices):
    h = _run(federated_optimizer="Async_FedAvg", comm_round=30,
             learning_rate=0.3, async_staleness_alpha=0.6,
             frequency_of_the_test=10)
    accs = [m["test_acc"] for m in h if "test_acc" in m]
    assert accs[-1] > 0.3, accs
    stals = [m["staleness"] for m in h]
    assert max(stals) > 0, "staleness never exercised"


def test_splitnn(eight_devices):
    h = _run(federated_optimizer="split_nn", comm_round=4, client_num_in_total=4,
             learning_rate=0.2, frequency_of_the_test=2)
    accs = [m["test_acc"] for m in h if "test_acc" in m]
    assert accs[-1] > 0.3, accs


def test_fedgkt(eight_devices):
    h = _run(federated_optimizer="FedGKT", comm_round=4, client_num_in_total=4,
             learning_rate=0.2, frequency_of_the_test=2)
    accs = [m["test_acc"] for m in h if "test_acc" in m]
    assert accs[-1] > 0.25, accs


def test_vertical_fl(eight_devices):
    h = _run(federated_optimizer="vertical_fl", comm_round=4, learning_rate=0.2,
             epochs=2, frequency_of_the_test=2)
    accs = [m["test_acc"] for m in h if "test_acc" in m]
    assert accs[-1] > 0.4, accs


def test_hierarchical_over_2d_silo_mesh(eight_devices):
    """The P5 design: hierarchical FL over a 2-D (silo, data) mesh — the
    stacked clients shard over the outer silo axis (shard_leading_axis falls
    back to the mesh's first axis when 'clients' is absent)."""
    import fedml_tpu
    from fedml_tpu.runner import FedMLRunner

    cfg = tiny_config(
        federated_optimizer="HierarchicalFL", client_num_in_total=8,
        client_num_per_round=8, comm_round=2, group_num=2, group_comm_round=2,
        mesh_shape="silo:2,data:4", frequency_of_the_test=1,
    )
    fedml_tpu.init(cfg)
    history = FedMLRunner(cfg).run()
    assert np.isfinite(history[-1]["train_loss"])
    assert history[-1]["test_acc"] > 0.2
