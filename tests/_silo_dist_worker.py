"""Worker entry for the 2-process distributed-silo test (spawned by
tests/test_silo_dist.py).  Usage:

    python tests/_silo_dist_worker.py <process_id> <num_processes> <port>

One silo spans both processes (4 virtual CPU devices each -> an 8-device
global ``data`` mesh for its local SGD).  Process 0 runs the FULL cross-silo
FL group (server + silo master over INPROC); process 1 runs the follower
loop.  Process 0 prints the final global checksum as MULTIHOST_RESULT.
"""

import json
import os
import sys


def main():
    pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["JAX_PLATFORM_NAME"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax

    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

    import fedml_tpu
    from fedml_tpu.arguments import Config
    from fedml_tpu.parallel import multihost

    cfg = Config(
        training_type="cross_silo",
        dataset="synthetic",
        model="lr",
        client_num_in_total=1,
        client_num_per_round=1,
        comm_round=2,
        epochs=1,
        batch_size=16,
        learning_rate=0.1,
        synthetic_train_size=256,
        synthetic_test_size=64,
        partition_method="homo",
        frequency_of_the_test=1,
        compute_dtype="float32",
        random_seed=0,
        backend="INPROC",
        extra={
            "coordinator_address": f"localhost:{port}",
            "num_processes": nproc,
            "process_id": pid,
        },
    )
    fedml_tpu.init(cfg)
    multihost.ensure_initialized(cfg)
    assert jax.process_count() == nproc, jax.process_count()

    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub

    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)
    ix = ds.client_idx[0]
    x, y = ds.train_x[ix], ds.train_y[ix]

    if pid == 0:
        import numpy as np

        from fedml_tpu.comm.inproc import InProcRouter
        from fedml_tpu.cross_silo import build_server
        from fedml_tpu.cross_silo.client import ClientMasterManager
        from fedml_tpu.cross_silo.silo_dist import DistributedSiloTrainer

        InProcRouter.reset("silo-dist")
        trainer = DistributedSiloTrainer(cfg, model, x, y)
        client = ClientMasterManager(cfg, trainer, rank=1, backend="INPROC")
        client.run_in_thread()
        server = build_server(cfg, ds, model, backend="INPROC")
        try:
            history = server.run_until_done(timeout=180.0)
        finally:
            trainer.finish()  # release the follower
            client.finish()
        flat = np.concatenate([
            np.asarray(l, dtype=np.float64).ravel()
            for l in jax.tree_util.tree_leaves(jax.device_get(server.aggregator.global_vars))
        ])
        print("MULTIHOST_RESULT " + json.dumps({
            "pid": pid,
            "checksum": float(flat.sum()),
            "l2": float(np.sqrt((flat ** 2).sum())),
            "test_acc": history[-1].get("test_acc"),
        }), flush=True)
    else:
        from fedml_tpu.cross_silo.silo_dist import run_silo_follower

        rounds = run_silo_follower(cfg, model, x, y)
        print("MULTIHOST_RESULT " + json.dumps({"pid": pid, "rounds": rounds}), flush=True)


if __name__ == "__main__":
    main()
