"""Model-deploy scheduler tests (VERDICT item 8, reference
computing/scheduler/model_scheduler/): endpoint lifecycle, kill-and-recover
reconcile, scale up/down, autoscaler policy decisions, gateway routing."""

import os
import signal
import time

import numpy as np
import pytest

from .conftest import tiny_config


@pytest.fixture
def lr_card(tmp_path, eight_devices):
    """A registered ModelCard for a trained-ish LR model."""
    import jax
    import jax.numpy as jnp

    import fedml_tpu
    from fedml_tpu.models import model_hub
    from fedml_tpu.serving.deploy import ModelCard, save_params_card

    cfg = tiny_config()
    fedml_tpu.init(cfg)
    model = model_hub.create(cfg, 10)
    variables = model.init({"params": jax.random.PRNGKey(0)}, jnp.zeros((1, 32)), train=True)
    path = save_params_card(variables, str(tmp_path / "lr.wire"))
    return ModelCard(name="lr-demo", version="v1", model="lr", classes=10, params_path=path)


def _scheduler(tmp_path, **kw):
    from fedml_tpu.serving.deploy import ModelDeployScheduler

    return ModelDeployScheduler(str(tmp_path / "endpoints.db"), **kw)


def test_deploy_predict_and_kill_recovery(tmp_path, lr_card):
    """Deploy -> predict -> kill the replica process -> the reconcile loop
    restarts it and the endpoint serves again (the monitor guarantee)."""
    sched = _scheduler(tmp_path, reconcile_interval_s=0.3)
    sched.cards.register(lr_card)
    try:
        ep = sched.deploy("demo", "lr-demo", replicas=1)
        sched.run_in_thread()
        assert sched.wait_ready("demo", replicas=1, timeout=180)
        out = sched.predict("demo", {"inputs": np.zeros((2, 32)).tolist()})
        assert len(out["outputs"]) == 2 and len(out["outputs"][0]) == 10

        # kill the replica out from under the scheduler
        victim = ep.procs[0]
        victim.kill()
        victim.wait(timeout=10)
        assert sched.wait_ready("demo", replicas=1, timeout=180), "monitor did not restart replica"
        assert ep.procs[0].pid != victim.pid
        out2 = sched.predict("demo", {"inputs": np.zeros((1, 32)).tolist()})
        assert len(out2["outputs"]) == 1
        db_rows = sched.db.replicas("demo")
        assert db_rows and db_rows[0]["restarts"] >= 1
    finally:
        sched.stop()


def test_scale_up_down(tmp_path, lr_card):
    sched = _scheduler(tmp_path)
    sched.cards.register(lr_card)
    try:
        sched.deploy("demo", "lr-demo", replicas=1)
        assert sched.wait_ready("demo", replicas=1, timeout=180)
        sched.scale("demo", 2)
        assert sched.wait_ready("demo", replicas=2, timeout=180)
        assert len(sched.db.replicas("demo")) == 2
        sched.scale("demo", 1)
        sched.reconcile_once()
        assert len(sched.endpoints["demo"].procs) == 1
        assert len(sched.db.replicas("demo")) == 1
    finally:
        sched.stop()


def test_undeploy_stops_processes(tmp_path, lr_card):
    sched = _scheduler(tmp_path)
    sched.cards.register(lr_card)
    ep = sched.deploy("demo", "lr-demo", replicas=1)
    assert sched.wait_ready("demo", timeout=180)
    proc = ep.procs[0]
    sched.undeploy("demo")
    assert proc.poll() is not None  # process stopped
    assert sched.db.endpoint("demo")["status"] == "UNDEPLOYED"
    with pytest.raises(KeyError):
        sched.predict("demo", {"inputs": [[0.0] * 32]})


def test_autoscaler_policies():
    from fedml_tpu.serving.deploy import AutoscalePolicy, Autoscaler

    # EWM scale-up: sustained qps over target grows replicas, bounded by max
    a = Autoscaler(AutoscalePolicy(min_replicas=1, max_replicas=3,
                                   target_qps_per_replica=10.0, scaledown_delay_s=5.0))
    assert a.desired(current=1, qps=25.0, concurrency=0, now=0.0) == 3
    assert a.desired(current=3, qps=100.0, concurrency=0, now=1.0) == 3  # capped

    # scale-down honors the delay interval (reference enforce_scaling_down_delay)
    b = Autoscaler(AutoscalePolicy(min_replicas=1, max_replicas=4,
                                   target_qps_per_replica=10.0, scaledown_delay_s=10.0))
    assert b.desired(current=4, qps=5.0, concurrency=0, now=0.0) == 4   # delay starts
    assert b.desired(current=4, qps=5.0, concurrency=0, now=5.0) == 4   # still waiting
    assert b.desired(current=4, qps=5.0, concurrency=0, now=11.0) == 1  # committed

    # concurrency policy
    c = Autoscaler(AutoscalePolicy(policy="concurrency", min_replicas=1, max_replicas=8,
                                   target_concurrency_per_replica=2.0))
    assert c.desired(current=1, qps=0.0, concurrency=7.0, now=0.0) == 4

    # model card versioning resolves latest
    from fedml_tpu.serving.deploy import ModelCard, ModelCardRepo

    repo = ModelCardRepo()
    repo.register(ModelCard("m", "v1", "lr", 10, "/a"))
    repo.register(ModelCard("m", "v2", "lr", 10, "/b"))
    assert repo.get("m").version == "v2"
    assert repo.get("m", "v1").params_path == "/a"


def test_deploy_through_injected_runtime(tmp_path, lr_card):
    """Full endpoint lifecycle through the ReplicaRuntime seam (round-3
    verdict item 5b): an injected 'container' runtime sees every start/stop,
    the gateway serves through it, a killed replica is restarted via poll,
    scale-down and undeploy stop its replicas."""
    import json
    import threading
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    from fedml_tpu.serving.deploy import ReplicaRuntime

    class FakeContainer:
        def __init__(self, cid):
            self.cid = cid
            self.exit_code = None

            class Handler(BaseHTTPRequestHandler):
                def log_message(self, *a):
                    pass

                def do_GET(h):
                    h.send_response(200)
                    body = json.dumps({"status": "ready"}).encode()
                    h.send_header("Content-Length", str(len(body)))
                    h.end_headers()
                    h.wfile.write(body)

                def do_POST(h):
                    n = int(h.headers.get("Content-Length", 0))
                    h.rfile.read(n)
                    body = json.dumps({"outputs": [[0.0] * 10], "container": self.cid}).encode()
                    h.send_response(200)
                    h.send_header("Content-Length", str(len(body)))
                    h.end_headers()
                    h.wfile.write(body)

            self.server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
            self.port = self.server.server_address[1]
            threading.Thread(target=self.server.serve_forever, daemon=True).start()

        def kill(self, rc=137):
            self.exit_code = rc
            self.server.shutdown()
            self.server.server_close()

    class ContainerRuntime(ReplicaRuntime):
        def __init__(self):
            self.started, self.stopped = [], []
            self._next = 0

        def start(self, card):
            self._next += 1
            c = FakeContainer(self._next)
            self.started.append(c)
            return c, c.port

        def stop(self, handle):
            self.stopped.append(handle)
            if handle.exit_code is None:
                handle.kill(rc=0)

        def poll(self, handle):
            return handle.exit_code

        def replica_id(self, handle):
            return handle.cid

    rt = ContainerRuntime()
    sched = _scheduler(tmp_path, reconcile_interval_s=30, runtime=rt)
    sched.cards.register(lr_card)
    try:
        sched.deploy("ct", "lr-demo", replicas=2)
        assert sched.wait_ready("ct", replicas=2, timeout=30)
        assert len(rt.started) == 2

        # the gateway routes through the injected runtime's replicas
        out = sched.predict("ct", {"inputs": np.zeros((1, 32)).tolist()})
        assert out["container"] in (1, 2)

        # kill container 1 -> reconcile restarts through the seam
        rt.started[0].kill()
        sched.reconcile_once()
        assert len(rt.started) == 3
        assert sched.wait_ready("ct", replicas=2, timeout=30)

        # scale down -> the extra replica is stopped through the seam
        sched.scale("ct", 1)
        assert any(h.cid for h in rt.stopped)

        sched.undeploy("ct")
        live = [c for c in rt.started if c.exit_code is None]
        assert not live, "undeploy must stop every container"
        row = sched.db.endpoint("ct")
        assert row is not None and row["status"] == "UNDEPLOYED", row
        assert sched.db.replicas("ct") == []
    finally:
        sched.stop()
