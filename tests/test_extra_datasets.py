"""Dataset breadth tail (VERDICT round-2 missing #8): ImageNet folder
reader, UCI tables, NUS-WIDE, FeTS2021 masks, and the canonical edge-case
poisoned sets — each exercised end-to-end from generated fixtures."""

import pickle

import numpy as np
import pytest

from .conftest import tiny_config


def test_image_folder_reader(tmp_path):
    from fedml_tpu.data import loader

    rng = np.random.RandomState(0)
    root = tmp_path / "ILSVRC2012"
    for split, n in (("train", 3), ("val", 2)):
        for cls in ("dog", "cat"):
            d = root / split / cls
            d.mkdir(parents=True)
            for i in range(n):
                np.save(d / f"{i}.npy", rng.rand(8, 8, 3).astype(np.float32))
    cfg = tiny_config(dataset="ILSVRC2012", data_cache_dir=str(tmp_path),
                      synthetic_fallback=False, client_num_in_total=2,
                      client_num_per_round=2, partition_method="homo")
    ds = loader.load(cfg)
    assert ds.train_x.shape == (6, 8, 8, 3)
    assert ds.test_x.shape == (4, 8, 8, 3)
    assert set(np.unique(ds.train_y)) == {0, 1}  # cat=0, dog=1 (sorted)


def test_susy_and_room_occupancy_readers(tmp_path):
    from fedml_tpu.data import loader

    d = tmp_path / "SUSY"
    d.mkdir()
    rng = np.random.RandomState(1)
    rows = []
    for i in range(50):
        rows.append(",".join([str(i % 2)] + [f"{v:.4f}" for v in rng.rand(18)]))
    (d / "SUSY.csv").write_text("\n".join(rows) + "\n")
    cfg = tiny_config(dataset="susy", data_cache_dir=str(tmp_path),
                      synthetic_fallback=False, client_num_in_total=2,
                      client_num_per_round=2, partition_method="homo")
    ds = loader.load(cfg)
    assert ds.train_x.shape == (40, 18) and ds.test_x.shape == (10, 18)
    assert set(np.unique(ds.train_y)) <= {0, 1}

    ro = tmp_path / "room_occupancy"
    ro.mkdir()
    header = '"id","date","Temperature","Humidity","Light","CO2","HumidityRatio","Occupancy"'
    for fname, n in (("datatraining.txt", 30), ("datatest.txt", 10)):
        lines = [header]
        for i in range(n):
            lines.append(f'"{i}","2015-02-04",{20+i%3},{27.2},{420+i},{700+i},{0.004},{i%2}')
        (ro / fname).write_text("\n".join(lines) + "\n")
    cfg2 = tiny_config(dataset="room_occupancy", data_cache_dir=str(tmp_path),
                       synthetic_fallback=False, client_num_in_total=2,
                       client_num_per_round=2, partition_method="homo")
    ds2 = loader.load(cfg2)
    assert ds2.train_x.shape == (30, 5) and ds2.test_x.shape == (10, 5)
    assert set(np.unique(ds2.train_y)) == {0, 1}


def test_nus_wide_prepared_npz(tmp_path):
    from fedml_tpu.data import loader

    d = tmp_path / "NUS_WIDE"
    d.mkdir()
    rng = np.random.RandomState(2)
    np.savez(d / "nus_wide_prepared.npz",
             train_x=rng.rand(40, 634).astype(np.float32),
             train_y=rng.randint(0, 5, 40).astype(np.int32),
             test_x=rng.rand(10, 634).astype(np.float32),
             test_y=rng.randint(0, 5, 10).astype(np.int32))
    cfg = tiny_config(dataset="nus_wide", data_cache_dir=str(tmp_path),
                      synthetic_fallback=False, client_num_in_total=2,
                      client_num_per_round=2, partition_method="homo")
    ds = loader.load(cfg)
    assert ds.train_x.shape == (40, 634) and ds.class_num == 5


def test_fets2021_masks_flow_to_fedseg(tmp_path):
    """FeTS volumes: masks ride FederatedDataset.masks; train_y is the
    dominant tissue class; FedSeg consumes the REAL masks."""
    from fedml_tpu.data import loader

    d = tmp_path / "FeTS2021"
    d.mkdir()
    rng = np.random.RandomState(3)
    m = np.zeros((12, 16, 16), np.int32)
    m[:, 4:8, 4:8] = (np.arange(12) % 3 + 1)[:, None, None]
    np.savez(d / "fets2021_prepared.npz",
             train_x=rng.rand(12, 16, 16, 4).astype(np.float32), train_m=m,
             test_x=rng.rand(4, 16, 16, 4).astype(np.float32), test_m=m[:4])
    cfg = tiny_config(dataset="fets2021", data_cache_dir=str(tmp_path),
                      synthetic_fallback=False, client_num_in_total=2,
                      client_num_per_round=2, partition_method="homo")
    ds = loader.load(cfg)
    assert ds.masks is not None and ds.masks.shape == (12, 16, 16)
    np.testing.assert_array_equal(ds.train_y, np.arange(12) % 3 + 1)

    from fedml_tpu.sim.fedseg import FedSegSimulator

    sim = FedSegSimulator(tiny_config(dataset="fets2021", client_num_in_total=2,
                                      client_num_per_round=2, comm_round=1,
                                      batch_size=4), ds)
    # the simulator's stacked masks are the REAL masks, not synthesized
    # quadrants: client 0's first slot equals its first real mask
    first_ix = int(ds.client_idx[0][0])
    np.testing.assert_array_equal(np.asarray(sim._m[0, 0]), m[first_ix])
    np.testing.assert_array_equal(np.asarray(sim._test[1][0]), m[0])


def test_fets2021_synthetic_fallback(eight_devices):
    from fedml_tpu.data import loader

    cfg = tiny_config(dataset="fets2021", synthetic_train_size=24,
                      synthetic_test_size=8, client_num_in_total=2,
                      client_num_per_round=2, partition_method="homo")
    ds = loader.load(cfg)
    assert ds.train_x.shape == (24, 64, 64, 4)
    assert ds.masks.shape == (24, 64, 64)
    assert ds.masks.max() >= 1  # lesions present


def test_edge_case_backdoor_consumes_canonical_sets(tmp_path):
    """With the Southwest pickles on disk, poisoned slots are the canonical
    edge images relabeled to the target class (reference
    edge_case_examples/data_loader.py:460)."""
    from fedml_tpu.data import loader
    from fedml_tpu.trust.attack.attacks import FedMLAttacker

    d = tmp_path / "edge_case_examples" / "southwest_cifar10"
    d.mkdir(parents=True)
    rng = np.random.RandomState(4)
    edge = (rng.rand(5, 32, 32, 3) * 255).astype(np.uint8)
    with open(d / "southwest_images_new_train.pkl", "wb") as f:
        pickle.dump(edge, f)
    with open(d / "southwest_images_new_test.pkl", "wb") as f:
        pickle.dump(edge[:2], f)

    cfg = tiny_config(dataset="cifar10", data_cache_dir=str(tmp_path),
                      synthetic_train_size=64, synthetic_test_size=16,
                      client_num_in_total=2, client_num_per_round=2,
                      enable_attack=True, attack_type="edge_case_backdoor",
                      poisoned_client_list=(0,),
                      extra={"attack_target_class": 7, "attack_poison_frac": 0.5})
    ds = loader.load(cfg)
    poisoned = FedMLAttacker(cfg).poison_data(ds)
    # the canonical images are moment-matched to the destination
    # distribution (the reference applies the dataset transform the same way)
    e = edge.astype(np.float32) / 255.0
    ax = (0, 1, 2)
    x = ds.train_x
    expected_imgs = (e - e.mean(axis=ax)) / (e.std(axis=ax) + 1e-8) \
        * (x.std(axis=ax) + 1e-8) + x.mean(axis=ax)
    hits = 0
    for i in range(poisoned.train_x.shape[0]):
        diffs = np.abs(expected_imgs - poisoned.train_x[i]).reshape(5, -1).max(axis=1)
        if diffs.min() < 1e-4:
            hits += 1
            assert poisoned.train_y[i] == 7
    expected = int(len(ds.client_idx[0]) * 0.5)
    assert hits == expected, (hits, expected)
    # scale sanity: poison lives in the same per-channel moment range
    assert abs(expected_imgs.mean() - x.mean()) < 0.5


def test_edge_case_backdoor_falls_back_without_sets(eight_devices):
    from fedml_tpu.data import loader
    from fedml_tpu.trust.attack.attacks import FedMLAttacker

    cfg = tiny_config(dataset="cifar10", synthetic_train_size=64,
                      synthetic_test_size=16, client_num_in_total=2,
                      client_num_per_round=2, enable_attack=True,
                      attack_type="edge_case_backdoor", poisoned_client_list=(0,),
                      extra={"attack_target_class": 3, "attack_poison_frac": 0.5})
    ds = loader.load(cfg)
    poisoned = FedMLAttacker(cfg).poison_data(ds)
    changed = np.abs(poisoned.train_x - ds.train_x).reshape(len(ds.train_x), -1).max(axis=1) > 1e-6
    assert changed.sum() == int(len(ds.client_idx[0]) * 0.5)
    assert (poisoned.train_y[changed] == 3).all()
