"""Long-tail algorithm families (VERDICT rows 13/14): FedGAN, FedNAS,
FedSeg, TurboAggregate."""

import numpy as np
import pytest

from .conftest import tiny_config


def test_fedgan_trains_both_nets(eight_devices):
    import fedml_tpu
    from fedml_tpu.runner import FedMLRunner

    cfg = tiny_config(
        federated_optimizer="FedGan", dataset="mnist", model="gan",
        comm_round=2, client_num_in_total=4, client_num_per_round=2,
        batch_size=8, synthetic_train_size=128, synthetic_test_size=32,
        learning_rate=2e-4,
    )
    fedml_tpu.init(cfg)
    sim = FedMLRunner(cfg).runner
    history = sim.run()
    assert len(history) == 2
    assert np.isfinite(history[-1]["d_loss"]) and np.isfinite(history[-1]["g_loss"])
    imgs = np.asarray(sim.sample(4))
    assert imgs.shape == (4, 28, 28, 1)
    assert np.abs(imgs).max() <= 1.0 + 1e-5  # tanh range


def test_fednas_searches_and_derives_genotype(eight_devices):
    import fedml_tpu
    from fedml_tpu.runner import FedMLRunner
    from fedml_tpu.models.darts import OPS

    cfg = tiny_config(
        federated_optimizer="FedNAS", dataset="cifar10", model="darts",
        comm_round=2, client_num_in_total=4, client_num_per_round=2,
        batch_size=8, synthetic_train_size=128, synthetic_test_size=64,
        learning_rate=0.05,
    )
    fedml_tpu.init(cfg)
    sim = FedMLRunner(cfg).runner
    history = sim.run()
    assert np.isfinite(history[-1]["train_loss"]) and np.isfinite(history[-1]["arch_loss"])
    geno = sim.genotype()
    assert len(geno) == 2 and all(op in OPS for cell in geno for op in cell)
    # alphas actually moved from their zero init
    alphas = np.asarray(sim.variables["params"]["alphas"])
    assert np.abs(alphas).max() > 0


def test_fedseg_miou_metrics(eight_devices):
    import fedml_tpu
    from fedml_tpu.runner import FedMLRunner

    cfg = tiny_config(
        federated_optimizer="FedSeg", dataset="mnist", model="unet",
        comm_round=2, client_num_in_total=4, client_num_per_round=2,
        batch_size=4, synthetic_train_size=64, synthetic_test_size=32,
        learning_rate=0.1, frequency_of_the_test=1,
    )
    fedml_tpu.init(cfg)
    history = FedMLRunner(cfg).run()
    last = history[-1]
    assert np.isfinite(last["train_loss"])
    for key in ("pixel_acc", "miou", "fwiou"):
        assert 0.0 <= last[key] <= 1.0, (key, last)


def test_turboaggregate_matches_fedavg_and_hides_models(eight_devices):
    """The ring aggregate must equal plain weighted FedAvg, and no group may
    observe an unmasked individual model."""
    import fedml_tpu
    from fedml_tpu.runner import FedMLRunner

    base = dict(
        dataset="synthetic", model="lr", comm_round=2,
        client_num_in_total=8, client_num_per_round=8, batch_size=16,
        synthetic_train_size=512, synthetic_test_size=128,
        frequency_of_the_test=1,
    )
    cfg_ta = tiny_config(federated_optimizer="TA", **base)
    fedml_tpu.init(cfg_ta)
    sim = FedMLRunner(cfg_ta).runner
    history = sim.run()
    assert history[-1]["test_acc"] > 0.4

    cfg_plain = tiny_config(federated_optimizer="FedAvg", **base)
    plain = FedMLRunner(cfg_plain).runner
    plain_history = plain.run()
    # same client sampling/rng -> accuracy trajectories must agree closely
    # (the masked ring adds only float roundoff)
    assert abs(history[-1]["test_acc"] - plain_history[-1]["test_acc"]) < 0.03

    # privacy audit: every vector any group observed is either masked (norm
    # dominated by the mask scale) or a partial SUM, never a bare update
    import jax

    flat_updates_norm = 10.0  # mask stddev is 10 x update scale
    for group_views in sim.observed_by_group[1:]:  # later groups see sums too
        for v in group_views[:-1]:  # masked individual models
            assert np.linalg.norm(v) > flat_updates_norm, np.linalg.norm(v)


def test_turboaggregate_dropout_tolerant(eight_devices):
    import fedml_tpu
    from fedml_tpu.runner import FedMLRunner

    cfg = tiny_config(
        federated_optimizer="TA", comm_round=3, client_num_in_total=8,
        client_num_per_round=8, frequency_of_the_test=3,
        extra={"ta_dropout_prob": 0.3},
    )
    fedml_tpu.init(cfg)
    history = FedMLRunner(cfg).run()
    assert all(h["alive"] >= 1 for h in history)
    assert history[-1]["test_acc"] > 0.4  # survivors still learn
