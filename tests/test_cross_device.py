"""Cross-device platform test (VERDICT row 20, reference
cross_device/server_mnn): the runner's cross_device dispatch drives a fleet
of NATIVE C++ clients over TCP and dumps the per-round model artifact."""

import os
import struct
import subprocess
import sys

import numpy as np
import pytest

from .conftest import tiny_config
from .test_native_client import _wait_listening, _write_shard, native_binary  # noqa: F401


def test_cross_device_runner_with_native_fleet(native_binary, tmp_path, eight_devices):
    import fedml_tpu
    from fedml_tpu.comm import wire
    from fedml_tpu.runner import FedMLRunner

    base_port = 22790
    artifact = tmp_path / "global_model.wire"
    cfg = tiny_config(
        training_type="cross_device", backend="TCP",
        client_num_in_total=2, client_num_per_round=2, comm_round=2,
        batch_size=16, synthetic_train_size=320, synthetic_test_size=160,
        frequency_of_the_test=1,
        extra={"tcp_base_port": base_port, "global_model_file_path": str(artifact)},
    )
    fedml_tpu.init(cfg)
    from fedml_tpu.data import loader

    ds = loader.load(cfg)

    procs = []
    try:
        for rank in (1, 2):
            shard = tmp_path / f"shard_{rank}.bin"
            ix = ds.client_idx[rank - 1]
            _write_shard(shard, ds.train_x[ix].reshape(len(ix), -1), ds.train_y[ix])
            procs.append(subprocess.Popen(
                [native_binary, "client", "--rank", str(rank),
                 "--base-port", str(base_port), "--data", str(shard)],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            ))
        for rank in (1, 2):
            assert _wait_listening(base_port + rank), f"device {rank} never bound"

        history = FedMLRunner(cfg).run()
        assert len(history) == 2
        assert history[-1]["test_acc"] > 0.3, history

        # the device-facing model artifact was written and decodes
        tree = wire.decode_pytree(artifact.read_bytes())
        leaves = [np.asarray(v) for v in _flatten(tree)]
        assert any(l.ndim == 2 for l in leaves)
        for p in procs:
            assert p.wait(timeout=20) == 0, p.stderr.read()[-2000:]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()


def _flatten(tree):
    if isinstance(tree, dict):
        for v in tree.values():
            yield from _flatten(v)
    elif isinstance(tree, (list, tuple)):
        for v in tree:
            yield from _flatten(v)
    else:
        yield tree
