"""Cross-device platform test (VERDICT row 20, reference
cross_device/server_mnn): the runner's cross_device dispatch drives a fleet
of NATIVE C++ clients over TCP and dumps the per-round model artifact."""

import os
import struct
import subprocess
import sys

import numpy as np
import pytest

from .conftest import tiny_config
from .test_native_client import _free_port_block, _wait_listening, _write_shard, native_binary  # noqa: F401


def test_cross_device_runner_with_native_fleet(native_binary, tmp_path, eight_devices):
    import fedml_tpu
    from fedml_tpu.comm import wire
    from fedml_tpu.runner import FedMLRunner

    # ephemeral block: a fixed port is one orphaned listener away from flaky
    base_port = _free_port_block(3)
    artifact = tmp_path / "global_model.wire"
    cfg = tiny_config(
        training_type="cross_device", backend="TCP",
        client_num_in_total=2, client_num_per_round=2, comm_round=2,
        batch_size=16, synthetic_train_size=320, synthetic_test_size=160,
        frequency_of_the_test=1,
        # global_model_file_path is a typed Config field (YAML model_args
        # lands there); only tcp_base_port is an extra knob
        global_model_file_path=str(artifact),
        extra={"tcp_base_port": base_port},
    )
    fedml_tpu.init(cfg)
    from fedml_tpu.data import loader

    ds = loader.load(cfg)

    procs = []
    try:
        for rank in (1, 2):
            shard = tmp_path / f"shard_{rank}.bin"
            ix = ds.client_idx[rank - 1]
            _write_shard(shard, ds.train_x[ix].reshape(len(ix), -1), ds.train_y[ix])
            procs.append(subprocess.Popen(
                [native_binary, "client", "--rank", str(rank),
                 "--base-port", str(base_port), "--data", str(shard)],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            ))
        for rank in (1, 2):
            assert _wait_listening(base_port + rank), f"device {rank} never bound"

        history = FedMLRunner(cfg).run()
        assert len(history) == 2
        assert history[-1]["test_acc"] > 0.3, history

        # the device-facing model artifact was written and decodes
        tree = wire.decode_pytree(artifact.read_bytes())
        leaves = [np.asarray(v) for v in _flatten(tree)]
        assert any(l.ndim == 2 for l in leaves)
        for p in procs:
            assert p.wait(timeout=20) == 0, p.stderr.read()[-2000:]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()


def _flatten(tree):
    if isinstance(tree, dict):
        for v in tree.values():
            yield from _flatten(v)
    elif isinstance(tree, (list, tuple)):
        for v in tree:
            yield from _flatten(v)
    else:
        yield tree


def test_device_registry_missed_selection_liveness():
    """Exclusion counts consecutive MISSED SELECTIONS only: a healthy device
    the sampler never picks stays live forever; a device that ignores its
    own selections is excluded; any participation signal clears the count."""
    from fedml_tpu.cross_device import DeviceRegistry

    reg = DeviceRegistry(max_missed=2)
    reg.register(1, "android")
    reg.register(2, "linux")
    reg.register(3, "android")
    assert set(reg.live_ids()) == {1, 2, 3}
    assert reg.status()[1]["os"] == "android"
    # device 3 never selected: stays live no matter how many rounds pass
    for _ in range(10):
        reg.note_missed_selection(2)
    assert reg.live_ids() == [1, 3]
    # rejoin: a probe answer clears the strikes
    reg.register(2)
    assert set(reg.live_ids()) == {1, 2, 3}
    # under the threshold: still live
    reg.note_missed_selection(1)
    reg.note_missed_selection(1)
    assert 1 in reg.live_ids()
    reg.note_missed_selection(1)
    assert 1 not in reg.live_ids()
    # unknown device participation auto-registers
    reg.note_participation(7)
    assert 7 in reg.live_ids()


def test_cross_device_server_tracks_and_selects_live_devices(eight_devices):
    """The cross-device server registers devices from status messages and
    schedules rounds over LIVE devices only."""
    import fedml_tpu
    from fedml_tpu.comm.inproc import InProcRouter
    from fedml_tpu.cross_silo import build_client
    from fedml_tpu.cross_device import build_cross_device_server
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub

    from .conftest import tiny_config

    cfg = tiny_config(
        training_type="cross_device", client_num_in_total=2,
        client_num_per_round=2, comm_round=1, run_id="cd-reg",
        frequency_of_the_test=1,
    )
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)
    InProcRouter.reset("cd-reg")
    clients = [build_client(cfg, ds, model, rank=r, backend="INPROC") for r in (1, 2)]
    for c in clients:
        c.run_in_thread()
    server = build_cross_device_server(cfg, ds, model, backend="INPROC")
    try:
        history = server.run_until_done(timeout=120.0)
    finally:
        for c in clients:
            c.finish()
    assert history and history[-1]["round"] == 0
    st = server.registry.status(server.round_idx)
    assert set(st) == {1, 2}
    assert all(d["live"] for d in st.values())
    assert all(d["os"] for d in st.values())


def test_cross_device_server_excludes_dead_and_probes_for_rejoin(eight_devices):
    """A device that missed too many rounds is excluded from the candidate
    set AND receives a status probe; its reply re-registers it."""
    import fedml_tpu
    from fedml_tpu.comm.inproc import InProcRouter
    from fedml_tpu.cross_silo import build_aggregator
    from fedml_tpu.cross_device import ServerMNN
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub

    from .conftest import tiny_config

    cfg = tiny_config(
        training_type="cross_device", client_num_in_total=3,
        client_num_per_round=2, comm_round=2, run_id="cd-dead",
        frequency_of_the_test=0, extra={"device_max_missed_rounds": 1},
    )
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)
    InProcRouter.reset("cd-dead")
    server = ServerMNN(cfg, build_aggregator(cfg, ds, model), backend="INPROC")
    probed = []
    orig_send = server.send_message

    def spy_send(msg):
        if msg.get_type() == 6:  # CHECK_CLIENT_STATUS
            probed.append(msg.get_receiver_id())
        return orig_send(msg)

    server.send_message = spy_send
    # device 3 ignored its last two selections (max_missed=1 -> excluded)
    server.registry.register(1, "android")
    server.registry.register(2, "linux")
    server.registry.register(3, "android")
    server.registry.note_missed_selection(3)
    server.registry.note_missed_selection(3)
    cand = server._candidate_ids()
    assert cand == [1, 2]          # dead device excluded from scheduling
    import time as _t
    for _ in range(50):            # probes fire on a daemon thread
        if probed:
            break
        _t.sleep(0.05)
    assert probed == [3]           # ...but probed for rejoin
    # probe answer clears the strikes: live again next round
    server.registry.register(3)
    probed.clear()
    assert server._candidate_ids() == [1, 2, 3]
    # selected-but-silent devices earn a strike at the next candidate pass
    server.selected = [1, 2]
    server._uploaded_this_round = {1}
    server._candidate_ids()
    assert server.registry.devices[2]["missed"] == 1
    assert server.registry.devices[1]["missed"] == 0


def test_cross_device_health_aware_candidate_narrowing(eight_devices):
    """Behind extra.health_aware_selection the LIVE candidate pool is further
    narrowed by health-ledger scores: degraded devices (deadline breaches)
    are admitted only when the healthy pool cannot fill the round; without
    the flag the candidate set is liveness-only (reference-exact)."""
    import fedml_tpu
    from fedml_tpu.comm.inproc import InProcRouter
    from fedml_tpu.cross_silo import build_aggregator
    from fedml_tpu.cross_device import ServerMNN
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub

    from .conftest import tiny_config

    def make_server(extra):
        cfg = tiny_config(
            training_type="cross_device", client_num_in_total=3,
            client_num_per_round=2, comm_round=2, run_id="cd-health",
            frequency_of_the_test=0, extra=extra,
        )
        fedml_tpu.init(cfg)
        ds = loader.load(cfg)
        model = model_hub.create(cfg, ds.class_num)
        InProcRouter.reset("cd-health")
        server = ServerMNN(cfg, build_aggregator(cfg, ds, model), backend="INPROC")
        for d in (1, 2, 3):
            server.registry.register(d, "android")
        # device 2 repeatedly blows the straggler deadline; the others are
        # proven healthy by completed round trips
        for _ in range(6):
            server.health.record_deadline_breach(2)
        for d in (1, 3):
            server.health.observe_rtt(d, 0.05)
        return server

    flagged = make_server({"health_aware_selection": True})
    assert flagged.health_aware
    assert flagged._candidate_ids() == [1, 3]  # healthy pool fills the round
    # a recovered device re-enters: successful round trips decay the breaches
    for _ in range(40):
        flagged.health.observe_rtt(2, 0.05)
    assert 2 in flagged._candidate_ids()

    # degraded devices still fill the round when health narrowing would
    # starve it (healthy pool smaller than per_round)
    for _ in range(6):
        flagged.health.record_deadline_breach(2)
        flagged.health.record_deadline_breach(3)
    cand = flagged._candidate_ids()
    assert len(cand) == flagged.per_round and 1 in cand

    # without the flag: liveness-only, all live devices stay candidates
    plain = make_server({})
    assert not plain.health_aware
    assert plain._candidate_ids() == [1, 2, 3]
