"""``fedml-tpu lint --fix`` (ISSUE 7 satellite, ``analysis/fix.py``).

The fixer mechanically rewrites legacy ``extra.get(...)`` reads to
``cfg_extra(cfg, name, default)`` — proven here to (1) rewrite every
recoverable idiom including nested defaults, (2) be idempotent, (3) preserve
runtime semantics exactly (the old default expression rides along), (4) leave
suppressed and non-mechanical sites alone with a manual-migration note, and
(5) silence GL001's legacy findings on the fixed sources.
"""

import subprocess
import sys
import textwrap
from pathlib import Path

from fedml_tpu.analysis.engine import run_lint
from fedml_tpu.analysis.fix import fix_source, fix_tree

REPO_ROOT = Path(__file__).resolve().parent.parent

FLAGS_FIXTURE = """
    class FlagSpec:
        def __init__(self, name, type, default, doc):
            pass

    FLAGS = {
        "fused_blocks": FlagSpec("fused_blocks", "bool", False, "doc"),
        "mlp_hidden": FlagSpec("mlp_hidden", "int", 128, "doc"),
        "silo_dp": FlagSpec("silo_dp", "bool", True, "doc"),
        "comm_topk_ratio": FlagSpec("comm_topk_ratio", "float", None, "doc"),
        "comm_compress_min_size": FlagSpec("comm_compress_min_size", "float", 0.01, "doc"),
    }
"""

LEGACY_MOD = '''
    """Fixture with every rewriteable legacy idiom."""
    import os


    def f(cfg):
        a = cfg.extra.get("fused_blocks")
        b = (getattr(cfg, "extra", {}) or {}).get("mlp_hidden", 64)
        extra = cfg.extra
        c = extra.get("silo_dp", True)
        nested = cfg.extra.get("comm_topk_ratio",
                               cfg.extra.get("comm_compress_min_size", 0.01))
        return a, b, c, nested
'''


def test_fix_rewrites_all_idioms_and_is_idempotent():
    src = textwrap.dedent(LEGACY_MOD)
    fixed, n, skipped = fix_source(src, "mod.py")
    assert n == 5  # 3 direct + the nested pair (outer, then inner on pass 2)
    assert skipped == []
    assert "from fedml_tpu.core.flags import cfg_extra" in fixed
    assert ".get(" not in fixed
    assert "cfg_extra(cfg, 'fused_blocks', None)" in fixed
    assert "cfg_extra(cfg, 'mlp_hidden', 64)" in fixed
    assert "cfg_extra(cfg, 'silo_dp', True)" in fixed
    assert "cfg_extra(cfg, 'comm_topk_ratio', cfg_extra(cfg, 'comm_compress_min_size', 0.01))" in fixed
    again, n2, _ = fix_source(fixed, "mod.py")
    assert n2 == 0 and again == fixed  # idempotent
    compile(fixed, "mod.py", "exec")  # still valid python


def test_fix_preserves_runtime_semantics():
    """The rewrite keeps ``.get``'s default (an unset flag stays ``None``,
    never swapped for the registry default)."""
    from fedml_tpu.arguments import Config

    src = textwrap.dedent(LEGACY_MOD)
    fixed, _, _ = fix_source(src, "mod.py")
    orig_ns, fixed_ns = {}, {}
    exec(compile(src, "orig.py", "exec"), orig_ns)
    exec(compile(fixed, "fixed.py", "exec"), fixed_ns)
    for extra in ({}, {"mlp_hidden": 256, "silo_dp": False},
                  {"fused_blocks": True, "comm_compress_min_size": 0.5}):
        cfg = Config(dataset="synthetic", model="lr", extra=dict(extra))
        assert fixed_ns["f"](cfg) == orig_ns["f"](cfg), extra


def test_fix_skips_manual_sites_and_suppressions(tmp_path):
    (tmp_path / "mod.py").write_text(textwrap.dedent('''
        def f(cfg, name):
            cfg.extra.setdefault(name, 3)  # non-literal name: manual
            cfg.extra["seg_base"]  # statement-position subscript: no value use
            c = name in cfg.extra  # non-literal membership: manual
            d = cfg.extra.get(name)
            e = cfg.extra[name]
            return c, d, e


        def g(cfg):  # graftlint: disable=GL001(deliberate raw read)
            return cfg.extra.get("fused_blocks")
    '''))
    before = (tmp_path / "mod.py").read_text()
    res = fix_tree(tmp_path)
    assert res.rewrites == 0
    assert (tmp_path / "mod.py").read_text() == before  # untouched
    notes = "\n".join(res.skipped)
    assert "setdefault" in notes and "statement-position extra[...]" in notes
    assert "membership test with a non-literal name" in notes
    assert notes.count("literal flag name") == 2  # .get(name) + extra[name]
    assert "fused_blocks" not in notes  # suppressed site: no nag either


def test_fix_rewrites_value_position_subscript(tmp_path):
    """ISSUE 12 satellite: value-position ``extra["k"]`` reads become
    ``cfg_extra(cfg, 'k', None)``.  Statement-position reads stay report-
    only; single-target stores now rewrite to ``set_cfg_extra`` (ISSUE 20
    satellite) with only the helpers actually used imported."""
    src = textwrap.dedent('''
        def f(cfg):
            a = cfg.extra["mlp_hidden"]
            extra = cfg.extra
            b = extra["silo_dp"]
            if cfg.extra["fused_blocks"]:
                a += 1
            cfg.extra["comm_topk_ratio"]  # statement position: report-only
            cfg.extra["mlp_hidden"] = 3   # write target: blessed-write rewrite
            return a, b
    ''')
    fixed, n, skipped = fix_source(src, "mod.py")
    assert n == 4, fixed
    assert "cfg_extra(cfg, 'mlp_hidden', None)" in fixed
    assert "cfg_extra(cfg, 'silo_dp', None)" in fixed
    assert "cfg_extra(cfg, 'fused_blocks', None)" in fixed
    assert 'cfg.extra["comm_topk_ratio"]' in fixed  # statement form survives
    assert "set_cfg_extra(cfg, 'mlp_hidden', 3)" in fixed  # store: rewritten
    assert "from fedml_tpu.core.flags import cfg_extra, set_cfg_extra" in fixed
    assert any("statement-position extra[...]" in s for s in skipped)
    compile(fixed, "mod.py", "exec")
    again, n2, _ = fix_source(fixed, "mod.py")
    assert n2 == 0 and again == fixed  # idempotent


def test_fix_subscript_semantics():
    """Set keys: identical values.  Missing key: the documented trade —
    the subscript's KeyError becomes cfg_extra's None default."""
    import pytest

    from fedml_tpu.arguments import Config

    src = "def f(cfg):\n    return cfg.extra['mlp_hidden']\n"
    fixed, n, _ = fix_source(src, "mod.py")
    assert n == 1
    orig_ns, fixed_ns = {}, {}
    exec(compile(src, "o.py", "exec"), orig_ns)
    exec(compile(fixed, "f.py", "exec"), fixed_ns)
    cfg = Config(dataset="synthetic", model="lr", extra={"mlp_hidden": 256})
    assert orig_ns["f"](cfg) == fixed_ns["f"](cfg) == 256
    empty = Config(dataset="synthetic", model="lr", extra={})
    with pytest.raises(KeyError):
        orig_ns["f"](empty)
    assert fixed_ns["f"](empty) is None


def test_fix_rewrites_value_position_setdefault(tmp_path):
    """The ROADMAP carried item: ``x = extra.setdefault(k, v)`` reads the
    flag with default ``v`` — rewritten to the registry-backed read.  The
    statement form becomes an explicit seed assignment (ISSUE 19
    satellite) — see the statement-position tests below."""
    src = textwrap.dedent('''
        def f(cfg):
            a = cfg.extra.setdefault("mlp_hidden", 64)
            extra = cfg.extra
            b = extra.setdefault("silo_dp")
            if extra.setdefault("fused_blocks", False):
                a += 1
            cfg.extra.setdefault("comm_topk_ratio", 0.1)  # statement form
            return a, b
    ''')
    fixed, n, skipped = fix_source(src, "mod.py")
    assert n == 4, fixed
    assert "cfg_extra(cfg, 'mlp_hidden', 64)" in fixed
    assert "cfg_extra(cfg, 'silo_dp', None)" in fixed
    assert "cfg_extra(cfg, 'fused_blocks', False)" in fixed
    # the statement-position seed becomes an explicit seed through the
    # registry-checked write (ISSUE 20: set_cfg_extra replaces the raw store)
    assert ("set_cfg_extra(cfg, 'comm_topk_ratio', "
            "cfg_extra(cfg, 'comm_topk_ratio', 0.1))") in fixed
    assert skipped == []
    compile(fixed, "mod.py", "exec")
    again, n2, _ = fix_source(fixed, "mod.py")
    assert n2 == 0 and again == fixed  # idempotent


def test_fix_rewrites_statement_position_setdefault():
    """ISSUE 19 satellite (write half upgraded by ISSUE 20): a statement-
    position ``extra.setdefault(k, v)`` (pure dict seeding for raw
    downstream readers) is rewritten to
    ``set_cfg_extra(cfg, 'k', cfg_extra(cfg, 'k', v))`` — seeded dict
    preserved, flag name declared and GL001-checked on both halves — and
    the rewrite is idempotent."""
    src = textwrap.dedent('''
        def seed(cfg):
            cfg.extra.setdefault("mlp_hidden", 64)
            extra = cfg.extra
            extra.setdefault("silo_dp")
            return cfg
    ''')
    fixed, n, skipped = fix_source(src, "mod.py")
    assert n == 2, fixed
    assert skipped == []
    assert ("set_cfg_extra(cfg, 'mlp_hidden', "
            "cfg_extra(cfg, 'mlp_hidden', 64))") in fixed
    # the no-default form seeds the explicit None that setdefault() would have
    assert ("set_cfg_extra(cfg, 'silo_dp', "
            "cfg_extra(cfg, 'silo_dp', None))") in fixed
    assert "from fedml_tpu.core.flags import cfg_extra, set_cfg_extra" in fixed
    compile(fixed, "mod.py", "exec")
    again, n2, again_skipped = fix_source(fixed, "mod.py")
    assert n2 == 0 and again == fixed and again_skipped == []  # idempotent


def test_fix_statement_setdefault_exec_semantics():
    """Exec'd before/after: a PRESENT key keeps its value and a missing key
    lands the same seed, so every raw downstream ``extra[...]`` reader sees
    an identical dict."""
    from fedml_tpu.arguments import Config

    src = textwrap.dedent('''
        def seed(cfg):
            cfg.extra.setdefault("mlp_hidden", 64)
            cfg.extra.setdefault("silo_dp", True)
            return cfg.extra
    ''')
    fixed, n, _ = fix_source(src, "mod.py")
    assert n == 2
    orig_ns, fixed_ns = {}, {}
    exec(compile(src, "o.py", "exec"), orig_ns)
    exec(compile(fixed, "f.py", "exec"), fixed_ns)
    for extra in ({}, {"mlp_hidden": 256}, {"mlp_hidden": 0, "silo_dp": False}):
        got_orig = dict(orig_ns["seed"](
            Config(dataset="synthetic", model="lr", extra=dict(extra))))
        got_fixed = dict(fixed_ns["seed"](
            Config(dataset="synthetic", model="lr", extra=dict(extra))))
        assert got_orig == got_fixed, (extra, got_orig, got_fixed)


def test_fix_setdefault_semantics_match_on_value_use():
    """For the value use itself, setdefault(k, v) and cfg_extra(cfg, k, v)
    agree whether the flag is set or unset."""
    from fedml_tpu.arguments import Config

    src = "def f(cfg):\n    return cfg.extra.setdefault('mlp_hidden', 64)\n"
    fixed, n, _ = fix_source(src, "mod.py")
    assert n == 1
    orig_ns, fixed_ns = {}, {}
    exec(compile(src, "o.py", "exec"), orig_ns)
    exec(compile(fixed, "f.py", "exec"), fixed_ns)
    for extra in ({}, {"mlp_hidden": 256}):
        assert (orig_ns["f"](Config(dataset="synthetic", model="lr", extra=dict(extra)))
                == fixed_ns["f"](Config(dataset="synthetic", model="lr", extra=dict(extra))))


def test_fix_rewrites_membership_tests():
    """ISSUE 20 satellite: value-position ``"k" in extra`` / ``not in``
    membership tests become ``cfg_extra_present(cfg, 'k')`` (the ``not in``
    form paren-wrapped), and only the helper actually used is imported."""
    src = textwrap.dedent('''
        def f(cfg):
            a = "mlp_hidden" in cfg.extra
            extra = cfg.extra
            b = "silo_dp" not in extra
            if "fused_blocks" in (getattr(cfg, "extra", {}) or {}):
                a = not a
            return a, b
    ''')
    fixed, n, skipped = fix_source(src, "mod.py")
    assert n == 3, fixed
    assert skipped == []
    assert "from fedml_tpu.core.flags import cfg_extra_present" in fixed
    assert "a = cfg_extra_present(cfg, 'mlp_hidden')" in fixed
    assert "b = (not cfg_extra_present(cfg, 'silo_dp'))" in fixed
    assert "if cfg_extra_present(cfg, 'fused_blocks'):" in fixed
    compile(fixed, "mod.py", "exec")
    again, n2, _ = fix_source(fixed, "mod.py")
    assert n2 == 0 and again == fixed  # idempotent


def test_fix_membership_exec_semantics():
    """Exec'd before/after: membership agrees set/unset, including the
    present-but-None key the probe exists to keep distinct from absent."""
    from fedml_tpu.arguments import Config

    src = textwrap.dedent('''
        def f(cfg):
            return "mlp_hidden" in cfg.extra, "silo_dp" not in cfg.extra
    ''')
    fixed, n, _ = fix_source(src, "mod.py")
    assert n == 2
    orig_ns, fixed_ns = {}, {}
    exec(compile(src, "o.py", "exec"), orig_ns)
    exec(compile(fixed, "f.py", "exec"), fixed_ns)
    for extra in ({}, {"mlp_hidden": 256}, {"mlp_hidden": None},
                  {"mlp_hidden": 0, "silo_dp": False}):
        cfg = Config(dataset="synthetic", model="lr", extra=dict(extra))
        assert fixed_ns["f"](cfg) == orig_ns["f"](cfg), extra


def test_fix_store_exec_semantics():
    """Exec'd before/after: the ``set_cfg_extra`` rewrite lands the same
    dict contents a raw subscript store would, and is idempotent."""
    from fedml_tpu.arguments import Config

    src = textwrap.dedent('''
        def seed(cfg, v):
            cfg.extra["mlp_hidden"] = v
            extra = cfg.extra
            extra["silo_dp"] = v * 2
            return cfg.extra
    ''')
    fixed, n, _ = fix_source(src, "mod.py")
    assert n == 2, fixed
    assert "set_cfg_extra(cfg, 'mlp_hidden', v)" in fixed
    assert "set_cfg_extra(cfg, 'silo_dp', v * 2)" in fixed
    orig_ns, fixed_ns = {}, {}
    exec(compile(src, "o.py", "exec"), orig_ns)
    exec(compile(fixed, "f.py", "exec"), fixed_ns)
    for v in (3, 0):
        got_orig = dict(orig_ns["seed"](
            Config(dataset="synthetic", model="lr", extra={}), v))
        got_fixed = dict(fixed_ns["seed"](
            Config(dataset="synthetic", model="lr", extra={}), v))
        assert got_orig == got_fixed == {"mlp_hidden": v, "silo_dp": v * 2}
    again, n2, _ = fix_source(fixed, "mod.py")
    assert n2 == 0 and again == fixed  # idempotent


def test_fixed_package_is_gl001_legacy_clean(tmp_path):
    (tmp_path / "core").mkdir()
    (tmp_path / "core" / "flags.py").write_text(textwrap.dedent(FLAGS_FIXTURE))
    (tmp_path / "mod.py").write_text(textwrap.dedent(LEGACY_MOD))
    assert any(f.symbol.startswith("legacy:") for f in run_lint(tmp_path).findings)
    res = fix_tree(tmp_path)
    assert res.rewrites == 5 and res.files_changed == ["mod.py"]
    after = run_lint(tmp_path)
    assert not any(f.symbol.startswith("legacy:") for f in after.findings), \
        [f.render() for f in after.findings]


def test_cli_lint_fix_end_to_end(tmp_path):
    (tmp_path / "core").mkdir()
    (tmp_path / "core" / "flags.py").write_text(textwrap.dedent(FLAGS_FIXTURE))
    (tmp_path / "mod.py").write_text(textwrap.dedent(LEGACY_MOD))
    cmd = [sys.executable, "-m", "fedml_tpu.cli", "lint", "--fix", str(tmp_path)]
    out = subprocess.run(cmd, capture_output=True, text=True, timeout=120,
                         cwd=str(REPO_ROOT))
    assert out.returncode == 0, out.stdout + out.stderr
    assert "fixed 5 legacy extra read(s) in 1 file(s)" in out.stdout
    assert "cfg_extra(cfg, 'silo_dp', True)" in (tmp_path / "mod.py").read_text()
    # second invocation: nothing left to fix, lint stays clean
    out2 = subprocess.run(cmd, capture_output=True, text=True, timeout=120,
                          cwd=str(REPO_ROOT))
    assert out2.returncode == 0, out2.stdout + out2.stderr
    assert "fixed 0 legacy extra read(s)" in out2.stdout
