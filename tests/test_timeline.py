"""Performance timeline, program-time attribution, dash, and the bench
regression sentinel (ISSUE 18).

Covers the tentpole's correctness core with hand-computed fixtures
(windowed rates, histogram-delta percentiles, rounds-to-target), the
memory bound under sustained sampling (tracemalloc), segment-file
durability (torn/foreign rejection), the flag-off bitwise A/B pin, the
dash renderers, the regression comparator's direction heuristic, and the
edge flight-recorder satellite (a SIGKILLed edge leaves a stitchable
bundle behind)."""

import json
import os
import tracemalloc

import numpy as np
import pytest

from fedml_tpu.obs import dash as obsdash
from fedml_tpu.obs import regress as obsregress
from fedml_tpu.obs import timeline as obstl
from fedml_tpu.obs.registry import MetricsRegistry


def _private_registry():
    reg = MetricsRegistry()
    c = reg.counter("fedml_test_uploads_total", "t", labels=("tier",))
    g = reg.gauge("fedml_test_depth", "t")
    h = reg.histogram("fedml_test_step_seconds", "t", buckets=(0.1, 0.5, 2.0))
    return reg, c, g, h


# ---------------------------------------------------------------------------
# query correctness vs hand-computed fixtures


def _fixture_samples():
    """Three samples of a cumulative counter + histogram, hand-checkable."""
    return [
        {"ts": 100.0, "scalars": {"fedml_test_uploads_total{tier=edge}": 10.0},
         "hists": {"fedml_test_step_seconds":
                   {"counts": [1, 0, 0, 0], "sum": 0.05, "count": 1}}},
        {"ts": 110.0, "scalars": {"fedml_test_uploads_total{tier=edge}": 30.0},
         "hists": {"fedml_test_step_seconds":
                   {"counts": [1, 4, 0, 0], "sum": 1.25, "count": 5}}},
        {"ts": 120.0, "scalars": {"fedml_test_uploads_total{tier=edge}": 70.0},
         "hists": {"fedml_test_step_seconds":
                   {"counts": [3, 8, 4, 1], "sum": 9.0, "count": 16}}},
    ]


def test_windowed_rate_hand_computed():
    s = _fixture_samples()
    # full span: (70-10)/(120-100) = 3.0/s
    assert obstl.windowed_rate(s, "fedml_test_uploads_total{tier=edge}") == 3.0
    # 10s window anchored at the last sample: (70-30)/(120-110) = 4.0/s
    assert obstl.windowed_rate(
        s, "fedml_test_uploads_total{tier=edge}", window_s=10.0) == 4.0
    # explicit now excluding the last sample: (30-10)/10 = 2.0/s
    assert obstl.windowed_rate(
        s, "fedml_test_uploads_total{tier=edge}",
        window_s=15.0, now=112.0) == 2.0
    # no data / single sample -> None, never a fabricated zero
    assert obstl.windowed_rate(s, "fedml_nope") is None
    assert obstl.windowed_rate(s[:1],
                               "fedml_test_uploads_total{tier=edge}") is None


def test_range_scan_bounds():
    s = _fixture_samples()
    assert [x["ts"] for x in obstl.range_scan(s, 105.0, None)] == [110.0, 120.0]
    assert [x["ts"] for x in obstl.range_scan(s, None, 105.0)] == [100.0]
    assert obstl.range_scan(s, 130.0, 140.0) == []


def test_hist_pnn_hand_computed():
    s = _fixture_samples()
    buckets = [0.1, 0.5, 2.0, float("inf")]
    # window = full span: delta counts [2, 8, 4, 1], total 15
    # p50 -> target 7.5: bucket0 holds 2, bucket1 reaches 10 >= 7.5
    #   frac = (7.5-2)/8 = 0.6875 -> 0.1 + 0.6875*0.4 = 0.375
    p50 = obstl.hist_pnn(s, "fedml_test_step_seconds", 0.5, buckets)
    assert p50 == pytest.approx(0.375)
    # p90 -> target 13.5: cumulative 2, 10, then bucket2 reaches 14
    #   frac = (13.5-10)/4 = 0.875 -> 0.5 + 0.875*1.5 = 1.8125
    p90 = obstl.hist_pnn(s, "fedml_test_step_seconds", 0.9, buckets)
    assert p90 == pytest.approx(1.8125)
    # p100 lands in the +Inf bucket -> last finite bound
    p100 = obstl.hist_pnn(s, "fedml_test_step_seconds", 1.0, buckets)
    assert p100 == 2.0
    # window covering only the last pair: delta [2, 4, 4, 1]
    p50w = obstl.hist_pnn(s, "fedml_test_step_seconds", 0.5, buckets,
                          window_s=10.0)
    # target 5.5: bucket0 2, bucket1 reaches 6 -> frac (5.5-2)/4 = 0.875
    assert p50w == pytest.approx(0.1 + 0.875 * 0.4)
    # zero observations in the window -> None
    assert obstl.hist_pnn(s[:1], "fedml_test_step_seconds", 0.5, buckets) is None


def test_rounds_to_target_first_crossing():
    rounds = [{"round_idx": i, "test_acc": a}
              for i, a in enumerate([0.1, 0.45, 0.61, 0.55, 0.72, 0.93])]
    out = obstl.rounds_to_target(rounds, targets=(0.5, 0.7, 0.9, 0.99))
    # FIRST crossing, not latest: the 0.55 dip after round 2 must not move it
    assert out == {"0.5": 2.0, "0.7": 4.0, "0.9": 5.0, "0.99": None}
    # async series keyed by server_version works the same
    vrounds = [{"server_version": i, "test_acc": a}
               for i, a in enumerate([0.2, 0.8])]
    assert obstl.rounds_to_target(vrounds, targets=(0.7,)) == {"0.7": 1.0}


# ---------------------------------------------------------------------------
# recorder: ring, gauges, segments, memory bound


def test_recorder_live_queries_and_convergence_gauge(tmp_path):
    reg, c, g, h = _private_registry()
    rec = obstl.TimelineRecorder(str(tmp_path), name="t", capacity=32,
                                 registry=reg, targets=(0.5, 0.9))
    for i in range(6):
        c.inc(5, tier="edge")
        h.observe(0.3)
        g.set(float(i))
        rec.sample_now(now=1000.0 + i)
    assert rec.latest("fedml_test_uploads_total{tier=edge}") == 30.0
    assert rec.rate("fedml_test_uploads_total{tier=edge}") == pytest.approx(5.0)
    assert rec.pnn("fedml_test_step_seconds", 0.5) is not None

    for i, acc in enumerate([0.2, 0.6, 0.95]):
        rec.note_round(round_idx=i, test_acc=acc, wall=1000.0 + i)
    assert rec.crossed_targets() == {"0.5": 1.0, "0.9": 2.0}
    # the live gauge carries the same first crossings
    assert obstl.ROUNDS_TO_TARGET.value(target="0.5") == 1.0
    assert obstl.ROUNDS_TO_TARGET.value(target="0.9") == 2.0
    assert obstl.CONV_TEST_ACC.value() == pytest.approx(0.95)
    rec.close()


def test_segments_roundtrip_and_load(tmp_path):
    reg, c, g, h = _private_registry()
    rec = obstl.TimelineRecorder(str(tmp_path), name="seg", capacity=8,
                                 registry=reg)
    for i in range(10):  # flush_every = 4 -> at least two mid-run segments
        c.inc(tier="edge")
        rec.sample_now(now=2000.0 + i)
    rec.note_round(round_idx=0, test_acc=0.4, wall=2000.5)
    rec.close()
    segs = obstl.list_segments(str(tmp_path))
    assert len(segs) >= 2
    one = obstl.read_segment(segs[0])
    assert one["meta"]["format"] == "fedml-timeline-v1"
    assert one["meta"]["n_samples"] == len(one["samples"])
    loaded = obstl.load_timeline(str(tmp_path))
    # every sample survives the roundtrip, in timestamp order
    assert len(loaded["samples"]) == 11  # 10 + the close() final sample
    ts = [s["ts"] for s in loaded["samples"]]
    assert ts == sorted(ts)
    assert loaded["rounds"][0]["test_acc"] == 0.4
    assert "fedml_test_step_seconds" in loaded["buckets"]
    assert loaded["skipped"] == 0


def test_torn_and_foreign_segments_rejected(tmp_path):
    reg, c, g, h = _private_registry()
    rec = obstl.TimelineRecorder(str(tmp_path), name="torn", capacity=8,
                                 registry=reg)
    c.inc(tier="edge")
    rec.sample_now(now=3000.0)
    rec.close()
    good = obstl.list_segments(str(tmp_path))
    assert good
    # foreign magic
    (tmp_path / "foreign.tseg").write_bytes(b"NOTMINE\n{}\n{}")
    # torn: magic but truncated before the header newline
    (tmp_path / "torn.tseg").write_bytes(b"FMLTLN1\n" + b'{"trunc')
    # half-written body
    blob = (tmp_path / good[0].split(os.sep)[-1]).read_bytes()
    (tmp_path / "half.tseg").write_bytes(blob[: len(blob) - len(blob) // 3])
    with pytest.raises(ValueError):
        obstl.read_segment(str(tmp_path / "foreign.tseg"))
    with pytest.raises(ValueError):
        obstl.read_segment(str(tmp_path / "torn.tseg"))
    loaded = obstl.load_timeline(str(tmp_path))
    assert loaded["skipped"] == 3
    assert len(loaded["samples"]) == 2  # the good segment only


def test_memory_bounded_under_sustained_sampling(tmp_path):
    """The ring + pending buffers must hold memory flat: 4x more samples
    than capacity may not grow the recorder's footprint materially."""
    reg, c, g, h = _private_registry()
    rec = obstl.TimelineRecorder(str(tmp_path), name="mem", capacity=64,
                                 registry=reg)
    for i in range(128):  # warm: fill the ring + segment machinery
        c.inc(tier="edge")
        h.observe(0.2)
        rec.sample_now(now=float(i))
    tracemalloc.start()
    base, _ = tracemalloc.get_traced_memory()
    for i in range(256):
        c.inc(tier="edge")
        h.observe(0.2)
        rec.sample_now(now=200.0 + i)
    current, peak = tracemalloc.get_traced_memory()
    tracemalloc.stop()
    rec.close()
    # steady-state growth after warmup stays under 1 MiB — a leak of the
    # per-sample dicts (each ~1KiB x 256) would blow well past this
    assert current - base < 1 << 20, (base, current, peak)
    assert len(rec.samples()) <= 64


# ---------------------------------------------------------------------------
# dash


def _recorded_timeline(tmp_path):
    reg, c, g, h = _private_registry()
    hop = reg.counter("fedml_hier_hop_bytes_total", "t", labels=("hop",))
    rs = reg.histogram("fedml_crosssilo_round_seconds", "t", buckets=(1.0, 5.0))
    rec = obstl.TimelineRecorder(str(tmp_path), name="d", capacity=32,
                                 registry=reg)
    for i in range(5):
        hop.inc(1000, hop="client_edge")
        hop.inc(200, hop="edge_root")
        rs.observe(0.5)
        rec.sample_now(now=5000.0 + i)
    for i, acc in enumerate([0.3, 0.65, 0.92]):
        rec.note_round(round_idx=i, test_acc=acc, wall=5000.0 + i)
    # flush, not close: close() appends a wall-clock-stamped final sample,
    # which would dwarf this fixture's pinned-timestamp span
    rec.flush()
    return obstl.load_timeline(str(tmp_path))


def test_dash_text_and_html_render(tmp_path):
    loaded = _recorded_timeline(tmp_path)
    data = obsdash.dash_data(loaded)
    assert data["throughput"]["rounds_per_s"] == pytest.approx(1.0)
    assert data["comm_bytes"]["client_edge"] == pytest.approx(4000.0)
    assert data["comm_bytes"]["edge_root"] == pytest.approx(800.0)
    assert data["convergence"]["rounds_to_target"]["0.9"] == 2.0
    txt = obsdash.render_dash_text(loaded)
    assert "client_edge" in txt and "target 0.9" in txt
    html = obsdash.render_dash_html(loaded)
    assert html.startswith("<!doctype html>")
    assert "Convergence" in html and "polyline" in html
    assert "client_edge" in html


# ---------------------------------------------------------------------------
# regression sentinel


def _trajectory(vals_by_metric, n=4):
    out = []
    for i in range(n):
        out.append({"path": f"b{i}", "round": i,
                    "metrics": {m: v[i] for m, v in vals_by_metric.items()}})
    return out


def test_compare_direction_heuristic():
    traj = _trajectory({"detail.llm.mfu": [0.40, 0.41, 0.40, 0.41],
                        "detail.llm.step_time_s": [1.0, 1.02, 0.98, 1.0]})
    # mfu is higher-better: halving regresses, doubling improves
    r = obsregress.compare(traj, {"detail.llm.mfu": 0.20,
                                  "detail.llm.step_time_s": 1.0})
    assert not r["ok"]
    assert [x["metric"] for x in r["regressions"]] == ["detail.llm.mfu"]
    r = obsregress.compare(traj, {"detail.llm.mfu": 0.80,
                                  "detail.llm.step_time_s": 1.0})
    assert r["ok"] and r["improvements"]
    # step_time is lower-better: doubling regresses
    r = obsregress.compare(traj, {"detail.llm.mfu": 0.41,
                                  "detail.llm.step_time_s": 2.0})
    assert not r["ok"]
    assert [x["metric"] for x in r["regressions"]] == ["detail.llm.step_time_s"]


def test_compare_noise_tolerance_and_new_metrics():
    # high variance across the trajectory widens the slack (3 sigma)
    traj = _trajectory({"detail.x": [1.0, 2.0, 1.0, 2.0]})
    assert obsregress.compare(traj, {"detail.x": 0.9})["ok"]
    # brand-new metric never regresses, it is reported as new
    r = obsregress.compare(traj, {"detail.x": 1.5, "detail.fresh": 7.0})
    assert r["ok"] and r["new_metrics"] == ["detail.fresh"]
    # empty trajectory: nothing to compare against, trivially ok
    r = obsregress.compare([], {"detail.x": 1.0})
    assert r["ok"] and r["checked"] == 0


def test_compare_candidate_against_bench_files(tmp_path):
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    docs = sorted(f for f in os.listdir(repo)
                  if f.startswith("BENCH_") and f.endswith(".json"))
    if not docs:
        pytest.skip("no BENCH_*.json trajectory in repo root")
    with open(os.path.join(repo, docs[-1])) as f:
        doc = json.load(f)
    cand = tmp_path / "cand.json"
    cand.write_text(json.dumps(doc))
    res = obsregress.compare_candidate(str(cand), repo)
    assert res["ok"], res["regressions"]
    # injected regression on a metric that is STABLE across the trajectory
    # (the top-level "value" mixes units across bench modes, so its sigma
    # slack legitimately swallows perturbations)
    llm = doc["parsed"].get("detail", {}).get("llm")
    if not isinstance(llm, dict) or "mfu" not in llm:
        pytest.skip("trajectory carries no detail.llm.mfu")
    llm["mfu"] = float(llm["mfu"]) * 0.5
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps(doc))
    res = obsregress.compare_candidate(str(bad), repo)
    assert not res["ok"]
    with pytest.raises(ValueError):
        obsregress.compare_candidate(str(tmp_path / "missing.json"), repo)


# ---------------------------------------------------------------------------
# report: hierarchy section from hier_tree trail records


def test_report_hier_rows_differences_cumulative_records():
    from fedml_tpu.obs.report import hier_rows, render_report

    records = [
        {"kind": "metric", "metric": "hier_tree", "round_idx": 0,
         "hop_bytes": {"client_edge": 400, "edge_region": 0, "edge_root": 100},
         "folds": 4, "relays": 0, "deduped": 0, "partials_sent": 2,
         "depth": 2, "fanout": 2, "edges": 2},
        {"kind": "metric", "metric": "hier_tree", "round_idx": 1,
         "hop_bytes": {"client_edge": 900, "edge_region": 0, "edge_root": 220},
         "folds": 9, "relays": 1, "deduped": 1, "partials_sent": 4,
         "depth": 2, "fanout": 2, "edges": 2},
    ]
    rows = hier_rows(records)
    assert rows[0]["hop_bytes"]["client_edge"] == 400
    assert rows[0]["folds"] == 4
    # second row is the per-round DELTA of the cumulative counters
    assert rows[1]["hop_bytes"]["client_edge"] == 500
    assert rows[1]["hop_bytes"]["edge_root"] == 120
    assert rows[1]["folds"] == 5 and rows[1]["relays"] == 1
    assert rows[1]["partials_sent"] == 2
    # shape gauges pass through undifferenced
    assert rows[1]["depth"] == 2 and rows[1]["edges"] == 2
    text = render_report(records)
    assert "== hierarchy ==" in text
    assert "tree depth=2 fanout=2 edges=2" in text


# ---------------------------------------------------------------------------
# flag-off bitwise A/B pin + live cross-silo integration


def _cross_silo_run(run_id, extra):
    import jax

    import fedml_tpu
    from fedml_tpu.comm.inproc import InProcRouter
    from fedml_tpu.cross_silo import build_client, build_server
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub

    from .conftest import tiny_config

    cfg = tiny_config(training_type="cross_silo", run_id=run_id,
                      client_num_in_total=2, client_num_per_round=2,
                      comm_round=2, frequency_of_the_test=1)
    cfg.extra = dict(extra)
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)
    InProcRouter.reset(run_id)
    clients = [build_client(cfg, ds, model, rank=r, backend="INPROC")
               for r in (1, 2)]
    for c in clients:
        c.run_in_thread()
    server = build_server(cfg, ds, model, backend="INPROC")
    try:
        history = server.run_until_done(timeout=120.0)
    finally:
        for c in clients:
            c.finish()
    return history, jax.device_get(server.aggregator.global_vars)


def test_perf_timeline_off_is_bitwise_identical(eight_devices, tmp_path):
    """All six new flags unset -> byte-for-byte the seed path; with the
    timeline ON the training outcome must ALSO be bit-identical (pure
    observer), and the run leaves a queryable convergence series."""
    hist_off, vars_off = _cross_silo_run("tl_off", {})
    hist_on, vars_on = _cross_silo_run("tl_on", {
        "perf_timeline": True,
        "timeline_dir": str(tmp_path / "tl"),
        "timeline_interval_s": 0.05,
        "timeline_capacity": 64,
    })
    assert [h.get("round_idx") for h in hist_off] == \
        [h.get("round_idx") for h in hist_on]
    flat_off = jax_flatten(vars_off)
    flat_on = jax_flatten(vars_on)
    assert len(flat_off) == len(flat_on)
    for a, b in zip(flat_off, flat_on):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    loaded = obstl.load_timeline(str(tmp_path / "tl"))
    assert loaded["samples"], "timeline ON recorded nothing"
    assert loaded["rounds"], "convergence series empty"
    # the sync server tees round_idx + test_acc; accuracy present because
    # frequency_of_the_test=1
    accs = [r for r in loaded["rounds"] if r.get("test_acc") is not None]
    assert accs, loaded["rounds"]
    assert obstl.rounds_to_target(loaded["rounds"], targets=(0.0,))["0"] is not None


def jax_flatten(tree):
    import jax

    return jax.tree_util.tree_leaves(tree)


# ---------------------------------------------------------------------------
# edge flight-recorder satellite: a SIGKILLed edge leaves a stitchable bundle


def test_edge_kill_leaves_stitchable_flight_bundle(eight_devices, tmp_path):
    from fedml_tpu.cross_silo.async_soak import run_edge_kill_soak
    from fedml_tpu.obs.flight import list_bundles, read_bundle
    from fedml_tpu.obs.postmortem import stitch_bundles

    flight_dir = str(tmp_path / "flt")
    res = run_edge_kill_soak(
        n_clients=4, fanout=2, rounds=2, kill=(0, 0, 1), seed=0,
        timeout_s=120.0,
        extra_flags={"flight_recorder": True, "flight_dir": flight_dir})
    assert res["edge_kills"] == 1 and res["unaccounted"] == 0, res

    bundles = list_bundles(flight_dir)
    assert bundles, "edge kill left no flight bundle"
    edge_bundles = [read_bundle(p) for p in bundles]
    names = {b["meta"]["name"] for b in edge_bundles}
    assert any(n.startswith("edge_") for n in names), names
    killed = [b for b in edge_bundles if b["meta"]["reason"] == "hard_kill"
              and b["meta"]["name"].startswith("edge_")]
    assert killed, [b["meta"]["reason"] for b in edge_bundles]
    # the ring carries the pre-kill fold events with round attribution
    kinds = {e.get("kind") for b in killed for e in b.get("events", ())}
    assert "edge_fold" in kinds, kinds
    # and the whole set stitches into one time-ordered postmortem timeline
    stitched = stitch_bundles(flight_dir)
    assert stitched["timeline"]
    ts = [e["ts"] for e in stitched["timeline"]]
    assert ts == sorted(ts)
