"""M0 end-to-end: FedAvg on the mesh backend — the sp_fedavg parity slice.

Mirrors the reference smoke pattern (SURVEY.md §4): run the tiny recipe for a
few rounds and assert accuracy rises above the random floor; plus the
MESH == SP cross-backend numerics check the reference never had.
"""

import numpy as np
import pytest

from .conftest import tiny_config


def _run(cfg):
    import fedml_tpu

    return fedml_tpu.run_simulation(cfg)


def test_fedavg_mesh_learns(eight_devices):
    cfg = tiny_config(comm_round=8, learning_rate=0.3, client_num_per_round=8)
    history = _run(cfg)
    accs = [h["test_acc"] for h in history if "test_acc" in h]
    assert accs[-1] > 0.4, f"synthetic LR should beat 0.1 floor easily, got {accs}"
    assert history[-1]["train_loss"] < history[0]["train_loss"]


def test_mesh_equals_sp_backend(eight_devices):
    """Same seeds -> same params whether clients run vmapped-on-mesh or in a
    host loop.  This is the guarantee that sharding is semantics-free."""
    import jax
    import fedml_tpu
    from fedml_tpu.runner import FedMLRunner

    results = {}
    for backend in ("MESH", "sp"):
        cfg = tiny_config(comm_round=2, backend_sim=backend)
        fedml_tpu.init(cfg)
        runner = FedMLRunner(cfg)
        runner.run()
        results[backend] = jax.device_get(runner.runner.global_vars)
    flat_mesh = jax.tree_util.tree_leaves(results["MESH"])
    flat_sp = jax.tree_util.tree_leaves(results["sp"])
    for a, b in zip(flat_mesh, flat_sp):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5)


def test_mesh_equals_sp_on_undivisible_shapes(eight_devices):
    """The flagship-recipe shape (clients and clients/round NOT multiples of
    the mesh axis) must keep exact parity with the SP twin via zero-impact
    lane/stack padding — and must never hit the REPLICATING fallback (round-3
    verdict item 2)."""
    import warnings

    import jax
    import fedml_tpu
    from fedml_tpu.runner import FedMLRunner

    results = {}
    for backend in ("MESH", "sp"):
        # 13 clients / 5 per round: neither divides the 8-device clients axis
        cfg = tiny_config(comm_round=3, backend_sim=backend,
                          client_num_in_total=13, client_num_per_round=5,
                          partition_method="hetero", partition_alpha=0.5)
        fedml_tpu.init(cfg)
        runner = FedMLRunner(cfg)
        with warnings.catch_warnings():
            warnings.simplefilter("error", UserWarning)  # REPLICATING warns -> fail
            runner.run()
        results[backend] = jax.device_get(runner.runner.global_vars)
    for a, b in zip(jax.tree_util.tree_leaves(results["MESH"]),
                    jax.tree_util.tree_leaves(results["sp"])):
        np.testing.assert_allclose(a, b, rtol=2e-4, atol=2e-5)


def test_padded_client_stack_shards_evenly(eight_devices):
    """With 13 clients on an 8-device axis the stacks are padded to 16 rows
    and actually sharded (2 rows per device), not replicated."""
    import fedml_tpu
    from fedml_tpu.runner import FedMLRunner

    cfg = tiny_config(comm_round=1, client_num_in_total=13, client_num_per_round=5)
    fedml_tpu.init(cfg)
    runner = FedMLRunner(cfg)
    sim = runner.runner
    assert sim._n_real == 13 and sim._n_pad == 16
    x = sim._data[0]
    assert x.shape[0] == 16
    shard_rows = {s.data.shape[0] for s in x.addressable_shards}
    assert shard_rows == {2}, f"expected 2 rows/device, got {shard_rows}"
    # dummy rows carry zero weight so they can never contribute
    assert float(sim.counts[13:].sum()) == 0.0
    runner.run()  # and the padded round still runs


def test_client_sampling_matches_reference_semantics():
    from fedml_tpu.core import rng

    idx = rng.sample_clients_np(3, 10, 5)
    # bit-exact vs np.random.seed(3); np.random.choice(range(10), 5, replace=False)
    np.random.seed(3)
    expected = np.random.choice(range(10), 5, replace=False)
    np.testing.assert_array_equal(idx, expected)
    # jit-side sampler: right shape, no duplicates, deterministic
    import jax

    k = rng.root_key(0)
    s1 = np.asarray(rng.sample_clients(k, 4, 10, 5))
    s2 = np.asarray(rng.sample_clients(k, 4, 10, 5))
    np.testing.assert_array_equal(s1, s2)
    assert len(set(s1.tolist())) == 5
    assert ((s1 >= 0) & (s1 < 10)).all()


def test_dirichlet_partition_properties():
    from fedml_tpu.data import partition as part

    labels = np.random.RandomState(0).randint(0, 10, size=5000)
    idx_map = part.partition_hetero_dirichlet(labels, 8, alpha=0.5, seed=1)
    all_idx = np.concatenate(idx_map)
    assert len(all_idx) == 5000
    assert len(np.unique(all_idx)) == 5000  # exact partition, no dup/loss
    assert min(len(ix) for ix in idx_map) >= part.MIN_PARTITION_SIZE
    # determinism
    idx_map2 = part.partition_hetero_dirichlet(labels, 8, alpha=0.5, seed=1)
    for a, b in zip(idx_map, idx_map2):
        np.testing.assert_array_equal(a, b)


def test_resnet20_forward_shape(eight_devices):
    import jax
    import jax.numpy as jnp
    from fedml_tpu.models import resnet

    model = resnet.resnet20(10)
    x = jnp.zeros((2, 32, 32, 3))
    variables = model.init(jax.random.PRNGKey(0), x, train=True)
    out = model.apply(variables, x, train=False)
    assert out.shape == (2, 10)
    n_params = sum(p.size for p in jax.tree_util.tree_leaves(variables["params"]))
    # reference resnet20 has ~272k params; ours should match closely
    assert 250_000 < n_params < 300_000, n_params


def test_run_rounds_chunk_matches_per_round(eight_devices):
    """run_rounds(k) (jit(scan(round)) + donation) must produce the same
    trained state and metrics as k iterative run_round() calls — the chunked
    fast path may not diverge from the per-round reference path."""
    import jax
    import jax.numpy as jnp
    from fedml_tpu.runner import FedMLRunner

    k = 3
    params = {}
    metrics = {}
    for mode in ("per_round", "chunk"):
        cfg = tiny_config(comm_round=k, frequency_of_the_test=0)
        import fedml_tpu

        fedml_tpu.init(cfg)
        sim = FedMLRunner(cfg).runner
        if mode == "per_round":
            ms = [sim.run_round() for _ in range(k)]
        else:
            ms = sim.run_rounds(k)
        params[mode] = jax.device_get(sim.global_vars)
        metrics[mode] = ms
    for a, b in zip(
        jax.tree_util.tree_leaves(params["per_round"]),
        jax.tree_util.tree_leaves(params["chunk"]),
    ):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-5, atol=2e-6)
    for ma, mb in zip(metrics["per_round"], metrics["chunk"]):
        for key in ma:
            np.testing.assert_allclose(ma[key], mb[key], rtol=2e-4, atol=1e-5)


def test_next_boundary_table(eight_devices):
    """Chunk boundaries must reproduce the per-round eval/checkpoint cadence."""
    import fedml_tpu
    from fedml_tpu.runner import FedMLRunner

    cfg = tiny_config(comm_round=10, frequency_of_the_test=3)
    fedml_tpu.init(cfg)
    sim = FedMLRunner(cfg).runner
    # eval after rounds 2, 5, 8 (1-indexed multiples of 3) and the last round
    assert sim._next_boundary(0) == 3
    assert sim._next_boundary(3) == 6
    assert sim._next_boundary(8) == 9
    assert sim._next_boundary(9) == 10

    cfg2 = tiny_config(comm_round=7, frequency_of_the_test=0)
    cfg2.checkpoint_every_rounds = 4
    fedml_tpu.init(cfg2)
    sim2 = FedMLRunner(cfg2).runner
    assert sim2._next_boundary(0) == 4
    assert sim2._next_boundary(4) == 7

    cfg3 = tiny_config(comm_round=5, frequency_of_the_test=0)
    cfg3.enable_contribution = True
    fedml_tpu.init(cfg3)
    sim3 = FedMLRunner(cfg3).runner
    # must stop before the final round so its pre-round state is snapshotted
    assert sim3._next_boundary(0) == 4
    assert sim3._next_boundary(4) == 5
