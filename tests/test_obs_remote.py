"""Remote observability sink (round-3 verdict item 6): client telemetry —
round events, perf metrics, RuntimeLogDaemon batches — rides the FL comm
backend to a server-side collector with JSONL persistence (reference
``core/mlops/mlops_metrics.py`` / ``mlops_runtime_log_daemon.py``)."""

import json
import time

import pytest

from .conftest import tiny_config


def test_shipper_batches_and_collector_aggregates(tmp_path):
    """Unit: shipper flush semantics + collector aggregation/persistence,
    with a lossy transport that must never raise into the caller.  A batch
    that fails transiently is re-buffered ONCE and rides the next flush —
    nothing is lost to a single transport blip."""
    from fedml_tpu.comm.message import Message
    from fedml_tpu.obs.remote import (
        MSG_TYPE_C2S_OBS, OBS_REBUFFERED, OBS_SHIPPED,
        ObsCollector, RemoteObsShipper,
    )

    collector = ObsCollector(str(tmp_path / "obs.jsonl"))
    sent = []

    def send(msg):
        if len(sent) == 0 and msg.get_sender_id() == 7:
            sent.append("dropped")
            raise OSError("transport down")  # first batch from rank 7 fails
        sent.append(msg)
        collector.handle(msg)

    shipped0 = OBS_SHIPPED.value()
    rebuffered0 = OBS_REBUFFERED.value()
    sh = RemoteObsShipper(send, rank=7, flush_every=3, flush_interval_s=0)
    sh.metric({"train_loss": 1.5, "round": 0})
    sh.event("train", "started", round_idx=0)
    assert sh.shipped == 0  # below flush_every
    sh.metric({"train_loss": 1.2, "round": 1})  # hits 3 -> flush -> FAILS
    # re-buffered once, not silently dropped
    assert sh.dropped == 0 and sh.shipped == 0
    assert OBS_REBUFFERED.value() - rebuffered0 == 3
    sh.log_lines(["line a", "line b"])
    sh.event("train", "ended", round_idx=1)
    sh.close()  # flush ships the re-buffered 3 + the remaining 2
    assert sh.shipped == 5 and sh.dropped == 0
    assert OBS_SHIPPED.value() - shipped0 == 5
    assert sh._thread is None  # no interval thread was started (interval 0)

    recs = collector.records(sender=7)
    assert len(recs) == 5
    assert collector.records(sender=7, kind="log")[0]["lines"] == ["line a", "line b"]
    assert collector.counts() == {7: 5}
    collector.close()
    lines = [json.loads(l) for l in (tmp_path / "obs.jsonl").read_text().splitlines()]
    assert all(l["sender"] == 7 for l in lines) and len(lines) == 5


def test_shipper_drops_twice_failed_batch_and_joins_thread(tmp_path):
    """A batch that fails its re-buffered retry too is dropped (bounded —
    no unbounded growth against a dead transport), counted in the registry;
    close() joins the interval flush thread."""
    from fedml_tpu.obs.remote import OBS_DROPPED, RemoteObsShipper

    def send_always_down(msg):
        raise OSError("transport down")

    dropped0 = OBS_DROPPED.value()
    sh = RemoteObsShipper(send_always_down, rank=3, flush_every=2,
                          flush_interval_s=0.05)
    thread = sh._thread
    assert thread is not None and thread.is_alive()
    sh.metric({"a": 1})
    sh.metric({"a": 2})  # flush -> fail -> re-buffer
    sh.flush()           # retry -> fail again -> drop
    assert sh.dropped == 2
    assert OBS_DROPPED.value() - dropped0 == 2
    sh.close()
    assert sh._thread is None and not thread.is_alive()


def test_secagg_clients_ship_train_telemetry(eight_devices):
    """The obs instrumentation wraps trainer.train itself, so protocol
    variants that override the train-and-send path (SecAgg here) ship the
    same per-round events as the plain client manager."""
    import fedml_tpu
    from fedml_tpu.comm.inproc import InProcRouter
    from fedml_tpu.cross_silo.lightsecagg import run_lightsecagg_process_group
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub

    cfg = tiny_config(
        training_type="cross_silo", client_num_in_total=4, client_num_per_round=4,
        comm_round=2, learning_rate=0.3, frequency_of_the_test=0,
        run_id="obs-lsa", enable_secagg=True,
    )
    cfg.extra = {"enable_remote_obs": True}
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)
    InProcRouter.reset("obs-lsa")
    history, server = run_lightsecagg_process_group(cfg, ds, model, timeout=120.0)
    assert len(history) == 2
    col = server.obs_collector
    assert col is not None
    for rank in (1, 2, 3, 4):
        ended = [e for e in col.records(sender=rank, kind="event")
                 if e["phase"] == "ended"]
        assert len(ended) == 2, (rank, col.counts())


def test_cross_silo_round_events_arrive_server_side(tmp_path, eight_devices):
    """E2E: with enable_remote_obs, every client's per-round train events,
    its perf-sampler metrics, and its log-daemon line batches all arrive at
    the server's collector over the FL transport and persist to JSONL."""
    import fedml_tpu
    from fedml_tpu.comm.inproc import InProcRouter
    from fedml_tpu.cross_silo import build_client, build_server
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub
    from fedml_tpu.obs.sampler import RuntimeLogDaemon

    jsonl = tmp_path / "server_obs.jsonl"
    cfg = tiny_config(
        training_type="cross_silo", client_num_in_total=2, client_num_per_round=2,
        comm_round=3, learning_rate=0.3, frequency_of_the_test=1, run_id="obs-e2e",
    )
    cfg.extra = {"enable_remote_obs": True, "obs_jsonl_path": str(jsonl)}
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)
    InProcRouter.reset("obs-e2e")
    clients = [build_client(cfg, ds, model, rank=r, backend="INPROC") for r in (1, 2)]
    for c in clients:
        c.run_in_thread()

    # client 1 also ships perf metrics and a runtime log through the SAME
    # shipper (the log daemon's sink is shipper.log_lines)
    log_file = tmp_path / "client1.log"
    log_file.write_text("epoch 0 ok\nepoch 1 ok\n")
    daemon = RuntimeLogDaemon(str(log_file), sink=clients[0].obs.log_lines)
    daemon.sweep_once()
    clients[0].obs.metric({"cpu_utilization": 12.5})

    server = build_server(cfg, ds, model, backend="INPROC")
    try:
        history = server.run_until_done(timeout=120.0)
    finally:
        for c in clients:
            c.finish()
    assert len(history) == 3

    col = server.obs_collector
    assert col is not None
    # both clients' train events for every round arrived
    for rank in (1, 2):
        events = col.records(sender=rank, kind="event")
        started = [e for e in events if e["phase"] == "started"]
        ended = [e for e in events if e["phase"] == "ended"]
        assert len(started) == 3 and len(ended) == 3, (rank, events)
        assert sorted(e["round_idx"] for e in ended) == [0, 1, 2]
        assert all(e["num_samples"] > 0 for e in ended)
    # the log-daemon batch and the perf metric rode the same path
    logs = col.records(sender=1, kind="log")
    assert logs and logs[0]["lines"] == ["epoch 0 ok", "epoch 1 ok"]
    metrics = col.records(sender=1, kind="metric")
    assert metrics and metrics[0]["cpu_utilization"] == 12.5
    # persisted server-side: both clients' telemetry plus the server's own
    # round/aggregate spans (rank 0) share ONE trail
    lines = [json.loads(l) for l in jsonl.read_text().splitlines()]
    assert {l["sender"] for l in lines} == {0, 1, 2}
    assert any(l.get("kind") == "log" for l in lines)
    assert any(l.get("kind") == "span" and l["sender"] == 0 for l in lines)
