"""Population engine tests (ISSUE 6): sharded store, hierarchical sampler,
streamed cohort execution.

The load-bearing guarantees:

- the store round-trips per-client state through gather -> mutate ->
  scatter -> (eviction/flush) -> regather, with disk as the source of truth;
- host memory for a cohort gather is bounded by the COHORT, not the
  population (tracemalloc-measured);
- the sampler is deterministic under (seed, round) and honors the
  DeviceRegistry liveness mask and, behind ``health_aware_selection``, the
  health ledger;
- the population-backed MeshSimulator fit path matches the in-memory path
  on a full cohort (loss/params parity) and leaves the default path's
  behavior untouched;
- the prefetch pipeline reports its overlap metric into the registry.
"""

import os
import tracemalloc

import numpy as np
import pytest

from .conftest import tiny_config

from fedml_tpu.population import (
    CohortPipeline, HierarchicalCohortSampler, ShardedClientStore, StoreSpec,
    cyclic_builder,
)


def _make_store(tmp_path, n_clients, shard_size=64, max_resident=4,
                capacity=8, dim=4, state=True, name="store"):
    base_n = min(n_clients, 16)
    rs = np.random.RandomState(0)
    base_x = rs.randn(base_n, capacity, dim).astype(np.float32)
    base_y = rs.randint(0, 10, size=(base_n, capacity)).astype(np.int32)
    base_counts = rs.randint(1, capacity + 1, size=base_n).astype(np.int32)
    spec = StoreSpec(n_clients=n_clients, capacity=capacity, x_shape=(dim,),
                     x_dtype="float32", y_shape=(), y_dtype="int32",
                     shard_size=shard_size)
    template = {"ctrl": np.zeros((dim,), np.float32),
                "step": np.zeros((), np.int32)} if state else None
    return ShardedClientStore(
        tmp_path / name, spec, builder=cyclic_builder(base_x, base_y, base_counts),
        state_template=template, max_resident=max_resident,
    ), (base_x, base_y, base_counts)


# -- store ---------------------------------------------------------------------

def test_store_gather_matches_builder_and_orders_by_id(tmp_path):
    store, (bx, by, bc) = _make_store(tmp_path, n_clients=200, shard_size=32)
    ids = np.array([5, 130, 7, 64, 199], np.int32)  # 4 distinct shards, unordered
    batch = store.gather_cohort(ids)
    np.testing.assert_array_equal(batch.ids, ids)
    for pos, cid in enumerate(ids):
        np.testing.assert_array_equal(batch.x[pos], bx[cid % len(bx)])
        np.testing.assert_array_equal(batch.y[pos], by[cid % len(by)])
        assert batch.counts[pos] == bc[cid % len(bc)]


def test_store_state_roundtrip_through_eviction(tmp_path):
    """gather -> mutate -> scatter -> force eviction churn -> regather: the
    refreshed rows come back exactly, from DISK (resident set dropped)."""
    store, _ = _make_store(tmp_path, n_clients=256, shard_size=32, max_resident=2)
    ids = np.array([1, 40, 90, 200], np.int32)  # 4 shards > max_resident=2
    st = store.gather_state(ids)
    np.testing.assert_array_equal(st["ctrl"], np.zeros((4, 4), np.float32))
    st["ctrl"] = st["ctrl"] + np.arange(4, dtype=np.float32)[:, None] + 1.0
    st["step"] = st["step"] + 7
    store.scatter_state(ids, st)
    # churn the LRU through other shards so every dirty shard is evicted
    store.gather_cohort(np.arange(224, 256, dtype=np.int32))
    store.gather_cohort(np.arange(128, 160, dtype=np.int32))
    store.drop_resident()  # flush + clear: disk is now the only copy
    back = store.gather_state(ids)
    np.testing.assert_array_equal(back["ctrl"], st["ctrl"])
    np.testing.assert_array_equal(back["step"], np.full(4, 7, np.int32))
    # untouched clients kept template state
    other = store.gather_state(np.array([2, 41], np.int32))
    np.testing.assert_array_equal(other["ctrl"], np.zeros((2, 4), np.float32))


def test_store_rss_bounded_by_cohort_not_population(tmp_path):
    """tracemalloc peak of a cohort gather must not grow with the
    population: a 2k-client and a 64k-client store gather a same-size
    hierarchically-sampled cohort within the same memory envelope (the
    sampler bounds the shards touched; the LRU bounds what stays resident)."""
    cohort = 128

    def peak_for(n_clients, name):
        store, _ = _make_store(tmp_path, n_clients=n_clients, shard_size=256,
                               max_resident=3, name=name)
        ids = HierarchicalCohortSampler(
            n_clients, cohort, shard_size=256, seed=7).sample(0)
        tracemalloc.start()
        batch = store.gather_cohort(ids)
        state = store.gather_state(ids)
        _, peak = tracemalloc.get_traced_memory()
        tracemalloc.stop()
        assert batch.x.shape[0] == cohort and state is not None
        return peak

    small = peak_for(2_000, "small")
    big = peak_for(64_000, "big")
    # identical cohort work => identical envelope; 2x headroom for allocator
    # noise, still far below any population-proportional growth (32x here)
    assert big < 2 * small + (1 << 20), (small, big)


def test_store_lru_stays_bounded_and_counts_hits(tmp_path):
    from fedml_tpu.population.store import RESIDENT_SHARDS

    store, _ = _make_store(tmp_path, n_clients=512, shard_size=32, max_resident=3)
    for lo in range(0, 512, 32):
        store.gather_cohort(np.arange(lo, lo + 8, dtype=np.int32))
    with store._lock:
        assert len(store._resident) <= 3
    assert RESIDENT_SHARDS._snapshot()["samples"][0]["value"] <= 3
    # a re-gather of a resident shard is a hit (no disk touch)
    before = dict(store._resident)
    store.gather_cohort(np.arange(480, 488, dtype=np.int32))
    with store._lock:
        assert set(store._resident) == set(before)


# -- sampler -------------------------------------------------------------------

def test_sampler_deterministic_and_full_coverage():
    s = HierarchicalCohortSampler(n_clients=10_000, cohort_size=500,
                                  shard_size=512, seed=3)
    a = s.sample(4)
    b = HierarchicalCohortSampler(10_000, 500, 512, seed=3).sample(4)
    np.testing.assert_array_equal(a, b)          # pure in (seed, round)
    assert len(a) == 500 and len(np.unique(a)) == 500
    assert a.min() >= 0 and a.max() < 10_000
    assert not np.array_equal(a, s.sample(5))    # rounds differ
    assert not np.array_equal(a, HierarchicalCohortSampler(
        10_000, 500, 512, seed=9).sample(4))     # seeds differ
    # cohort >= population degenerates to everyone, in id order (the
    # in-memory engine's semantics — pinned by the parity test below)
    tiny = HierarchicalCohortSampler(64, 64, 16, seed=0)
    np.testing.assert_array_equal(tiny.sample(0), np.arange(64))
    # bounded shard touch: a 500-id cohort over 512-sized shards must not
    # touch more than a handful of shards (two-level locality)
    touched = len(np.unique(a // 512))
    assert touched <= s.shards_per_cohort + 2, touched


def test_sampler_honors_liveness_mask():
    from fedml_tpu.cross_device import DeviceRegistry

    reg = DeviceRegistry(max_missed=1)
    dead = [3, 77, 150]
    for d in dead:
        reg.register(d)
        reg.note_missed_selection(d)
        reg.note_missed_selection(d)
    s = HierarchicalCohortSampler(n_clients=200, cohort_size=150,
                                  shard_size=64, seed=1, registry=reg)
    cohort = s.sample(0)
    assert len(cohort) == 150
    assert not set(dead) & set(cohort.tolist())  # struck-out ids excluded
    # unknown ids (never registered) are assumed live
    assert len(set(cohort.tolist()) - set(dead)) == 150
    # when exclusion would starve the cohort, excluded ids backfill
    s_all = HierarchicalCohortSampler(n_clients=200, cohort_size=200,
                                      shard_size=64, seed=1, registry=reg)
    assert len(s_all.sample(0)) == 200


def test_sampler_health_deprioritizes_behind_flag():
    from fedml_tpu.obs.health import ClientHealthLedger

    ledger = ClientHealthLedger()
    degraded = [10, 11, 12, 13]
    for d in degraded:
        for _ in range(6):
            ledger.record_deadline_breach(d)
    kw = dict(n_clients=64, cohort_size=32, shard_size=32, seed=2, health=ledger)
    aware = HierarchicalCohortSampler(health_aware=True, **kw).sample(1)
    assert not set(degraded) & set(aware.tolist())
    # flag off: the ledger is ignored (reference-exact sampling pool)
    blind = HierarchicalCohortSampler(health_aware=False, **kw).sample(1)
    assert len(blind) == 32
    # degraded ids still fill a cohort that healthy ids alone cannot
    full = HierarchicalCohortSampler(health_aware=True, n_clients=64,
                                     cohort_size=64, shard_size=32, seed=2,
                                     health=ledger).sample(1)
    assert len(full) == 64 and set(degraded) < set(full.tolist())


# -- population-backed simulator ----------------------------------------------

def _run_sim(cfg):
    import jax
    import fedml_tpu
    from fedml_tpu.runner import FedMLRunner

    fedml_tpu.init(cfg)
    runner = FedMLRunner(cfg)
    history = runner.run()
    return history, jax.device_get(runner.runner.global_vars), runner.runner


@pytest.mark.parametrize("optimizer", ["FedAvg", "SCAFFOLD"])
def test_population_matches_in_memory_on_full_cohort(tmp_path, eight_devices, optimizer):
    """Same recipe, full-population cohort: the store-backed path must match
    the in-memory path (loss, accuracy, final params) — including per-client
    state scattered through the store (SCAFFOLD) with an LRU small enough to
    force eviction churn between rounds."""
    kw = dict(comm_round=3, client_num_in_total=8, client_num_per_round=8,
              frequency_of_the_test=1, federated_optimizer=optimizer)
    hist_mem, params_mem, _ = _run_sim(tiny_config(**kw))
    hist_pop, params_pop, sim = _run_sim(tiny_config(
        **kw, extra={"population_store": str(tmp_path / f"pop_{optimizer}"),
                     "population_shard_size": 4,
                     "population_max_resident_shards": 1}))
    assert sim._population is not None
    import jax

    for a, b in zip(jax.tree_util.tree_leaves(params_mem),
                    jax.tree_util.tree_leaves(params_pop)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-5, atol=2e-6)
    for hm, hp in zip(hist_mem, hist_pop):
        np.testing.assert_allclose(hm["train_loss"], hp["train_loss"],
                                   rtol=1e-5, atol=1e-6)
        if "test_acc" in hm:
            assert hm["test_acc"] == pytest.approx(hp["test_acc"], abs=1e-6)


def test_population_expanded_cohort_subsampling_learns(tmp_path, eight_devices):
    """A 10k-id population cyclically backed by the 8-client base dataset,
    16-client cohorts: the run completes, improves, touches only a bounded
    set of shards, and reports the prefetch-overlap metric."""
    from fedml_tpu.obs import registry as obsreg

    root = tmp_path / "pop10k"
    hist, _params, sim = _run_sim(tiny_config(
        comm_round=4, client_num_in_total=8, client_num_per_round=16,
        frequency_of_the_test=0,
        extra={"population_store": str(root), "population_size": 10_000,
               "population_shard_size": 64}))
    assert len(hist) == 4
    assert hist[-1]["train_loss"] < hist[0]["train_loss"]
    assert sim._population.store.spec.n_clients == 10_000
    # only the sampled shards ever materialized on disk
    n_files = len([f for f in os.listdir(root) if f.endswith(".npz")])
    assert 0 < n_files < 40, n_files
    # prefetch overlap metric present in the registry text exposition
    text = obsreg.REGISTRY.render()
    assert "fedml_pop_prefetch_overlap_fraction" in text
    assert "fedml_pop_gather_seconds_count" in text
    assert sim._population.pipeline.overlap_mean() is not None


def test_population_flag_unset_leaves_default_path_untouched(eight_devices):
    _hist, _params, sim = _run_sim(tiny_config(comm_round=1))
    assert sim._population is None
    assert sim.client_states is None or sim.client_states is not None  # attr exists
    # SP backend refuses the flag rather than silently ignoring it
    with pytest.raises(ValueError, match="population_store"):
        _run_sim(tiny_config(comm_round=1, backend_sim="sp",
                             extra={"population_store": "/tmp/nope"}))
