"""Causal postmortem (ISSUE 16 tentpole, part c) + the default-path
acceptance criterion.

- ``stitch_bundles`` over the bundles a REAL ``run_kill_recover_soak``
  leaves behind: the fleet and both server incarnations stitch into one
  timeline, the kill and the recovery are both visible, and EVERY lost
  upload gets a cause (``unattributed_lost == 0`` — the acceptance bar);
- ``render_postmortem`` says what a human asks first (what was in flight,
  what was lost and why, accounting verdict);
- ``fedml-tpu obs postmortem`` exit codes: 0 on a fully-attributed run,
  1 on no bundles, 2 on a missing path; ``--json`` emits the stitched dict;
- corrupt bundles are skipped, not fatal;
- the A/B half of the acceptance criterion: the same seeded INPROC
  cross-silo run with flight + SLO + cost-model gauges ON converges to the
  BITWISE-identical global model as the all-defaults run, with zero SLO
  breaches recorded along the way.
"""

import json
import os

import numpy as np
import pytest

from fedml_tpu.obs import flight as flightlib
from fedml_tpu.obs.postmortem import render_postmortem, stitch_bundles

_ATTRIBUTIONS = {"in_flight_at_kill", "in_kill_gap", "in_killed_epoch",
                 "post_finish", "chaos_silent_loss"}


@pytest.fixture(scope="module")
def kill_run(tmp_path_factory, eight_devices):
    """One real kill-and-recover soak with flight recording on; every test
    below reads the same bundle set."""
    from fedml_tpu.cross_silo.async_soak import run_kill_recover_soak

    flight_dir = str(tmp_path_factory.mktemp("flight"))
    res = run_kill_recover_soak(
        n_clients=16, concurrency=8, buffer_k=4, versions=3,
        drop_prob=0.05, latency_mean_s=0.002, redispatch_timeout_s=1.0,
        seed=0, timeout_s=180.0,
        extra_flags={"flight_recorder": True, "flight_dir": flight_dir})
    assert res["monotone"] and res["unaccounted"] == 0, res
    return flight_dir, res


def test_stitch_joins_kill_recovery_and_attributes_every_loss(kill_run):
    flight_dir, _ = kill_run
    stitched = stitch_bundles(flight_dir)

    names = {b["name"] for b in stitched["bundles"]}
    reasons = {b["reason"] for b in stitched["bundles"]}
    assert "fleet" in names, stitched["bundles"]
    assert "hard_kill" in reasons, stitched["bundles"]

    # the merged timeline interleaves sources and is time-ordered
    assert stitched["timeline"]
    ts = [e["ts"] for e in stitched["timeline"]]
    assert ts == sorted(ts)
    assert len({e["src"] for e in stitched["timeline"]}) >= 2

    assert stitched["kills"], "hard_kill bundle carried no kill context"
    assert stitched["recoveries"], "no recovery event in any ring"
    # the kill context names the in-flight dispatch ledger
    assert any((k["context"] or {}).get("outstanding") is not None
               or (k["context"] or {}).get("prev_epoch_inflight") is not None
               for k in stitched["kills"])

    # the acceptance bar: nothing unaccounted, nothing unattributable
    assert (stitched["unaccounted"] or 0) == 0, stitched["accounting"]
    up = stitched["uploads"]
    assert up["sent"] > 0
    assert sum(up["arrived"].values()) > 0
    assert up["unattributed_lost"] == 0, up["lost"]
    for rec in up["lost"]:
        assert rec["attribution"] in _ATTRIBUTIONS, rec


def test_render_answers_the_human_questions(kill_run):
    flight_dir, _ = kill_run
    stitched = stitch_bundles(flight_dir)
    text = render_postmortem(stitched, limit=10)
    assert f"{len(stitched['bundles'])} bundle(s)" in text
    assert "in flight at the kill" in text
    assert "recovered:" in text
    assert "upload ledger:" in text
    assert "OK — every loss accounted" in text
    assert "WARNING" not in text
    # limit trims the timeline but keeps the ledger
    assert f"timeline (10/{len(stitched['timeline'])} events" in text


def test_cli_exit_codes_and_json(kill_run, tmp_path, capsys):
    from fedml_tpu.cli import main as cli_main

    flight_dir, _ = kill_run
    assert cli_main(["obs", "postmortem", flight_dir]) == 0
    assert "upload ledger:" in capsys.readouterr().out

    assert cli_main(["obs", "postmortem", flight_dir, "--json"]) == 0
    stitched = json.loads(capsys.readouterr().out)
    assert stitched["uploads"]["unattributed_lost"] == 0

    assert cli_main(["obs", "postmortem", str(tmp_path / "nope")]) == 2
    empty = tmp_path / "empty"
    empty.mkdir()
    assert cli_main(["obs", "postmortem", str(empty)]) == 1
    capsys.readouterr()


def test_corrupt_bundles_are_skipped_not_fatal(kill_run, tmp_path):
    flight_dir, _ = kill_run
    good = stitch_bundles(flight_dir)
    # drop garbage next to the real bundles: same stitch must come out
    (tmp_path / "d").mkdir()
    for p in flightlib.list_bundles(flight_dir):
        data = open(p, "rb").read()
        open(tmp_path / "d" / os.path.basename(p), "wb").write(data)
    (tmp_path / "d" / "zz.flight").write_bytes(b"FMLFLT1\ngarbage")
    (tmp_path / "d" / "aa.flight").write_bytes(b"not a bundle at all")
    dirty = stitch_bundles(str(tmp_path / "d"))
    assert len(dirty["bundles"]) == len(good["bundles"])
    assert dirty["uploads"] == good["uploads"]


def test_stitch_attributes_unknown_loss_as_unattributed(tmp_path):
    """The red-flag path: a sender-recorded key the server never saw, with
    no kill, no gap, no chaos budget — MUST come out unattributed (that is
    the postmortem's whole alarm)."""
    rec = flightlib.FlightRecorder(str(tmp_path), name="fleet")
    rec.note("reply", client=1, version=0, key="1:0:-1:0")
    rec.note("virtual_round", version=99, arrivals=1)  # run "ended" after
    rec.dump("soak_finish", context={"unaccounted": 1})
    stitched = stitch_bundles(str(tmp_path))
    assert stitched["uploads"]["unattributed_lost"] == 1
    assert stitched["unaccounted"] == 1
    text = render_postmortem(stitched)
    assert "VIOLATION" in text and "WARNING" in text

    from fedml_tpu.cli import main as cli_main

    assert cli_main(["obs", "postmortem", str(tmp_path)]) == 1


# ---------------------------------------------------------------------------
# acceptance: all-flags-on run is bitwise the default run


def _cross_silo_run(run_id, extra):
    import jax

    import fedml_tpu
    from fedml_tpu.comm.inproc import InProcRouter
    from fedml_tpu.cross_silo import build_client, build_server
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub

    from .conftest import tiny_config

    cfg = tiny_config(training_type="cross_silo", run_id=run_id,
                      client_num_in_total=2, client_num_per_round=2,
                      comm_round=2, frequency_of_the_test=0)
    cfg.extra = dict(extra)
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)
    InProcRouter.reset(run_id)
    clients = [build_client(cfg, ds, model, rank=r, backend="INPROC")
               for r in (1, 2)]
    for c in clients:
        c.run_in_thread()
    server = build_server(cfg, ds, model, backend="INPROC")
    try:
        history = server.run_until_done(timeout=120.0)
    finally:
        for c in clients:
            c.finish()
    slo_summary = server.slo.summary() if server.slo is not None else None
    return (history, jax.device_get(server.aggregator.global_vars),
            slo_summary)


def test_observability_on_is_bitwise_identical_and_breach_free(
        eight_devices, tmp_path):
    """Flight recorder + SLO watchdog + cost-model gauges all ON must not
    perturb training by one bit, and a healthy run records ZERO breaches."""
    hist_off, vars_off, slo_off = _cross_silo_run("pm_obs_off", {})
    assert slo_off is None  # default path: no engine at all

    obs_extra = {
        "flight_recorder": True,
        "flight_dir": str(tmp_path / "flt"),
        "slo_flight_dump": True,
        "cost_model_gauges": True,
        "slo_interval_s": 0.2,
        "slo_specs": {
            "round_p95": {"metric": "fedml_crosssilo_round_seconds",
                          "stat": "p95", "op": "<=", "threshold": 120.0},
            "rounds_done": {"metric": "fedml_crosssilo_rounds_total",
                            "op": "<=", "threshold": 1e9},
        },
    }
    hist_on, vars_on, slo_on = _cross_silo_run("pm_obs_on", obs_extra)

    import jax

    assert [h["round"] for h in hist_off] == [h["round"] for h in hist_on]
    leaves_off = jax.tree_util.tree_leaves(vars_off)
    leaves_on = jax.tree_util.tree_leaves(vars_on)
    assert len(leaves_off) == len(leaves_on)
    for a, b in zip(leaves_off, leaves_on):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    assert slo_on is not None
    assert slo_on["evaluations"] >= 1
    assert slo_on["breaches"] == 0 and slo_on["breached_slos"] == []
