"""mqtt_real.py adapter tests with an injected fake paho client.

VERDICT round-2 weak #3: the paho adapter contains real logic (2.x/1.x API
switch, subscribe-before-connect, resubscribe-on-reconnect, will ordering)
that only ran in production before.  The fake scripts a paho-shaped client so
every branch is exercised hermetically.
"""

import json
import threading

import pytest


class FakePahoClient:
    def __init__(self, *args, **kwargs):
        self.ctor_args = args
        self.ctor_kwargs = kwargs
        self.on_message = None
        self.on_connect = None
        self.connect_calls = []
        self.loop_started = 0
        self.loop_stopped = 0
        self.subscriptions = []  # (topic, qos)
        self.published = []  # (topic, payload, qos)
        self.will = None
        self.userpass = None
        self.disconnected = 0

    def username_pw_set(self, u, p):
        self.userpass = (u, p)

    def connect(self, host, port, keepalive):
        self.connect_calls.append((host, port, keepalive))
        if self.on_connect:
            self.on_connect(self, None, None, 0)

    def loop_start(self):
        self.loop_started += 1

    def loop_stop(self):
        self.loop_stopped += 1

    def subscribe(self, topic, qos=0):
        self.subscriptions.append((topic, qos))

    def publish(self, topic, payload, qos=0):
        self.published.append((topic, payload, qos))

    def will_set(self, topic, payload, qos=0, retain=False):
        self.will = (topic, payload, qos, retain)

    def disconnect(self):
        self.disconnected += 1

    # test helper: simulate an inbound broker message
    def deliver(self, topic, payload):
        class M:
            pass

        m = M()
        m.topic, m.payload = topic, payload
        self.on_message(self, None, m)


class FakePaho2:
    """paho-mqtt >= 2.0 shape: has CallbackAPIVersion."""

    class CallbackAPIVersion:
        VERSION1 = "v1"

    Client = FakePahoClient


class FakePaho1:
    """paho-mqtt 1.x shape: no CallbackAPIVersion, clean_session kwarg."""

    Client = FakePahoClient


def _broker(paho, **kw):
    from fedml_tpu.comm.mqtt_real import PahoMqttBroker

    return PahoMqttBroker("broker.test", 1883, client_id="c0", paho_module=paho, **kw)


def test_paho2_constructor_uses_callback_api_version():
    b = _broker(FakePaho2)
    assert b._client.ctor_args == ("v1",)
    assert b._client.ctor_kwargs == {"client_id": "c0"}


def test_paho1_constructor_uses_clean_session():
    b = _broker(FakePaho1)
    assert b._client.ctor_args == ()
    assert b._client.ctor_kwargs == {"client_id": "c0", "clean_session": True}


def test_username_password_forwarded():
    b = _broker(FakePaho2, username="u", password="s3cret")
    assert b._client.userpass == ("u", "s3cret")


def test_will_before_connect_and_lazy_single_connect():
    b = _broker(FakePaho2)
    b.set_will("c0", "t/status", b"bye")
    assert b._client.will, "will must be set before any connect"
    assert b._client.connect_calls == []
    b.publish("t/a", b"one")
    b.publish("t/a", b"two")
    # exactly one connect + loop_start despite two publishes
    assert len(b._client.connect_calls) == 1
    assert b._client.loop_started == 1
    assert b._client.will == ("t/status", b"bye", 2, False)
    assert [(t, p) for t, p, _q in b._client.published] == [("t/a", b"one"), ("t/a", b"two")]
    # everything rides QoS 2
    assert all(q == 2 for _t, _p, q in b._client.published)


def test_resubscribe_on_reconnect():
    """Clean-session reconnects start with zero subscriptions: on_connect
    must re-issue every subscribe or a broker restart silently drops all
    round traffic."""
    b = _broker(FakePaho2)
    got = []
    b.subscribe("t/x", lambda t, p: got.append((t, p)))
    b.subscribe("t/y", lambda t, p: got.append((t, p)))
    before = list(b._client.subscriptions)
    assert ("t/x", 2) in before and ("t/y", 2) in before
    # broker restart: paho fires on_connect again
    b._client.on_connect(b._client, None, None, 0)
    after = b._client.subscriptions[len(before):]
    assert sorted(after) == [("t/x", 2), ("t/y", 2)], after


def test_dispatch_routes_to_topic_callbacks():
    b = _broker(FakePaho2)
    got_x, got_y = [], []
    b.subscribe("t/x", lambda t, p: got_x.append(p))
    b.subscribe("t/y", lambda t, p: got_y.append(p))
    b._client.deliver("t/x", b"payload-x")
    assert got_x == [b"payload-x"] and got_y == []


def test_disconnect_stops_loop_once():
    b = _broker(FakePaho2)
    b.publish("t", b"x")
    b.disconnect()
    b.disconnect()  # idempotent
    assert b._client.loop_stopped == 1
    assert b._client.disconnected == 1


def test_s3_store_with_injected_client():
    from fedml_tpu.comm.mqtt_real import S3ObjectStore

    blobs = {}

    class FakeS3:
        def put_object(self, Bucket, Key, Body):
            blobs[(Bucket, Key)] = Body

        def get_object(self, Bucket, Key):
            class Body:
                def __init__(self, b):
                    self._b = b

                def read(self):
                    return self._b

            return {"Body": Body(blobs[(Bucket, Key)])}

    store = S3ObjectStore(bucket="bkt", client=FakeS3())
    key = store.put("model-r1", b"\x01\x02")
    assert key == "model-r1"
    assert ("bkt", "fedml_tpu/model-r1") in blobs  # prefix applied
    assert store.get("model-r1") == b"\x01\x02"


def test_comm_manager_rides_fake_paho_end_to_end():
    """MqttS3CommManager over the paho adapter: ONLINE status published,
    per-rank topic subscribed, a delivered frame reaches the observer."""
    from fedml_tpu.comm.message import Message
    from fedml_tpu.comm.mqtt_real import PahoMqttBroker, S3ObjectStore
    from fedml_tpu.comm.mqtt_s3 import InMemoryObjectStore, MqttS3CommManager

    b = _broker(FakePaho2)
    store = InMemoryObjectStore()
    mgr = MqttS3CommManager("run9", 1, broker=b, store=store)
    # will set before the first connect, ONLINE announced after
    assert b._client.will[0] == "fedml_run9_status"
    assert json.loads(b._client.will[1].decode())["status"] == "OFFLINE"
    online = [p for t, p, _q in b._client.published if t == "fedml_run9_status"]
    assert online and json.loads(online[0].decode())["status"] == "ONLINE"
    assert ("fedml_run9_to_1", 2) in b._client.subscriptions

    # outbound: manager publishes through the paho adapter with the D/R marker
    out = Message(3, sender_id=1, receiver_id=2)
    out.add_params("k", 1.5)
    mgr.send_message(out)
    sent = [(t, p) for t, p, _q in b._client.published if t == "fedml_run9_to_2"]
    assert len(sent) == 1 and sent[0][1][:1] == b"D"

    # inbound: a frame delivered by paho lands in the inbox and decodes
    b._client.deliver("fedml_run9_to_1", sent[0][1])
    data = mgr._inbox.get(timeout=2)
    m = mgr._decode_bytes(data)
    assert m.get_type() == 3 and m.get_sender_id() == 1
    assert float(m.get("k")) == 1.5


def test_import_error_without_paho(monkeypatch):
    import fedml_tpu.comm.mqtt_real as mr

    # paho_module=None means 'use the real import'; simulate its absence
    # explicitly so the test passes whether or not paho-mqtt is installed
    monkeypatch.setattr(mr, "_paho", None)
    with pytest.raises(ImportError):
        mr.PahoMqttBroker("h", paho_module=None)
