"""Global-registry metric lint (ISSUE 3 satellite).

Every family registered in the process-global registry by any instrumented
layer must carry the ``fedml_`` namespace (``fedml_[a-z0-9_]+``) with valid
label names, and a name can never be re-registered with a conflicting
type/label set — the registry enforces it, this test proves it stays
enforced.  Runs against the real global registry after importing every
module that registers metrics, so a new metric with a bad name fails CI
here, not in someone's Grafana.
"""

import importlib
import re

import pytest

#: every module that registers families in the global registry — extend this
#: list when instrumenting a new layer
INSTRUMENTED_MODULES = [
    "fedml_tpu.comm.base",
    "fedml_tpu.comm.codecs",
    "fedml_tpu.cross_silo.server",
    "fedml_tpu.obs.health",
    "fedml_tpu.obs.otlp",
    "fedml_tpu.obs.remote",
    "fedml_tpu.ops.pallas.timing",
    "fedml_tpu.sim.engine",
]

_NAME = re.compile(r"fedml_[a-z0-9_]+")
_LABEL = re.compile(r"[a-z][a-z0-9_]*")


def test_global_registry_names_are_namespaced_and_unique():
    for mod in INSTRUMENTED_MODULES:
        importlib.import_module(mod)
    from fedml_tpu.obs.registry import REGISTRY

    families = REGISTRY.snapshot()
    assert families, "instrumented modules registered nothing?"
    names = [fam["name"] for fam in families]
    for fam in families:
        assert _NAME.fullmatch(fam["name"]), (
            f"metric {fam['name']!r} violates the fedml_[a-z0-9_]+ namespace")
        assert fam["kind"] in ("counter", "gauge", "histogram"), fam
        for label in fam["labels"]:
            assert _LABEL.fullmatch(label), (fam["name"], label)
            assert label != "le", f"{fam['name']}: 'le' is reserved for histograms"
    # one family per name — the registry's dict keying guarantees it; keep
    # the invariant asserted so a refactor can't silently lose it
    assert len(names) == len(set(names))


def test_comm_compression_families_registered():
    """ISSUE-4 families must exist under the fedml_comm_*/fedml_crosssilo_*
    namespaces (the lint above then validates their shapes)."""
    for mod in INSTRUMENTED_MODULES:
        importlib.import_module(mod)
    from fedml_tpu.obs.registry import REGISTRY

    names = {fam["name"] for fam in REGISTRY.snapshot()}
    for required in (
        "fedml_comm_payload_bytes_total",
        "fedml_comm_payload_raw_bytes_total",
        "fedml_comm_compression_ratio",
        "fedml_crosssilo_buffered_updates_peak",
    ):
        assert required in names, f"{required} not registered"


def test_conflicting_reregistration_is_refused():
    """No metric can be registered twice with a conflicting type or label
    set — same-spec re-registration returns the SAME family object."""
    for mod in INSTRUMENTED_MODULES:
        importlib.import_module(mod)
    from fedml_tpu.obs.registry import REGISTRY

    cls_for = {"counter": REGISTRY.counter, "gauge": REGISTRY.gauge,
               "histogram": REGISTRY.histogram}
    for fam in REGISTRY.snapshot():
        # same spec -> same object
        metric = REGISTRY.get(fam["name"])
        assert cls_for[fam["kind"]](fam["name"], labels=tuple(fam["labels"])) is metric
        # conflicting labels -> loud failure
        with pytest.raises(ValueError):
            cls_for[fam["kind"]](fam["name"], labels=tuple(fam["labels"]) + ("rogue",))
        # conflicting type -> loud failure
        other = REGISTRY.gauge if fam["kind"] != "gauge" else REGISTRY.counter
        with pytest.raises(ValueError):
            other(fam["name"], labels=tuple(fam["labels"]))