"""Global-registry metric lint (ISSUE 3 satellite; ISSUE 5 moved the
name/label rule into the static engine as GL005).

The namespace rule itself now lives in
``fedml_tpu/analysis/rules/gl005_metrics.py`` and runs over every module in
tier-1 via ``fedml-tpu lint`` — this file DELEGATES to it (same compiled
regexes, plus a whole-package static pass) and keeps the complementary
RUNTIME checks the static rule cannot do: families registered with computed
names, and re-registration conflict behavior of the live registry.
"""

import importlib

import pytest

from fedml_tpu.analysis.rules.gl005_metrics import (
    LABEL_RE as _LABEL,
    METRIC_NAME_RE as _NAME,
    MetricNamespaceRule,
)

#: every module that registers families in the global registry — extend this
#: list when instrumenting a new layer
INSTRUMENTED_MODULES = [
    "fedml_tpu.comm.base",
    "fedml_tpu.comm.chaos",
    "fedml_tpu.comm.codecs",
    "fedml_tpu.core.aot",
    "fedml_tpu.cross_silo.async_server",
    "fedml_tpu.cross_silo.client_journal",
    "fedml_tpu.cross_silo.journal",
    "fedml_tpu.cross_silo.runtime",
    "fedml_tpu.cross_silo.server",
    "fedml_tpu.sched.multi_tenant",
    "fedml_tpu.obs.flight",
    "fedml_tpu.obs.health",
    "fedml_tpu.obs.otlp",
    "fedml_tpu.obs.profiler",
    "fedml_tpu.obs.remote",
    "fedml_tpu.obs.slo",
    "fedml_tpu.obs.timeline",
    "fedml_tpu.ops.pallas.timing",
    "fedml_tpu.population.cohorts",
    "fedml_tpu.population.store",
    "fedml_tpu.serving.batcher",
    "fedml_tpu.serving.gateway",
    "fedml_tpu.serving.publisher",
    "fedml_tpu.sim.engine",
]


def test_static_gl005_pass_over_package_is_clean():
    """The engine's own rule over the real package: every literal
    REGISTRY.counter/gauge/histogram registration anywhere in fedml_tpu/
    (imported by a test or not) is fedml_-namespaced with valid labels."""
    from pathlib import Path

    from fedml_tpu.analysis.engine import run_lint

    pkg = Path(importlib.import_module("fedml_tpu").__file__).parent
    result = run_lint(pkg, rules=[MetricNamespaceRule()],
                      baseline=pkg / "analysis" / "baseline.json")
    assert result.ok, "\n" + result.render()


def test_global_registry_names_are_namespaced_and_unique():
    for mod in INSTRUMENTED_MODULES:
        importlib.import_module(mod)
    from fedml_tpu.obs.registry import REGISTRY

    families = REGISTRY.snapshot()
    assert families, "instrumented modules registered nothing?"
    names = [fam["name"] for fam in families]
    for fam in families:
        assert _NAME.fullmatch(fam["name"]), (
            f"metric {fam['name']!r} violates the fedml_[a-z0-9_]+ namespace")
        assert fam["kind"] in ("counter", "gauge", "histogram"), fam
        for label in fam["labels"]:
            assert _LABEL.fullmatch(label), (fam["name"], label)
            assert label != "le", f"{fam['name']}: 'le' is reserved for histograms"
    # one family per name — the registry's dict keying guarantees it; keep
    # the invariant asserted so a refactor can't silently lose it
    assert len(names) == len(set(names))


def test_comm_compression_families_registered():
    """ISSUE-4 families must exist under the fedml_comm_*/fedml_crosssilo_*
    namespaces (the lint above then validates their shapes)."""
    for mod in INSTRUMENTED_MODULES:
        importlib.import_module(mod)
    from fedml_tpu.obs.registry import REGISTRY

    names = {fam["name"] for fam in REGISTRY.snapshot()}
    for required in (
        "fedml_comm_payload_bytes_total",
        "fedml_comm_payload_raw_bytes_total",
        "fedml_comm_compression_ratio",
        "fedml_crosssilo_buffered_updates_peak",
    ):
        assert required in names, f"{required} not registered"


def test_conflicting_reregistration_is_refused():
    """No metric can be registered twice with a conflicting type or label
    set — same-spec re-registration returns the SAME family object."""
    for mod in INSTRUMENTED_MODULES:
        importlib.import_module(mod)
    from fedml_tpu.obs.registry import REGISTRY

    cls_for = {"counter": REGISTRY.counter, "gauge": REGISTRY.gauge,
               "histogram": REGISTRY.histogram}
    for fam in REGISTRY.snapshot():
        # same spec -> same object
        metric = REGISTRY.get(fam["name"])
        assert cls_for[fam["kind"]](fam["name"], labels=tuple(fam["labels"])) is metric
        # conflicting labels -> loud failure
        with pytest.raises(ValueError):
            cls_for[fam["kind"]](fam["name"], labels=tuple(fam["labels"]) + ("rogue",))
        # conflicting type -> loud failure
        other = REGISTRY.gauge if fam["kind"] != "gauge" else REGISTRY.counter
        with pytest.raises(ValueError):
            other(fam["name"], labels=tuple(fam["labels"]))