"""Serving (HTTP inference), federated analytics, workflow DAG tests."""

import json
import urllib.request

import numpy as np
import pytest

from .conftest import tiny_config


# ---------------------------------------------------------------------------
# serving
# ---------------------------------------------------------------------------

def test_inference_runner_http(eight_devices):
    import jax
    from fedml_tpu.models.simple import LogisticRegression
    from fedml_tpu.serving.inference import FedMLInferenceRunner, JaxPredictor

    model = LogisticRegression(num_classes=3)
    variables = model.init(jax.random.PRNGKey(0), np.zeros((1, 8), np.float32))
    runner = FedMLInferenceRunner(JaxPredictor(model, variables, max_batch=8), port=0)
    port = runner.run(block=False)
    try:
        # ready
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/ready") as r:
            assert json.loads(r.read())["status"] == "ready"
        # predict
        req = json.dumps({"inputs": [[0.1] * 8, [0.2] * 8]}).encode()
        q = urllib.request.Request(
            f"http://127.0.0.1:{port}/predict", data=req,
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(q) as r:
            out = json.loads(r.read())["outputs"]
        assert np.asarray(out).shape == (2, 3)
        # malformed request -> 400 with error body, server stays alive
        bad = urllib.request.Request(f"http://127.0.0.1:{port}/predict", data=b"{}",
                                     headers={"Content-Type": "application/json"})
        try:
            urllib.request.urlopen(bad)
            assert False, "should have errored"
        except urllib.error.HTTPError as e:
            assert e.code == 400
            assert "error" in json.loads(e.read())
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/ready") as r:
            assert r.status == 200
    finally:
        runner.stop()


# ---------------------------------------------------------------------------
# federated analytics
# ---------------------------------------------------------------------------

def _fa_cfg(rounds=1, per_round=8):
    return tiny_config(comm_round=rounds, client_num_per_round=per_round)


def test_fa_avg_and_frequency():
    from fedml_tpu.fa.analyzers import create_analyzer_pair
    from fedml_tpu.fa.frame import FASimulator

    rng = np.random.RandomState(0)
    data = [rng.normal(5.0, 1.0, 100) for _ in range(8)]
    ca, sa = create_analyzer_pair("avg")
    result = FASimulator(_fa_cfg(), data, ca, sa).run()
    expected = np.mean(np.concatenate(data))
    assert abs(result - expected) < 1e-9

    cat_data = [rng.randint(0, 4, 200) for _ in range(8)]
    ca, sa = create_analyzer_pair("frequency_estimation")
    freqs = FASimulator(_fa_cfg(), cat_data, ca, sa).run()
    assert abs(sum(freqs.values()) - 1.0) < 1e-9
    assert set(freqs) <= {0, 1, 2, 3}


def test_fa_intersection_union_percentile():
    from fedml_tpu.fa.analyzers import create_analyzer_pair
    from fedml_tpu.fa.frame import FASimulator

    sets = [np.array([1, 2, 3, 4]), np.array([2, 3, 4, 5]), np.array([3, 4, 6])] * 3
    ca, sa = create_analyzer_pair("intersection")
    inter = FASimulator(_fa_cfg(per_round=9), sets[:9], ca, sa).run()
    assert inter == {3, 4}
    ca, sa = create_analyzer_pair("union")
    union = FASimulator(_fa_cfg(per_round=9), sets[:9], ca, sa).run()
    assert union == {1, 2, 3, 4, 5, 6}

    rng = np.random.RandomState(1)
    data = [rng.uniform(0, 100, 500) for _ in range(8)]
    ca, sa = create_analyzer_pair("k_percentile")
    sa.k = 50.0
    est = FASimulator(_fa_cfg(rounds=25), data, ca, sa).run()
    true_median = np.percentile(np.concatenate(data), 50)
    assert abs(est - true_median) < 2.0, (est, true_median)


def test_fa_heavy_hitters():
    from fedml_tpu.fa.analyzers import create_analyzer_pair
    from fedml_tpu.fa.frame import FASimulator

    # 30 clients mostly holding "the"/"cat"; each also holds one singleton
    # word.  Zero-padded rare{i:02d} keeps every FULL singleton word (6
    # chars) from being a prefix of another client's word — the unpadded
    # "rare2" used to be BOTH client 2's full word and a prefix of
    # rare20..rare29 (10 clients), so TrieHH correctly promoted it and the
    # old "no rare heavy hitter" assert could never hold.
    common = ["the", "cat"]
    data = []
    for i in range(30):
        words = [common[i % 2]] * 5 + [f"rare{i:02d}"]
        data.append(np.array(words))
    ca, sa = create_analyzer_pair("heavy_hitter_triehh")
    sa.theta = 3
    FASimulator(_fa_cfg(rounds=12, per_round=20), data, ca, sa).run()
    hh = sa.heavy_hitters()
    assert any(h.startswith("the"[:len(h)]) or h.startswith("cat"[:len(h)]) for h in hh), hh
    # shared prefixes ("rare", "rare0".."rare2", 10 clients each) may clear
    # the theta=3 threshold; a FULL singleton word (held by one client) must
    # never — that is the DP guarantee under test
    assert not any(h.startswith("rare") and len(h) > 5 for h in hh), hh


# ---------------------------------------------------------------------------
# workflow
# ---------------------------------------------------------------------------

def test_workflow_dag_order_and_outputs():
    from fedml_tpu.workflow.workflow import Job, JobStatus, Workflow

    order = []

    def make(name, result):
        def fn(**inputs):
            order.append(name)
            return result + sum(v for v in inputs.values())

        return fn

    wf = Workflow("test")
    a = Job("a", make("a", 1))
    b = Job("b", make("b", 10))
    c = Job("c", make("c", 100))
    wf.add_job(a)
    wf.add_job(b, dependencies=[a])
    wf.add_job(c, dependencies=[a, b])
    outputs = wf.run()
    assert order.index("a") < order.index("b") < order.index("c")
    assert outputs == {"a": 1, "b": 11, "c": 112}
    assert wf.get_workflow_status() == JobStatus.FINISHED


def test_workflow_rejects_cycles_and_failures():
    from fedml_tpu.workflow.workflow import Job, JobStatus, Workflow

    wf = Workflow()
    a, b = Job("a", lambda **kw: 1), Job("b", lambda **kw: 2)
    wf.add_job(a, dependencies=["b"])
    wf.add_job(b, dependencies=["a"])
    with pytest.raises(ValueError, match="cycle"):
        wf.run()

    wf2 = Workflow()
    boom = Job("boom", lambda **kw: 1 / 0)
    wf2.add_job(boom)
    with pytest.raises(ZeroDivisionError):
        wf2.run()
    assert wf2.get_workflow_status() == JobStatus.FAILED
