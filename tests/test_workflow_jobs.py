"""Workflow customized jobs (VERDICT r4 item 3): the DAG engine driving the
REAL sched + serving verticals — a LaunchJob that packages a config into the
agent spool and waits on JobDB, feeding a DeployJob that brings an endpoint
to readiness and serves a predict.

Reference: ``workflow/customized_jobs/train_job.py``,
``model_deploy_job.py``, ``workflow/jobs.py:43``.
"""

import json
import os
import textwrap
from pathlib import Path

import numpy as np
import pytest


TRAIN_MAIN = textwrap.dedent("""
    import json, os
    import fedml_tpu
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub
    from fedml_tpu.serving.deploy import save_params_card
    from fedml_tpu.sim.engine import MeshSimulator

    cfg = fedml_tpu.init(argv=["--cf", "fedml_config.yaml"])
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)
    sim = MeshSimulator(cfg, ds, model)
    for _ in range(cfg.comm_round):
        sim.run_round()
    path = save_params_card(sim.global_vars, "model.wire")
    seen = {}
    if os.path.exists("__workflow_inputs__.json"):
        with open("__workflow_inputs__.json") as f:
            seen = json.load(f)
    with open("output.json", "w") as f:
        json.dump({
            "params_path": os.path.abspath(path),
            "model": cfg.model,
            "classes": ds.class_num,
            "model_name": "wf-trained",
            "seen_inputs": seen,
        }, f)
""")

TRAIN_CONFIG = textwrap.dedent("""
    common_args:
      training_type: "simulation"
      random_seed: 0
    data_args:
      dataset: "synthetic"
      partition_method: "homo"
      synthetic_train_size: 320
      synthetic_test_size: 80
    model_args:
      model: "lr"
    train_args:
      federated_optimizer: "FedAvg"
      client_num_in_total: 4
      client_num_per_round: 2
      comm_round: 2
      epochs: 1
      batch_size: 16
      learning_rate: 0.1
""")


def _make_train_workspace(root: Path) -> Path:
    """A launchable workspace + job yaml, reference launch-example shape."""
    ws = root / "train_ws"
    ws.mkdir()
    (ws / "main.py").write_text(TRAIN_MAIN)
    (ws / "fedml_config.yaml").write_text(TRAIN_CONFIG)
    (root / "job.yaml").write_text(
        "workspace: train_ws\n"
        "job: python main.py\n"
        "job_name: wf-train\n"
    )
    return root / "job.yaml"


def test_workflow_trains_then_deploys_then_serves(tmp_path, eight_devices):
    """The reference's headline workflow: a 2-node DAG where node 1 launches
    a (tiny) federated training run through the agent spool and node 2
    deploys the produced artifact and answers a predict — plus a leading
    config node proving dependency outputs reach the launched process."""
    from fedml_tpu.sched.agent import FedMLAgent
    from fedml_tpu.serving.deploy import ModelDeployScheduler
    from fedml_tpu.workflow.customized_jobs import DeployJob, LaunchJob
    from fedml_tpu.workflow.workflow import Job, JobStatus, Workflow

    spool = tmp_path / "spool"
    yaml_path = _make_train_workspace(tmp_path)

    agent = FedMLAgent(str(spool), env={"JAX_PLATFORMS": "cpu"},
                       capacity={"num_devices": 1})
    agent.run_in_thread(poll_s=0.2)
    sched = ModelDeployScheduler(str(tmp_path / "endpoints.db"),
                                 reconcile_interval_s=0.3)
    try:
        wf = Workflow("train-deploy")
        cfg_job = Job("config", fn=lambda: {"tag": "e2e", "lr": 0.1})
        train = LaunchJob("train", str(yaml_path), str(spool), timeout=420)
        deploy = DeployJob("deploy", endpoint="wf-ep", scheduler=sched,
                           replicas=1, ready_timeout=180)
        wf.add_job(cfg_job)
        wf.add_job(train, dependencies=[cfg_job])
        wf.add_job(deploy, dependencies=[train])
        outputs = wf.run()

        # the launch job surfaced the run's output.json
        assert outputs["train"]["model"] == "lr"
        assert Path(outputs["train"]["params_path"]).exists()
        # dependency outputs reached the launched subprocess via the package
        assert outputs["train"]["seen_inputs"] == {"config": {"tag": "e2e", "lr": 0.1}}
        # ...without leaking the inputs file into the SOURCE workspace
        assert not (tmp_path / "train_ws" / "__workflow_inputs__.json").exists()
        # the deploy job exposed a LIVE endpoint
        assert outputs["deploy"]["ready_replicas"] == 1
        # synthetic dataset features are 60-dim (loader _KNOWN table)
        out = outputs["deploy"]["predict"]({"inputs": np.zeros((2, 60)).tolist()})
        assert len(out["outputs"]) == 2 and len(out["outputs"][0]) == 10
        assert wf.get_workflow_status() == JobStatus.FINISHED
    finally:
        sched.stop()
        agent.stop()


def test_launch_job_failure_propagates(tmp_path):
    """A FAILED run fails the LaunchJob (with the log tail in the error) and
    the workflow reports FAILED — reference Workflow status semantics."""
    from fedml_tpu.sched.agent import FedMLAgent
    from fedml_tpu.workflow.customized_jobs import LaunchJob
    from fedml_tpu.workflow.workflow import JobStatus, Workflow

    spool = tmp_path / "spool"
    ws = tmp_path / "bad_ws"
    ws.mkdir()
    (ws / "main.py").write_text("import sys; print('boom-marker'); sys.exit(3)\n")
    (tmp_path / "job.yaml").write_text("workspace: bad_ws\njob: python main.py\n")

    agent = FedMLAgent(str(spool), capacity={"num_devices": 1})
    agent.run_in_thread(poll_s=0.2)
    try:
        wf = Workflow("failing")
        job = LaunchJob("bad", str(tmp_path / "job.yaml"), str(spool), timeout=60)
        wf.add_job(job)
        with pytest.raises(RuntimeError, match="boom-marker"):
            wf.run()
        assert job.status == JobStatus.FAILED
        assert wf.get_workflow_status() == JobStatus.FAILED
    finally:
        agent.stop()


def test_deploy_job_requires_artifact(tmp_path):
    """No params_path anywhere -> a loud ValueError, not a half-deploy."""
    from fedml_tpu.serving.deploy import ModelDeployScheduler
    from fedml_tpu.workflow.customized_jobs import DeployJob

    sched = ModelDeployScheduler(str(tmp_path / "e.db"))
    job = DeployJob("d", endpoint="none", scheduler=sched)
    with pytest.raises(ValueError, match="params_path"):
        job.run(dep={"no": "artifact"})
    assert job.status.value == "FAILED"


def test_deploy_job_rejects_ambiguous_target():
    from fedml_tpu.workflow.customized_jobs import DeployJob

    with pytest.raises(ValueError, match="exactly one"):
        DeployJob("d", endpoint="x")
