"""Worker entry for the 2-process multi-host test (spawned by
tests/test_multihost.py).  Usage:

    python tests/_multihost_worker.py <process_id> <num_processes> <port>

Each process backs 4 virtual CPU devices; the global mesh is 8 devices over
2 processes.  Prints the final global-parameter checksum and last-round
metrics as one JSON line tagged MULTIHOST_RESULT.
"""

import json
import os
import sys


def main():
    pid, nproc, port = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["JAX_PLATFORM_NAME"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax

    jax.config.update("jax_platforms", "cpu")

    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    import fedml_tpu
    from fedml_tpu.arguments import Config

    cfg = Config(
        dataset="synthetic",
        model="lr",
        client_num_in_total=8,
        client_num_per_round=8,
        comm_round=2,
        epochs=1,
        batch_size=16,
        learning_rate=0.1,
        synthetic_train_size=640,
        synthetic_test_size=160,
        partition_method="homo",
        frequency_of_the_test=1,
        compute_dtype="float32",
        random_seed=0,
        backend_sim="MULTIPROCESS",
        extra={
            "coordinator_address": f"localhost:{port}",
            "num_processes": nproc,
            "process_id": pid,
        },
    )
    fedml_tpu.init(cfg)
    assert jax.process_count() == nproc, jax.process_count()
    assert len(jax.devices()) == 4 * nproc, len(jax.devices())

    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub
    from fedml_tpu.sim.engine import MeshSimulator

    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)
    sim = MeshSimulator(cfg, ds, model)
    history = sim.run()

    import numpy as np

    flat = np.concatenate([
        np.asarray(x, dtype=np.float64).ravel()
        for x in jax.tree_util.tree_leaves(jax.device_get(sim.global_vars))
    ])
    print("MULTIHOST_RESULT " + json.dumps({
        "pid": pid,
        "checksum": float(flat.sum()),
        "l2": float(np.sqrt((flat ** 2).sum())),
        "test_acc": history[-1].get("test_acc"),
    }), flush=True)


if __name__ == "__main__":
    main()
