"""AOT program store (ISSUE 7, ``fedml_tpu/core/aot.py``).

The contract under test:

- export/import roundtrip parity: a program loaded from the store produces
  BITWISE the same outputs as the freshly built jit on CPU;
- fingerprints are stable across processes and sensitive to every key
  component (site, tree structure/shape/dtype, mesh, hparams, extras);
- corrupt / truncated / version-mismatched entries fall back to a rebuild,
  never a crash;
- two processes racing on one key produce ONE export (advisory flock);
- flag unset is a strict no-op (``store_from_config`` returns None and the
  simulators run their pre-store jit paths) and the flagged path is
  bit-identical to the default path — cold AND warm;
- every wired site (mesh chunk, population round, sim eval, hierarchical
  round, ring gossip, cross-silo server eval) hits the store on a second
  construction with zero rebuilds.
"""

import json
import os
import subprocess
import sys
import textwrap

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from fedml_tpu.core import aot
from fedml_tpu.core.aot import (
    AOT_EXPORTS, AOT_HITS, AOT_MISSES, ProgramStore, export_program,
    program_key, store_from_config,
)

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def counters():
    return AOT_HITS.value(), AOT_MISSES.value(), AOT_EXPORTS.value()


def _toy_fn():
    def fn(w, x, key):
        for _ in range(3):
            w = jnp.tanh(x @ w) + 0.5 * w
        noise = jax.random.normal(key, w.shape) * 1e-3
        return w + noise, (w * x[:, : w.shape[1]]).sum()

    args = (
        jnp.linspace(0.0, 1.0, 32, dtype=jnp.float32).reshape(8, 4),
        jnp.ones((8, 8), jnp.float32),
        jax.random.PRNGKey(7),
    )
    return fn, args


def _leaves_equal(a, b):
    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    return all(np.array_equal(np.asarray(x), np.asarray(y)) for x, y in zip(la, lb))


# -- roundtrip parity ---------------------------------------------------------

def test_roundtrip_parity_bitwise(tmp_path):
    fn, args = _toy_fn()
    key = program_key("test.roundtrip", trees={"args": args})
    store = ProgramStore(str(tmp_path))
    h0, m0, e0 = counters()
    built = store.get_or_build(key, lambda: export_program(jax.jit(fn), args))
    assert built is not None and not built.from_cache
    assert counters() == (h0, m0 + 1, e0 + 1)

    # a FRESH store object (new process stand-in) must load from disk
    loaded = ProgramStore(str(tmp_path)).get_or_build(
        key, lambda: pytest.fail("warm lookup must not rebuild"))
    assert loaded.from_cache
    assert counters() == (h0 + 1, m0 + 1, e0 + 1)

    fresh = jax.device_get(jax.jit(fn)(*args))
    stored = jax.device_get(loaded.bind()(*args))
    assert _leaves_equal(fresh, stored)  # bitwise, not allclose


# -- fingerprints -------------------------------------------------------------

def test_fingerprint_stable_across_processes():
    tree = {"w": jnp.zeros((4, 8), jnp.float32), "b": jnp.zeros((8,), jnp.bfloat16)}
    key = program_key("test.stable", trees={"a": tree},
                      hparams={"lr": 0.1, "epochs": 2},
                      config={"model": "lr"}, extra={"chunk": 3})
    code = textwrap.dedent(f"""
        import sys; sys.path.insert(0, {REPO_ROOT!r})
        import jax, jax.numpy as jnp
        from fedml_tpu.core.aot import program_key
        tree = {{"w": jnp.zeros((4, 8), jnp.float32),
                 "b": jnp.zeros((8,), jnp.bfloat16)}}
        print(program_key("test.stable", trees={{"a": tree}},
                          hparams={{"lr": 0.1, "epochs": 2}},
                          config={{"model": "lr"}}, extra={{"chunk": 3}}))
    """)
    out = subprocess.run([sys.executable, "-c", code], capture_output=True,
                         text=True, timeout=120, env=dict(os.environ))
    assert out.returncode == 0, out.stderr[-2000:]
    assert out.stdout.strip().splitlines()[-1] == key


def test_fingerprint_sensitive_to_each_component():
    from jax.sharding import Mesh

    tree = {"w": jnp.zeros((4, 8), jnp.float32)}
    base = dict(trees={"a": tree}, hparams={"lr": 0.1},
                config={"model": "lr"}, extra={"chunk": 2})
    keys = {
        "base": program_key("s", **base),
        "site": program_key("s2", **base),
        "tree_shape": program_key("s", **{**base, "trees": {"a": {"w": jnp.zeros((4, 9), jnp.float32)}}}),
        "tree_dtype": program_key("s", **{**base, "trees": {"a": {"w": jnp.zeros((4, 8), jnp.bfloat16)}}}),
        "tree_structure": program_key("s", **{**base, "trees": {"a": {"v": jnp.zeros((4, 8), jnp.float32)}}}),
        "hparams": program_key("s", **{**base, "hparams": {"lr": 0.2}}),
        "config": program_key("s", **{**base, "config": {"model": "mlp"}}),
        "extra_chunk": program_key("s", **{**base, "extra": {"chunk": 4}}),
        "mesh": program_key("s", mesh=Mesh(np.array(jax.devices()), ("clients",)), **base),
    }
    assert len(set(keys.values())) == len(keys), keys


# -- corruption / version fallback -------------------------------------------

def _entry_path(store, key):
    return store._path(key)


def test_truncated_entry_rebuilds(tmp_path):
    fn, args = _toy_fn()
    key = program_key("test.trunc", trees={"args": args})
    store = ProgramStore(str(tmp_path))
    build = lambda: export_program(jax.jit(fn), args)
    store.get_or_build(key, build)
    path = _entry_path(store, key)
    blob = open(path, "rb").read()
    open(path, "wb").write(blob[: len(blob) // 2])  # torn write stand-in

    h0, m0, e0 = counters()
    prog = ProgramStore(str(tmp_path)).get_or_build(key, build)
    assert prog is not None and not prog.from_cache  # rebuilt, no crash
    assert counters() == (h0, m0 + 1, e0 + 1)
    # the rebuilt entry is valid again
    again = ProgramStore(str(tmp_path)).get_or_build(
        key, lambda: pytest.fail("rebuilt entry must load"))
    assert again.from_cache


def test_garbage_and_version_mismatch_rebuild(tmp_path):
    fn, args = _toy_fn()
    key = program_key("test.vers", trees={"args": args})
    store = ProgramStore(str(tmp_path))
    build = lambda: export_program(jax.jit(fn), args)
    store.get_or_build(key, build)
    path = _entry_path(store, key)

    # garbage magic
    open(path, "wb").write(b"not a program store entry")
    assert not ProgramStore(str(tmp_path)).get_or_build(key, build).from_cache

    # valid envelope, wrong toolchain version
    blob = open(path, "rb").read()
    magic = b"FMLAOT1\n"
    header, payload = blob[len(magic):].split(b"\n", 1)
    meta = json.loads(header)
    meta["jax"] = "0.0.0"
    open(path, "wb").write(magic + json.dumps(meta, sort_keys=True).encode() + b"\n" + payload)
    h0, m0, _ = counters()
    prog = ProgramStore(str(tmp_path)).get_or_build(key, build)
    assert prog is not None and not prog.from_cache
    assert counters()[0] == h0  # the mismatched entry never counts as a hit


def test_failing_build_falls_back_to_none(tmp_path):
    store = ProgramStore(str(tmp_path))

    def bad_build():
        raise RuntimeError("unexportable program")

    assert store.get_or_build("test.bad.000", bad_build) is None  # no crash
    assert store.entries() == []


# -- cross-process concurrency ------------------------------------------------

def test_concurrent_two_process_single_export(tmp_path):
    """Two processes race get_or_build on one key: the flock serializes them
    into exactly ONE export; the loser loads the winner's entry and both
    programs produce identical outputs."""
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent(f"""
        import json, sys, time
        sys.path.insert(0, {REPO_ROOT!r})
        import jax, jax.numpy as jnp
        import numpy as np
        from fedml_tpu.core.aot import (AOT_EXPORTS, AOT_HITS, AOT_MISSES,
                                        ProgramStore, export_program, program_key)

        def fn(w):
            for _ in range(3):
                w = jnp.tanh(w @ w.T) @ w
            return w

        args = (jnp.linspace(0.0, 1.0, 64, dtype=jnp.float32).reshape(8, 8),)
        key = program_key("test.race", trees={{"args": args}})
        store = ProgramStore({str(tmp_path)!r})

        def build():
            time.sleep(1.0)  # hold the flock long enough to overlap the peer
            return export_program(jax.jit(fn), args)

        prog = store.get_or_build(key, build)
        out = np.asarray(jax.device_get(prog.bind()(*args)))
        print(json.dumps({{"misses": AOT_MISSES.value(), "hits": AOT_HITS.value(),
                           "exports": AOT_EXPORTS.value(),
                           "checksum": float(out.sum()),
                           "from_cache": prog.from_cache}}))
    """))
    procs = [subprocess.Popen([sys.executable, str(script)], stdout=subprocess.PIPE,
                              stderr=subprocess.PIPE, text=True, env=dict(os.environ))
             for _ in range(2)]
    results = []
    for p in procs:
        out, err = p.communicate(timeout=240)
        assert p.returncode == 0, err[-2000:]
        results.append(json.loads(out.strip().splitlines()[-1]))
    assert sum(r["misses"] for r in results) == 1, results  # ONE build total
    assert sum(r["exports"] for r in results) == 1, results
    assert results[0]["checksum"] == results[1]["checksum"]
    assert len([f for f in os.listdir(tmp_path) if f.endswith(".jaxprog")]) == 1


# -- flag gating + end-to-end parity ------------------------------------------

def test_flag_unset_is_noop(make_tiny_config):
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub
    from fedml_tpu.sim.engine import MeshSimulator

    import fedml_tpu

    cfg = make_tiny_config()
    assert store_from_config(cfg) is None
    assert store_from_config(None) is None
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    sim = MeshSimulator(cfg, ds, model_hub.create(cfg, ds.class_num))
    assert sim._aot is None  # every jit below runs the pre-store path


def _run_mesh(make_tiny_config, extra):
    import fedml_tpu
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub
    from fedml_tpu.sim.engine import MeshSimulator

    cfg = make_tiny_config(metrics_jsonl_path="", extra=dict(extra))
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    sim = MeshSimulator(cfg, ds, model_hub.create(cfg, ds.class_num))
    hist = sim.run()
    return sim, hist


def test_mesh_parity_flag_off_cold_warm(tmp_path, make_tiny_config):
    """The acceptance pin: default path vs store-cold vs store-warm are all
    BITWISE identical, and the warm run reports hits with zero misses."""
    sim_off, hist_off = _run_mesh(make_tiny_config, {})
    flags = {"aot_programs": True, "aot_programs_dir": str(tmp_path)}
    sim_cold, hist_cold = _run_mesh(make_tiny_config, flags)
    h0, m0, _ = counters()
    sim_warm, hist_warm = _run_mesh(make_tiny_config, flags)
    assert AOT_MISSES.value() - m0 == 0  # warm run rebuilt nothing
    assert AOT_HITS.value() - h0 > 0

    off = jax.device_get(sim_off.global_vars)
    assert _leaves_equal(off, jax.device_get(sim_cold.global_vars))
    assert _leaves_equal(off, jax.device_get(sim_warm.global_vars))
    for h in (hist_cold, hist_warm):
        assert h[-1]["test_acc"] == hist_off[-1]["test_acc"]
        assert h[-1]["test_loss"] == hist_off[-1]["test_loss"]


def test_population_round_program_cached(tmp_path, make_tiny_config):
    import fedml_tpu
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub
    from fedml_tpu.sim.engine import MeshSimulator

    losses = []
    for i in range(2):
        cfg = make_tiny_config(
            client_num_in_total=16, client_num_per_round=8, batch_size=8,
            synthetic_train_size=256, frequency_of_the_test=0,
            metrics_jsonl_path="",
            extra={"aot_programs": True, "aot_programs_dir": str(tmp_path / "aot"),
                   "population_store": str(tmp_path / f"pop{i}"),
                   "population_size": 64})
        fedml_tpu.init(cfg)
        ds = loader.load(cfg)
        h0, m0, _ = counters()
        sim = MeshSimulator(cfg, ds, model_hub.create(cfg, ds.class_num))
        out = sim.run_rounds(2)
        losses.append(out[-1]["train_loss"])
        if i == 1:  # second process stand-in: eval + population round both hit
            assert AOT_MISSES.value() - m0 == 0
            assert AOT_HITS.value() - h0 >= 2
    assert losses[0] == losses[1]


def test_hierarchical_and_gossip_and_crosssilo_eval_hit(tmp_path, make_tiny_config):
    import dataclasses

    import fedml_tpu
    from fedml_tpu.cross_silo.server import FedMLAggregator
    from fedml_tpu.data import loader
    from fedml_tpu.data.dataset import pad_eval_set
    from fedml_tpu.models import model_hub
    from fedml_tpu.sim.decentralized import DecentralizedSimulator
    from fedml_tpu.sim.hierarchical import HierarchicalSimulator

    flags = {"aot_programs": True, "aot_programs_dir": str(tmp_path)}

    def hier():
        cfg = make_tiny_config(
            federated_optimizer="HierarchicalFL", group_num=2,
            group_comm_round=2, client_num_per_round=8,
            frequency_of_the_test=0, metrics_jsonl_path="", extra=dict(flags))
        fedml_tpu.init(cfg)
        ds = loader.load(cfg)
        sim = HierarchicalSimulator(cfg, ds, model_hub.create(cfg, ds.class_num))
        return sim.run_round()["train_loss"]

    def ring():
        cfg = make_tiny_config(
            federated_optimizer="decentralized_fl", client_num_per_round=8,
            frequency_of_the_test=0, metrics_jsonl_path="", extra=dict(flags))
        fedml_tpu.init(cfg)
        ds = loader.load(cfg)
        sim = DecentralizedSimulator(
            cfg, ds, model_hub.create(cfg, ds.class_num), mode="ring")
        return sim.run_round()["train_loss"]

    def cs_eval(extra):
        cfg = make_tiny_config(
            training_type="cross_silo", client_num_in_total=2,
            client_num_per_round=2, metrics_jsonl_path="", extra=dict(extra))
        fedml_tpu.init(cfg)
        ds = loader.load(cfg)
        model = model_hub.create(cfg, ds.class_num)
        test = pad_eval_set(ds.test_x, ds.test_y, min(256, max(32, cfg.test_batch_size)))
        agg = FedMLAggregator(cfg, model, ds.test_x[:1], test)
        return {k: float(v) for k, v in
                agg._eval_fn(agg.global_vars, *agg._test).items()}

    for build in (hier, ring):
        first = build()
        h0, m0, _ = counters()
        second = build()
        assert second == first  # loaded program, identical numerics
        assert AOT_MISSES.value() - m0 == 0
        assert AOT_HITS.value() - h0 > 0

    ev_cold = cs_eval(flags)
    h0, m0, _ = counters()
    ev_warm = cs_eval(flags)
    assert AOT_MISSES.value() - m0 == 0 and AOT_HITS.value() - h0 > 0
    assert cs_eval({}) == ev_cold == ev_warm  # flag-off parity too
