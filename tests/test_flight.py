"""Flight recorder (ISSUE 16): bounded black-box ring + atomic bundles.

- the ring is allocation-bounded under sustained load (tracemalloc: peak
  does not scale with the number of events pushed, only with capacity);
- bundle envelope roundtrip (MAGIC + meta line + JSON body, atomic
  tmp+os.replace — no torn/tmp files left behind) and foreign-file
  rejection;
- the dump window filter, metric-delta capture, and trigger accounting
  (``fedml_flight_dumps_total{reason}``);
- excepthook/SIGTERM chaining installs and uninstalls cleanly;
- the config gate: ``extra.flight_recorder`` unset -> ``None`` (no ring,
  no taps, no handlers — the bit-identical-default half lives in
  test_postmortem's A/B run).
"""

import json
import os
import sys
import threading
import tracemalloc

import pytest

from fedml_tpu.obs import flight as flightlib
from fedml_tpu.obs import registry as obsreg


def _recorder(tmp_path, **kw):
    kw.setdefault("capacity", 64)
    kw.setdefault("window_s", 0.0)  # <= 0: dump everything in the ring
    return flightlib.FlightRecorder(str(tmp_path), name="t", **kw)


# ---------------------------------------------------------------------------
# bounded memory


def test_ring_memory_is_capacity_bounded_not_load_bounded(tmp_path):
    """Push 40k events through a 256-slot ring: traced peak must track the
    ring capacity, not the event count.  The comparison run pushes 10x
    fewer events — a leaky ring scales ~10x; a bounded one stays flat."""
    payload = "x" * 200

    def pump(n_events):
        rec = _recorder(tmp_path / f"r{n_events}", capacity=256)
        tracemalloc.start()
        for i in range(n_events):
            rec.note("load", i=i, payload=payload, client=i % 7)
        _, peak = tracemalloc.get_traced_memory()
        tracemalloc.stop()
        assert len(rec.events()) == 256
        return peak

    small = pump(4_000)
    large = pump(40_000)
    # bounded: 10x the traffic must not cost anywhere near 10x the memory
    assert large < small * 3 + 1_000_000, (small, large)


def test_note_never_raises_even_from_threads(tmp_path):
    rec = _recorder(tmp_path, capacity=32)
    errs = []

    def hammer():
        try:
            for i in range(2_000):
                rec.note("t", i=i, obj=object())  # non-serializable is fine
        except BaseException as e:  # noqa: BLE001 — the assertion target
            errs.append(e)

    threads = [threading.Thread(target=hammer) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    assert len(rec.events()) == 32


# ---------------------------------------------------------------------------
# bundles


def test_bundle_envelope_roundtrip_and_atomicity(tmp_path):
    rec = _recorder(tmp_path)
    rec.note("upload", client=3, key="3:0:-1:0")
    rec.note("epoch", event="recovery", step=2)
    path = rec.dump("unit_test", context={"why": "roundtrip"})
    assert os.path.dirname(path) == str(tmp_path)

    bundle = flightlib.read_bundle(path)
    assert bundle["meta"]["format"] == "fedml-flight-v1"
    assert bundle["meta"]["reason"] == "unit_test"
    assert bundle["meta"]["name"] == "t"
    assert bundle["meta"]["n_events"] == 2
    assert bundle["context"] == {"why": "roundtrip"}
    kinds = [e["kind"] for e in bundle["events"]]
    assert kinds == ["upload", "epoch"]
    # atomic write: no tmp droppings, and list_bundles skips them anyway
    assert not [f for f in os.listdir(tmp_path) if f.startswith(".tmp_")]
    assert flightlib.list_bundles(str(tmp_path)) == [path]


def test_read_bundle_rejects_foreign_and_torn_files(tmp_path):
    foreign = tmp_path / "x.flight"
    foreign.write_bytes(b"not a bundle")
    with pytest.raises(ValueError):
        flightlib.read_bundle(str(foreign))
    torn = tmp_path / "y.flight"
    torn.write_bytes(b"FMLFLT1\n" + b'{"no": "newline"')
    with pytest.raises(ValueError):
        flightlib.read_bundle(str(torn))


def test_dump_window_filters_old_events(tmp_path):
    rec = _recorder(tmp_path, window_s=60.0)
    rec.note("old")
    with rec._lock:  # age the event past the window
        rec._ring[0]["ts"] -= 120.0
    rec.note("fresh")
    events = rec.events()
    assert [e["kind"] for e in events] == ["fresh"]
    # window <= 0 keeps everything
    assert len(rec.events(window_s=0)) == 2


def test_trigger_counts_and_sequences_bundles(tmp_path):
    rec = _recorder(tmp_path)
    before = flightlib.FLIGHT_DUMPS.value(reason="unit_seq")
    p1 = rec.trigger("unit_seq", detail=1)
    p2 = rec.trigger("unit_seq", detail=2)
    assert p1 != p2 and os.path.exists(p1) and os.path.exists(p2)
    assert flightlib.FLIGHT_DUMPS.value(reason="unit_seq") == before + 2
    # the trigger note itself rides in the bundle
    b2 = flightlib.read_bundle(p2)
    assert [e for e in b2["events"] if e["kind"] == "trigger"]
    assert b2["context"]["detail"] == 2
    assert b2["meta"]["seq"] == flightlib.read_bundle(p1)["meta"]["seq"] + 1


def test_metric_deltas_ring_only_changes(tmp_path):
    reg = obsreg.MetricsRegistry()
    c = reg.counter("fedml_test_flight_events_total", "t")
    rec = _recorder(tmp_path, registry=reg)
    assert rec.record_metric_deltas() == 0  # first call: baseline only
    c.inc(3)
    assert rec.record_metric_deltas() == 1
    assert rec.record_metric_deltas() == 0  # nothing moved
    deltas = [e for e in rec.events() if e["kind"] == "metrics_delta"]
    assert len(deltas) == 1
    assert deltas[0]["delta"]["fedml_test_flight_events_total"] == 3.0


# ---------------------------------------------------------------------------
# triggers: hooks + signal chaining


def test_excepthook_chain_installs_and_uninstalls(tmp_path):
    rec = _recorder(tmp_path)
    prev_hook, prev_thook = sys.excepthook, threading.excepthook
    rec.install_signal_handlers()
    try:
        assert sys.excepthook is not prev_hook
        sys.excepthook(ValueError, ValueError("boom"), None)
        bundles = flightlib.list_bundles(str(tmp_path))
        assert len(bundles) == 1
        b = flightlib.read_bundle(bundles[0])
        assert b["meta"]["reason"] == "unhandled_exception"
        assert b["context"] == {"exc_type": "ValueError", "exc": "boom"}
    finally:
        rec.uninstall_signal_handlers()
    assert sys.excepthook is prev_hook
    assert threading.excepthook is prev_thook


def test_close_is_idempotent_and_detaches(tmp_path):
    rec = _recorder(tmp_path)
    rec.attach_comm()
    rec.install_signal_handlers()
    prev = sys.excepthook
    rec.close()
    rec.close()
    assert sys.excepthook is not prev or rec._prev_excepthook is None
    assert rec._comm_sink is None


# ---------------------------------------------------------------------------
# the gate


def test_recorder_from_config_gate(tmp_path):
    from .conftest import tiny_config

    cfg = tiny_config()
    cfg.extra = {}
    assert flightlib.recorder_from_config(cfg, name="x") is None
    assert flightlib.recorder_from_config(None, name="x") is None

    cfg.extra = {"flight_recorder": True, "flight_dir": str(tmp_path / "fd"),
                 "flight_capacity": 128, "flight_window_s": 5.0}
    rec = flightlib.recorder_from_config(cfg, name="x", meta={"role": "test"})
    assert rec is not None
    assert rec.capacity == 128 and rec.window_s == 5.0
    assert rec.meta["role"] == "test"
    assert os.path.isdir(tmp_path / "fd")
    path = rec.trigger("gate_check")
    assert json.loads(b"{}") == {} and path is not None  # bundle landed
    rec.close()
