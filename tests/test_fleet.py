"""One fleet for everything (ISSUE 19): per-job submesh partition of a
single device fleet, the device-slot scheduler's token-bucket quota
(throttled, never starved), the tenant-routed serving gateway under live
training, fallback to the PR-14 time-sliced gate when the shapes don't
tile, and the flags-unset regression pins (no SubmeshPlan object, no lease
metrics — the time-sliced semantics bit-identical pins live in
tests/test_multi_tenant.py)."""

import json
import os
import threading
import time
import urllib.request

import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.arguments import Config
from fedml_tpu.cross_silo.runtime import GangScheduler, ServerRuntime
from fedml_tpu.obs import registry as obsreg
from fedml_tpu.parallel import mesh as meshlib
from fedml_tpu.sched.multi_tenant import MultiTenantControlPlane


def _cfg(extra=None):
    return Config(dataset="synthetic", model="lr", extra=dict(extra or {}))


# ---------------------------------------------------------------------------
# submesh carving + config plumbing
# ---------------------------------------------------------------------------

def test_carve_submeshes_disjoint_and_identically_shaped(eight_devices):
    plan = meshlib.carve_submeshes(("clients",), (2,), 4)
    assert len(plan) == 4
    assert plan.describe() == {"jobs": 4, "shape": {"clients": 2},
                               "devices_per_job": 2}
    seen = set()
    for i in range(4):
        lease = plan.lease(i)
        assert lease.axis_names == ("clients",)
        assert lease.devices.shape == (2,)
        ids = {d.id for d in lease.devices.flat}
        assert not (ids & seen), "leases must be disjoint"
        seen |= ids
    assert seen == {d.id for d in eight_devices}
    # shapes that don't tile the fleet refuse loudly
    with pytest.raises(ValueError):
        meshlib.carve_submeshes(("clients",), (3,), 3)  # 9 > 8 devices
    with pytest.raises(ValueError):
        meshlib.carve_submeshes(("clients",), (-1,), 2)  # non-concrete
    with pytest.raises(ValueError):
        meshlib.carve_submeshes(("clients",), (2,), 0)


def test_submesh_plan_from_config_and_fallback(caplog, eight_devices):
    """Flags unset -> no SubmeshPlan object at all; a shape that cannot
    tile the fleet -> None WITH a warning (the control plane then keeps the
    PR-14 time-sliced gate); a valid shape without mt_submesh_jobs derives
    the job count from the fleet size."""
    assert meshlib.submesh_plan_from_config(_cfg()) is None
    assert not caplog.records

    plan = meshlib.submesh_plan_from_config(
        _cfg({"mt_submesh_shape": "clients:2"}))
    assert plan is not None and len(plan) == 4  # 8 devices / 2 per job

    with caplog.at_level("WARNING", logger="fedml_tpu.parallel.mesh"):
        bad = _cfg({"mt_submesh_shape": "clients:3", "mt_submesh_jobs": 4})
        assert meshlib.submesh_plan_from_config(bad) is None
    assert any("falling back" in r.getMessage() for r in caplog.records)

    # the plane built from the rejected config keeps slots semantics
    plane = MultiTenantControlPlane(slots=2, base_cfg=bad)
    try:
        assert plane.plan is None
        assert plane.slots == 2
        assert plane.scheduler.plan is None
    finally:
        plane.close()


def test_flags_unset_no_plan_no_lease_metrics():
    """Regression pin: without the mt_submesh flags the scheduler is the
    PR-14 time-sliced gate — no plan, no lease, slot grants metered as slot
    grants and NEVER as lease grants, submesh gauge at zero."""
    rt = ServerRuntime(name="t-noplan")
    sched = GangScheduler(rt, slots=1)
    lease_metric = obsreg.REGISTRY.get("fedml_fleet_lease_grants_total")
    l0 = lease_metric.value(job="np")
    try:
        assert sched.plan is None
        assert obsreg.REGISTRY.get("fedml_fleet_submeshes").value() == 0.0
        job = object()
        sched.register(job, "np")
        assert sched.lease_of(job) is None
        evt = threading.Event()
        sched.request(job, evt.set)
        assert evt.wait(5.0)
        sched.release(job)
        assert sched.stats["np"]["grants"] == 1
        assert lease_metric.value(job="np") - l0 == 0.0
    finally:
        rt.close()


# ---------------------------------------------------------------------------
# token-bucket quota: throttled, never starved
# ---------------------------------------------------------------------------

def test_quota_throttled_tenant_resumes_after_refill():
    """A tenant past its burst is deferred (metered as throttled) while a
    sibling with tokens is granted FIRST despite arriving later — and the
    throttled tenant's grant arrives on its own once the bucket refills
    (the refill timer re-pumps; nobody nudges the scheduler)."""
    rt = ServerRuntime(name="t-quota")
    sched = GangScheduler(rt, slots=1, quota_burst=2.0, quota_refill_s=0.3)
    throttled_metric = obsreg.REGISTRY.get("fedml_fleet_quota_throttled_total")
    t0 = throttled_metric.value(job="qa")
    a, b = object(), object()
    sched.register(a, "qa")
    sched.register(b, "qb")
    try:
        # drain A's bucket with two immediate rounds
        for _ in range(2):
            evt = threading.Event()
            sched.request(a, evt.set)
            assert evt.wait(5.0)
            sched.release(a)
        # A (empty bucket) requests BEFORE B (full bucket): B wins the slot,
        # A is metered throttled — capped, not starved
        order = []
        ea, eb = threading.Event(), threading.Event()
        sched.request(a, lambda: (order.append("a"), ea.set()))
        sched.request(b, lambda: (order.append("b"), eb.set()))
        assert eb.wait(5.0), "sibling with tokens must not wait on A's quota"
        assert order[0] == "b", order
        assert sched.stats["qa"]["throttled"] >= 1
        assert throttled_metric.value(job="qa") - t0 >= 1.0
        sched.release(b)
        # the refill timer resumes A without any further request/release
        assert ea.wait(5.0), "throttled tenant starved past the refill"
        sched.release(a)
        assert sched.stats["qa"]["grants"] == 3
        assert sched.stats["qb"]["grants"] == 1
    finally:
        rt.close()


# ---------------------------------------------------------------------------
# submesh-vs-dedicated bitwise parity
# ---------------------------------------------------------------------------

def _parity_cfg(i, run_id):
    # per-job learning rates: genuinely distinct jobs, so a single
    # cross-tenant fold leak would break the bitwise identity
    return Config(
        training_type="cross_silo", dataset="synthetic", model="lr",
        client_num_in_total=2, client_num_per_round=2, comm_round=2,
        epochs=1, batch_size=16, learning_rate=0.05 + 0.02 * i,
        partition_method="homo", synthetic_train_size=64,
        synthetic_test_size=32, frequency_of_the_test=0,
        compute_dtype="float32", metrics_jsonl_path="", run_id=run_id,
        extra={"streaming_aggregation": True, "server_shard_fold": True})


def _final_bytes(server):
    import jax

    from fedml_tpu.comm import wire

    return wire.encode_pytree(jax.device_get(server.aggregator.global_vars))


@pytest.mark.locksan
def test_submesh_vs_dedicated_bitwise_parity(eight_devices):
    """Two distinct sync jobs folding concurrently on disjoint 2-device
    leases produce finals BIT-FOR-BIT equal to each job run alone on a
    dedicated identically shaped mesh — submesh placement is invisible to
    the math, and zero bytes bleed across tenants."""
    import jax

    from fedml_tpu.comm.inproc import InProcRouter
    from fedml_tpu.cross_silo import build_client, build_server
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub

    plan = meshlib.carve_submeshes(("clients",), (2,), 2)
    plane = MultiTenantControlPlane(slots=1, plan=plan)
    fleet_finals = {}
    try:
        jobs = []
        for i in range(2):
            cfg = _parity_cfg(i, f"tfleet_par_c_{i}")
            fedml_tpu.init(cfg)
            jobs.append(plane.admit(cfg, job_id=f"t{i}"))
        # each job's server folds on its OWN lease, not the full mesh
        for i, job in enumerate(jobs):
            ids = {d.id for d in job.mesh.devices.flat}
            assert ids == {d.id for d in plan.lease(i).devices.flat}
        assert not ({d.id for d in jobs[0].mesh.devices.flat}
                    & {d.id for d in jobs[1].mesh.devices.flat})
        plane.start()
        out = plane.run_until_done(timeout=300.0)
        for i, job in enumerate(jobs):
            assert out["jobs"][f"t{i}"]["rounds"] == 2
            fleet_finals[i] = _final_bytes(job.server)
    finally:
        plane.close()
    assert fleet_finals[0] != fleet_finals[1], (
        "identical finals would blind the parity check to a leak")

    for i in range(2):
        cfg = _parity_cfg(i, f"tfleet_par_d_{i}")
        fedml_tpu.init(cfg)
        ds = loader.load(cfg)
        model = model_hub.create(cfg, ds.class_num)
        dmesh = meshlib.make_mesh(("clients",), (2,),
                                  devices=jax.devices()[:2])
        InProcRouter.reset(cfg.run_id)
        clients = [build_client(cfg, ds, model, rank=r, backend="INPROC")
                   for r in (1, 2)]
        for c in clients:
            c.run_in_thread()
        server = build_server(cfg, ds, model, backend="INPROC", mesh=dmesh)
        try:
            server.run_until_done(timeout=120.0)
            for c in clients:
                c.done.wait(5.0)
            assert fleet_finals[i] == _final_bytes(server), (
                f"job t{i}: submesh final != dedicated final")
        finally:
            for c in clients:
                c.finish()
            server.finish()
            InProcRouter.reset(cfg.run_id)


# ---------------------------------------------------------------------------
# tenant-routed gateway under live training
# ---------------------------------------------------------------------------

@pytest.mark.locksan
def test_gateway_routes_two_tenants_under_live_training(tmp_path, eight_devices):
    """Two async jobs train on disjoint submeshes while BOTH tenants serve
    through one gateway: zero dropped requests, every response tagged with
    the requested tenant, and every served version attributable to that
    tenant's own manifest (the tenants publish DIFFERENT version counts, so
    a cross-tenant route would surface as an impossible version)."""
    from fedml_tpu.cross_silo.async_soak import _soak_config
    from fedml_tpu.serving.gateway import ServingGateway
    from fedml_tpu.serving.publisher import ManifestWatcher
    from fedml_tpu.serving.worker import ServingWorker

    pub = str(tmp_path / "pub")
    versions = {"t0": 3, "t1": 2}
    plane = MultiTenantControlPlane(
        slots=1, journal_root=str(tmp_path / "journals"),
        plan=meshlib.carve_submeshes(("clients",), (2,), 2))
    workers, gw = [], None
    try:
        for i, (jid, nver) in enumerate(versions.items()):
            cfg = _soak_config(
                f"tfleet_gw_{i}", 6, 3, 3, nver, staleness_exponent=0.5,
                redispatch_timeout_s=5.0,
                extra_flags={"server_shard_fold": True,
                             "model_publish_dir": pub})
            fedml_tpu.init(cfg)
            job = plane.admit(cfg, job_id=jid, build_clients=False)
            plane.attach_sim_fleet(job, drop_prob=0.0, latency_mean_s=0.08,
                                   latency_sigma=0.25, seed=i, workers=2)
        plane.start()
        gw = ServingGateway(max_batch=8, flush_ms=1.0)
        for jid in versions:
            w = ServingWorker("lr", 10, publish_dir=os.path.join(pub, f"job_{jid}"),
                              max_batch=16, flush_ms=1.0, poll_s=0.02,
                              bootstrap_timeout_s=120.0)
            workers.append(w)
            gw.add_tenant(jid, port=w.start(block=False),
                          publish_dir=os.path.join(pub, f"job_{jid}"))
        gport = gw.start(block=False)
        feat = workers[0].predictor.feature_shape[0]

        def ask(tenant):
            body = json.dumps({"tenant": tenant,
                               "inputs": [[0.0] * feat]}).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{gport}/predict", data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30.0) as r:
                return json.loads(r.read())

        seen = {jid: set() for jid in versions}
        dropped = 0
        while not all(j.server.done.is_set() for j in plane.jobs.values()):
            for jid in versions:
                try:
                    out = ask(jid)
                    assert out["tenant"] == jid, out
                    seen[jid].add(int(out["version"]))
                except (urllib.error.URLError, OSError):
                    dropped += 1
            time.sleep(0.01)
        out = plane.run_until_done(timeout=300.0)
        for jid, nver in versions.items():
            assert out["jobs"][jid]["rounds"] == nver, out
        assert dropped == 0
        # final state: each tenant serves exactly its own manifest's version
        for (jid, nver), w in zip(versions.items(), workers):
            manifest = ManifestWatcher(os.path.join(pub, f"job_{jid}")
                                       ).read_manifest() or {}
            assert int(manifest.get("version", -1)) == nver, (jid, manifest)
            assert str(manifest.get("run_id", "")).endswith(f"_job_{jid}")
            deadline = time.time() + 10.0
            while w.served_version < nver and time.time() < deadline:
                time.sleep(0.02)
            final = ask(jid)
            assert final["version"] == nver, (jid, final)
            seen[jid].add(int(final["version"]))
            # attribution: every version this tenant ever served exists in
            # ITS publish history (0..nver) — t1 answering t0's version 3
            # would fail here
            assert seen[jid] <= set(range(nver + 1)), (jid, seen)
            lane = gw.stats()["tenants"][jid]
            assert lane["forwarded"] > 0 and lane["last_version"] == nver
        # an unknown tenant is refused, never misrouted
        with pytest.raises(urllib.error.HTTPError) as exc:
            ask("ghost")
        assert exc.value.code == 404
    finally:
        if gw is not None:
            gw.stop()
        for w in workers:
            w.stop()
        plane.close()
