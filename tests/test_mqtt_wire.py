"""Real-socket MQTT proof (round-3 verdict item 3).

The in-repo MQTT 3.1.1 broker (`comm/mqtt_wire.py`) + socket client replace
"adapter code exists" with "adapter works": every test here moves real MQTT
frames over real loopback TCP — zero injected fakes.  The e2e mirrors the
reference CI shape (`tests/cross-silo/run_cross_silo.sh:1-27`: broker + S3),
with payloads on the in-repo HTTP object store.
"""

import threading
import time

import pytest

from .conftest import tiny_config


@pytest.fixture
def broker():
    from fedml_tpu.comm.mqtt_wire import MiniMqttBroker

    b = MiniMqttBroker()
    b.start()
    yield b
    b.stop()


def _client(broker, cid, **kw):
    from fedml_tpu.comm.mqtt_wire import SocketMqttClient

    return SocketMqttClient("127.0.0.1", broker.port, cid, **kw)


def _wait(pred, timeout=10.0, msg="condition"):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {msg}")


# ---------------------------------------------------------------------------
# wire level
# ---------------------------------------------------------------------------

def test_pubsub_roundtrip_and_wildcards(broker):
    got = []
    a, b = _client(broker, "a"), _client(broker, "b")
    a.connect()
    b.connect()
    try:
        a.subscribe("fl/1/exact", lambda t, p: got.append(("exact", t, p)))
        a.subscribe("fl/+/plus", lambda t, p: got.append(("plus", t, p)))
        a.subscribe("deep/#", lambda t, p: got.append(("hash", t, p)))
        time.sleep(0.2)  # SUBACKs land
        b.publish("fl/1/exact", b"\x00\x01binary\xff")  # QoS1: blocks for PUBACK
        b.publish("fl/42/plus", b"p")
        b.publish("deep/x/y/z", b"h")
        b.publish("fl/2/exact", b"MISS")  # matches nothing
        _wait(lambda: len(got) >= 3, msg="3 deliveries")
        assert ("exact", "fl/1/exact", b"\x00\x01binary\xff") in got
        assert ("plus", "fl/42/plus", b"p") in got
        assert ("hash", "deep/x/y/z", b"h") in got
        assert not any(p == b"MISS" for _, _, p in got)
    finally:
        a.disconnect()
        b.disconnect()


def test_will_fires_on_abrupt_loss_only(broker):
    status = []
    watcher = _client(broker, "watcher")
    watcher.connect()
    try:
        watcher.subscribe("status", lambda t, p: status.append(p))
        time.sleep(0.2)

        doomed = _client(broker, "doomed")
        doomed.will_set("status", b"doomed-OFFLINE")
        doomed.connect()
        _wait(lambda: broker.session_count() == 2, msg="doomed connected")
        doomed._stopping = True  # silence its reconnect loop for the kick
        broker.kick("doomed")  # abrupt loss -> will fires
        _wait(lambda: b"doomed-OFFLINE" in status, msg="will delivery")

        polite = _client(broker, "polite")
        polite.will_set("status", b"polite-OFFLINE")
        polite.connect()
        _wait(lambda: broker.session_count() == 2, msg="polite connected")
        polite.disconnect()  # graceful -> will discarded
        time.sleep(0.3)
        assert b"polite-OFFLINE" not in status
    finally:
        watcher.disconnect()


def test_reconnect_resubscribes_and_traffic_resumes(broker):
    """Kill the subscriber's socket broker-side: the client must reconnect,
    replay its subscriptions, and receive traffic again — the clean-session
    trap the round-3 verdict wanted proven on a real socket."""
    got = []
    sub = _client(broker, "sub", reconnect_delay=0.05)
    pub = _client(broker, "pub")
    sub.connect()
    pub.connect()
    try:
        sub.subscribe("fl/round", lambda t, p: got.append(p))
        time.sleep(0.2)
        pub.publish("fl/round", b"before")
        _wait(lambda: b"before" in got, msg="pre-kick delivery")

        broker.kick("sub")
        _wait(lambda: sub.reconnects >= 1, msg="client reconnect")
        time.sleep(0.2)  # re-SUBSCRIBE lands
        pub.publish("fl/round", b"after")
        _wait(lambda: b"after" in got, msg="post-reconnect delivery")
        assert sub.reconnects >= 1
    finally:
        sub.disconnect()
        pub.disconnect()


def test_qos2_exactly_once_roundtrip(broker):
    """QoS2 publish completes the PUBREC/PUBREL/PUBCOMP handshake and the
    subscriber sees the message exactly once."""
    got = []
    sub, pub = _client(broker, "q2sub"), _client(broker, "q2pub")
    sub.connect()
    pub.connect()
    try:
        sub.subscribe("fl/q2", lambda t, p: got.append(p))
        time.sleep(0.2)
        for i in range(5):
            pub.publish("fl/q2", f"m{i}".encode(), qos=2)  # blocks to PUBCOMP
        _wait(lambda: len(got) >= 5, msg="qos2 deliveries")
        time.sleep(0.2)
        assert got == [f"m{i}".encode() for i in range(5)], got
        # handshake state fully drained on both ends
        assert not pub._qos2_recs and not pub._qos2_comps
        assert not sub._qos2_in
    finally:
        sub.disconnect()
        pub.disconnect()


def test_qos2_duplicate_publish_delivered_once(broker):
    """A redelivered QoS2 PUBLISH (same pid, before PUBREL) must reach the
    subscriber exactly once — the stash-until-PUBREL contract.  Speaks the
    raw wire so the duplicate is byte-exact."""
    import socket
    import struct

    from fedml_tpu.comm import mqtt_wire as w

    got = []
    sub = _client(broker, "dupsub")
    sub.connect()
    try:
        sub.subscribe("fl/dup", lambda t, p: got.append(p))
        time.sleep(0.2)

        raw = socket.create_connection(("127.0.0.1", broker.port), timeout=5)
        body = w._enc_str("MQTT") + bytes([4, 0x02]) + struct.pack(">H", 30) + w._enc_str("rawdup")
        raw.sendall(w._packet(w.CONNECT, 0, body))
        assert w._read_packet(raw)[0] == w.CONNACK

        pub_body = w._enc_str("fl/dup") + struct.pack(">H", 7) + b"once"
        raw.sendall(w._packet(w.PUBLISH, 0x04, pub_body))          # qos2 pid=7
        assert w._read_packet(raw)[0] == w.PUBREC
        raw.sendall(w._packet(w.PUBLISH, 0x0C, pub_body))          # DUP redelivery
        assert w._read_packet(raw)[0] == w.PUBREC                  # idempotent
        time.sleep(0.3)
        assert got == [], "must not deliver before PUBREL"
        raw.sendall(w._packet(w.PUBREL, 0x02, struct.pack(">H", 7)))
        assert w._read_packet(raw)[0] == w.PUBCOMP
        _wait(lambda: got == [b"once"], msg="exactly-once delivery")
        # duplicate PUBREL after release: PUBCOMP again, still no re-delivery
        raw.sendall(w._packet(w.PUBREL, 0x02, struct.pack(">H", 7)))
        assert w._read_packet(raw)[0] == w.PUBCOMP
        time.sleep(0.3)
        assert got == [b"once"]
        raw.close()
    finally:
        sub.disconnect()


def test_session_takeover_closes_old_connection(broker):
    first = _client(broker, "same-id")
    first.connect()
    first._stopping = True  # a takeover must not trigger its reconnect loop
    second = _client(broker, "same-id")
    second.connect()
    try:
        _wait(lambda: broker.session_count() == 1, msg="takeover")
    finally:
        second.disconnect()


def test_http_object_store_roundtrip():
    from fedml_tpu.comm.object_store_http import HttpObjectStore, MiniObjectStoreServer

    srv = MiniObjectStoreServer()
    srv.start()
    try:
        store = HttpObjectStore(srv.url)
        blob = bytes(range(256)) * 200  # 51 KB binary
        assert store.put("run/abc", blob) == "run/abc"
        assert store.get("run/abc") == blob
        # missing key raises KeyError — the InMemoryObjectStore contract the
        # HTTP store substitutes for (callers handle missing-payload races)
        with pytest.raises(KeyError):
            store.get("run/missing")
    finally:
        srv.stop()


def test_poisoned_message_does_not_kill_receive_loop():
    """A store-ref to a never-PUT key (missing-payload race -> KeyError) or
    garbage framing (ValueError) must be dropped, not kill the comm manager's
    receive thread — a dead loop silently drops every later FL message."""
    import json

    from fedml_tpu.comm.message import Message
    from fedml_tpu.comm.mqtt_real import TcpMqttBroker
    from fedml_tpu.comm.mqtt_s3 import MqttS3CommManager
    from fedml_tpu.comm.mqtt_wire import MiniMqttBroker, SocketMqttClient
    from fedml_tpu.comm.object_store_http import (
        HttpObjectStore,
        MiniObjectStoreServer,
    )

    broker = MiniMqttBroker()
    broker.start()
    store_srv = MiniObjectStoreServer()
    store_srv.start()
    mgr = peer = evil = None
    try:
        got = []

        class Obs:
            def receive_message(self, t, m):
                got.append((t, m.get("k")))

        mgr = MqttS3CommManager(
            "poison", 0,
            broker=TcpMqttBroker("127.0.0.1", broker.port, client_id="poison_0"),
            store=HttpObjectStore(store_srv.url),
        )
        mgr.add_observer(Obs())
        threading.Thread(target=mgr.handle_receive_message, daemon=True).start()
        time.sleep(0.3)
        evil = SocketMqttClient("127.0.0.1", broker.port, "evil")
        evil.connect()
        evil.publish(
            "fedml_poison_to_0",
            b"R" + json.dumps({"store_key": "poison/never-put"}).encode(),
        )
        evil.publish("fedml_poison_to_0", b"D\xde\xad\xbe\xef")
        time.sleep(0.3)
        peer = MqttS3CommManager(
            "poison", 1,
            broker=TcpMqttBroker("127.0.0.1", broker.port, client_id="poison_1"),
            store=HttpObjectStore(store_srv.url),
        )
        m = Message(7, 1, 0)
        m.add("k", "alive")
        peer.send_message(m)
        _wait(lambda: bool(got), msg="post-poison delivery")
        assert got[0] == (7, "alive")
    finally:
        # shut the wire clients down BEFORE the broker dies, or their
        # reconnect loops spin at 10 Hz against the closed port for the
        # rest of the pytest session (and could attach to a reused port)
        if mgr is not None:
            mgr.stop_receive_message()
            mgr.broker.disconnect()
        if peer is not None:
            peer.broker.disconnect()
        if evil is not None:
            evil.disconnect()
        broker.stop()
        store_srv.stop()


# ---------------------------------------------------------------------------
# cross-silo e2e over the real transport — zero fakes
# ---------------------------------------------------------------------------

def test_cross_silo_fedavg_over_real_mqtt(eight_devices, monkeypatch):
    """Full cross-silo FedAvg over real MQTT TCP framing + real HTTP payload
    store, INCLUDING a mid-run abrupt client kill: the client reconnects,
    re-subscribes, and the run completes every round (bounded-wait quorum
    covers any broadcast lost during the dead window)."""
    import fedml_tpu
    from fedml_tpu.comm.mqtt_wire import MiniMqttBroker
    from fedml_tpu.comm.object_store_http import MiniObjectStoreServer
    from fedml_tpu.cross_silo import build_client, build_server
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub

    # the tiny lr model's ~1.3 KB messages would all ride inline at the
    # default 8 KB threshold; lower it so model payloads REALLY cross the
    # HTTP store (the reference's S3 offload path)
    from fedml_tpu.comm import mqtt_s3 as mqtt_s3_mod

    monkeypatch.setattr(mqtt_s3_mod, "PAYLOAD_INLINE_LIMIT", 512)

    broker = MiniMqttBroker()
    broker.start()
    store_srv = MiniObjectStoreServer()
    store_srv.start()
    run_id = "mqtt-e2e"
    cfg = tiny_config(
        training_type="cross_silo", client_num_in_total=2, client_num_per_round=2,
        comm_round=4, learning_rate=0.3, frequency_of_the_test=2, run_id=run_id,
    )
    cfg.extra = {
        "mqtt_host": "127.0.0.1", "mqtt_port": broker.port,
        "object_store_url": store_srv.url,
        "straggler_timeout_s": 3.0, "straggler_quorum_frac": 0.5,
    }
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)
    clients = [build_client(cfg, ds, model, rank=r, backend="MQTT_S3") for r in (1, 2)]
    for c in clients:
        c.run_in_thread()
    server = build_server(cfg, ds, model, backend="MQTT_S3")

    kicked = threading.Event()

    def kick_after_first_round():
        _wait(lambda: len(server.history) >= 1, timeout=60, msg="round 1")
        broker.kick(f"{run_id}_2")  # abrupt loss of client 2 mid-run
        kicked.set()

    threading.Thread(target=kick_after_first_round, daemon=True).start()
    try:
        history = server.run_until_done(timeout=120.0)
    finally:
        for c in clients:
            c.finish()
        broker.stop()
        store_srv.stop()

    assert len(history) == 4, history
    assert kicked.is_set()
    # the kicked client's wire session really reconnected
    mqtt_client = clients[1].com_manager.broker._client
    assert mqtt_client.reconnects >= 1
    # model payloads (>8 KB) actually rode the HTTP store
    assert len(store_srv._blobs) > 0
    # learning happened over the real transport
    accs = [h["test_acc"] for h in history if "test_acc" in h]
    assert accs and accs[-1] > 0.3, accs
