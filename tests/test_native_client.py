"""C++ polyglot client + native LightSecAgg kernel conformance (VERDICT
item 5, SURVEY.md §2.13).

The native binary (``native/fedml_native``) must
1. reproduce the Python finite-field kernels bit-exactly, and
2. complete a real multi-round FedAvg run against the Python cross-silo
   server over the TCP transport, training softmax regression in C++.
"""

import os
import shutil
import socket
import struct
import subprocess
import sys
import time

import numpy as np
import pytest

from .conftest import tiny_config

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_NATIVE_DIR = os.path.join(_REPO, "native")
_BINARY = os.path.join(_NATIVE_DIR, "fedml_native")


@pytest.fixture(scope="module")
def native_binary():
    if shutil.which("g++") is None:
        pytest.skip("no g++ in environment")
    res = subprocess.run(["make", "-C", _NATIVE_DIR], capture_output=True, text=True)
    assert res.returncode == 0, res.stderr[-2000:]
    assert os.path.exists(_BINARY)
    return _BINARY


def test_field_kernel_conformance(native_binary):
    """COEFFS/SHARES/DECODED/INVERSES from C++ == trust/secagg math."""
    from fedml_tpu.trust.secagg.field import DEFAULT_PRIME, gen_lagrange_coeffs, mod_inverse
    from fedml_tpu.trust.secagg.lightsecagg import LightSecAggProtocol

    n, t, u, s = 5, 2, 3, 4
    k = u - t
    rng = np.random.RandomState(7)
    mask = rng.randint(0, DEFAULT_PRIME, size=k * s, dtype=np.int64)
    noise = rng.randint(0, DEFAULT_PRIME, size=t * s, dtype=np.int64)

    stdin = " ".join(map(str, mask.tolist() + noise.tolist()))
    res = subprocess.run(
        [native_binary, "fieldtest", str(n), str(t), str(u), str(s)],
        input=stdin, capture_output=True, text=True, timeout=60,
    )
    assert res.returncode == 0, res.stderr
    sections: dict[str, list[list[int]]] = {}
    current = None
    for line in res.stdout.splitlines():
        if line in ("COEFFS", "SHARES", "DECODED", "INVERSES"):
            current = line
            sections[current] = []
        elif line.strip():
            sections[current].append([int(v) for v in line.split()])

    proto = LightSecAggProtocol(n, t, u)
    W_py = gen_lagrange_coeffs(proto.betas, proto.alphas)
    np.testing.assert_array_equal(np.array(sections["COEFFS"]), W_py)

    shares_py = proto.encode_mask(mask, noise=noise)
    np.testing.assert_array_equal(np.array(sections["SHARES"]), shares_py)

    # single-mask decode must return the mask itself (both languages)
    decoded_cpp = np.array(sections["DECODED"]).ravel()
    np.testing.assert_array_equal(decoded_cpp, mask)
    agg = {i: shares_py[i] for i in range(u)}
    decoded_py = proto.decode_aggregate_mask(agg, len(mask))
    np.testing.assert_array_equal(decoded_cpp, decoded_py)

    for v, inv in sections["INVERSES"]:
        assert inv == mod_inverse(v), v


def _write_shard(path, x, y):
    x = np.ascontiguousarray(x, dtype=np.float32)
    y = np.ascontiguousarray(y, dtype=np.int32)
    c = int(y.max()) + 1
    with open(path, "wb") as f:
        f.write(struct.pack("<III", x.shape[0], x.shape[1], max(c, 10)))
        f.write(x.tobytes())
        f.write(y.tobytes())


def _free_port_block(n: int, attempts: int = 64) -> int:
    """Find a base port such that base..base+n-1 are all currently bindable.
    The TCP transport derives each rank's listener as base+rank, so the block
    must be consecutive — a fixed base (the round-2 flake) collides with
    TIME_WAIT leftovers under full-suite load."""
    rng = np.random.RandomState(os.getpid() ^ int(time.time()))
    for _ in range(attempts):
        base = int(rng.randint(20000, 60000))
        socks = []
        try:
            for off in range(n):
                # probe EXACTLY what the transport will bind (wildcard, no
                # REUSEADDR): a loopback probe with REUSEADDR can succeed
                # where the real 0.0.0.0 bind then fails on a live listener
                s = socket.socket()
                s.bind(("0.0.0.0", base + off))
                socks.append(s)
            return base
        except OSError:
            continue
        finally:
            for s in socks:
                s.close()
    raise RuntimeError("no free port block found")


def _wait_listening(port, timeout=60.0):
    t0 = time.time()
    while time.time() - t0 < timeout:
        with socket.socket() as s:
            s.settimeout(0.2)
            try:
                s.connect(("127.0.0.1", port))
                return True
            except OSError:
                time.sleep(0.1)
    return False


def test_cpp_client_completes_fedavg_rounds(native_binary, tmp_path, eight_devices):
    """Two C++ clients + the Python server complete a 3-round FedAvg run over
    TCP; accuracy improves, proving the C++ side genuinely trains."""
    import fedml_tpu
    from fedml_tpu.cross_silo import build_server
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub

    base_port = _free_port_block(3)
    cfg = tiny_config(
        client_num_in_total=2, client_num_per_round=2, comm_round=3,
        batch_size=16, synthetic_train_size=320, synthetic_test_size=160,
        frequency_of_the_test=1,
        extra={"tcp_base_port": base_port},
    )
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)

    procs = []
    try:
        for rank in (1, 2):
            shard_path = tmp_path / f"shard_{rank}.bin"
            ix = ds.client_idx[rank - 1]
            _write_shard(shard_path, ds.train_x[ix].reshape(len(ix), -1), ds.train_y[ix])
            procs.append(subprocess.Popen(
                [native_binary, "client", "--rank", str(rank),
                 "--base-port", str(base_port), "--data", str(shard_path),
                 "--lr", "0.3", "--epochs", "1", "--batch", "16"],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            ))
        for rank in (1, 2):
            assert _wait_listening(base_port + rank), f"client {rank} never bound"

        server = build_server(cfg, ds, model, backend="TCP")
        # generous: the 1-core CI box runs jit compiles from sibling tests
        history = server.run_until_done(timeout=300.0)
        assert len(history) == 3
        accs = [h["test_acc"] for h in history if "test_acc" in h]
        assert accs[-1] > 0.35, accs  # C++ SGD genuinely learned
        for p in procs:
            assert p.wait(timeout=20) == 0, p.stderr.read()[-2000:]
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
