"""Algorithm-zoo coverage: every registered optimizer runs end-to-end on the
mesh backend and learns on the tiny synthetic task.

The reference covers algorithm math only for security ops (SURVEY.md §4);
here each federated optimizer is exercised through the full jitted round —
including the stateful ones (SCAFFOLD control variates, FedDyn lambda,
EF-TopK residuals) whose per-client state rides the device scatter/gather.
"""

import numpy as np
import pytest

from .conftest import tiny_config


ALGOS = [
    "FedAvg",
    "FedAvg_seq",
    "FedOpt",
    "FedProx",
    "FedNova",
    "FedDyn",
    "SCAFFOLD",
    "Mime",
    "FedSGD",
]


@pytest.mark.parametrize("algo", ALGOS)
def test_algorithm_runs_and_learns(algo, eight_devices):
    import fedml_tpu

    kwargs = dict(federated_optimizer=algo, comm_round=6, learning_rate=0.3, client_num_per_round=8)
    if algo == "FedOpt":
        kwargs.update(server_optimizer="adam", server_lr=0.03)
    if algo == "FedSGD":
        kwargs.update(server_lr=0.5, server_optimizer="sgd", comm_round=12)
    history = fedml_tpu.run_simulation(tiny_config(**kwargs))
    accs = [h["test_acc"] for h in history if "test_acc" in h]
    assert np.isfinite(accs).all()
    assert accs[-1] > 0.25, f"{algo}: acc stuck at {accs}"


@pytest.mark.parametrize("compression", ["topk", "eftopk", "quantize", "qsgd"])
def test_fedsgd_compression(compression, eight_devices):
    import fedml_tpu

    cfg = tiny_config(
        federated_optimizer="FedSGD",
        compression=compression,
        compression_ratio=0.3,
        server_lr=0.5,
        comm_round=10,
        client_num_per_round=8,
    )
    history = fedml_tpu.run_simulation(cfg)
    accs = [h["test_acc"] for h in history if "test_acc" in h]
    assert np.isfinite(accs).all()
    assert accs[-1] > 0.15, f"{compression}: {accs}"


def test_scaffold_state_persists(eight_devices):
    """Control variates must be non-zero after training (state round-trip)."""
    import jax
    import fedml_tpu
    from fedml_tpu.runner import FedMLRunner

    cfg = tiny_config(federated_optimizer="SCAFFOLD", comm_round=2, client_num_per_round=4)
    fedml_tpu.init(cfg)
    runner = FedMLRunner(cfg)
    runner.run()
    sim = runner.runner
    leaves = jax.tree_util.tree_leaves(sim.client_states)
    total = sum(float(abs(l).sum()) for l in leaves)
    assert total > 0, "SCAFFOLD c_i never updated"
    server_c = sum(float(abs(l).sum()) for l in jax.tree_util.tree_leaves(sim.server_state))
    assert server_c > 0, "SCAFFOLD global c never updated"
