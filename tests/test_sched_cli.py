"""Scheduler vertical + CLI tests: package -> agent -> subprocess -> status DB
-> logs, mirroring the reference launch pipeline (SURVEY.md §3.4) on the
local spool transport."""

import json
import os
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest


def _make_workspace(tmp_path: Path, body: str, job: str = "python main.py") -> Path:
    ws = tmp_path / "workspace"
    ws.mkdir()
    (ws / "main.py").write_text(body)
    job_yaml = tmp_path / "job.yaml"
    job_yaml.write_text(
        f"workspace: workspace\njob: \"{job}\"\n"
        "bootstrap: \"echo bootstrap-ran\"\n"
        "job_name: test_job\n"
        "computing:\n  minimum_num_gpus: 1\n"
    )
    return job_yaml


def test_launch_agent_pipeline(tmp_path):
    from fedml_tpu.sched.agent import FedMLAgent
    from fedml_tpu.sched.launch import FedMLLaunchManager

    spool = tmp_path / "spool"
    job_yaml = _make_workspace(tmp_path, "print('hello-from-job')\n")
    mgr = FedMLLaunchManager(str(spool))
    run_id = mgr.launch_job(str(job_yaml))
    assert run_id in mgr.list_queue()

    agent = FedMLAgent(str(spool))
    row = agent.wait_for(run_id, timeout=60)
    assert row["status"] == "FINISHED", row
    logs = agent.logs(run_id)
    assert "bootstrap-ran" in logs
    assert "hello-from-job" in logs


def test_agent_marks_failed_job(tmp_path):
    from fedml_tpu.sched.agent import FedMLAgent
    from fedml_tpu.sched.launch import FedMLLaunchManager

    spool = tmp_path / "spool"
    job_yaml = _make_workspace(tmp_path, "import sys; sys.exit(3)\n")
    run_id = FedMLLaunchManager(str(spool)).launch_job(str(job_yaml))
    agent = FedMLAgent(str(spool))
    row = agent.wait_for(run_id, timeout=60)
    assert row["status"] == "FAILED"
    assert row["returncode"] == 3


def test_resource_matcher():
    from fedml_tpu.sched.agent import match_resources

    jobs = [
        {"run_id": "big", "computing": {"minimum_num_gpus": 4}},
        {"run_id": "small", "computing": {"minimum_num_gpus": 1}},
    ]
    agents = [{"id": "a8", "num_devices": 8}, {"id": "a1", "num_devices": 1}]
    asg = match_resources(jobs, agents)
    assert asg["big"] == "a8"
    assert asg["small"] in ("a8", "a1")


def test_cli_env_version_and_launch(tmp_path):
    from fedml_tpu import cli

    rc = cli.main(["version"])
    assert rc == 0
    job_yaml = _make_workspace(tmp_path, "print('cli-job')\n")
    spool = str(tmp_path / "spool")
    rc = cli.main(["--spool", spool, "launch", str(job_yaml)])
    assert rc == 0
    rc = cli.main(["--spool", spool, "jobs"])
    assert rc == 0


def test_cli_run_subprocess(tmp_path):
    """The reference CI pattern: run the tiny recipe via the CLI, assert exit
    code 0 (SURVEY.md §4 'smoke_test_pip_cli_sp')."""
    cfg = tmp_path / "fedml_config.yaml"
    cfg.write_text(
        "common_args:\n  federated_optimizer: FedAvg\n"
        "data_args:\n  dataset: synthetic\n  partition_method: homo\n"
        "  synthetic_train_size: 320\n  synthetic_test_size: 64\n"
        "model_args:\n  model: lr\n"
        "train_args:\n  client_num_in_total: 4\n  client_num_per_round: 2\n"
        "  comm_round: 2\n  batch_size: 16\n  learning_rate: 0.3\n"
        "device_args:\n  compute_dtype: float32\n"
        "validation_args:\n  frequency_of_the_test: 2\n"
    )
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    out = subprocess.run(
        [sys.executable, "-m", "fedml_tpu.cli", "run", "--cf", str(cfg)],
        capture_output=True, text=True, timeout=300, env=env,
        cwd=str(Path(__file__).resolve().parent.parent),
    )
    assert out.returncode == 0, out.stderr[-2000:]
    last = json.loads(out.stdout.strip().splitlines()[-1])
    assert "test_acc" in last
