"""Scheduler vertical + CLI tests: package -> agent -> subprocess -> status DB
-> logs, mirroring the reference launch pipeline (SURVEY.md §3.4) on the
local spool transport."""

import json
import os
import subprocess
import sys
import time
from pathlib import Path

import numpy as np
import pytest


def _make_workspace(tmp_path: Path, body: str, job: str = "python main.py") -> Path:
    ws = tmp_path / "workspace"
    ws.mkdir()
    (ws / "main.py").write_text(body)
    job_yaml = tmp_path / "job.yaml"
    job_yaml.write_text(
        f"workspace: workspace\njob: \"{job}\"\n"
        "bootstrap: \"echo bootstrap-ran\"\n"
        "job_name: test_job\n"
        "computing:\n  minimum_num_gpus: 1\n"
    )
    return job_yaml


def test_launch_agent_pipeline(tmp_path):
    from fedml_tpu.sched.agent import FedMLAgent
    from fedml_tpu.sched.launch import FedMLLaunchManager

    spool = tmp_path / "spool"
    job_yaml = _make_workspace(tmp_path, "print('hello-from-job')\n")
    mgr = FedMLLaunchManager(str(spool))
    run_id = mgr.launch_job(str(job_yaml))
    assert run_id in mgr.list_queue()

    agent = FedMLAgent(str(spool))
    row = agent.wait_for(run_id, timeout=60)
    assert row["status"] == "FINISHED", row
    logs = agent.logs(run_id)
    assert "bootstrap-ran" in logs
    assert "hello-from-job" in logs


def test_agent_marks_failed_job(tmp_path):
    from fedml_tpu.sched.agent import FedMLAgent
    from fedml_tpu.sched.launch import FedMLLaunchManager

    spool = tmp_path / "spool"
    job_yaml = _make_workspace(tmp_path, "import sys; sys.exit(3)\n")
    run_id = FedMLLaunchManager(str(spool)).launch_job(str(job_yaml))
    agent = FedMLAgent(str(spool))
    row = agent.wait_for(run_id, timeout=60)
    assert row["status"] == "FAILED"
    assert row["returncode"] == 3


def test_resource_matcher():
    from fedml_tpu.sched.agent import match_resources

    jobs = [
        {"run_id": "big", "computing": {"minimum_num_gpus": 4}},
        {"run_id": "small", "computing": {"minimum_num_gpus": 1}},
    ]
    agents = [{"id": "a8", "num_devices": 8}, {"id": "a1", "num_devices": 1}]
    asg = match_resources(jobs, agents)
    assert asg["big"] == "a8"
    assert asg["small"] in ("a8", "a1")


def test_resource_matcher_type_and_memory():
    """Matcher honors device type and memory (reference scheduler_matcher):
    unmatchable jobs stay out of the assignment."""
    from fedml_tpu.sched.agent import match_resources

    jobs = [
        {"run_id": "tpu-job", "computing": {"minimum_num_gpus": 2, "request_gpu_type": "tpu-v5e"}},
        {"run_id": "mem-hog", "computing": {"minimum_num_gpus": 1, "minimum_memory_gb": 64}},
        {"run_id": "impossible", "computing": {"minimum_num_gpus": 99}},
    ]
    agents = [
        {"id": "cpu-box", "num_devices": 8, "device_type": "cpu", "mem_gb": 16},
        {"id": "tpu-box", "num_devices": 4, "device_type": "tpu-v5e", "mem_gb": 128},
    ]
    asg = match_resources(jobs, agents)
    assert asg["tpu-job"] == "tpu-box"          # type must match exactly
    assert asg["mem-hog"] == "tpu-box"          # only box with 64+ GB
    assert "impossible" not in asg              # nobody has 99 devices
    # free_devices (not raw capacity) is what the matcher consumes
    asg2 = match_resources(
        [{"run_id": "j", "computing": {"minimum_num_gpus": 4}}],
        [{"id": "busy", "num_devices": 8, "free_devices": 2}],
    )
    assert asg2 == {}


def test_agent_claims_only_fitting_jobs(tmp_path):
    """An agent must leave a too-big job in the queue for a bigger agent
    (round-3 verdict item 5a: 'any agent takes any job' is the gap)."""
    import yaml

    from fedml_tpu.sched.agent import FedMLAgent, registered_agents
    from fedml_tpu.sched.launch import FedMLLaunchManager

    spool = tmp_path / "spool"
    ws = tmp_path / "ws"
    ws.mkdir()
    (ws / "main.py").write_text("print('ok')\n")
    import sys

    job = {
        "workspace": "ws", "job": f"{sys.executable} main.py",
        "computing": {"minimum_num_gpus": 4, "request_gpu_type": "tpu-v5e"},
    }
    ypath = tmp_path / "job.yaml"
    ypath.write_text(yaml.safe_dump(job))
    mgr = FedMLLaunchManager(str(spool))
    run_id = mgr.launch_job(str(ypath))

    small = FedMLAgent(str(spool), agent_id="small",
                       capacity={"num_devices": 1, "device_type": "tpu-v5e"})
    wrong_type = FedMLAgent(str(spool), agent_id="wrongtype",
                            capacity={"num_devices": 8, "device_type": "cpu"})
    assert small.sweep_once() == [] and wrong_type.sweep_once() == []
    assert mgr.list_queue() == [run_id], "job must stay queued"

    big = FedMLAgent(str(spool), agent_id="big",
                     capacity={"num_devices": 8, "device_type": "tpu-v5e"})
    assert big.free_devices() == 8
    claimed = big.sweep_once()
    assert claimed == [run_id]
    assert big.free_devices() == 4  # 4 devices held while the job runs
    row = big.wait_for(run_id, timeout=60)
    assert row["status"] == "FINISHED"
    big.sweep_once()
    assert big.free_devices() == 8  # released on reap

    # all three agents registered capacity + heartbeat in the spool
    recs = {r["id"]: r for r in registered_agents(str(spool))}
    assert set(recs) == {"small", "wrongtype", "big"}
    assert recs["big"]["num_devices"] == 8


def test_cli_env_version_and_launch(tmp_path):
    from fedml_tpu import cli

    rc = cli.main(["version"])
    assert rc == 0
    job_yaml = _make_workspace(tmp_path, "print('cli-job')\n")
    spool = str(tmp_path / "spool")
    rc = cli.main(["--spool", spool, "launch", str(job_yaml)])
    assert rc == 0
    rc = cli.main(["--spool", spool, "jobs"])
    assert rc == 0


def test_cli_run_subprocess(tmp_path):
    """The reference CI pattern: run the tiny recipe via the CLI, assert exit
    code 0 (SURVEY.md §4 'smoke_test_pip_cli_sp')."""
    cfg = tmp_path / "fedml_config.yaml"
    cfg.write_text(
        "common_args:\n  federated_optimizer: FedAvg\n"
        "data_args:\n  dataset: synthetic\n  partition_method: homo\n"
        "  synthetic_train_size: 320\n  synthetic_test_size: 64\n"
        "model_args:\n  model: lr\n"
        "train_args:\n  client_num_in_total: 4\n  client_num_per_round: 2\n"
        "  comm_round: 2\n  batch_size: 16\n  learning_rate: 0.3\n"
        "device_args:\n  compute_dtype: float32\n"
        "validation_args:\n  frequency_of_the_test: 2\n"
    )
    env = dict(os.environ)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("PALLAS_AXON_POOL_IPS", None)
    out = subprocess.run(
        [sys.executable, "-m", "fedml_tpu.cli", "run", "--cf", str(cfg)],
        capture_output=True, text=True, timeout=300, env=env,
        cwd=str(Path(__file__).resolve().parent.parent),
    )
    assert out.returncode == 0, out.stderr[-2000:]
    last = json.loads(out.stdout.strip().splitlines()[-1])
    assert "test_acc" in last


def test_cli_account_model_storage_diagnosis(tmp_path, eight_devices, monkeypatch):
    """The reference CLI verb surface in self-hosted semantics (VERDICT row 1):
    login/logout, model create/list/deploy, storage, device, cluster,
    diagnosis."""
    import json as _json

    import jax
    import jax.numpy as jnp

    from fedml_tpu import cli
    from fedml_tpu.models import model_hub
    from fedml_tpu.serving.deploy import save_params_card
    from .conftest import tiny_config

    monkeypatch.setattr(cli, "_cred_path", lambda: tmp_path / "creds.json")
    spool = str(tmp_path / "spool")

    assert cli.main(["--spool", spool, "login", "alice", "--api-key", "k1"]) == 0
    assert _json.loads((tmp_path / "creds.json").read_text())["account"] == "alice"
    assert cli.main(["--spool", spool, "logout"]) == 0
    assert not (tmp_path / "creds.json").exists()

    # model registry + deploy + predict through the scheduler
    cfg = tiny_config()
    model = model_hub.create(cfg, 10)
    variables = model.init({"params": jax.random.PRNGKey(0)}, jnp.zeros((1, 60)), train=True)
    params = save_params_card(variables, str(tmp_path / "lr.wire"))
    assert cli.main(["--spool", spool, "model", "create", "--name", "m1",
                     "--arch", "lr", "--classes", "10", "--params", params]) == 0
    assert cli.main(["--spool", spool, "model", "list"]) == 0
    assert cli.main(["--spool", spool, "model", "deploy", "--name", "m1",
                     "--endpoint", "e1", "--timeout", "120"]) == 0

    # storage roundtrip
    src = tmp_path / "blob.bin"
    src.write_bytes(b"hello")
    assert cli.main(["--spool", spool, "storage", "upload", str(src)]) == 0
    assert cli.main(["--spool", spool, "storage", "list"]) == 0
    out = tmp_path / "blob.out"
    assert cli.main(["--spool", spool, "storage", "download", "blob.bin",
                     "--output", str(out)]) == 0
    assert out.read_bytes() == b"hello"
    assert cli.main(["--spool", spool, "storage", "delete", "blob.bin"]) == 0

    assert cli.main(["--spool", spool, "device"]) == 0
    assert cli.main(["--spool", spool, "cluster"]) == 0
    assert cli.main(["--spool", spool, "diagnosis"]) == 0


def test_cli_federate_refuses_centralized(tmp_path, eight_devices):
    from fedml_tpu import cli

    cfg_yaml = tmp_path / "central.yaml"
    cfg_yaml.write_text(
        "common_args:\n  training_type: \"centralized\"\n"
        "data_args:\n  dataset: \"synthetic\"\n  synthetic_train_size: 64\n"
        "  synthetic_test_size: 32\nmodel_args:\n  model: \"lr\"\n"
        "train_args:\n  comm_round: 1\n  batch_size: 16\n"
    )
    assert cli.main(["federate", "--cf", str(cfg_yaml)]) == 2


def test_cli_storage_refuses_traversal(tmp_path):
    from fedml_tpu import cli

    spool = str(tmp_path / "spool")
    victim = tmp_path / "spool" / "jobs.sqlite"
    victim.parent.mkdir(parents=True)
    victim.write_text("precious")
    import pytest as _pt

    with _pt.raises(SystemExit):
        cli.main(["--spool", spool, "storage", "delete", "../jobs.sqlite"])
    assert victim.exists()


def test_compress_dispatch_qsgd_int8(eight_devices):
    import jax
    import jax.numpy as jnp

    from fedml_tpu.ops.compression import compress

    k = jax.random.PRNGKey(0)
    x = jax.random.normal(k, (3000,))
    out, _ = compress("qsgd_int8", x, key=k)
    assert out.shape == x.shape
    assert float(jnp.abs(out - x).max()) < 0.2  # one int8 step per block
