"""Distributed round tracing + metrics registry (ISSUE 1 tentpole).

- trace-context propagation across an INPROC cross-silo round: every client
  train span carries the SAME trace_id as the server's aggregate span for
  that round, and ``obs.report`` reconstructs the per-round span tree from
  the collector JSONL trail alone;
- Prometheus text-format invariants of ``MetricsRegistry.render()`` and the
  stdlib ``/metrics`` + ``/healthz`` endpoint round-trip;
- the comm receive loop's non-blocking transient-decode retry (healthy
  messages keep draining while a flaky payload backs off) with its registry
  counters;
- ``obs report`` timeline reconstruction from a recorded JSONL trail.
"""

import json
import queue
import threading
import time
import urllib.request

import pytest

from .conftest import tiny_config


# ---------------------------------------------------------------------------
# trace primitives


def test_span_parenting_and_wire_header():
    from fedml_tpu.comm.message import Message
    from fedml_tpu.obs import trace

    with trace.traced("round", round_idx=7) as round_span:
        msg = Message(3, 0, 1)
        trace.inject(msg, round_span)
        # wire round trip: the header survives encode/decode as JSON control
        decoded = Message.decode(msg.encode())
    header = trace.extract(decoded)
    assert header == {"trace_id": round_span.trace_id, "span_id": round_span.span_id}

    # receive side: activate the header, open a child span
    with trace.activate(header):
        with trace.traced("train", client_idx=2) as train_span:
            time.sleep(0.002)
    assert train_span.trace_id == round_span.trace_id
    assert train_span.parent_id == round_span.span_id
    rec = train_span.to_record()
    assert rec["kind"] == "span" and rec["client_idx"] == 2
    assert rec["dur_s"] >= 0.002

    # no ambient context -> fresh trace; inject never overwrites a header
    with trace.traced("orphan") as orphan:
        pass
    assert orphan.parent_id is None and orphan.trace_id != round_span.trace_id
    trace.inject(decoded, orphan)
    assert trace.extract(decoded)["trace_id"] == round_span.trace_id


def test_traced_decorator_nests_and_sinks():
    from fedml_tpu.obs import trace

    records = []

    @trace.traced("outer", sink=records.append)
    def outer():
        with trace.traced("inner", sink=records.append):
            pass

    outer()
    inner_rec, outer_rec = records
    assert inner_rec["name"] == "inner" and outer_rec["name"] == "outer"
    assert inner_rec["trace_id"] == outer_rec["trace_id"]
    assert inner_rec["parent_id"] == outer_rec["span_id"]


# ---------------------------------------------------------------------------
# metrics registry + Prometheus exposition


def test_registry_render_prometheus_invariants():
    from fedml_tpu.obs.registry import MetricsRegistry

    reg = MetricsRegistry()
    c = reg.counter("demo_requests_total", "requests", labels=("code",))
    c.inc(code="200")
    c.inc(2, code='5"00\n')  # label value needing escaping
    g = reg.gauge("demo_temp", "temperature")
    g.set(-3.5)
    h = reg.histogram("demo_latency_seconds", "latency", buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.05, 0.5, 5.0):
        h.observe(v)

    out = reg.render()
    lines = out.splitlines()
    assert out.endswith("\n")

    # one HELP + one TYPE per family, TYPE correct
    for name, kind in (("demo_requests_total", "counter"), ("demo_temp", "gauge"),
                       ("demo_latency_seconds", "histogram")):
        assert lines.count(f"# TYPE {name} {kind}") == 1
        assert sum(1 for l in lines if l.startswith(f"# HELP {name} ")) == 1

    assert 'demo_requests_total{code="200"} 1' in lines
    assert 'demo_requests_total{code="5\\"00\\n"} 2' in lines
    assert "demo_temp -3.5" in lines

    # histogram invariants: cumulative monotone buckets, +Inf == _count, _sum
    buckets = [l for l in lines if l.startswith("demo_latency_seconds_bucket")]
    counts = [int(l.rsplit(" ", 1)[1]) for l in buckets]
    assert counts == sorted(counts), buckets
    assert buckets[-1].startswith('demo_latency_seconds_bucket{le="+Inf"}')
    assert counts == [1, 3, 4, 5]
    assert "demo_latency_seconds_count 5" in lines
    sum_line = next(l for l in lines if l.startswith("demo_latency_seconds_sum"))
    assert abs(float(sum_line.split(" ")[1]) - 5.605) < 1e-9
    assert h.count() == 5

    # re-registration: same spec returns the same family, mismatch is loud
    assert reg.counter("demo_requests_total", "requests", labels=("code",)) is c
    with pytest.raises(ValueError):
        reg.gauge("demo_requests_total")
    with pytest.raises(ValueError):
        reg.counter("bad name!")
    with pytest.raises(ValueError):
        c.inc(-1, code="200")


def test_metrics_endpoint_roundtrip():
    from fedml_tpu.obs.registry import MetricsHTTPServer, MetricsRegistry

    reg = MetricsRegistry()
    reg.counter("endpoint_hits_total", "hits").inc(3)
    server = MetricsHTTPServer(reg, port=0).start()
    try:
        base = f"http://127.0.0.1:{server.port}"
        with urllib.request.urlopen(f"{base}/metrics", timeout=5) as resp:
            assert resp.status == 200
            assert resp.headers["Content-Type"].startswith("text/plain; version=0.0.4")
            body = resp.read().decode()
        assert "endpoint_hits_total 3" in body
        with urllib.request.urlopen(f"{base}/healthz", timeout=5) as resp:
            assert resp.status == 200
            assert json.loads(resp.read())["status"] == "ok"
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(f"{base}/nope", timeout=5)
    finally:
        server.close()


# ---------------------------------------------------------------------------
# comm receive loop: non-blocking transient-decode retry


class _FlakyBackend:
    """Observer-loop harness whose decode fails transiently for payloads
    starting with b'bad' (recovering after 2 attempts) — the object-store-
    briefly-unreachable shape the retry path exists for."""

    def __init__(self):
        from fedml_tpu.comm.base import ObserverLoopMixin

        self._mixin = ObserverLoopMixin()
        self._mixin._init_observer_loop()
        self.failures: dict[bytes, int] = {}

        def decode(data):
            from fedml_tpu.comm.message import Message

            if data.startswith(b"bad"):
                seen = self.failures.get(data, 0)
                self.failures[data] = seen + 1
                if seen < 2:
                    raise OSError("object store unreachable")
            msg = Message(int(data.split(b":")[1]), 1, 0)
            return msg

        self._mixin._decode_bytes = decode


def test_transient_decode_retry_does_not_block_queue():
    from fedml_tpu.comm.base import DECODE_RETRIES, MSG_DROPPED

    backend = _FlakyBackend()
    mixin = backend._mixin
    arrivals = []

    class Recorder:
        def receive_message(self, msg_type, msg):
            arrivals.append((msg_type, time.monotonic()))

    mixin.add_observer(Recorder())
    retries_before = DECODE_RETRIES.value()
    dropped_before = MSG_DROPPED.value(reason="retries_exhausted")

    t = threading.Thread(target=mixin.handle_receive_message, daemon=True)
    t.start()
    t0 = time.monotonic()
    mixin._inbox.put(b"bad:7")   # needs 2 backoff windows before decoding
    mixin._inbox.put(b"ok:1")
    mixin._inbox.put(b"ok:2")
    deadline = time.monotonic() + 5
    while len(arrivals) < 3 and time.monotonic() < deadline:
        time.sleep(0.01)
    mixin.stop_receive_message()
    t.join(timeout=2)

    assert [mt for mt, _ in sorted(arrivals, key=lambda a: a[1])][-1] == 7, arrivals
    ok_times = [ts for mt, ts in arrivals if mt in (1, 2)]
    bad_times = [ts for mt, ts in arrivals if mt == 7]
    assert len(ok_times) == 2 and len(bad_times) == 1
    # healthy messages drained while the flaky payload sat in backoff:
    # first retry is not-before t0+0.2s, so both OK messages beat it
    assert max(ok_times) - t0 < 0.2, (t0, arrivals)
    assert bad_times[0] - t0 >= 0.2
    assert DECODE_RETRIES.value() - retries_before == 2
    assert MSG_DROPPED.value(reason="retries_exhausted") == dropped_before


def test_poisoned_payload_dropped_after_retry_budget():
    from fedml_tpu.comm.base import MSG_DROPPED

    backend = _FlakyBackend()
    backend.failures[b"bad:9"] = -10**6  # never recovers within the budget
    mixin = backend._mixin
    arrivals = []

    class Recorder:
        def receive_message(self, msg_type, msg):
            arrivals.append(msg_type)

    mixin.add_observer(Recorder())
    dropped_before = MSG_DROPPED.value(reason="retries_exhausted")
    t = threading.Thread(target=mixin.handle_receive_message, daemon=True)
    t.start()
    mixin._inbox.put(b"bad:9")
    mixin._inbox.put(b"ok:1")
    deadline = time.monotonic() + 5
    while MSG_DROPPED.value(reason="retries_exhausted") == dropped_before \
            and time.monotonic() < deadline:
        time.sleep(0.02)
    mixin.stop_receive_message()
    t.join(timeout=2)
    assert MSG_DROPPED.value(reason="retries_exhausted") == dropped_before + 1
    assert arrivals == [1]  # the healthy message was dispatched, the bad one never


# ---------------------------------------------------------------------------
# e2e: trace propagation across an INPROC cross-silo run + report


def test_cross_silo_round_trace_propagates_and_report_reconstructs(tmp_path, eight_devices):
    """The acceptance criterion: an INPROC cross-silo run with
    enable_remote_obs yields a collector JSONL from which obs.report
    reconstructs a per-round span tree where every client train span carries
    the same trace_id as the server's aggregate span for that round; the
    registry render is served over /metrics while the run is live."""
    import fedml_tpu
    from fedml_tpu.comm.inproc import InProcRouter
    from fedml_tpu.cross_silo import build_client, build_server
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub
    from fedml_tpu.obs import report

    jsonl = tmp_path / "trail.jsonl"
    cfg = tiny_config(
        training_type="cross_silo", client_num_in_total=2, client_num_per_round=2,
        comm_round=3, learning_rate=0.3, frequency_of_the_test=1, run_id="trace-e2e",
    )
    cfg.extra = {"enable_remote_obs": True, "obs_jsonl_path": str(jsonl),
                 "metrics_port": 0}
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)
    InProcRouter.reset("trace-e2e")
    clients = [build_client(cfg, ds, model, rank=r, backend="INPROC") for r in (1, 2)]
    for c in clients:
        c.run_in_thread()
    server = build_server(cfg, ds, model, backend="INPROC")
    assert server.metrics_server is not None
    port = server.metrics_server.port
    try:
        # the endpoint is live for the duration of the run (finish() closes
        # it): scrape now, before the protocol completes
        with urllib.request.urlopen(f"http://127.0.0.1:{port}/metrics", timeout=5) as resp:
            assert resp.status == 200
            pre_body = resp.read().decode()
        assert "fedml_comm_messages_sent_total" in pre_body
        assert "fedml_crosssilo_client_round_trip_seconds" in pre_body
        history = server.run_until_done(timeout=120.0)
    finally:
        for c in clients:
            c.finish()
    assert len(history) == 3

    # after the run the process-global registry holds the per-client RTT
    # histogram samples the straggler attribution is built from
    from fedml_tpu.obs.registry import REGISTRY

    post_body = REGISTRY.render()
    assert 'fedml_crosssilo_client_round_trip_seconds_bucket{client="1",le="+Inf"}' in post_body
    assert 'fedml_crosssilo_client_round_trip_seconds_bucket{client="2",le="+Inf"}' in post_body

    records = [json.loads(l) for l in jsonl.read_text().splitlines()]
    spans = [r for r in records if r.get("kind") == "span"]

    # server spans: one round + one aggregate per round, rank-0 sourced
    agg_trace_by_round = {}
    for rec in spans:
        if rec["name"] == "aggregate":
            assert rec["sender"] == 0
            agg_trace_by_round[rec["round_idx"]] = rec["trace_id"]
    assert sorted(agg_trace_by_round) == [0, 1, 2]

    # EVERY client train span shares the round's trace and parents to the
    # round span (the server stamp each broadcast carried)
    round_span_by_trace = {r["trace_id"]: r for r in spans if r["name"] == "round"}
    trains = [r for r in spans if r["name"] == "train"]
    assert len(trains) == 6  # 2 clients x 3 rounds
    for rec in trains:
        assert rec["trace_id"] == agg_trace_by_round[rec["round_idx"]], rec
        assert rec["parent_id"] == round_span_by_trace[rec["trace_id"]]["span_id"]
        assert rec["sender"] in (1, 2) and rec["dur_s"] > 0

    # span-tree reconstruction: each round's tree has the round span as root
    # with the aggregate span and both train spans among its children
    trees = report.build_span_trees(records)
    assert len(trees) == 3
    for roots in trees.values():
        root_names = {n.name for n in roots}
        assert "round" in root_names
        round_node = next(n for n in roots if n.name == "round")
        child_names = [c.name for c in round_node.children]
        assert child_names.count("train") == 2
        assert "aggregate" in child_names

    # timeline rows + straggler ranking come straight from the trail
    rows = report.round_rows(records)
    assert [r["round_idx"] for r in rows] == [0, 1, 2]
    for row in rows:
        assert row["round_dur_s"] > 0 and row["aggregate_dur_s"] > 0
        assert len(row["train"]) == 2
        assert set(row["round_trips"]) == {"1", "2"}
    ranking = report.slowest_clients(records)
    assert {r["client"] for r in ranking} == {"1", "2"}
    assert all(r["rounds"] == 3 and "mean_round_trip_s" in r for r in ranking)

    rendered = report.render_report(records)
    assert "== round timeline ==" in rendered
    assert "== slowest clients ==" in rendered
    assert "p50_s" in rendered and "p95_s" in rendered


def test_obs_report_from_recorded_trail(tmp_path):
    """`fedml-tpu obs report` reconstructs a deterministic timeline from a
    synthetic recorded trail (no live run needed)."""
    from fedml_tpu.cli import main as cli_main

    trail = tmp_path / "obs.jsonl"
    records = []
    for r, trace_id in enumerate(["t0", "t1"]):
        records.append({"sender": 0, "kind": "span", "name": "round", "trace_id": trace_id,
                        "span_id": f"r{r}", "parent_id": None, "ts": 100.0 + r,
                        "dur_s": 2.0, "round_idx": r})
        records.append({"sender": 0, "kind": "span", "name": "aggregate", "trace_id": trace_id,
                        "span_id": f"a{r}", "parent_id": f"r{r}", "ts": 101.0 + r,
                        "dur_s": 0.25, "round_idx": r})
        for rank, dur in ((1, 0.5), (2, 1.5)):
            records.append({"sender": rank, "kind": "span", "name": "train",
                            "trace_id": trace_id, "span_id": f"c{rank}{r}",
                            "parent_id": f"r{r}", "ts": 100.1 + r, "dur_s": dur,
                            "round_idx": r, "client_idx": rank - 1})
            records.append({"sender": 0, "kind": "metric", "metric": "client_round_trip_s",
                            "client": rank, "value": dur + 0.1, "round_idx": r,
                            "trace_id": trace_id, "ts": 102.0 + r})
    trail.write_text("\n".join(json.dumps(r) for r in records)
                     + "\nnot json\n")  # malformed tail line must be skipped

    from fedml_tpu.obs import report
    recs = report.load_jsonl(trail)
    assert len(recs) == len(records)

    phases = report.phase_percentiles(recs)
    assert phases["train"]["n"] == 4
    assert abs(phases["train"]["p50_s"] - 1.0) < 1e-9   # median of .5,.5,1.5,1.5
    assert abs(phases["round"]["p95_s"] - 2.0) < 1e-9

    ranking = report.slowest_clients(recs)
    assert ranking[0]["client"] == "2"  # slowest first
    assert abs(ranking[0]["mean_train_s"] - 1.5) < 1e-9
    assert abs(ranking[0]["mean_round_trip_s"] - 1.6) < 1e-9

    rc = cli_main(["obs", "report", str(trail)])
    assert rc == 0


def test_ring_mode_requires_three_clients(eight_devices):
    """Satellite: ring gossip with n <= 2 silently diverged from the dense
    ring_topology reference — now refused loudly."""
    import fedml_tpu
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub
    from fedml_tpu.sim.decentralized import DecentralizedSimulator

    cfg = tiny_config(client_num_in_total=2, client_num_per_round=2,
                      synthetic_train_size=160, synthetic_test_size=32)
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)
    with pytest.raises(ValueError, match="n >= 3"):
        DecentralizedSimulator(cfg, ds, model, mode="ring")


def test_launch_job_cleans_up_inputs_file_on_failure(tmp_path, monkeypatch):
    """Satellite: __workflow_inputs__.json must not leak into the source
    workspace even when packaging explodes (try/finally path)."""
    from fedml_tpu.workflow.customized_jobs import LaunchJob

    ws = tmp_path / "ws"
    ws.mkdir()
    (ws / "main.py").write_text("print('hi')\n")
    yaml_path = tmp_path / "job.yaml"
    yaml_path.write_text("workspace: ws\njob: python main.py\n")

    from fedml_tpu.sched import launch as launch_mod

    def boom(self, spec, base_dir=None):
        assert (ws / "__workflow_inputs__.json").exists()  # visible to packaging
        raise RuntimeError("disk full")

    monkeypatch.setattr(launch_mod.FedMLLaunchManager, "build_package", boom)
    job = LaunchJob("leaky", str(yaml_path), str(tmp_path / "spool"), timeout=5)
    with pytest.raises(RuntimeError, match="disk full"):
        job.run(dep={"tag": "x"})
    assert not (ws / "__workflow_inputs__.json").exists()
