"""Multi-host (MULTIPROCESS backend) tests — VERDICT.md item 4.

A 2-process CPU run (gloo collectives, 4 virtual devices per process, one
8-device global mesh) must produce numerics identical to the single-process
8-device mesh run: the jitted round is the same SPMD program either way.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _single_process_reference():
    """Same config on this process's own 8-device mesh."""
    import jax

    import fedml_tpu
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub
    from fedml_tpu.sim.engine import MeshSimulator

    from .conftest import tiny_config

    cfg = tiny_config(client_num_per_round=8)
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)
    sim = MeshSimulator(cfg, ds, model)
    history = sim.run()
    flat = np.concatenate([
        np.asarray(x, dtype=np.float64).ravel()
        for x in jax.tree_util.tree_leaves(jax.device_get(sim.global_vars))
    ])
    return float(flat.sum()), float(np.sqrt((flat ** 2).sum())), history[-1].get("test_acc")


@pytest.mark.skipif(
    (os.cpu_count() or 1) < 4,
    reason="spawns multiple jax processes whose collective programs starve "
           "the XLA:CPU rendezvous on hosts with too few cores (observed "
           "240s hangs then timeout failures on 1-core CI)",
)
def test_two_process_mesh_equals_single_process(eight_devices):
    port = _free_port()
    worker = os.path.join(_REPO, "tests", "_multihost_worker.py")
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "JAX_PLATFORM_NAME")}
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(pid), "2", str(port)],
            env=env, cwd=_REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=240)
        outs.append(out)
        assert p.returncode == 0, out[-3000:]
    results = {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith("MULTIHOST_RESULT "):
                r = json.loads(line[len("MULTIHOST_RESULT "):])
                results[r["pid"]] = r
    assert set(results) == {0, 1}, outs[0][-2000:]
    # both processes hold the identical replicated global model
    assert results[0]["checksum"] == pytest.approx(results[1]["checksum"], abs=1e-9)
    assert results[0]["l2"] == pytest.approx(results[1]["l2"], abs=1e-9)

    ref_sum, ref_l2, ref_acc = _single_process_reference()
    # the 2-process global mesh runs the same SPMD program as the 1-process
    # 8-device mesh — numerics must match to float tolerance
    assert results[0]["checksum"] == pytest.approx(ref_sum, rel=1e-5, abs=1e-5)
    assert results[0]["l2"] == pytest.approx(ref_l2, rel=1e-5, abs=1e-5)
    assert results[0]["test_acc"] == pytest.approx(ref_acc, abs=1e-6)


def test_shard_leading_axis_warns_on_undivisible(eight_devices):
    """VERDICT 'what's weak' #3: silent replication is a perf cliff — it must
    warn."""
    import warnings

    import jax.numpy as jnp

    from fedml_tpu.parallel import mesh as meshlib

    m = meshlib.make_mesh((meshlib.AXIS_CLIENTS,), (8,))
    meshlib._undivisible_warned.clear()
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        meshlib.shard_leading_axis(jnp.zeros((127, 4)), m)
    assert any("127" in str(x.message) and "REPLICATING" in str(x.message) for x in w), [
        str(x.message) for x in w
    ]
    # divisible dims stay silent
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        meshlib.shard_leading_axis(jnp.zeros((128, 4)), m)
    assert not w, [str(x.message) for x in w]
