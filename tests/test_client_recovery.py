"""Survivable clients (ISSUE 13): the client crash-recovery journal,
exactly-once uploads under the idempotence-key dedup, mid-round sync-server
journaling, the backoff purpose namespacing, and the real-process SIGKILL
soak (slow-marked)."""

import threading

import numpy as np
import pytest

from .conftest import tiny_config


def _load(cfg):
    import fedml_tpu
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub

    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)
    return ds, model


# ---------------------------------------------------------------------------
# ClientJournal: roundtrip, sequence, gate
# ---------------------------------------------------------------------------

def test_client_journal_roundtrip_and_sequence(tmp_path):
    from fedml_tpu.cross_silo.client_journal import (
        ClientJournal, pack_client_state, unpack_client_state,
    )

    j = ClientJournal(str(tmp_path / "cj"), rank=3, keep=2)
    residuals = [None, np.arange(8, dtype=np.float32), None,
                 np.ones(4, np.float32) * 0.5]
    tstate = {"momentum": {"w": np.arange(6, dtype=np.float32)}}
    proto, arrays = pack_client_state(
        rank=3, round_idx=5, session_epoch=2, rounds_trained=6,
        server_restarts_seen=1, upload_attempts={"5:2": 2},
        residuals=residuals, trainer_state=tstate)
    j.snapshot_state(proto, arrays)
    j.snapshot_state(proto, arrays)

    # a fresh journal object (the restarted client) restores the newest step
    # and continues the sequence past it
    j2 = ClientJournal(str(tmp_path / "cj"), rank=3, keep=2)
    snap = j2.restore_state()
    assert snap["step"] == 2
    state = unpack_client_state(snap)
    assert state["round_idx"] == 5 and state["session_epoch"] == 2
    assert state["rounds_trained"] == 6 and state["server_restarts_seen"] == 1
    assert state["upload_attempts"] == {"5:2": 2}
    got = state["residuals"]
    assert len(got) == 4 and got[0] is None and got[2] is None
    np.testing.assert_array_equal(got[1], residuals[1])
    np.testing.assert_array_equal(got[3], residuals[3])
    np.testing.assert_array_equal(
        state["trainer_state"]["momentum"]["w"], tstate["momentum"]["w"])
    j2.snapshot_state(proto, arrays)
    assert j2.steps()[-1] == 3  # never rewinds over the restored step


def test_client_journal_keep_prunes(tmp_path):
    from fedml_tpu.cross_silo.client_journal import ClientJournal

    j = ClientJournal(str(tmp_path / "cj"), rank=1, keep=2)
    for _ in range(5):
        j.snapshot_state({"kind": "client"}, {})
    assert j.steps() == [4, 5]


def test_client_journal_gate(tmp_path):
    from fedml_tpu.cross_silo.client_journal import client_journal_from_config

    assert client_journal_from_config(tiny_config(), rank=1) is None
    assert client_journal_from_config(None, rank=1) is None
    j = client_journal_from_config(
        tiny_config(extra={"client_journal_dir": str(tmp_path / "cj")}), rank=2)
    assert j is not None and j.rank == 2 and j.keep == 2


# ---------------------------------------------------------------------------
# EF-residual durability: crash-resume is BITWISE the uncrashed client
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("codec", ["topk", "qsgd8"])
def test_client_crash_resume_bitwise_parity(codec, eight_devices):
    from fedml_tpu.cross_silo.async_soak import run_client_crash_parity

    res = run_client_crash_parity(codec=codec, rounds=3, kill_before_round=2)
    assert res["swapped"] == 1, res
    assert res["resumed"], res
    if codec == "topk":
        # the EF carry exists and survived the crash bit for bit
        assert res["residual_leaves"] > 0, res
    assert res["bitwise_residuals"], res
    assert res["bitwise_global"], res


# ---------------------------------------------------------------------------
# exactly-once uploads: idempotence-key dedup on both servers
# ---------------------------------------------------------------------------

def _async_server(tmp_path, **extra):
    from fedml_tpu.comm.inproc import InProcRouter
    from fedml_tpu.cross_silo import build_server

    cfg = tiny_config(
        training_type="cross_silo", comm_round=50, run_id="dedup_async",
        frequency_of_the_test=0,
        extra={"async_aggregation": True, "async_buffer_k": 100,
               "async_redispatch_timeout_s": 0.0,
               "server_journal_dir": str(tmp_path / "j"), **extra})
    ds, model = _load(cfg)
    InProcRouter.reset("dedup_async")
    return build_server(cfg, ds, model, backend="INPROC"), ds, model


def _keyed_upload(rank, params, version, key):
    from fedml_tpu.comm.message import Message
    from fedml_tpu.cross_silo import message_define as md

    msg = Message(md.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, rank, 0)
    msg.add_params(md.MSG_ARG_KEY_MODEL_PARAMS, params)
    msg.add_params(md.MSG_ARG_KEY_NUM_SAMPLES, 16.0)
    msg.add_params(md.MSG_ARG_KEY_ROUND_INDEX, int(version))
    if key is not None:
        msg.add_params(md.MSG_ARG_KEY_UPLOAD_KEY, str(key))
    return Message.decode(msg.encode())


def test_async_dedup_folds_each_key_once(tmp_path, eight_devices):
    import jax

    server, ds, model = _async_server(tmp_path)
    base = jax.device_get(server.aggregator.global_vars)

    server.handle_message_receive_model(_keyed_upload(1, base, 0, "1:0:-1:0"))
    assert server.total_arrivals == 1 and server.deduped_uploads == 0

    # the identical key redelivered (chaos duplicate / reconnect resend /
    # crash-resend of a journaled attempt): DEDUPED, never double-folded
    server.handle_message_receive_model(_keyed_upload(1, base, 0, "1:0:-1:0"))
    assert server.total_arrivals == 1 and server.deduped_uploads == 1

    # a NEW attempt of the same assignment is new work (the client journaled
    # a fresh attempt, so the old one never folded or was lost): FOLDED
    server.handle_message_receive_model(_keyed_upload(1, base, 0, "1:0:-1:1"))
    assert server.total_arrivals == 2 and server.deduped_uploads == 1

    # key-less uploads (client journaling off) take the historical path
    server.handle_message_receive_model(_keyed_upload(2, base, 0, None))
    server.handle_message_receive_model(_keyed_upload(2, base, 0, None))
    assert server.total_arrivals == 4 and server.deduped_uploads == 1
    server.finish()


def test_async_dedup_table_survives_server_crash(tmp_path, eight_devices):
    """The folded-key table is journaled: a duplicate of a PRE-crash fold
    arriving at the RECOVERED server still dedups instead of re-entering
    through the in-flight acceptance."""
    import jax

    server_a, ds, model = _async_server(tmp_path, async_buffer_k=2)
    base = jax.device_get(server_a.aggregator.global_vars)
    # two keyed folds close the virtual round -> journal snapshot commits
    # the key table with the version bump
    server_a.handle_message_receive_model(_keyed_upload(1, base, 0, "1:0:-1:0"))
    server_a.handle_message_receive_model(_keyed_upload(2, base, 0, "2:0:-1:0"))
    assert server_a.server_version == 1
    server_a.hard_kill()

    from fedml_tpu.cross_silo import build_server

    server_b = build_server(server_a.cfg, ds, model, backend="INPROC")
    assert server_b.server_version == 1  # recovered
    assert server_b.session_epoch == 1
    server_b.handle_message_receive_model(_keyed_upload(1, base, 0, "1:0:-1:0"))
    assert server_b.deduped_uploads == 1
    assert server_b.total_arrivals == 2  # journaled counter, nothing refolded
    server_b.finish()


def test_sync_dedup_counts_duplicates(tmp_path, eight_devices):
    from fedml_tpu.comm.inproc import InProcRouter
    from fedml_tpu.cross_silo import build_server

    cfg = tiny_config(
        training_type="cross_silo", client_num_in_total=4,
        client_num_per_round=4, comm_round=1, run_id="dedup_sync",
        frequency_of_the_test=0,
        extra={"streaming_aggregation": True,
               "server_journal_dir": str(tmp_path / "j")})
    ds, model = _load(cfg)
    InProcRouter.reset("dedup_sync")
    server = build_server(cfg, ds, model, backend="INPROC")
    import jax

    base = jax.device_get(server.aggregator.global_vars)
    server.selected = [1, 2, 3, 4]
    server._init_sent = True
    server.handle_message_receive_model(_keyed_upload(1, base, 0, "1:0:0:0"))
    server.handle_message_receive_model(_keyed_upload(1, base, 0, "1:0:0:0"))
    assert server.deduped_uploads == 1
    assert server.aggregator.received_count() == 1
    server.finish()
    InProcRouter.reset("dedup_sync")


# ---------------------------------------------------------------------------
# mid-round sync journaling: crash between folds resumes the partial fold
# ---------------------------------------------------------------------------

def _scaled(params, cid):
    import jax

    return jax.tree_util.tree_map(
        lambda a: ((np.asarray(a) * (1.0 + 0.01 * cid)).astype(a.dtype)
                   if np.asarray(a).dtype.kind == "f" else a), params)


def _mk_sync_server(tmp_path, run_id, journal):
    from fedml_tpu.comm.inproc import InProcRouter
    from fedml_tpu.cross_silo import build_server

    cfg = tiny_config(
        training_type="cross_silo", client_num_in_total=4,
        client_num_per_round=4, comm_round=2, run_id=run_id,
        frequency_of_the_test=0,
        extra={"streaming_aggregation": True,
               **({"server_journal_dir": str(tmp_path / "j"),
                   "server_journal_every_folds": 1} if journal else {})})
    ds, model = _load(cfg)
    InProcRouter.reset(run_id)
    server = build_server(cfg, ds, model, backend="INPROC")
    server.selected = [1, 2, 3, 4]
    server._init_sent = True
    return server, ds, model


def test_sync_midround_crash_resumes_partial_fold_bitwise(tmp_path,
                                                          eight_devices):
    """The acceptance run: round 0 completes, round 1 is killed after 2 of
    4 folds (each journaled at the fold cadence), the restart resumes the
    PARTIAL fold (folds-after-recovery = 2 < 4) and the finished global is
    BITWISE the uninterrupted run's — including the model_step reference
    (the mid-round sidecar points at round 0's boundary checkpoint instead
    of rewriting the model)."""
    import jax

    # uninterrupted reference: 2 rounds, uploads in fixed order 1..4
    ref, _, _ = _mk_sync_server(tmp_path / "ref", "midround_ref", journal=False)
    base = jax.device_get(ref.aggregator.global_vars)
    for r in (0, 1):
        for cid in (1, 2, 3, 4):
            ref.handle_message_receive_model(
                _keyed_upload(cid, _scaled(base, cid), r, None))
        if r == 0:
            ref.selected = [1, 2, 3, 4]  # _broadcast_model re-selected; pin
    assert ref.done.is_set()
    ref_leaves = jax.tree_util.tree_leaves(
        jax.device_get(ref.aggregator.global_vars))

    # crashed run: same uploads, killed mid-round-1 after 2 folds
    srv_a, ds, model = _mk_sync_server(tmp_path / "crash", "midround_a",
                                       journal=True)
    for cid in (1, 2, 3, 4):
        srv_a.handle_message_receive_model(
            _keyed_upload(cid, _scaled(base, cid), 0, None))
    srv_a.selected = [1, 2, 3, 4]
    assert srv_a.round_idx == 1
    for cid in (1, 2):
        srv_a.handle_message_receive_model(
            _keyed_upload(cid, _scaled(base, cid), 1, None))
    assert srv_a.aggregator._stream_folded == 2
    srv_a.hard_kill()

    from fedml_tpu.cross_silo import build_server

    srv_b = build_server(srv_a.cfg, ds, model, backend="INPROC")
    # resumed MID-round: partial fold + folded-client set restored, model
    # loaded through the referenced boundary step
    assert srv_b.round_idx == 1
    assert srv_b.session_epoch == 1
    assert srv_b.aggregator._stream_folded == 2
    assert srv_b.aggregator.has_received(1) and srv_b.aggregator.has_received(2)
    assert not srv_b.aggregator.has_received(3)
    srv_b.selected = [1, 2, 3, 4]
    srv_b._init_sent = True
    for cid in (3, 4):  # folds-after-recovery = 2 < 4 clients/round
        srv_b.handle_message_receive_model(
            _keyed_upload(cid, _scaled(base, cid), 1, None))
    assert srv_b.done.is_set()
    res_leaves = jax.tree_util.tree_leaves(
        jax.device_get(srv_b.aggregator.global_vars))
    for x, y in zip(ref_leaves, res_leaves):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))
    srv_a.finish()
    srv_b.finish()


def test_midround_broadcast_skips_folded_clients(tmp_path, eight_devices):
    """A recovered mid-round server re-broadcasts the interrupted round only
    to the NOT-yet-folded clients — the journal kept the others' work."""
    from fedml_tpu.comm.inproc import InProcRouter
    from fedml_tpu.cross_silo import build_server, message_define as md
    import jax

    srv_a, ds, model = _mk_sync_server(tmp_path, "midround_bcast",
                                       journal=True)
    base = jax.device_get(srv_a.aggregator.global_vars)
    for cid in (1, 2):
        srv_a.handle_message_receive_model(
            _keyed_upload(cid, _scaled(base, cid), 0, None))
    srv_a.hard_kill()

    srv_b = build_server(srv_a.cfg, ds, model, backend="INPROC")
    sent = []
    router = InProcRouter.get("midround_bcast")
    orig_route = router.route

    def tap(msg):
        if msg.get_type() in (md.MSG_TYPE_S2C_INIT_CONFIG,
                              md.MSG_TYPE_S2C_SYNC_MODEL_TO_CLIENT):
            sent.append(msg.get_receiver_id())
        orig_route(msg)

    router.route = tap
    srv_b.send_init_msg()  # all-online entry point of the resumed round
    assert sorted(sent) == [3, 4]  # folded clients not re-asked
    assert sorted(srv_b.selected) == [1, 2, 3, 4]  # but still counted
    srv_a.finish()
    srv_b.finish()
    InProcRouter.reset("midround_bcast")


# ---------------------------------------------------------------------------
# journal back-compat + prune (satellite)
# ---------------------------------------------------------------------------

def test_pre13_snapshot_still_restores(tmp_path, eight_devices):
    """A PR-10-era snapshot (no model_step, folded_keys, deduped, or
    stream_clients fields) restores into the ISSUE-13 servers with empty
    dedup state — the sidecar format change is purely additive."""
    from fedml_tpu.cross_silo.journal import ServerJournal

    server, ds, model = _async_server(tmp_path / "fresh")
    model_state = server.aggregator.model_state()
    server.finish()

    jd = tmp_path / "old" / "j"
    j = ServerJournal(str(jd), keep=3)
    j.snapshot(2, {"kind": "async", "session_epoch": 0, "server_version": 2,
                   "round_idx": 2, "outstanding": {"1": 1}, "rr_cursor": 4,
                   "total_arrivals": 7},
               arrays={}, model_state=model_state)

    srv, _, _ = _async_server(tmp_path / "old")
    assert srv.server_version == 2
    assert srv.session_epoch == 1
    assert srv.total_arrivals == 7
    assert srv.deduped_uploads == 0 and srv._folded_keys == {}
    assert srv._prev_epoch_inflight == {1: 1}
    srv.finish()


def test_midround_snapshots_respect_keep_and_never_prune_newest(tmp_path):
    from fedml_tpu.cross_silo.journal import ServerJournal

    j = ServerJournal(str(tmp_path / "j"), keep=2)
    for step in (1, 2, 3):
        j.snapshot(step, {"server_version": step}, arrays={})
    assert j.steps() == [2, 3]
    # mid-round cadence: the in-progress round OVERWRITES its own step with
    # more progress — no step-count growth, so keep never prunes the newest
    for folds in (1, 2, 3):
        # model-less mid-round sidecar (a round started from the fresh init
        # references no model step; the model_step restore path is covered
        # by test_sync_midround_crash_resumes_partial_fold_bitwise)
        j.snapshot(3, {"server_version": 3, "stream_folded": folds},
                   arrays={"stream_sum_0": np.ones(4, np.float32) * folds})
    assert j.steps() == [2, 3]
    snap = j.restore()
    assert snap["step"] == 3
    assert snap["protocol"]["stream_folded"] == 3  # the newest overwrite won
    np.testing.assert_array_equal(snap["arrays"]["stream_sum_0"],
                                  np.ones(4, np.float32) * 3)


# ---------------------------------------------------------------------------
# backoff purpose namespacing (satellite)
# ---------------------------------------------------------------------------

def test_backoff_purpose_streams_decorrelate():
    """Colocated retry schedules whose numeric seeds coincide must NOT draw
    identical jitter: each call site's purpose constant namespaces its
    stream, while any single schedule stays exactly reproducible."""
    from fedml_tpu.comm.base import (
        BACKOFF_PURPOSE_DECODE_RETRY, BACKOFF_PURPOSE_RECONNECT,
        BACKOFF_PURPOSE_STATUS_PROBE, backoff_delay,
    )

    kw = dict(base=0.2, cap=2.0, seed=0)
    decode = [backoff_delay(a, purpose=BACKOFF_PURPOSE_DECODE_RETRY, **kw)
              for a in range(8)]
    reconnect = [backoff_delay(a, purpose=BACKOFF_PURPOSE_RECONNECT, **kw)
                 for a in range(8)]
    probe = [backoff_delay(a, purpose=BACKOFF_PURPOSE_STATUS_PROBE, **kw)
             for a in range(8)]
    # deterministic per stream
    assert decode == [backoff_delay(a, purpose=BACKOFF_PURPOSE_DECODE_RETRY,
                                    **kw) for a in range(8)]
    # the streams are namespaced apart despite the identical seed
    assert decode != reconnect and decode != probe and reconnect != probe
    # the jitter envelope is unchanged: [0.5, 1.0) of the capped exponential
    for sched in (decode, reconnect, probe):
        for a, d in enumerate(sched):
            raw = min(2.0, 0.2 * 2 ** a)
            assert 0.5 * raw <= d < raw


# ---------------------------------------------------------------------------
# client-kill soak (in-proc, real clients) + multiproc SIGKILL soak (slow)
# ---------------------------------------------------------------------------

def test_client_kill_soak_resumes_and_accounts(eight_devices):
    from fedml_tpu.cross_silo.async_soak import run_client_kill_soak

    res = run_client_kill_soak(
        n_clients=4, versions=4, buffer_k=2, concurrency=2,
        kill_marks=((2, 1),), redispatch_timeout_s=1.0, seed=0,
        timeout_s=180.0)
    assert res["versions"] == 4, res
    assert res["kills"] == 1, res
    assert res["resumed_from_journal"] == 1, res
    assert res["unaccounted"] == 0, res
    assert res["peak_buffered_updates"] <= 2, res
    assert res["clients_finished"] == 4, res


@pytest.mark.slow
def test_multiproc_sigkill_soak():
    """The acceptance soak (ISSUE 13 + the ISSUE 14 chaos satellite): REAL
    OS processes over TCP with the seeded ``chaos_*`` fault mix threaded
    into every worker's cfg — drop/delay/duplicate/corrupt faults ride the
    real transport in the SAME run as the genuine SIGKILLs of the server
    and >= 2 clients; every party journal-recovers and the run completes
    with the extended accounting identity still closing.  Out of tier-1
    (slow): interpreter restarts alone cost ~30s."""
    from fedml_tpu.cross_silo.async_soak import (
        DEFAULT_CHAOS_FLAGS, run_multiproc_kill_soak,
    )

    res = run_multiproc_kill_soak(chaos=dict(DEFAULT_CHAOS_FLAGS))
    assert res["completed"], res
    assert res["versions"] == 160, res
    assert res["server_kills"] == 1, res
    assert res["client_kills"] == 2, res
    assert res["monotone"], res
    assert res["session_epoch"] >= 1, res
    assert res["unaccounted"] == 0, res
    assert (res["resumed_from_journal"] + res["cold_rejoins"]
            == res["client_kills"]), res
    # the chaos wrapper really was live on the server's real TCP leg
    assert res["chaos"] is not None, res
    assert sum(res["chaos"]["injected"].values()) > 0, res
