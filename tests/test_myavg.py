"""MyAvg (fork research) — CKA layer-selective personalized aggregation.

Pins the behaviors of reference ``my_research/.../MyAvgAPI_7.py``:
mod-N layer schedule, CKA top-k partner personalization, personal models
persisting across rounds, and end-to-end learning on the hetero recipe.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from .conftest import tiny_config


def _myavg_cfg(**over):
    base = dict(
        model="mlp",
        federated_optimizer="MyAvg",
        client_num_in_total=5,
        client_num_per_round=5,
        comm_round=4,
        partition_method="hetero",
        partition_alpha=0.5,
        # normal rounds: only Dense_0 aggregates; every 3rd round: everything
        agg_unselect_layer=("Dense_1",),
        agg_mod_list=(3,),
        agg_mod_dict={3: {}},
        # CKA personalization on the head
        cka_any_select_layer=("Dense_1",),
        cka_select_topk=2,
        cka_low_thresh=0.0,
        cka_high_thresh=1.0,
    )
    base.update(over)
    return tiny_config(**base)


def _build(cfg):
    from fedml_tpu.runner import FedMLRunner

    runner = FedMLRunner(cfg)
    return runner.runner


def _leaf(tree, path_sub):
    from fedml_tpu.sim.myavg import leaf_paths

    leaves = jax.tree_util.tree_leaves(tree)
    paths = leaf_paths(tree)
    hits = [l for p, l in zip(paths, leaves) if path_sub in p]
    assert hits, f"no leaf matching {path_sub} in {paths}"
    return np.asarray(jax.device_get(hits[0]))


def test_runner_dispatches_myavg(eight_devices):
    from fedml_tpu.sim.myavg import MyAvgSimulator

    for name in ("MyAvg", "MyAgg-7"):
        sim = _build(_myavg_cfg(federated_optimizer=name))
        assert isinstance(sim, MyAvgSimulator)


def test_mod_schedule_gates_layer_aggregation(eight_devices):
    """Dense_1 is excluded by the default filter, so the GLOBAL head must not
    move on rounds 0-2 and must move on round 3 (3 % 3 == 0) — the mod-N
    round-interval schedule of MyAvgAPI_7.py:242-263."""
    sim = _build(_myavg_cfg())
    head0 = _leaf(sim.global_vars, "Dense_1.kernel")
    body0 = _leaf(sim.global_vars, "Dense_0.kernel")
    for _ in range(3):  # rounds 0, 1, 2 — default filter
        sim.run_round()
    head_after = _leaf(sim.global_vars, "Dense_1.kernel")
    body_after = _leaf(sim.global_vars, "Dense_0.kernel")
    np.testing.assert_array_equal(head0, head_after)  # head gated off
    assert np.abs(body_after - body0).max() > 0  # body aggregated
    sim.run_round()  # round 3 — mod filter aggregates everything
    head_mod = _leaf(sim.global_vars, "Dense_1.kernel")
    assert np.abs(head_mod - head_after).max() > 0


def test_personal_models_persist_and_personalize(eight_devices):
    """Clients keep personal weights on unaggregated layers (set_param=False
    semantics), and the CKA round hands each client a DIFFERENT personalized
    head while the plain-aggregated body is shared."""
    sim = _build(_myavg_cfg())
    for _ in range(3):
        sim.run_round()
    # non-mod rounds: heads are the clients' own trained leaves -> differ
    # (the stack is padded to the mesh multiple; rows past _n_real are dummies)
    heads = _leaf(sim.client_states, "Dense_1.kernel")[: sim._n_real]
    assert heads.shape[0] == 5
    spread = np.abs(heads - heads[0]).max()
    assert spread > 1e-6, "personal heads should diverge under hetero data"
    # body was plain-aggregated for everyone -> identical across clients
    bodies = _leaf(sim.client_states, "Dense_0.kernel")[: sim._n_real]
    np.testing.assert_allclose(bodies, np.broadcast_to(bodies[:1], bodies.shape),
                               rtol=0, atol=1e-6)
    sim.run_round()  # CKA round
    heads_cka = _leaf(sim.client_states, "Dense_1.kernel")[: sim._n_real]
    # personalized: clients differ (top-2 partner sets differ under hetero)
    assert np.abs(heads_cka - heads_cka[0]).max() > 1e-6
    # but each equals old-global + corrected partner-average delta, which is
    # NOT the plain trained head carried from before
    assert np.abs(heads_cka - heads).max() > 1e-6


def test_myavg_learns_end_to_end(eight_devices):
    cfg = _myavg_cfg(comm_round=6, learning_rate=0.3)
    sim = _build(cfg)
    history = sim.run()
    assert history[-1]["train_loss"] < history[0]["train_loss"]
    pers = sim.evaluate_personalized()
    assert pers["personalized_test_acc_mean"] > 0.3, pers
    # the run-loop history carries the personalized metric (the quantity
    # MyAvg optimizes), not just the global-model accuracy
    evals = [h for h in history if "personalized_test_acc_mean" in h]
    assert evals and "test_acc" in evals[-1]
    # scan path and config-id metric: rounds 0-2 default (0), round 3 mod (1)
    cids = [h["myavg_config_id"] for h in history]
    assert cids[:4] == [0.0, 0.0, 0.0, 1.0], cids


def test_linear_cka_matrix_properties():
    from fedml_tpu.sim.myavg import linear_cka_matrix

    rng = np.random.default_rng(0)
    x = rng.standard_normal((4, 8, 6)).astype(np.float32)
    c = np.asarray(linear_cka_matrix(jnp.asarray(x)))
    np.testing.assert_allclose(np.diag(c), 1.0, atol=1e-5)
    np.testing.assert_allclose(c, c.T, atol=1e-5)
    assert (c <= 1.0 + 1e-6).all()
    # identical inputs are maximally similar; scaling is invariant
    x2 = np.stack([x[0], 2.5 * x[0], x[1], x[2]])
    c2 = np.asarray(linear_cka_matrix(jnp.asarray(x2)))
    np.testing.assert_allclose(c2[0, 1], 1.0, atol=1e-5)
    assert c2[0, 1] > c2[0, 2]


def test_cka_partner_selection_prefers_similar_clients(eight_devices):
    """Two client clusters with distinct label mappings: a client's CKA
    partners for the head layer should come from its own cluster, so the
    personalized heads converge within clusters and differ across them."""
    sim = _build(_myavg_cfg(comm_round=8, learning_rate=0.3,
                            partition_method="homo"))
    # hand-craft cluster structure: clients 0-2 keep labels, clients 3-4 see
    # permuted labels -> their head deltas point in different directions
    y = np.array(jax.device_get(sim._data[1]))
    y_perm = (y + 1) % int(sim.dataset.class_num)
    y[3:] = y_perm[3:]
    sim._data = (sim._data[0], jnp.asarray(y))
    for _ in range(7):
        sim.run_round()
    heads = _leaf(sim.client_states, "Dense_1.kernel")[: sim._n_real]
    flat = heads.reshape(5, -1)

    def d(i, j):
        return np.linalg.norm(flat[i] - flat[j])

    within = (d(0, 1) + d(0, 2) + d(1, 2) + d(3, 4)) / 4
    across = (d(0, 3) + d(0, 4) + d(1, 3) + d(2, 4)) / 4
    assert across > within, (within, across)


def test_myavg_composes_with_defense_and_dp(eight_devices):
    """Round-3 verdict item 9: transforming defenses and DP ride the MyAvg
    round through the same trust hooks as the engine round.

    Stepped per round rather than via run()'s scanned chunk: the 4-round
    lax.scan of the MyAvg+defense+LDP program intermittently SIGABRTs inside
    XLA:CPU *execution* under full-suite load (never solo, never the
    single-round program, not cache-related — reproduced with a fresh
    compilation cache).  The single-round jit is the same math; the scanned
    multi-round path stays covered by test_myavg_learns_end_to_end."""
    sim = _build(_myavg_cfg(
        comm_round=4, learning_rate=0.3,
        enable_defense=True, defense_type="norm_diff_clipping", norm_bound=50.0,
        enable_dp=True, dp_solution_type="ldp", mechanism_type="gaussian",
        epsilon=50.0, delta=1e-5, sensitivity=0.01,
    ))
    assert sim.trust is not None and sim.trust.defense is not None
    history = [sim.run_round() for _ in range(4)]
    assert history[-1]["train_loss"] < history[0]["train_loss"]
    pers = sim.evaluate_personalized()
    assert pers["personalized_test_acc_mean"] > 0.3, pers


def test_myavg_defense_zero_weight_excludes_partner(eight_devices):
    """A defense that zeroes a client's weight removes it from the global
    aggregate AND from everyone's CKA partner pool (the weights flow into
    partner_select)."""
    import fedml_tpu
    from fedml_tpu.sim.myavg import MyAvgSimulator
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub
    from fedml_tpu.trust.pipeline import TrustPipeline

    cfg = _myavg_cfg(comm_round=2)
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)

    class ZeroClient0(TrustPipeline):
        def __init__(self):
            super().__init__(cfg)

        @property
        def active(self):
            return True

        def on_aggregation(self, contribs, weights, global_vars, key, prev_delta=None):
            return contribs, weights.at[0].set(0.0), None

    sim = MyAvgSimulator(cfg, ds, model)
    sim.trust = ZeroClient0()
    sim._round_fn = jax.jit(sim._make_round_fn())
    sim._multi_round_fns = {}
    before = _leaf(sim.client_states, "Dense_0.kernel")[: sim._n_real].copy()
    sim.run_round()
    # client 0's weight is zero: the shared body still updates (other
    # clients aggregate), and the round runs without NaNs
    after = _leaf(sim.client_states, "Dense_0.kernel")[: sim._n_real]
    assert np.isfinite(after).all()
    assert np.abs(after - before).max() > 0


def test_ldp_noise_never_touches_retained_personal_state(eight_devices):
    """LDP noise applies to the SHIPPED update only: a personal head that
    never aggregates must be bit-identical with and without DP after a round
    (the retained local model is not part of the privacy surface)."""
    heads = {}
    for dp in (False, True):
        kw = dict(comm_round=2)
        if dp:
            kw.update(enable_dp=True, dp_solution_type="ldp",
                      mechanism_type="gaussian", epsilon=0.5, delta=1e-5,
                      sensitivity=1.0)  # LOUD noise: a leak would be visible
        sim = _build(_myavg_cfg(**kw))
        sim.run_round()  # round 0: default filter -> head unaggregated
        heads[dp] = _leaf(sim.client_states, "Dense_1.kernel")[: sim._n_real]
    np.testing.assert_array_equal(heads[False], heads[True])


def test_myavg_refuses_aggregation_replacing_defense(eight_devices):
    """Defenses that collapse the per-client deltas into one aggregate
    (on_agg overrides) are refused; weight-masking Krum is fine and runs."""
    with pytest.raises(NotImplementedError, match="replaces the|per-client"):
        _build(_myavg_cfg(enable_defense=True, defense_type="geometric_median"))
    # krum masks weights in before() — composes, and the round runs
    sim = _build(_myavg_cfg(comm_round=2, enable_defense=True,
                            defense_type="krum", krum_param_m=3,
                            byzantine_client_num=1))
    sim.run_round()


def test_myavg_still_refuses_secagg(eight_devices):
    with pytest.raises(NotImplementedError, match="secagg"):
        _build(_myavg_cfg(enable_secagg=True))


def test_condshift_personalization_beats_fedavg(eight_devices):
    """The MyAvg-wins benchmark (round-3 verdict item 8), CI-sized: under
    cluster-dependent class conditionals, layer-selective personalization
    scored on per-client test shards beats FedAvg by a wide margin (full
    recipe + ablations: scripts/myavg_condshift.py -> MYAVG_r4.json)."""
    import fedml_tpu
    from fedml_tpu.arguments import Config
    from fedml_tpu.runner import FedMLRunner

    base = dict(
        dataset="synthetic_condshift", model="mlp",
        client_num_in_total=10, client_num_per_round=10, comm_round=25,
        epochs=2, batch_size=32, learning_rate=0.5,
        synthetic_train_size=1500, synthetic_test_size=2000,
        frequency_of_the_test=25, random_seed=0, compute_dtype="float32",
        extra={"condshift_clusters": 2, "condshift_scale": 2.5},
    )
    cfg = Config(federated_optimizer="FedAvg", **base)
    fedml_tpu.init(cfg)
    h = FedMLRunner(cfg).run()
    fed_acc = [x["test_acc"] for x in h if "test_acc" in x][-1]

    cfg2 = Config(federated_optimizer="MyAvg",
                  agg_unselect_layer=("Dense_1",),
                  agg_mod_list=(9999,), agg_mod_dict={9999: {}},
                  cka_any_select_layer=("Dense_1",), cka_select_topk=4,
                  **base)
    fedml_tpu.init(cfg2)
    r2 = FedMLRunner(cfg2)
    r2.run()
    pers = r2.runner.evaluate_personalized()

    # FedAvg is capped by contradictory label mappings (~0.5 structural);
    # personalization resolves each client's own conditional
    assert fed_acc < 0.55, fed_acc
    assert pers["personalized_test_acc_mean"] > fed_acc + 0.2, (pers, fed_acc)
    assert pers["personalized_test_acc_min"] > 0.55, pers


def test_myavg_rejects_sp_backend(eight_devices):
    with pytest.raises(NotImplementedError):
        _build(_myavg_cfg(backend_sim="sp"))


def test_myavg_refuses_dead_filter_substrings(eight_devices):
    """A filter substring matching no leaf silently degenerates MyAvg to
    plain FedAvg (the torch-vs-flax naming trap) — it must refuse loudly."""
    with pytest.raises(ValueError, match="match NO model leaf"):
        _build(_myavg_cfg(agg_unselect_layer=("head",)))  # torch name, not flax
    with pytest.raises(ValueError, match="selects zero leaves"):
        _build(_myavg_cfg(cka_any_select_layer=("Dense_1",),
                          cka_unselect_layer=("Dense_1",)))
