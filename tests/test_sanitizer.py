"""Runtime lock sanitizer (ISSUE 9) — unit tests on the instrumented-lock
core plus the tier-1 gate: the existing async/comm e2e surface, run under
``FEDML_TPU_LOCKSAN=1`` in a subprocess, must complete with ZERO witnessed
lock-order inversions.

Unit tests build the wrappers directly (no ``threading.Lock`` patching), so
they cannot perturb the rest of the suite; only the subprocess test and the
no-op test exercise the install path.
"""

import json
import os
import subprocess
import sys
import threading
import time
from pathlib import Path

import pytest

from fedml_tpu.analysis.sanitizer import (
    ENV_FLAG, ENV_REPORT, LockSanitizer, _SanLock, _SanRLock,
    maybe_install_from_env,
)

REPO_ROOT = Path(__file__).resolve().parent.parent


def make_locks(san, *sites):
    return [_SanLock(san, s) for s in sites]


# -- ordering graph -----------------------------------------------------------

def test_consistent_order_records_edges_but_no_inversion():
    san = LockSanitizer()
    a, b = make_locks(san, "fedml_tpu/x.py:1", "fedml_tpu/x.py:2")
    for _ in range(3):
        with a:
            with b:
                pass
    rep = san.report()
    assert rep["locks_instrumented"] == 2
    assert rep["edges_observed"] == 1
    assert rep["inversions"] == []


def test_inversion_across_threads_is_witnessed():
    """A->B on one thread, then B->A on another (sequentially, so the test
    itself cannot deadlock) — the instance graph gains a 2-cycle."""
    san = LockSanitizer()
    a, b = make_locks(san, "fedml_tpu/x.py:10", "fedml_tpu/x.py:20")

    def ab():
        with a:
            with b:
                pass

    def ba():
        with b:
            with a:
                pass

    t1 = threading.Thread(target=ab)
    t1.start()
    t1.join()
    t2 = threading.Thread(target=ba)
    t2.start()
    t2.join()
    rep = san.report()
    assert len(rep["inversions"]) == 1
    inv = rep["inversions"][0]
    assert set(inv["locks"]) == {"fedml_tpu/x.py:10", "fedml_tpu/x.py:20"}
    # both directions carry a witness with a thread name and stack
    assert len(inv["witnessed_edges"]) == 2
    assert all(w["stack"] for w in inv["witnessed_edges"])


def test_three_lock_rotation_cycle_detected():
    """A->B, B->C, C->A: no 2-cycle anywhere, still a deadlockable cycle."""
    san = LockSanitizer()
    a, b, c = make_locks(san, "fedml_tpu/r.py:1", "fedml_tpu/r.py:2", "fedml_tpu/r.py:3")
    def nest(first, second):
        with first:
            with second:
                pass

    for first, second in ((a, b), (b, c), (c, a)):
        t = threading.Thread(target=nest, args=(first, second))
        t.start()
        t.join()
    rep = san.report()
    assert len(rep["inversions"]) == 1
    assert len(rep["inversions"][0]["locks"]) == 3


def test_same_thread_nesting_both_orders_is_also_flagged():
    """Even on ONE thread, with-A-take-B in one call path and with-B-take-A
    in another is latent: two threads running those paths concurrently
    deadlock."""
    san = LockSanitizer()
    a, b = make_locks(san, "fedml_tpu/y.py:1", "fedml_tpu/y.py:2")
    with a:
        with b:
            pass
    with b:
        with a:
            pass
    assert len(san.report()["inversions"]) == 1


# -- hold-time accounting ------------------------------------------------------

def test_hold_times_and_long_hold_outliers():
    san = LockSanitizer(long_hold_s=0.05)
    (lk,) = make_locks(san, "fedml_tpu/slow.py:9")
    with lk:
        time.sleep(0.08)
    with lk:
        pass
    rep = san.report()
    stats = rep["hold_stats"]["fedml_tpu/slow.py:9"]
    assert stats["holds"] == 2
    assert stats["max_s"] >= 0.05
    assert len(rep["long_holds"]) == 1
    outlier = rep["long_holds"][0]
    assert outlier["site"] == "fedml_tpu/slow.py:9" and outlier["held_s"] >= 0.05
    assert outlier["stack"], "long holds must carry the holder's stack"


def test_rlock_reentry_is_not_an_edge_and_times_once():
    san = LockSanitizer()
    r = _SanRLock(san, "fedml_tpu/re.py:5")
    with r:
        with r:
            pass
    rep = san.report()
    assert rep["edges_observed"] == 0
    assert rep["hold_stats"]["fedml_tpu/re.py:5"]["holds"] == 1


def test_condition_over_instrumented_rlock_releases_during_wait():
    """Condition.wait must not be timed as one giant hold (the lock is
    released for the duration) and must keep working on the wrapper."""
    san = LockSanitizer(long_hold_s=0.1)
    r = _SanRLock(san, "fedml_tpu/cv.py:7")
    cv = threading.Condition(r)
    fired = []

    def waiter():
        with cv:
            cv.wait(timeout=5.0)
            fired.append(True)

    t = threading.Thread(target=waiter)
    t.start()
    time.sleep(0.3)  # let the waiter park well past long_hold_s
    with cv:
        cv.notify_all()
    t.join(timeout=5.0)
    assert fired == [True]
    rep = san.report()
    assert rep["long_holds"] == [], rep["long_holds"]


def test_non_blocking_acquire_failure_records_nothing():
    san = LockSanitizer()
    a, b = make_locks(san, "fedml_tpu/nb.py:1", "fedml_tpu/nb.py:2")
    with a:
        b.acquire()
    contender = []
    t = threading.Thread(target=lambda: contender.append(b.acquire(blocking=False)))
    t.start()
    t.join()
    assert contender == [False]  # held elsewhere: non-blocking attempt fails
    b.release()
    # the failed attempt must leave no phantom hold and no bogus edge
    with b:
        pass
    rep = san.report()
    assert rep["hold_stats"]["fedml_tpu/nb.py:2"]["holds"] == 2
    assert rep["edges_observed"] == 1  # only the a->b nesting


# -- gating --------------------------------------------------------------------

def test_env_unset_is_a_strict_noop(monkeypatch):
    monkeypatch.delenv(ENV_FLAG, raising=False)
    before = threading.Lock
    assert maybe_install_from_env() is None
    assert threading.Lock is before


def test_install_instruments_only_package_locks():
    """Under FEDML_TPU_LOCKSAN=1 in a fresh process, a lock created from
    fedml_tpu code is wrapped while a stdlib/user lock stays raw."""
    code = (
        "import os, threading\n"
        "os.environ['FEDML_TPU_LOCKSAN'] = '1'\n"
        "from fedml_tpu.analysis.sanitizer import maybe_install_from_env, active\n"
        "san = maybe_install_from_env()\n"
        "assert san is not None and active() is san\n"
        "mine = threading.Lock()\n"                 # test-file site: raw
        "assert type(mine).__name__ != '_SanLock', type(mine)\n"
        "from fedml_tpu.obs.health import ClientHealthLedger\n"
        "led = ClientHealthLedger()\n"              # package site: wrapped
        "assert type(led._lock).__name__ == '_SanLock', type(led._lock)\n"
        "led.observe_rtt(1, 0.05)\n"
        "assert led.score(1) == 1.0\n"
        "rep = san.report()\n"
        "assert rep['locks_instrumented'] >= 1\n"
        "print('NOOP_OK')\n"
    )
    res = subprocess.run([sys.executable, "-c", code], cwd=str(REPO_ROOT),
                         capture_output=True, text=True, timeout=120,
                         env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert res.returncode == 0, res.stderr[-3000:]
    assert "NOOP_OK" in res.stdout


# -- the tier-1 gate: async/comm suite under the sanitizer ---------------------

#: the gate's collection is MARKER-driven (ISSUE 11 satellite): any test
#: carrying ``@pytest.mark.locksan`` joins the sanitizer run — no more
#: hard-coded id list.  Current members: the buffered-async server with
#: real training clients (receive loops + watchdog timer + health ledger),
#: the event-heap soak fleet (worker threads + condition), the synchronous
#: cross-silo protocol (straggler timer + agg lock), and the serving
#: hot-swap e2e (batcher dispatcher + watcher thread + swap controller).
#: The file list only bounds collection cost; `-m locksan` selects.
LOCKSAN_GATE_FILES = [
    "tests/test_async_agg.py",
    "tests/test_comm_cross_silo.py",
    "tests/test_serving_batch.py",
]


def test_locksan_marker_is_registered_and_populated():
    """The marker exists (conftest) and collects at least the four threaded
    e2e surfaces the gate was built around — an empty `-m locksan` run
    would pass vacuously and silently disarm the gate."""
    res = subprocess.run(
        [sys.executable, "-m", "pytest", *LOCKSAN_GATE_FILES, "-m", "locksan",
         "--collect-only", "-q", "-p", "no:cacheprovider", "-p", "no:randomly"],
        cwd=str(REPO_ROOT), env={**os.environ, "JAX_PLATFORMS": "cpu"},
        capture_output=True, text=True, timeout=300,
    )
    assert res.returncode == 0, res.stdout[-2000:] + res.stderr[-1000:]
    collected = [l for l in res.stdout.splitlines() if "::" in l]
    assert len(collected) >= 4, (
        f"locksan marker collects only {collected} — the gate is shrinking")


def test_locksan_gate_async_comm_suite_has_zero_inversions(tmp_path):
    """Run every @pytest.mark.locksan threaded e2e with the sanitizer
    installed; the run must pass AND witness zero lock-order inversions.
    An inversion here means a real deadlock interleaving exists in the
    production server — fix the ordering, do not relax this test."""
    report = tmp_path / "locksan.json"
    env = {
        **os.environ,
        ENV_FLAG: "1",
        ENV_REPORT: str(report),
        "JAX_PLATFORMS": "cpu",
    }
    res = subprocess.run(
        [sys.executable, "-m", "pytest", *LOCKSAN_GATE_FILES, "-m", "locksan",
         "-q", "-p", "no:cacheprovider", "-p", "no:randomly"],
        cwd=str(REPO_ROOT), env=env, capture_output=True, text=True,
        timeout=900,
    )
    assert res.returncode == 0, (
        f"locksan-marked suite failed under FEDML_TPU_LOCKSAN=1:\n"
        f"{res.stdout[-3000:]}\n{res.stderr[-2000:]}")
    assert report.exists(), "sanitizer report was not dumped at exit"
    rep = json.loads(report.read_text())
    assert rep["locks_instrumented"] > 0, "sanitizer saw no package locks"
    assert rep["edges_observed"] > 0, (
        "no nested acquisitions observed — the gate is not exercising the "
        "threaded paths it exists for")
    assert rep["inversions"] == [], (
        "lock-order inversion(s) witnessed in the async/comm suite:\n"
        + json.dumps(rep["inversions"], indent=1))
