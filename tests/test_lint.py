"""The lint engine's own tests (ISSUE 5).

Each GL rule is proven BOTH ways on fixture packages — it fires on the
violation and goes quiet under a ``# graftlint: disable=...`` — plus the
baseline round-trips, and the real ``fedml_tpu`` package lints clean with
the SHIPPED (empty) baseline: the same invariant the tier-1 gate enforces
forever after.
"""

import json
import textwrap
from pathlib import Path

import pytest

from fedml_tpu.analysis.engine import run_lint
from fedml_tpu.analysis.findings import (
    Finding, load_baseline, parse_suppressions, save_baseline,
)

PKG_ROOT = Path(__file__).resolve().parent.parent / "fedml_tpu"

#: a minimal registry module for GL001 fixtures
FLAGS_FIXTURE = """
    class FlagSpec:
        def __init__(self, name, type, default, doc):
            pass

    FLAGS = {
        "declared_flag": FlagSpec("declared_flag", "int", 1, "declared + read"),
        "dead_flag": FlagSpec("dead_flag", "bool", False, "declared, never read"),
    }
"""


def lint_files(tmp_path, files):
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return run_lint(tmp_path)


def rules_fired(result):
    return {f.rule for f in result.findings}


# -- GL001: flag registry -----------------------------------------------------

def test_gl001_undeclared_read_fires(tmp_path):
    r = lint_files(tmp_path, {
        "core/flags.py": FLAGS_FIXTURE,
        "mod.py": """
            from .core.flags import cfg_extra

            def f(cfg):
                return cfg_extra(cfg, "mystery_flag")
        """,
    })
    assert any(f.rule == "GL001" and "mystery_flag" in f.message for f in r.findings)


def test_gl001_declared_cfg_extra_read_is_clean(tmp_path):
    r = lint_files(tmp_path, {
        "core/flags.py": FLAGS_FIXTURE,
        "mod.py": """
            from .core.flags import cfg_extra

            def f(cfg):
                return cfg_extra(cfg, "declared_flag", 3)
        """,
    })
    assert not any(f.symbol == "undeclared:declared_flag" for f in r.findings)
    # only the dead_flag declaration should fire
    assert [f.symbol for f in r.findings] == ["dead:dead_flag"]


def test_gl001_dead_declaration_fires_and_reads_clear_it(tmp_path):
    r = lint_files(tmp_path, {"core/flags.py": FLAGS_FIXTURE, "mod.py": "x = 1\n"})
    symbols = {f.symbol for f in r.findings if f.rule == "GL001"}
    assert symbols == {"dead:dead_flag", "dead:declared_flag"}


def test_gl001_legacy_idioms_fire(tmp_path):
    r = lint_files(tmp_path, {
        "core/flags.py": FLAGS_FIXTURE,
        "mod.py": """
            def f(cfg):
                extra = getattr(cfg, "extra", {}) or {}
                a = extra.get("declared_flag", 1)
                b = (getattr(cfg, "extra", {}) or {}).get("inline_flag")
                c = extra["declared_flag"]
                return a, b, c
        """,
    })
    syms = {f.symbol for f in r.findings if f.rule == "GL001"}
    assert "legacy:declared_flag" in syms           # .get and subscript
    assert "legacy:inline_flag" in syms             # inline chained idiom
    assert "undeclared:inline_flag" in syms         # and it is undeclared too


def test_gl001_nonliteral_name_fires_and_suppression_silences(tmp_path):
    r = lint_files(tmp_path, {
        "core/flags.py": FLAGS_FIXTURE,
        "mod.py": """
            from .core.flags import cfg_extra

            def f(cfg, name):
                bad = cfg_extra(cfg, name)
                ok = cfg_extra(cfg, name)  # graftlint: disable=GL001(fixture reason)
                return bad, ok
        """,
    })
    nonliteral = [f for f in r.findings if f.symbol.startswith("nonliteral")]
    assert len(nonliteral) == 1
    assert len(r.suppressed) == 1


def test_gl001_duck_typed_getattr_counts_as_read(tmp_path):
    # getattr(cfg, "<declared flag>", d) keeps a declaration alive but is
    # not itself flagged (Config.__getattr__ falls through to extra)
    r = lint_files(tmp_path, {
        "core/flags.py": FLAGS_FIXTURE,
        "mod.py": """
            def f(cfg):
                return getattr(cfg, "declared_flag", False)
        """,
    })
    assert [f.symbol for f in r.findings] == ["dead:dead_flag"]


# -- GL002: jit purity --------------------------------------------------------

GL002_CASES = [
    ("import time\nimport jax\n\ndef step(x):\n    t = time.time()\n    return x + t\n\njitted = jax.jit(step)\n",
     "host clock"),
    ("import numpy as np\nimport jax\n\ndef step(x):\n    return x + np.random.rand()\n\njitted = jax.jit(step)\n",
     "host randomness"),
    ("import jax\n\ndef step(x):\n    print(x)\n    return x\n\njitted = jax.jit(step)\n",
     "print"),
    ("import logging\nimport jax\nlog = logging.getLogger(__name__)\n\ndef step(x):\n    log.info('hi')\n    return x\n\njitted = jax.jit(step)\n",
     "logging"),
    ("import jax\n\ndef outer():\n    n = 0\n    def step(x):\n        nonlocal n\n        n += 1\n        return x\n    return jax.jit(step)\n",
     "nonlocal"),
]


@pytest.mark.parametrize("src,what", GL002_CASES, ids=[w for _, w in GL002_CASES])
def test_gl002_impurities_fire(tmp_path, src, what):
    r = lint_files(tmp_path, {"mod.py": src})
    assert rules_fired(r) == {"GL002"}, (what, r.render())


def test_gl002_metric_and_scan_and_decorator_forms(tmp_path):
    r = lint_files(tmp_path, {"mod.py": """
        import jax
        from .obs import registry as obsreg

        COUNTER = obsreg.REGISTRY.counter("fedml_fixture_total", "doc")

        @jax.jit
        def decorated(x):
            COUNTER.inc()
            return x

        def body(carry, x):
            COUNTER.inc()
            return carry, x

        def run(xs):
            return jax.lax.scan(body, 0, xs)
    """})
    gl002 = [f for f in r.findings if f.rule == "GL002"]
    assert len(gl002) == 2  # the decorated fn AND the scan body
    assert all("metric mutation" in f.message for f in gl002)


def test_gl002_pure_fn_and_suppression(tmp_path):
    r = lint_files(tmp_path, {"mod.py": """
        import time
        import jax

        def pure(x):
            return x * 2

        def timed(x):
            t = time.time()  # graftlint: disable=GL002(fixture: trace-time stamp is intended)
            return x + t

        a = jax.jit(pure)
        b = jax.jit(timed)
    """})
    assert not r.findings
    assert len(r.suppressed) == 1


def test_gl002_profiler_and_registry_get_allowlisted(tmp_path):
    """ISSUE 20 satellite: deliberately trace-time instrumentation —
    ``REGISTRY.get`` cost-model reads and profiler ``note_program`` /
    window hooks — is allowlisted; a mutating REGISTRY chain still fires,
    and impurities nested in an allowlisted call's arguments still fire."""
    r = lint_files(tmp_path, {"mod.py": """
        import jax
        from obs.registry import REGISTRY

        def noted(x):
            profiler.note_program("sim.step", flops=2.0)
            self_like.attributor.maybe_start(0)
            fam = REGISTRY.get("fedml_cost_flops")
            return x * 2

        clean = jax.jit(noted)
    """})
    assert not [f for f in r.findings if f.rule == "GL002"], r.render()

    r2 = lint_files(tmp_path / "fire", {"mod.py": """
        import time
        import jax
        from obs.registry import REGISTRY

        def dirty(x):
            REGISTRY.counter("c", "doc")           # registration: still impure
            profiler.note_program(time.time())     # impure ARG inside allowed call
            return x

        bad = jax.jit(dirty)
    """})
    gl002 = [f for f in r2.findings if f.rule == "GL002"]
    assert len(gl002) == 2, r2.render()
    assert any("registry call" in f.message for f in gl002)
    assert any("host clock" in f.message for f in gl002)

def test_gl003_read_after_donation_fires(tmp_path):
    r = lint_files(tmp_path, {"mod.py": """
        import jax

        def run(state, x):
            step = jax.jit(lambda s, v: s, donate_argnums=(0,))
            out = step(state, x)
            return state  # read after donation
    """})
    assert [f.rule for f in r.findings] == ["GL003"]
    assert "state" in r.findings[0].message


def test_gl003_rebinding_is_clean_and_conditional_donate_unions(tmp_path):
    r = lint_files(tmp_path, {"mod.py": """
        import jax

        def ok(state, x):
            step = jax.jit(lambda s, v: s, donate_argnums=(0,))
            state = step(state, x)   # the correct donate idiom: rebind
            return state

        def conditional(state, x, on_cpu):
            donate = () if on_cpu else (0,)
            step = jax.jit(lambda s, v: s, donate_argnums=donate)
            out = step(state, x)
            return state  # donated on SOME path -> finding
    """})
    assert len(r.findings) == 1
    assert r.findings[0].line > 0 and r.findings[0].rule == "GL003"


def test_gl003_suppression(tmp_path):
    r = lint_files(tmp_path, {"mod.py": """
        import jax

        def run(state, x):
            step = jax.jit(lambda s, v: s, donate_argnums=(0,))
            out = step(state, x)
            return state  # graftlint: disable=GL003(fixture: CPU-gated path)
    """})
    assert not r.findings and len(r.suppressed) == 1


def test_gl003_donate_argnames_taints_keyword_and_positional(tmp_path):
    """donate_argnames: a keyword arg matching a donated name is tainted, and
    when the jitted callable is an inline lambda the names also map to
    positions, so the positional call form is caught too."""
    r = lint_files(tmp_path, {"mod.py": """
        import jax

        def kw_form(state, x):
            step = jax.jit(lambda state, v: state, donate_argnames=("state",))
            out = step(state=state, v=x)
            return state  # read after donation via argname

        def pos_form(state, x):
            step = jax.jit(lambda state, v: state, donate_argnames=("state",))
            out = step(state, x)
            return state  # same donation, positional call

        def rebind_ok(state, x):
            step = jax.jit(lambda state, v: state, donate_argnames=("state",))
            state = step(state, x)
            return state
    """})
    assert [f.rule for f in r.findings] == ["GL003", "GL003"]
    assert all("state" in f.message for f in r.findings)


def test_gl003_splat_covering_donated_position_taints_sequence(tmp_path):
    """``step(x, *rest)`` with a donated position inside the splat taints
    ``rest`` itself; a splat past every donated position stays clean."""
    r = lint_files(tmp_path, {"mod.py": """
        import jax

        def bad(rest, x):
            step = jax.jit(lambda a, b, c: a, donate_argnums=(1, 2))
            out = step(x, *rest)
            return rest  # elements were donated through the splat

        def ok(rest, x):
            step = jax.jit(lambda a, b, c: a, donate_argnums=(0,))
            out = step(x, *rest)
            return rest  # donated position 0 was the explicit arg
    """})
    assert [f.rule for f in r.findings] == ["GL003"]
    assert "rest" in r.findings[0].message


# -- GL006: tracer branches ---------------------------------------------------

def test_gl006_branch_on_param_and_derived_value_fires(tmp_path):
    r = lint_files(tmp_path, {"mod.py": """
        import jax

        def step(x):
            y = x + 1
            if y > 0:
                return x
            while x > 2:
                x = x - 1
            return y

        jitted = jax.jit(step)
    """})
    gl006 = [f for f in r.findings if f.rule == "GL006"]
    assert len(gl006) == 2  # the if AND the while, both on traced values
    assert {"`if` branch" in f.message or "`while` loop" in f.message
            for f in gl006} == {True}


def test_gl006_scan_body_and_decorator_forms(tmp_path):
    r = lint_files(tmp_path, {"mod.py": """
        import jax

        @jax.jit
        def decorated(x):
            return x if x else -x

        def run(xs):
            def body(carry, x):
                if carry:
                    carry = carry + x
                return carry, x
            return jax.lax.scan(body, 0, xs)
    """})
    gl006 = [f for f in r.findings if f.rule == "GL006"]
    assert len(gl006) == 2  # the decorated IfExp AND the scan body's if


def test_gl006_static_predicates_stay_clean(tmp_path):
    """Structure tests on tracers are trace-time-static by design: identity
    vs None, isinstance, len(), and the static array attributes."""
    r = lint_files(tmp_path, {"mod.py": """
        import jax

        def step(x, cs):
            if cs is not None:
                x = x + 1
            if isinstance(x, tuple):
                return x[0]
            if x.ndim == 2:
                x = x.sum(-1)
            if len(x) > 3:
                x = x[:3]
            if x.shape[0] % 2 == 0:
                x = x * 2
            return x

        jitted = jax.jit(step)
    """})
    assert not [f for f in r.findings if f.rule == "GL006"], r.render()


def test_gl006_untraced_function_and_suppression(tmp_path):
    r = lint_files(tmp_path, {"mod.py": """
        import jax

        def host_helper(x):
            if x:  # never traced: plain python is fine
                return 1
            return 0

        def step(x):
            if x:  # graftlint: disable=GL006(fixture: concrete at trace time)
                return x
            return -x

        jitted = jax.jit(step)
    """})
    assert not r.findings
    assert len(r.suppressed) == 1


# -- GL004: lock discipline ---------------------------------------------------

GL004_SRC = """
    import threading

    class Manager:
        def __init__(self):
            self._lock = threading.Lock()
            self.counter = 0   # ctor writes are exempt

        def locked_write(self):
            with self._lock:
                self.counter += 1

        def racy_read(self):
            return self.counter

        def documented(self):  # graftlint: disable=GL004(caller holds _lock)
            return self.counter
"""


def test_gl004_fires_outside_lock_and_def_line_suppression_covers_body(tmp_path):
    r = lint_files(tmp_path, {"mod.py": GL004_SRC})
    assert [f.rule for f in r.findings] == ["GL004"]
    assert "Manager.counter" in r.findings[0].symbol
    assert len(r.suppressed) == 1  # documented() is covered by its def line


def test_gl004_lockless_class_is_ignored(tmp_path):
    r = lint_files(tmp_path, {"mod.py": """
        class Plain:
            def __init__(self):
                self.counter = 0

            def bump(self):
                self.counter += 1
    """})
    assert not r.findings


# -- GL005: metric namespace --------------------------------------------------

def test_gl005_bad_name_label_and_le(tmp_path):
    r = lint_files(tmp_path, {"mod.py": """
        from .obs import registry as obsreg

        BAD_NAME = obsreg.REGISTRY.counter("unnamespaced_total", "doc")
        BAD_LABEL = obsreg.REGISTRY.gauge("fedml_ok", "doc", labels=("Client",))
        RESERVED = obsreg.REGISTRY.histogram("fedml_h", "doc", labels=("le",))
        GOOD = obsreg.REGISTRY.counter("fedml_good_total", "doc", labels=("client",))
    """})
    syms = {f.symbol for f in r.findings if f.rule == "GL005"}
    assert syms == {"unnamespaced_total", "fedml_ok:Client", "fedml_h:le"}


def test_gl005_suppression(tmp_path):
    r = lint_files(tmp_path, {"mod.py": """
        from .obs import registry as obsreg

        LEGACY = obsreg.REGISTRY.counter("legacy_total", "doc")  # graftlint: disable=GL005(fixture: grandfathered dashboard)
    """})
    assert not r.findings and len(r.suppressed) == 1


# -- GL007: lock order --------------------------------------------------------

def test_gl007_nested_with_cycle_fires(tmp_path):
    """A->B in one method, B->A in another: the classic ABBA deadlock."""
    r = lint_files(tmp_path, {"mod.py": """
        import threading

        class M:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def ab(self):
                with self._a:
                    with self._b:
                        pass

            def ba(self):
                with self._b:
                    with self._a:
                        pass
    """})
    cyc = [f for f in r.findings if f.rule == "GL007" and f.symbol.startswith("cycle:")]
    assert len(cyc) == 1 and "M._a" in cyc[0].message and "M._b" in cyc[0].message


def test_gl007_one_hop_cycle_and_self_deadlock(tmp_path):
    """The interprocedural hop: holding A, call a self-method that takes B
    (and the re-take of a non-reentrant lock through a helper)."""
    r = lint_files(tmp_path, {"mod.py": """
        import threading

        class M:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def outer(self):
                with self._a:
                    self.take_b()

            def take_b(self):
                with self._b:
                    pass

            def reverse(self):
                with self._b:
                    with self._a:
                        pass

            def recurse(self):
                with self._a:
                    self.take_a()

            def take_a(self):
                with self._a:
                    pass
    """})
    syms = {f.symbol for f in r.findings if f.rule == "GL007"}
    assert any(s.startswith("cycle:") for s in syms), r.render()
    assert any(s.startswith("selfdeadlock:M.recurse") for s in syms), r.render()


def test_gl007_rlock_reentry_and_ordered_nesting_stay_clean(tmp_path):
    r = lint_files(tmp_path, {"mod.py": """
        import threading

        class M:
            def __init__(self):
                self._a = threading.Lock()
                self._r = threading.RLock()

            def consistent_ab(self):
                with self._a:
                    with self._r:
                        pass

            def also_ab(self):
                with self._a:
                    self.take_r()

            def take_r(self):
                with self._r:
                    pass

            def reenter(self):
                with self._r:
                    self.take_r()  # RLock: reentry is the point
    """})
    assert not [f for f in r.findings if f.rule == "GL007"], r.render()


def test_gl007_blocking_ops_under_lock_fire_and_suppress(tmp_path):
    r = lint_files(tmp_path, {"mod.py": """
        import subprocess
        import time
        import threading

        class M:
            def __init__(self):
                self._lock = threading.Lock()
                self._queue = None

            def sleepy(self):
                with self._lock:
                    time.sleep(1.0)

            def drains(self):
                with self._lock:
                    item = self._queue.get()
                return item

            def spawns(self):
                with self._lock:
                    subprocess.run(["true"])

            def syncs(self, x):
                with self._lock:
                    x.block_until_ready()

            def documented(self):  # graftlint: disable=GL007(fixture: the lock serializes this send by design)
                with self._lock:
                    self._queue.sendall(b"x")

            def fine(self):
                time.sleep(1.0)  # no lock held
                with self._lock:
                    y = self._queue.get(timeout=1.0)  # bounded
                return y
    """})
    gl007 = [f for f in r.findings if f.rule == "GL007"]
    descs = {f.symbol for f in gl007}
    assert {"block:M.sleepy:time.sleep()",
            "block:M.drains:.get() (blocking queue read, no timeout)",
            "block:M.spawns:subprocess.run()",
            "block:M.syncs:.block_until_ready()"} <= descs, r.render()
    assert len(r.suppressed) == 1
    assert not any("fine" in f.symbol for f in gl007)


CROSS_OBJECT_CYCLE = """
    import threading

    class Ledger:
        def __init__(self, mgr):
            self._lock = threading.Lock()
            self.mgr = Manager()

        def note(self):
            with self._lock:
                pass

        def flush(self):{flush_suppress}
            with self._lock:
                self.mgr.poke()

    class Manager:
        def __init__(self):
            self._agg_lock = threading.Lock()
            self.ledger = Ledger(self)

        def poke(self):
            with self._agg_lock:
                pass

        def on_upload(self):{upload_suppress}
            with self._agg_lock:
                self.ledger.note()
"""


def test_gl007_cross_object_one_hop_cycle_fires(tmp_path):
    """The PR-9 follow-on: holding the manager lock, call a LEDGER method
    that takes the ledger lock — and a ledger method holding its lock calls
    back into the manager.  Two objects, opposite orders, one deadlock; the
    one-object-hop resolution must see it at lint time."""
    r = lint_files(tmp_path, {"mod.py": CROSS_OBJECT_CYCLE.format(
        flush_suppress="", upload_suppress="")})
    cyc = [f for f in r.findings if f.rule == "GL007" and f.symbol.startswith("cycle:")]
    assert len(cyc) == 1, r.render()
    assert "Manager._agg_lock" in cyc[0].message and "Ledger._lock" in cyc[0].message


def test_gl007_cross_object_cycle_suppresses(tmp_path):
    """def-line suppressions on both edge-recording methods silence the
    cycle (the anchor line always lands inside one of them)."""
    sup = "  # graftlint: disable=GL007(fixture: callback ordering is documented lock-free)"
    r = lint_files(tmp_path, {"mod.py": CROSS_OBJECT_CYCLE.format(
        flush_suppress=sup, upload_suppress=sup)})
    assert not [f for f in r.findings if f.rule == "GL007"
                and f.symbol.startswith("cycle:")], r.render()
    assert r.suppressed, "the cycle should be recorded as suppressed"


def test_gl007_cross_object_one_way_edge_is_clean(tmp_path):
    """manager lock -> ledger lock with NO reverse path (the real health-
    ledger shape, and the journal/recovery locks): an edge, not a cycle —
    must stay clean."""
    r = lint_files(tmp_path, {"mod.py": """
        import threading

        class Ledger:
            def __init__(self):
                self._lock = threading.Lock()

            def note(self):
                with self._lock:
                    pass

        class Manager:
            def __init__(self):
                self._agg_lock = threading.Lock()
                self.ledger = Ledger()

            def on_upload(self):
                with self._agg_lock:
                    self.ledger.note()
    """})
    assert not [f for f in r.findings if f.rule == "GL007"], r.render()


def test_gl007_cross_object_fluent_builder_attr_resolves(tmp_path):
    """``self.ledger = Ledger().attach()`` (the ClientHealthLedger idiom)
    still resolves the attr's class through the fluent chain — proven by the
    cycle FIRING through the fluent-assigned attr."""
    r = lint_files(tmp_path, {"mod.py": """
        import threading

        class Ledger:
            def __init__(self):
                self._lock = threading.Lock()
                self.mgr = Manager()

            def attach(self):
                return self

            def note(self):
                with self._lock:
                    pass

            def flush(self):
                with self._lock:
                    self.mgr.poke()

        class Manager:
            def __init__(self):
                self._agg_lock = threading.Lock()
                self.ledger = Ledger().attach()

            def poke(self):
                with self._agg_lock:
                    pass

            def on_upload(self):
                with self._agg_lock:
                    self.ledger.note()
    """})
    cyc = [f for f in r.findings if f.rule == "GL007" and f.symbol.startswith("cycle:")]
    assert len(cyc) == 1, r.render()


# -- GL008: thread-shared-state races ----------------------------------------

GL008_RACY = """
    import threading

    class Pump:
        def __init__(self):
            self._lock = threading.Lock()
            self.pending = []
            self.total = 0

        def start(self):
            threading.Thread(target=self._worker, daemon=True).start()

        def _worker(self):
            with self._lock:
                batch, self.pending = self.pending, []
            self.total += len(batch)   # RMW outside the lock

        def push(self, item):
            with self._lock:
                self.pending.append(item)

        def stats(self):
            return self.total
"""


def test_gl008_unlocked_rmw_across_threads_fires(tmp_path):
    r = lint_files(tmp_path, {"mod.py": GL008_RACY})
    gl008 = [f for f in r.findings if f.rule == "GL008"]
    assert [f.symbol for f in gl008] == ["Pump.total"], r.render()
    assert "thread" in gl008[0].message


def test_gl008_common_lock_everywhere_is_clean(tmp_path):
    r = lint_files(tmp_path, {"mod.py": """
        import threading

        class Pump:
            def __init__(self):
                self._lock = threading.Lock()
                self.total = 0

            def start(self):
                threading.Thread(target=self._worker, daemon=True).start()

            def _worker(self):
                with self._lock:
                    self.total += 1

            def stats(self):
                with self._lock:
                    return self.total
    """})
    assert not [f for f in r.findings if f.rule == "GL008"], r.render()


def test_gl008_caller_holds_lock_inference(tmp_path):
    """A private helper whose every call site holds the lock analyzes as
    entered with it held — the PR-5 'caller holds the lock' methods do not
    re-fire under GL008."""
    r = lint_files(tmp_path, {"mod.py": """
        import threading

        class Pump:
            def __init__(self):
                self._lock = threading.Lock()
                self.total = 0

            def start(self):
                threading.Thread(target=self._worker, daemon=True).start()

            def _worker(self):
                with self._lock:
                    self._bump()

            def add(self):
                with self._lock:
                    self._bump()

            def _bump(self):
                self.total += 1  # every caller holds _lock
    """})
    assert not [f for f in r.findings if f.rule == "GL008"], r.render()


def test_gl008_handler_roots_and_single_receive_loop(tmp_path):
    """Registered comm handlers share ONE receive-loop root (no false race
    between two handlers), but handler-vs-caller still fires."""
    r = lint_files(tmp_path, {"mod.py": """
        class Manager:
            def __init__(self):
                self.round_idx = 0
                self.seen = 0

            def register(self):
                self.register_message_receive_handler(1, self.handle_a)
                self.register_message_receive_handler(2, self.handle_b)

            def handle_a(self, msg):
                self.seen += 1       # only ever touched on the receive loop

            def handle_b(self, msg):
                self.seen += 1

            def poll(self):
                self.round_idx += 1  # caller thread
                return self.round_idx

            def handle_c(self, msg):
                self.round_idx += 1
    """})
    gl008 = [f for f in r.findings if f.rule == "GL008"]
    assert [f.symbol for f in gl008] == [], r.render()
    # now make handle_c a registered handler too: round_idx becomes shared
    r2 = lint_files(tmp_path / "v2", {"mod.py": """
        class Manager:
            def __init__(self):
                self.round_idx = 0

            def register(self):
                self.register_message_receive_handler(3, self.handle_c)

            def poll(self):
                self.round_idx += 1
                return self.round_idx

            def handle_c(self, msg):
                self.round_idx += 1
    """})
    assert [f.symbol for f in r2.findings if f.rule == "GL008"] == ["Manager.round_idx"]


def test_gl008_sync_objects_callbacks_and_suppression(tmp_path):
    r = lint_files(tmp_path, {"mod.py": """
        import queue
        import threading

        class Worker:
            def __init__(self):
                self._q = queue.Queue()
                self._done = threading.Event()
                self.count = 0
                self.latch = False

            def start(self):
                threading.Thread(target=self._loop, daemon=True).start()
                add_comm_event_sink(self._on_event)

            def _loop(self):
                while not self._done.is_set():
                    self._q.get(timeout=0.1)   # sync objects: no race

            def _on_event(self, event):
                self.count += 1                # sink runs on the comm thread

            def bump(self):
                self.count += 1                # caller thread: race

            def stop(self):  # graftlint: disable=GL008(fixture: one-way latch)
                self.latch = True

            def latched(self):
                return self.latch
    """})
    gl008 = [f for f in r.findings if f.rule == "GL008"]
    assert [f.symbol for f in gl008] == ["Worker.count"], r.render()
    assert not any(f.symbol in ("Worker._q", "Worker._done") for f in gl008)


def test_gl008_closure_thread_target_is_its_own_root(tmp_path):
    r = lint_files(tmp_path, {"mod.py": """
        import threading

        class Ticker:
            def __init__(self):
                self.ticks = 0

            def start(self):
                def loop():
                    self.ticks += 1   # runs on the spawned thread
                threading.Thread(target=loop, daemon=True).start()

            def read_modify(self):
                self.ticks += 1       # caller thread
    """})
    assert [f.symbol for f in r.findings if f.rule == "GL008"] == ["Ticker.ticks"]


def test_gl008_unthreaded_class_and_ctor_only_writes_are_clean(tmp_path):
    r = lint_files(tmp_path, {"mod.py": """
        import threading

        class Config:
            def __init__(self):
                self.value = 1

            def read(self):
                return self.value

            def write(self):
                self.value = 2   # no thread ever starts: not concurrency

        class Threaded:
            def __init__(self):
                self.limit = 10   # written ONLY here

            def start(self):
                threading.Thread(target=self._loop, daemon=True).start()

            def _loop(self):
                return self.limit

            def read(self):
                return self.limit
    """})
    assert not [f for f in r.findings if f.rule == "GL008"], r.render()


# -- GL009: handler conformance -----------------------------------------------

def test_gl009_unhandled_send_fires_and_registration_clears(tmp_path):
    r = lint_files(tmp_path, {
        "defs.py": "MSG_TYPE_PING = 1\nMSG_TYPE_PONG = 2\n",
        "node.py": """
            from .defs import MSG_TYPE_PING, MSG_TYPE_PONG

            class Node:
                def register(self):
                    self.register_message_receive_handler(MSG_TYPE_PING, self.on_ping)

                def on_ping(self, msg):
                    self.send_message(Message(MSG_TYPE_PONG, 0, 1))

                def start(self):
                    self.send_message(Message(MSG_TYPE_PING, 0, 1))
        """,
    })
    gl009 = [f for f in r.findings if f.rule == "GL009"]
    assert [f.symbol for f in gl009] == ["unhandled:MSG_TYPE_PONG"], r.render()


def test_gl009_dead_handler_fires_and_wildcard_send_exempts(tmp_path):
    r = lint_files(tmp_path, {
        "node.py": """
            MSG_TYPE_A = 1
            MSG_TYPE_B = 2

            class Node:
                def register(self):
                    self.register_message_receive_handler(MSG_TYPE_A, self.on_a)
                    self.register_message_receive_handler(MSG_TYPE_B, self.on_b)

                def start(self):
                    self.send_message(Message(MSG_TYPE_A, 0, 1))
        """,
        "generic.py": """
            MSG_TYPE_C = 3

            class Generic:
                def register(self):
                    self.register_message_receive_handler(MSG_TYPE_C, self.on_c)

                def send_any(self, msg_type):
                    self.send_message(Message(msg_type, 0, 1))  # wildcard
        """,
    })
    gl009 = [f for f in r.findings if f.rule == "GL009"]
    # MSG_TYPE_B is provably dead; MSG_TYPE_C's module routes dynamic types
    assert [f.symbol for f in gl009] == ["dead:MSG_TYPE_B"], r.render()


def test_gl009_value_matching_ifexp_and_suppression(tmp_path):
    r = lint_files(tmp_path, {
        "a.py": """
            MSG_TYPE_INIT = 1
            MSG_TYPE_SYNC = 2

            class Server:
                def dispatch(self, first):
                    self.send_message(Message(MSG_TYPE_INIT if first else MSG_TYPE_SYNC, 0, 1))

                def external(self):
                    self.send_message(Message(MSG_TYPE_EXTERNAL, 0, 1))  # graftlint: disable=GL009(fixture: handled by an out-of-repo peer)
        """,
        "b.py": """
            class Client:
                def register(self):
                    self.register_message_receive_handler(1, self.on_init)
                    self.register_message_receive_handler(2, self.on_sync)
        """,
    })
    gl009 = [f for f in r.findings if f.rule == "GL009"]
    assert not gl009, r.render()
    assert len(r.suppressed) == 1


# -- GL010: hot-path host sync ------------------------------------------------

def test_gl010_hot_path_syncs_fire_and_reachability_extends(tmp_path):
    r = lint_files(tmp_path, {"sim/engine.py": """
        import jax
        import jax.numpy as jnp

        class MeshSimulator:
            def run_rounds(self, n):
                metrics = self._round_fn(n)
                loss = float(metrics)
                host = jax.device_get(metrics)
                if metrics > 0:
                    loss += 1
                return host

            def evaluate(self):
                return self._finish()

            def _finish(self):
                acc = jnp.mean([1.0])
                return acc.item()
    """})
    gl010 = [f for f in r.findings if f.rule == "GL010"]
    whats = "\n".join(f.message for f in gl010)
    assert len(gl010) == 4, r.render()
    assert "implicit device->host sync float()" in whats
    assert "explicit host sync jax.device_get()" in whats
    assert "branching/comparing on a device value" in whats
    # reachability: _finish is hit only through the `evaluate` root
    assert any("'MeshSimulator._finish'" in f.message and ".item()" in f.message
               for f in gl010)


def test_gl010_suppression_and_cold_modules_stay_clean(tmp_path):
    r = lint_files(tmp_path, {
        "sim/engine.py": """
            import jax

            class MeshSimulator:
                def run_round(self, r):
                    out = self._round_fn(r)
                    if jax.tree_util.tree_structure(out) == self._treedef:
                        r += 1  # treedef comparison is host metadata: clean
                    host = jax.device_get(out)  # graftlint: disable=GL010(the one chunk-end sync)
                    return {k: float(v) for k, v in host.items()}
        """,
        # same syncs in a module that is NOT a hot-path root: out of scope
        "tools/report.py": """
            import jax
            import jax.numpy as jnp

            def summarize(xs):
                acc = jnp.mean(xs)
                return float(jax.device_get(acc))
        """,
    })
    assert not [f for f in r.findings if f.rule == "GL010"], r.render()
    assert len(r.suppressed) == 1
    # device_get UNTAINTS: the post-sync float() unpacking raised no finding


# -- GL011: recompile hazards -------------------------------------------------

def test_gl011_loop_rewrap_and_varying_scalar_fire(tmp_path):
    r = lint_files(tmp_path, {"mod.py": """
        import jax

        step = jax.jit(lambda s: s)

        def loop(xs):
            total = 0
            for i, x in enumerate(xs):
                fresh = jax.jit(lambda s: s)
                total = step(i)
            return total
    """})
    gl011 = [f for f in r.findings if f.rule == "GL011"]
    whats = "\n".join(f.message for f in gl011)
    assert len(gl011) == 2, r.render()
    assert "evaluated inside a loop body" in whats
    assert "per-call-varying Python scalar `i`" in whats


def test_gl011_disciplined_forms_are_clean_and_suppression_silences(tmp_path):
    r = lint_files(tmp_path, {
        "ok.py": """
            import jax
            import jax.numpy as jnp

            stepped = jax.jit(lambda s: s, static_argnums=(0,))

            def ok(xs):
                prog = jax.jit(lambda s: s)
                for i in range(3):
                    stepped(i)
                    prog(jnp.int32(i))
                return prog
        """,
        "memoized.py": """
            import jax

            def cohort(sizes):
                for n in sizes:
                    fn = jax.jit(lambda s: s)  # graftlint: disable=GL011(memoized one line below in real code)
                    fn(None)
        """,
    })
    assert not [f for f in r.findings if f.rule == "GL011"], r.render()
    assert len(r.suppressed) == 1


# -- GL012: atomic durability -------------------------------------------------

def test_gl012_direct_write_and_unfsynced_replace_fire(tmp_path):
    r = lint_files(tmp_path, {"store.py": """
        import os
        import tempfile

        def save(payload, out_dir):
            path = os.path.join(out_dir, "state.json")
            with open(path, "w") as f:
                f.write(payload)

        def commit(payload, out_dir):
            fd, tmp = tempfile.mkstemp(dir=out_dir)
            with os.fdopen(fd, "w") as f:
                f.write(payload)
            os.replace(tmp, os.path.join(out_dir, "state.json"))

        class Journal:
            def __init__(self, journal_dir):
                self.base = journal_dir

            def append(self, rec):
                with open(os.path.join(self.base, "log"), "a") as f:
                    f.write(rec)
    """})
    gl012 = [f for f in r.findings if f.rule == "GL012"]
    whats = "\n".join(f.message for f in gl012)
    assert len(gl012) == 3, r.render()
    assert "direct write under a durability directory" in whats
    assert "os.replace in 'commit' with no preceding os.fsync" in whats
    # ctor-assigned self.<attr> dir taint reaches the method's write
    assert any("'Journal.append'" in f.message for f in gl012)


def test_gl012_envelope_is_clean_and_append_log_suppresses(tmp_path):
    r = lint_files(tmp_path, {"store.py": """
        import os
        import tempfile

        def commit(payload, out_dir):
            fd, tmp = tempfile.mkstemp(dir=out_dir)
            with os.fdopen(fd, "w") as f:
                f.write(payload)
                f.flush()
                os.fsync(fd)
            os.replace(tmp, os.path.join(out_dir, "state.json"))

        def append_log(rec, log_dir):
            path = os.path.join(log_dir, "events.ndjson")
            with open(path, "a") as f:  # graftlint: disable=GL012(append-only; recovery drops a torn tail)
                f.write(rec)
    """})
    assert not [f for f in r.findings if f.rule == "GL012"], r.render()
    assert len(r.suppressed) == 1


# -- suppressions / baseline machinery ---------------------------------------

def test_parse_suppressions_multiple_ids_and_reasons():
    sup = parse_suppressions(
        "x = 1  # graftlint: disable=GL001(why),GL004\n"
        "y = 2\n"
        "z = 3  # graftlint: disable=GL005\n"
    )
    assert sup == {1: {"GL001", "GL004"}, 3: {"GL005"}}


def test_baseline_round_trip(tmp_path):
    files = {
        "core/flags.py": FLAGS_FIXTURE,
        "mod.py": "def f(cfg):\n    extra = getattr(cfg, 'extra', {}) or {}\n    return extra.get(\"rogue\")\n",
    }
    r = lint_files(tmp_path, files)
    assert r.findings
    baseline = tmp_path / "baseline.json"
    save_baseline(baseline, r.findings)
    assert load_baseline(baseline) == {f.key for f in r.findings}
    r2 = run_lint(tmp_path, baseline=baseline)
    assert r2.ok and len(r2.baselined) == len(r.findings)


def test_baseline_keys_are_line_independent():
    a = Finding("GL001", "m.py", 10, "msg", symbol="undeclared:x")
    b = Finding("GL001", "m.py", 99, "msg", symbol="undeclared:x")
    assert a.key == b.key


def test_unparseable_file_is_reported_not_crashed(tmp_path):
    r = lint_files(tmp_path, {"broken.py": "def f(:\n"})
    assert not r.ok and r.errors and "broken.py" in r.errors[0]


# -- the real package ---------------------------------------------------------

def test_fedml_tpu_package_lints_clean_with_shipped_baseline():
    """The tier-1 gate: every rule active over the real package, zero
    unsuppressed findings, and the SHIPPED baseline stays empty."""
    baseline_path = PKG_ROOT / "analysis" / "baseline.json"
    assert load_baseline(baseline_path) == set(), (
        "the shipped baseline must stay EMPTY — fix or inline-suppress new "
        "findings instead of baselining them")
    result = run_lint(PKG_ROOT, baseline=baseline_path)
    assert result.ok, "\n" + result.render()


def test_cli_lint_json_over_package():
    from fedml_tpu.cli import main

    import contextlib
    import io

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = main(["lint", "--format", "json"])
    doc = json.loads(buf.getvalue())
    assert rc == 0 and doc["ok"] and doc["findings"] == []


def _cli(args):
    """Run the lint CLI in-process, capturing (rc, stdout)."""
    import contextlib
    import io

    from fedml_tpu.cli import main

    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = main(args)
    return rc, buf.getvalue()


def test_cli_lint_json_shape_on_findings(tmp_path):
    """The documented --format json contract on a dirty tree: every finding
    carries rule/path/line/severity/message/key, counts_by_rule aggregates,
    and suppressed findings are counted but not listed."""
    (tmp_path / "core").mkdir()
    (tmp_path / "core" / "flags.py").write_text(textwrap.dedent(FLAGS_FIXTURE))
    (tmp_path / "mod.py").write_text(textwrap.dedent("""
        import threading
        import time

        class M:
            def __init__(self):
                self._lock = threading.Lock()

            def slow(self):
                with self._lock:
                    time.sleep(1.0)

            def documented(self):  # graftlint: disable=GL007(fixture reason)
                with self._lock:
                    time.sleep(1.0)
    """))
    rc, out = _cli(["lint", str(tmp_path), "--format", "json"])
    doc = json.loads(out)
    assert rc == 1 and doc["ok"] is False
    assert doc["parse_errors"] == []
    assert doc["suppressed"] == 1 and doc["baselined"] == 0
    assert doc["counts_by_rule"].get("GL007") == 1
    # dead_flag + declared_flag declarations are dead in this fixture too
    assert doc["counts_by_rule"].get("GL001") == 2
    for f in doc["findings"]:
        assert set(f) == {"rule", "path", "line", "severity", "message", "key"}
        assert f["severity"] in ("error", "warning") and f["line"] > 0
    keys = {f["key"] for f in doc["findings"]}
    assert any(k.startswith("GL007:mod.py:block:M.slow") for k in keys), keys


def test_cli_baseline_write_and_read_round_trip(tmp_path):
    """--write-baseline grandfathers the current findings; a second CLI run
    against that baseline exits 0 with everything baselined; fixing the code
    then leaves a stale baseline that changes nothing."""
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "mod.py").write_text(
        "def f(cfg):\n"
        "    extra = getattr(cfg, 'extra', {}) or {}\n"
        "    return extra.get('rogue_flag')\n")
    baseline = tmp_path / "baseline.json"
    rc, out = _cli(["lint", str(pkg), "--baseline", str(baseline), "--write-baseline"])
    assert rc == 0 and "baselined" in out
    doc = json.loads(baseline.read_text())
    assert doc["version"] == 1 and doc["findings"]
    assert all({"key", "rule", "path", "line", "message"} <= set(e)
               for e in doc["findings"])
    # second run: same findings, now grandfathered -> exit 0
    rc2, out2 = _cli(["lint", str(pkg), "--baseline", str(baseline),
                      "--format", "json"])
    doc2 = json.loads(out2)
    assert rc2 == 0 and doc2["ok"] and doc2["findings"] == []
    assert doc2["baselined"] == len(doc["findings"])
    # the fixed tree stays clean against the now-stale baseline
    (pkg / "mod.py").write_text("def f(cfg):\n    return None\n")
    rc3, out3 = _cli(["lint", str(pkg), "--baseline", str(baseline),
                      "--format", "json"])
    doc3 = json.loads(out3)
    assert rc3 == 0 and doc3["ok"] and doc3["baselined"] == 0


# -- the flag registry + accessor --------------------------------------------

def test_cfg_extra_resolution_order_and_undeclared_rejection():
    from fedml_tpu.arguments import Config
    from fedml_tpu.core.flags import FLAGS, cfg_extra

    cfg = Config(extra={"gan_z_dim": 32})
    assert cfg_extra(cfg, "gan_z_dim") == 32           # extra dict
    assert cfg_extra(cfg, "seg_base") == 8             # registry default
    assert cfg_extra(cfg, "seg_base", 99) == 99        # explicit default wins
    assert cfg_extra(None, "seg_base") == 8            # cfg=None short-circuit
    cfg.fused_blocks = True
    assert cfg_extra(cfg, "fused_blocks") is True      # direct attr wins
    with pytest.raises(KeyError):
        cfg_extra(cfg, "not_a_flag")
    assert all(s.name == n for n, s in FLAGS.items())


def test_flag_reference_renders_every_flag():
    from fedml_tpu.core.flags import FLAGS, render_flag_reference

    doc = render_flag_reference()
    for name in FLAGS:
        assert f"`{name}`" in doc
