"""Shamir pairwise-mask SecAgg wire-protocol tests.

Same three properties the LightSecAgg suite pins (VERDICT round-2 item 3):
1. secure aggregate == plaintext aggregate (full participation),
2. the server never sees a plaintext update,
3. dropout reconstruction: a client whose pair masks ARE in the survivors'
   uploads drops out; the server reconstructs its s_sk from T+1 shares and
   cancels the orphaned masks.
"""

import jax.flatten_util  # noqa: F401
import numpy as np
import pytest

from .conftest import tiny_config


def _sa_config(**kw):
    base = dict(
        client_num_in_total=4,
        client_num_per_round=4,
        comm_round=2,
        epochs=1,
        batch_size=16,
        synthetic_train_size=256,
        synthetic_test_size=64,
        training_type="cross_silo",
        enable_secagg=True,
        frequency_of_the_test=1,
        extra={"secagg_method": "shamir"},
    )
    extra = kw.pop("extra", {})
    base.update(kw)
    merged = dict(base["extra"])
    merged.update(extra)
    base["extra"] = merged
    return tiny_config(**base)


def test_shamir_roundtrip_and_per_round_seeds():
    from fedml_tpu.cross_silo.secagg_shamir import (
        derive_round_seed, dh_agree, dh_keypair,
    )
    from fedml_tpu.trust.secagg.shamir import shamir_reconstruct, shamir_share

    rng = np.random.RandomState(7)
    secret = 123456789
    shares = shamir_share(secret, 5, 3, rng)
    assert shamir_reconstruct(shares[1:4]) == secret
    assert shamir_reconstruct(shares[:3]) == secret
    # key agreement is symmetric
    sk1, pk1 = dh_keypair()
    sk2, pk2 = dh_keypair()
    assert dh_agree(sk1, pk2) == dh_agree(sk2, pk1)
    # per-round seeds never repeat
    assert derive_round_seed(42, 0) != derive_round_seed(42, 1)


def test_shamir_matches_plaintext_aggregate(eight_devices):
    import fedml_tpu
    from fedml_tpu.cross_silo import run_in_process_group
    from fedml_tpu.cross_silo.secagg_shamir import run_shamir_secagg_process_group
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub

    cfg = _sa_config(run_id="sa1")
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)
    history, server = run_shamir_secagg_process_group(cfg, ds, model, timeout=120.0)
    assert len(history) == cfg.comm_round
    assert history[-1]["test_acc"] > 0.4, history

    cfg2 = _sa_config(run_id="sa1p", enable_secagg=False)
    plain_history = run_in_process_group(cfg2, ds, model, timeout=120.0)
    for h_sa, h_plain in zip(history, plain_history):
        assert abs(h_sa["test_acc"] - h_plain["test_acc"]) < 0.05, (h_sa, h_plain)


def test_shamir_server_never_sees_plaintext(eight_devices):
    import fedml_tpu
    from fedml_tpu.cross_silo.secagg_shamir import SAAggregator, run_shamir_secagg_process_group
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub
    from fedml_tpu.trust.secagg.field import dequantize_from_field

    cfg = _sa_config(run_id="sa2", comm_round=1, frequency_of_the_test=0)
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)

    seen_masked = []
    orig_add = SAAggregator.add_local_trained_result

    def spy_add(self, client_idx, masked_vec, sample_num):
        seen_masked.append(np.asarray(masked_vec, dtype=np.int64).copy())
        orig_add(self, client_idx, masked_vec, sample_num)

    SAAggregator.add_local_trained_result = spy_add
    try:
        run_shamir_secagg_process_group(cfg, ds, model, timeout=120.0)
    finally:
        SAAggregator.add_local_trained_result = orig_add

    assert len(seen_masked) == cfg.client_num_in_total
    for vec in seen_masked:
        deq = np.abs(dequantize_from_field(vec, 1))
        assert np.mean(deq) > 100.0, np.mean(deq)


def test_shamir_masks_differ_across_rounds(eight_devices):
    """The reference reuses b_u every round (masks repeat — two uploads
    differ by exactly the model delta); our per-round seed derivation makes
    consecutive masked uploads field-uniform relative to each other."""
    import fedml_tpu
    from fedml_tpu.cross_silo.secagg_shamir import SAAggregator, run_shamir_secagg_process_group
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub
    from fedml_tpu.trust.secagg.field import dequantize_from_field

    cfg = _sa_config(run_id="sa5", comm_round=2, frequency_of_the_test=0)
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)

    by_client: dict[int, list] = {}
    orig_add = SAAggregator.add_local_trained_result

    def spy_add(self, client_idx, masked_vec, sample_num):
        by_client.setdefault(client_idx, []).append(
            np.asarray(masked_vec, dtype=np.int64).copy()
        )
        orig_add(self, client_idx, masked_vec, sample_num)

    SAAggregator.add_local_trained_result = spy_add
    try:
        run_shamir_secagg_process_group(cfg, ds, model, timeout=120.0)
    finally:
        SAAggregator.add_local_trained_result = orig_add

    for cid, vecs in by_client.items():
        assert len(vecs) == 2
        # if masks repeated, the difference would dequantize to a small model
        # delta; with fresh masks it is field-uniform noise
        from fedml_tpu.trust.secagg.field import DEFAULT_PRIME

        diff = (vecs[1] - vecs[0]) % DEFAULT_PRIME
        deq = np.abs(dequantize_from_field(diff, 1))
        assert np.mean(deq) > 100.0, (cid, np.mean(deq))


def test_shamir_dropout_reconstruction(eight_devices):
    """Client 4 completes setup (its pair masks are inside survivors'
    uploads) but never uploads.  With T=2, the server reconstructs s_sk_4
    from 3 reveals and cancels the orphaned masks; the result equals the
    survivors' plaintext mean."""
    import jax
    import fedml_tpu
    from fedml_tpu.core import rng
    from fedml_tpu.cross_silo.client import FedMLTrainer
    from fedml_tpu.cross_silo.secagg_shamir import build_sa_server, run_shamir_secagg_process_group
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub

    cfg = _sa_config(
        run_id="sa3", comm_round=1, frequency_of_the_test=0,
        extra={"straggler_timeout_s": 3.0, "straggler_quorum_frac": 0.5,
               "secagg_privacy_t": 2},
    )
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)

    history, server = run_shamir_secagg_process_group(
        cfg, ds, model, timeout=120.0, drop_ranks=frozenset({4})
    )
    assert len(history) == 1
    final = jax.device_get(server.aggregator.global_vars)

    ref = build_sa_server(cfg, ds, model, backend="INPROC")
    init_global = jax.device_get(ref.aggregator.global_vars)
    k0 = rng.root_key(cfg.random_seed)
    updates = []
    for rank in (1, 2, 3):
        ix = ds.client_idx[rank - 1]
        tr = FedMLTrainer(cfg, model, ds.train_x[ix], ds.train_y[ix])
        new_vars, _ = tr.train(init_global, 0, k0, client_idx=rank - 1)
        updates.append(new_vars)
    expected = jax.tree_util.tree_map(
        lambda *xs: np.mean(np.stack([np.asarray(x) for x in xs]), axis=0), *updates
    )
    flat_f, _ = jax.flatten_util.ravel_pytree(final)
    flat_e, _ = jax.flatten_util.ravel_pytree(expected)
    np.testing.assert_allclose(np.asarray(flat_f), np.asarray(flat_e), atol=2e-3)


def test_shamir_rejoin_after_drop_is_refused(eight_devices):
    """Once a client's s_sk was reconstructed (it dropped), the server knows
    its pairwise seeds; if it later rejoined, a b_u reveal would unmask it
    completely.  The aggregator must permanently refuse its uploads."""
    import fedml_tpu
    from fedml_tpu.cross_silo.secagg_shamir import build_sa_server, run_shamir_secagg_process_group

    cfg = _sa_config(
        run_id="sa7", comm_round=1, frequency_of_the_test=0,
        extra={"straggler_timeout_s": 3.0, "straggler_quorum_frac": 0.5,
               "secagg_privacy_t": 2},
    )
    fedml_tpu.init(cfg)
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub

    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)
    history, server = run_shamir_secagg_process_group(
        cfg, ds, model, timeout=120.0, drop_ranks=frozenset({4})
    )
    agg = server.aggregator
    assert 4 in agg.compromised
    # a late upload from the reconstructed client is silently refused
    agg.add_local_trained_result(4, np.zeros(agg.model_dim, dtype=np.int64), 1.0)
    assert 4 not in agg.model_dict


def test_share_pads_are_directional():
    """The u<->v DH agreement is symmetric; pads must still differ by
    direction and share kind (no known-plaintext reuse)."""
    from fedml_tpu.cross_silo.secagg_shamir import _share_pad

    key = 123456789
    b_uv, sk_uv = _share_pad(key, 1, 2)
    b_vu, sk_vu = _share_pad(key, 2, 1)
    assert len({b_uv, sk_uv, b_vu, sk_vu}) == 4


def test_shamir_method_dispatch(eight_devices):
    """secagg_method='shamir' routes the cross-silo runner through the
    Shamir protocol; unknown methods are refused."""
    import fedml_tpu
    from fedml_tpu.runner import FedMLRunner

    cfg = _sa_config(run_id="sa4", role="server", backend="INPROC", comm_round=1,
                     frequency_of_the_test=0)
    fedml_tpu.init(cfg)
    history = FedMLRunner(cfg).run()
    assert history and history[-1]["round"] == 0

    bad = _sa_config(run_id="sa6", role="server", backend="INPROC",
                     extra={"secagg_method": "nope"})
    with pytest.raises(ValueError):
        FedMLRunner(bad).run()
