"""Multi-tenant federated control plane (ISSUE 14).

Covers the event-driven server runtime (timer wheel + dispatch loop), the
gang scheduler's fair-share/priority policy, end-to-end tenant isolation
(flags, journal roots, metric namespaces), the shared AOT store's cross-job
warm start, the single-job bit-identity regression (multi-tenancy unused →
sync and async paths produce bitwise the pre-refactor results), pre-tenant
journal back-compat, and retired-client journal pruning.
"""

import os
import threading
import time

import numpy as np
import pytest

import fedml_tpu
from fedml_tpu.arguments import Config
from fedml_tpu.comm.inproc import InProcRouter
from fedml_tpu.cross_silo import build_client, build_server
from fedml_tpu.cross_silo.runtime import GangScheduler, ServerRuntime
from fedml_tpu.sched.multi_tenant import (
    MultiTenantControlPlane, run_multi_tenant_soak, tenant_config,
)


def _sync_cfg(run_id, rounds=2, extra=None, clients=2):
    return Config(
        training_type="cross_silo", dataset="synthetic", model="lr",
        client_num_in_total=clients, client_num_per_round=clients,
        comm_round=rounds, epochs=1, batch_size=16, learning_rate=0.1,
        partition_method="homo", synthetic_train_size=32 * clients,
        synthetic_test_size=32, frequency_of_the_test=0,
        compute_dtype="float32", metrics_jsonl_path="", run_id=run_id,
        extra=dict(extra or {}),
    )


def _run_group(cfg, ds, model):
    """1 server + clients on the plain (gate-free) path; returns the server
    so the test can read its final global."""
    InProcRouter.reset(cfg.run_id)
    clients = [build_client(cfg, ds, model, rank=r, backend="INPROC")
               for r in range(1, cfg.client_num_in_total + 1)]
    for c in clients:
        c.run_in_thread()
    server = build_server(cfg, ds, model, backend="INPROC")
    try:
        server.run_until_done(timeout=120.0)
        for c in clients:
            c.done.wait(5.0)
    finally:
        for c in clients:
            c.finish()
    InProcRouter.reset(cfg.run_id)
    return server


def _leaves(tree):
    import jax

    return [np.asarray(l) for l in jax.tree_util.tree_leaves(jax.device_get(tree))]


# ---------------------------------------------------------------------------
# ServerRuntime: timer wheel + dispatch loop
# ---------------------------------------------------------------------------

def test_runtime_timer_wheel_arm_supersede_cancel():
    rt = ServerRuntime(name="t-wheel")
    fired = []
    owner = object()
    try:
        # superseded timer never fires: re-arming the same (owner, name)
        # atomically replaces the previous entry
        rt.arm(owner, "a", 5.0, lambda: fired.append("stale"))
        rt.arm(owner, "a", 0.01, lambda: fired.append("fresh"))
        # cancelled timer never fires
        rt.arm(owner, "b", 0.01, lambda: fired.append("cancelled"))
        rt.cancel(owner, "b")
        # posted callbacks run promptly and in order
        rt.post(lambda: fired.append("p1"))
        rt.post(lambda: fired.append("p2"))
        deadline = time.monotonic() + 5.0
        while len(fired) < 3 and time.monotonic() < deadline:
            time.sleep(0.005)
        assert fired[:2] == ["p1", "p2"]
        assert fired[2] == "fresh"
        assert "stale" not in fired and "cancelled" not in fired
        # a raising callback is contained; the wheel keeps serving
        rt.post(lambda: 1 / 0)
        rt.post(lambda: fired.append("after-error"))
        deadline = time.monotonic() + 5.0
        while "after-error" not in fired and time.monotonic() < deadline:
            time.sleep(0.005)
        assert "after-error" in fired
        # cancel-all drops every timer of the owner
        rt.arm(owner, "x", 0.01, lambda: fired.append("x"))
        rt.arm(owner, "y", 0.01, lambda: fired.append("y"))
        rt.cancel(owner)
        time.sleep(0.1)
        assert "x" not in fired and "y" not in fired
    finally:
        rt.close()
    # post after close is a no-op, not a crash
    rt.post(lambda: fired.append("dead"))
    time.sleep(0.05)
    assert "dead" not in fired


def test_gang_scheduler_priority_and_fair_share():
    rt = ServerRuntime(name="t-sched")
    sched = GangScheduler(rt, slots=1)
    a, b, hi = object(), object(), object()
    sched.register(a, "a", weight=1.0, priority=0)
    sched.register(b, "b", weight=1.0, priority=0)
    sched.register(hi, "hi", weight=1.0, priority=5)
    granted = []
    evt = threading.Event()

    def grant(name):
        def cb():
            granted.append(name)
            evt.set()
        return cb

    def wait_grant(expected):
        assert evt.wait(5.0), f"no grant; got {granted}"
        evt.clear()
        assert granted[-1] == expected, granted

    try:
        # occupy the slot so the next three requests genuinely queue
        blocker = object()
        sched.register(blocker, "blocker")
        sched.request(blocker, grant("blocker"))
        wait_grant("blocker")
        # all three pending: strict priority wins the first grant even
        # though "a" arrived first — and the pass-over is metered as a
        # boundary preemption against the fair-share candidate
        sched.request(a, grant("a"))
        sched.request(b, grant("b"))
        sched.request(hi, grant("hi"))
        sched.release(blocker)
        wait_grant("hi")
        assert sched.stats["a"]["preempted"] == 1
        time.sleep(0.03)  # measurable hold charged to hi's virtual clock
        sched.release(hi)
        wait_grant("a")  # same class: arrival order at equal vtime
        time.sleep(0.05)
        sched.release(a)
        wait_grant("b")
        time.sleep(0.01)
        sched.release(b)
        # fair share: "a" accumulated ~5x "b"'s hold — queue both behind a
        # fresh holder, and the lower-virtual-time job ("b") wins the grant
        sched.request(hi, grant("hi"))
        wait_grant("hi")
        sched.request(a, grant("a"))
        sched.request(b, grant("b"))
        sched.release(hi)
        wait_grant("b")
        sched.release(b)
        assert evt.wait(5.0)  # a's turn drains
        sched.release(a)
        s = sched.summary()
        assert s["hi"]["grants"] == 2 and s["a"]["grants"] == 2
        assert s["a"]["hold_p95_s"] is not None
    finally:
        rt.close()


# ---------------------------------------------------------------------------
# single-job bit-identity: multi-tenancy unused == pre-refactor paths
# ---------------------------------------------------------------------------

def test_sync_single_job_bit_identical_with_and_without_plane():
    """The same sync recipe run plain and as a 1-job control-plane tenant
    must produce BITWISE the same final global (the gate only sequences the
    round start; with one tenant every grant is immediate)."""
    cfg = _sync_cfg("mt_bitid_sync", rounds=2)
    fedml_tpu.init(cfg)
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub

    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)
    plain = _run_group(cfg, ds, model)

    plane = MultiTenantControlPlane(slots=1)
    try:
        job = plane.admit(_sync_cfg("mt_bitid_sync", rounds=2), job_id="solo",
                          dataset=ds, model=model)
        plane.start()
        out = plane.run_until_done(timeout=120.0)
        assert out["jobs"]["solo"]["rounds"] == 2
    finally:
        plane.close()
    for pa, pb in zip(_leaves(plain.aggregator.global_vars),
                      _leaves(job.server.aggregator.global_vars)):
        assert np.array_equal(pa, pb)
    # no tenant key ever reaches the plain run's config
    assert "mt_job_id" not in (plain.cfg.extra or {})
    assert job.cfg.extra["mt_job_id"] == "solo"


def test_async_gated_vs_unused_fixed_arrival_order_bitwise():
    """Fixed arrival order, direct-driven: the 1-job GATED async server
    folds bitwise the same global as the plain (gate-free) server — the
    gang gate sequences DISPATCH only, never the fold math or the virtual-
    round boundary.  (With multi-tenancy unused the dispatch path is the
    exact pre-refactor code; tests/test_async_agg.py pins its behavior.)"""
    import jax

    from fedml_tpu.comm.message import Message
    from fedml_tpu.cross_silo import message_define as md

    extra = {"async_aggregation": True, "async_buffer_k": 3,
             "async_staleness_exponent": 0.5,
             "async_redispatch_timeout_s": 0.0}

    def upload(cid, params, n, version):
        msg = Message(md.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, cid, 0)
        msg.add_params(md.MSG_ARG_KEY_MODEL_PARAMS, params)
        msg.add_params(md.MSG_ARG_KEY_NUM_SAMPLES, float(n))
        msg.add_params(md.MSG_ARG_KEY_ROUND_INDEX, int(version))
        return Message.decode(msg.encode())

    def perturbed(base, salt):
        return jax.tree_util.tree_map(
            lambda a: (np.asarray(a) + 0.01 * (salt + 1)).astype(np.asarray(a).dtype)
            if np.asarray(a).dtype.kind == "f" else np.asarray(a), base)

    def run(run_id, gated):
        cfg = _sync_cfg(run_id, rounds=2, clients=6, extra=extra)
        fedml_tpu.init(cfg)
        from fedml_tpu.data import loader
        from fedml_tpu.models import model_hub

        ds = loader.load(cfg)
        model = model_hub.create(cfg, ds.class_num)
        InProcRouter.reset(run_id)
        rt = sched = None
        if gated:
            rt = ServerRuntime(name="t-async-gate")
            sched = GangScheduler(rt, slots=1)
        server = build_server(cfg, ds, model, backend="INPROC", runtime=rt)
        if gated:
            server.round_gate = sched
            sched.register(server, "solo")
        try:
            server.send_init_msg()
            base = jax.device_get(server.aggregator.global_vars)
            arrivals = [(1, 0), (4, 0), (2, 0), (3, 1), (1, 1), (5, 0)]
            for i, (cid, ver) in enumerate(arrivals):
                server.handle_message_receive_model(
                    upload(cid, perturbed(base, i), 16.0 + cid, ver))
            assert server.server_version == 2
            return _leaves(server.aggregator.global_vars)
        finally:
            server.finish()
            if rt is not None:
                rt.close()
            InProcRouter.reset(run_id)

    for pa, pb in zip(run("mt_async_plain", False), run("mt_async_gated", True)):
        assert np.array_equal(pa, pb)


# ---------------------------------------------------------------------------
# tenant isolation: flags, journals, metrics
# ---------------------------------------------------------------------------

def test_two_tenants_isolated_flags_journals_metrics(tmp_path):
    """Two concurrent jobs with DIFFERENT extra flags must not observe each
    other's config, journal steps, or metric samples — and a retired rank's
    journal dir is reclaimed at job finish while the live set survives."""
    from fedml_tpu.core.flags import cfg_extra
    from fedml_tpu.obs import registry as obsreg

    base_a = _sync_cfg("mt_iso", rounds=2,
                       extra={"streaming_aggregation": True,
                              "client_journal_dir": "unused-overridden",
                              "client_journal_keep_retired": 0})
    base_b = _sync_cfg("mt_iso", rounds=2)
    fedml_tpu.init(base_a)
    grants = obsreg.REGISTRY.get("fedml_mt_slot_grants_total")
    g0_a = grants.value(job="a") if grants is not None else 0.0
    g0_b = grants.value(job="b") if grants is not None else 0.0
    plane = MultiTenantControlPlane(slots=1, journal_root=str(tmp_path / "j"))
    try:
        ja = plane.admit(base_a, job_id="a")
        jb = plane.admit(base_b, job_id="b")
        # config isolation: fresh extra dicts, per-job run ids, A's flags
        # invisible to B (and to the admitted base recipes)
        assert ja.cfg.extra is not base_a.extra
        assert ja.cfg.run_id != jb.cfg.run_id
        assert cfg_extra(ja.cfg, "streaming_aggregation") is True
        assert not cfg_extra(jb.cfg, "streaming_aggregation")
        assert ja.server.aggregator.stream_mode
        assert not jb.server.aggregator.stream_mode
        # per-job journal roots under <journal_root>/job_<id>/
        sj_a = cfg_extra(ja.cfg, "server_journal_dir")
        sj_b = cfg_extra(jb.cfg, "server_journal_dir")
        assert "job_a" in sj_a and "job_b" in sj_b and sj_a != sj_b
        # a long-retired rank's client journal dir, planted before the run
        cj_a = cfg_extra(ja.cfg, "client_journal_dir")
        os.makedirs(os.path.join(cj_a, "client_99", "steps"), exist_ok=True)

        plane.start()
        out = plane.run_until_done(timeout=120.0)
    finally:
        plane.close()
    assert out["jobs"]["a"]["rounds"] == 2 and out["jobs"]["b"]["rounds"] == 2
    # journal steps landed in each job's own root, never the sibling's
    assert ja.server.journal is not None and jb.server.journal is not None
    steps_a = ja.server.journal.steps()
    steps_b = jb.server.journal.steps()
    assert steps_a and steps_b
    assert ja.server.journal.directory != jb.server.journal.directory
    # metric namespace: the same global families carry job-labeled series
    # that never bleed — each job saw exactly its own grants this run
    grants = obsreg.REGISTRY.get("fedml_mt_slot_grants_total")
    assert grants.value(job="a") - g0_a == 2.0
    assert grants.value(job="b") - g0_b == 2.0
    # retired-rank pruning fired at job A's finish (keep_retired=0): the
    # planted dir is gone, the live ranks' journals survive
    assert not os.path.exists(os.path.join(cj_a, "client_99"))
    assert os.path.isdir(os.path.join(cj_a, "client_1"))


def test_scoped_registry_collision_isolation():
    """Colliding family names registered through two job scopes share ONE
    family whose samples stay separated per job; bound labels cannot be
    overridden; conflicting re-registration still refuses."""
    from fedml_tpu.obs.registry import MetricsRegistry

    reg = MetricsRegistry()
    a = reg.scoped(job="a").counter("fedml_mt_test_collide", "shared family")
    b = reg.scoped(job="b").counter("fedml_mt_test_collide", "shared family")
    a.inc(3)
    b.inc(5)
    assert a.value() == 3.0 and b.value() == 5.0
    assert reg.get("fedml_mt_test_collide").value(job="a") == 3.0
    with pytest.raises(ValueError):
        a.inc(job="b")  # bound label override refused
    with pytest.raises(ValueError):
        reg.scoped(job="a").gauge("fedml_mt_test_collide")  # kind conflict
    h = reg.scoped(job="a").histogram("fedml_mt_test_hist", labels=("phase",))
    h.observe(0.5, phase="x")
    assert reg.get("fedml_mt_test_hist").count(job="a", phase="x") == 1


def test_tenant_config_scopes_existing_dirs_and_shared_aot(tmp_path):
    cfg = _sync_cfg("mt_tc", extra={"server_journal_dir": str(tmp_path / "sj"),
                                    "model_publish_dir": str(tmp_path / "pub")})
    t = tenant_config(cfg, "k7", aot_dir=str(tmp_path / "aot"))
    assert t.run_id == "mt_tc_job_k7"
    assert t.extra["server_journal_dir"] == str(tmp_path / "sj" / "job_k7")
    assert t.extra["model_publish_dir"] == str(tmp_path / "pub" / "job_k7")
    assert t.extra["aot_programs"] is True
    assert t.extra["aot_programs_dir"] == str(tmp_path / "aot")
    assert t.extra["mt_job_id"] == "k7"
    # the base recipe is untouched
    assert "mt_job_id" not in cfg.extra and cfg.run_id == "mt_tc"


def test_shared_aot_store_cross_job_warm_hit(tmp_path):
    """Job k+1 with the same tracing fingerprint deserializes job k's
    exported server program instead of recompiling."""
    cfg = _sync_cfg("mt_aot", rounds=1)
    fedml_tpu.init(cfg)
    plane = MultiTenantControlPlane(slots=1, aot_dir=str(tmp_path / "aot"))
    try:
        ja = plane.admit(_sync_cfg("mt_aot", rounds=1), job_id="a")
        jb = plane.admit(_sync_cfg("mt_aot", rounds=1), job_id="b")
        assert ja.aot_hits_at_admit == 0
        assert jb.aot_hits_at_admit > 0, (
            "second tenant re-traced a program the shared store already holds")
    finally:
        plane.close()


# ---------------------------------------------------------------------------
# journal back-compat + retired-client pruning
# ---------------------------------------------------------------------------

def test_pre_tenant_journal_layout_still_restores(tmp_path):
    """A PR 10/13-era journal (flag-direct directory, no mt_* keys in the
    protocol sidecar) restores through today's single-job server exactly as
    it did before the multi-tenant layer existed."""
    from fedml_tpu.cross_silo.journal import ServerJournal

    jdir = str(tmp_path / "legacy_journal")
    legacy = ServerJournal(jdir)
    # the PR-13-era sync sidecar shape: no model tree (model-less snapshots
    # reference nothing), no folded-keys/mt extensions beyond what PR 13 had
    legacy.snapshot(2, {
        "kind": "sync", "session_epoch": 0, "round_idx": 2,
        "rejected_stale": 0, "deduped": 0,
        "folded_keys": {}, "health": {},
        "stream_w": 0.0, "stream_w_delta": 0.0, "stream_folded": 0,
        "stream_samples": {}, "stream_clients": [],
    })
    cfg = _sync_cfg("mt_legacy", rounds=4,
                    extra={"server_journal_dir": jdir})
    fedml_tpu.init(cfg)
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub

    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)
    InProcRouter.reset(cfg.run_id)
    server = build_server(cfg, ds, model, backend="INPROC")
    try:
        assert server.recovered_step == 2
        assert server.round_idx == 2
        assert server.session_epoch == 1  # bumped past the legacy epoch
    finally:
        server.finish()
        InProcRouter.reset(cfg.run_id)


def test_prune_retired_client_dirs(tmp_path):
    from fedml_tpu.cross_silo.client_journal import prune_retired_client_dirs

    root = tmp_path / "cj"
    for rank in range(1, 7):
        d = root / f"client_{rank}"
        d.mkdir(parents=True)
        (d / "step_0000000001.journal").write_bytes(b"x")
        # stagger mtimes: higher rank = newer
        t = time.time() - (10 - rank) * 100
        os.utime(d / "step_0000000001.journal", (t, t))
    (root / "not_a_client_dir").mkdir()
    pruned = prune_retired_client_dirs(str(root), live_ranks=[1, 2], keep=2)
    # retired = {3,4,5,6}; newest 2 retired (5, 6) kept, 3 and 4 reclaimed
    assert sorted(pruned) == [3, 4]
    assert not (root / "client_3").exists() and not (root / "client_4").exists()
    for rank in (1, 2, 5, 6):
        assert (root / f"client_{rank}").exists()
    assert (root / "not_a_client_dir").exists()
    # live set is never pruned, whatever keep says
    assert prune_retired_client_dirs(str(root), live_ranks=[1, 2, 5, 6], keep=0) == []
    for rank in (1, 2, 5, 6):
        assert (root / f"client_{rank}").exists()


# ---------------------------------------------------------------------------
# fleet-scale concurrent soak (the bench shape, small)
# ---------------------------------------------------------------------------

def test_multi_tenant_soak_concurrent_completes_all_jobs():
    res = run_multi_tenant_soak(n_jobs=3, versions=3, concurrent=True, slots=2,
                                clients_per_job=12, concurrency=4, buffer_k=4,
                                timeout_s=120.0)
    assert res["versions_total"] == 9
    assert res["aggregate_versions_per_sec"] > 0
    assert res["rounds_granted"] == 9
    assert res["round_hold_p95_s"] is not None
    for jid, s in res["summary"]["jobs"].items():
        assert s["rounds"] == 3, (jid, s)
    for jid, s in res["summary"]["scheduler"].items():
        assert s["grants"] == 3, (jid, s)
