"""Federated serving managers + cross-cloud tests (VERDICT rows 21/46)."""

import numpy as np
import pytest

from .conftest import tiny_config


def test_federated_serving_train_then_deploy(tmp_path, eight_devices):
    """FL run completes, final model is registered + deployed, endpoint
    serves predictions — the train->serve loop (reference fedml_server.py:4
    wraps the FL run; deployment is its SaaS side, local here)."""
    import fedml_tpu
    from fedml_tpu.comm.inproc import InProcRouter
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub
    from fedml_tpu.serving.deploy import ModelDeployScheduler
    from fedml_tpu.serving.federated import FedMLModelServingClient, FedMLModelServingServer

    cfg = tiny_config(
        run_id="fsrv1", client_num_in_total=2, client_num_per_round=2,
        comm_round=2, batch_size=16, synthetic_train_size=256,
        synthetic_test_size=64, frequency_of_the_test=0,
    )
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)
    InProcRouter.reset("fsrv1")

    clients = [
        FedMLModelServingClient(cfg, "ep-demo", "fl-lr", dataset=ds, model=model,
                                rank=r, backend="INPROC")
        for r in (1, 2)
    ]
    for c in clients:
        c.run_in_thread()
    sched = ModelDeployScheduler(str(tmp_path / "ep.db"))
    server = FedMLModelServingServer(
        cfg, "ep-demo", "fl-lr", dataset=ds, model=model,
        scheduler=sched, backend="INPROC",
    )
    try:
        history, card = server.run(timeout=120.0, artifact_dir=str(tmp_path))
        assert len(history) == 2
        assert card is not None and card.name == "fl-lr"
        assert sched.wait_ready("ep-demo", timeout=180)
        feat = int(ds.train_x.shape[1])
        out = sched.predict("ep-demo", {"inputs": np.zeros((1, feat)).tolist()})
        assert len(out["outputs"][0]) == ds.class_num
    finally:
        sched.stop()
        for c in clients:
            c.finish()


def test_cross_cloud_over_tcp(eight_devices):
    """Cross-cloud = cross-silo over a routable transport with bounded-wait
    defaults; 1 server + 2 'cloud' silos complete a run over real sockets."""
    import threading

    import fedml_tpu
    from fedml_tpu.cross_cloud import FedMLCrossCloudClient, FedMLCrossCloudServer
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub

    cfg = tiny_config(
        training_type="cross_cloud", client_num_in_total=2, client_num_per_round=2,
        comm_round=2, batch_size=16, synthetic_train_size=256, synthetic_test_size=64,
        frequency_of_the_test=1, extra={"tcp_base_port": 23590},
    )
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)

    assert cfg.backend in ("", "INPROC", "MESH") or cfg.backend == "TCP"
    clients = [FedMLCrossCloudClient(cfg, ds, model, rank=r) for r in (1, 2)]
    assert cfg.backend == "TCP"  # WAN default applied
    assert cfg.extra["straggler_timeout_s"] == 60.0
    threads = [threading.Thread(target=c.run, daemon=True) for c in clients]
    for t in threads:
        t.start()
    server = FedMLCrossCloudServer(cfg, ds, model)
    history = server.run(timeout=120.0)
    assert len(history) == 2 and history[-1]["test_acc"] > 0.3
