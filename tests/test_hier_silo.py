"""Hierarchical cross-silo end-to-end over a real transport (round-3 verdict
item 4): one FL server + 2 silos over TCP, one silo spanning 2 OS processes
via jax.distributed — the full reference stack shape
(``cross_silo/client/client_launcher.py:46``,
``fedml_client_master_manager.py:200-212``) in one test, with numerics
parity against the flat single-process cross-silo run.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _flat_reference():
    """The identical FL run, flat: server + 2 plain clients, one process."""
    import jax

    import fedml_tpu
    from fedml_tpu.comm.inproc import InProcRouter
    from fedml_tpu.cross_silo import build_client, build_server
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub

    from .conftest import tiny_config

    cfg = tiny_config(
        training_type="cross_silo", client_num_in_total=2, client_num_per_round=2,
        comm_round=2, batch_size=16, synthetic_train_size=256,
        synthetic_test_size=64, frequency_of_the_test=1, run_id="hier-ref",
    )
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)
    InProcRouter.reset("hier-ref")
    clients = [build_client(cfg, ds, model, rank=r, backend="INPROC") for r in (1, 2)]
    for c in clients:
        c.run_in_thread()
    server = build_server(cfg, ds, model, backend="INPROC")
    try:
        history = server.run_until_done(timeout=180.0)
    finally:
        for c in clients:
            c.finish()
    flat = np.concatenate([
        np.asarray(l, dtype=np.float64).ravel()
        for l in jax.tree_util.tree_leaves(jax.device_get(server.aggregator.global_vars))
    ])
    return float(flat.sum()), float(np.sqrt((flat ** 2).sum())), history[-1].get("test_acc")


@pytest.mark.skipif(
    (os.cpu_count() or 1) < 4,
    reason="spawns multiple jax processes whose collective programs starve "
           "the XLA:CPU rendezvous on hosts with too few cores (observed "
           "240s hangs then timeout failures on 1-core CI)",
)
def test_hierarchical_silo_over_tcp_matches_flat(eight_devices):
    base_port, coord_port = _free_port(), _free_port()
    worker = os.path.join(_REPO, "tests", "_hier_silo_worker.py")
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "JAX_PLATFORM_NAME")}

    def spawn(role):
        return subprocess.Popen(
            [sys.executable, worker, role, str(base_port), str(coord_port)],
            env=env, cwd=_REPO, stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT, text=True,
        )

    # clients first (TCP listeners bind at construction); the server worker
    # itself waits for both listeners before broadcasting status checks
    procs = {r: spawn(r) for r in ("silo1", "siloA", "siloB", "server")}
    outs = {}
    for role, p in procs.items():
        out, _ = p.communicate(timeout=420)
        outs[role] = out
        assert p.returncode == 0, f"{role}:\n{out[-3000:]}"

    results = {}
    for role, out in outs.items():
        for line in out.splitlines():
            if line.startswith("MULTIHOST_RESULT "):
                results[role] = json.loads(line[len("MULTIHOST_RESULT "):])
    assert set(results) == {"server", "silo1", "siloA", "siloB"}, outs["server"][-2000:]

    assert results["server"]["rounds"] == 2
    assert results["silo1"]["done"] is True
    assert results["siloA"]["rounds"] == 2          # silo master trained each round
    assert results["siloB"]["rounds"] == 2          # follower joined every collective

    ref_sum, ref_l2, ref_acc = _flat_reference()
    assert results["server"]["checksum"] == pytest.approx(ref_sum, rel=1e-5, abs=1e-5)
    assert results["server"]["l2"] == pytest.approx(ref_l2, rel=1e-5, abs=1e-5)
    assert results["server"]["test_acc"] == pytest.approx(ref_acc, abs=1e-6)
