"""Continuous-batching serving fleet (ISSUE 11): micro-batcher coalescing /
deadline-flush / backpressure semantics, hot-swap-under-load with zero
dropped requests, canary rollback on an injected regression, AOT-warm worker
restart, and the flag-unset bit-identical default path for the publish hook."""

import json
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from .conftest import tiny_config


class StubPredictor:
    """Deterministic predictor stand-in: every output row is ``value`` (so a
    result names the version that produced it), with injectable delay /
    exception / NaN regression."""

    def __init__(self, value, max_batch=8, delay_s=0.0, fail=False, nan=False):
        self.value = float(value)
        self.max_batch = max_batch
        self.delay_s = delay_s
        self.fail = fail
        self.nan = nan
        self.calls = 0
        self.rows_seen = []

    def predict_rows(self, x):
        self.calls += 1
        self.rows_seen.append(int(np.asarray(x).shape[0]))
        if self.delay_s:
            time.sleep(self.delay_s)
        if self.fail:
            raise RuntimeError("injected predictor failure")
        fill = np.nan if self.nan else self.value
        return np.full((np.asarray(x).shape[0], 2), fill, np.float32)


def _batcher(pred, **kw):
    from fedml_tpu.serving.batcher import MicroBatcher

    return MicroBatcher(pred, **kw)


# ---------------------------------------------------------------------------
# micro-batcher semantics
# ---------------------------------------------------------------------------

def test_batcher_coalesces_concurrent_requests():
    """N concurrent single-row submits must land in FEWER predictor calls
    than requests (the whole point), with per-request results intact."""
    pred = StubPredictor(7.0, max_batch=8, delay_s=0.01)
    b = _batcher(pred, max_batch=8, max_queue=64, flush_ms=20.0)
    try:
        futs = []
        threads = [threading.Thread(
            target=lambda: futs.append(b.submit(np.zeros((1, 4)))))
            for _ in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        outs = [f.wait(10.0) for f in futs]
        assert len(outs) == 16
        for out in outs:
            assert out.shape == (1, 2) and float(out[0, 0]) == 7.0
        assert pred.calls < 16, f"no coalescing: {pred.calls} calls"
        assert max(pred.rows_seen) > 1
        # latency accounting rode the futures
        assert all(f.total_s >= f.queue_s >= 0.0 for f in futs)
    finally:
        b.stop()


def test_deadline_flush_never_waits_for_full_batch():
    """A lone request dispatches within ~flush_ms, not when the batch fills
    (there is nothing else coming — waiting would be unbounded latency)."""
    pred = StubPredictor(1.0, max_batch=32)
    b = _batcher(pred, max_batch=32, flush_ms=10.0)
    try:
        t0 = time.monotonic()
        out = b.submit(np.zeros((1, 4))).wait(5.0)
        elapsed = time.monotonic() - t0
        assert float(out[0, 0]) == 1.0
        assert elapsed < 2.0, f"lone request waited {elapsed}s for a full batch"
    finally:
        b.stop()


def test_backpressure_queue_overflow_is_explicit():
    """Admission past max_queue raises QueueOverflow with a positive
    retry-after hint — bounded memory, explicit 503, never silent growth."""
    from fedml_tpu.serving.batcher import QueueOverflow

    pred = StubPredictor(1.0, max_batch=1, delay_s=0.2)
    b = _batcher(pred, max_batch=1, max_queue=2, flush_ms=0.0)
    try:
        b.submit(np.zeros((1, 4)))  # occupies the device
        time.sleep(0.05)            # let the dispatcher pick it up
        b.submit(np.zeros((1, 4)))
        b.submit(np.zeros((1, 4)))
        with pytest.raises(QueueOverflow) as exc:
            for _ in range(4):  # the queue bound must hold
                b.submit(np.zeros((1, 4)))
        assert exc.value.retry_after_s > 0
        stats = b.stats()
        assert stats["rejected"] >= 1
        # oversized request is a 400-class error, not an overflow
        with pytest.raises(ValueError):
            b.submit(np.zeros((9, 4)))
    finally:
        b.stop()


def test_http_backpressure_maps_to_503_retry_after(eight_devices):
    """Through the HTTP runner: a full admission queue answers 503 with a
    Retry-After header; a well-formed request answers 200 + version."""
    from fedml_tpu.serving.inference import FedMLInferenceRunner
    from fedml_tpu.serving.publisher import HotSwapController

    pred = StubPredictor(3.0, max_batch=1, delay_s=0.3)
    ctl = HotSwapController(pred, version=5)
    b = _batcher(pred, controller=ctl, max_batch=1, max_queue=1, flush_ms=0.0)
    runner = FedMLInferenceRunner(pred, port=0, batcher=b, stats_fn=b.stats)
    port = runner.run(block=False)
    try:
        def post():
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/predict",
                data=json.dumps({"inputs": [[0.0] * 4]}).encode(),
                headers={"Content-Type": "application/json"})
            return urllib.request.urlopen(req, timeout=10.0)

        first = threading.Thread(target=lambda: post().read())
        first.start()
        time.sleep(0.05)
        threading.Thread(target=lambda: post().read(), daemon=True).start()
        time.sleep(0.05)
        with pytest.raises(urllib.error.HTTPError) as exc:
            post()
        assert exc.value.code == 503
        assert int(exc.value.headers["Retry-After"]) >= 1
        body = json.loads(exc.value.read())
        assert body["error"] == "overloaded" and body["retry_after_s"] > 0
        first.join(timeout=10.0)
        out = json.loads(post().read())
        assert out["version"] == 5 and out["outputs"][0][0] == 3.0
    finally:
        runner.stop()
        b.stop()


# ---------------------------------------------------------------------------
# hot swap + canary
# ---------------------------------------------------------------------------

def test_hot_swap_under_load_zero_dropped_requests():
    """Continuous submits while the version flips v1 -> v2: every request
    resolves (zero drops), every output is attributable to exactly one
    version, and the route eventually serves only v2."""
    from fedml_tpu.serving.publisher import HotSwapController

    v1, v2 = StubPredictor(1.0), StubPredictor(2.0)
    ctl = HotSwapController(v1, version=1)
    b = _batcher(v1, controller=ctl, max_batch=4, max_queue=128, flush_ms=0.5)
    results, errors = [], []
    stop = threading.Event()

    def load():
        while not stop.is_set():
            try:
                out = b.submit(np.zeros((1, 4))).wait(10.0)
                results.append(float(out[0, 0]))
            except Exception as e:  # any drop fails the test
                errors.append(e)

    threads = [threading.Thread(target=load) for _ in range(3)]
    try:
        for t in threads:
            t.start()
        time.sleep(0.1)
        ctl.offer(2, v2)  # the hot swap, mid-load
        deadline = time.time() + 5.0
        while ctl.version != 2 and time.time() < deadline:
            time.sleep(0.01)
        time.sleep(0.1)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10.0)
        b.stop()
    assert not errors, errors
    assert set(results) <= {1.0, 2.0}
    assert 2.0 in results, "new version never served"
    assert ctl.version == 2 and ctl.swaps == 1
    assert results[-1] == 2.0, "stable route did not converge on v2"


@pytest.mark.parametrize("regression", ["fail", "nan", "latency"])
def test_canary_rollback_on_injected_regression(regression):
    """A canary that raises, emits non-finite outputs, or regresses latency
    past the factor must roll back: the stable version keeps serving, zero
    requests are dropped (failed canary batches re-execute on stable), and
    the bad version is remembered as rejected."""
    from fedml_tpu.serving.publisher import HotSwapController

    stable = StubPredictor(1.0, delay_s=0.001)
    bad = StubPredictor(
        9.0,
        delay_s=0.25 if regression == "latency" else 0.0,
        fail=regression == "fail",
        nan=regression == "nan")
    ctl = HotSwapController(stable, version=1, canary_fraction=0.5,
                            canary_min_batches=4)
    b = _batcher(stable, controller=ctl, max_batch=2, max_queue=256,
                 flush_ms=0.0)
    try:
        ctl.offer(2, bad)
        outs = []
        deadline = time.time() + 20.0
        while ctl.stats()["canary_version"] is not None and time.time() < deadline:
            outs.append(float(b.submit(np.zeros((1, 4))).wait(10.0)[0, 0]))
        stats = ctl.stats()
        assert stats["rollbacks"] == 1, stats
        assert stats["served_version"] == 1, stats
        assert 2 in stats["rejected_versions"], stats
        assert not ctl.wants_version(2), "rejected version must never re-offer"
        # zero dropped AND zero poisoned results: fail/nan canary batches
        # fell back to stable, latency canary answers are still v-bad's
        # (slow but correct) — callers never see NaN or an exception
        expected = {1.0} if regression in ("fail", "nan") else {1.0, 9.0}
        assert set(outs) <= expected, set(outs)
        assert all(np.isfinite(o) for o in outs)
    finally:
        b.stop()


def test_canary_promotes_healthy_version():
    from fedml_tpu.serving.publisher import HotSwapController

    stable, fresh = StubPredictor(1.0), StubPredictor(2.0)
    ctl = HotSwapController(stable, version=1, canary_fraction=0.5,
                            canary_min_batches=3)
    b = _batcher(stable, controller=ctl, max_batch=2, flush_ms=0.0)
    try:
        ctl.offer(2, fresh)
        deadline = time.time() + 20.0
        while ctl.version != 2 and time.time() < deadline:
            b.submit(np.zeros((1, 4))).wait(10.0)
        stats = ctl.stats()
        assert stats["served_version"] == 2 and stats["swaps"] == 1, stats
        assert stats["rollbacks"] == 0, stats
    finally:
        b.stop()


class _LabelPredictor:
    """Predicts the class carried in feature 0 — or a constant wrong class.
    Numerically healthy either way (finite, fast): only the labeled eval
    batch can tell the good one from the bad one."""

    def __init__(self, wrong=False):
        self.wrong = wrong

    def predict_rows(self, x):
        x = np.asarray(x)
        logits = np.zeros((x.shape[0], 2), np.float32)
        cls = np.zeros(x.shape[0], int) if self.wrong \
            else x[:, 0].round().astype(int)
        logits[np.arange(x.shape[0]), cls] = 1.0
        return logits


def test_canary_rollback_on_eval_accuracy_regression():
    """ISSUE 19 satellite: the labeled eval batch folds into the health
    score — a canary that is numerically healthy (no errors, no latency
    regression) but WRONG on held-out data rolls back; an accurate
    candidate still promotes.  Without the eval batch the same wrong
    canary sails through, proving the accuracy factor is load-bearing."""
    from fedml_tpu.serving.publisher import HotSwapController

    ex = np.array([[0.0, 1.0], [1.0, 0.0], [0.0, 0.0], [1.0, 1.0]], np.float32)
    ey = np.array([0, 1, 0, 1])
    good, bad = _LabelPredictor(), _LabelPredictor(wrong=True)

    ctl = HotSwapController(good, version=1, canary_fraction=0.5,
                            canary_min_batches=2, regress_threshold=0.6,
                            eval_batch=(ex, ey))
    stats = ctl.stats()
    assert stats["stable_eval_acc"] == 1.0, stats
    # wrong canary: every canary batch reports healthy, yet the eval factor
    # (acc 0.5 vs stable 1.0) drags the score under the threshold
    ctl.offer(2, bad)
    assert ctl.stats()["canary_eval_acc"] == 0.5, ctl.stats()
    for _ in range(2):
        ctl.observe_batch(2, ok=True, execute_s=0.001, is_canary=True)
    stats = ctl.stats()
    assert stats["rollbacks"] == 1 and stats["served_version"] == 1, stats
    assert 2 in stats["rejected_versions"], stats
    assert stats["stable_eval_acc"] == 1.0  # stable's score survives rollback
    # accurate candidate: same healthy batches, promotes
    ctl.offer(3, _LabelPredictor())
    for _ in range(2):
        ctl.observe_batch(3, ok=True, execute_s=0.001, is_canary=True)
    stats = ctl.stats()
    assert stats["served_version"] == 3 and stats["swaps"] == 1, stats
    assert stats["stable_eval_acc"] == 1.0, stats
    # control: no eval batch -> the wrong canary promotes (nothing else
    # about it regresses), which is exactly the gap the satellite closes
    blind = HotSwapController(good, version=1, canary_fraction=0.5,
                              canary_min_batches=2, regress_threshold=0.6)
    blind.offer(2, bad)
    for _ in range(2):
        blind.observe_batch(2, ok=True, execute_s=0.001, is_canary=True)
    assert blind.stats()["served_version"] == 2, blind.stats()


@pytest.mark.locksan
def test_hot_swap_e2e_publisher_to_worker(tmp_path, eight_devices):
    """The full publication channel under load: ModelPublisher commits
    versions the way the training server does, an in-process ServingWorker
    bootstraps from the manifest, serves HTTP predicts through the
    micro-batcher, and hot-swaps each version — zero dropped requests."""
    import jax
    import jax.numpy as jnp

    import fedml_tpu
    from fedml_tpu.models import model_hub
    from fedml_tpu.serving.publisher import ModelPublisher
    from fedml_tpu.serving.worker import ServingWorker

    cfg = tiny_config()
    fedml_tpu.init(cfg)
    model = model_hub.create(cfg, 10)
    base = jax.device_get(model.init(
        {"params": jax.random.PRNGKey(0)}, jnp.zeros((1, 32)), train=True))
    pub = ModelPublisher(str(tmp_path / "pub"), keep=3)
    pub.publish(0, base, meta={"model": "lr"})

    worker = ServingWorker("lr", 10, publish_dir=str(tmp_path / "pub"),
                           max_batch=8, flush_ms=1.0, poll_s=0.01,
                           bootstrap_timeout_s=30.0)
    port = worker.start(block=False)
    ok, dropped = [0], [0]
    stop = threading.Event()

    def load():
        body = json.dumps({"inputs": [[0.0] * 32]}).encode()
        while not stop.is_set():
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/predict", data=body,
                headers={"Content-Type": "application/json"})
            try:
                with urllib.request.urlopen(req, timeout=10.0) as r:
                    json.loads(r.read())
                ok[0] += 1
            except Exception:
                dropped[0] += 1

    threads = [threading.Thread(target=load) for _ in range(2)]
    try:
        for t in threads:
            t.start()
        for version in (1, 2, 3):
            scaled = jax.tree_util.tree_map(
                lambda a, f=1.0 + 0.1 * version: (np.asarray(a) * f).astype(
                    np.asarray(a).dtype) if np.asarray(a).dtype.kind == "f"
                else a, base)
            pub.publish(version, scaled)
            deadline = time.time() + 10.0
            while worker.served_version < version and time.time() < deadline:
                time.sleep(0.01)
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10.0)
        stats = worker.stats()
        worker.stop()
    assert dropped[0] == 0 and stats["errored"] == 0, (dropped, stats)
    assert ok[0] > 0
    assert stats["served_version"] == 3, stats
    assert stats["swaps"] >= 2, stats  # >= 2 distinct hot swaps under load
    # version pruning: keep=3 retains the newest files, manifest intact
    files = sorted(p.name for p in (tmp_path / "pub").glob("params-*.wire"))
    assert len(files) <= 3 and "params-v00000003.wire" in files


# ---------------------------------------------------------------------------
# AOT-warm worker restart
# ---------------------------------------------------------------------------

def test_aot_warm_worker_restart(tmp_path, eight_devices):
    """First predictor construction populates the program store (misses >
    0); a 'restarted' worker over the same store deserializes — warm hits >
    0, misses == 0 — and its outputs are bitwise the cold run's."""
    import jax
    import jax.numpy as jnp

    import fedml_tpu
    from fedml_tpu.core.aot import AOT_HITS, AOT_MISSES, ProgramStore
    from fedml_tpu.models import model_hub
    from fedml_tpu.serving.inference import JaxPredictor

    cfg = tiny_config()
    fedml_tpu.init(cfg)
    model = model_hub.create(cfg, 10)
    variables = jax.device_get(model.init(
        {"params": jax.random.PRNGKey(0)}, jnp.zeros((1, 32)), train=True))
    x = np.linspace(0, 1, 2 * 32).reshape(2, 32).astype(np.float32)

    m0, h0 = AOT_MISSES.value(), AOT_HITS.value()
    cold = JaxPredictor(model, variables, max_batch=8,
                        aot_store=ProgramStore(str(tmp_path / "aot")),
                        feature_shape=(32,), model_name="lr")
    cold.warm()
    assert AOT_MISSES.value() - m0 > 0, "cold run must populate the store"
    cold_out = cold.predict_rows(x)

    m1, h1 = AOT_MISSES.value(), AOT_HITS.value()
    warm = JaxPredictor(model, variables, max_batch=8,
                        aot_store=ProgramStore(str(tmp_path / "aot")),
                        feature_shape=(32,), model_name="lr")
    warm.warm()
    assert AOT_MISSES.value() - m1 == 0, "warm restart re-traced"
    assert AOT_HITS.value() - h1 > 0, "warm restart never hit the store"
    np.testing.assert_array_equal(cold_out, warm.predict_rows(x))


# ---------------------------------------------------------------------------
# publish hook: default path + satellite flags
# ---------------------------------------------------------------------------

def _run_cs(run_id, extra=None):
    import fedml_tpu
    from fedml_tpu.comm.inproc import InProcRouter
    from fedml_tpu.cross_silo import build_client, build_server
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub

    cfg = tiny_config(training_type="cross_silo", client_num_in_total=2,
                      client_num_per_round=2, comm_round=2, batch_size=16,
                      synthetic_train_size=128, synthetic_test_size=64,
                      frequency_of_the_test=0, run_id=run_id,
                      extra=dict(extra or {}))
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)
    InProcRouter.reset(run_id)
    clients = [build_client(cfg, ds, model, rank=r, backend="INPROC")
               for r in (1, 2)]
    for c in clients:
        c.run_in_thread()
    server = build_server(cfg, ds, model, backend="INPROC")
    try:
        history = server.run_until_done(timeout=120.0)
    finally:
        for c in clients:
            c.finish()
    return server, history


def test_publish_hook_flag_unset_is_bit_identical(tmp_path, eight_devices):
    """extra.model_publish_dir unset -> no publisher object, zero publish
    writes, and the aggregation result is bitwise the published run's (the
    hook only OBSERVES the round, never perturbs it)."""
    import jax

    pub_dir = tmp_path / "pub"
    server_off, hist_off = _run_cs("pub_off")
    assert server_off.publisher is None
    server_on, hist_on = _run_cs("pub_on", extra={"model_publish_dir": str(pub_dir)})
    assert server_on.publisher is not None
    assert not list(tmp_path.glob("**/params-*.wire")) or pub_dir.exists()
    # versions 0 (bootstrap), 1, 2 published; manifest commits the last
    manifest = json.loads((pub_dir / "MANIFEST.json").read_text())
    assert manifest["version"] == 2
    assert (pub_dir / manifest["path"]).exists()
    # flag-off: not a single publish artifact anywhere
    assert not (tmp_path / "pub_off").exists()
    for a, b in zip(jax.tree_util.tree_leaves(server_off.aggregator.global_vars),
                    jax.tree_util.tree_leaves(server_on.aggregator.global_vars)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert [h["round"] for h in hist_off] == [h["round"] for h in hist_on]


def test_published_artifact_matches_server_global(tmp_path, eight_devices):
    """The manifest-referenced params file decodes to exactly the server's
    final global tree (the artifact a hot-swapping worker will serve)."""
    from fedml_tpu.comm import wire
    from fedml_tpu.cross_silo import message_define as md

    server, _ = _run_cs("pub_art", extra={"model_publish_dir": str(tmp_path / "p")})
    manifest = json.loads((tmp_path / "p" / "MANIFEST.json").read_text())
    with open(tmp_path / "p" / manifest["path"], "rb") as f:
        published = wire.decode_pytree(f.read())
    import jax

    host = jax.device_get(server.aggregator.global_vars)
    flat_pub = wire.flatten_with_skeleton({md.MSG_ARG_KEY_MODEL_PARAMS: published})[1]
    flat_srv = wire.flatten_with_skeleton({md.MSG_ARG_KEY_MODEL_PARAMS: host})[1]
    for a, b in zip(flat_pub, flat_srv):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_worker_cli_feature_dim_flag():
    """The docstring has advertised --feature-dim since the seed; the
    argparse surface must actually define it (satellite), and the parser
    must accept both scalar and conv-shaped specs."""
    from fedml_tpu.serving.worker import parse_feature_dim

    assert parse_feature_dim("32") == (32,)
    assert parse_feature_dim("32,32,3") == (32, 32, 3)
    assert parse_feature_dim(None) is None
    assert parse_feature_dim("") is None
    import os
    from pathlib import Path

    res = subprocess.run(
        [sys.executable, "-m", "fedml_tpu.serving.worker", "--help"],
        capture_output=True, text=True, timeout=120,
        cwd=str(Path(__file__).parent.parent),
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert res.returncode == 0, res.stderr[-2000:]
    for flag in ("--feature-dim", "--publish-dir", "--canary-fraction",
                 "--aot-dir", "--max-queue"):
        assert flag in res.stdout, f"{flag} missing from worker CLI"


def test_worker_feature_dim_overrides_inference(eight_devices):
    """An explicit feature shape warms a predictor whose tree gives no
    inferable input shape (the conv-model gap the satellite closes)."""
    from fedml_tpu.serving.worker import _infer_feature_shape

    # a conv-ish tree (4-d kernel) defeats inference...
    conv_tree = {"params": {"Conv_0": {"kernel": np.zeros((3, 3, 3, 8))}}}
    assert _infer_feature_shape(conv_tree) is None
    # ...but an explicit shape lets the predictor warm before serving
    import jax
    import jax.numpy as jnp

    import fedml_tpu
    from fedml_tpu.models import model_hub
    from fedml_tpu.serving.inference import JaxPredictor

    cfg = tiny_config()
    fedml_tpu.init(cfg)
    model = model_hub.create(cfg, 10)
    variables = jax.device_get(model.init(
        {"params": jax.random.PRNGKey(0)}, jnp.zeros((1, 32)), train=True))
    pred = JaxPredictor(model, variables, max_batch=4, feature_shape=(32,))
    pred.warm()  # would no-op (and first request would pay the compile)
    assert pred.predict_rows(np.zeros((1, 32), np.float32)).shape == (1, 10)
