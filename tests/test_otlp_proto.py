"""OTLP binary wire format (ISSUE 16 satellite): the hand-rolled
protobuf encoder, the ``auto`` content-negotiation fallback, and the
multi-tenant non-collapse regression.

- golden bytes: the encoder's output for a one-span request is compared
  against a HAND-DECODED fixture (field numbers and wire types worked out
  from the OTLP .proto definitions by hand, not by running the encoder);
- a minimal wire-format reader (varint/fixed/length-delimited only — no
  protobuf dependency) structurally decodes a full metrics request:
  sum/gauge/histogram shapes, packed bucket counts, datapoint attributes;
- ``protocol="auto"``: a collector that 415s JSON flips the exporter to
  protobuf, sticky, within one export call;
- two tenants writing the SAME family through ``ScopedRegistry`` must ship
  as two datapoints with distinct ``job`` attributes — not collapse into
  one series (the ISSUE 16 multi-tenant OTLP fix), and ``mt_job_id`` must
  stamp the per-tenant OTLP *resource*.
"""

import struct
import threading

import pytest

from fedml_tpu.obs import otlp as otlplib
from fedml_tpu.obs import otlp_proto
from fedml_tpu.obs import registry as obsreg

# ---------------------------------------------------------------------------
# golden bytes


GOLDEN_TRACE_HEX = (
    # ExportTraceServiceRequest { resource_spans#1 (85 bytes) {
    "0a55"
    #   resource#1 (23) { attributes#1 (21) { key#1 "service.name",
    #     value#2 { string_value#1 "svc" } } }
    "0a170a150a0c736572766963652e6e616d6512050a03737663"
    #   scope_spans#2 (58) { scope#1 (3) { name#1 "s" }
    "123a0a030a0173"
    #     spans#2 (51) {
    "1233"
    #       trace_id#1: 16 bytes, "ab" zero-padded to 32 hex chars
    "0a10000000000000000000000000000000ab"
    #       span_id#2: 8 bytes
    "120800000000000000cd"
    #       name#5 "r", kind#6 = 1 (INTERNAL)
    "2a01723001"
    #       start_time_unix_nano#7 fixed64 LE: 1.0 s = 1e9 ns = 0x3B9ACA00
    "3900ca9a3b00000000"
    #       end_time_unix_nano#8 fixed64 LE: 1.5 s = 0x59682F00
    "41002f685900000000"
    # } } }
)


def test_trace_request_matches_hand_decoded_golden_bytes():
    payload, n = otlplib.spans_to_otlp(
        [{"kind": "span", "name": "r", "trace_id": "ab", "span_id": "cd",
          "ts": 1.0, "dur_s": 0.5}],
        service_name="svc", scope="s")
    assert n == 1
    wire = otlp_proto.encode_trace_request(payload)
    assert wire.hex() == GOLDEN_TRACE_HEX
    assert len(wire) == 87
    # encode_request dispatches to the same bytes off the top-level key
    assert otlp_proto.encode_request(payload) == wire


# ---------------------------------------------------------------------------
# a minimal wire reader (stdlib only) for structural checks


def _read_varint(buf, i):
    shift = v = 0
    while True:
        b = buf[i]
        i += 1
        v |= (b & 0x7F) << shift
        if not b & 0x80:
            return v, i
        shift += 7


def _fields(buf):
    """Decode one message's fields -> list of (field_number, value): bytes
    for length-delimited, int for varint/fixed."""
    out, i = [], 0
    while i < len(buf):
        tag, i = _read_varint(buf, i)
        field, wire = tag >> 3, tag & 7
        if wire == 0:
            v, i = _read_varint(buf, i)
        elif wire == 1:
            v = struct.unpack("<Q", buf[i:i + 8])[0]
            i += 8
        elif wire == 2:
            n, i = _read_varint(buf, i)
            v = buf[i:i + n]
            i += n
        elif wire == 5:
            v = struct.unpack("<I", buf[i:i + 4])[0]
            i += 4
        else:  # pragma: no cover — the encoder never emits groups
            raise AssertionError(f"unexpected wire type {wire}")
        out.append((field, v))
    return out


def _one(fields, n):
    vals = [v for f, v in fields if f == n]
    assert len(vals) == 1, (n, fields)
    return vals[0]


def _all(fields, n):
    return [v for f, v in fields if f == n]


def _attrs(fields, n):
    """KeyValue list at field ``n`` -> {key: decoded AnyValue}."""
    out = {}
    for kv in _all(fields, n):
        f = _fields(kv)
        key = _one(f, 1).decode()
        av = _fields(_one(f, 2))
        assert len(av) == 1  # the oneof is always emitted exactly once
        field, raw = av[0]
        out[key] = {1: lambda r: r.decode(),
                    3: lambda r: r - (1 << 64) if r >> 63 else r,
                    4: lambda r: struct.unpack("<d", struct.pack("<Q", r))[0],
                    2: bool}.get(field, lambda r: r)(raw)
    return out


def _metrics_by_name(wire):
    rm = _fields(_one(_fields(wire), 1))
    sm = _fields(_one(rm, 2))
    return rm, {_one(_fields(m), 1).decode(): _fields(m)
                for m in _all(sm, 2)}


def test_metrics_request_structure_survives_the_wire():
    reg = obsreg.MetricsRegistry()
    reg.counter("fedml_t_proto_total", "c", labels=("path",)).inc(5, path="x")
    reg.gauge("fedml_t_proto_gauge", "g").set(2.5)
    reg.histogram("fedml_t_proto_seconds", "h",
                  buckets=(0.1, 1.0)).observe(0.05)
    payload, n = otlplib.metrics_snapshot_to_otlp(
        reg.snapshot(), service_name="svc",
        resource_attributes={"job": "7"}, time_unix_nano=1_000)
    assert n == 3
    wire = otlp_proto.encode_metrics_request(payload)
    rm, metrics = _metrics_by_name(wire)

    # the resource carries service.name AND the tenant attribute
    res_attrs = _attrs(_fields(_one(rm, 1)), 1)
    assert res_attrs == {"service.name": "svc", "job": "7"}

    # counter -> Sum{temporality=CUMULATIVE(2), monotonic, labeled point}
    sum_msg = _fields(_one(metrics["fedml_t_proto_total"], 7))
    assert _one(sum_msg, 2) == 2 and _one(sum_msg, 3) == 1
    dp = _fields(_one(sum_msg, 1))
    assert _one(dp, 3) == 1_000  # timeUnixNano made it through as fixed64
    assert struct.unpack("<d", struct.pack("<Q", _one(dp, 4)))[0] == 5.0
    assert _attrs(dp, 7) == {"path": "x"}

    # gauge -> Gauge{point asDouble}
    gdp = _fields(_one(_fields(_one(metrics["fedml_t_proto_gauge"], 5)), 1))
    assert struct.unpack("<d", struct.pack("<Q", _one(gdp, 4)))[0] == 2.5

    # histogram -> packed fixed64 bucket counts + packed double bounds
    hist = _fields(_one(metrics["fedml_t_proto_seconds"], 9))
    hdp = _fields(_one(hist, 1))
    assert _one(hdp, 4) == 1  # count (varint)
    counts = struct.unpack("<3Q", _one(hdp, 6))  # 2 bounds + overflow
    assert counts == (1, 0, 0)
    bounds = struct.unpack("<2d", _one(hdp, 7))
    assert bounds == (0.1, 1.0)


# ---------------------------------------------------------------------------
# content negotiation


class _PickyCollector:
    """200s application/x-protobuf, 415s everything else — the collector
    shape that motivates ``protocol="auto"``."""

    def __init__(self):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        self.seen: list[tuple[str, str]] = []
        self.bodies: list[bytes] = []
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            def do_POST(self):  # noqa: N802 (http.server API)
                ctype = self.headers.get("Content-Type", "")
                body = self.rfile.read(int(self.headers.get("Content-Length", 0)))
                outer.seen.append((self.path, ctype))
                ok = ctype == "application/x-protobuf"
                if ok:
                    outer.bodies.append(body)
                self.send_response(200 if ok else 415)
                self.send_header("Content-Length", "0")
                self.end_headers()

            def log_message(self, *args):
                pass

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), _Handler)
        self.httpd.daemon_threads = True
        self.endpoint = f"http://127.0.0.1:{self.httpd.server_address[1]}"
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


def test_auto_protocol_falls_back_to_protobuf_on_415_and_sticks():
    collector = _PickyCollector()
    reg = obsreg.MetricsRegistry()
    reg.counter("fedml_t_auto_total", "c").inc(3)
    exp = otlplib.OTLPExporter(collector.endpoint, registry=reg,
                               protocol="auto", max_retries=0,
                               timeout_s=5.0)
    try:
        assert exp._wire == "json"
        assert exp.export_metrics_now()  # 415 -> re-POST as protobuf -> 200
        assert exp._wire == "protobuf"
        assert [c for _, c in collector.seen] == [
            "application/json", "application/x-protobuf"]
        assert exp.export_metrics_now()  # sticky: no second JSON attempt
        assert [c for _, c in collector.seen][-1] == "application/x-protobuf"
        assert len(collector.seen) == 3
        # what landed is decodable wire bytes carrying the counter
        _, metrics = _metrics_by_name(collector.bodies[0])
        assert "fedml_t_auto_total" in metrics
    finally:
        exp.close()
        collector.close()


def test_post_otlp_rejects_unknown_and_exporter_validates():
    with pytest.raises(ValueError):
        otlplib.OTLPExporter("http://127.0.0.1:9", protocol="grpc")


# ---------------------------------------------------------------------------
# multi-tenant: per-job datapoints, per-job resource


def test_two_tenants_ship_two_datapoints_not_one():
    """Regression (ISSUE 16): two jobs incrementing the same family through
    ``ScopedRegistry`` must reach OTLP as separate attribute-scoped
    datapoints — before the fix they collapsed into one series."""
    reg = obsreg.MetricsRegistry()
    reg.scoped(job="a").counter("fedml_t_mt_total", "c").inc(5)
    reg.scoped(job="b").counter("fedml_t_mt_total", "c").inc(11)
    payload, n = otlplib.metrics_snapshot_to_otlp(
        reg.snapshot(), service_name="svc", time_unix_nano=1)
    assert n == 2
    # JSON side: two datapoints, job attribute distinguishes them
    (metric,) = payload["resourceMetrics"][0]["scopeMetrics"][0]["metrics"]
    dps = metric["sum"]["dataPoints"]
    by_job = {kv["value"]["stringValue"]: dp["asDouble"]
              for dp in dps for kv in dp["attributes"] if kv["key"] == "job"}
    assert by_job == {"a": 5.0, "b": 11.0}
    # and the binary wire preserves both
    _, metrics = _metrics_by_name(otlp_proto.encode_metrics_request(payload))
    wire_dps = [_fields(dp) for dp in
                _all(_fields(_one(metrics["fedml_t_mt_total"], 7)), 1)]
    wire_by_job = {
        _attrs(dp, 7)["job"]:
            struct.unpack("<d", struct.pack("<Q", _one(dp, 4)))[0]
        for dp in wire_dps}
    assert wire_by_job == {"a": 5.0, "b": 11.0}


def test_exporter_from_config_stamps_tenant_resource():
    from .conftest import tiny_config

    cfg = tiny_config()
    cfg.extra = {}
    assert otlplib.exporter_from_config(cfg) is None  # the gate

    cfg.extra = {"otlp_endpoint": "http://127.0.0.1:9",
                 "otlp_protocol": "protobuf", "mt_job_id": "3"}
    exp = otlplib.exporter_from_config(cfg)
    try:
        assert exp.protocol == "protobuf" and exp._wire == "protobuf"
        assert exp.resource_attributes["job"] == "3"
        assert exp.resource_attributes["service.instance.id"] == "job_3"
    finally:
        exp.close(timeout=1.0)
