"""Trust stack tests.

Models the reference's security test pattern (``python/tests/security/`` —
assert attack/defense math on synthetic gradient lists, SURVEY.md §4), plus
end-to-end "defense recovers accuracy under attack" runs the reference lacks.
"""

import numpy as np
import pytest

from .conftest import tiny_config


def _mat(m=8, d=20, seed=0):
    return np.random.RandomState(seed).normal(0, 1, (m, d)).astype(np.float32)


# ---------------------------------------------------------------------------
# defense math units
# ---------------------------------------------------------------------------

def test_krum_rejects_outlier(eight_devices):
    import jax.numpy as jnp
    from fedml_tpu.trust.defense.robust_agg import KrumDefense

    u = _mat()
    u[3] += 100.0  # blatant outlier
    d = KrumDefense(byzantine_num=1, select_m=3)
    _, w = d.before(jnp.asarray(u), jnp.ones(8), jnp.zeros(20))
    w = np.asarray(w)
    assert w[3] == 0.0, "outlier should be deselected"
    assert w.sum() == 3.0


def test_geometric_median_robust(eight_devices):
    import jax.numpy as jnp
    from fedml_tpu.trust.defense.robust_agg import GeometricMedianDefense

    u = np.zeros((9, 5), np.float32)
    u[:6] = 1.0  # honest cluster at 1
    u[6:] = 1000.0  # 3 attackers far away
    agg = GeometricMedianDefense(iters=32).on_agg(jnp.asarray(u), jnp.ones(9), jnp.zeros(5))
    assert np.allclose(np.asarray(agg), 1.0, atol=0.2), np.asarray(agg)


def test_trimmed_mean_and_median(eight_devices):
    import jax.numpy as jnp
    from fedml_tpu.trust.defense.robust_agg import (
        CoordinateWiseMedianDefense, TrimmedMeanDefense,
    )

    u = _mat(10, 6, seed=1)
    u[0] = 1e6
    med = CoordinateWiseMedianDefense().on_agg(jnp.asarray(u), jnp.ones(10), jnp.zeros(6))
    assert np.abs(np.asarray(med)).max() < 10
    tm = TrimmedMeanDefense(beta=0.2).on_agg(jnp.asarray(u), jnp.ones(10), jnp.zeros(6))
    assert np.abs(np.asarray(tm)).max() < 10


def test_norm_clipping(eight_devices):
    import jax.numpy as jnp
    from fedml_tpu.trust.defense.clipping import NormDiffClippingDefense

    g = jnp.zeros(16)
    u = jnp.ones((4, 16)) * 10.0
    clipped, _ = NormDiffClippingDefense(norm_bound=1.0).before(u, jnp.ones(4), g)
    norms = np.linalg.norm(np.asarray(clipped), axis=1)
    assert (norms <= 1.0 + 1e-5).all()


def test_foolsgold_downweights_sybils(eight_devices):
    import jax.numpy as jnp
    from fedml_tpu.trust.defense.anomaly import FoolsGoldDefense

    rng = np.random.RandomState(0)
    honest = rng.normal(0, 1, (5, 30)).astype(np.float32)
    sybil = np.tile(rng.normal(0, 1, (1, 30)), (3, 1)).astype(np.float32)
    u = jnp.asarray(np.concatenate([honest, sybil]))
    _, w = FoolsGoldDefense().before(u, jnp.ones(8), jnp.zeros(30))
    w = np.asarray(w)
    assert w[5:].max() < 0.1 * max(w[:5].mean(), 1e-9), w


def test_three_sigma_family(eight_devices):
    import jax.numpy as jnp
    from fedml_tpu.trust.defense import create
    from fedml_tpu.arguments import Config

    u = _mat(10, 8, seed=2)
    u[7] += 50.0
    for dt in ("three_sigma", "three_sigma_geomedian", "three_sigma_krum"):
        cfg = Config(enable_defense=True, defense_type=dt, outlier_detection_k=2.0)
        d = create(cfg)
        _, w = d.before(jnp.asarray(u), jnp.ones(10), jnp.zeros(8))
        assert np.asarray(w)[7] == 0.0, dt


# ---------------------------------------------------------------------------
# attack units
# ---------------------------------------------------------------------------

def test_byzantine_and_replacement(eight_devices):
    import jax
    import jax.numpy as jnp
    from fedml_tpu.trust.attack import attacks as atk

    u = jnp.asarray(_mat(6, 10))
    sampled = jnp.arange(6, dtype=jnp.int32)
    mask = atk.malicious_mask(6, sampled, [1, 4])
    np.testing.assert_array_equal(np.asarray(mask), [0, 1, 0, 0, 1, 0])
    z = atk.byzantine_zero(u, mask)
    assert np.asarray(z)[1].sum() == 0 and np.asarray(z)[4].sum() == 0
    assert np.allclose(np.asarray(z)[0], np.asarray(u)[0])
    g = jnp.ones(10)
    lazy = atk.lazy_worker(u, mask, g)
    assert np.allclose(np.asarray(lazy)[1], 1.0)
    boosted = atk.model_replacement(u, mask, g, boost=5.0)
    expected = 1.0 + 5.0 * (np.asarray(u)[1] - 1.0)
    assert np.allclose(np.asarray(boosted)[1], expected, atol=1e-5)


def test_label_flipping_poison():
    from fedml_tpu.trust.attack.attacks import flip_labels

    labels = np.array([0, 1, 0, 1, 0, 1, 0, 1])
    client_idx = [np.array([0, 1, 2, 3]), np.array([4, 5, 6, 7])]
    out = flip_labels(labels, client_idx, [0], original_class=1, target_class=0)
    np.testing.assert_array_equal(out[:4], [0, 0, 0, 0])  # client 0 poisoned
    np.testing.assert_array_equal(out[4:], labels[4:])  # client 1 untouched


def test_revealing_labels(eight_devices):
    import jax
    import jax.numpy as jnp
    import optax
    from fedml_tpu.trust.attack.dlg import revealing_labels_from_gradients

    # simple linear model with bias; batch contains classes {1, 3}
    k = jax.random.PRNGKey(0)
    W = jax.random.normal(k, (12, 5)) * 0.1
    b = jnp.zeros(5)
    x = jax.random.normal(jax.random.fold_in(k, 1), (4, 12))
    y = jnp.array([1, 3, 1, 3])
    gb = jax.grad(
        lambda b: optax.softmax_cross_entropy_with_integer_labels(x @ W + b, y).mean()
    )(b)
    present = np.asarray(revealing_labels_from_gradients(gb))
    assert present[1] and present[3]
    assert not present[0] and not present[2] and not present[4]


# ---------------------------------------------------------------------------
# DP units
# ---------------------------------------------------------------------------

def test_dp_calibration_and_noise(eight_devices):
    import jax
    import jax.numpy as jnp
    from fedml_tpu.arguments import Config
    from fedml_tpu.trust.dp.dp import FedMLDifferentialPrivacy, gaussian_sigma

    sigma = gaussian_sigma(epsilon=1.0, delta=1e-5, sensitivity=1.0)
    assert 4.0 < sigma < 5.0  # sqrt(2 ln(1.25e5)) ~ 4.84
    dp = FedMLDifferentialPrivacy(Config(enable_dp=True, dp_solution_type="ldp", epsilon=1.0))
    x = jnp.zeros(100000)
    noised = dp.add_local_noise(x, jax.random.PRNGKey(0))
    emp = float(jnp.std(noised))
    assert abs(emp - sigma) / sigma < 0.05


def test_rdp_accountant_monotone():
    from fedml_tpu.trust.dp.accountant import RDPAccountant

    a = RDPAccountant(q=0.01, noise_multiplier=1.0)
    a.step(10)
    e10 = a.get_epsilon(1e-5)
    a.step(990)
    e1000 = a.get_epsilon(1e-5)
    assert 0 < e10 < e1000 < 100


# ---------------------------------------------------------------------------
# SecAgg units
# ---------------------------------------------------------------------------

def test_shamir_roundtrip():
    from fedml_tpu.trust.secagg.shamir import shamir_reconstruct, shamir_share

    rng = np.random.RandomState(0)
    secret = 123456789
    shares = shamir_share(secret, n=5, t=3, rng=rng)
    assert shamir_reconstruct(shares[:3]) == secret
    assert shamir_reconstruct(shares[1:4]) == secret
    # fewer than t shares gives garbage (overwhelmingly likely)
    assert shamir_reconstruct(shares[:2]) != secret


def test_shamir_pairwise_mask_dropout_roundtrip():
    """Full SecAgg masking equation with a dropped client: the recovered sum
    must equal the survivors' plain field sum (regression for the unmask sign
    inversion on dropped clients' pairwise masks)."""
    from fedml_tpu.trust.secagg.field import DEFAULT_PRIME
    from fedml_tpu.trust.secagg.shamir import masked_input, unmask_sum

    p = DEFAULT_PRIME
    rng = np.random.RandomState(7)
    n, d = 4, 12
    xs = {i: rng.randint(0, 1000, size=d).astype(np.int64) for i in range(n)}
    self_seeds = {i: int(rng.randint(1, 2**30)) for i in range(n)}
    pair_seeds = {}
    for i in range(n):
        for j in range(i + 1, n):
            pair_seeds[(i, j)] = int(rng.randint(1, 2**30))

    def peer_seeds_of(i):
        return {j: pair_seeds[(min(i, j), max(i, j))] for j in range(n) if j != i}

    masked = {i: masked_input(xs[i], i, peer_seeds_of(i), self_seeds[i]) for i in range(n)}

    # no dropout: all pairwise masks cancel; only self-masks removed
    full = unmask_sum(masked, self_seeds, {})
    np.testing.assert_array_equal(full, sum(xs.values()) % p)

    # client 1 drops AFTER peers computed their masked inputs: server removes
    # survivors' self-masks and reconstructs client 1's pairwise seeds
    for dropped in range(n):
        survivors = {i: masked[i] for i in range(n) if i != dropped}
        surv_self = {i: self_seeds[i] for i in survivors}
        dropped_pairs = {
            (dropped, j): pair_seeds[(min(dropped, j), max(dropped, j))]
            for j in survivors
        }
        got = unmask_sum(survivors, surv_self, dropped_pairs)
        expected = sum(xs[i] for i in survivors) % p
        np.testing.assert_array_equal(got, expected)


def test_lightsecagg_with_dropout():
    from fedml_tpu.trust.secagg.field import dequantize_from_field, quantize_to_field
    from fedml_tpu.trust.secagg.lightsecagg import LightSecAggProtocol, secure_aggregate

    rng = np.random.RandomState(1)
    vecs_f = [rng.normal(0, 1, 40) for _ in range(6)]
    proto = LightSecAggProtocol(n_clients=6, privacy_t=1, target_u=4, seed=0)
    vecs_q = [quantize_to_field(v) for v in vecs_f]
    # drop 2 clients; sum should equal survivors' plain sum
    dropped = {2, 5}
    total_field = secure_aggregate(vecs_q, proto, dropout=dropped)
    got = dequantize_from_field(total_field[:40], n_summands=4)
    expected = sum(vecs_f[i] for i in range(6) if i not in dropped)
    np.testing.assert_allclose(got, expected, atol=1e-3)


# ---------------------------------------------------------------------------
# end-to-end: attack degrades, defense restores
# ---------------------------------------------------------------------------

def test_defense_restores_accuracy_under_attack(eight_devices):
    import fedml_tpu

    base = dict(
        comm_round=8, learning_rate=0.3, client_num_per_round=8,
        enable_attack=True, attack_type="byzantine_random",
        poisoned_client_list=(0, 1, 2),
    )
    # attacked, undefended
    h_atk = fedml_tpu.run_simulation(tiny_config(**base))
    acc_atk = h_atk[-1]["test_acc"]
    # attacked + krum defense
    h_def = fedml_tpu.run_simulation(tiny_config(
        **base, enable_defense=True, defense_type="multikrum",
        byzantine_client_num=3, krum_param_m=4,
    ))
    acc_def = h_def[-1]["test_acc"]
    assert acc_def > acc_atk + 0.1, f"defense {acc_def} vs attacked {acc_atk}"
    assert acc_def > 0.4


def test_ldp_noise_changes_model_but_learns(eight_devices):
    import fedml_tpu

    h = fedml_tpu.run_simulation(tiny_config(
        comm_round=8, learning_rate=0.3, client_num_per_round=8,
        enable_dp=True, dp_solution_type="ldp", mechanism_type="gaussian",
        epsilon=50.0, delta=1e-5, sensitivity=0.01,
    ))
    assert h[-1]["test_acc"] > 0.3


def test_contribution_assessment(eight_devices):
    import fedml_tpu

    cfg = tiny_config(
        comm_round=3, client_num_per_round=4, enable_contribution=True,
        contribution_method="leave_one_out",
    )
    fedml_tpu.init(cfg)
    from fedml_tpu.runner import FedMLRunner

    runner = FedMLRunner(cfg)
    runner.run()
    scores = runner.runner.assess_contribution()
    assert scores is not None and len(scores) == 4
    assert np.isfinite(scores).all()


def test_contribution_assesses_actual_round_contributions(eight_devices):
    """VERDICT 'what's weak' #6: the assessed coalitions must be the EXACT
    contributions that were aggregated last round — their FedAvg aggregate
    must reproduce the post-round global, bit-for-bit up to float tolerance."""
    import jax
    import fedml_tpu
    from fedml_tpu.runner import FedMLRunner

    cfg = tiny_config(
        comm_round=2, client_num_per_round=4, enable_contribution=True,
        contribution_method="leave_one_out",
    )
    fedml_tpu.init(cfg)
    sim = FedMLRunner(cfg).runner
    sim.run()
    replay = sim.last_round_contributions()
    assert replay is not None
    stacked, weights, sampled, snap = replay
    import jax.numpy as jnp

    agg = sim.algorithm.aggregate(stacked, jnp.asarray(weights, jnp.float32))
    new_global, _ = sim.algorithm.server_update(
        jax.tree_util.tree_map(jnp.asarray, snap["global_vars"]),
        jax.tree_util.tree_map(jnp.asarray, snap["server_state"]),
        agg, snap["round"],
    )
    for a, b in zip(jax.tree_util.tree_leaves(new_global),
                    jax.tree_util.tree_leaves(sim.global_vars)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5, rtol=1e-5)


def test_label_flipping_end_to_end(eight_devices):
    """Data-poisoning attacks must actually poison the stacked dataset."""
    import fedml_tpu
    from fedml_tpu.runner import FedMLRunner

    cfg = tiny_config(
        comm_round=1, enable_attack=True, attack_type="label_flipping",
        poisoned_client_list=(0, 1, 2, 3),
    )
    cfg.extra = {"attack_original_class": 0, "attack_target_class": 1}
    fedml_tpu.init(cfg)
    runner = FedMLRunner(cfg)
    ds = runner.runner.dataset
    for c in (0, 1, 2, 3):
        assert (ds.train_y[ds.client_idx[c]] == 0).sum() == 0, "class 0 should be flipped"
    # honest clients keep class 0 samples
    remaining = sum((ds.train_y[ds.client_idx[c]] == 0).sum() for c in (4, 5, 6, 7))
    assert remaining > 0


def test_unknown_attack_type_raises(eight_devices):
    import fedml_tpu
    from fedml_tpu.runner import FedMLRunner
    import pytest as _pt

    cfg = tiny_config(enable_attack=True, attack_type="mind_control")
    fedml_tpu.init(cfg)
    with _pt.raises(ValueError, match="unknown attack_type"):
        FedMLRunner(cfg)


def test_trust_applies_on_sp_backend(eight_devices):
    """Security hooks must be backend-independent: byzantine attack with no
    defense must degrade the SP backend run too."""
    import fedml_tpu

    base = dict(comm_round=6, learning_rate=0.3, client_num_per_round=8, backend_sim="sp")
    h_clean = fedml_tpu.run_simulation(tiny_config(**base))
    h_atk = fedml_tpu.run_simulation(tiny_config(
        **base, enable_attack=True, attack_type="byzantine_random",
        poisoned_client_list=(0, 1, 2, 3),
    ))
    assert h_atk[-1]["test_acc"] < h_clean[-1]["test_acc"] - 0.1


def test_cross_round_defense_history_threads(eight_devices):
    import fedml_tpu
    from fedml_tpu.runner import FedMLRunner
    import numpy as _np

    cfg = tiny_config(
        comm_round=3, client_num_per_round=4,
        enable_defense=True, defense_type="cross_round",
    )
    fedml_tpu.init(cfg)
    runner = FedMLRunner(cfg)
    sim = runner.runner
    assert sim.defense_history is not None
    assert float(abs(sim.defense_history).sum()) == 0.0
    runner.run()
    assert float(abs(sim.defense_history).sum()) > 0.0, "history never updated"


def test_gtg_shapley_nonzero_on_distinct_clients(eight_devices):
    """GTG-Shapley must produce nonzero marginals when coalitions matter."""
    import jax.numpy as jnp
    import numpy as _np
    from fedml_tpu.trust.contribution import gtg_shapley

    # 1-d "models": contribution i has value v_i; eval = -|mean - target|
    stacked = {"w": jnp.asarray([[1.0], [1.0], [-5.0]])}
    empty = {"w": jnp.asarray([0.0])}
    weights = _np.ones(3)

    def eval_fn(model):
        return -abs(float(model["w"][0] if model["w"].ndim else model["w"]) - 1.0)

    scores = gtg_shapley(stacked, weights, eval_fn, empty, rounds_cap=30, eps=1e-4, seed=0)
    assert _np.abs(scores).sum() > 0, scores
    # the adversarial client (-5) must score below the helpful ones
    assert scores[2] < scores[0] and scores[2] < scores[1], scores
