"""Zoo breadth tests (VERDICT item 10): CNN zoo models, new dataset specs,
Soteria/WBC defenses, edge-case backdoor attack."""

import numpy as np
import pytest

from .conftest import tiny_config


@pytest.mark.parametrize("model_name", ["mobilenet", "mobilenet_v3", "efficientnet", "vgg11", "vgg16"])
def test_cnn_zoo_forward_and_grad(model_name, eight_devices):
    import jax
    import jax.numpy as jnp
    import fedml_tpu
    from fedml_tpu.models import model_hub

    cfg = tiny_config(model=model_name, dataset="cifar10", norm="group")
    fedml_tpu.init(cfg)
    model = model_hub.create(cfg, 10)
    x = jax.random.normal(jax.random.PRNGKey(42), (2, 32, 32, 3), jnp.float32)
    variables = model.init({"params": jax.random.PRNGKey(0)}, x, train=True)
    # jit everything: un-jitted apply/grad compiles op-by-op (eager), which
    # the persistent compilation cache cannot help with — the jitted programs
    # cache across suite runs
    logits = jax.jit(lambda v, x: model.apply(v, x, train=False))(variables, x)
    assert logits.shape == (2, 10)
    assert jnp.isfinite(logits).all()

    def loss(v):
        out = model.apply(v, x, train=True)
        return jnp.mean((out.astype(jnp.float32) - 1.0) ** 2)

    g = jax.jit(jax.grad(loss))(variables)
    norms = [float(jnp.abs(t).sum()) for t in jax.tree_util.tree_leaves(g)]
    assert all(np.isfinite(norms))
    assert sum(n > 0 for n in norms) > len(norms) // 2  # gradients actually flow


@pytest.mark.slow
def test_cnn_zoo_trains_one_fl_round(eight_devices):
    """mobilenet runs an end-to-end FedAvg round (registration is real, not
    just a forward pass).  SP backend: the vmapped-mesh mobilenet round is a
    ~6-minute CPU compile that defeats the persistent cache (CPU AOT
    machine-feature rejection on large entries); SP runs the identical
    model/trainer code through the identical server path, and conv-on-mesh
    coverage lives in test_small_cnn_mesh_round below.

    @slow: ~210 s every run (the mobilenet step compile also defeats the
    cache), ~25% of the tier-1 wall-clock ceiling.  Tier-1 keeps the same
    marginal coverage via test_small_cnn_mesh_round (conv through the full
    vmapped mesh round + server path) and
    test_cnn_zoo_forward_and_grad[mobilenet] (mobilenet registration +
    gradient flow)."""
    import fedml_tpu
    from fedml_tpu.runner import FedMLRunner

    cfg = tiny_config(
        model="mobilenet", dataset="cifar10", norm="group", comm_round=1,
        client_num_in_total=4, client_num_per_round=2, batch_size=8,
        synthetic_train_size=64, synthetic_test_size=32, frequency_of_the_test=1,
        backend_sim="sp",
    )
    fedml_tpu.init(cfg)
    history = FedMLRunner(cfg).run()
    assert np.isfinite(history[-1]["train_loss"])


def test_small_cnn_mesh_round(eight_devices):
    """A convolutional model through the full vmapped MESH round program
    (the path the mobilenet test exercises via SP)."""
    import fedml_tpu
    from fedml_tpu.runner import FedMLRunner

    cfg = tiny_config(
        model="cnn", dataset="cifar10", norm="group", comm_round=1,
        client_num_in_total=4, client_num_per_round=2, batch_size=8,
        synthetic_train_size=64, synthetic_test_size=32, frequency_of_the_test=1,
    )
    fedml_tpu.init(cfg)
    history = FedMLRunner(cfg).run()
    assert np.isfinite(history[-1]["train_loss"])


@pytest.mark.parametrize("name,feat,classes", [
    ("gld23k", (96, 96, 3), 203),
    ("stackoverflow_lr", (10000,), 500),
    ("lending_club", (200,), 2),
])
def test_new_dataset_specs(name, feat, classes, eight_devices):
    import fedml_tpu
    from fedml_tpu.data import loader

    cfg = tiny_config(dataset=name, synthetic_train_size=256, synthetic_test_size=64,
                      client_num_in_total=4)
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    assert ds.train_x.shape[1:] == feat
    assert ds.class_num == classes
    assert len(ds.client_idx) == 4


def test_reddit_text_spec(eight_devices):
    import fedml_tpu
    from fedml_tpu.data import loader

    cfg = tiny_config(dataset="reddit", synthetic_train_size=128, synthetic_test_size=32,
                      client_num_in_total=4)
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    assert ds.train_x.shape[1] == 20       # seq len
    assert ds.train_x.max() < 10000        # vocab bound


def test_soteria_mask_defends_feature_gradient(eight_devices):
    """The faithful client-side Soteria: sensitivity from one jacrev pass,
    mask prunes exactly the lowest-percentile coordinates."""
    import jax
    import jax.numpy as jnp
    import fedml_tpu
    from fedml_tpu.models import model_hub
    from fedml_tpu.trust.defense import soteria_mask, soteria_sensitivity

    cfg = tiny_config()
    fedml_tpu.init(cfg)
    model = model_hub.create(cfg, 10)  # LR: output == representation
    x = jax.random.normal(jax.random.PRNGKey(0), (32,))
    variables = model.init({"params": jax.random.PRNGKey(1)}, x[None], train=True)
    sens = soteria_sensitivity(model, variables, x)
    assert sens.shape == (10,) and bool(jnp.isfinite(sens).all())
    mask, _ = soteria_mask(model, variables, x, percentile=20.0)
    assert mask.shape == (10,)
    assert int((mask == 0).sum()) == 2  # 20% of 10 pruned


def test_soteria_and_wbc_registered_and_run(eight_devices):
    import fedml_tpu

    for defense in ("soteria", "wbc"):
        cfg = tiny_config(
            comm_round=2, client_num_per_round=4,
            enable_defense=True, defense_type=defense,
        )
        history = fedml_tpu.run_simulation(cfg)
        assert np.isfinite(history[-1]["train_loss"]), defense
        # mild perturbations must not destroy learning
        assert history[-1]["test_acc"] > 0.3, (defense, history[-1])


def test_edge_case_backdoor_poisons_tail(eight_devices):
    import fedml_tpu
    from fedml_tpu.data import loader
    from fedml_tpu.trust.attack.attacks import FedMLAttacker

    cfg = tiny_config(
        enable_attack=True, attack_type="edge_case_backdoor",
        poisoned_client_list=(0, 1),
        extra={"attack_target_class": 3, "attack_poison_frac": 0.5},
    )
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    poisoned = FedMLAttacker(cfg).poison_data(ds)
    changed = np.flatnonzero((poisoned.train_y != ds.train_y)
                             | (np.abs(poisoned.train_x - ds.train_x).reshape(len(ds.train_y), -1).sum(1) > 0))
    assert len(changed) > 0
    # poisoned samples: target label + pushed into the distribution tail
    assert (poisoned.train_y[changed] == 3).all()
    orig_dev = np.abs(ds.train_x - ds.train_x.mean(0)).reshape(len(ds.train_y), -1).sum(1)
    new_dev = np.abs(poisoned.train_x - ds.train_x.mean(0)).reshape(len(ds.train_y), -1).sum(1)
    assert (new_dev[changed] > orig_dev[changed] * 1.5).all()
    # only clients 0/1's shards touched
    allowed = set(np.concatenate([ds.client_idx[0], ds.client_idx[1]]))
    assert set(changed).issubset(allowed)

    # end-to-end: the attack degrades accuracy vs clean run when undefended
    h_atk = fedml_tpu.run_simulation(tiny_config(
        comm_round=3, client_num_per_round=8, learning_rate=0.3,
        enable_attack=True, attack_type="edge_case_backdoor",
        poisoned_client_list=(0, 1, 2, 3),
        extra={"attack_target_class": 3, "attack_poison_frac": 1.0},
    ))
    assert np.isfinite(h_atk[-1]["train_loss"])
