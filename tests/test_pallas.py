"""Pallas kernel tests (interpret mode — CPU CI; the compiled path is
exercised on the real chip by the round driver's bench/verify runs)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest


def test_quantize_kernel_matches_reference(eight_devices):
    from fedml_tpu.ops.pallas import (
        dequantize_int8,
        quantize_int8_reference,
        quantize_int8_stochastic,
    )

    k = jax.random.PRNGKey(0)
    x = jax.random.normal(k, (5000,)) * 3.0
    v, s, n = quantize_int8_stochastic(x, k, interpret=True)
    vr, sr, nr = quantize_int8_reference(x, k)
    np.testing.assert_array_equal(np.asarray(v), np.asarray(vr))
    np.testing.assert_allclose(np.asarray(s), np.asarray(sr), rtol=1e-6)
    assert n == nr == 5000
    assert v.dtype == jnp.int8

    back = dequantize_int8(v, s, n, interpret=True)
    assert back.shape == x.shape
    # error bounded by one quantization step per block
    assert float(jnp.abs(back - x).max()) <= float(s.max()) + 1e-6


def test_quantize_kernel_unbiased(eight_devices):
    from fedml_tpu.ops.compression import qsgd_int8_fused

    k = jax.random.PRNGKey(1)
    x = jax.random.normal(k, (2048,))
    est = jnp.stack([
        qsgd_int8_fused(x, jax.random.PRNGKey(i), interpret=True) for i in range(40)
    ]).mean(0)
    assert float(jnp.abs(est - x).mean()) < 0.01


def test_quantize_kernel_edge_shapes(eight_devices):
    from fedml_tpu.ops.pallas import dequantize_int8, quantize_int8_stochastic

    k = jax.random.PRNGKey(2)
    for n in (1, 1023, 1024, 1025, 4096):
        x = jax.random.normal(k, (n,))
        v, s, length = quantize_int8_stochastic(x, k, interpret=True)
        back = dequantize_int8(v, s, length, interpret=True)
        assert back.shape == (n,)
        assert float(jnp.abs(back - x).max()) <= float(s.max()) + 1e-6


# -- fused BasicBlock epilogue kernel (ops/pallas/fused_block.py) ------------

def _fused_inputs(shape, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 5)
    y = jax.random.normal(ks[0], shape, dtype)
    r = jax.random.normal(ks[1], shape, dtype)
    s = jax.random.normal(ks[2], (shape[-1],), jnp.float32)
    b = jax.random.normal(ks[3], (shape[-1],), jnp.float32)
    g = jax.random.normal(ks[4], shape, dtype)
    return y, r, s, b, g


# 3024 elements (padded tail), exact block multiple, and the three flagship
# channel widths
_FUSED_SHAPES = [(3, 7, 9, 16), (4, 8, 8, 32), (2, 5, 5, 64)]


def test_fused_block_fwd_bitwise_f32(eight_devices):
    """Jitted interpret-mode kernel == jitted pure-jnp reference, bitwise.

    Both sides jitted: eager-vs-jitted comparison differs in the final ulp
    because XLA contracts mul+add to FMA only when it compiles the whole
    expression — the production paths (local SGD scan, eval) are always
    jitted, so that is the contract worth pinning."""
    from functools import partial

    from fedml_tpu.ops.pallas import (
        fused_block_reference, fused_bn_relu, fused_bn_residual_relu,
    )

    for shape in _FUSED_SHAPES:
        y, r, s, b, _ = _fused_inputs(shape)
        out = jax.jit(partial(fused_bn_residual_relu, interpret=True))(y, s, b, r)
        ref = jax.jit(fused_block_reference)(y, s, b, r)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(ref))
        out2 = jax.jit(partial(fused_bn_relu, interpret=True))(y, s, b)
        ref2 = jax.jit(fused_block_reference)(y, s, b)
        np.testing.assert_array_equal(np.asarray(out2), np.asarray(ref2))


def test_fused_block_grad_parity_f32(eight_devices):
    """Fused custom-VJP backward vs autodiff of the reference: the
    elementwise cotangents (dy, dresidual) are bitwise; the per-channel
    reductions (dscale, dshift) accumulate blockwise in the kernel vs one
    flat XLA reduce in the reference — different f32 association, so those
    are pinned to 1e-5."""
    from functools import partial

    from fedml_tpu.ops.pallas import fused_block_reference, fused_bn_residual_relu

    for shape in _FUSED_SHAPES:
        y, r, s, b, g = _fused_inputs(shape)

        def loss_k(y, s, b, r):
            return jnp.sum(fused_bn_residual_relu(y, s, b, r, interpret=True) * g)

        def loss_r(y, s, b, r):
            return jnp.sum(fused_block_reference(y, s, b, r) * g)

        dy, ds, db, dr = jax.jit(jax.grad(loss_k, argnums=(0, 1, 2, 3)))(y, s, b, r)
        dyr, dsr, dbr, drr = jax.jit(jax.grad(loss_r, argnums=(0, 1, 2, 3)))(y, s, b, r)
        np.testing.assert_array_equal(np.asarray(dy), np.asarray(dyr))
        np.testing.assert_array_equal(np.asarray(dr), np.asarray(drr))
        np.testing.assert_allclose(np.asarray(ds), np.asarray(dsr), rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(np.asarray(db), np.asarray(dbr), rtol=1e-5, atol=1e-5)


def test_fused_block_bf16_tolerance(eight_devices):
    """bf16 activations: kernel computes the epilogue in f32 internally and
    casts once at the end, so it is at least as accurate as the reference's
    own bf16 output — compare both to the f32 ground truth."""
    from functools import partial

    from fedml_tpu.ops.pallas import fused_block_reference, fused_bn_residual_relu

    shape = (4, 8, 8, 32)
    y32, r32, s, b, g = _fused_inputs(shape)
    y16, r16 = y32.astype(jnp.bfloat16), r32.astype(jnp.bfloat16)
    out = jax.jit(partial(fused_bn_residual_relu, interpret=True))(y16, s, b, r16)
    assert out.dtype == jnp.bfloat16
    truth = fused_block_reference(y16.astype(jnp.float32), s, b, r16.astype(jnp.float32))
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(truth), rtol=1e-2, atol=1e-2
    )
    # grads exist and are finite in bf16
    dy = jax.jit(jax.grad(lambda yy: jnp.sum(
        fused_bn_residual_relu(yy, s, b, r16, interpret=True).astype(jnp.float32))))(y16)
    assert dy.dtype == jnp.bfloat16
    assert bool(jnp.isfinite(dy.astype(jnp.float32)).all())


def test_fused_block_vmap(eight_devices):
    """vmap (the local-SGD client dimension) must agree with per-example
    calls — in particular the bwd accumulator tile must stay per-example
    under the pallas batching rule's prepended grid axis."""
    from functools import partial

    from fedml_tpu.ops.pallas import fused_bn_residual_relu

    y, r, s, b, g = _fused_inputs((3, 7, 9, 16))
    yv = jnp.stack([y, y * 0.5, -y])
    rv = jnp.stack([r, -r, r * 2.0])
    gv = jnp.stack([g, g, g])

    def one(y, r, g):
        out, pull = jax.vjp(
            lambda yy, rr: fused_bn_residual_relu(yy, s, b, rr, interpret=True), y, r)
        return out, pull(g)

    outs, (dys, drs) = jax.jit(jax.vmap(one))(yv, rv, gv)
    for i in range(3):
        out_i, (dy_i, dr_i) = jax.jit(one)(yv[i], rv[i], gv[i])
        np.testing.assert_array_equal(np.asarray(outs[i]), np.asarray(out_i))
        np.testing.assert_array_equal(np.asarray(dys[i]), np.asarray(dy_i))
        np.testing.assert_array_equal(np.asarray(drs[i]), np.asarray(dr_i))


def test_fused_resnet_tree_identical_and_close(eight_devices):
    """The fused model is a drop-in: identical variable tree (names, shapes,
    init values) and numerically equivalent forward/backward."""
    from fedml_tpu.models import resnet

    x = jax.random.normal(jax.random.PRNGKey(3), (4, 32, 32, 3), jnp.float32)
    k = jax.random.PRNGKey(0)
    m_u = resnet.CifarResNet(num_blocks=1)
    m_f = resnet.CifarResNet(num_blocks=1, fused=True)
    v_u = m_u.init({"params": k, "dropout": k}, x, train=True)
    v_f = m_f.init({"params": k, "dropout": k}, x, train=True)
    assert jax.tree_util.tree_structure(v_u) == jax.tree_util.tree_structure(v_f)
    for a, b in zip(jax.tree_util.tree_leaves(v_u), jax.tree_util.tree_leaves(v_f)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    lu, su = jax.jit(lambda v: m_u.apply(v, x, train=True, mutable=["batch_stats"]))(v_u)
    lf, sf = jax.jit(lambda v: m_f.apply(v, x, train=True, mutable=["batch_stats"]))(v_f)
    np.testing.assert_allclose(np.asarray(lu), np.asarray(lf), rtol=1e-5, atol=1e-5)
    for a, b in zip(jax.tree_util.tree_leaves(su), jax.tree_util.tree_leaves(sf)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)

    def loss(m, v):
        logits, _ = m.apply(v, x, train=True, mutable=["batch_stats"])
        return jnp.mean((logits.astype(jnp.float32) - 1.0) ** 2)

    gu = jax.jit(jax.grad(lambda p: loss(m_u, {"params": p, "batch_stats": v_u["batch_stats"]})))(v_u["params"])
    gf = jax.jit(jax.grad(lambda p: loss(m_f, {"params": p, "batch_stats": v_f["batch_stats"]})))(v_f["params"])
    for a, b in zip(jax.tree_util.tree_leaves(gu), jax.tree_util.tree_leaves(gf)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-5)


def test_fused_sim_smoke_loss_parity(eight_devices, make_tiny_config):
    """One MeshSimulator round, fused vs unfused, identical recipe/seed: the
    losses and the post-round global params must agree — the end-to-end pin
    that the fused custom-VJP composes with vmapped clients, the step scan
    and the round program."""
    import fedml_tpu
    from fedml_tpu.data import loader
    from fedml_tpu.models import resnet
    from fedml_tpu.parallel import mesh as meshlib
    from fedml_tpu.sim.engine import MeshSimulator

    cfg = make_tiny_config(
        dataset="cifar10", model="resnet20", client_num_in_total=4,
        client_num_per_round=2, batch_size=8, synthetic_train_size=64,
        synthetic_test_size=64, frequency_of_the_test=0,
    )
    fedml_tpu.init(cfg)
    mesh = meshlib.make_mesh((meshlib.AXIS_CLIENTS,), (2,), jax.devices()[:2])
    ds = loader.load(cfg)
    results = {}
    for fused in (False, True):
        model = resnet.CifarResNet(num_blocks=1, num_classes=ds.class_num, fused=fused)
        sim = MeshSimulator(cfg, ds, model, mesh=mesh)
        metrics = sim.run_round()
        results[fused] = (metrics, jax.device_get(sim.global_vars))
    mu, vu = results[False]
    mf, vf = results[True]
    assert np.isfinite(mu["train_loss"]) and np.isfinite(mf["train_loss"])
    np.testing.assert_allclose(mu["train_loss"], mf["train_loss"], rtol=1e-4)
    for a, b in zip(jax.tree_util.tree_leaves(vu), jax.tree_util.tree_leaves(vf)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-3, atol=1e-5)


def test_pallas_kernel_seconds_histogram(eight_devices):
    """Eager kernel invocations land in the process-global
    ``fedml_pallas_kernel_seconds`` histogram (labels=kernel) and surface in
    both the Prometheus rendering and the bench summary helper."""
    from fedml_tpu.obs.registry import REGISTRY
    from fedml_tpu.ops.pallas import (
        fused_bn_relu, kernel_time_summary, quantize_int8_stochastic,
    )

    hist = REGISTRY.get("fedml_pallas_kernel_seconds")
    assert hist is not None
    before = hist.count(kernel="fused_bn_relu")
    y, _, s, b, _ = _fused_inputs((2, 4, 4, 16))
    fused_bn_relu(y, s, b, interpret=True)  # eager -> observed
    assert hist.count(kernel="fused_bn_relu") == before + 1
    quantize_int8_stochastic(jnp.ones(2048), jax.random.PRNGKey(0), interpret=True)
    assert hist.count(kernel="quantize_int8_stochastic") >= 1
    summary = kernel_time_summary()
    assert summary["fused_bn_relu"]["count"] >= 1
    assert "fedml_pallas_kernel_seconds_bucket" in REGISTRY.render()
    # traced invocations are NOT host-timed (wall clock there measures
    # tracing, not the kernel)
    n = hist.count(kernel="fused_bn_relu")
    jax.jit(lambda yy: fused_bn_relu(yy, s, b, interpret=True))(y)
    assert hist.count(kernel="fused_bn_relu") == n


def test_pallas_kernel_sink_and_report_section(eight_devices):
    """Registered timing sinks see each eager observation (the cross-silo
    client ships them as metric records), and ``obs report`` renders those
    records as a per-kernel summary table."""
    from fedml_tpu.obs import report as obs_report
    from fedml_tpu.ops.pallas import fused_bn_relu
    from fedml_tpu.ops.pallas import timing

    records = []
    sink = timing.add_sink(lambda k, s: records.append(
        {"kind": "metric", "metric": "pallas_kernel_seconds", "kernel": k, "value": s}))
    try:
        y, _, s, b, _ = _fused_inputs((2, 4, 4, 16))
        fused_bn_relu(y, s, b, interpret=True)
    finally:
        timing.remove_sink(sink)
    assert records and records[0]["kernel"] == "fused_bn_relu"
    stats = obs_report.pallas_kernel_stats(records)
    assert stats[0]["kernel"] == "fused_bn_relu" and stats[0]["n"] == len(records)
    trail = records + [{"kind": "span", "name": "round", "trace_id": "t",
                        "span_id": "s1", "round_idx": 0, "ts": 1.0, "dur_s": 1.0}]
    text = obs_report.render_report(trail)
    assert "pallas kernels" in text and "fused_bn_relu" in text
    # a trail with no kernel records renders no (empty) kernel section
    assert "pallas kernels" not in obs_report.render_report(trail[-1:])
