"""Pallas kernel tests (interpret mode — CPU CI; the compiled path is
exercised on the real chip by the round driver's bench/verify runs)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest


def test_quantize_kernel_matches_reference(eight_devices):
    from fedml_tpu.ops.pallas import (
        dequantize_int8,
        quantize_int8_reference,
        quantize_int8_stochastic,
    )

    k = jax.random.PRNGKey(0)
    x = jax.random.normal(k, (5000,)) * 3.0
    v, s, n = quantize_int8_stochastic(x, k, interpret=True)
    vr, sr, nr = quantize_int8_reference(x, k)
    np.testing.assert_array_equal(np.asarray(v), np.asarray(vr))
    np.testing.assert_allclose(np.asarray(s), np.asarray(sr), rtol=1e-6)
    assert n == nr == 5000
    assert v.dtype == jnp.int8

    back = dequantize_int8(v, s, n, interpret=True)
    assert back.shape == x.shape
    # error bounded by one quantization step per block
    assert float(jnp.abs(back - x).max()) <= float(s.max()) + 1e-6


def test_quantize_kernel_unbiased(eight_devices):
    from fedml_tpu.ops.compression import qsgd_int8_fused

    k = jax.random.PRNGKey(1)
    x = jax.random.normal(k, (2048,))
    est = jnp.stack([
        qsgd_int8_fused(x, jax.random.PRNGKey(i), interpret=True) for i in range(40)
    ]).mean(0)
    assert float(jnp.abs(est - x).mean()) < 0.01


def test_quantize_kernel_edge_shapes(eight_devices):
    from fedml_tpu.ops.pallas import dequantize_int8, quantize_int8_stochastic

    k = jax.random.PRNGKey(2)
    for n in (1, 1023, 1024, 1025, 4096):
        x = jax.random.normal(k, (n,))
        v, s, length = quantize_int8_stochastic(x, k, interpret=True)
        back = dequantize_int8(v, s, length, interpret=True)
        assert back.shape == (n,)
        assert float(jnp.abs(back - x).max()) <= float(s.max()) + 1e-6
