"""Cross-cloud federated LLM (UnitedLLM parity) + full runner dispatch.

VERDICT round-2 item 4: silos exchange LoRA adapters over a routable
transport through the cross-silo protocol — adapter-only payloads, loss
decreases — and FedMLRunner dispatches every training_type constant.
"""

import socket

import numpy as np
import pytest

from .conftest import tiny_config


def _free_port_block(n: int = 8) -> int:
    """A base port whose first n+1 offsets are currently free."""
    while True:
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            base = s.getsockname()[1]
        if base + n < 65000:
            return base


def _llm_cfg(**kw):
    base = dict(
        training_type="cross_cloud",
        dataset="shakespeare",
        model="transformer",
        client_num_in_total=2,
        client_num_per_round=2,
        comm_round=2,
        epochs=1,
        batch_size=4,
        learning_rate=0.01,
        synthetic_train_size=128,
        synthetic_test_size=32,
        frequency_of_the_test=1,
        extra={"unitedllm": True, "lora_r": 2},
    )
    extra = kw.pop("extra", {})
    base.update(kw)
    merged = dict(base["extra"])
    merged.update(extra)
    base["extra"] = merged
    return tiny_config(**base)


def test_unitedllm_adapters_only_over_tcp(eight_devices):
    """2 LLM silos + server over REAL TCP loopback sockets: every model
    payload on the wire is the LoRA tree (a small fraction of the base
    model's size), and training loss decreases across rounds."""
    import jax
    import fedml_tpu
    from fedml_tpu.comm.message import Message
    from fedml_tpu.cross_silo import message_define as md
    from fedml_tpu.data import loader
    from fedml_tpu.llm import lora as lora_lib
    from fedml_tpu.llm.unitedllm import LoRASiloTrainer, run_unitedllm_process_group

    base_port = _free_port_block()
    cfg = _llm_cfg(run_id="ccllm1", backend="TCP",
                   extra={"tcp_base_port": base_port})
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)

    sizes = []
    orig_encode = Message.encode

    def spy_encode(self):
        blob = orig_encode(self)
        if self.get(md.MSG_ARG_KEY_MODEL_PARAMS) is not None:
            sizes.append(len(blob))
        return blob

    Message.encode = spy_encode
    try:
        history, server = run_unitedllm_process_group(cfg, ds, backend="TCP", timeout=240.0)
    finally:
        Message.encode = orig_encode

    assert len(history) == cfg.comm_round
    # loss decreases and perplexity is finite
    assert history[-1]["test_loss"] <= history[0]["test_loss"] + 1e-6, history
    # adapter-only payloads: every model message is a small fraction of the
    # full base model's wire size
    base_bytes = sum(
        np.asarray(l).nbytes
        for l in jax.tree_util.tree_leaves(server.aggregator.base_params)
    )
    lora_bytes = sum(
        np.asarray(l).nbytes
        for l in jax.tree_util.tree_leaves(server.aggregator.global_vars)
    )
    assert lora_bytes < base_bytes / 10, (lora_bytes, base_bytes)
    assert sizes, "no model payloads observed on the wire"
    for s in sizes:
        assert s < base_bytes / 2, (s, base_bytes)


def test_runner_dispatches_cross_cloud_llm(eight_devices):
    """training_type='cross_cloud' + extra.unitedllm through FedMLRunner."""
    import fedml_tpu
    from fedml_tpu.runner import FedMLRunner

    cfg = _llm_cfg(run_id="ccllm2", role="server", backend="INPROC", comm_round=1)
    fedml_tpu.init(cfg)
    history = FedMLRunner(cfg).run()
    assert history and "test_loss" in history[-1]


def test_runner_dispatches_cross_cloud_plain(eight_devices):
    """Non-LLM cross-cloud = cross-silo protocol with WAN defaults."""
    import fedml_tpu
    from fedml_tpu.runner import FedMLRunner

    cfg = tiny_config(
        training_type="cross_cloud", role="server", backend="INPROC",
        client_num_in_total=2, client_num_per_round=2, comm_round=1,
        run_id="ccplain", frequency_of_the_test=1,
    )
    fedml_tpu.init(cfg)
    history = FedMLRunner(cfg).run()
    assert history and history[-1]["test_acc"] > 0.2


def test_cross_cloud_routes_secagg_to_secure_managers(eight_devices):
    """cross_cloud + enable_secagg must dispatch the secure protocol, not
    plain cross-silo (a silent WAN privacy downgrade otherwise)."""
    import fedml_tpu
    from fedml_tpu.cross_silo.secagg_shamir import SAAggregator
    from fedml_tpu.runner import FedMLRunner

    seen = []
    orig = SAAggregator.add_local_trained_result

    def spy(self, *a, **k):
        seen.append(1)
        return orig(self, *a, **k)

    cfg = tiny_config(
        training_type="cross_cloud", role="server", backend="INPROC",
        client_num_in_total=4, client_num_per_round=4, comm_round=1,
        run_id="ccsec", frequency_of_the_test=0, enable_secagg=True,
        extra={"secagg_method": "shamir"},
    )
    fedml_tpu.init(cfg)
    SAAggregator.add_local_trained_result = spy
    try:
        FedMLRunner(cfg).run()
    finally:
        SAAggregator.add_local_trained_result = orig
    assert seen, "secagg cross-cloud run did not go through the Shamir aggregator"


def test_serving_refuses_secagg_flags(eight_devices):
    import fedml_tpu
    from fedml_tpu.runner import FedMLRunner

    cfg = tiny_config(
        training_type="model_serving", role="server", backend="INPROC",
        client_num_in_total=2, client_num_per_round=2, comm_round=1,
        run_id="srvsec", enable_secagg=True,
    )
    fedml_tpu.init(cfg)
    with pytest.raises(NotImplementedError):
        FedMLRunner(cfg)


def test_runner_dispatches_model_serving(eight_devices, tmp_path):
    """training_type='model_serving' runs the federated job under an
    endpoint identity through FedMLRunner."""
    import fedml_tpu
    from fedml_tpu.runner import FedMLRunner

    cfg = tiny_config(
        training_type="model_serving", role="server", backend="INPROC",
        client_num_in_total=2, client_num_per_round=2, comm_round=1,
        run_id="ccserve", frequency_of_the_test=1,
        extra={"end_point_name": "ep-test", "serving_model_name": "lr-test"},
    )
    fedml_tpu.init(cfg)
    history = FedMLRunner(cfg).run()
    assert history and history[-1]["test_acc"] > 0.2


def test_runner_dispatches_all_platform_constants(eight_devices):
    """Every training_type constant reaches a platform runner (reference
    runner.py:19 dispatches all platforms); unknown values are refused."""
    import fedml_tpu
    from fedml_tpu import constants as C
    from fedml_tpu.runner import FedMLRunner

    for t in (C.TRAINING_PLATFORM_SIMULATION, C.TRAINING_PLATFORM_CROSS_SILO,
              C.TRAINING_PLATFORM_CROSS_DEVICE, C.TRAINING_PLATFORM_CROSS_CLOUD,
              C.TRAINING_PLATFORM_SERVING, C.TRAINING_PLATFORM_CENTRALIZED):
        cfg = tiny_config(training_type=t, role="client", rank=1,
                          client_num_in_total=2, client_num_per_round=2,
                          run_id=f"disp-{t}")
        fedml_tpu.init(cfg)
        runner = FedMLRunner(cfg)  # construction must succeed for every platform
        assert runner.runner is not None

    with pytest.raises(ValueError):
        FedMLRunner(tiny_config(training_type="nope"))
