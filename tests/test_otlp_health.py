"""OTLP export sink + per-client health ledger (ISSUE 3).

- golden payload-shape tests against a fake stdlib OTLP/HTTP collector:
  span records -> ResourceSpans (32/16-hex ids, unix-nano clocks, typed
  attributes) and registry snapshots -> ResourceMetrics (monotonic sums,
  gauges, histograms with explicit bounds);
- exponential-backoff retry on 429/503 with registry-visible
  shipped/dropped/retried accounting, bounded-loss behavior against a dead
  collector, and the no-endpoint-no-thread gate;
- the acceptance run: an INPROC cross-silo round exports its COMPLETE
  distributed span tree (server round/aggregate spans + both clients'
  train spans under one trace_id per round) plus a registry snapshot;
- `fedml-tpu obs export` backfills a recorded JSONL trail;
- the health ledger: EWMA/recovery scoring, deadline breaches recorded on
  straggler timeouts, and health-aware selection deprioritizing a degraded
  rank end-to-end.
"""

import json
import threading
import time

import pytest

from .conftest import tiny_config


# ---------------------------------------------------------------------------
# fake OTLP/HTTP collector (stdlib http.server)


class FakeOTLPCollector:
    """Records POSTed JSON bodies per path; optionally fails the first N
    requests with a configurable status (the 429/5xx retry path)."""

    def __init__(self, fail_first: int = 0, fail_status: int = 503):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        self.requests: list[tuple[str, dict]] = []
        self.fail_remaining = fail_first
        self.fail_status = fail_status
        self.lock = threading.Lock()
        collector = self

        class _Handler(BaseHTTPRequestHandler):
            def do_POST(self):  # noqa: N802 (http.server API)
                length = int(self.headers.get("Content-Length", 0))
                body = self.rfile.read(length)
                with collector.lock:
                    if collector.fail_remaining > 0:
                        collector.fail_remaining -= 1
                        status = collector.fail_status
                    else:
                        collector.requests.append((self.path, json.loads(body)))
                        status = 200
                out = b"{}"
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(out)))
                self.end_headers()
                self.wfile.write(out)

            def log_message(self, *args):
                pass

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), _Handler)
        self.httpd.daemon_threads = True
        self.port = self.httpd.server_address[1]
        self.endpoint = f"http://127.0.0.1:{self.port}"
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()

    def spans(self) -> list[dict]:
        out = []
        with self.lock:
            for path, payload in self.requests:
                if path != "/v1/traces":
                    continue
                for rs in payload.get("resourceSpans", []):
                    for ss in rs.get("scopeSpans", []):
                        out.extend(ss.get("spans", []))
        return out

    def metrics(self) -> dict:
        names = {}
        with self.lock:
            for path, payload in self.requests:
                if path != "/v1/metrics":
                    continue
                for rm in payload.get("resourceMetrics", []):
                    for sm in rm.get("scopeMetrics", []):
                        for m in sm.get("metrics", []):
                            names[m["name"]] = m
        return names

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


@pytest.fixture
def fake_collector():
    c = FakeOTLPCollector()
    yield c
    c.close()


def _attr_map(attrs):
    return {a["key"]: a["value"] for a in attrs}


_HEX = set("0123456789abcdef")


# ---------------------------------------------------------------------------
# golden payload shapes


def test_trace_payload_golden_shape(fake_collector):
    """A native Span round trip: 32/16-hex zero-padded ids, unix-nano
    string clocks, typed attributes, parent linkage."""
    from fedml_tpu.obs import trace
    from fedml_tpu.obs.otlp import OTLPExporter

    with trace.traced("round", round_idx=7, clients=2) as round_span:
        with trace.traced("train", client_idx=1, rank=1) as train_span:
            time.sleep(0.002)

    exp = OTLPExporter(fake_collector.endpoint, flush_interval_s=0.05)
    exp.enqueue_span({"sender": 0, **round_span.to_record()})
    exp.enqueue_span({"sender": 1, **train_span.to_record()})
    assert exp.flush(timeout=10.0)

    spans = fake_collector.spans()
    assert {s["name"] for s in spans} == {"round", "train"}
    by_name = {s["name"]: s for s in spans}
    root, child = by_name["round"], by_name["train"]
    for s in spans:
        assert len(s["traceId"]) == 32 and set(s["traceId"]) <= _HEX
        assert len(s["spanId"]) == 16 and set(s["spanId"]) <= _HEX
        assert s["kind"] == 1
        # proto3-JSON encodes uint64 nanos as strings
        assert isinstance(s["startTimeUnixNano"], str)
        assert int(s["endTimeUnixNano"]) >= int(s["startTimeUnixNano"]) > 1e18
    # native 16-hex ids are zero-padded into the trace id width
    assert root["traceId"].endswith(round_span.trace_id)
    assert root["traceId"].startswith("0" * 16)
    assert child["traceId"] == root["traceId"]
    assert child["parentSpanId"] == root["spanId"]
    assert "parentSpanId" not in root
    attrs = _attr_map(root["attributes"])
    assert attrs["round_idx"] == {"intValue": "7"}
    assert attrs["clients"] == {"intValue": "2"}
    assert attrs["sender"] == {"intValue": "0"}
    assert int(child["endTimeUnixNano"]) - int(child["startTimeUnixNano"]) >= 2e6

    exp.close()
    # close ships a final registry snapshot to /v1/metrics
    assert fake_collector.metrics()


def test_metrics_payload_golden_shape(fake_collector):
    """Registry snapshot mapping: Counter -> monotonic cumulative sum,
    Gauge -> gauge, Histogram -> histogram with explicit bounds where the
    +Inf bucket becomes the overflow count."""
    from fedml_tpu.obs.otlp import OTLPExporter
    from fedml_tpu.obs.registry import MetricsRegistry

    reg = MetricsRegistry()
    c = reg.counter("fedml_demo_requests_total", "requests", labels=("code",))
    c.inc(3, code="200")
    g = reg.gauge("fedml_demo_temp", "temperature")
    g.set(-3.5)
    h = reg.histogram("fedml_demo_latency_seconds", "latency",
                      buckets=(0.01, 0.1, 1.0))
    for v in (0.005, 0.05, 0.05, 0.5, 5.0):
        h.observe(v)

    exp = OTLPExporter(fake_collector.endpoint, registry=reg,
                       flush_interval_s=0.05)
    assert exp.export_metrics_now()
    exp.close()

    metrics = fake_collector.metrics()
    ctr = metrics["fedml_demo_requests_total"]["sum"]
    assert ctr["isMonotonic"] is True and ctr["aggregationTemporality"] == 2
    dp = ctr["dataPoints"][0]
    assert dp["asDouble"] == 3.0
    assert _attr_map(dp["attributes"]) == {"code": {"stringValue": "200"}}

    gauge_dp = metrics["fedml_demo_temp"]["gauge"]["dataPoints"][0]
    assert gauge_dp["asDouble"] == -3.5

    hist = metrics["fedml_demo_latency_seconds"]["histogram"]
    assert hist["aggregationTemporality"] == 2
    hdp = hist["dataPoints"][0]
    assert hdp["explicitBounds"] == [0.01, 0.1, 1.0]
    assert hdp["bucketCounts"] == ["1", "2", "1", "1"]  # len(bounds) + 1
    assert hdp["count"] == "5"
    assert abs(hdp["sum"] - 5.605) < 1e-9


def test_foreign_ids_hash_deterministically():
    """Hand-written trail ids (non-hex) still produce consistent 32/16-hex
    ids, preserving parent/child linkage after conversion."""
    from fedml_tpu.obs.otlp import span_record_to_otlp

    parent = span_record_to_otlp({"kind": "span", "name": "round", "trace_id": "t0",
                                  "span_id": "r0", "ts": 100.0, "dur_s": 2.0})
    child = span_record_to_otlp({"kind": "span", "name": "train", "trace_id": "t0",
                                 "span_id": "c10", "parent_id": "r0",
                                 "ts": 100.1, "dur_s": 0.5})
    assert child["traceId"] == parent["traceId"] and len(parent["traceId"]) == 32
    assert child["parentSpanId"] == parent["spanId"] and len(parent["spanId"]) == 16


# ---------------------------------------------------------------------------
# retry / backoff / bounded loss


def test_retry_backoff_on_503_then_delivers():
    from fedml_tpu.obs.otlp import OTLP_RETRIED, OTLP_SHIPPED, OTLPExporter

    collector = FakeOTLPCollector(fail_first=2, fail_status=503)
    try:
        retried0 = OTLP_RETRIED.value()
        shipped0 = OTLP_SHIPPED.value(signal="traces")
        exp = OTLPExporter(collector.endpoint, flush_interval_s=0.05,
                           backoff_base_s=0.02, max_retries=4)
        exp.enqueue_span({"kind": "span", "name": "round", "trace_id": "ab" * 8,
                          "span_id": "cd" * 8, "ts": time.time(), "dur_s": 0.1})
        assert exp.flush(timeout=15.0)
        assert OTLP_RETRIED.value() - retried0 >= 2
        assert OTLP_SHIPPED.value(signal="traces") - shipped0 == 1
        assert len(collector.spans()) == 1
        exp.close()
    finally:
        collector.close()


def test_429_is_retryable_and_4xx_drops():
    from fedml_tpu.obs.otlp import OTLP_DROPPED, OTLPExporter, post_otlp

    # 429 -> retried until the 200 behind it
    collector = FakeOTLPCollector(fail_first=1, fail_status=429)
    try:
        status = post_otlp(collector.endpoint + "/v1/traces", {"resourceSpans": []},
                           max_retries=3, backoff_base_s=0.02)
        assert status == 200
    finally:
        collector.close()

    # 400 -> non-retryable: dropped immediately with reason=rejected
    collector = FakeOTLPCollector(fail_first=10**6, fail_status=400)
    try:
        dropped0 = OTLP_DROPPED.value(signal="traces", reason="rejected")
        exp = OTLPExporter(collector.endpoint, flush_interval_s=0.05,
                           backoff_base_s=0.02, max_retries=3)
        exp.enqueue_span({"kind": "span", "name": "x", "trace_id": "ab" * 8,
                          "span_id": "cd" * 8, "ts": time.time(), "dur_s": 0.0})
        assert exp.flush(timeout=10.0)
        assert OTLP_DROPPED.value(signal="traces", reason="rejected") - dropped0 == 1
        exp.close()
    finally:
        collector.close()


def test_dead_collector_bounded_loss_accounting():
    """Against an unreachable endpoint every span is eventually dropped —
    and every drop is accounted for (queue_full + retries_exhausted sum to
    exactly what was enqueued).  Telemetry loss is observable, never
    silent."""
    from fedml_tpu.obs.otlp import OTLP_DROPPED, OTLP_SHIPPED, OTLPExporter

    def dropped_total():
        fam = OTLP_DROPPED._snapshot()
        return sum(s["value"] for s in fam["samples"]
                   if s["labels"]["signal"] == "traces")

    d0 = dropped_total()
    s0 = OTLP_SHIPPED.value(signal="traces")
    exp = OTLPExporter("http://127.0.0.1:9", queue_size=8, batch_size=4,
                       flush_interval_s=0.02, max_retries=1,
                       backoff_base_s=0.01, timeout_s=0.2)
    n = 50
    for i in range(n):
        exp.enqueue_span({"kind": "span", "name": f"s{i}", "trace_id": "ab" * 8,
                          "span_id": f"{i:016d}"[-16:], "ts": time.time(),
                          "dur_s": 0.0})
    exp.flush(timeout=20.0)
    exp.close(timeout=20.0)
    assert OTLP_SHIPPED.value(signal="traces") == s0
    assert dropped_total() - d0 == n


def test_no_endpoint_means_no_exporter_and_no_thread():
    from fedml_tpu.obs.otlp import exporter_from_config

    before = [t.name for t in threading.enumerate()
              if t.name == "fedml-otlp-export"]
    cfg = tiny_config()
    assert exporter_from_config(cfg) is None
    cfg.extra = {"metrics_port": None}
    assert exporter_from_config(cfg) is None
    after = [t.name for t in threading.enumerate()
             if t.name == "fedml-otlp-export"]
    assert before == after


# ---------------------------------------------------------------------------
# acceptance: cross-silo INPROC run exports the whole round tree


def test_cross_silo_exports_complete_round_tree(fake_collector, eight_devices):
    """With extra.otlp_endpoint set, rank 0 exports the WHOLE distributed
    round tree — its own round/aggregate/eval spans AND both clients' train
    spans sharing one trace_id per round — plus a final registry snapshot,
    all as OTLP/HTTP JSON, stdlib only."""
    import fedml_tpu
    from fedml_tpu.comm.inproc import InProcRouter
    from fedml_tpu.cross_silo import build_client, build_server
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub

    cfg = tiny_config(
        training_type="cross_silo", client_num_in_total=2, client_num_per_round=2,
        comm_round=2, learning_rate=0.3, frequency_of_the_test=1, run_id="otlp-e2e",
    )
    cfg.extra = {"enable_remote_obs": True, "otlp_endpoint": fake_collector.endpoint}
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)
    InProcRouter.reset("otlp-e2e")
    clients = [build_client(cfg, ds, model, rank=r, backend="INPROC") for r in (1, 2)]
    for c in clients:
        c.run_in_thread()
    server = build_server(cfg, ds, model, backend="INPROC")
    assert server.otlp is not None
    try:
        history = server.run_until_done(timeout=120.0)
    finally:
        for c in clients:
            c.finish()
    assert len(history) == 2

    spans = fake_collector.spans()
    by_name: dict[str, list] = {}
    for s in spans:
        by_name.setdefault(s["name"], []).append(s)
    assert len(by_name["round"]) == 2
    assert len(by_name["aggregate"]) == 2
    assert len(by_name["train"]) == 4  # 2 clients x 2 rounds

    for round_span in by_name["round"]:
        tid = round_span["traceId"]
        assert len(tid) == 32 and set(tid) <= _HEX
        members = [s for s in spans if s["traceId"] == tid and s is not round_span]
        names = [s["name"] for s in members]
        assert names.count("train") == 2 and "aggregate" in names
        # the train spans (client-side halves of the tree) parent to the
        # server's round span — the stamp each broadcast carried
        for s in members:
            if s["name"] == "train":
                assert s["parentSpanId"] == round_span["spanId"]
                assert _attr_map(s["attributes"])["sender"]["intValue"] in ("1", "2")

    # the final registry snapshot arrived as ResourceMetrics
    metrics = fake_collector.metrics()
    assert all(name.startswith("fedml_") for name in metrics)
    assert "fedml_crosssilo_client_round_trip_seconds" in metrics
    hist = metrics["fedml_crosssilo_client_round_trip_seconds"]["histogram"]
    assert hist["dataPoints"] and hist["aggregationTemporality"] == 2
    assert "fedml_client_health_score" in metrics
    assert "fedml_otlp_shipped_total" in metrics  # the exporter observes itself


def test_cross_silo_without_endpoint_is_unchanged(eight_devices):
    """extra.otlp_endpoint unset -> no exporter object, no worker thread,
    and the default remote-obs path behaves exactly as before."""
    import fedml_tpu
    from fedml_tpu.comm.inproc import InProcRouter
    from fedml_tpu.cross_silo import build_client, build_server
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub

    cfg = tiny_config(
        training_type="cross_silo", client_num_in_total=2, client_num_per_round=2,
        comm_round=2, learning_rate=0.3, frequency_of_the_test=0, run_id="otlp-off",
    )
    cfg.extra = {"enable_remote_obs": True}
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)
    InProcRouter.reset("otlp-off")
    clients = [build_client(cfg, ds, model, rank=r, backend="INPROC") for r in (1, 2)]
    for c in clients:
        c.run_in_thread()
    server = build_server(cfg, ds, model, backend="INPROC")
    assert server.otlp is None
    assert server.obs_collector is not None and server.obs_collector.otlp is None
    try:
        history = server.run_until_done(timeout=120.0)
    finally:
        for c in clients:
            c.finish()
    assert len(history) == 2
    assert not [t for t in threading.enumerate() if t.name == "fedml-otlp-export"]


# ---------------------------------------------------------------------------
# obs export backfill


def test_obs_export_backfills_trail(tmp_path, fake_collector):
    from fedml_tpu.cli import main as cli_main

    trail = tmp_path / "obs.jsonl"
    records = [
        {"sender": 0, "kind": "span", "name": "round", "trace_id": "t0",
         "span_id": "r0", "ts": 100.0, "dur_s": 2.0, "round_idx": 0},
        {"sender": 1, "kind": "span", "name": "train", "trace_id": "t0",
         "span_id": "c10", "parent_id": "r0", "ts": 100.1, "dur_s": 0.5,
         "round_idx": 0, "client_idx": 0},
        {"sender": 0, "kind": "metric", "metric": "client_round_trip_s",
         "client": 1, "value": 0.6, "round_idx": 0, "trace_id": "t0", "ts": 102.0},
        {"sender": 1, "kind": "log", "lines": ["not a span"]},
    ]
    trail.write_text("\n".join(json.dumps(r) for r in records) + "\n")

    rc = cli_main(["obs", "export", str(trail),
                   "--endpoint", fake_collector.endpoint])
    assert rc == 0
    spans = fake_collector.spans()
    assert {s["name"] for s in spans} == {"round", "train"}
    train = next(s for s in spans if s["name"] == "train")
    root = next(s for s in spans if s["name"] == "round")
    assert train["parentSpanId"] == root["spanId"]
    metrics = fake_collector.metrics()
    dp = metrics["client_round_trip_s"]["gauge"]["dataPoints"][0]
    assert dp["asDouble"] == 0.6
    assert _attr_map(dp["attributes"])["client"] == {"intValue": "1"}


# ---------------------------------------------------------------------------
# health ledger


def test_health_ledger_scoring_and_recovery():
    from fedml_tpu.obs.health import ClientHealthLedger

    ledger = ClientHealthLedger(ewma_alpha=0.5, recovery=0.5)
    assert ledger.score(1) == 1.0  # unknown = healthy

    ledger.observe_rtt(1, 1.0)
    assert ledger.summary()[1]["ewma_rtt_s"] == 1.0
    ledger.observe_rtt(1, 2.0)
    assert abs(ledger.summary()[1]["ewma_rtt_s"] - 1.5) < 1e-9  # EWMA, not mean

    # breaches degrade the score multiplicatively...
    for _ in range(4):
        ledger.record_deadline_breach(2)
    assert ledger.score(2) == pytest.approx(1.0 / 3.0)  # 1/(1+0.5*4)
    # ...and decay on successful round trips (recovery)
    ledger.observe_rtt(2, 1.0)
    ledger.observe_rtt(2, 1.0)
    assert ledger.score(2) > 0.5

    # an RTT far above the fleet median degrades even without breaches
    for c in (3, 4, 5):
        ledger.observe_rtt(c, 0.1)
    ledger.observe_rtt(6, 10.0)
    assert ledger.score(6) < 0.5 < ledger.score(3)

    healthy, degraded = ledger.partition([1, 2, 3, 6])
    assert 6 in degraded and 6 not in healthy
    assert set(healthy) | set(degraded) == {1, 2, 3, 6}

    recs = ledger.records(trace_id="t-1")
    assert all(r["kind"] == "metric" and r["metric"] == "client_health"
               and r["trace_id"] == "t-1" for r in recs)
    assert {r["client"] for r in recs} == {1, 2, 3, 4, 5, 6}


def test_health_ledger_comm_sink_and_gauges():
    from fedml_tpu.comm import base as comm_base
    from fedml_tpu.obs.health import ClientHealthLedger
    from fedml_tpu.obs.registry import REGISTRY

    ledger = ClientHealthLedger().attach_comm()
    try:
        comm_base._emit_comm_event("dropped", reason="undecodable")
        comm_base._emit_comm_event("retried")
        comm_base._emit_comm_event("retried")
        assert ledger.summary()["_comm"] == {"drops": 1, "retries": 2}
        ledger.record_comm_failure(9, 2)
        assert REGISTRY.get("fedml_client_health_comm_failures").value(client="9") == 2.0
        assert REGISTRY.get("fedml_client_health_score").value(client="9") == \
            pytest.approx(1.0 / 1.5)
    finally:
        ledger.detach_comm()
    # after detach the sink no longer counts
    comm_base._emit_comm_event("retried")
    assert ledger.summary()["_comm"]["retries"] == 2


def test_health_aware_selection_deprioritizes_degraded_rank(eight_devices):
    """Acceptance: an INPROC run where rank 3 carries injected deadline
    breaches — behind extra.health_aware_selection the server samples only
    the healthy ranks, so rank 3 never trains while the others carry every
    round."""
    import fedml_tpu
    from fedml_tpu.comm.inproc import InProcRouter
    from fedml_tpu.cross_silo import build_client, build_server
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub

    cfg = tiny_config(
        training_type="cross_silo", client_num_in_total=3, client_num_per_round=2,
        comm_round=3, learning_rate=0.3, frequency_of_the_test=0, run_id="health-sel",
    )
    cfg.extra = {"health_aware_selection": True}
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)
    InProcRouter.reset("health-sel")
    clients = [build_client(cfg, ds, model, rank=r, backend="INPROC") for r in (1, 2, 3)]
    for c in clients:
        c.run_in_thread()
    server = build_server(cfg, ds, model, backend="INPROC")
    assert server.health_aware
    for _ in range(6):  # score 1/(1+0.5*6) = 0.25 < the 0.5 threshold
        server.health.record_deadline_breach(3)
    assert server.health.score(3) < 0.5
    try:
        history = server.run_until_done(timeout=120.0)
    finally:
        for c in clients:
            c.finish()
    assert len(history) == 3
    assert clients[0].rounds_trained == 3
    assert clients[1].rounds_trained == 3
    assert clients[2].rounds_trained == 0  # deprioritized every round


def test_straggler_timeout_records_deadline_breaches(eight_devices):
    """The e2e breach source: a client whose uploads vanish breaches the
    straggler deadline every round, and the server's ledger remembers."""
    import fedml_tpu
    from fedml_tpu.comm.inproc import InProcRouter
    from fedml_tpu.cross_silo import build_client, build_server
    from fedml_tpu.cross_silo import message_define as md
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub

    cfg = tiny_config(
        training_type="cross_silo", client_num_in_total=4, client_num_per_round=4,
        comm_round=2, learning_rate=0.3, frequency_of_the_test=0, run_id="health-brch",
    )
    cfg.extra = {"straggler_timeout_s": 1.0, "straggler_quorum_frac": 0.5}
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)
    InProcRouter.reset("health-brch")
    router = InProcRouter.get("health-brch")
    router.drop_rule = lambda m: (
        m.get_sender_id() == 4 and m.get_type() == md.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER
    )
    clients = [build_client(cfg, ds, model, rank=r, backend="INPROC") for r in range(1, 5)]
    for c in clients:
        c.run_in_thread()
    server = build_server(cfg, ds, model, backend="INPROC")
    try:
        history = server.run_until_done(timeout=60.0)
    finally:
        for c in clients:
            c.finish()
    assert len(history) == 2
    summary = server.health.summary()
    assert summary[4]["breaches"] >= 1.0
    assert summary[4]["score"] < 1.0
    # the replying clients stayed healthy
    for cid in (1, 2, 3):
        assert summary[cid]["score"] > summary[4]["score"]


def test_client_selection_without_health_is_reference_exact():
    """No ledger -> bit-identical to the reference's round-seeded sampler;
    with a ledger but everyone healthy -> same draw over the same pool."""
    import numpy as np

    import fedml_tpu
    from fedml_tpu.core import rng
    from fedml_tpu.cross_silo import build_aggregator
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub
    from fedml_tpu.obs.health import ClientHealthLedger

    cfg = tiny_config(client_num_in_total=8, client_num_per_round=3)
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)
    agg = build_aggregator(cfg, ds, model)
    ids = list(range(1, 9))
    expected = [ids[i] for i in rng.sample_clients_np(5, 8, 3)]
    assert agg.client_selection(5, ids, 3) == expected
    assert agg.client_selection(5, ids, 3, health=ClientHealthLedger()) == expected
    # degraded ranks drop out of the sampled pool
    ledger = ClientHealthLedger()
    for _ in range(6):
        ledger.record_deadline_breach(8)
    selected = agg.client_selection(5, ids, 3, health=ledger)
    assert 8 not in selected and len(selected) == 3
    # everyone fits -> everyone participates, degraded or not (reference)
    assert agg.client_selection(5, ids, 8, health=ledger) == ids


# ---------------------------------------------------------------------------
# report tolerance + health section (satellite)


def test_report_tolerates_missing_dur_and_clock_skew():
    """Records without dur_s and with skewed/missing/non-numeric timestamps
    must neither raise nor reshuffle the timeline: ordering falls back to
    collector ingest order."""
    from fedml_tpu.obs import report

    records = [
        # round 0 from a host whose clock is AHEAD of round 1's host
        {"sender": 0, "kind": "span", "name": "round", "trace_id": "t0",
         "span_id": "r0", "ts": 900.0, "dur_s": None, "round_idx": 0},
        {"sender": 1, "kind": "span", "name": "train", "trace_id": "t0",
         "span_id": "c0", "parent_id": "r0", "round_idx": 0},  # no dur_s, no ts
        {"sender": 0, "kind": "span", "name": "round", "trace_id": "t1",
         "span_id": "r1", "ts": 100.0, "dur_s": "oops", "round_idx": 1},
        {"sender": 1, "kind": "span", "name": "train", "trace_id": "t1",
         "span_id": "c1", "parent_id": "r1", "ts": "not-a-clock",
         "dur_s": 0.5, "round_idx": 1},
    ]
    rows = report.round_rows(records)
    assert [r["round_idx"] for r in rows] == [0, 1]
    assert rows[0]["round_dur_s"] == 0.0 and rows[1]["round_dur_s"] == 0.0
    assert rows[0]["train"][0]["dur_s"] == 0.0
    assert rows[1]["train"][0]["dur_s"] == 0.5

    trees = report.build_span_trees(records)
    assert set(trees) == {"t0", "t1"}
    rendered = report.render_report(records)
    assert "== round timeline ==" in rendered

    # non-numeric round indexes fall back to ingest order instead of raising
    mixed = records + [
        {"sender": 0, "kind": "span", "name": "round", "trace_id": "t2",
         "span_id": "r2", "ts": 50.0, "dur_s": 1.0, "round_idx": "warmup"},
    ]
    rows = report.round_rows(mixed)
    assert [r["round_idx"] for r in rows] == [0, 1, "warmup"]


def test_report_renders_client_health_section():
    from fedml_tpu.obs import report

    records = [
        {"sender": 0, "kind": "metric", "metric": "client_health", "client": 1,
         "score": 1.0, "ewma_rtt_s": 0.2, "breaches": 0.0, "comm_failures": 0.0,
         "ts": 100.0},
        {"sender": 0, "kind": "metric", "metric": "client_health", "client": 2,
         "score": 0.8, "ewma_rtt_s": 0.3, "breaches": 1.0, "comm_failures": 0.0,
         "ts": 100.0},
        # a later record for client 2 supersedes the first
        {"sender": 0, "kind": "metric", "metric": "client_health", "client": 2,
         "score": 0.25, "ewma_rtt_s": 0.9, "breaches": 3.0, "comm_failures": 1.0,
         "ts": 101.0},
    ]
    rows = report.client_health_rows(records)
    assert [r["client"] for r in rows] == ["2", "1"]  # worst first
    assert rows[0]["score"] == 0.25 and rows[0]["breaches"] == 3.0
    rendered = report.render_report(records)
    assert "== client health ==" in rendered
