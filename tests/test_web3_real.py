"""web3_real.py Ledger adapters driven by scripted fakes (the pattern of
test_mqtt_real.py): every branch of the web3 contract adapter and the Theta
EdgeStore adapter runs hermetically, including an end-to-end FL message
exchange through BlockchainCommManager."""

import base64

import pytest


# ---------------------------------------------------------------------------
# fake web3 module (contract mailbox semantics in memory)
# ---------------------------------------------------------------------------

class _FakeFn:
    def __init__(self, chain, name, args):
        self.chain, self.name, self.args = chain, name, args

    def call(self):
        assert self.name == "getMessages"
        return self.chain.messages[self.args[0]:]

    def transact(self, tx):
        assert self.name == "sendMessage"
        self.chain.transactions.append(("unlocked", tx["from"]))
        self.chain.messages.append((self.chain.pending_sender, *self.args))
        return f"0xhash{len(self.chain.messages)}"

    def build_transaction(self, tx):
        return {"fn": self, "tx": tx}


class _FakeFunctions:
    def __init__(self, chain):
        self.chain = chain

    def sendMessage(self, recipient, data):
        return _FakeFn(self.chain, "sendMessage", (recipient, data))

    def getMessages(self, from_index):
        return _FakeFn(self.chain, "getMessages", (from_index,))


class _FakeChainState:
    def __init__(self):
        self.messages = []  # (sender, recipient, data)
        self.transactions = []
        self.pending_sender = 0
        self.nonces = {}


class _FakeEth:
    def __init__(self, chain):
        self.chain = chain
        self.account = self

    def contract(self, address, abi):
        class C:
            functions = _FakeFunctions(self.chain)
        return C()

    def get_transaction_count(self, account):
        return self.chain.nonces.get(account, 0)

    def sign_transaction(self, tx, key):
        class S:
            raw_transaction = ("signed", tx, key)
        return S()

    def send_raw_transaction(self, raw):
        _tag, built, _key = raw
        fn = built["fn"]
        self.chain.transactions.append(("signed", built["tx"]["from"]))
        self.chain.messages.append((self.chain.pending_sender, *fn.args))
        return f"0xhash{len(self.chain.messages)}"

    def wait_for_transaction_receipt(self, tx_hash):
        return {"status": 1, "hash": tx_hash}


class FakeWeb3Module:
    last = None

    class Web3:
        def __init__(self, provider):
            self.provider = provider
            self.chain = FakeWeb3Module.last = FakeWeb3Module.last or _FakeChainState()
            self.eth = _FakeEth(self.chain)

        @staticmethod
        def HTTPProvider(url):
            return ("http", url)


@pytest.fixture(autouse=True)
def _fresh_chain():
    FakeWeb3Module.last = None
    yield
    FakeWeb3Module.last = None


def test_web3_ledger_append_and_read_unlocked():
    from fedml_tpu.comm.web3_real import Web3ContractLedger

    led = Web3ContractLedger("http://node", "0xABC", account="0xme",
                             web3_module=FakeWeb3Module)
    h0 = led.append_tx(1, 2, "payloadA")
    h1 = led.append_tx(1, 3, "payloadB")
    assert (h0, h1) == (0, 1)
    rows = led.read_since(0)
    assert [(r["recipient"], r["data"]) for r in rows] == [(2, "payloadA"), (3, "payloadB")]
    assert led.read_since(1)[0]["data"] == "payloadB"
    # unlocked-account path used (no key given)
    assert FakeWeb3Module.last.transactions[0][0] == "unlocked"


def test_web3_ledger_signed_path():
    from fedml_tpu.comm.web3_real import Web3ContractLedger

    led = Web3ContractLedger("http://node", "0xABC", account="0xme",
                             private_key="0xkey", web3_module=FakeWeb3Module)
    led.append_tx(1, 2, "x")
    assert FakeWeb3Module.last.transactions[0][0] == "signed"


def test_web3_import_error_without_module(monkeypatch):
    import fedml_tpu.comm.web3_real as wr

    monkeypatch.setattr(wr, "_web3", None)
    with pytest.raises(ImportError):
        wr.Web3ContractLedger("http://node", "0xABC", account="0xme")


# ---------------------------------------------------------------------------
# Theta EdgeStore adapter
# ---------------------------------------------------------------------------

class FakeEdgeStore:
    def __init__(self):
        self.blobs = {}

    def put(self, key, data):
        self.blobs[key] = data
        return key

    def get(self, key):
        return self.blobs[key]


def test_theta_ledger_roundtrip():
    from fedml_tpu.comm.web3_real import ThetaEdgeStoreLedger

    store = FakeEdgeStore()
    led = ThetaEdgeStoreLedger("run7", http_client=store)
    assert led.append_tx(1, 2, "aaa") == 0
    assert led.append_tx(2, 1, "bbb") == 1
    rows = led.read_since(0)
    assert [(r["sender"], r["recipient"], r["data"]) for r in rows] == [
        (1, 2, "aaa"), (2, 1, "bbb"),
    ]
    assert led.read_since(1)[0]["data"] == "bbb"
    # payload blobs (unique keys) and the index live in the store
    assert sum("/tx-" in k for k in store.blobs) == 2


def test_web3_reverted_tx_raises():
    from fedml_tpu.comm.web3_real import Web3ContractLedger

    led = Web3ContractLedger("http://node", "0xABC", account="0xme",
                             web3_module=FakeWeb3Module)
    orig = _FakeEth.wait_for_transaction_receipt
    _FakeEth.wait_for_transaction_receipt = lambda self, h: {"status": 0, "hash": h}
    try:
        with pytest.raises(RuntimeError, match="reverted"):
            led.append_tx(1, 2, "x")
    finally:
        _FakeEth.wait_for_transaction_receipt = orig


def test_theta_append_retries_on_clobbered_index():
    """A racing writer overwrites the index between our write and re-read:
    the optimistic retry re-merges until our entry survives; unique blob
    keys mean no payload is ever clobbered."""
    from fedml_tpu.comm.web3_real import ThetaEdgeStoreLedger

    class RacyStore(FakeEdgeStore):
        def __init__(self):
            super().__init__()
            self.race_once = True

        def put(self, key, data):
            out = super().put(key, data)
            if key.endswith("ledger_index") and self.race_once:
                # simulate a concurrent writer clobbering our index write
                self.race_once = False
                import json as _json

                self.blobs[key] = _json.dumps([
                    {"height": 0, "sender": 9, "recipient": 9, "key": "other/tx"}
                ]).encode()
                self.blobs["other/tx"] = b"zzz"
            return out

    store = RacyStore()
    led = ThetaEdgeStoreLedger("runR", http_client=store)
    h = led.append_tx(1, 2, "mine")
    assert h == 1  # merged AFTER the racer's entry
    rows = led.read_since(0)
    assert [(r["sender"], r["data"]) for r in rows] == [(9, "zzz"), (1, "mine")]


def test_theta_requires_client():
    from fedml_tpu.comm.web3_real import ThetaEdgeStoreLedger

    with pytest.raises(ImportError):
        ThetaEdgeStoreLedger("run7")


# ---------------------------------------------------------------------------
# end-to-end: FL messages through BlockchainCommManager over a real adapter
# ---------------------------------------------------------------------------

def test_comm_manager_rides_theta_ledger():
    from fedml_tpu.comm.blockchain import BlockchainCommManager
    from fedml_tpu.comm.message import Message
    from fedml_tpu.comm.web3_real import ThetaEdgeStoreLedger

    store = FakeEdgeStore()
    led1 = ThetaEdgeStoreLedger("runE", http_client=store)
    led2 = ThetaEdgeStoreLedger("runE", http_client=store)
    m1 = BlockchainCommManager("runE", 1, ledger=led1, poll_interval_s=0.02)
    m2 = BlockchainCommManager("runE", 2, ledger=led2, poll_interval_s=0.02)
    try:
        out = Message(3, sender_id=1, receiver_id=2)
        out.add_params("k", 2.5)
        m1.send_message(out)
        data = m2._inbox.get(timeout=5)
        got = Message.decode(data)
        assert got.get_type() == 3 and float(got.get("k")) == 2.5
        # rank 1's own inbox stays empty (transaction addressed to 2)
        assert m1._inbox.empty()
    finally:
        m1.stop_receive_message()
        m2.stop_receive_message()
