"""Algorithm Flow DSL tests (reference core/distributed/flow/fedml_flow.py,
exercised like its test_fedml_flow.py demo: Client/Server executors composing
a two-round FedAvg-shaped protocol)."""

import numpy as np

from .conftest import tiny_config


class Client:
    pass  # defined via FedMLExecutor subclass below (names matter for routing)


def test_flow_two_round_fedavg_shape(eight_devices):
    import fedml_tpu
    from fedml_tpu.comm.inproc import InProcRouter
    from fedml_tpu.flow import FedMLAlgorithmFlow, FedMLExecutor, Params

    class ClientEx(FedMLExecutor):
        def __init__(self, id, neighbors):
            super().__init__(id, neighbors)
            self.local_value = float(id)
            self.trained = 0

        def local_training(self):
            p = self.get_params()
            if p is not None and "model" in p:
                self.local_value = float(np.asarray(p["model"])[0])
            self.trained += 1
            return Params(update=np.array([self.local_value + 1.0]), n=1)

    class ServerEx(FedMLExecutor):
        def __init__(self, id, neighbors):
            super().__init__(id, neighbors)
            self.aggregates = []

        def server_agg(self):
            p = self.get_params()
            ups = p["upstream_list"] if "upstream_list" in p else [p]
            vals = [float(np.asarray(u["update"])[0]) for u in ups]
            agg = float(np.mean(vals))
            self.aggregates.append(agg)
            return Params(model=np.array([agg]))

        def finalize(self):
            return None

    cfg = tiny_config(run_id="flow1", backend="INPROC")
    fedml_tpu.init(cfg)
    InProcRouter.reset("flow1")
    cast = {"ClientEx": [1, 2], "ServerEx": [0]}
    flows = []
    for node_id in (0, 1, 2):
        ex = (ServerEx(node_id, [1, 2]) if node_id == 0 else ClientEx(node_id, [0]))
        flow = FedMLAlgorithmFlow(cfg, ex, cast)
        flow.add_flow("local_training", ClientEx.local_training)
        flow.add_flow("server_agg", ServerEx.server_agg)
        flow.loop(times=2)
        flow.add_flow("finalize", ServerEx.finalize)
        flow.build()
        flows.append(flow)

    from fedml_tpu.flow.flow import run_flow_group

    results = run_flow_group(cfg, flows, timeout=60.0)

    # trace shape: clients executed local_training twice; server aggregated twice + finalized
    assert [n.split("#")[0] for n in results[1]] == ["local_training", "local_training"]
    assert [n.split("#")[0] for n in results[0]] == ["server_agg", "server_agg", "finalize"]

    server = flows[0].executor
    # round 1: clients (1, 2) send (2, 3) -> mean 2.5
    assert server.aggregates[0] == 2.5
    # round 2: both clients resume from 2.5 and send 3.5 -> mean 3.5
    assert server.aggregates[1] == 3.5
    # clients actually consumed the broadcast model
    assert flows[1].executor.local_value == 2.5
