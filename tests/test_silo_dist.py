"""Distributed silo (multi-process local SGD) — VERDICT round-2 item 5.

A silo spanning 2 processes (jax.distributed, 8-device global data mesh)
must produce numerics IDENTICAL to the same silo as 1 process: the jitted
local-SGD program is the same SPMD math, only partitioned.
"""

import json
import os
import socket
import subprocess
import sys

import numpy as np
import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _single_process_silo_reference():
    """The identical FL run with the silo as ONE process (plain trainer)."""
    import jax

    import fedml_tpu
    from fedml_tpu.comm.inproc import InProcRouter
    from fedml_tpu.cross_silo import build_client, build_server
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub

    from .conftest import tiny_config

    cfg = tiny_config(
        training_type="cross_silo", client_num_in_total=1, client_num_per_round=1,
        comm_round=2, batch_size=16, synthetic_train_size=256,
        synthetic_test_size=64, frequency_of_the_test=1, run_id="silo-ref",
    )
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)
    InProcRouter.reset("silo-ref")
    client = build_client(cfg, ds, model, rank=1, backend="INPROC")
    client.run_in_thread()
    server = build_server(cfg, ds, model, backend="INPROC")
    try:
        history = server.run_until_done(timeout=180.0)
    finally:
        client.finish()
    flat = np.concatenate([
        np.asarray(l, dtype=np.float64).ravel()
        for l in jax.tree_util.tree_leaves(jax.device_get(server.aggregator.global_vars))
    ])
    return float(flat.sum()), float(np.sqrt((flat ** 2).sum())), history[-1].get("test_acc")


@pytest.mark.skipif(
    (os.cpu_count() or 1) < 4,
    reason="spawns multiple jax processes whose collective programs starve "
           "the XLA:CPU rendezvous on hosts with too few cores (observed "
           "240s hangs then timeout failures on 1-core CI)",
)
def test_two_process_silo_equals_one_process_silo(eight_devices):
    port = _free_port()
    worker = os.path.join(_REPO, "tests", "_silo_dist_worker.py")
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS", "JAX_PLATFORM_NAME")}
    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(pid), "2", str(port)],
            env=env, cwd=_REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        )
        for pid in (0, 1)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=300)
        outs.append(out)
        assert p.returncode == 0, out[-3000:]
    results = {}
    for out in outs:
        for line in out.splitlines():
            if line.startswith("MULTIHOST_RESULT "):
                r = json.loads(line[len("MULTIHOST_RESULT "):])
                results[r["pid"]] = r
    assert set(results) == {0, 1}, outs[0][-2000:]
    # the follower trained every round in lockstep with the master
    assert results[1]["rounds"] == 2, results

    ref_sum, ref_l2, ref_acc = _single_process_silo_reference()
    assert results[0]["checksum"] == pytest.approx(ref_sum, rel=1e-5, abs=1e-5)
    assert results[0]["l2"] == pytest.approx(ref_l2, rel=1e-5, abs=1e-5)
    assert results[0]["test_acc"] == pytest.approx(ref_acc, abs=1e-6)
