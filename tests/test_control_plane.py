"""Agent control-plane tests (VERDICT row 40: the MQTT start/stop/status/OTA
verbs of the reference slave agent, over the hermetic comm fabric)."""

import io
import json
import time
import zipfile

from .conftest import tiny_config


def _job_package(run_id: str, command: str) -> bytes:
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w") as z:
        z.writestr("__fedml_job__.json", json.dumps({"run_id": run_id, "job": command}))
    return buf.getvalue()


def test_control_plane_start_status_stop_ota(tmp_path, eight_devices):
    import fedml_tpu
    from fedml_tpu.comm.inproc import InProcRouter
    from fedml_tpu.sched.agent import FedMLAgent
    from fedml_tpu.sched.control_plane import AgentControlPlane, AgentController

    cfg = tiny_config(run_id="cp1", backend="INPROC")
    fedml_tpu.init(cfg)
    InProcRouter.reset("cp1")

    agent = FedMLAgent(str(tmp_path / "spool"))
    plane = AgentControlPlane(cfg, agent, rank=7, backend="INPROC")
    plane.run_in_thread()
    controller = AgentController(cfg, backend="INPROC")
    controller.run_in_thread()
    try:
        # START_RUN -> package lands in the queue -> agent sweep claims it
        controller.start_run(7, "job-1", _job_package("job-1", "echo control-plane-ok"))
        deadline = time.time() + 30
        while not list(agent.queue.glob("*.zip")) and time.time() < deadline:
            time.sleep(0.05)
        assert list(agent.queue.glob("*.zip")), "package never spooled"
        agent.sweep_once()
        deadline = time.time() + 60
        while agent._procs and time.time() < deadline:
            agent.sweep_once()
            time.sleep(0.1)
        row = agent.db.get("job-1")
        assert row["status"] == "FINISHED", row

        # STATUS round trip
        controller.request_status(7)
        jobs = controller.wait_status(7, timeout=30)
        assert jobs is not None and any(j["run_id"] == "job-1" for j in jobs)

        # STOP_RUN on a long-running job
        controller.start_run(7, "job-2", _job_package("job-2", "sleep 60"))
        deadline = time.time() + 30
        while not list(agent.queue.glob("*.zip")) and time.time() < deadline:
            time.sleep(0.05)
        agent.sweep_once()
        assert "job-2" in agent._procs
        controller.stop_run(7, "job-2")
        # wait on the DB row, not the process table: the handler pops the
        # proc BEFORE it writes KILLED, so polling _procs races the upsert
        deadline = time.time() + 45
        while agent.db.get("job-2")["status"] != "KILLED" and time.time() < deadline:
            time.sleep(0.1)
        assert agent.db.get("job-2")["status"] == "KILLED"
        assert agent._procs.get("job-2") is None

        # OTA stages the package + restart marker
        controller.push_ota(7, "0.2.0", b"new-agent-code")
        deadline = time.time() + 30
        marker = tmp_path / "spool" / "ota" / "RESTART_REQUIRED"
        while not marker.exists() and time.time() < deadline:
            time.sleep(0.05)
        assert marker.exists()
        meta = json.loads(marker.read_text())
        assert meta["version"] == "0.2.0"
        assert (tmp_path / "spool" / "ota" / "agent-0.2.0.zip").read_bytes() == b"new-agent-code"
    finally:
        plane.finish()
        controller.finish()


def test_control_plane_rejects_traversal_and_stop_races(tmp_path, eight_devices):
    import time

    import fedml_tpu
    from fedml_tpu.comm.inproc import InProcRouter
    from fedml_tpu.sched.agent import FedMLAgent
    from fedml_tpu.sched.control_plane import AgentControlPlane, AgentController

    cfg = tiny_config(run_id="cp2", backend="INPROC")
    fedml_tpu.init(cfg)
    InProcRouter.reset("cp2")
    agent = FedMLAgent(str(tmp_path / "spool"))
    plane = AgentControlPlane(cfg, agent, rank=3, backend="INPROC")
    plane.run_in_thread()
    controller = AgentController(cfg, backend="INPROC")
    try:
        # traversal run_id must never land outside the queue
        controller.start_run(3, "../../evil", _job_package("x", "echo hi"))
        time.sleep(0.5)
        assert not (tmp_path / "evil.zip").exists()
        assert not list(agent.queue.glob("*.zip"))

        # stop-before-start: queued package must be removed, job never runs
        controller.start_run(3, "job-r", _job_package("job-r", "echo nope"))
        deadline = time.time() + 30
        while not list(agent.queue.glob("*.zip")) and time.time() < deadline:
            time.sleep(0.05)
        controller.stop_run(3, "job-r")
        deadline = time.time() + 30
        while list(agent.queue.glob("*.zip")) and time.time() < deadline:
            time.sleep(0.05)
        assert not list(agent.queue.glob("*.zip"))
        agent.sweep_once()
        assert agent.db.get("job-r")["status"] == "KILLED"
        assert "job-r" not in agent._procs
    finally:
        plane.finish()
        controller.finish()


def test_control_plane_package_auth(tmp_path, eight_devices):
    """START_RUN/OTA are code execution on the agent: with a configured
    shared secret a bad/absent HMAC must be rejected, a good one accepted;
    without a secret, routable backends must refuse package verbs outright."""
    import fedml_tpu
    from fedml_tpu.comm.inproc import InProcRouter
    from fedml_tpu.comm.message import Message
    from fedml_tpu.sched.agent import FedMLAgent
    from fedml_tpu.sched.control_plane import (
        KEY_PACKAGE, KEY_RUN_ID, KEY_SIGNATURE, KEY_TIMESTAMP,
        MSG_TYPE_START_RUN, MSG_TYPE_STOP_RUN,
        AgentControlPlane, AgentController, _verb_signature,
    )

    cfg = tiny_config(run_id="cp3", backend="INPROC")
    cfg.control_plane_secret = "sesame"
    fedml_tpu.init(cfg)
    InProcRouter.reset("cp3")
    agent = FedMLAgent(str(tmp_path / "spool"))
    plane = AgentControlPlane(cfg, agent, rank=5, backend="INPROC")
    plane.run_in_thread()
    controller = AgentController(cfg, backend="INPROC")
    try:
        import numpy as np

        pkg = _job_package("job-a", "echo authed")

        # forged signature (fresh timestamp): package must never hit the spool
        msg = Message(MSG_TYPE_START_RUN, 0, 5)
        msg.add_params(KEY_PACKAGE, np.frombuffer(pkg, dtype=np.uint8).copy())
        msg.add_params(KEY_RUN_ID, "job-a")
        msg.add_params(KEY_TIMESTAMP, repr(time.time()))
        msg.add_params(KEY_SIGNATURE, "0" * 64)
        controller.send_message(msg)
        time.sleep(0.5)
        assert not list(agent.queue.glob("*.zip")), "forged package spooled"

        # stale-but-correctly-signed (replay): rejected by the freshness window
        old_ts = repr(time.time() - 3600)
        replay = Message(MSG_TYPE_START_RUN, 0, 5)
        replay.add_params(KEY_PACKAGE, np.frombuffer(pkg, dtype=np.uint8).copy())
        replay.add_params(KEY_RUN_ID, "job-a")
        replay.add_params(KEY_TIMESTAMP, old_ts)
        replay.add_params(
            KEY_SIGNATURE, _verb_signature("sesame", MSG_TYPE_START_RUN, 5, "job-a", old_ts, pkg)
        )
        controller.send_message(replay)
        time.sleep(0.5)
        assert not list(agent.queue.glob("*.zip")), "replayed package spooled"

        # unsigned STOP_RUN must not kill jobs when a secret is configured
        agent.db.upsert("job-x", status="RUNNING")
        bare_stop = Message(MSG_TYPE_STOP_RUN, 0, 5)
        bare_stop.add_params(KEY_RUN_ID, "job-x")
        controller.send_message(bare_stop)
        time.sleep(0.5)
        assert agent.db.get("job-x")["status"] == "RUNNING"

        # correctly signed (controller signs automatically with the secret)
        controller.start_run(5, "job-a", pkg)
        deadline = time.time() + 30
        while not list(agent.queue.glob("*.zip")) and time.time() < deadline:
            time.sleep(0.05)
        assert list(agent.queue.glob("*.zip")), "signed package rejected"

        # signed STOP_RUN works
        controller.stop_run(5, "job-x")
        deadline = time.time() + 30
        while agent.db.get("job-x")["status"] != "KILLED" and time.time() < deadline:
            time.sleep(0.05)
        assert agent.db.get("job-x")["status"] == "KILLED"

        # signature is verb/name-bound
        s1 = _verb_signature("sesame", MSG_TYPE_START_RUN, 5, "job-a", "1.0", pkg)
        s2 = _verb_signature("sesame", MSG_TYPE_START_RUN, 5, "job-b", "1.0", pkg)
        assert s1 != s2

        # unauthenticated plane on a routable backend refuses packages
        # (backend attribute faked to avoid binding a real TCP socket)
        plane_open = AgentControlPlane(
            tiny_config(run_id="cp3b", backend="INPROC"), agent, rank=6, backend="INPROC"
        )
        plane_open.secret = None
        plane_open.backend = "TCP"
        import pytest

        with pytest.raises(ValueError, match="unauthenticated"):
            plane_open._verify(msg, MSG_TYPE_START_RUN, "job-a", pkg)
        plane_open.finish()
    finally:
        plane.finish()
        controller.finish()
