"""Agent control-plane tests (VERDICT row 40: the MQTT start/stop/status/OTA
verbs of the reference slave agent, over the hermetic comm fabric)."""

import io
import json
import time
import zipfile

from .conftest import tiny_config


def _job_package(run_id: str, command: str) -> bytes:
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, "w") as z:
        z.writestr("__fedml_job__.json", json.dumps({"run_id": run_id, "job": command}))
    return buf.getvalue()


def test_control_plane_start_status_stop_ota(tmp_path, eight_devices):
    import fedml_tpu
    from fedml_tpu.comm.inproc import InProcRouter
    from fedml_tpu.sched.agent import FedMLAgent
    from fedml_tpu.sched.control_plane import AgentControlPlane, AgentController

    cfg = tiny_config(run_id="cp1", backend="INPROC")
    fedml_tpu.init(cfg)
    InProcRouter.reset("cp1")

    agent = FedMLAgent(str(tmp_path / "spool"))
    plane = AgentControlPlane(cfg, agent, rank=7, backend="INPROC")
    plane.run_in_thread()
    controller = AgentController(cfg, backend="INPROC")
    controller.run_in_thread()
    try:
        # START_RUN -> package lands in the queue -> agent sweep claims it
        controller.start_run(7, "job-1", _job_package("job-1", "echo control-plane-ok"))
        deadline = time.time() + 10
        while not list(agent.queue.glob("*.zip")) and time.time() < deadline:
            time.sleep(0.05)
        assert list(agent.queue.glob("*.zip")), "package never spooled"
        agent.sweep_once()
        deadline = time.time() + 20
        while agent._procs and time.time() < deadline:
            agent.sweep_once()
            time.sleep(0.1)
        row = agent.db.get("job-1")
        assert row["status"] == "FINISHED", row

        # STATUS round trip
        controller.request_status(7)
        jobs = controller.wait_status(7, timeout=10)
        assert jobs is not None and any(j["run_id"] == "job-1" for j in jobs)

        # STOP_RUN on a long-running job
        controller.start_run(7, "job-2", _job_package("job-2", "sleep 60"))
        deadline = time.time() + 10
        while not list(agent.queue.glob("*.zip")) and time.time() < deadline:
            time.sleep(0.05)
        agent.sweep_once()
        assert "job-2" in agent._procs
        controller.stop_run(7, "job-2")
        deadline = time.time() + 10
        while agent._procs.get("job-2") is not None \
                and agent._procs["job-2"].poll() is None and time.time() < deadline:
            time.sleep(0.1)
        assert agent.db.get("job-2")["status"] == "KILLED"

        # OTA stages the package + restart marker
        controller.push_ota(7, "0.2.0", b"new-agent-code")
        deadline = time.time() + 10
        marker = tmp_path / "spool" / "ota" / "RESTART_REQUIRED"
        while not marker.exists() and time.time() < deadline:
            time.sleep(0.05)
        assert marker.exists()
        meta = json.loads(marker.read_text())
        assert meta["version"] == "0.2.0"
        assert (tmp_path / "spool" / "ota" / "agent-0.2.0.zip").read_bytes() == b"new-agent-code"
    finally:
        plane.finish()
        controller.finish()


def test_control_plane_rejects_traversal_and_stop_races(tmp_path, eight_devices):
    import time

    import fedml_tpu
    from fedml_tpu.comm.inproc import InProcRouter
    from fedml_tpu.sched.agent import FedMLAgent
    from fedml_tpu.sched.control_plane import AgentControlPlane, AgentController

    cfg = tiny_config(run_id="cp2", backend="INPROC")
    fedml_tpu.init(cfg)
    InProcRouter.reset("cp2")
    agent = FedMLAgent(str(tmp_path / "spool"))
    plane = AgentControlPlane(cfg, agent, rank=3, backend="INPROC")
    plane.run_in_thread()
    controller = AgentController(cfg, backend="INPROC")
    try:
        # traversal run_id must never land outside the queue
        controller.start_run(3, "../../evil", _job_package("x", "echo hi"))
        time.sleep(0.5)
        assert not (tmp_path / "evil.zip").exists()
        assert not list(agent.queue.glob("*.zip"))

        # stop-before-start: queued package must be removed, job never runs
        controller.start_run(3, "job-r", _job_package("job-r", "echo nope"))
        deadline = time.time() + 10
        while not list(agent.queue.glob("*.zip")) and time.time() < deadline:
            time.sleep(0.05)
        controller.stop_run(3, "job-r")
        deadline = time.time() + 10
        while list(agent.queue.glob("*.zip")) and time.time() < deadline:
            time.sleep(0.05)
        assert not list(agent.queue.glob("*.zip"))
        agent.sweep_once()
        assert agent.db.get("job-r")["status"] == "KILLED"
        assert "job-r" not in agent._procs
    finally:
        plane.finish()
        controller.finish()
