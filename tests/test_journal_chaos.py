"""Crash-safe federated rounds (ISSUE 10): the durable server recovery
journal, the session-epoch fence (never double-folded), the deterministic
chaos harness at the comm boundary, and the satellite hardening — exp-backoff
decode retries, the configurable chunk-stream sweep, and the checkpoint
corrupt-step fallback."""

import dataclasses
import os
import queue
import threading
import time

import numpy as np
import pytest

from .conftest import tiny_config


def _load(cfg):
    import fedml_tpu
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub

    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)
    return ds, model


# ---------------------------------------------------------------------------
# ServerJournal: atomic snapshots, corrupt-step fallback
# ---------------------------------------------------------------------------

def test_journal_snapshot_restore_roundtrip(tmp_path):
    from fedml_tpu.cross_silo.journal import ServerJournal

    j = ServerJournal(str(tmp_path / "j"), keep=3)
    model_state = {"global_vars": {"w": np.arange(6, dtype=np.float32)},
                   "server_state": {}}
    j.snapshot(1, {"session_epoch": 0, "server_version": 1,
                   "outstanding": {"3": 0}},
               arrays={"stream_sum_0": np.ones(4, np.float32)},
               model_state=model_state)
    snap = j.restore(model_template=model_state)
    assert snap["step"] == 1
    assert snap["protocol"]["server_version"] == 1
    assert snap["protocol"]["outstanding"] == {"3": 0}
    np.testing.assert_array_equal(snap["arrays"]["stream_sum_0"],
                                  np.ones(4, np.float32))
    np.testing.assert_array_equal(
        np.asarray(snap["model"]["global_vars"]["w"]),
        np.arange(6, dtype=np.float32))


def test_journal_corrupt_latest_step_falls_back(tmp_path):
    """A truncated latest sidecar (hard kill mid-write would be prevented by
    atomic replace, but disk corruption is not) is discarded; restore serves
    the previous intact step — the AOT store's corrupt-entry semantics."""
    from fedml_tpu.cross_silo.journal import ServerJournal

    j = ServerJournal(str(tmp_path / "j"), keep=5)
    for step in (1, 2, 3):
        j.snapshot(step, {"server_version": step}, arrays={})
    # truncate step 3's sidecar mid-payload
    p3 = j._step_path(3)
    blob = open(p3, "rb").read()
    with open(p3, "wb") as f:
        f.write(blob[: len(blob) // 2])
    snap = j.restore()
    assert snap["step"] == 2
    assert snap["protocol"]["server_version"] == 2
    # the corrupt step is gone from disk (discarded, not retried forever)
    assert 3 not in j.steps()


def test_journal_garbage_and_empty(tmp_path):
    from fedml_tpu.cross_silo.journal import ServerJournal

    j = ServerJournal(str(tmp_path / "j"))
    assert j.restore() is None  # empty journal: fresh start
    with open(j._step_path(7), "wb") as f:
        f.write(b"not a journal at all")
    assert j.restore() is None  # pure garbage: discarded, still fresh start


def test_journal_prunes_to_keep(tmp_path):
    from fedml_tpu.cross_silo.journal import ServerJournal

    j = ServerJournal(str(tmp_path / "j"), keep=2)
    for step in range(1, 6):
        j.snapshot(step, {"server_version": step}, arrays={})
    assert j.steps() == [4, 5]


def test_journal_from_config_gate(tmp_path):
    from fedml_tpu.cross_silo.journal import journal_from_config

    assert journal_from_config(tiny_config()) is None
    assert journal_from_config(None) is None
    j = journal_from_config(tiny_config(
        extra={"server_journal_dir": str(tmp_path / "j")}))
    assert j is not None and j.keep == 3


# ---------------------------------------------------------------------------
# RoundCheckpointer: corrupt/partial step falls back (satellite)
# ---------------------------------------------------------------------------

def test_checkpointer_truncated_latest_step_discarded(tmp_path):
    """A truncated latest orbax step must be discarded and latest_round()
    fall back to the previous intact step (mirrors the AOT store's
    corrupt-entry rebuild semantics)."""
    from fedml_tpu.core.checkpoint import RoundCheckpointer

    ck = RoundCheckpointer(str(tmp_path / "ck"), keep=5)
    state = {"w": np.arange(8, dtype=np.float32)}
    ck.save(0, state)
    ck.save(1, {"w": np.arange(8, dtype=np.float32) + 1})
    assert ck.latest_round() == 1
    # corrupt step 1: truncate every regular file in its directory
    step_dir = tmp_path / "ck" / "1"
    corrupted = 0
    for root, _dirs, files in os.walk(step_dir):
        for name in files:
            p = os.path.join(root, name)
            data = open(p, "rb").read()
            with open(p, "wb") as f:
                f.write(data[: max(1, len(data) // 3)])
            corrupted += 1
    assert corrupted > 0
    assert ck.latest_round() == 0  # fell back past the corrupt step
    restored = ck.restore(template=state)
    np.testing.assert_array_equal(np.asarray(restored["w"]),
                                  np.arange(8, dtype=np.float32))


# ---------------------------------------------------------------------------
# chaos: determinism, gate, fault classes
# ---------------------------------------------------------------------------

class _FakeComm:
    """Minimal inner backend recording every delivery."""

    def __init__(self, fail=False):
        self.sent = []
        self.raw = []
        self.fail = fail

    def send_message(self, msg):
        if self.fail:
            raise ConnectionResetError("inner down")
        self.sent.append(msg)

    def send_raw(self, rid, payload):
        self.raw.append((rid, bytes(payload)))

    def add_observer(self, obs):
        pass

    def remove_observer(self, obs):
        pass

    def handle_receive_message(self):
        pass

    def stop_receive_message(self):
        self.stopped = True


def _mk_msg(rid=1, nonce=0):
    from fedml_tpu.comm.message import Message

    m = Message(3, 0, rid)
    m.add_params("round_idx", nonce)
    m.add_params("model_params", np.arange(64, dtype=np.float32))
    return m


def _chaos_mgr(inner, **kw):
    from fedml_tpu.comm.chaos import ChaosCommManager, ChaosConfig

    return ChaosCommManager(inner, ChaosConfig(**kw), rank=0)


def test_chaos_gate_returns_inner_untouched():
    """All chaos flags unset -> wrap_with_chaos returns the INNER OBJECT
    (no wrapper, no per-send rng — the default path is bit-identical)."""
    from fedml_tpu.comm.chaos import chaos_from_config, wrap_with_chaos

    inner = _FakeComm()
    cfg = tiny_config()
    assert chaos_from_config(cfg) is None
    assert wrap_with_chaos(inner, cfg, rank=0) is inner
    on = tiny_config(extra={"chaos_drop_prob": 0.5})
    assert wrap_with_chaos(inner, on, rank=0) is not inner


def test_chaos_same_seed_reproduces_schedule():
    """The acceptance property: same seed + same message sequence -> the
    IDENTICAL fault schedule; a different seed -> a different one."""
    def run(seed):
        inner = _FakeComm()
        mgr = _chaos_mgr(inner, seed=seed, drop=0.2, duplicate=0.1,
                         reorder=0.1, corrupt=0.1, delay=0.0)
        for i in range(200):
            mgr.send_message(_mk_msg(rid=1 + (i % 3), nonce=i))
        return list(mgr.schedule)

    a, b, c = run(7), run(7), run(8)
    assert a == b
    assert a != c
    assert len(a) > 0


def test_chaos_drop_duplicate_and_counters():
    inner = _FakeComm()
    mgr = _chaos_mgr(inner, seed=1, drop=0.3, duplicate=0.3)
    n = 300
    for i in range(n):
        mgr.send_message(_mk_msg(rid=1, nonce=i))
    drops = mgr.injected.get("drop", 0)
    dups = mgr.injected.get("duplicate", 0)
    assert drops > 0 and dups > 0
    # delivered = sends - drops + duplicates (each duplicate delivers twice)
    assert len(inner.sent) == n - drops + dups
    assert mgr.silent_losses() == drops


def test_chaos_reset_raises_and_partition_window():
    inner = _FakeComm()
    mgr = _chaos_mgr(inner, seed=0, reset=1.0)
    with pytest.raises(ConnectionResetError):
        mgr.send_message(_mk_msg())
    # partition: a window starting immediately fails every send
    inner2 = _FakeComm()
    mgr2 = _chaos_mgr(inner2, seed=0, partition=(0.0, 60.0))
    with pytest.raises(ConnectionResetError):
        mgr2.send_message(_mk_msg())
    assert mgr2.injected.get("partition") == 1
    # a window that has not opened yet delivers normally
    inner3 = _FakeComm()
    mgr3 = _chaos_mgr(inner3, seed=0, partition=(60.0, 60.0))
    mgr3.send_message(_mk_msg())
    assert len(inner3.sent) == 1


def test_chaos_reorder_holds_frame_until_next_send():
    inner = _FakeComm()
    mgr = _chaos_mgr(inner, seed=3, reorder=1.0)
    first, second = _mk_msg(rid=1, nonce=0), _mk_msg(rid=1, nonce=1)
    mgr.send_message(first)
    assert inner.sent == []  # held back
    mgr.send_message(second)
    # second went out first... both present, order flipped; second is itself
    # reorder-rolled (prob 1.0) but its hold slot was freed by the flush
    assert first in inner.sent
    # stop flushes any residue so a clean shutdown strands nothing
    mgr.stop_receive_message()
    assert second in inner.sent


def test_chaos_corrupt_frame_dies_in_receive_loop_drop_path():
    """A corrupt-frame injection must be dropped by the receive loop's
    undecodable path (metered), never dispatched to a handler."""
    from fedml_tpu.comm.base import MSG_DROPPED
    from fedml_tpu.comm.inproc import InProcCommManager, InProcRouter

    run_id = "chaos_corrupt_test"
    InProcRouter.reset(run_id)
    rx = InProcCommManager(run_id, rank=1)
    tx = InProcCommManager(run_id, rank=0)
    mgr = _chaos_mgr(tx, seed=0, corrupt=1.0)

    got = []

    class Obs:
        def receive_message(self, t, m):
            got.append(m)

    rx.add_observer(Obs())
    t = threading.Thread(target=rx.handle_receive_message, daemon=True)
    t.start()
    base = MSG_DROPPED.value(reason="undecodable")
    mgr.send_message(_mk_msg(rid=1))
    deadline = time.monotonic() + 5.0
    while MSG_DROPPED.value(reason="undecodable") == base:
        assert time.monotonic() < deadline, "corrupt frame never dropped"
        time.sleep(0.01)
    rx.stop_receive_message()
    t.join(timeout=5.0)
    assert got == []  # nothing reached a handler
    assert mgr.injected.get("corrupt") == 1
    InProcRouter.reset(run_id)


# ---------------------------------------------------------------------------
# session-epoch fence: folded-once-or-rejected, never double-folded
# ---------------------------------------------------------------------------

def _async_server(tmp_path, **extra):
    from fedml_tpu.cross_silo import build_server
    from fedml_tpu.comm.inproc import InProcRouter

    cfg = tiny_config(
        training_type="cross_silo", comm_round=50, run_id="epoch_fence",
        frequency_of_the_test=0,
        extra={"async_aggregation": True, "async_buffer_k": 100,
               "async_redispatch_timeout_s": 0.0,
               "server_journal_dir": str(tmp_path / "j"), **extra})
    ds, model = _load(cfg)
    InProcRouter.reset("epoch_fence")
    return build_server(cfg, ds, model, backend="INPROC"), ds, model


def _epoch_upload(rank, params, version, epoch):
    from fedml_tpu.comm.message import Message
    from fedml_tpu.cross_silo import message_define as md

    msg = Message(md.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, rank, 0)
    msg.add_params(md.MSG_ARG_KEY_MODEL_PARAMS, params)
    msg.add_params(md.MSG_ARG_KEY_NUM_SAMPLES, 16.0)
    msg.add_params(md.MSG_ARG_KEY_ROUND_INDEX, int(version))
    msg.add_params(md.MSG_ARG_KEY_SESSION_EPOCH, int(epoch))
    return Message.decode(msg.encode())


def test_epoch_fence_folds_inflight_once_rejects_rest(tmp_path, eight_devices):
    """The never-double-folded policy, unit-level: an old-epoch upload folds
    exactly once iff its (client, version) survives in the recovered
    in-flight table; a redelivery and an unknown sender are both rejected
    deterministically."""
    import jax

    server, ds, model = _async_server(tmp_path)
    base = jax.device_get(server.aggregator.global_vars)
    # simulate a recovered server: epoch bumped, clients 1+2 were in flight
    # at versions 0 and 1 when the old process died
    server.session_epoch = 1
    server._prev_epoch_inflight = {1: 0, 2: 1}
    server.server_version = 2

    # client 1 echoes its pre-crash dispatch (epoch 0, version 0): FOLDED
    server.handle_message_receive_model(_epoch_upload(1, base, 0, 0))
    assert server.total_arrivals == 1
    assert server.rejected_stale == 0
    assert 1 not in server._prev_epoch_inflight

    # the SAME upload redelivered (at-least-once transport): REJECTED
    server.handle_message_receive_model(_epoch_upload(1, base, 0, 0))
    assert server.total_arrivals == 1
    assert server.rejected_stale == 1

    # client 2 echoes a version that does NOT match its journaled dispatch:
    # REJECTED (and its slot stays armed for the real reply)
    server.handle_message_receive_model(_epoch_upload(2, base, 0, 0))
    assert server.total_arrivals == 1
    assert server.rejected_stale == 2
    assert server._prev_epoch_inflight == {2: 1}

    # client 3 was never in flight pre-crash: REJECTED
    server.handle_message_receive_model(_epoch_upload(3, base, 1, 0))
    assert server.rejected_stale == 3

    # current-epoch uploads are untouched by the fence
    server.handle_message_receive_model(_epoch_upload(4, base, 2, 1))
    assert server.total_arrivals == 2
    server.finish()


# ---------------------------------------------------------------------------
# sync server: journal resume reproduces the uninterrupted run
# ---------------------------------------------------------------------------

def _run_sync_group(cfg, ds, model, timeout=180.0):
    from fedml_tpu.comm.inproc import InProcRouter
    from fedml_tpu.cross_silo import build_client, build_server

    InProcRouter.reset(str(cfg.run_id))
    clients = [build_client(cfg, ds, model, rank=r, backend="INPROC")
               for r in range(1, cfg.client_num_in_total + 1)]
    for c in clients:
        c.run_in_thread()
    server = build_server(cfg, ds, model, backend="INPROC")
    try:
        history = server.run_until_done(timeout=timeout)
        for c in clients:
            c.done.wait(5.0)
    finally:
        for c in clients:
            c.finish()
    return server, history


def test_sync_journal_resume_matches_uninterrupted(tmp_path, eight_devices):
    """Run 2/4 rounds with the journal, 'crash', restart with the same
    journal: the resumed server re-enters at round 2 under epoch 1 and the
    final model matches the uninterrupted 4-round run."""
    import jax

    jd = str(tmp_path / "journal")
    base = dict(training_type="cross_silo", client_num_in_total=2,
                client_num_per_round=2, synthetic_train_size=64,
                frequency_of_the_test=0)

    # uninterrupted 4-round reference
    cfg_ref = tiny_config(comm_round=4, run_id="jres_ref", **base)
    ds, model = _load(cfg_ref)
    srv_ref, _ = _run_sync_group(cfg_ref, ds, model)

    # first life: 2 rounds, journaled
    cfg_a = tiny_config(comm_round=2, run_id="jres_a", **base,
                        extra={"server_journal_dir": jd})
    srv_a, hist_a = _run_sync_group(cfg_a, ds, model)
    assert [h["round"] for h in hist_a] == [0, 1]
    assert srv_a.journal.steps()[-1] == 2

    # second life: same journal, 4 total rounds -> resumes at round 2
    cfg_b = tiny_config(comm_round=4, run_id="jres_b", **base,
                        extra={"server_journal_dir": jd})
    srv_b, hist_b = _run_sync_group(cfg_b, ds, model)
    assert srv_b.recovered_step == 2
    assert srv_b.session_epoch == 1
    assert [h["round"] for h in hist_b] == [2, 3]

    ref_leaves = jax.tree_util.tree_leaves(
        jax.device_get(srv_ref.aggregator.global_vars))
    res_leaves = jax.tree_util.tree_leaves(
        jax.device_get(srv_b.aggregator.global_vars))
    for x, y in zip(ref_leaves, res_leaves):
        np.testing.assert_allclose(np.asarray(x), np.asarray(y),
                                   rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# async kill-and-recover soak (the acceptance run, small)
# ---------------------------------------------------------------------------

def test_kill_recover_soak_invariants(eight_devices):
    from fedml_tpu.cross_silo.async_soak import run_kill_recover_soak

    res = run_kill_recover_soak(
        n_clients=64, concurrency=16, buffer_k=8, versions=6,
        drop_prob=0.05, latency_mean_s=0.002, redispatch_timeout_s=1.0,
        seed=0, timeout_s=180.0)
    assert res["versions"] == 6
    assert res["monotone"], res
    assert 0 < res["recovered_version"] <= res["versions_at_kill"], res
    assert res["session_epoch"] == 1, res
    assert res["unaccounted"] == 0, res
    assert res["peak_buffered_updates"] <= 2, res
    # chaos was live on the dispatch leg
    assert res["chaos_silent_losses"] + res["fleet_drops_injected"] > 0, res


# ---------------------------------------------------------------------------
# default-path regression: journal off + chaos off -> byte-identical wire
# ---------------------------------------------------------------------------

def test_default_path_wire_and_manager_identical(eight_devices):
    """Flags unset: no chaos wrapper, no journal object (server OR client),
    and NOT ONE frame carries the session-epoch or upload-key headers — the
    control JSON is byte-identical to the pre-ISSUE-10/13 protocol (same
    discipline as comm_compression / async_aggregation)."""
    import json as _json

    from fedml_tpu.comm.inproc import InProcCommManager, InProcRouter
    from fedml_tpu.cross_silo import build_client, build_server
    from fedml_tpu.cross_silo import message_define as md

    cfg = tiny_config(training_type="cross_silo", client_num_in_total=2,
                      client_num_per_round=2, comm_round=1,
                      synthetic_train_size=64, frequency_of_the_test=0,
                      run_id="default_wire")
    ds, model = _load(cfg)
    InProcRouter.reset("default_wire")
    captured = []
    router = InProcRouter.get("default_wire")
    orig_route = router.route

    def tap(msg):
        if msg.get_type() in (md.MSG_TYPE_S2C_INIT_CONFIG,
                              md.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER):
            captured.append(msg.encode())
        orig_route(msg)

    router.route = tap
    clients = [build_client(cfg, ds, model, rank=r, backend="INPROC")
               for r in (1, 2)]
    for c in clients:
        c.run_in_thread()
    server = build_server(cfg, ds, model, backend="INPROC")
    assert server.journal is None
    assert all(c.client_journal is None for c in clients)
    assert type(server.com_manager) is InProcCommManager  # no chaos wrapper
    try:
        server.run_until_done(timeout=120.0)
    finally:
        for c in clients:
            c.finish()
    assert captured
    for data in captured:
        clen = int.from_bytes(data[:4], "little")
        control = _json.loads(bytes(data[4:4 + clen]).decode())
        assert md.MSG_ARG_KEY_SESSION_EPOCH not in control
        assert md.MSG_ARG_KEY_UPLOAD_KEY not in control


# ---------------------------------------------------------------------------
# satellites: exp-backoff retry schedule + chunk-sweep flag
# ---------------------------------------------------------------------------

def test_backoff_delay_schedule():
    """Capped exponential with deterministic jitter: monotone envelope,
    hard cap, jitter in [0.5, 1.0) of the raw value, reproducible per seed,
    de-synchronized across seeds."""
    from fedml_tpu.comm.base import backoff_delay

    base, cap = 0.2, 2.0
    raws = [min(cap, base * 2 ** a) for a in range(8)]
    delays = [backoff_delay(a, base=base, cap=cap, seed=0) for a in range(8)]
    for d, raw in zip(delays, raws):
        assert 0.5 * raw <= d < raw
    # deterministic: same (seed, attempt) -> same delay
    assert delays == [backoff_delay(a, base=base, cap=cap, seed=0)
                      for a in range(8)]
    # seeds de-synchronize
    other = [backoff_delay(a, base=base, cap=cap, seed=1) for a in range(8)]
    assert delays != other
    # capped: late attempts never exceed the ceiling
    assert backoff_delay(50, base=base, cap=cap, seed=0) < cap
    # grows past the old linear schedule's early waits
    assert max(delays) > base * 3


def test_chunk_sweep_flag_threads_through_and_evicts(eight_devices):
    """``comm_chunk_idle_sweep_s`` reaches the receive loop's assembler, and
    an abandoned chunk stream is swept and metered WITH sender attribution
    after that timeout."""
    from fedml_tpu.comm import base as comm_base, wire
    from fedml_tpu.comm.base import MSG_DROPPED
    from fedml_tpu.comm.comm_manager import FedMLCommManager
    from fedml_tpu.comm.inproc import InProcRouter
    from fedml_tpu.comm.message import Message

    run_id = "sweep_flag"
    InProcRouter.reset(run_id)
    cfg = tiny_config(run_id=run_id,
                      extra={"comm_chunk_idle_sweep_s": 0.05})

    class Mgr(FedMLCommManager):
        def register_message_receive_handlers(self):
            pass

    mgr = Mgr(cfg, rank=0, backend="INPROC")
    assert mgr.com_manager._chunk_sweep_s == 0.05

    events = []
    sink = comm_base.add_comm_event_sink(
        lambda event, **info: events.append((event, info.get("client"))))
    try:
        # first frame of a 2+-chunk stream from sender 9, then silence
        msg = Message(3, 9, 0)
        msg.add_params("model_params", np.arange(4096, dtype=np.float32))
        frames = list(wire.encode_chunk_frames(
            msg.encode(), stream_id="9.0", sender=9, chunk_bytes=1024))
        assert len(frames) > 1
        mgr.com_manager._inbox.put(bytes(frames[0]))
        base_drops = MSG_DROPPED.value(reason="chunk_stream_timeout")
        t = threading.Thread(target=mgr.com_manager.handle_receive_message,
                             daemon=True)
        t.start()
        deadline = time.monotonic() + 5.0
        while MSG_DROPPED.value(reason="chunk_stream_timeout") == base_drops:
            assert time.monotonic() < deadline, "stale stream never swept"
            time.sleep(0.01)
        mgr.com_manager.stop_receive_message()
        t.join(timeout=5.0)
    finally:
        comm_base.remove_comm_event_sink(sink)
        InProcRouter.reset(run_id)
    assert ("dropped", 9) in events  # sender-attributed


def test_health_ledger_state_roundtrip():
    from fedml_tpu.obs.health import ClientHealthLedger

    a = ClientHealthLedger()
    a.observe_rtt(1, 0.5)
    a.record_deadline_breach(2)
    a.record_comm_failure(2)
    state = a.export_state()
    b = ClientHealthLedger()
    b.import_state(state)
    assert b.score(2) == a.score(2)
    assert b.score(1) == a.score(1)
    assert b.export_state() == state
