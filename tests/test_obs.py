"""Observability tests: device perf sampler + runtime log daemon (VERDICT
row 44, reference mlops_device_perfs.py / mlops_runtime_log_daemon.py), plus
the invert-gradient privacy attack variant (row 32)."""

import json
import time

import numpy as np

from .conftest import tiny_config


def test_device_perf_sampler_streams(tmp_path, eight_devices):
    from fedml_tpu.obs.metrics import MetricsLogger
    from fedml_tpu.obs.sampler import DevicePerfSampler

    path = tmp_path / "perf.jsonl"
    logger = MetricsLogger(str(path), stdout=False)
    sampler = DevicePerfSampler(logger, interval_s=0.1)
    s = sampler.sample_once()
    assert "perf_ts" in s
    assert "system_memory_utilization" in s or "loadavg_1m" in s
    assert isinstance(s["devices"], list) and s["devices"]
    assert "kind" in s["devices"][0]

    sampler.start()
    time.sleep(0.45)
    sampler.stop()
    assert sampler.samples >= 3
    lines = [json.loads(l) for l in path.read_text().splitlines() if l.strip()]
    assert len(lines) >= 3


def test_runtime_log_daemon_ships_batches(tmp_path):
    from fedml_tpu.obs.sampler import RuntimeLogDaemon

    log = tmp_path / "run.log"
    shipped: list[list[str]] = []
    daemon = RuntimeLogDaemon(str(log), sink=shipped.append, interval_s=0.05, batch_lines=2)
    log.write_text("line1\nline2\nline3\npartial")
    assert daemon.sweep_once() == 3
    assert [l for batch in shipped for l in batch] == ["line1", "line2", "line3"]
    # the partial line ships once completed
    with open(log, "a") as f:
        f.write("-done\nline5\n")
    assert daemon.sweep_once() == 2
    assert [l for b in shipped for l in b][-2:] == ["partial-done", "line5"]

    # default sink: offset-tracked spool file, no duplicates across sweeps
    log2 = tmp_path / "run2.log"
    d2 = RuntimeLogDaemon(str(log2), interval_s=0.05)
    log2.write_text("a\nb\n")
    d2.start()
    time.sleep(0.3)
    d2.stop()
    uploaded = (tmp_path / "run2.log.uploaded").read_text().splitlines()
    assert uploaded == ["a", "b"]


def test_invert_gradient_attack_reconstructs(eight_devices):
    """Known-label cosine-matching inversion must recover the victim input
    substantially better than the random init does (reference
    invert_gradient_attack.py capability)."""
    import jax
    import jax.numpy as jnp
    import fedml_tpu
    from fedml_tpu.models import model_hub
    from fedml_tpu.trust.attack.dlg import invert_gradient_attack

    cfg = tiny_config()
    fedml_tpu.init(cfg)
    model = model_hub.create(cfg, 10)  # LR on 60-dim features
    k = jax.random.PRNGKey(0)
    x_true = jax.random.normal(k, (2, 60))
    y_true = jnp.array([3, 7])
    variables = model.init({"params": jax.random.PRNGKey(1)}, x_true, train=True)

    def loss(v, x, y_onehot):
        logits = model.apply(v, x, train=False)
        return -jnp.mean(jnp.sum(jax.nn.log_softmax(logits) * y_onehot, axis=-1))

    victim_grads = jax.grad(loss)(variables, x_true, jax.nn.one_hot(y_true, 10))

    def grad_fn(x, y_onehot):
        return jax.grad(loss)(variables, x, y_onehot)

    x_hat, final = invert_gradient_attack(
        grad_fn, victim_grads, x_true.shape, y_true, jax.random.PRNGKey(2),
        steps=400, lr=0.05,
    )
    err = float(jnp.abs(x_hat - x_true).mean())
    base = float(jnp.abs(jax.random.normal(jax.random.PRNGKey(2), x_true.shape) * 0.1 - x_true).mean())
    assert np.isfinite(final)
    assert err < 0.6 * base, (err, base)


def test_log_daemon_handles_truncation(tmp_path):
    from fedml_tpu.obs.sampler import RuntimeLogDaemon

    log = tmp_path / "r.log"
    shipped = []
    d = RuntimeLogDaemon(str(log), sink=shipped.append)
    log.write_text("one\ntwo\n")
    assert d.sweep_once() == 2
    log.write_text("fresh\n")  # rotation: file shrank
    assert d.sweep_once() == 1
    assert [l for b in shipped for l in b] == ["one", "two", "fresh"]
