"""Buffered-async aggregation (ISSUE 8): staleness-decayed folds, K-arrival
virtual rounds, health-gated admission, chunked transport frames, and the
associative-fold protocol — plus the flag-unset parity guarantees."""

import threading
import time

import numpy as np
import pytest

from .conftest import tiny_config


def _load(cfg):
    import fedml_tpu
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub

    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)
    return ds, model


def _upload_msg(rank, params, n_samples=16.0, version=0):
    """A model reply as the server receives it: encoded + decoded, so the
    tensor section is a real lazy wire frame."""
    from fedml_tpu.comm.message import Message
    from fedml_tpu.cross_silo import message_define as md

    msg = Message(md.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, rank, 0)
    msg.add_params(md.MSG_ARG_KEY_MODEL_PARAMS, params)
    msg.add_params(md.MSG_ARG_KEY_NUM_SAMPLES, float(n_samples))
    msg.add_params(md.MSG_ARG_KEY_ROUND_INDEX, int(version))
    return Message.decode(msg.encode())


def _perturbed(params, salt: int):
    import jax

    return jax.tree_util.tree_map(
        lambda a: (np.asarray(a) + 1e-3 * (salt + 1)).astype(np.asarray(a).dtype)
        if np.asarray(a).dtype.kind == "f" else np.asarray(a),
        params)


# ---------------------------------------------------------------------------
# staleness decay math
# ---------------------------------------------------------------------------

def test_staleness_scale_math():
    from fedml_tpu.cross_silo.async_server import staleness_scale

    assert staleness_scale(0, 0.5) == 1.0  # literal 1.0: bitwise-neutral fold
    assert staleness_scale(0, 0.0) == 1.0
    assert staleness_scale(7, 0.0) == 1.0  # exponent 0 disables the decay
    assert staleness_scale(1, 0.5) == pytest.approx(2.0 ** -0.5)
    assert staleness_scale(3, 1.0) == pytest.approx(0.25)
    # monotonically decreasing in tau, and never negative
    prev = 1.0
    for tau in range(1, 50):
        s = staleness_scale(tau, 0.5)
        assert 0.0 < s < prev
        prev = s


def test_tau0_fold_bitwise_matches_sync_streaming(eight_devices):
    """A fresh (tau=0) async fold must be BITWISE the synchronous streaming
    fold: same accumulator math, scale multiplies by literal 1.0."""
    import jax
    from fedml_tpu.cross_silo import build_aggregator
    from fedml_tpu.cross_silo.async_server import staleness_scale

    cfg = tiny_config(extra={"streaming_aggregation": True})
    ds, model = _load(cfg)
    agg_sync = build_aggregator(cfg, ds, model)
    agg_async = build_aggregator(cfg, ds, model)
    assert agg_sync.stream_mode and agg_async.stream_mode

    base = jax.device_get(agg_sync.global_vars)
    for cid in (1, 2, 3):
        params = _perturbed(base, cid)
        assert agg_sync.ingest_streaming(
            cid, _upload_msg(cid, params), 16.0 + cid, is_delta=False)
        assert agg_async.fold(
            cid, _upload_msg(cid, params), 16.0 + cid, is_delta=False,
            scale=staleness_scale(0, 0.5))
    a = jax.device_get(agg_sync.aggregate(0))
    b = jax.device_get(agg_async.aggregate(0))
    for x, y in zip(jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_stale_fold_downweights(eight_devices):
    """A stale update must pull the aggregate toward it LESS than the same
    update folded fresh."""
    import jax
    from fedml_tpu.cross_silo import build_aggregator

    cfg = tiny_config(extra={"streaming_aggregation": True})
    ds, model = _load(cfg)

    def run(scale_outlier):
        agg = build_aggregator(cfg, ds, model)
        base = jax.device_get(agg.global_vars)
        outlier = jax.tree_util.tree_map(
            lambda a: (np.asarray(a) + 1.0).astype(np.asarray(a).dtype)
            if np.asarray(a).dtype.kind == "f" else np.asarray(a), base)
        assert agg.fold(1, _upload_msg(1, base), 16.0, False, scale=1.0)
        assert agg.fold(2, _upload_msg(2, outlier), 16.0, False, scale=scale_outlier)
        return np.concatenate([np.asarray(l).ravel() for l in
                               jax.tree_util.tree_leaves(jax.device_get(agg.aggregate(0)))])

    fresh = run(1.0)
    decayed = run(0.25)
    base_agg = run(1e-9)  # outlier weight ~0: essentially only client 1
    # decayed sits strictly between "full weight" and "no weight"
    assert np.linalg.norm(decayed - base_agg) < np.linalg.norm(fresh - base_agg)
    assert np.linalg.norm(decayed - base_agg) > 1e-6


# ---------------------------------------------------------------------------
# virtual rounds: K-boundary, determinism, health gating
# ---------------------------------------------------------------------------

def _async_server(cfg, ds, model, run_id):
    from fedml_tpu.comm.inproc import InProcRouter
    from fedml_tpu.cross_silo import build_server

    InProcRouter.reset(run_id)
    cfg.run_id = run_id
    server = build_server(cfg, ds, model, backend="INPROC")
    return server


def _async_cfg(**overrides):
    extra = {"async_aggregation": True, "async_buffer_k": 3,
             "async_staleness_exponent": 0.5,
             "async_redispatch_timeout_s": 0.0}  # no watchdog in direct-drive
    extra.update(overrides.pop("extra", {}))
    return tiny_config(training_type="cross_silo", client_num_in_total=6,
                       client_num_per_round=4, comm_round=2,
                       frequency_of_the_test=0, extra=extra, **overrides)


def test_virtual_round_k_boundary(eight_devices):
    """Exactly the Kth arrival closes the virtual round — not K-1, not K+1 —
    and a client may legitimately contribute twice within one round."""
    import jax

    cfg = _async_cfg()
    ds, model = _load(cfg)
    server = _async_server(cfg, ds, model, "async_kb")
    try:
        server.send_init_msg()
        base = jax.device_get(server.aggregator.global_vars)
        # K-1 arrivals (client 1 twice: async allows repeat contributions)
        for i, cid in enumerate((1, 1)):
            server.handle_message_receive_model(
                _upload_msg(cid, _perturbed(base, i), version=0))
        assert server.server_version == 0 and not server.history
        server.handle_message_receive_model(
            _upload_msg(2, _perturbed(base, 7), version=0))
        assert server.server_version == 1
        assert len(server.history) == 1
        assert server.history[0]["arrivals"] == 3
        # next arrival starts the NEW round's buffer against version 1
        server.handle_message_receive_model(
            _upload_msg(3, _perturbed(base, 9), version=0))
        assert server.server_version == 1
        assert server.history[0]["staleness_max"] == 0
        assert server.aggregator.peak_buffered_updates <= 2
    finally:
        server.finish()


def test_virtual_round_deterministic_under_fixed_arrival_order(eight_devices):
    """Same arrivals in the same order -> bitwise-identical global model."""
    import jax

    def run(run_id):
        cfg = _async_cfg()
        ds, model = _load(cfg)
        server = _async_server(cfg, ds, model, run_id)
        try:
            server.send_init_msg()
            base = jax.device_get(server.aggregator.global_vars)
            arrivals = [(1, 0), (4, 0), (2, 0), (3, 1), (1, 1), (5, 0)]
            for i, (cid, ver) in enumerate(arrivals):
                server.handle_message_receive_model(
                    _upload_msg(cid, _perturbed(base, i), 16.0 + cid, version=ver))
            assert server.server_version == 2  # both virtual rounds closed
            return jax.device_get(server.aggregator.global_vars)
        finally:
            server.finish()

    a, b = run("async_det_a"), run("async_det_b")
    for x, y in zip(jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_health_gated_admission_throttles_not_drops(eight_devices):
    """A degraded sender's upload is FOLDED, but its next dispatch waits for
    the virtual-round boundary; healthy senders are re-dispatched at once."""
    import jax

    cfg = _async_cfg(extra={"health_aware_selection": True})
    ds, model = _load(cfg)
    server = _async_server(cfg, ds, model, "async_health")
    try:
        server.send_init_msg()
        base = jax.device_get(server.aggregator.global_vars)
        for _ in range(8):  # degrade rank 2 well below the 0.5 threshold
            server.health.record_deadline_breach(2)
        assert server.health.score(2) < server.health.degraded_threshold

        folded_before = server.aggregator._stream_folded
        server.handle_message_receive_model(_upload_msg(2, _perturbed(base, 0)))
        assert server.aggregator._stream_folded == folded_before + 1  # folded...
        assert 2 in server._throttled                  # ...but throttled
        assert 2 not in server._outstanding            # no immediate re-dispatch

        server.handle_message_receive_model(_upload_msg(1, _perturbed(base, 1)))
        assert 1 not in server._throttled              # healthy: back in flight
        # third arrival closes the round -> the throttled client re-enters
        server.handle_message_receive_model(_upload_msg(3, _perturbed(base, 2)))
        assert server.server_version == 1
        assert not server._throttled
        assert 2 in server._outstanding
    finally:
        server.finish()


@pytest.mark.locksan
def test_async_e2e_inproc_real_clients(eight_devices):
    """Full protocol with REAL training clients over the in-proc fabric:
    virtual rounds close, eval runs, peak buffered stays <= 2."""
    import fedml_tpu
    from fedml_tpu.cross_silo import run_in_process_group
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub

    cfg = tiny_config(
        training_type="cross_silo", client_num_in_total=4,
        client_num_per_round=4, comm_round=2, frequency_of_the_test=1,
        run_id="async_e2e",
        extra={"async_aggregation": True, "async_buffer_k": 4,
               "async_staleness_exponent": 0.5,
               "async_redispatch_timeout_s": 5.0})
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)
    history = run_in_process_group(cfg, ds, model, timeout=120.0)
    assert len(history) == 2
    assert all(h["arrivals"] == 4 for h in history)
    assert np.isfinite(history[-1]["test_acc"])


def test_async_flag_unset_is_the_sync_server(eight_devices):
    """Parity gate: without extra.async_aggregation, build_server returns
    the synchronous manager (and the async module is never even needed)."""
    from fedml_tpu.cross_silo import build_server
    from fedml_tpu.cross_silo.async_server import AsyncFedMLServerManager
    from fedml_tpu.cross_silo.server import FedMLServerManager

    cfg = tiny_config(training_type="cross_silo", run_id="async_off")
    ds, model = _load(cfg)
    server = build_server(cfg, ds, model, backend="INPROC")
    try:
        assert type(server) is FedMLServerManager
        assert not server.aggregator.stream_mode  # default path untouched
    finally:
        server.finish()
    cfg_on = tiny_config(training_type="cross_silo", run_id="async_on",
                         extra={"async_aggregation": True})
    ds2, model2 = _load(cfg_on)
    server_on = build_server(cfg_on, ds2, model2, backend="INPROC")
    try:
        assert isinstance(server_on, AsyncFedMLServerManager)
        assert server_on.aggregator.stream_mode
    finally:
        server_on.finish()


# ---------------------------------------------------------------------------
# associative-fold protocol
# ---------------------------------------------------------------------------

def test_associative_fold_protocol(eight_devices):
    import jax
    import jax.numpy as jnp

    from fedml_tpu.fl.algorithm import FedAlgorithm
    from fedml_tpu.fl.types import HParams

    hp = HParams(learning_rate=0.1, epochs=1, batch_size=8, steps_per_epoch=1)
    assert FedAlgorithm(hp).supports_associative_fold()

    class Median(FedAlgorithm):
        def aggregate(self, stacked, weights):  # order/set-sensitive
            return jax.tree_util.tree_map(lambda s: jnp.median(s, 0), stacked)

    assert not Median(hp).supports_associative_fold()


def test_custom_aggregate_refuses_stream_mode(eight_devices):
    """An algorithm overriding aggregate must keep the exact buffered path
    even when the async/streaming flags ask for folding."""
    from fedml_tpu.cross_silo import build_aggregator

    cfg = tiny_config(federated_optimizer="FedDyn",
                      extra={"streaming_aggregation": True})
    ds, model = _load(cfg)
    agg = build_aggregator(cfg, ds, model)
    if agg.algorithm.supports_associative_fold():
        pytest.skip("FedDyn aggregate became associative; pick another")
    assert not agg.stream_mode
    assert not agg.fold(1, _upload_msg(1, {}), 1.0, False)


def test_lora_aggregator_defaults_stay_exact(eight_devices):
    """LoRAAggregator opts into the associative fold via _init_stream_mode
    (ISSUE 12), but the CLASS defaults must stay exact-mode-safe: a subclass
    that skips every __init__ still refuses the fold, and the one fold entry
    point stays the base class's (tests/test_federated_lora.py covers the
    instance-level opt-in and the trust gate)."""
    from fedml_tpu.llm.unitedllm import LoRAAggregator

    assert LoRAAggregator.stream_mode is False
    # fold() consults stream_mode first, so an instance that never ran
    # _init_stream_mode refuses the associative path outright
    assert "fold" not in LoRAAggregator.__dict__  # inherits the one entry point


# ---------------------------------------------------------------------------
# chunked transport frames
# ---------------------------------------------------------------------------

def test_chunk_frames_roundtrip_and_reorder():
    from fedml_tpu.comm import wire
    from fedml_tpu.comm.message import ChunkAssembler, Message

    msg = Message(3, 2, 0)
    msg.add_params("model_params", {"w": np.arange(4096, dtype=np.float32).reshape(64, 64)})
    msg.add_params("num_samples", 64.0)
    payload = msg.encode()
    frames = list(wire.encode_chunk_frames(payload, stream_id="s", sender=2,
                                           chunk_bytes=900))
    assert len(frames) > 3
    assert all(wire.is_chunk_frame(f) for f in frames)
    assert not wire.is_chunk_frame(payload)

    def assemble(seq):
        asm = ChunkAssembler()
        out = None
        for f in seq:
            m, err, sender = asm.feed(f)
            assert err is None and sender == 2
            if m is not None:
                out = m
        assert asm.pending_streams() == 0
        return out

    for order in (frames, list(reversed(frames))):
        out = assemble(order)
        assert out is not None
        assert out.wire_nbytes == len(payload)
        assert out.get("num_samples") == 64.0
        assert out.recv_monotonic is not None
        np.testing.assert_array_equal(
            out.get("model_params")["w"], msg.msg_params["model_params"]["w"])


def test_chunk_streams_interleave_per_peer():
    """Chunks from two concurrent uploads interleave freely — the anti-
    head-of-line property the framing exists for."""
    import itertools

    from fedml_tpu.comm import wire
    from fedml_tpu.comm.message import ChunkAssembler, Message

    def upload(rank, scale):
        m = Message(3, rank, 0)
        m.add_params("model_params", {"w": np.full((100, 100), scale, np.float32)})
        return m.encode()

    f1 = list(wire.encode_chunk_frames(upload(1, 1.0), stream_id="a", sender=1, chunk_bytes=512))
    f2 = list(wire.encode_chunk_frames(upload(5, 5.0), stream_id="b", sender=5, chunk_bytes=2048))
    asm = ChunkAssembler()
    done = {}
    for f in (x for pair in itertools.zip_longest(f1, f2) for x in pair if x is not None):
        m, err, _ = asm.feed(f)
        assert err is None
        if m is not None:
            done[m.get_sender_id()] = m
    assert set(done) == {1, 5}
    assert float(done[5].get("model_params")["w"][0, 0]) == 5.0
    assert asm.pending_streams() == 0


def test_chunk_corrupt_and_timeout_are_attributed_drops():
    from fedml_tpu.comm import wire
    from fedml_tpu.comm.message import ChunkAssembler, Message

    m = Message(3, 7, 0)
    m.add_params("model_params", {"w": np.ones((64, 64), np.float32)})
    frames = list(wire.encode_chunk_frames(m.encode(), stream_id="x", sender=7,
                                           chunk_bytes=1024))
    # corrupt a mid-stream chunk's payload length -> stream dropped, sender named
    asm = ChunkAssembler()
    asm.feed(frames[0])
    bad = frames[1][:-10]  # truncated tensor bytes corrupt the leaf framing
    res = [asm.feed(f) for f in [bad] + frames[2:]]
    # either the corrupt chunk kills the stream now or the total-length
    # mismatch kills it at completion; both must attribute sender 7
    errs = [(err, sender) for _m, err, sender in res if err is not None]
    assert errs and all(s == 7 for _e, s in errs)
    assert asm.pending_streams() == 0

    # a sender that dies mid-upload: the idle stream is swept
    asm2 = ChunkAssembler(stream_timeout_s=0.01)
    asm2.feed(frames[0])
    time.sleep(0.05)
    evicted = asm2.sweep()
    assert evicted == [(7, "x")]
    assert asm2.pending_streams() == 0


def test_dropped_event_with_client_feeds_health_ledger():
    """Satellite: receive-loop drop/retry pressure now attributes to the
    named client, same as the synchronous broadcast-failure path."""
    from fedml_tpu.comm import base as comm_base
    from fedml_tpu.obs.health import ClientHealthLedger

    ledger = ClientHealthLedger().attach_comm()
    try:
        assert ledger.score(9) == 1.0
        comm_base._emit_comm_event("dropped", reason="chunk_stream_timeout", client=9)
        assert ledger.comm_drops == 1
        assert ledger.score(9) < 1.0  # per-client pressure accrued
        before = ledger.score(9)
        comm_base._emit_comm_event("retried", client=9)
        assert ledger.comm_retries == 1
        assert ledger.score(9) < before
        # unattributed events move only the process-wide counters
        comm_base._emit_comm_event("dropped", reason="undecodable")
        assert ledger.comm_drops == 2
    finally:
        ledger.detach_comm()


def test_tcp_chunked_end_to_end(eight_devices):
    """A chunked TCP send must arrive as one Message with identical tensors
    (and the receive loop must meter the chunk frames)."""
    from fedml_tpu.comm.base import CHUNK_FRAMES
    from fedml_tpu.comm.message import Message
    from fedml_tpu.comm.tcp_backend import TCPCommManager

    base = 19450
    a = TCPCommManager("127.0.0.1", base + 0, 0, base_port=base, chunk_bytes=4096)
    b = TCPCommManager("127.0.0.1", base + 1, 1, base_port=base, chunk_bytes=4096)
    received = []

    class Obs:
        def receive_message(self, t, m):
            received.append(m)

    b.add_observer(Obs())
    t = threading.Thread(target=b.handle_receive_message, daemon=True)
    t.start()
    frames0 = CHUNK_FRAMES.value()
    try:
        big = Message(3, 0, 1)
        big.add_params("model_params", {"w": np.random.default_rng(0)
                                        .normal(size=(128, 128)).astype(np.float32)})
        big.add_params("num_samples", 7.0)
        a.send_message(big)
        deadline = time.time() + 10
        while not received and time.time() < deadline:
            time.sleep(0.01)
    finally:
        b.stop_receive_message()
        a.stop_receive_message()
    assert received, "chunked message never delivered"
    out = received[0]
    assert CHUNK_FRAMES.value() - frames0 >= 2, "send was not actually chunked"
    assert out.get("num_samples") == 7.0
    np.testing.assert_array_equal(out.get("model_params")["w"],
                                  big.msg_params["model_params"]["w"])


def test_tcp_unchunked_default_is_legacy_single_frame(eight_devices):
    """chunk_bytes=0 (the default / flag unset) must keep the legacy one-
    frame-per-message bytes: no chunk frames on the wire at all."""
    from fedml_tpu.comm.base import CHUNK_FRAMES
    from fedml_tpu.comm.message import Message
    from fedml_tpu.comm.tcp_backend import TCPCommManager

    base = 19470
    a = TCPCommManager("127.0.0.1", base + 0, 0, base_port=base)
    b = TCPCommManager("127.0.0.1", base + 1, 1, base_port=base)
    assert a.chunk_bytes == 0
    received = []

    class Obs:
        def receive_message(self, t, m):
            received.append(m)

    b.add_observer(Obs())
    t = threading.Thread(target=b.handle_receive_message, daemon=True)
    t.start()
    frames0 = CHUNK_FRAMES.value()
    try:
        big = Message(3, 0, 1)
        big.add_params("model_params", {"w": np.ones((256, 256), np.float32)})
        a.send_message(big)
        deadline = time.time() + 10
        while not received and time.time() < deadline:
            time.sleep(0.01)
    finally:
        b.stop_receive_message()
        a.stop_receive_message()
    assert received
    assert CHUNK_FRAMES.value() == frames0, "flag-unset send produced chunk frames"
    assert received[0].wire_nbytes == len(big.encode())  # byte-identical frame


def test_grpc_chunked_end_to_end(eight_devices):
    from fedml_tpu.comm.grpc_backend import GRPCCommManager
    from fedml_tpu.comm.message import Message

    base = 19500
    a = GRPCCommManager("127.0.0.1", base + 0, 0, base_port=base, chunk_bytes=8192)
    b = GRPCCommManager("127.0.0.1", base + 1, 1, base_port=base, chunk_bytes=8192)
    received = []

    class Obs:
        def receive_message(self, t, m):
            received.append(m)

    b.add_observer(Obs())
    t = threading.Thread(target=b.handle_receive_message, daemon=True)
    t.start()
    try:
        big = Message(3, 0, 1)
        big.add_params("model_params", {"w": np.arange(128 * 128, dtype=np.float32).reshape(128, 128)})
        a.send_message(big)
        deadline = time.time() + 10
        while not received and time.time() < deadline:
            time.sleep(0.01)
    finally:
        b.stop_receive_message()
        a.stop_receive_message()
    assert received
    np.testing.assert_array_equal(received[0].get("model_params")["w"],
                                  big.msg_params["model_params"]["w"])


def test_fold_accepts_chunk_decoded_message(eight_devices):
    """The fold entry point must stream chunk-assembled (pre-decoded-leaves)
    messages exactly like lazy whole frames — same accumulator, same result."""
    import jax
    from fedml_tpu.comm import wire
    from fedml_tpu.comm.message import ChunkAssembler
    from fedml_tpu.cross_silo import build_aggregator

    cfg = tiny_config(extra={"streaming_aggregation": True})
    ds, model = _load(cfg)
    agg_whole = build_aggregator(cfg, ds, model)
    agg_chunked = build_aggregator(cfg, ds, model)
    base = jax.device_get(agg_whole.global_vars)
    for cid in (1, 2):
        params = _perturbed(base, cid)
        whole = _upload_msg(cid, params, 16.0)
        # the same reply delivered as chunk frames instead of one blob
        asm = ChunkAssembler()
        chunked = None
        for f in wire.encode_chunk_frames(
                raw_payload_bytes(params, cid),
                stream_id=f"c{cid}", sender=cid, chunk_bytes=600):
            m, err, _ = asm.feed(f)
            assert err is None
            if m is not None:
                chunked = m
        assert chunked is not None and chunked.tensor_frame() is not None
        assert agg_whole.fold(cid, whole, 16.0, False)
        assert agg_chunked.fold(cid, chunked, 16.0, False)
    a = jax.device_get(agg_whole.aggregate(0))
    b = jax.device_get(agg_chunked.aggregate(0))
    for x, y in zip(jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def raw_payload_bytes(params, rank):
    from fedml_tpu.comm.message import Message
    from fedml_tpu.cross_silo import message_define as md

    msg = Message(md.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, rank, 0)
    msg.add_params(md.MSG_ARG_KEY_MODEL_PARAMS, params)
    msg.add_params(md.MSG_ARG_KEY_NUM_SAMPLES, 16.0)
    msg.add_params(md.MSG_ARG_KEY_ROUND_INDEX, 0)
    return msg.encode()


def test_get_control_never_materializes(eight_devices):
    """Reading an ABSENT control key (the raw upload's missing delta flag)
    must not collapse the lazy tensor frame — the regression that silently
    demoted streaming folds to the dense buffer-all path."""
    params = {"w": np.ones((32, 32), np.float32)}
    msg = _upload_msg(1, params)
    assert msg.tensor_stream() is not None
    assert msg.get_control("model_is_delta", False) is False
    assert msg.tensor_stream() is not None  # still lazy
    assert msg.get("model_is_delta", False) is False  # plain get materializes
    assert msg.tensor_stream() is None


# ---------------------------------------------------------------------------
# soak harness (small), AOT satellites
# ---------------------------------------------------------------------------

@pytest.mark.locksan
def test_soak_small(eight_devices):
    from fedml_tpu.cross_silo.async_soak import run_soak

    res = run_soak(n_clients=200, concurrency=32, buffer_k=8, versions=3,
                   drop_prob=0.1, latency_mean_s=0.002,
                   redispatch_timeout_s=0.5, seed=1, timeout_s=60.0)
    assert res["versions"] == 3
    assert res["arrivals"] == 24
    assert res["versions_per_sec"] > 0
    assert res["peak_buffered_updates"] <= 2
    assert res["unaccounted_drops"] == 0
    assert res["fold_lag_p95_s"] is not None
    assert res["staleness_max"] >= 1  # concurrency >> K forces staleness


def test_client_train_program_rides_aot_store(eight_devices, tmp_path):
    """Satellite: the cross-silo CLIENT local-train program exports through
    the program store — a second (restarted) trainer deserializes instead of
    re-tracing, with bitwise-identical training results."""
    import jax
    import fedml_tpu
    from fedml_tpu.core import rng
    from fedml_tpu.core.aot import AOT_HITS, AOT_MISSES
    from fedml_tpu.cross_silo.client import FedMLTrainer
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub

    store = str(tmp_path / "aot")
    mk = lambda **extra: tiny_config(
        extra={"silo_dp": False, **extra})
    cfg_plain = mk()
    fedml_tpu.init(cfg_plain)
    ds = loader.load(cfg_plain)
    model = model_hub.create(cfg_plain, ds.class_num)
    ix = ds.client_idx[0]
    k0 = rng.root_key(cfg_plain.random_seed)
    variables = jax.device_get(model.init(
        {"params": jax.random.PRNGKey(1)},
        np.asarray(ds.train_x[:2]), train=True))

    plain = FedMLTrainer(cfg_plain, model, ds.train_x[ix], ds.train_y[ix])
    out_plain, n_plain = plain.train(variables, 0, k0, client_idx=0)

    cfg_aot = mk(aot_programs=True, aot_programs_dir=store)
    m0, h0 = AOT_MISSES.value(), AOT_HITS.value()
    t1 = FedMLTrainer(cfg_aot, model, ds.train_x[ix], ds.train_y[ix])
    out_cold, _ = t1.train(variables, 0, k0, client_idx=0)
    assert AOT_MISSES.value() - m0 == 1  # cold: traced + exported once

    t2 = FedMLTrainer(cfg_aot, model, ds.train_x[ix], ds.train_y[ix])  # "restart"
    m1 = AOT_MISSES.value()
    out_warm, n_warm = t2.train(variables, 0, k0, client_idx=0)
    assert AOT_MISSES.value() == m1, "warm trainer re-traced the program"
    assert AOT_HITS.value() > h0
    assert n_warm == n_plain
    for a, b in zip(jax.tree_util.tree_leaves(out_plain),
                    jax.tree_util.tree_leaves(out_warm)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree_util.tree_leaves(out_cold),
                    jax.tree_util.tree_leaves(out_warm)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_server_warm_programs(eight_devices, tmp_path):
    """Satellite: the async server's startup resolves every stored server
    program via ProgramStore.warm() — zero failures, and a second
    construction is served from the store."""
    from fedml_tpu.cross_silo import build_aggregator

    cfg = tiny_config(extra={"aot_programs": True,
                             "aot_programs_dir": str(tmp_path / "aot")})
    ds, model = _load(cfg)
    agg = build_aggregator(cfg, ds, model)
    stats = agg.warm_programs()
    assert stats is not None
    assert stats["failed"] == 0
    assert stats["loaded"] + stats["built"] >= 1

    agg2 = build_aggregator(cfg, ds, model)  # "restarted server"
    stats2 = agg2.warm_programs()
    assert stats2["failed"] == 0 and stats2["loaded"] >= 1

    # flag unset -> no store, warm is a no-op None
    cfg_off = tiny_config()
    ds3, model3 = _load(cfg_off)
    agg3 = build_aggregator(cfg_off, ds3, model3)
    assert agg3.warm_programs() is None
