"""fedavg_seq runtime-fit + min-makespan scheduler tests (VERDICT item 9d,
reference core/schedule/seq_train_scheduler.py + runtime_estimate.py)."""

import itertools

import numpy as np

from fedml_tpu.sched.seq_scheduler import (
    RuntimeEstimator,
    SeqTrainScheduler,
    balanced_client_order,
    fit_linear_runtime,
)


def _brute_force_makespan(workloads, costs, n_devices):
    n = len(workloads)
    best = float("inf")
    for assign in itertools.product(range(n_devices), repeat=n):
        loads = [0.0] * n_devices
        for ci, d in enumerate(assign):
            loads[d] += costs[d][ci]
        best = min(best, max(loads))
    return best


def test_linear_runtime_fit_recovers_slope():
    rng = np.random.RandomState(0)
    n = rng.randint(50, 500, size=40).astype(float)
    t = 0.003 * n + 0.7 + rng.normal(0, 0.01, size=40)
    fn, (a, b), err = fit_linear_runtime(n, t)
    assert abs(a - 0.003) < 5e-4 and abs(b - 0.7) < 0.1
    assert err < 0.05
    assert fn(1000) > fn(100)


def test_runtime_estimator_heterogeneous_devices():
    est = RuntimeEstimator(uniform_devices=False)
    for n in (100, 200, 400):
        est.record(0, n, 0.001 * n)   # fast device
        est.record(1, n, 0.004 * n)   # slow device
    fns, errs = est.cost_fns(2)
    assert fns[1](300) > 3 * fns[0](300)
    assert max(errs) < 1e-6


def test_exact_matches_brute_force():
    rng = np.random.RandomState(1)
    for trial in range(5):
        w = rng.randint(1, 100, size=7).astype(float)
        sched = SeqTrainScheduler(w, 3)
        got = sched.schedule_exact()
        want = _brute_force_makespan(w, sched.costs, 3)
        assert got.makespan == pytest_approx(want), (got.makespan, want)
        # every client assigned exactly once
        flat = sorted(ci for a in got.assignment for ci in a)
        assert flat == list(range(7))


def pytest_approx(x):
    import pytest

    return pytest.approx(x, rel=1e-9)


def test_lpt_within_4_3_of_optimal():
    rng = np.random.RandomState(2)
    for trial in range(5):
        w = rng.randint(1, 1000, size=10).astype(float)
        sched = SeqTrainScheduler(w, 4)
        lpt = sched.schedule_lpt()
        opt = _brute_force_makespan(w, sched.costs, 4)
        assert lpt.makespan <= (4.0 / 3.0) * opt + 1e-9


def test_lpt_scales_to_ragged_dirichlet_shards():
    """The motivating case: 128 Dirichlet-ragged client shard sizes onto an
    8-device axis — balanced loads, much better than contiguous chunking."""
    rng = np.random.RandomState(3)
    sizes = np.maximum(10, (rng.dirichlet([0.3] * 128) * 50000)).astype(float)
    sched = SeqTrainScheduler(sizes, 8)
    s = sched.schedule_lpt()
    naive = max(
        sizes[i * 16 : (i + 1) * 16].sum() for i in range(8)
    )  # contiguous chunks
    assert s.makespan <= naive
    # within 5% of the perfect-fraction lower bound
    assert s.makespan <= 1.05 * sizes.sum() / 8


def test_heterogeneous_cost_assignment_prefers_fast_device():
    w = np.array([100.0, 100.0, 100.0, 100.0])
    fast = lambda n: 0.001 * n
    slow = lambda n: 0.010 * n
    s = SeqTrainScheduler(w, 2, cost_fns=[fast, slow]).schedule_exact()
    n_fast = len(s.assignment[0])
    # optimal: fast device takes the lion's share (makespan ~0.4 on 3/1 or 4/0 split)
    assert n_fast >= 3


def test_balanced_client_order_spreads_heavy_clients():
    rng = np.random.RandomState(4)
    counts = np.concatenate([np.full(8, 1000.0), np.full(56, 10.0)])
    rng.shuffle(counts)
    order = balanced_client_order(counts, 8)
    assert sorted(order.tolist()) == list(range(64))
    per = 8
    group_heavy = [
        int((counts[order[g * per : (g + 1) * per]] >= 1000).sum()) for g in range(8)
    ]
    # each shard group gets exactly one heavy client
    assert group_heavy == [1] * 8, group_heavy
