"""Host-side actor-loop race stress (SURVEY §5 'race detection': the
reference has none and its concurrency safety is ad-hoc; the JAX core is
functional, so the places that CAN race are the host-side managers).

Each test hammers a manager's message handlers from many threads at once —
the situation real transports create (gRPC thread pools, MQTT callbacks,
TCP accept threads) — and asserts the protocol invariants hold: exactly one
aggregate per round, no double round-advance, no lost or duplicated state.
"""

import threading

import numpy as np
import pytest

from .conftest import tiny_config


def _storm(fns, repeats=4):
    """Run every callable in `fns` `repeats` times concurrently."""
    threads = [
        threading.Thread(target=fn)
        for fn in fns for _ in range(repeats)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not any(t.is_alive() for t in threads), "storm thread hung"


def test_server_duplicate_and_stale_uploads(eight_devices):
    """Duplicate model uploads (MQTT redelivery) and stale-round arrivals
    must produce EXACTLY one aggregation per round and never double-advance
    the round counter."""
    import fedml_tpu
    from fedml_tpu.comm.inproc import InProcRouter
    from fedml_tpu.comm.message import Message
    from fedml_tpu.cross_silo import build_aggregator, message_define as md
    from fedml_tpu.cross_silo.server import FedMLServerManager
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub

    cfg = tiny_config(
        training_type="cross_silo", client_num_in_total=4,
        client_num_per_round=4, comm_round=3, run_id="race-dup",
        frequency_of_the_test=0,
    )
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)
    InProcRouter.reset("race-dup")
    server = FedMLServerManager(cfg, build_aggregator(cfg, ds, model), backend="INPROC")

    agg_calls = []
    orig_agg = server.aggregator.aggregate

    def counting_agg(round_idx):
        agg_calls.append(round_idx)
        return orig_agg(round_idx)

    server.aggregator.aggregate = counting_agg
    import jax

    params = jax.device_get(server.aggregator.global_vars)
    server.selected = list(server.client_ids)
    server.round_idx = 0

    def upload(sender, round_idx):
        msg = Message(md.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, sender, 0)
        msg.add_params(md.MSG_ARG_KEY_MODEL_PARAMS, params)
        msg.add_params(md.MSG_ARG_KEY_NUM_SAMPLES, 10.0)
        msg.add_params(md.MSG_ARG_KEY_ROUND_INDEX, round_idx)
        return msg

    # storm: every client uploads round 0 FOUR times each, plus stale
    # round -1 and future round 7 uploads interleaved
    fns = []
    for c in (1, 2, 3, 4):
        fns.append(lambda c=c: server.handle_message_receive_model(upload(c, 0)))
        fns.append(lambda c=c: server.handle_message_receive_model(upload(c, -1)))
        fns.append(lambda c=c: server.handle_message_receive_model(upload(c, 7)))
    _storm(fns, repeats=3)

    # exactly ONE aggregation happened, for round 0, and the round advanced once
    assert agg_calls == [0], agg_calls
    assert server.round_idx == 1
    # and the next round can still proceed (no corrupted state)
    for c in (1, 2, 3, 4):
        server.handle_message_receive_model(upload(c, 1))
    assert agg_calls == [0, 1]
    assert server.round_idx == 2


def test_fa_server_duplicate_submissions(eight_devices):
    """Same at-least-once property for the FA wire server."""
    import fedml_tpu
    from fedml_tpu.comm.inproc import InProcRouter
    from fedml_tpu.comm.message import Message
    from fedml_tpu.cross_silo import message_define as md
    from fedml_tpu.fa.analyzers import create_analyzer_pair
    from fedml_tpu.fa.cross_silo import (
        MSG_ARG_KEY_FA_PAYLOAD, MSG_TYPE_C2S_FA_SUBMISSION, FAServerManager, fa_encode,
    )

    cfg = tiny_config(client_num_in_total=4, client_num_per_round=4,
                      comm_round=2, run_id="race-fa")
    fedml_tpu.init(cfg)
    InProcRouter.reset("race-fa")
    _, aggregator = create_analyzer_pair("frequency_estimation", cfg)
    server = FAServerManager(cfg, aggregator, backend="INPROC")
    server.selected = list(server.client_ids)

    agg_calls = []
    orig = server.aggregator.aggregate

    def counting(subs):
        agg_calls.append(len(subs))
        return orig(subs)

    server.aggregator.aggregate = counting

    def submit(sender, round_idx):
        msg = Message(MSG_TYPE_C2S_FA_SUBMISSION, sender, 0)
        msg.add_params(MSG_ARG_KEY_FA_PAYLOAD, fa_encode({int(sender): 1}))
        msg.add_params(md.MSG_ARG_KEY_ROUND_INDEX, round_idx)
        return msg

    fns = [
        (lambda c=c: server.handle_message_submission(submit(c, 0)))
        for c in (1, 2, 3, 4)
    ]
    _storm(fns, repeats=4)
    assert agg_calls == [4], agg_calls  # one aggregate, all four clients
    assert server.round_idx == 1


def test_deploy_predict_under_scale_churn(tmp_path):
    """Concurrent predicts while the reconcile loop scales up and down:
    every predict either succeeds or fails with the documented no-ready /
    all-failed errors — never a dict-mutation crash or a wedged lock."""
    import jax

    import fedml_tpu
    from fedml_tpu.models import model_hub
    from fedml_tpu.serving.deploy import ModelCard, ModelDeployScheduler, save_params_card

    cfg = tiny_config()
    fedml_tpu.init(cfg)
    model = model_hub.create(cfg, 10)
    variables = model.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        np.zeros((1, 32), np.float32), train=True,
    )
    path = str(tmp_path / "m.wire")
    save_params_card(variables, path)
    sched = ModelDeployScheduler(str(tmp_path / "db.sqlite"), reconcile_interval_s=0.2)
    sched.cards.register(ModelCard(name="lr-r", version="v1", model="lr",
                                   classes=10, params_path=path))
    errors = []
    try:
        sched.deploy("demo", "lr-r", replicas=1)
        sched.run_in_thread()
        assert sched.wait_ready("demo", replicas=1, timeout=180)

        stop = threading.Event()

        def pounder():
            while not stop.is_set():
                try:
                    sched.predict("demo", {"inputs": np.zeros((1, 32)).tolist()},
                                  timeout=10.0)
                except RuntimeError:
                    pass  # documented: no ready replicas / all failed
                except Exception as e:  # anything else is a race bug
                    errors.append(repr(e))
                    return

        pounders = [threading.Thread(target=pounder) for _ in range(4)]
        for t in pounders:
            t.start()
        # churn the replica count under the load
        for n in (3, 1, 2, 1):
            sched.scale("demo", n)
            sched.wait_ready("demo", replicas=1, timeout=180)
        stop.set()
        for t in pounders:
            t.join(timeout=30)
        assert not errors, errors
        out = sched.predict("demo", {"inputs": np.zeros((1, 32)).tolist()})
        assert len(out["outputs"]) == 1
    finally:
        sched.stop()
