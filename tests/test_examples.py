"""Recipe gallery smoke test (round-3 verdict item 10): every YAML under
``examples/`` must parse through the reference-style sectioned loader and
run 2 rounds end-to-end via FedMLRunner — the gallery is the discoverable
YAML vocabulary (reference ``examples/federate/...``), and a recipe that
rots breaks here."""

import dataclasses
import glob
import os

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_EXAMPLES = sorted(glob.glob(os.path.join(_REPO, "examples", "*", "fedml_config.yaml")))

# smoke-size overrides: the gallery documents full-size recipes; CI runs
# them tiny (the YAML vocabulary and dispatch are what is under test)
_SMOKE = dict(
    comm_round=2,
    frequency_of_the_test=2,
    synthetic_train_size=512,
    synthetic_test_size=128,
    client_num_in_total=4,
    client_num_per_round=4,
    batch_size=16,
    checkpoint_dir="",
    metrics_jsonl_path="",
)
# per-recipe overrides: shape fields that must survive the shrink, and conv
# models swapped to "lr" in CI — a conv-model mesh round compiles for
# minutes on this 1-core virtual-CPU box (env artifact; the resnet path is
# exercised on the real chip by bench.py and the zoo tests).  The YAML
# vocabulary, optimizer dispatch, and round loop are what this test pins.
_KEEP = {
    "myavg_condshift_mlp": {"client_num_in_total": 10, "client_num_per_round": 10,
                            "synthetic_train_size": 1500, "synthetic_test_size": 2000},
    "sim_hierarchical_cifar10": {"client_num_in_total": 8, "client_num_per_round": 8,
                                 "model": "lr"},
    "sp_fedavg_cifar10_resnet20": {"model": "lr"},
    "sp_fedopt_cifar10_resnet20": {"model": "lr"},
    "sp_fedsgd_eftopk_cifar10_resnet20": {"model": "lr"},
}


def test_gallery_is_populated():
    assert len(_EXAMPLES) >= 8, _EXAMPLES


@pytest.mark.parametrize("yaml_path", _EXAMPLES,
                         ids=[os.path.basename(os.path.dirname(p)) for p in _EXAMPLES])
def test_example_recipe_smokes(yaml_path, eight_devices, tmp_path):
    import fedml_tpu
    from fedml_tpu.runner import FedMLRunner

    cfg = fedml_tpu.arguments.add_args(["--cf", yaml_path])
    name = os.path.basename(os.path.dirname(yaml_path))
    over = dict(_SMOKE)
    over.update(_KEEP.get(name, {}))
    over["data_cache_dir"] = str(tmp_path)  # never read real data in CI
    cfg = dataclasses.replace(cfg, **over)
    fedml_tpu.init(cfg)
    runner = FedMLRunner(cfg)
    history = runner.run()
    assert history, f"{name}: empty history"
    last = history[-1]
    assert any(k.startswith("train_loss") or k in ("round", "test_acc", "test_ppl")
               for k in last), (name, last)
