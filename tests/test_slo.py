"""SLO watchdog (ISSUE 16): declarative specs over registry snapshots.

- ``evaluate_spec`` stat resolution (value/sum/count/mean/percentile/rate,
  the ``per`` ratio, label filters, and the no-data -> no-verdict rule);
- edge-triggered breach semantics: one alert record + one counter bump per
  transition, ``fedml_slo_healthy`` flips and recovers, breaches land in
  the collector trail and (once per SLO) a flight dump;
- per-job scoping: a ``job=``-bound engine over ``ScopedRegistry`` series
  sees only its tenant's samples;
- the config gate (``extra.slo_specs`` unset -> ``None``, invalid specs ->
  disabled loudly, not a crash);
- the healthy e2e half of the acceptance criterion: a clean async soak
  with generous SLOs records >= 1 evaluation and ZERO breaches.
"""

import pytest

from fedml_tpu.obs import registry as obsreg
from fedml_tpu.obs.slo import (
    SLO_BREACHES,
    SLO_HEALTHY,
    SLOEngine,
    engine_from_config,
    evaluate_spec,
)


def _counter_snap(name, samples, labels=()):
    return {"name": name, "kind": "counter", "labels": list(labels),
            "samples": samples}


def _gauge_snap(name, samples, labels=()):
    return {"name": name, "kind": "gauge", "labels": list(labels),
            "samples": samples}


def _hist_snap(name, buckets, samples, labels=()):
    return {"name": name, "kind": "histogram", "labels": list(labels),
            "buckets": list(buckets), "samples": samples}


# ---------------------------------------------------------------------------
# evaluate_spec


def test_value_sums_matching_samples_and_filters_labels():
    snap = [_counter_snap("fedml_t_total", [
        {"labels": {"path": "fold"}, "value": 7.0},
        {"labels": {"path": "buffer"}, "value": 2.0},
    ], labels=("path",))]
    assert evaluate_spec({"metric": "fedml_t_total", "threshold": 0}, snap) == 9.0
    assert evaluate_spec({"metric": "fedml_t_total", "threshold": 0,
                          "labels": {"path": "fold"}}, snap) == 7.0
    # undeclared filter keys are dropped, not silently non-matching: a
    # job-scoped engine can still watch global single-series families
    assert evaluate_spec({"metric": "fedml_t_total", "threshold": 0},
                         snap, extra_labels={"job": "1"}) == 9.0


def test_no_data_means_no_verdict():
    assert evaluate_spec({"metric": "fedml_absent", "threshold": 1}, []) is None
    hist = [_hist_snap("fedml_h", [1.0, float("inf")], [])]
    assert evaluate_spec({"metric": "fedml_h", "stat": "p95", "threshold": 1},
                         hist) is None  # zero observations -> no percentile
    assert evaluate_spec({"metric": "fedml_h", "stat": "mean", "threshold": 1},
                         hist) is None


def test_histogram_stats_mean_count_sum_percentile():
    snap = [_hist_snap("fedml_h_seconds", [0.1, 1.0, float("inf")], [
        {"labels": {}, "count": 10, "sum": 4.0, "counts": [8, 2, 0]},
    ])]
    spec = {"metric": "fedml_h_seconds", "threshold": 0}
    assert evaluate_spec({**spec, "stat": "count"}, snap) == 10.0
    assert evaluate_spec({**spec, "stat": "sum"}, snap) == 4.0
    assert evaluate_spec({**spec, "stat": "mean"}, snap) == pytest.approx(0.4)
    assert evaluate_spec({**spec, "stat": "p50"}, snap) == pytest.approx(0.1)
    assert evaluate_spec({**spec, "stat": "p95"}, snap) == pytest.approx(1.0)


def test_rate_needs_two_ticks_and_divides_by_wall():
    state = {}
    snap1 = [_counter_snap("fedml_r_total", [{"labels": {}, "value": 10.0}])]
    snap2 = [_counter_snap("fedml_r_total", [{"labels": {}, "value": 25.0}])]
    spec = {"metric": "fedml_r_total", "stat": "rate", "threshold": 0}
    assert evaluate_spec(spec, snap1, rate_state=state, now=100.0) is None
    assert evaluate_spec(spec, snap2, rate_state=state, now=105.0) == pytest.approx(3.0)


def test_per_ratio_and_zero_denominator():
    snap = [
        _counter_snap("fedml_dedup_total", [{"labels": {}, "value": 3.0}]),
        _counter_snap("fedml_arrivals_total", [{"labels": {}, "value": 12.0}]),
    ]
    spec = {"metric": "fedml_dedup_total", "per": "fedml_arrivals_total",
            "threshold": 0}
    assert evaluate_spec(spec, snap) == pytest.approx(0.25)
    snap[1]["samples"][0]["value"] = 0.0
    assert evaluate_spec(spec, snap) is None  # no denominator -> no verdict


# ---------------------------------------------------------------------------
# the engine: edge-triggered breaches


class _TrailStub:
    def __init__(self):
        self.records = []

    def ingest(self, sender, batch):
        self.records.extend(batch)


def _engine(specs, **kw):
    return SLOEngine(specs, registry=obsreg.MetricsRegistry(), **kw)


def test_breach_is_edge_triggered_once_and_recovers(tmp_path):
    from fedml_tpu.obs.flight import FlightRecorder, list_bundles

    trail = _TrailStub()
    flight = FlightRecorder(str(tmp_path), name="slo_t")
    eng = _engine({"lag": {"metric": "fedml_lag", "stat": "value",
                           "op": "<=", "threshold": 5.0}},
                  collector=trail, flight=flight)
    breached = [_gauge_snap("fedml_lag", [{"labels": {}, "value": 9.0}])]
    healthy = [_gauge_snap("fedml_lag", [{"labels": {}, "value": 1.0}])]
    before = SLO_BREACHES.value(slo="lag", job="")

    assert eng.evaluate_now(healthy) == []
    new = eng.evaluate_now(breached)
    assert len(new) == 1 and new[0]["slo"] == "lag" and new[0]["value"] == 9.0
    assert eng.evaluate_now(breached) == []  # still breached: no re-alert
    assert SLO_BREACHES.value(slo="lag", job="") == before + 1
    assert SLO_HEALTHY.value(slo="lag", job="") == 0.0

    assert eng.evaluate_now(healthy) == []  # recovery flips healthy back
    assert SLO_HEALTHY.value(slo="lag", job="") == 1.0
    new2 = eng.evaluate_now(breached)  # NEW transition -> alerts again
    assert len(new2) == 1
    assert SLO_BREACHES.value(slo="lag", job="") == before + 2

    # both transitions hit the collector trail; the flight dump fired ONCE
    assert [r["slo"] for r in trail.records] == ["lag", "lag"]
    assert all(r["kind"] == "slo_breach" for r in trail.records)
    dumps = [p for p in list_bundles(str(tmp_path)) if "slo_breach" in p]
    assert len(dumps) == 1
    assert eng.summary()["breaches"] == 2
    assert eng.summary()["breached_slos"] == ["lag"]


def test_job_scoped_engine_sees_only_its_tenant():
    reg = obsreg.MetricsRegistry()
    fam = reg.counter("fedml_t_rounds_total", "t", labels=("job",))
    fam.inc(100, job="1")  # tenant 1 is way over
    fam.inc(1, job="2")    # tenant 2 is fine
    spec = {"metric": "fedml_t_rounds_total", "op": "<=", "threshold": 10}
    e1 = SLOEngine({"rounds": spec}, registry=reg, job="1")
    e2 = SLOEngine({"rounds": spec}, registry=reg, job="2")
    assert len(e1.evaluate_now()) == 1
    assert e2.evaluate_now() == []
    assert SLO_HEALTHY.value(slo="rounds", job="1") == 0.0
    assert SLO_HEALTHY.value(slo="rounds", job="2") == 1.0
    # the breach record carries the job for downstream attribution
    assert e1.breach_records[0]["job"] == "1"


def test_scoped_registry_writes_feed_job_scoped_specs():
    """The multi-tenant path end to end: ScopedRegistry stamps the job
    label on write, and the per-job engine filters on it."""
    reg = obsreg.MetricsRegistry()
    s1 = reg.scoped(job="a").counter("fedml_t_scoped_total", "t")
    s2 = reg.scoped(job="b").counter("fedml_t_scoped_total", "t")
    s1.inc(50)
    s2.inc(2)
    spec = {"metric": "fedml_t_scoped_total", "op": "<=", "threshold": 10}
    assert len(SLOEngine({"x": spec}, registry=reg, job="a").evaluate_now()) == 1
    assert SLOEngine({"x": spec}, registry=reg, job="b").evaluate_now() == []


def test_engine_rejects_bad_specs_loudly():
    with pytest.raises(ValueError):
        _engine({"x": {"metric": "m", "op": "!=", "threshold": 1}})
    with pytest.raises(ValueError):
        _engine({"x": {"metric": "m"}})  # no threshold
    with pytest.raises(ValueError):
        _engine({"x": {"threshold": 1}})  # no metric


def test_engine_from_config_gate():
    from .conftest import tiny_config

    cfg = tiny_config()
    cfg.extra = {}
    assert engine_from_config(cfg, runtime=None) is None
    # invalid specs disable the engine instead of crashing the server
    cfg.extra = {"slo_specs": {"x": {"metric": "m", "op": "!=", "threshold": 1}}}
    assert engine_from_config(cfg, runtime=None) is None
    cfg.extra = {"slo_specs": {"x": {"metric": "fedml_lag", "threshold": 5}},
                 "slo_interval_s": 0.25, "mt_job_id": "7"}
    eng = engine_from_config(cfg, runtime=None)
    assert eng is not None and eng.interval_s == 0.25 and eng.job == "7"
    # slo_flight_dump unset -> the flight recorder is NOT handed over
    assert eng.flight is None


# ---------------------------------------------------------------------------
# healthy e2e: zero breaches on a clean run (acceptance criterion)


def test_clean_async_soak_records_zero_breaches(eight_devices):
    from fedml_tpu.cross_silo.async_soak import run_soak

    specs = {
        "buffered_peak": {"metric": "fedml_crosssilo_buffered_updates_peak",
                          "stat": "value", "op": "<=", "threshold": 64},
        "fold_lag_p95": {"metric": "fedml_async_fold_lag_seconds",
                         "stat": "p95", "op": "<=", "threshold": 120.0},
        "versions_rate": {"metric": "fedml_async_virtual_rounds_total",
                          "stat": "rate", "op": ">=", "threshold": 0.0},
    }
    res = run_soak(n_clients=32, concurrency=8, buffer_k=4, versions=3,
                   drop_prob=0.0, latency_mean_s=0.001,
                   redispatch_timeout_s=1.0, seed=0, timeout_s=120.0,
                   extra_flags={"slo_specs": specs, "slo_interval_s": 0.1})
    assert res["versions"] == 3
    slo = res["slo"]
    assert slo["evaluations"] >= 1  # the engine ran (timer wheel or final pass)
    assert slo["breaches"] == 0 and slo["breached_slos"] == []
