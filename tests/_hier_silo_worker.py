"""Worker entry for the hierarchical cross-silo e2e test (spawned by
tests/test_hier_silo.py).  Usage:

    python tests/_hier_silo_worker.py <role> <tcp_base_port> <coord_port>

Roles (the full reference stack shape, SURVEY.md §3.3 /
``cross_silo/client/client_launcher.py:46``):

  server — FL server over TCP (rank 0); waits for both client listeners
           before starting; prints MULTIHOST_RESULT with the final global
           checksum.
  silo1  — plain single-process silo (rank 1) over TCP.
  siloA  — silo-2 MASTER (rank 2) over TCP; its local SGD spans 2 processes
           via jax.distributed (4+4 virtual CPU devices, global data mesh).
  siloB  — silo-2 follower: no FL transport, lockstep collective training
           until the master's CMD_FINISH.
"""

import json
import os
import socket
import sys
import time


def main():
    role, base_port, coord_port = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["JAX_PLATFORM_NAME"] = "cpu"
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax

    jax.config.update("jax_platforms", "cpu")
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

    import numpy as np

    import fedml_tpu
    from fedml_tpu.arguments import Config

    dist = role in ("siloA", "siloB")
    cfg = Config(
        training_type="cross_silo",
        dataset="synthetic",
        model="lr",
        client_num_in_total=2,
        client_num_per_round=2,
        comm_round=2,
        epochs=1,
        batch_size=16,
        learning_rate=0.1,
        synthetic_train_size=256,
        synthetic_test_size=64,
        partition_method="homo",
        frequency_of_the_test=1,
        compute_dtype="float32",
        random_seed=0,
        backend="TCP",
        extra={
            "tcp_base_port": base_port,
            **({"coordinator_address": f"localhost:{coord_port}",
                "num_processes": 2,
                "process_id": 0 if role == "siloA" else 1} if dist else {}),
        },
    )
    fedml_tpu.init(cfg)
    if dist:
        from fedml_tpu.parallel import multihost

        multihost.ensure_initialized(cfg)
        assert jax.process_count() == 2

    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub

    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)

    if role == "server":
        from fedml_tpu.cross_silo import build_server

        # both client listeners must be up before the status broadcast (the
        # TCP transport has no retry; probe exactly as the transport connects)
        for rank in (1, 2):
            deadline = time.time() + 120
            while True:
                try:
                    socket.create_connection(("127.0.0.1", base_port + rank), timeout=1).close()
                    break
                except OSError:
                    if time.time() > deadline:
                        raise RuntimeError(f"client rank {rank} never listened")
                    time.sleep(0.2)
        server = build_server(cfg, ds, model, backend="TCP")
        history = server.run_until_done(timeout=240.0)
        flat = np.concatenate([
            np.asarray(l, dtype=np.float64).ravel()
            for l in jax.tree_util.tree_leaves(jax.device_get(server.aggregator.global_vars))
        ])
        print("MULTIHOST_RESULT " + json.dumps({
            "role": role,
            "rounds": len(history),
            "checksum": float(flat.sum()),
            "l2": float(np.sqrt((flat ** 2).sum())),
            "test_acc": history[-1].get("test_acc"),
        }), flush=True)
        return

    if role == "silo1":
        from fedml_tpu.cross_silo import build_client

        client = build_client(cfg, ds, model, rank=1, backend="TCP")
        client.run_in_thread()
        assert client.done.wait(timeout=240.0), "silo1 never saw FINISH"
        print("MULTIHOST_RESULT " + json.dumps({"role": role, "done": True}), flush=True)
        return

    ix = ds.client_idx[1]  # silo 2's shard for both of its processes
    x, y = ds.train_x[ix], ds.train_y[ix]

    if role == "siloA":
        from fedml_tpu.cross_silo.client import ClientMasterManager
        from fedml_tpu.cross_silo.silo_dist import DistributedSiloTrainer

        trainer = DistributedSiloTrainer(cfg, model, x, y)
        client = ClientMasterManager(cfg, trainer, rank=2, backend="TCP")
        client.run_in_thread()
        assert client.done.wait(timeout=240.0), "siloA never saw FINISH"
        print("MULTIHOST_RESULT " + json.dumps(
            {"role": role, "rounds": client.rounds_trained}), flush=True)
        return

    if role == "siloB":
        from fedml_tpu.cross_silo.silo_dist import run_silo_follower

        rounds = run_silo_follower(cfg, model, x, y)
        print("MULTIHOST_RESULT " + json.dumps({"role": role, "rounds": rounds}), flush=True)
        return

    raise SystemExit(f"unknown role {role!r}")


if __name__ == "__main__":
    main()
