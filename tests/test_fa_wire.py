"""FA over the wire (VERDICT round-2 item 8): analyzers ride the cross-silo
comm managers — heavy-hitter e2e over INPROC, parity with the simulator."""

import numpy as np
import pytest

from .conftest import tiny_config


def _fa_cfg(**kw):
    base = dict(
        client_num_in_total=4,
        client_num_per_round=4,
        comm_round=3,
        run_id="fa-wire",
    )
    base.update(kw)
    return tiny_config(**base)


def _heavy_hitter_data():
    """4 clients; 'aaa' and 'bbb' are globally frequent strings."""
    rng = np.random.default_rng(0)
    common = ["aaa", "bbb"]
    out = []
    for c in range(4):
        words = common * 6 + [f"rare{c}{i}" for i in range(3)]
        rng.shuffle(words)
        out.append(np.asarray(words))
    return out


def test_triehh_heavy_hitters_over_inproc(eight_devices):
    """TrieHH over the real message protocol discovers the global heavy
    hitters without any client revealing its raw strings."""
    import fedml_tpu
    from fedml_tpu.fa.cross_silo import run_fa_process_group

    cfg = _fa_cfg(comm_round=10, run_id="fa-hh")
    fedml_tpu.init(cfg)
    data = _heavy_hitter_data()
    result, server = run_fa_process_group(cfg, "heavy_hitter_triehh", data, timeout=60.0)
    hh = server.aggregator.heavy_hitters()
    assert "aaa" in hh and "bbb" in hh, hh
    assert not any(h.startswith("rare") for h in hh), hh


def test_fa_wire_matches_simulator(eight_devices):
    """The wire protocol computes the same result as the single-process
    simulator for a deterministic aggregate (frequency counts)."""
    import fedml_tpu
    from fedml_tpu.fa.analyzers import create_analyzer_pair
    from fedml_tpu.fa.cross_silo import run_fa_process_group
    from fedml_tpu.fa.frame import FASimulator

    cfg = _fa_cfg(comm_round=2, run_id="fa-freq")
    fedml_tpu.init(cfg)
    data = [np.asarray([c % 3, (c + 1) % 3, 0]) for c in range(4)]
    wire_result, _server = run_fa_process_group(cfg, "frequency_estimation", data, timeout=60.0)

    analyzer, aggregator = create_analyzer_pair("frequency_estimation", cfg)
    sim_result = FASimulator(cfg, data, analyzer, aggregator).run()
    assert dict(wire_result) == dict(sim_result), (wire_result, sim_result)


def test_fa_wire_union_and_sampling(eight_devices):
    """Per-round client sampling + a set-union aggregate over the wire."""
    import fedml_tpu
    from fedml_tpu.fa.cross_silo import run_fa_process_group

    cfg = _fa_cfg(client_num_per_round=2, comm_round=4, run_id="fa-union")
    fedml_tpu.init(cfg)
    data = [np.asarray([c, 100 + c]) for c in range(4)]
    result, _server = run_fa_process_group(cfg, "union", data, timeout=60.0)
    got = set(int(v) for v in result)
    # expected union over the deterministic per-round sample (same sampler
    # the server uses)
    from fedml_tpu.core import rng as _rng

    expected = set()
    for r in range(cfg.comm_round):
        for i in _rng.sample_clients_np(r, 4, 2):
            expected |= {int(v) for v in data[int(i)]}
    assert got == expected, (got, expected)
