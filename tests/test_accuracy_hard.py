"""Accuracy-story hardening (VERDICT round-2 item 7).

1. The low-SNR ``synthetic_hard`` benchmark has a LOCKED expected-accuracy
   band: learnable but never trivially saturated (the old stand-in hit 99.95%
   by round 9, proving only wiring).
2. BN-statistics aggregation semantics under Dirichlet skew are pinned:
   ``batch_stats`` leaves are sample-weight averaged exactly like weights
   (SURVEY §7 hard-part 3 — the behavior the accuracy story depends on).
3. The real-file CIFAR reader is exercised end-to-end from a generated
   3-image ``cifar-10-batches-py`` fixture.
"""

import pickle

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from .conftest import tiny_config


def test_hard_benchmark_band_and_gradual_learning(eight_devices):
    """FedAvg hetero alpha=0.5 on synthetic_hard: accuracy climbs gradually
    into a locked band — no early saturation, no failure to learn."""
    import fedml_tpu
    from fedml_tpu.runner import FedMLRunner

    cfg = tiny_config(
        dataset="synthetic_hard", model="lr", client_num_in_total=8,
        client_num_per_round=8, comm_round=8, epochs=2, batch_size=32,
        learning_rate=0.1, synthetic_train_size=8192, synthetic_test_size=2048,
        partition_method="hetero", partition_alpha=0.5, frequency_of_the_test=2,
    )
    fedml_tpu.init(cfg)
    hist = FedMLRunner(cfg).run()
    accs = [h["test_acc"] for h in hist if "test_acc" in h]
    assert len(accs) >= 3
    # locked band for this seed/recipe (measured 0.656-0.694 over rounds
    # 2-12; re-lock deliberately if the generator changes)
    assert 0.55 <= accs[-1] <= 0.85, accs
    # gradual: later evals keep improving and nothing saturates
    assert accs[-1] > accs[0] + 0.01, accs
    assert max(accs) < 0.95, f"benchmark must not saturate: {accs}"


def test_hard_benchmark_is_not_trivial_early(eight_devices):
    """Round-0 accuracy sits far below the band — accuracy must be EARNED
    across rounds (the old stand-in was >90% after one round)."""
    import fedml_tpu
    from fedml_tpu.runner import FedMLRunner

    cfg = tiny_config(
        dataset="synthetic_hard", model="lr", client_num_in_total=8,
        client_num_per_round=8, comm_round=1, epochs=1, batch_size=32,
        learning_rate=0.1, synthetic_train_size=8192, synthetic_test_size=2048,
        partition_method="hetero", partition_alpha=0.5, frequency_of_the_test=1,
    )
    fedml_tpu.init(cfg)
    hist = FedMLRunner(cfg).run()
    assert hist[-1]["test_acc"] < 0.55, hist[-1]


def test_hard_benchmark_deterministic():
    from fedml_tpu.data import loader

    a = loader.load(tiny_config(dataset="synthetic_hard", synthetic_train_size=512,
                                synthetic_test_size=128))
    b = loader.load(tiny_config(dataset="synthetic_hard", synthetic_train_size=512,
                                synthetic_test_size=128))
    np.testing.assert_array_equal(a.train_x, b.train_x)
    np.testing.assert_array_equal(a.train_y, b.train_y)
    # balanced classes (interleaved cluster->class assignment)
    counts = np.bincount(a.train_y, minlength=10)
    assert counts.min() > 0.5 * counts.max(), counts


def test_bn_stats_aggregated_as_sample_weighted_mean(eight_devices):
    """Pin the BN-statistics aggregation semantics under alpha=0.5 skew:
    the new global ``batch_stats`` equal the sample-weighted mean of the
    clients' post-training stats — the same rule as weights (FedAvg
    contribution = full variables; SURVEY §7 hard-part 3)."""
    import flax.linen as nn

    import fedml_tpu
    from fedml_tpu.core import rng
    from fedml_tpu.sim.engine import MeshSimulator
    from fedml_tpu.data import loader

    class TinyBN(nn.Module):
        classes: int = 10

        @nn.compact
        def __call__(self, x, train: bool = False):
            x = x.reshape((x.shape[0], -1))
            x = nn.Dense(16)(x)
            x = nn.BatchNorm(use_running_average=not train, momentum=0.9)(x)
            x = nn.relu(x)
            if self.is_mutable_collection("params"):
                nn.Dropout(0.0, deterministic=True)(x)  # init rng shape parity
            return nn.Dense(self.classes)(x)

    cfg = tiny_config(
        dataset="synthetic", model="mlp", client_num_in_total=4,
        client_num_per_round=4, comm_round=1, epochs=1, batch_size=16,
        partition_method="hetero", partition_alpha=0.5,
        synthetic_train_size=512, synthetic_test_size=128,
        frequency_of_the_test=0,
    )
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    sim = MeshSimulator(cfg, ds, TinyBN())
    assert "batch_stats" in sim.global_vars, "model must carry BN stats"
    g0 = jax.device_get(sim.global_vars)

    # independently recompute each sampled client's contribution
    n_total = ds.n_clients
    m = cfg.client_num_per_round
    sampled = np.asarray(rng.sample_clients(sim.root_key, 0, n_total, m))
    rkey = rng.round_key(sim.root_key, jnp.int32(0))
    contribs, weights = [], []
    for ci in sampled:
        k = rng.client_key(rkey, int(ci))
        out = sim.algorithm.client_update(
            sim.global_vars, None, sim.server_state,
            sim._data[0][int(ci)], sim._data[1][int(ci)], sim.counts[int(ci)], k,
        )
        contribs.append(jax.device_get(out.contribution))
        weights.append(float(sim.counts[int(ci)]))
    w = np.asarray(weights) / np.sum(weights)

    sim.run_round()
    g1 = jax.device_get(sim.global_vars)

    for key in ("mean", "var"):
        leaf = g1["batch_stats"]["BatchNorm_0"][key]
        expected = sum(
            wi * np.asarray(c["batch_stats"]["BatchNorm_0"][key])
            for wi, c in zip(w, contribs)
        )
        np.testing.assert_allclose(np.asarray(leaf), expected, rtol=2e-4, atol=2e-5)
        # the skewed clients genuinely disagree (the pin is meaningful)
        stack = np.stack([np.asarray(c["batch_stats"]["BatchNorm_0"][key]) for c in contribs])
        assert np.abs(stack - stack[0]).max() > 1e-5


def _write_cifar_fixture(root, n_per_batch=1):
    """Generate a minimal cifar-10-batches-py layout (3 known images)."""
    d = root / "cifar-10-batches-py"
    d.mkdir(parents=True)
    rng = np.random.RandomState(0)
    all_imgs, all_labels = [], []
    for i in range(1, 6):
        img = rng.randint(0, 256, size=(n_per_batch, 3072), dtype=np.uint8)
        labels = [int(rng.randint(0, 10)) for _ in range(n_per_batch)]
        with open(d / f"data_batch_{i}", "wb") as f:
            pickle.dump({b"data": img, b"labels": labels}, f)
        all_imgs.append(img)
        all_labels.extend(labels)
    timg = rng.randint(0, 256, size=(2, 3072), dtype=np.uint8)
    tlabels = [3, 7]
    with open(d / "test_batch", "wb") as f:
        pickle.dump({b"data": timg, b"labels": tlabels}, f)
    return np.concatenate(all_imgs), np.asarray(all_labels), timg, np.asarray(tlabels)


def test_cifar_reader_end_to_end(tmp_path):
    """loader.load(dataset='cifar10') consumes a real cifar-10-batches-py
    directory: NCHW->NHWC reshape, /255, canonical per-channel normalization,
    labels intact."""
    from fedml_tpu.data import loader

    raw_train, train_y, raw_test, test_y = _write_cifar_fixture(tmp_path)
    cfg = tiny_config(
        dataset="cifar10", data_cache_dir=str(tmp_path), synthetic_fallback=False,
        client_num_in_total=2, client_num_per_round=2,
    )
    ds = loader.load(cfg)
    assert ds.train_x.shape == (5, 32, 32, 3)
    assert ds.test_x.shape == (2, 32, 32, 3)
    np.testing.assert_array_equal(ds.train_y, train_y)
    np.testing.assert_array_equal(ds.test_y, test_y)
    # exact normalization math on a known pixel
    mean = np.array([0.4914, 0.4822, 0.4465], np.float32)
    std = np.array([0.2470, 0.2435, 0.2616], np.float32)
    expected = (raw_train[0].reshape(3, 32, 32).transpose(1, 2, 0) / 255.0 - mean) / std
    np.testing.assert_allclose(ds.train_x[0], expected, rtol=1e-5)
    # without the fixture and with synthetic_fallback=False the loader refuses
    cfg_missing = tiny_config(dataset="cifar10", data_cache_dir=str(tmp_path / "nope"),
                              synthetic_fallback=False)
    with pytest.raises(FileNotFoundError):
        loader.load(cfg_missing)
