"""Hierarchical aggregation tree (ISSUE 17): topology spec, edge folds,
bitwise pins against the flat streaming fold, per-hop compression byte
accounting, and edge SIGKILL recovery.

The bitwise discipline under test (cross_silo/edge.py module docstring):
f32 addition is non-associative, so a general multi-child tree fold is NOT
bit-equal to the flat fold — but (a) a prefix tree (one edge holding a
prefix of the client order, the rest singletons) runs the identical op
sequence, and (b) with exactly-representable payloads (small integers,
products < 2^24) EVERY grouping is exact, so even the full 2x2 tree pins.
Both pins are asserted here, (a) at the protocol level on the real wire and
(b) at the aggregator level.
"""

import numpy as np
import pytest

from .conftest import tiny_config


def _hier_cfg(**kw):
    base = dict(
        training_type="cross_silo",
        client_num_in_total=4,
        client_num_per_round=4,
        comm_round=2,
        learning_rate=0.3,
        frequency_of_the_test=1,
    )
    base.update(kw)
    return tiny_config(**base)


def _decode(msg):
    from fedml_tpu.comm.message import Message

    return Message.decode(msg.encode())


def _model_msg(rank, params, n_samples, round_idx=0):
    from fedml_tpu.comm.message import Message
    from fedml_tpu.cross_silo import message_define as md

    m = Message(md.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, rank, 0)
    m.add_params(md.MSG_ARG_KEY_MODEL_PARAMS, params)
    m.add_params(md.MSG_ARG_KEY_NUM_SAMPLES, float(n_samples))
    m.add_params(md.MSG_ARG_KEY_ROUND_INDEX, round_idx)
    return _decode(m)


# ---------------------------------------------------------------------------
# topology spec
# ---------------------------------------------------------------------------

def test_topology_fanout_default():
    from fedml_tpu.cross_silo.edge import build_topology, round_robin_groups

    cfg = _hier_cfg(client_num_in_total=5, extra={"hier_fanout": 3})
    topo = build_topology(cfg)
    assert topo is not None and topo.depth == 2
    # ceil(5/3) = 2 edges at ranks N+1, N+2, round-robin membership
    assert topo.edge_ranks == [6, 7]
    assert topo.children_of[6] == [1, 3, 5]
    assert topo.children_of[7] == [2, 4]
    assert topo.parent(6) == 0 and topo.parent(1) == 6 and topo.parent(2) == 7
    assert topo.world_size == 8
    np.testing.assert_array_equal(topo.group_of, round_robin_groups(5, 2))
    # flat config -> no topology, the historical protocol
    assert build_topology(_hier_cfg()) is None


def test_topology_depth3_and_dispatch_plan():
    from fedml_tpu.cross_silo.edge import build_topology

    cfg = _hier_cfg(client_num_in_total=8,
                    extra={"hier_fanout": 2, "hier_depth": 3})
    topo = build_topology(cfg)
    assert topo.depth == 3
    assert topo.edge_ranks == [9, 10, 11, 12]
    assert topo.region_ranks == [13, 14]
    assert topo.parent(9) == 13 and topo.parent(10) == 14
    assert topo.parent(13) == 0
    plan = topo.dispatch_plan(list(range(1, 9)))
    # root dispatches only to its direct children (the regions)
    assert sorted(int(k) for k in plan) == [13, 14]
    sub = plan[13]["aggs"]
    assert all(isinstance(k, str) for k in sub)  # JSON-safe keys
    # skip= removes already-folded clients from the plan
    plan2 = topo.dispatch_plan(list(range(1, 9)), skip=[1, 5])
    flat = []
    for spec in plan2.values():
        for e in spec["aggs"].values():
            flat += [int(c) for c in e["clients"]]
    assert 1 not in flat and 5 not in flat


def test_topology_validation_errors():
    from fedml_tpu.cross_silo.edge import HierTopology, build_topology

    with pytest.raises(ValueError):  # client 3 unassigned
        HierTopology(3, [[1, 2]])
    with pytest.raises(ValueError):  # client 2 assigned twice
        HierTopology(3, [[1, 2], [2, 3]])
    with pytest.raises(ValueError):  # region over unknown edge ordinal
        HierTopology(2, [[1], [2]], regions=[[0, 5]])
    with pytest.raises(ValueError, match="hier_depth"):
        build_topology(_hier_cfg(extra={"hier_fanout": 2, "hier_depth": 4}))
    with pytest.raises(ValueError, match="hier_hop_codec"):
        from fedml_tpu.cross_silo.edge import hop_codec_from_config

        hop_codec_from_config(_hier_cfg(extra={"hier_hop_codec": "gzip"}))


def test_hier_secagg_and_async_gates():
    import fedml_tpu
    from fedml_tpu.cross_silo import build_server
    from fedml_tpu.cross_silo.edge import build_topology
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub

    with pytest.raises(NotImplementedError, match="secure-"):
        build_topology(_hier_cfg(enable_secagg=True,
                                 extra={"hier_fanout": 2}))
    cfg = _hier_cfg(run_id="hier_async_gate",
                    extra={"hier_fanout": 2, "async_aggregation": True,
                           "async_buffer_k": 2})
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)
    with pytest.raises(NotImplementedError, match="synchronous-only"):
        build_server(cfg, ds, model, backend="INPROC")


def test_edge_fold_supported_mirrors_stream_gate():
    from fedml_tpu.cross_silo.edge import edge_fold_supported

    assert not edge_fold_supported(_hier_cfg())  # no streaming trigger
    assert edge_fold_supported(
        _hier_cfg(extra={"streaming_aggregation": True}))
    assert edge_fold_supported(_hier_cfg(extra={"comm_compression": "qsgd8"}))


# ---------------------------------------------------------------------------
# bitwise pins
# ---------------------------------------------------------------------------

def test_aggregator_pin_full_tree_exact_payloads():
    """Full 2x2 tree == flat fold, BITWISE, with exactly-representable
    payloads: integer f32 values and weights keep every product and partial
    sum exact (< 2^24), so f32 non-associativity cannot bite and the tree
    grouping must reproduce the flat bits under ANY topology."""
    import fedml_tpu
    import jax
    from fedml_tpu.cross_silo import build_aggregator
    from fedml_tpu.cross_silo.edge import EdgePartialFold
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub

    cfg = _hier_cfg(run_id="hier_pin_exact",
                    extra={"streaming_aggregation": True})
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)

    def payload(host_tree, cid):
        return jax.tree_util.tree_map(
            lambda a: np.full(np.shape(a), float(cid), np.float32), host_tree)

    weights = {1: 2.0, 2: 3.0, 3: 5.0, 4: 7.0}

    def run(tree_shape):
        agg = build_aggregator(cfg, ds, model)
        assert agg.stream_mode
        host = jax.device_get(agg.global_vars)
        if tree_shape == "flat":
            for cid in (1, 2, 3, 4):
                assert agg.ingest_streaming(
                    cid, _model_msg(cid, payload(host, cid), weights[cid]),
                    weights[cid], False)
        else:
            for members in ((1, 2), (3, 4)):
                fold = EdgePartialFold(host)
                for cid in members:
                    assert fold.fold_child(
                        cid, _model_msg(cid, payload(host, cid), weights[cid]),
                        weights[cid], False)
                assert fold.peak_buffered <= 2
                tag = fold.control_tag()
                pmsg = _model_msg(members[0], fold.partial_tree(), fold.w)
                assert agg.fold_partial(pmsg, tag["sources"], tag["w_delta"])
        assert agg.check_whether_all_receive(4)
        agg.aggregate(0)
        return [np.asarray(l) for l in
                jax.tree_util.tree_leaves(jax.device_get(agg.global_vars))]

    flat, tree = run("flat"), run("tree")
    for a, b in zip(flat, tree):
        np.testing.assert_array_equal(a, b)


def test_fold_partial_redelivery_and_overlap():
    """Root fold_partial semantics: full redelivery of an already-folded
    partial is swallowed (True, no double fold); a PARTIAL overlap cannot be
    split and is rejected (False)."""
    import fedml_tpu
    import jax
    from fedml_tpu.cross_silo import build_aggregator
    from fedml_tpu.cross_silo.edge import EdgePartialFold
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub

    cfg = _hier_cfg(run_id="hier_partial_sem",
                    extra={"streaming_aggregation": True})
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)
    agg = build_aggregator(cfg, ds, model)
    host = jax.device_get(agg.global_vars)
    fold = EdgePartialFold(host)
    for cid in (1, 2):
        fold.fold_child(cid, _model_msg(cid, host, 8.0), 8.0, False)
    tag = fold.control_tag()
    pmsg = _model_msg(1, fold.partial_tree(), fold.w)
    assert agg.fold_partial(pmsg, tag["sources"], 0.0)
    w_after = agg._stream_w
    # exact redelivery: swallowed, nothing double-counts
    assert agg.fold_partial(_model_msg(1, fold.partial_tree(), fold.w),
                            tag["sources"], 0.0)
    assert agg._stream_w == w_after
    # overlapping superset (sources 1,2,3 with 1,2 already folded): rejected
    fold2 = EdgePartialFold(host)
    for cid in (1, 2, 3):
        fold2.fold_child(cid, _model_msg(cid, host, 8.0), 8.0, False)
    t2 = fold2.control_tag()
    assert not agg.fold_partial(_model_msg(1, fold2.partial_tree(), fold2.w),
                                t2["sources"], 0.0)


def test_sim_parity_bridge_segment_vs_edge_fold():
    """ISSUE 17 satellite: the simulator's segment-sum group fold and the
    protocol's EdgePartialFold agree BITWISE on one round of group sums —
    same round_robin_groups map, full participation, ascending member
    order on both sides (f32 multiply-then-add, identical op sequence)."""
    import jax.numpy as jnp
    from fedml_tpu.cross_silo.edge import EdgePartialFold, round_robin_groups
    from fedml_tpu.sim.hierarchical import segment_group_sums

    n, groups = 8, 3
    rs = np.random.RandomState(7)
    leaf = rs.randn(n, 4, 3).astype(np.float32)
    w = (1.0 + np.arange(n)).astype(np.float32)
    g = round_robin_groups(n, groups)
    sgm = np.asarray(segment_group_sums(
        jnp.asarray(leaf), jnp.asarray(w), jnp.asarray(g), groups))
    for grp in range(groups):
        fold = EdgePartialFold({"w": np.zeros((4, 3), np.float32)})
        for i in range(n):  # ascending order == segment_sum's scatter order
            if g[i] != grp:
                continue
            fold.fold_child(i + 1, _model_msg(i + 1, {"w": leaf[i]}, w[i]),
                            float(w[i]), False)
        np.testing.assert_array_equal(fold.partial_tree()["w"], sgm[grp])
        assert fold.peak_buffered <= 2


@pytest.mark.locksan
def test_protocol_pin_prefix_tree_bitwise(eight_devices):
    """THE tentpole pin on the real wire: a 2-level prefix tree (edge over
    clients [1, 2], singletons for the rest) folds the identical op sequence
    the flat streaming fold does under fixed arrival order, so the final
    globals match bit for bit.  Root connections drop 4 -> 3 and ingress
    bytes shrink even on the raw hop (partials < uploads)."""
    from fedml_tpu.cross_silo.async_soak import run_edge_kill_soak

    flat = run_edge_kill_soak(n_clients=4, fanout=0, rounds=2, kill=None,
                              seed=0)
    tree = run_edge_kill_soak(n_clients=4, fanout=0, rounds=2, kill=None,
                              seed=0, topology={"edges": [[1, 2], [3], [4]]})
    for a, b in zip(flat["global_leaves"], tree["global_leaves"]):
        np.testing.assert_array_equal(a, b)
    assert tree["edges"] == 3
    assert tree["partials_sent"] == 3 * 2  # one per edge per round
    assert tree["root_ingress_bytes"] < flat["root_ingress_bytes"]
    assert tree["peak_buffered_root"] <= 2
    assert tree["peak_buffered_edge"] <= 2
    assert tree["unaccounted"] == 0


@pytest.mark.locksan
def test_edge_sigkill_recovery_soak(eight_devices):
    """ISSUE 17 satellite: SIGKILL an edge mid-round; the journal-restored
    replacement dedups the re-sent uploads, folds the rest, ships the
    partial, and the run completes with the accounting identity closed
    (zero unaccounted uploads across both manager lifetimes) and the final
    global BITWISE the clean run's."""
    from fedml_tpu.cross_silo.async_soak import run_edge_kill_soak

    clean = run_edge_kill_soak(n_clients=4, fanout=2, rounds=2, kill=None,
                               seed=3)
    kill = run_edge_kill_soak(n_clients=4, fanout=2, rounds=2, kill=(0, 0, 1),
                              seed=3)
    assert kill["edge_kills"] == 1
    assert kill["edge_dedups"] >= 1  # the re-sent pre-kill upload
    assert kill["unaccounted"] == 0 and clean["unaccounted"] == 0
    assert kill["peak_buffered_root"] <= 2 and kill["peak_buffered_edge"] <= 2
    for a, b in zip(clean["global_leaves"], kill["global_leaves"]):
        np.testing.assert_array_equal(a, b)


@pytest.mark.locksan
def test_root_ingress_ratio_qsgd8_fanout8(eight_devices):
    """Acceptance floor: at fanout 8 with qsgd8 on every hop, the root's
    ingress bytes drop >= 4x vs the flat protocol on the same compressed
    wire (16 uploads/round -> 2 partials/round), and the per-hop re-encode
    beats raw partial shipping."""
    from fedml_tpu.cross_silo.async_soak import run_edge_kill_soak

    flat = run_edge_kill_soak(n_clients=16, fanout=0, rounds=2, kill=None,
                              seed=0, codec="qsgd8")
    tree = run_edge_kill_soak(n_clients=16, fanout=8, rounds=2, kill=None,
                              seed=0, codec="qsgd8", hop_codec="qsgd8")
    raw_tree = run_edge_kill_soak(n_clients=16, fanout=8, rounds=2, kill=None,
                                  seed=0)
    assert tree["edges"] == 2
    ratio = flat["root_ingress_bytes"] / max(tree["root_ingress_bytes"], 1)
    assert ratio >= 4.0, (flat["root_ingress_bytes"],
                          tree["root_ingress_bytes"])
    # the hop codec genuinely engages: compressed partials < raw partials
    assert tree["root_ingress_bytes"] < raw_tree["root_ingress_bytes"]


# ---------------------------------------------------------------------------
# end-to-end trees with real clients
# ---------------------------------------------------------------------------

@pytest.mark.locksan
def test_tree_run_trains_like_flat(eight_devices):
    """run_in_process_group with hier_fanout: real clients train, edges
    fold, the root converges — accuracy tracks the flat run (f32 grouping
    differences only)."""
    import fedml_tpu
    from fedml_tpu.cross_silo import run_in_process_group
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub

    accs = {}
    for name, extra in (
            ("flat", {"streaming_aggregation": True}),
            ("tree", {"streaming_aggregation": True, "hier_fanout": 2})):
        cfg = _hier_cfg(run_id=f"hier_e2e_{name}", comm_round=2, extra=extra)
        fedml_tpu.init(cfg)
        ds = loader.load(cfg)
        model = model_hub.create(cfg, ds.class_num)
        history = run_in_process_group(cfg, ds, model, timeout=120.0)
        assert len(history) == 2
        accs[name] = [h["test_acc"] for h in history if "test_acc" in h][-1]
    assert accs["tree"] == pytest.approx(accs["flat"], abs=0.05), accs


@pytest.mark.locksan
def test_tree_relay_mode_completes(eight_devices):
    """No streaming trigger -> edge_fold_supported is False and edges
    store-and-forward: the root still sees individual uploads (connection
    thinning only) and the run completes."""
    import fedml_tpu
    from fedml_tpu.cross_silo import run_in_process_group
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub

    cfg = _hier_cfg(run_id="hier_e2e_relay", comm_round=2,
                    frequency_of_the_test=0, extra={"hier_fanout": 2})
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)
    history = run_in_process_group(cfg, ds, model, timeout=120.0)
    assert len(history) == 2


@pytest.mark.locksan
def test_tree_depth3_completes(eight_devices):
    """Depth-3 (client -> edge -> region -> root): partials re-fold at the
    region tier and the run completes with the same accounting."""
    import fedml_tpu
    from fedml_tpu.cross_silo import run_in_process_group
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub

    cfg = _hier_cfg(run_id="hier_e2e_d3", client_num_in_total=8,
                    client_num_per_round=8, comm_round=2,
                    frequency_of_the_test=0,
                    extra={"streaming_aggregation": True, "hier_fanout": 2,
                           "hier_depth": 3})
    fedml_tpu.init(cfg)
    ds = loader.load(cfg)
    model = model_hub.create(cfg, ds.class_num)
    history = run_in_process_group(cfg, ds, model, timeout=180.0)
    assert len(history) == 2
