#!/usr/bin/env python
"""Headline benchmark: FedAvg CIFAR-10 ResNet-20 simulation throughput.

Runs the north-star recipe shape (BASELINE.md: sp_fedavg_cifar10_resnet20,
128 simulated clients) on the available accelerator and prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The reference publishes no numeric baselines (BASELINE.md); the recorded
baseline here is the reference's implicit CI ceiling translated to throughput:
its SP simulator time-multiplexes clients in python+torch — measured on this
recipe shape it processes ~O(10^2) samples/s/device on CPU and the paper-cited
GPU path is bounded by per-client python dispatch.  We report absolute
samples/sec/chip; vs_baseline compares against BENCH_BASELINE (samples/s) if
present in BASELINE.json, else 1.0.
"""

import json
import os
import sys
import time


def main():
    import jax
    import jax.numpy as jnp

    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import fedml_tpu
    from fedml_tpu.arguments import Config
    from fedml_tpu.runner import FedMLRunner

    n_clients = int(os.environ.get("BENCH_CLIENTS", "128"))
    per_round = int(os.environ.get("BENCH_CLIENTS_PER_ROUND", "8"))
    samples_per_client = int(os.environ.get("BENCH_SAMPLES_PER_CLIENT", "512"))
    batch = int(os.environ.get("BENCH_BATCH", "32"))
    rounds = int(os.environ.get("BENCH_ROUNDS", "5"))

    cfg = Config(
        dataset="cifar10",
        model="resnet20",
        client_num_in_total=n_clients,
        client_num_per_round=per_round,
        comm_round=rounds + 1,
        epochs=1,
        batch_size=batch,
        learning_rate=0.03,
        partition_method="homo",
        synthetic_train_size=n_clients * samples_per_client,
        synthetic_test_size=1024,
        frequency_of_the_test=0,
        compute_dtype="bfloat16",
        step_mode="match",
        metrics_jsonl_path="",
    )
    fedml_tpu.init(cfg)
    runner = FedMLRunner(cfg)
    sim = runner.runner

    # warmup: first round compiles
    sim.run_round()
    jax.block_until_ready(jax.tree_util.tree_leaves(sim.global_vars)[0])

    t0 = time.perf_counter()
    for _ in range(rounds):
        sim.run_round()
    jax.block_until_ready(jax.tree_util.tree_leaves(sim.global_vars)[0])
    dt = time.perf_counter() - t0

    # samples actually trained per round: sum over sampled clients of
    # epochs * steps * batch (match mode trains ceil(count/batch)*batch slots)
    steps_per_client = -(-samples_per_client // batch)
    samples_per_round = per_round * cfg.epochs * steps_per_client * batch
    n_chips = len(jax.devices())
    samples_per_sec_chip = samples_per_round * rounds / dt / n_chips

    baseline = None
    try:
        with open(os.path.join(os.path.dirname(os.path.abspath(__file__)), "BASELINE.json")) as f:
            baseline = json.load(f).get("published", {}).get("samples_per_sec_chip")
    except Exception:
        pass
    vs = samples_per_sec_chip / baseline if baseline else 1.0

    print(json.dumps({
        "metric": "fedavg_cifar10_resnet20_samples_per_sec_per_chip",
        "value": round(samples_per_sec_chip, 2),
        "unit": "samples/s/chip",
        "vs_baseline": round(vs, 3),
        "detail": {
            "clients_total": n_clients,
            "clients_per_round": per_round,
            "rounds_per_sec": round(rounds / dt, 4),
            "chips": n_chips,
            "device": str(jax.devices()[0].platform),
        },
    }))


if __name__ == "__main__":
    main()
