#!/usr/bin/env python
"""Headline benchmarks with MFU accounting.

Three benches, one JSON line:

1. **LLM train step** (the headline metric): a 542M-param llama-style
   transformer (d=2048, L=8, SwiGLU 5632, vocab 32k) trained at seq 2048 —
   the shape class where BASELINE.md's >=35% MFU target is physically
   reachable on one chip.  Metric = MFU (nominal 6N+attention FLOPs per
   token x tokens/s / chip peak); vs_baseline = MFU / 0.35 target.
2. **FedAvg CIFAR-10 ResNet-20 simulation** (the north-star FL recipe,
   BASELINE.md): samples/s/chip with 64 vmapped clients/round x batch 128
   on the clients mesh axis, plus its own (low, conv-bound) MFU — measured
   twice, unfused and with the fused Pallas conv epilogues
   (``extra.fused_blocks``, ops/pallas/fused_block.py), the round-6 A/B.
   The regression floors are asserted on the UNFUSED number only.
3. **Compressed cross-silo rounds** (round-7): the qsgd8 wire ratio on the
   ResNet-20 pytree (floor 3.5x, platform independent) plus an in-proc
   4-client e2e raw-vs-qsgd8 A/B — wall/round, wire bytes, payload
   compression ratio, peak buffered updates (streaming accumulator <= 2).
4. **Million-client population round** (ISSUE 6): a 1M-id population in the
   sharded on-disk client store, a 10k-client cohort per round streamed
   through the vmapped round step — samples/s/chip, gather/scatter seconds,
   prefetch overlap, and a cohort-bounded host-RSS ceiling (platform
   independent, floor-guarded).
5. **AOT cold start** (ISSUE 7): the same tiny recipe run in two fresh
   processes sharing one program store + compilation cache — cold populates,
   warm must deserialize (``fedml_aot_misses_total == 0``) and reach the
   first round in <= 0.5x the cold wall time (platform independent,
   floor-guarded).
6. **Buffered-async soak** (ISSUE 8): ~10k simulated clients (skewed
   latencies, injected drops) against one buffered-async server —
   versions/s (floor-guarded), staleness histogram, fold-lag p95, peak
   buffered updates <= 2, zero unaccounted drops.
7. **Chaos recovery** (ISSUE 10): the same async shape run clean and
   killed-and-recovered (recovery journal + seeded chaos on the dispatch
   leg, server hard-killed mid-run, restarted against its journal) — the
   recovered run must retain >= 0.5x the clean versions/s (floor-guarded)
   with monotone version, zero unaccounted losses, peak buffered <= 2.
8. **Continuous serving under live training** (ISSUE 11): an async server
   publishes a version-stamped model at every virtual-round bump while a
   continuous-batching worker serves HTTP traffic and hot-swaps each
   version — QPS (floor-guarded), p50/p99 latency, zero dropped requests
   across >= 3 hot swaps, final served version == final published version.
9. **Federated LoRA rounds** (ISSUE 12): 2 LLM silos exchange rank-8
   adapter deltas through the streaming cross-silo protocol, raw vs qsgd8 —
   bytes/round (adapter wire ratio floor >= 3.5x), rounds/s, peak buffered
   updates <= 2, MFU during local LoRA steps, the dense-model-vs-adapter
   wire ratio (~100x, floor >= 50x), and a streaming-vs-exact bitwise
   equality proof at staleness 0.  CPU-runnable; `--mode federated_lora`
   runs just this section with the same exit-3 / one-retry floor policy.
10. **Multi-tenant control plane** (ISSUE 14): 8 concurrent gang-scheduled
   FL jobs (per-job fleets, configs, journals, metric namespaces; one
   shared event-driven runtime) vs the 8x-sequential baseline — aggregate
   versions/s ratio (floor >= 0.5x, exit 3, one-retry) plus the p95
   round-latency interference of sharing the pool.
11. **Hierarchical aggregation tree** (ISSUE 17): 16 clients flat vs a
   fanout-8 edge tree, qsgd8 on every hop — root ingress bytes ratio
   (floor >= 4x, exit 3, one-retry), peak buffered <= 2 per hop, and an
   edge-SIGKILL leg whose journal recovery must close the accounting
   identity and reproduce the clean tree run's final global bitwise;
   `--mode hierarchy` runs just this section.

The reference publishes no numeric baselines (BASELINE.md) and has no MFU
accounting at all; the 0.35 target comes from BASELINE.json's north star.
"""

import json
import os
import sys
import time


def bench_fedavg(peak, fused=False):
    import jax

    import fedml_tpu
    from fedml_tpu.arguments import Config
    from fedml_tpu.ops import flops as flopslib
    from fedml_tpu.runner import FedMLRunner

    n_clients = int(os.environ.get("BENCH_CLIENTS", "128"))
    per_round = int(os.environ.get("BENCH_CLIENTS_PER_ROUND", "64"))
    samples_per_client = int(os.environ.get("BENCH_SAMPLES_PER_CLIENT", "512"))
    batch = int(os.environ.get("BENCH_BATCH", "128"))
    rounds = int(os.environ.get("BENCH_ROUNDS", "5"))

    cfg = Config(
        dataset="cifar10",
        model="resnet20",
        client_num_in_total=n_clients,
        client_num_per_round=per_round,
        comm_round=rounds + 1,
        epochs=1,
        batch_size=batch,
        learning_rate=0.03,
        partition_method="homo",
        synthetic_train_size=n_clients * samples_per_client,
        synthetic_test_size=1024,
        frequency_of_the_test=0,
        compute_dtype="bfloat16",
        step_mode="match",
        metrics_jsonl_path="",
        # fused=True: identical recipe, conv epilogues via the fused Pallas
        # kernel (ops/pallas/fused_block.py) — the round-6 A/B
        extra={"fused_blocks": True} if fused else {},
    )
    fedml_tpu.init(cfg)
    sim = FedMLRunner(cfg).runner

    # the round loop lives on-device (jit(scan(round))): ONE dispatch + ONE
    # host sync per chunk — per-round metric pulls would otherwise dominate
    # wall clock on a tunneled chip (host<->device latency >> round compute)
    sim.run_rounds(rounds)  # compile + warm
    t0 = time.perf_counter()
    sim.run_rounds(rounds)  # run_rounds syncs on its stacked metrics
    dt = time.perf_counter() - t0

    steps_per_client = -(-samples_per_client // batch)
    samples_per_round = per_round * cfg.epochs * steps_per_client * batch
    n_chips = len(jax.devices())
    sps_chip = samples_per_round * rounds / dt / n_chips
    flops_sample = flopslib.resnet20_cifar_train_flops_per_sample()
    mfu = (sps_chip * flops_sample / peak) if peak else None
    # Ceilings so the raw number is self-interpreting (PERF.md roofline):
    # - lane ceiling 0.214: analytic FLOP-weighted MXU output-lane bound for
    #   ResNet-20's 16/32/64 channels on the 128-wide systolic array.
    # - attainable 0.150: trace-derived estimate — the conv fusions run at
    #   0.163 MFU while sustaining 71% of HBM bandwidth (82% of round time);
    #   mandatory BN/relu/residual second passes account for the rest.
    #   See PERF.md "Per-op attribution".
    lane_ceiling, attainable = 0.214, 0.150
    result = {
        "samples_per_sec_chip": round(sps_chip, 1),
        "mfu": round(mfu, 4) if mfu is not None else None,
        "mfu_ceiling": lane_ceiling,
        "mfu_vs_ceiling": round(mfu / lane_ceiling, 3) if mfu is not None else None,
        "mfu_attainable": attainable,
        "mfu_vs_attainable": round(mfu / attainable, 3) if mfu is not None else None,
        "rounds_per_sec": round(rounds / dt, 4),
        "clients_total": n_clients,
        "clients_per_round": per_round,
        "batch": batch,
        "fused_blocks": fused,
    }
    if fused:
        result["pallas_kernels"] = _kernel_microbench(batch)
    return result


def _kernel_microbench(batch):
    """Standalone eager timings of each Pallas kernel on the flagship's
    per-stage activation shapes: populates the process-global
    ``pallas_kernel_seconds`` histogram (ROADMAP "Pallas-level timing hooks")
    and returns its summary for the BENCH json.  Eager wall time includes
    dispatch — an upper bound on the in-program cost, useful for
    kernel-vs-kernel comparison, not for round accounting."""
    import jax
    import jax.numpy as jnp

    from fedml_tpu.ops.pallas import (
        fused_bn_relu, fused_bn_residual_relu, kernel_time_summary, qsgd_int8,
    )

    key = jax.random.PRNGKey(0)
    iters = int(os.environ.get("BENCH_KERNEL_ITERS", "10"))
    for shape in [(batch, 32, 32, 16), (batch, 16, 16, 32), (batch, 8, 8, 64)]:
        y = jax.random.normal(key, shape, jnp.bfloat16)
        r = jax.random.normal(key, shape, jnp.bfloat16)
        s = jnp.full((shape[-1],), 1.1, jnp.float32)
        b = jnp.full((shape[-1],), -0.1, jnp.float32)
        g = jnp.ones(shape, jnp.bfloat16)
        for _ in range(iters):
            fused_bn_residual_relu(y, s, b, r)  # eager fwd, observed
            _, pull = jax.vjp(lambda yy, rr: fused_bn_residual_relu(yy, s, b, rr), y, r)
            pull(g)  # eager pullback -> the fused bwd kernel, also observed
            fused_bn_relu(y, s, b)
    vec = jax.random.normal(key, (1 << 20,), jnp.float32)
    for i in range(iters):
        qsgd_int8(vec, jax.random.PRNGKey(i), interpret=jax.default_backend() != "tpu")
    return kernel_time_summary()


def bench_crosssilo():
    """Compressed streaming cross-silo rounds (in-proc backend): wire bytes,
    compression ratio, and round wall time, raw vs qsgd8.

    Two measurements: (1) the qsgd8 wire ratio on the flagship ResNet-20
    pytree — the floor-guarded number (>= 3.5x, exit 3 on violation; platform
    independent, so it also runs on CPU), and (2) an e2e 4-client run whose
    payload bytes / round times / peak-buffered-update count come from the
    live registry counters and the server's streaming accumulator."""
    import jax
    import jax.numpy as jnp

    import fedml_tpu
    from fedml_tpu.arguments import Config
    from fedml_tpu.comm import codecs, wire
    from fedml_tpu.comm.base import BYTES_RECEIVED
    from fedml_tpu.comm.inproc import InProcRouter
    from fedml_tpu.cross_silo import build_client, build_server
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub, resnet

    # ---- 1) qsgd8 wire ratio on the ResNet-20 pytree (the floor) ----
    model = resnet.resnet20(10)
    variables = jax.device_get(model.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 32, 32, 3), jnp.float32), train=True))
    raw_wire = wire.encode_pytree({"model_params": variables})
    comp, _, _ = codecs.compress_pytree(variables, "qsgd8", key=jax.random.PRNGKey(1))
    comp_wire = wire.encode_pytree({"model_params": comp})
    resnet_ratio = len(raw_wire) / max(len(comp_wire), 1)

    # ---- 2) e2e in-proc rounds, raw vs qsgd8 ----
    def run(codec):
        rounds = int(os.environ.get("BENCH_CS_ROUNDS", "3"))
        extra = {"mlp_hidden": 512}
        if codec:
            extra["comm_compression"] = codec
        cfg = Config(
            training_type="cross_silo", dataset="synthetic", model="mlp",
            client_num_in_total=4, client_num_per_round=4, comm_round=rounds,
            epochs=1, batch_size=32, learning_rate=0.1, partition_method="homo",
            synthetic_train_size=2048, synthetic_test_size=512,
            frequency_of_the_test=0, compute_dtype="float32",
            metrics_jsonl_path="", run_id=f"bench_cs_{codec or 'raw'}",
            extra=extra,
        )
        fedml_tpu.init(cfg)
        ds = loader.load(cfg)
        mdl = model_hub.create(cfg, ds.class_num)
        InProcRouter.reset(cfg.run_id)
        clients = [build_client(cfg, ds, mdl, rank=r, backend="INPROC")
                   for r in range(1, 5)]
        for c in clients:
            c.run_in_thread()
        server = build_server(cfg, ds, mdl, backend="INPROC")
        bytes0 = BYTES_RECEIVED.value()
        t0 = time.perf_counter()
        try:
            server.run_until_done(timeout=300.0)
        finally:
            for c in clients:
                c.finish()
        dt = time.perf_counter() - t0
        return {
            "wall_s": round(dt, 3),
            "rounds_per_sec": round(rounds / dt, 3),
            "wire_bytes_received": int(BYTES_RECEIVED.value() - bytes0),
            "peak_buffered_updates": int(server.aggregator.peak_buffered_updates),
            "streaming": bool(server.aggregator.stream_mode),
        }

    raw = run(None)
    qsgd8 = run("qsgd8")
    return {
        "qsgd8_ratio_resnet20": round(resnet_ratio, 3),
        "raw": raw,
        "qsgd8": qsgd8,
        "payload_counters": codecs.payload_counters(),
        "e2e_bytes_reduction": round(
            raw["wire_bytes_received"] / max(qsgd8["wire_bytes_received"], 1), 3),
    }


def bench_population():
    """Million-client population round (ISSUE 6): a 1M-id population backed
    by the sharded on-disk client store, a 10k-client active cohort per
    round streamed through the MeshSimulator's vmapped round step with
    double-buffered prefetch.

    Platform independent (the population layer is host-side; the round runs
    wherever the chips are), so it runs on CPU too.  The guarded number is
    ``rss_multiple``: tracemalloc peak of the streamed rounds over the
    cohort's data bytes — the store's bounded LRU (8 shards of 4096 clients
    ≈ 3.3x a 10k cohort) plus the double-buffered gather must keep host
    memory proportional to the COHORT, never the 1M population."""
    import tempfile
    import tracemalloc

    import numpy as np
    import jax

    import fedml_tpu
    from fedml_tpu.arguments import Config
    from fedml_tpu.runner import FedMLRunner
    from fedml_tpu.population.store import GATHER_TIME, SCATTER_TIME

    population = int(os.environ.get("BENCH_POP_SIZE", "1000000"))
    cohort = int(os.environ.get("BENCH_POP_COHORT", "10000"))
    rounds = int(os.environ.get("BENCH_POP_ROUNDS", "3"))
    batch = 16
    samples_per_client = 16
    base_clients = 64

    with tempfile.TemporaryDirectory() as root:
        cfg = Config(
            dataset="synthetic", model="lr",
            client_num_in_total=base_clients, client_num_per_round=cohort,
            comm_round=rounds + 1, epochs=1, batch_size=batch,
            learning_rate=0.1, partition_method="homo",
            synthetic_train_size=base_clients * samples_per_client,
            synthetic_test_size=512, frequency_of_the_test=0,
            compute_dtype="float32", metrics_jsonl_path="",
            extra={"population_store": root, "population_size": population},
        )
        fedml_tpu.init(cfg)
        sim = FedMLRunner(cfg).runner
        sim.run_rounds(1)  # compile + warm (materializes the first shards)
        g0, g0n = GATHER_TIME.sum(), GATHER_TIME.count()
        s0 = SCATTER_TIME.sum()
        tracemalloc.start()
        t0 = time.perf_counter()
        history = sim.run_rounds(rounds)
        dt = time.perf_counter() - t0
        _, peak = tracemalloc.get_traced_memory()
        tracemalloc.stop()
        pop = sim._population
        spec = pop.store.spec
        sample_bytes = (
            int(np.prod(spec.x_shape or (1,))) * np.dtype(spec.x_dtype).itemsize
            + int(np.prod(spec.y_shape or (1,))) * np.dtype(spec.y_dtype).itemsize)
        cohort_bytes = cohort * spec.capacity * sample_bytes
        overlap = pop.pipeline.overlap_mean()
        shards_on_disk = len([f for f in os.listdir(root) if f.endswith(".npz")])

    steps_per_client = -(-samples_per_client // batch)
    samples_per_round = cohort * steps_per_client * batch
    n_chips = len(jax.devices())
    return {
        "population_clients": population,
        "cohort_clients": cohort,
        "rounds": rounds,
        "samples_per_sec_chip": round(samples_per_round * rounds / dt / n_chips, 1),
        "rounds_per_sec": round(rounds / dt, 4),
        "train_loss_last": round(float(history[-1]["train_loss"]), 4),
        "gather_seconds": round(GATHER_TIME.sum() - g0, 4),
        "gathers": int(GATHER_TIME.count() - g0n),
        "scatter_seconds": round(SCATTER_TIME.sum() - s0, 4),
        "prefetch_overlap_fraction": round(overlap, 4) if overlap is not None else None,
        "cohort_bytes": int(cohort_bytes),
        "peak_tracemalloc_bytes": int(peak),
        "rss_multiple": round(peak / cohort_bytes, 3),
        "shards_touched": shards_on_disk,
        "shard_size": spec.shard_size,
    }


def bench_aot_cold_start():
    """One phase of the cold-vs-warm start bench (ISSUE 7): run a small FL
    recipe with ``extra.aot_programs`` on, timing construction through the
    first scanned chunk.  The parent runs this TWICE in fresh processes
    against ONE shared ``BENCH_AOT_ROOT`` (program store + XLA persistent
    cache): the cold phase traces + exports + compiles everything, the warm
    phase must deserialize every program (misses == 0) and start in half the
    time.  Platform independent — startup cost is a CPU problem too."""
    root = os.environ["BENCH_AOT_ROOT"]
    # re-point the XLA persistent cache INTO the shared phase root: the cold
    # phase must not borrow the repo-root cache the test suite keeps warm
    # (nothing has compiled yet in this child, so the re-point is complete)
    from fedml_tpu.core.cache import setup_persistent_cache

    setup_persistent_cache(root)

    import fedml_tpu
    from fedml_tpu.arguments import Config
    from fedml_tpu.core.aot import (
        AOT_BUILD_TIME, AOT_EXPORTS, AOT_HITS, AOT_LOAD_TIME, AOT_MISSES,
    )
    from fedml_tpu.runner import FedMLRunner

    # Recipe shape matters: the measured quantity is (fixed + load) /
    # (fixed + build), where fixed = eager model.init + dataset gen + the
    # round's execution — costs the store cannot remove.  ResNet-20 at 2
    # clients x 1 local step keeps execution ~1.5 s while its scanned-round
    # trace+compile is ~11 s, so the ratio isolates what the store saves;
    # wider/shallower recipes (mlp) are fixed-cost-dominated and read ~1.
    rounds = int(os.environ.get("BENCH_AOT_ROUNDS", "1"))
    t0 = time.perf_counter()
    cfg = Config(
        dataset="cifar10", model="resnet20",
        client_num_in_total=2, client_num_per_round=2, comm_round=rounds,
        epochs=1, batch_size=8, learning_rate=0.1, partition_method="homo",
        synthetic_train_size=2 * 8, synthetic_test_size=32,
        frequency_of_the_test=0, compute_dtype="float32",
        metrics_jsonl_path="",
        extra={"aot_programs": True,
               "aot_programs_dir": os.path.join(root, "aot_programs")},
    )
    fedml_tpu.init(cfg)
    sim = FedMLRunner(cfg).runner
    sim.warm_start()        # the store's warm() path: every chunk program
    sim.run_rounds(rounds)  # resolved before round 0
    start_s = time.perf_counter() - t0
    return {
        "start_to_first_round_s": round(start_s, 3),
        "rounds": rounds,
        "hits": int(AOT_HITS.value()),
        "misses": int(AOT_MISSES.value()),
        "exports": int(AOT_EXPORTS.value()),
        "build_seconds": round(AOT_BUILD_TIME.sum(), 3),
        "load_seconds": round(AOT_LOAD_TIME.sum(), 4),
    }


def bench_async_soak():
    """Buffered-async aggregation soak (ISSUE 8): ~10k simulated clients
    (event-scheduled, skewed lognormal latencies, 2% injected upload drops)
    against ONE real AsyncFedMLServerManager over the in-proc fabric — real
    wire bytes, real staleness-decayed folds, K-arrival virtual rounds.

    Platform independent (host-side server path), so it runs on CPU too.
    Floor-guarded on versions/s; the acceptance bounds (peak buffered
    updates <= 2, zero unaccounted drops) are asserted as violations as
    well — a leaking fold buffer is a regression, not a statistic."""
    from fedml_tpu.cross_silo.async_soak import run_soak

    return run_soak(
        n_clients=int(os.environ.get("BENCH_ASYNC_CLIENTS", "10000")),
        concurrency=int(os.environ.get("BENCH_ASYNC_CONCURRENCY", "1024")),
        buffer_k=int(os.environ.get("BENCH_ASYNC_BUFFER_K", "64")),
        versions=int(os.environ.get("BENCH_ASYNC_VERSIONS", "20")),
        drop_prob=0.02, latency_mean_s=0.005, redispatch_timeout_s=2.0,
        seed=0, timeout_s=900.0,
    )


def bench_slo():
    """SLO watchdog on a clean leg (ISSUE 16): the buffered-async soak with
    a declarative SLO suite live on the server's timer wheel — thresholds
    generous enough that a HEALTHY run cannot breach them.  The guarded
    numbers: the engine actually ticked (evaluations > 0) and recorded ZERO
    breaches — a breach here is either a real regression or a broken
    default, both of which must fail the bench, not pass silently.

    Platform independent (host-side server path + registry snapshots)."""
    from fedml_tpu.cross_silo.async_soak import run_soak

    specs = {
        # streaming fold keeps peak buffered <= 2; 64 is "the fold broke"
        "buffered_peak": {"metric": "fedml_crosssilo_buffered_updates_peak",
                          "stat": "value", "op": "<=", "threshold": 64},
        # fold lag p95 in the seconds, not minutes
        "fold_lag_p95": {"metric": "fedml_async_fold_lag_seconds",
                         "stat": "p95", "op": "<=", "threshold": 120.0},
        # dedup pressure: re-uploads must stay a small fraction of arrivals
        "dedup_ratio": {"metric": "fedml_crosssilo_uploads_deduped_total",
                        "per": "fedml_async_arrivals_total",
                        "stat": "value", "op": "<=", "threshold": 0.9},
        # exercises the rate stat (two-tick delta) without ever firing
        "versions_rate": {"metric": "fedml_async_virtual_rounds_total",
                          "stat": "rate", "op": ">=", "threshold": 0.0},
    }
    res = run_soak(
        n_clients=int(os.environ.get("BENCH_SLO_CLIENTS", "2000")),
        concurrency=256, buffer_k=32,
        versions=int(os.environ.get("BENCH_SLO_VERSIONS", "10")),
        drop_prob=0.02, latency_mean_s=0.003, redispatch_timeout_s=2.0,
        seed=0, timeout_s=600.0,
        extra_flags={"slo_specs": specs, "slo_interval_s": 0.2})
    return res


def bench_chaos():
    """Crash recovery under chaos (ISSUE 10): the same buffered-async shape
    run twice — CLEAN (no journal, no chaos) and KILL-AND-RECOVER (recovery
    journal on, every chaos fault class live on the dispatch leg, the server
    hard-killed mid-run and restarted against its journal).  The guarded
    number is ``recovery_ratio`` = recovered-run versions/s over the clean
    run's: recovery must cost at most half the throughput, or restarts are
    not production-viable.  Platform independent (host-side server path).

    Both runs pay the journal's per-round snapshot (the clean leg runs with
    the journal ON, kill-free), so the ratio isolates what the CRASH costs —
    re-discovery, epoch fencing, watchdog re-issue — not what durability
    costs.  Both runs also re-assert the correctness invariants (completion,
    monotone version, zero unaccounted losses, peak buffered <= 2) as floor
    violations — a recovery that loses work silently is a regression, not a
    statistic.

    ISSUE-13 adds the CLIENT-side mirror: ``client_kill_recover`` runs REAL
    in-proc clients with two of them hard-killed mid-run and journal-resumed,
    guarded by ``client_kill_ratio`` (recovered/clean versions/s, floor
    CLIENT_KILL_RECOVERY_RATIO_FLOOR) plus the client accounting identity
    (kills == journal resumes, zero unaccounted restarts)."""
    import shutil
    import tempfile

    from fedml_tpu.cross_silo.async_soak import (
        run_client_kill_soak, run_kill_recover_soak, run_soak,
    )

    clients = int(os.environ.get("BENCH_CHAOS_CLIENTS", "2000"))
    concurrency = int(os.environ.get("BENCH_CHAOS_CONCURRENCY", "256"))
    buffer_k = int(os.environ.get("BENCH_CHAOS_BUFFER_K", "32"))
    versions = int(os.environ.get("BENCH_CHAOS_VERSIONS", "12"))
    common = dict(n_clients=clients, concurrency=concurrency,
                  buffer_k=buffer_k, versions=versions, drop_prob=0.02,
                  latency_mean_s=0.003, redispatch_timeout_s=2.0, seed=0,
                  timeout_s=600.0)
    clean_journal = tempfile.mkdtemp(prefix="bench_chaos_clean_")
    try:
        clean = run_soak(journal_dir=clean_journal, **common)
    finally:
        shutil.rmtree(clean_journal, ignore_errors=True)
    recovered = run_kill_recover_soak(**common)
    ratio = (recovered["versions_per_sec"] / clean["versions_per_sec"]
             if clean["versions_per_sec"] else None)
    # ISSUE-13 leg: REAL in-proc clients, two of them hard-killed mid-run
    # and journal-resumed — same shape run clean (zero kills) for the ratio
    # denominator, so the guarded number isolates what client churn costs
    ck_kwargs = dict(
        n_clients=int(os.environ.get("BENCH_CLIENTKILL_CLIENTS", "6")),
        versions=int(os.environ.get("BENCH_CLIENTKILL_VERSIONS", "6")),
        buffer_k=3, concurrency=3, redispatch_timeout_s=1.0, seed=0,
        timeout_s=300.0)
    ck_clean = run_client_kill_soak(kill_marks=(), **ck_kwargs)
    ck_recovered = run_client_kill_soak(kill_marks=((2, 1), (4, 2)), **ck_kwargs)
    ck_ratio = (ck_recovered["versions_per_sec"] / ck_clean["versions_per_sec"]
                if ck_clean["versions_per_sec"] else None)
    return {
        "clean": clean,
        "recovered": recovered,
        "recovery_ratio": round(ratio, 4) if ratio is not None else None,
        "client_kill_clean": ck_clean,
        "client_kill_recover": ck_recovered,
        "client_kill_ratio": round(ck_ratio, 4) if ck_ratio is not None else None,
    }


def bench_serving():
    """Continuous-batching serving fleet under LIVE training (ISSUE 11): a
    buffered-async server runs a small simulated fleet and publishes a
    version-stamped model at every virtual-round bump
    (``extra.model_publish_dir``), while an in-process ServingWorker serves
    HTTP predict traffic through the micro-batcher and hot-swaps each
    published version between micro-batches.

    Platform independent (host-side serving path), so it runs on CPU too.
    The guarded numbers: QPS (floor, exit 3, one-retry policy), zero
    dropped requests across >= 3 hot swaps (503 backpressure answers are
    retried by the load generator and counted separately — a 503 is
    explicit flow control, not a drop), and the final served version must
    equal the final published version."""
    import json as _json
    import shutil
    import tempfile
    import threading
    import urllib.error
    import urllib.request

    import numpy as np

    from fedml_tpu.cross_silo.async_soak import run_soak
    from fedml_tpu.serving.batcher import (
        EXECUTE_TIME, QUEUE_TIME, percentile_from_histogram,
    )
    from fedml_tpu.serving.publisher import ManifestWatcher
    from fedml_tpu.serving.worker import ServingWorker

    versions = int(os.environ.get("BENCH_SERVING_VERSIONS", "6"))
    load_threads = int(os.environ.get("BENCH_SERVING_THREADS", "4"))
    rows_per_request = int(os.environ.get("BENCH_SERVING_ROWS", "2"))
    publish_dir = tempfile.mkdtemp(prefix="bench_serving_pub_")
    try:
        # -- live training: async server publishing at every version bump.
        # buffer_k == concurrency + a real per-client latency means each
        # virtual round waits one full dispatch wave (~latency_mean), so
        # version bumps are spaced far enough apart for the worker's poll
        # to hot-swap most of them individually.
        soak_out: dict = {}
        soak_err: list = []

        def _train():
            try:
                soak_out.update(run_soak(
                    n_clients=64, concurrency=16, buffer_k=16,
                    versions=versions, drop_prob=0.0, latency_mean_s=0.25,
                    latency_sigma=0.25, redispatch_timeout_s=5.0, seed=0,
                    timeout_s=300.0,
                    extra_flags={"model_publish_dir": publish_dir}))
            except Exception as e:  # surfaced after the load stops
                soak_err.append(e)

        trainer = threading.Thread(target=_train, daemon=True)
        trainer.start()

        # -- the serving worker bootstraps from the manifest (version 0 is
        # published at send_init) and polls fast enough to swap per bump
        worker = ServingWorker(
            "lr", 10, publish_dir=publish_dir, max_batch=32, max_queue=256,
            flush_ms=1.0, poll_s=0.02, bootstrap_timeout_s=60.0)
        port = worker.start(block=False)
        feat = worker.predictor.feature_shape[0]

        # -- load generation while training publishes versions
        stop_load = threading.Event()
        lock = threading.Lock()
        latencies: list = []
        counts = {"ok": 0, "dropped": 0, "backpressure": 0}
        body = _json.dumps(
            {"inputs": np.zeros((rows_per_request, feat)).tolist()}).encode()

        def _load():
            while not stop_load.is_set():
                req = urllib.request.Request(
                    f"http://127.0.0.1:{port}/predict", data=body,
                    headers={"Content-Type": "application/json"})
                t0 = time.perf_counter()
                try:
                    with urllib.request.urlopen(req, timeout=30.0) as r:
                        _json.loads(r.read())
                    dt = time.perf_counter() - t0
                    with lock:
                        counts["ok"] += 1
                        latencies.append(dt)
                except urllib.error.HTTPError as e:
                    if e.code == 503:
                        # explicit backpressure: honor Retry-After, retry
                        retry = float(e.headers.get("Retry-After", "1") or 1)
                        with lock:
                            counts["backpressure"] += 1
                        time.sleep(min(retry, 1.0))
                    else:
                        with lock:
                            counts["dropped"] += 1
                except Exception:
                    with lock:
                        counts["dropped"] += 1

        threads = [threading.Thread(target=_load, daemon=True)
                   for _ in range(load_threads)]
        t_load0 = time.perf_counter()
        for t in threads:
            t.start()
        trainer.join(timeout=360.0)
        # settle: let the worker's poll adopt the final published version
        watcher = ManifestWatcher(publish_dir)
        manifest = watcher.read_manifest() or {}
        deadline = time.monotonic() + 10.0
        while (worker.served_version < int(manifest.get("version", 0))
               and time.monotonic() < deadline):
            time.sleep(0.02)
        stop_load.set()
        for t in threads:
            t.join(timeout=10.0)
        load_wall = time.perf_counter() - t_load0
        stats = worker.stats()
        worker.stop()
        if soak_err:
            raise soak_err[0]

        lat = np.asarray(sorted(latencies)) if latencies else np.zeros(1)
        return {
            "versions_published": int(manifest.get("version", -1)),
            "served_version_final": int(stats["served_version"]),
            "hot_swaps": int(stats["swaps"]),
            "rollbacks": int(stats["rollbacks"]),
            "requests_ok": counts["ok"],
            "requests_backpressure_503": counts["backpressure"],
            "dropped_requests": counts["dropped"] + int(stats["errored"]),
            "qps": round(counts["ok"] / max(load_wall, 1e-9), 2),
            "latency_p50_ms": round(float(np.percentile(lat, 50)) * 1e3, 3),
            "latency_p99_ms": round(float(np.percentile(lat, 99)) * 1e3, 3),
            "batch_fill_ewma": stats["batch_fill_ewma"],
            "batches": int(stats["batches"]),
            "queue_p50_s": percentile_from_histogram(QUEUE_TIME, 0.50),
            "execute_p50_s": percentile_from_histogram(EXECUTE_TIME, 0.50),
            "load_threads": load_threads,
            "rows_per_request": rows_per_request,
            "load_wall_s": round(load_wall, 3),
            "training": {
                "versions": soak_out.get("versions"),
                "versions_per_sec": soak_out.get("versions_per_sec"),
                "arrivals": soak_out.get("arrivals"),
            },
        }
    finally:
        shutil.rmtree(publish_dir, ignore_errors=True)


def bench_federated_lora():
    """Federated LoRA rounds on the fast path (ISSUE 12): 2 LLM silos fine-
    tune a shared tiny transformer and exchange ONLY rank-8 adapter deltas
    through the cross-silo streaming protocol, raw vs qsgd8.

    Four measurements: (1) the qsgd8 wire ratio on the adapter tree (floor
    >= 3.5x, platform independent — per-tree low-rank compression floor);
    (2) the dense-model-vs-adapter wire ratio (the ~100x saving the
    unitedllm module docstring promises; floor >= 50x); (3) an e2e in-proc
    raw-vs-qsgd8 A/B — bytes/round, rounds/s, peak buffered updates (<= 2);
    (4) MFU during the silo's local LoRA steps.  Plus the bitwise proof:
    streaming LoRA aggregation == exact buffer-all at staleness 0."""
    import jax
    import numpy as np

    import fedml_tpu
    from fedml_tpu.arguments import Config
    from fedml_tpu.comm import codecs, wire
    from fedml_tpu.comm.base import BYTES_RECEIVED
    from fedml_tpu.comm.inproc import InProcRouter
    from fedml_tpu.comm.message import Message
    from fedml_tpu.cross_silo import message_define as md
    from fedml_tpu.data import loader
    from fedml_tpu.llm.unitedllm import (
        LoRAAggregator, LoRASiloTrainer, run_unitedllm_process_group,
    )
    from fedml_tpu.ops import flops as flopslib

    rounds = int(os.environ.get("BENCH_LORA_ROUNDS", "2"))
    silos = int(os.environ.get("BENCH_LORA_SILOS", "2"))
    lora_r = 8
    # q/k/v projections only: every rank-8 factor is exactly one qsgd8 block
    # (1024 elements), so the compressed tree carries zero padding waste
    targets = r".*attn/w[qkv]/kernel"

    def make_cfg(run_id, extra=None):
        e = {"unitedllm": True, "lora_r": lora_r, "lora_targets": targets,
             "streaming_aggregation": True}
        e.update(extra or {})
        return Config(
            training_type="cross_cloud", dataset="shakespeare",
            model="transformer", client_num_in_total=silos,
            client_num_per_round=silos, comm_round=rounds, epochs=1,
            batch_size=4, learning_rate=0.01,
            synthetic_train_size=64 * silos, synthetic_test_size=32,
            frequency_of_the_test=0, compute_dtype="float32",
            metrics_jsonl_path="", run_id=run_id, extra=e,
        )

    # ---- 1) static wire ratios on the adapter tree (the floors) ----
    cfg0 = make_cfg("bench_lora_static")
    fedml_tpu.init(cfg0)
    ds = loader.load(cfg0)
    agg = LoRAAggregator(cfg0, ds)
    r_state = np.random.RandomState(0)
    adapters = jax.tree_util.tree_map(
        lambda x: r_state.randn(*np.shape(x)).astype(np.float32),
        jax.device_get(agg.global_vars))
    raw_wire = len(wire.encode_pytree({"model_params": adapters}))
    comp, _, _ = codecs.compress_pytree(
        adapters, "qsgd8", key=jax.random.PRNGKey(1),
        min_elems=codecs.LOW_RANK_MIN_COMPRESS_ELEMS)
    comp_wire = len(wire.encode_pytree({"model_params": comp}))
    dense_wire = len(wire.encode_pytree(
        {"model_params": jax.device_get(agg.base_params)}))
    qsgd8_ratio = raw_wire / max(comp_wire, 1)
    dense_ratio = dense_wire / max(comp_wire, 1)

    # ---- 2) streaming == exact, bitwise at staleness 0 ----
    exact = LoRAAggregator(make_cfg("bench_lora_ex", {"streaming_aggregation": False}), ds)
    stream = LoRAAggregator(make_cfg("bench_lora_st"), ds)
    base = jax.device_get(exact.global_vars)
    for cid in (1, 2):
        rs = np.random.RandomState(cid)
        params = jax.tree_util.tree_map(
            lambda x: np.asarray(x, np.float32)
            + rs.randn(*np.shape(x)).astype(np.float32), base)
        exact.add_local_trained_result(cid, params, 64.0)
        msg = Message(md.MSG_TYPE_C2S_SEND_MODEL_TO_SERVER, cid, 0)
        msg.add_params(md.MSG_ARG_KEY_MODEL_PARAMS, params)
        assert stream.ingest_streaming(cid, Message.decode(msg.encode()), 64.0,
                                       is_delta=False)
    bitwise = all(
        np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(exact.aggregate(0))),
                        jax.tree_util.tree_leaves(jax.device_get(stream.aggregate(0)))))

    # ---- 3) MFU during local LoRA steps ----
    trainer = LoRASiloTrainer(cfg0, ds, ds.train_x[ds.client_idx[0]],
                              ds.train_y[ds.client_idx[0]])
    lora0 = jax.tree_util.tree_map(np.asarray, adapters)
    from fedml_tpu.core import rng as rnglib

    seed_key = rnglib.root_key(cfg0.random_seed)
    trainer.train(lora0, 0, seed_key, 0)  # compile + warm
    t0 = time.perf_counter()
    trainer.train(lora0, 1, seed_key, 0)
    dt_local = time.perf_counter() - t0
    seq = int(ds.train_x.shape[1])
    tokens = int(trainer._steps) * cfg0.batch_size * seq
    n_params = sum(int(np.asarray(l).size) for l in jax.tree_util.tree_leaves(
        jax.device_get(trainer.base_params))) + sum(
        int(np.asarray(l).size) for l in jax.tree_util.tree_leaves(lora0))
    tcfg = trainer.model.cfg
    flops_tok = flopslib.transformer_train_flops_per_token(
        n_params, tcfg.vocab_size * tcfg.d_model, tcfg.n_layers,
        tcfg.d_model, seq)
    peak = flopslib.device_peak_flops(jax.devices()[0])
    tps_chip = tokens / dt_local / len(jax.devices())
    local = {
        "tokens_per_sec_chip": round(tps_chip, 1),
        "mfu": round(tps_chip * flops_tok / peak, 4) if peak else None,
        "n_params_m": round(n_params / 1e6, 3),
        "seq_len": seq,
        "local_steps": int(trainer._steps),
    }

    # ---- 4) e2e in-proc rounds, raw vs qsgd8 ----
    def run(codec):
        extra = {"comm_compression": codec} if codec else {}
        cfg = make_cfg(f"bench_lora_{codec or 'raw'}", extra)
        fedml_tpu.init(cfg)
        run_ds = loader.load(cfg)
        bytes0 = BYTES_RECEIVED.value()
        t0 = time.perf_counter()
        _, server = run_unitedllm_process_group(cfg, run_ds, backend="INPROC",
                                                timeout=600.0)
        dt = time.perf_counter() - t0
        return {
            "wall_s": round(dt, 3),
            "rounds_per_sec": round(rounds / dt, 3),
            "wire_bytes_received": int(BYTES_RECEIVED.value() - bytes0),
            "bytes_per_round": int((BYTES_RECEIVED.value() - bytes0) / rounds),
            "peak_buffered_updates": int(server.aggregator.peak_buffered_updates),
            "streaming": bool(server.aggregator.stream_mode),
        }

    raw = run(None)
    qsgd8 = run("qsgd8")
    return {
        "rounds": rounds,
        "silos": silos,
        "lora_r": lora_r,
        "qsgd8_ratio_lora": round(qsgd8_ratio, 3),
        "adapter_wire_bytes_raw": int(raw_wire),
        "adapter_wire_bytes_qsgd8": int(comp_wire),
        "dense_model_bytes": int(dense_wire),
        "dense_vs_adapter_ratio": round(dense_ratio, 1),
        "stream_exact_bitwise": bool(bitwise),
        "peak_buffered_updates": max(raw["peak_buffered_updates"],
                                     qsgd8["peak_buffered_updates"]),
        "raw": raw,
        "qsgd8": qsgd8,
        "e2e_bytes_reduction": round(
            raw["wire_bytes_received"] / max(qsgd8["wire_bytes_received"], 1), 3),
        "local_lora": local,
        "payload_counters": codecs.payload_counters(),
    }


def bench_multi_tenant():
    """Multi-tenant control plane (ISSUE 14): N concurrent buffered-async FL
    jobs — each with its own simulated client fleet, per-job config/metric
    namespace, and journal root — gang-scheduled onto ONE host pool through
    the shared event-driven runtime, versus the SAME N jobs run one at a
    time through the identical gated machinery.

    Platform independent (host-side control plane), so it runs on CPU too.
    The guarded number is ``throughput_ratio`` = concurrent aggregate
    versions/s over the Nx-sequential aggregate: packing N tenants onto one
    pool must retain at least half the sequential aggregate throughput
    (floor MULTI_TENANT_THROUGHPUT_RATIO_FLOOR, exit 3, one-retry) — in
    practice overlap wins (>1x) because one tenant's dispatch-wave latency
    hides behind a sibling's folds.  ``round_hold_p95_interference`` is the
    p95 round-latency cost of sharing: concurrent p95 hold over sequential
    p95 hold."""
    from fedml_tpu.sched.multi_tenant import run_multi_tenant_soak

    n_jobs = int(os.environ.get("BENCH_MT_JOBS", "8"))
    versions = int(os.environ.get("BENCH_MT_VERSIONS", "6"))
    slots = int(os.environ.get("BENCH_MT_SLOTS", "2"))
    common = dict(
        clients_per_job=int(os.environ.get("BENCH_MT_CLIENTS_PER_JOB", "64")),
        concurrency=int(os.environ.get("BENCH_MT_CONCURRENCY", "16")),
        buffer_k=int(os.environ.get("BENCH_MT_BUFFER_K", "16")),
        latency_mean_s=0.002, seed=0, timeout_s=600.0, slots=slots)
    sequential = run_multi_tenant_soak(n_jobs, versions, concurrent=False,
                                       **common)
    concurrent = run_multi_tenant_soak(n_jobs, versions, concurrent=True,
                                       **common)
    ratio = (concurrent["aggregate_versions_per_sec"]
             / max(sequential["aggregate_versions_per_sec"], 1e-9))
    interference = None
    if concurrent["round_hold_p95_s"] and sequential["round_hold_p95_s"]:
        interference = round(concurrent["round_hold_p95_s"]
                             / sequential["round_hold_p95_s"], 4)
    return {
        "jobs": n_jobs,
        "slots": slots,
        "versions_per_job": versions,
        "concurrent_aggregate_versions_per_sec":
            concurrent["aggregate_versions_per_sec"],
        "sequential_aggregate_versions_per_sec":
            sequential["aggregate_versions_per_sec"],
        "throughput_ratio": round(ratio, 4),
        "round_hold_p95_s_concurrent": concurrent["round_hold_p95_s"],
        "round_hold_p95_s_sequential": sequential["round_hold_p95_s"],
        "round_hold_p95_interference": interference,
        "concurrent_wall_s": concurrent["wall_s"],
        "sequential_wall_s": sequential["wall_s"],
        "rounds_granted_concurrent": concurrent["rounds_granted"],
        "scheduler": concurrent["summary"]["scheduler"],
        "jobs_detail": {j: {"rounds": s["rounds"]}
                        for j, s in concurrent["summary"]["jobs"].items()},
    }


def bench_fleet():
    """One fleet for everything (ISSUE 19): partition an 8-device host mesh
    into 4 disjoint 2-device submeshes — every job leases its own devices
    through the device-slot scheduler and rounds run genuinely concurrently
    — versus the SAME 4 jobs run one at a time on the full mesh.

    Three guarantees ride the one measurement.  (1) ``throughput_ratio`` =
    concurrent aggregate versions/s over the 4x-sequential aggregate, floor
    FLEET_THROUGHPUT_RATIO_FLOOR (exit 3, one-retry): a fleet partition
    must BEAT time-sharing, not merely match it, because nothing is ever
    waiting for a slot.  (2) Per-job bitwise parity: a sync job run on its
    submesh LEASE inside the 4-tenant plane produces bit-for-bit the final
    global of the same job run ALONE on an identically shaped dedicated
    mesh — the submesh is a real mesh to the job (NamedShardings, pjit
    server fold, AOT fingerprints), not an approximation of one.  (3) Zero
    cross-tenant bleed: every lease grant, journal step, and published
    manifest is attributable to exactly one tenant.

    The child process forces an 8-device CPU platform (``_run_one``), so
    the measured ratio is a CPU number on every host — the partition win
    is a host-side control-plane property, not a chip property."""
    import shutil
    import tempfile

    import jax
    import numpy as np

    from fedml_tpu.obs import registry as obsreg
    from fedml_tpu.parallel import mesh as meshlib
    from fedml_tpu.sched.multi_tenant import run_multi_tenant_soak
    from fedml_tpu.serving.publisher import MANIFEST_NAME

    n_jobs = int(os.environ.get("BENCH_FLEET_JOBS", "4"))
    versions = int(os.environ.get("BENCH_FLEET_VERSIONS", "3"))
    shape = os.environ.get("BENCH_FLEET_SUBMESH", "clients:2")
    names, sizes = meshlib.parse_mesh_shape(shape)
    per_job = int(np.prod(sizes))
    n_devices = len(jax.devices())
    if per_job * n_jobs > n_devices:
        raise RuntimeError(
            f"fleet bench needs {per_job * n_jobs} devices for {n_jobs} "
            f"submeshes of {shape!r}, have {n_devices} "
            "(XLA_FLAGS=--xla_force_host_platform_device_count=8 missing?)")

    root = tempfile.mkdtemp(prefix="bench_fleet_")
    try:
        def leg(concurrent):
            tag = "conc" if concurrent else "seq"
            return run_multi_tenant_soak(
                n_jobs, versions, concurrent=concurrent, slots=1,
                clients_per_job=int(
                    os.environ.get("BENCH_FLEET_CLIENTS_PER_JOB", "8")),
                concurrency=4, buffer_k=4, latency_mean_s=0.002, seed=0,
                journal_root=os.path.join(root, f"journal_{tag}"),
                submesh_shape=(shape if concurrent else None),
                extra_flags={
                    "server_shard_fold": True,
                    "model_publish_dir": os.path.join(root, f"pub_{tag}"),
                },
                timeout_s=600.0)

        sequential = leg(False)
        lease_fam = obsreg.REGISTRY.get("fedml_fleet_lease_grants_total")
        lease0 = {f"t{i}": (lease_fam.value(job=f"t{i}") if lease_fam else 0.0)
                  for i in range(n_jobs)}
        concurrent = leg(True)
        ratio = (concurrent["aggregate_versions_per_sec"]
                 / max(sequential["aggregate_versions_per_sec"], 1e-9))

        # -- cross-tenant bleed: metrics ----------------------------------
        # every lease grant is attributable to exactly one tenant, and each
        # tenant saw exactly its own virtual rounds' worth
        lease_fam = obsreg.REGISTRY.get("fedml_fleet_lease_grants_total")
        lease_grants = {
            f"t{i}": int(lease_fam.value(job=f"t{i}") - lease0[f"t{i}"])
            for i in range(n_jobs)} if lease_fam else {}
        metric_bleed_clean = all(
            lease_grants.get(f"t{i}") == versions for i in range(n_jobs))
        throttled_fam = obsreg.REGISTRY.get("fedml_fleet_quota_throttled_total")
        quota_throttled = sum(
            throttled_fam.value(job=f"t{i}") for i in range(n_jobs)
        ) if throttled_fam else 0.0

        # -- cross-tenant bleed: journals ---------------------------------
        # each tenant's steps landed ONLY under its own job dir, and the
        # journal root holds nothing but the n_jobs job dirs
        jdir = os.path.join(root, "journal_conc")
        expected_dirs = sorted(f"job_t{i}" for i in range(n_jobs))
        journal_bleed_clean = (
            sorted(os.listdir(jdir)) == expected_dirs
            and all(os.listdir(os.path.join(jdir, d, "server"))
                    for d in expected_dirs))

        # -- cross-tenant bleed: publications -----------------------------
        # each tenant's manifest names ITS run id at the final version, and
        # the publish root holds nothing but the n_jobs job dirs
        pdir = os.path.join(root, "pub_conc")
        publish_bleed_clean = sorted(os.listdir(pdir)) == expected_dirs
        for i in range(n_jobs):
            mpath = os.path.join(pdir, f"job_t{i}", MANIFEST_NAME)
            try:
                with open(mpath, encoding="utf-8") as f:
                    manifest = json.load(f)
            except OSError:
                publish_bleed_clean = False
                continue
            if (manifest.get("version") != versions
                    or not str(manifest.get("run_id", "")).endswith(
                        f"_job_t{i}")):
                publish_bleed_clean = False
    finally:
        shutil.rmtree(root, ignore_errors=True)

    parity = _fleet_parity_leg(names, sizes, n_jobs)

    return {
        "jobs": n_jobs,
        "versions_per_job": versions,
        "devices": n_devices,
        "submesh": concurrent["submesh"],
        "concurrent_aggregate_versions_per_sec":
            concurrent["aggregate_versions_per_sec"],
        "sequential_aggregate_versions_per_sec":
            sequential["aggregate_versions_per_sec"],
        "throughput_ratio": round(ratio, 4),
        "concurrent_wall_s": concurrent["wall_s"],
        "sequential_wall_s": sequential["wall_s"],
        "rounds_granted_concurrent": concurrent["rounds_granted"],
        "lease_grants": lease_grants,
        "quota_throttled_total": quota_throttled,
        "metric_bleed_clean": bool(metric_bleed_clean),
        "journal_bleed_clean": bool(journal_bleed_clean),
        "publish_bleed_clean": bool(publish_bleed_clean),
        "scheduler": concurrent["summary"]["scheduler"],
        "jobs_detail": {j: {"rounds": s["rounds"]}
                        for j, s in concurrent["summary"]["jobs"].items()},
        **parity,
    }


def _fleet_parity_leg(names, sizes, n_jobs):
    """Submesh-vs-dedicated bitwise parity: each of ``n_jobs`` DISTINCT sync
    jobs (per-job learning rates, so the finals genuinely differ) runs once
    on its submesh lease inside the n_jobs-tenant plane, and once ALONE on
    an identically shaped dedicated mesh.  Hard requirement: the two finals
    are bit-for-bit equal per job — which also proves zero cross-tenant
    bleed at the model-bytes layer, since a single leaked fold would break
    the identity."""
    import jax
    import numpy as np

    import fedml_tpu
    from fedml_tpu.arguments import Config
    from fedml_tpu.comm.inproc import InProcRouter
    from fedml_tpu.cross_silo import build_client, build_server
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub
    from fedml_tpu.parallel import mesh as meshlib
    from fedml_tpu.sched.multi_tenant import MultiTenantControlPlane

    per_job = int(np.prod(sizes))

    def job_cfg(i, run_id):
        return Config(
            training_type="cross_silo", dataset="synthetic", model="lr",
            client_num_in_total=2, client_num_per_round=2, comm_round=2,
            epochs=1, batch_size=16, learning_rate=0.05 + 0.02 * i,
            partition_method="homo", synthetic_train_size=64,
            synthetic_test_size=32, frequency_of_the_test=0,
            compute_dtype="float32", metrics_jsonl_path="", run_id=run_id,
            extra={"streaming_aggregation": True, "server_shard_fold": True})

    def final_bytes(server):
        from fedml_tpu.comm import wire

        return wire.encode_pytree(jax.device_get(
            server.aggregator.global_vars))

    # fleet leg: all jobs in ONE plane, each round folding on its own lease
    plan = meshlib.carve_submeshes(names, sizes, n_jobs)
    plane = MultiTenantControlPlane(slots=1, plan=plan)
    fleet_finals = {}
    try:
        jobs = []
        for i in range(n_jobs):
            cfg = job_cfg(i, f"fleetpar_c_{i}")
            fedml_tpu.init(cfg)
            jobs.append(plane.admit(cfg, job_id=f"t{i}"))
        plane.start()
        plane.run_until_done(timeout=300.0)
        for i, job in enumerate(jobs):
            fleet_finals[i] = final_bytes(job.server)
    finally:
        plane.close()

    # dedicated leg: the same job alone on a fresh mesh of the same shape
    parity_jobs = {}
    for i in range(n_jobs):
        cfg = job_cfg(i, f"fleetpar_d_{i}")
        fedml_tpu.init(cfg)
        ds = loader.load(cfg)
        model = model_hub.create(cfg, ds.class_num)
        dmesh = meshlib.make_mesh(names, sizes,
                                  devices=jax.devices()[:per_job])
        InProcRouter.reset(cfg.run_id)
        clients = [build_client(cfg, ds, model, rank=r, backend="INPROC")
                   for r in range(1, cfg.client_num_in_total + 1)]
        for c in clients:
            c.run_in_thread()
        server = build_server(cfg, ds, model, backend="INPROC", mesh=dmesh)
        try:
            server.run_until_done(timeout=120.0)
            for c in clients:
                c.done.wait(5.0)
            parity_jobs[f"t{i}"] = bool(fleet_finals[i] == final_bytes(server))
        finally:
            for c in clients:
                c.finish()
            server.finish()
            InProcRouter.reset(cfg.run_id)

    return {
        "parity_jobs": parity_jobs,
        "parity_bitwise": bool(parity_jobs
                               and all(parity_jobs.values())),
        # distinct per-job finals: identical blobs would mean the parity
        # check could not see a cross-tenant leak
        "parity_finals_distinct": bool(
            len(set(fleet_finals.values())) == n_jobs),
    }


def bench_secagg():
    """Streaming secure aggregation (ISSUE 15): trust off the memory cliff.

    Three measurements. (1) The 10k simulated-cohort soak: masked uploads
    fold one at a time into the field accumulator — peak buffered <= 2
    asserted at the full cohort, versions/s with SecAgg on vs off (floor:
    the secure path keeps >= half the plain throughput at a deliberately
    cheap proxy local step — real training makes the ratio approach 1), and
    the streamed-masked == exact-unmasked INTEGER identity.  (2) bytes/round
    of quantize-then-mask (qsgd8 grid in a cohort-sized ring) vs dense+mask
    (fixed-point u32) — floor on the ratio — plus the legacy int64 wire for
    scale.  (3) The real 4-client Shamir protocol e2e: a streamed run's
    final global must be BITWISE the buffer-all run's (mod-field exactness),
    with the reveal/dropout machinery live."""
    import jax
    import numpy as np

    import fedml_tpu
    from fedml_tpu.arguments import Config
    from fedml_tpu.cross_silo.secagg_shamir import run_shamir_secagg_process_group
    from fedml_tpu.cross_silo.secagg_soak import run_secagg_stream_soak
    from fedml_tpu.data import loader
    from fedml_tpu.models import model_hub

    cohort = int(os.environ.get("BENCH_SECAGG_COHORT", "10000"))
    dim = int(os.environ.get("BENCH_SECAGG_DIM", "4096"))
    rounds = int(os.environ.get("BENCH_SECAGG_ROUNDS", "1"))
    qsgd8 = run_secagg_stream_soak(cohort=cohort, dim=dim, rounds=rounds)
    # dense leg: small cohort — it exists to pin the dense-ring identity,
    # not to re-measure throughput
    dense = run_secagg_stream_soak(cohort=min(cohort, 512),
                                   dim=min(dim, 2048), rounds=1,
                                   codec="dense")

    def sa_cfg(run_id, **extra):
        e = {"secagg_method": "shamir"}
        e.update(extra)
        return Config(
            dataset="synthetic", model="lr", training_type="cross_silo",
            client_num_in_total=4, client_num_per_round=4, comm_round=2,
            epochs=1, batch_size=16, learning_rate=0.1,
            synthetic_train_size=256, synthetic_test_size=64,
            partition_method="homo", frequency_of_the_test=0,
            compute_dtype="float32", metrics_jsonl_path="", run_id=run_id,
            enable_secagg=True, extra=e,
        )

    cfg_s = sa_cfg("bench_sa_stream", secagg_stream=True)
    fedml_tpu.init(cfg_s)
    ds = loader.load(cfg_s)
    model = model_hub.create(cfg_s, ds.class_num)
    t0 = time.perf_counter()
    _, srv_stream = run_shamir_secagg_process_group(cfg_s, ds, model, timeout=300.0)
    stream_wall = time.perf_counter() - t0
    cfg_l = sa_cfg("bench_sa_legacy")
    fedml_tpu.init(cfg_l)
    _, srv_legacy = run_shamir_secagg_process_group(cfg_l, ds, model, timeout=300.0)
    g_s = jax.device_get(srv_stream.aggregator.global_vars)
    g_l = jax.device_get(srv_legacy.aggregator.global_vars)
    e2e_bitwise = all(
        np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(jax.tree_util.tree_leaves(g_s),
                        jax.tree_util.tree_leaves(g_l)))
    return {
        "cohort": cohort,
        "dim": dim,
        "rounds": rounds,
        "soak_qsgd8_mask": qsgd8,
        "soak_dense_mask": dense,
        "throughput_ratio": qsgd8["throughput_ratio"],
        "peak_buffered": max(qsgd8["peak_buffered"], dense["peak_buffered"]),
        "bitwise_identity": bool(qsgd8["bitwise_identity"]
                                 and dense["bitwise_identity"]),
        "bytes_per_round_qsgd8_mask": qsgd8["bytes_per_round"],
        "bytes_per_round_dense_mask": qsgd8["bytes_per_round_dense_mask"],
        "bytes_per_round_legacy_int64": qsgd8["bytes_per_round_legacy_int64"],
        "bytes_ratio_dense_vs_qsgd8": round(
            qsgd8["bytes_per_round_dense_mask"]
            / max(qsgd8["bytes_per_round"], 1), 3),
        "e2e_stream_vs_legacy_bitwise": bool(e2e_bitwise),
        "e2e_peak_buffered": int(srv_stream.aggregator.peak_buffered_updates),
        "e2e_stream_wall_s": round(stream_wall, 3),
    }


def bench_hierarchy():
    """Hierarchical aggregation tree (ISSUE 17): O(edges) root fan-in.

    Three legs on one 16-client fleet, all over the qsgd8 client wire:
    (1) the flat protocol — every upload lands on rank 0; (2) a fanout-8
    edge tree with qsgd8 re-encode on the edge->root hop — the root sees
    ceil(16/8)=2 pre-folded partials per round, so its ingress bytes must
    drop >= HIER_ROOT_BYTES_RATIO_FLOOR; (3) the same tree with one edge
    SIGKILLed mid-round — the journal-restored replacement dedups the
    re-sent uploads, the accounting identity closes, and the final global
    is BITWISE the clean tree run's."""
    from fedml_tpu.cross_silo.async_soak import run_edge_kill_soak

    n = int(os.environ.get("BENCH_HIER_CLIENTS", "16"))
    fanout = int(os.environ.get("BENCH_HIER_FANOUT", "8"))
    rounds = int(os.environ.get("BENCH_HIER_ROUNDS", "2"))
    flat = run_edge_kill_soak(n_clients=n, fanout=0, rounds=rounds,
                              kill=None, seed=0, codec="qsgd8",
                              timeout_s=180.0)
    tree = run_edge_kill_soak(n_clients=n, fanout=fanout, rounds=rounds,
                              kill=None, seed=0, codec="qsgd8",
                              hop_codec="qsgd8", timeout_s=180.0)
    kill = run_edge_kill_soak(n_clients=n, fanout=fanout, rounds=rounds,
                              kill=(0, 0, 1), seed=0, codec="qsgd8",
                              hop_codec="qsgd8", timeout_s=180.0)
    import numpy as np

    kill_bitwise_clean = all(
        np.array_equal(a, b) for a, b in zip(tree["global_leaves"],
                                             kill["global_leaves"]))
    for leg in (flat, tree, kill):
        leg.pop("global_leaves", None)  # arrays are not bench-JSON material
    return {
        "clients": n,
        "fanout": fanout,
        "rounds": rounds,
        "root_ingress_bytes_flat": flat["root_ingress_bytes"],
        "root_ingress_bytes_tree": tree["root_ingress_bytes"],
        "root_bytes_ratio": round(
            flat["root_ingress_bytes"]
            / max(tree["root_ingress_bytes"], 1), 3),
        "root_fan_in_flat": n,
        "root_fan_in_tree": tree["edges"],
        "partials_per_round": tree["partials_sent"] // max(rounds, 1),
        "peak_buffered_root": max(tree["peak_buffered_root"],
                                  kill["peak_buffered_root"]),
        "peak_buffered_edge": max(tree["peak_buffered_edge"],
                                  kill["peak_buffered_edge"]),
        "edge_kills": kill["edge_kills"],
        "edge_dedups": kill["edge_dedups"],
        "unaccounted": max(tree["unaccounted"], kill["unaccounted"]),
        "kill_bitwise_clean": bool(kill_bitwise_clean),
        "flat": flat,
        "tree": tree,
        "kill": kill,
    }


def bench_llm(peak):
    import jax
    import jax.numpy as jnp

    from fedml_tpu.llm.train import LLMTrainArgs, LLMTrainer
    from fedml_tpu.models.transformer import TransformerConfig
    from fedml_tpu.ops import flops as flopslib

    on_cpu = jax.devices()[0].platform == "cpu"
    if on_cpu:
        tcfg = TransformerConfig.tiny(vocab_size=1024)
        args = LLMTrainArgs(batch_size=2, seq_len=128, total_steps=4, warmup_steps=1)
        steps = 2
    else:
        d = int(os.environ.get("BENCH_LLM_DMODEL", "2048"))
        L = int(os.environ.get("BENCH_LLM_LAYERS", "8"))
        remat = os.environ.get("BENCH_LLM_REMAT", "1") not in ("0", "false", "no")
        tcfg = TransformerConfig(
            vocab_size=32000, d_model=d, n_layers=L, n_heads=16, n_kv_heads=16,
            d_ff=5632, max_seq_len=2048, remat=remat,
            remat_policy=os.environ.get("BENCH_LLM_REMAT_POLICY", "dots"),
        )
        args = LLMTrainArgs(
            batch_size=int(os.environ.get("BENCH_LLM_BATCH", "8")),
            seq_len=2048, total_steps=16, warmup_steps=1,
        )
        steps = int(os.environ.get("BENCH_LLM_STEPS", "8"))

    trainer = LLMTrainer(tcfg, args)
    n_params = trainer.n_params()
    n_embed = tcfg.vocab_size * tcfg.d_model  # gather-only table
    tps = trainer.token_throughput(steps=steps)
    flops_tok = flopslib.transformer_train_flops_per_token(
        n_params, n_embed, tcfg.n_layers, tcfg.d_model, args.seq_len
    )
    # token_throughput is GLOBAL tokens/s over the whole mesh; MFU must be
    # per-chip throughput over one chip's peak
    tps_chip = tps / len(jax.devices())
    mfu = (tps_chip * flops_tok / peak) if peak else None
    return {
        "tokens_per_sec_chip": round(tps_chip, 1),
        "mfu": round(mfu, 4) if mfu is not None else None,
        "n_params_m": round(n_params / 1e6, 1),
        "seq_len": args.seq_len,
        "batch": args.batch_size,
        "flops_per_token_g": round(flops_tok / 1e9, 3),
    }


def _run_one(mode):
    if mode == "fleet":
        # must precede the first jax import: the fleet bench carves 4
        # disjoint 2-device submeshes out of an 8-device mesh, and the
        # partition win is a host-side control-plane property — so the
        # child pins an 8-device CPU platform (explicit JAX_PLATFORMS /
        # a forced device count in the caller's env are respected)
        os.environ.setdefault("JAX_PLATFORMS", "cpu")
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=8").strip()
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    # shared persistent compilation cache (core/cache.py — same dir as the
    # test suite and the multichip dryrun): warm re-runs skip the multi-minute
    # XLA compiles of the scanned round and LLM step programs
    from fedml_tpu.core.cache import setup_persistent_cache

    setup_persistent_cache()

    import jax

    from fedml_tpu.ops import flops as flopslib

    dev = jax.devices()[0]
    peak = flopslib.device_peak_flops(dev)
    if mode == "llm":
        result = bench_llm(peak)
    elif mode == "fedavg_fused":
        result = bench_fedavg(peak, fused=True)
    elif mode == "crosssilo":
        result = bench_crosssilo()
    elif mode == "population":
        result = bench_population()
    elif mode == "aot_cold_start":
        result = bench_aot_cold_start()
    elif mode == "async_soak":
        result = bench_async_soak()
    elif mode == "chaos":
        result = bench_chaos()
    elif mode == "slo":
        result = bench_slo()
    elif mode == "serving":
        result = bench_serving()
    elif mode == "federated_lora":
        result = bench_federated_lora()
    elif mode == "multi_tenant":
        result = bench_multi_tenant()
    elif mode == "fleet":
        result = bench_fleet()
    elif mode == "secagg":
        result = bench_secagg()
    elif mode == "hierarchy":
        result = bench_hierarchy()
    else:
        result = bench_fedavg(peak)
    result["device"] = str(getattr(dev, "device_kind", dev.platform))
    result["chip_peak_tflops"] = round(peak / 1e12, 1) if peak else None
    # telemetry overhead ledger: the OTLP exporter's shipped/dropped/retried
    # counters and whatever per-client health the run produced, so the perf
    # trajectory records what observability cost (0s when no otlp_endpoint /
    # no cross-silo clients — the honest default)
    from fedml_tpu.obs.health import health_summary_from_registry
    from fedml_tpu.obs.otlp import otlp_counters

    client_health = health_summary_from_registry()
    if len(client_health) > 64:
        # fleet-sized runs (the async soak tracks thousands of clients):
        # summarize instead of dumping one score per client into the JSON
        scores = list(client_health.values())
        client_health = {"clients": len(scores), "min": round(min(scores), 4),
                         "mean": round(sum(scores) / len(scores), 4)}
    result["telemetry"] = {
        "otlp": otlp_counters(),
        "client_health": client_health,
    }
    print("BENCH_RESULT " + json.dumps(result))


def _subprocess_bench(mode, extra_env=None):
    """Each bench in a fresh process: the LLM bench's ~7 GB of device state
    can't be reliably freed in-process and would starve the FedAvg bench.
    (The AOT cold-start bench NEEDS the fresh process — warm means a new
    process finding the programs on disk, not a warm in-process jit cache.)"""
    import subprocess

    res = subprocess.run(
        [sys.executable, os.path.abspath(__file__)],
        env={**os.environ, "BENCH_MODE": mode, **(extra_env or {})},
        capture_output=True,
        text=True,
        timeout=1500,
    )
    for line in res.stdout.splitlines():
        if line.startswith("BENCH_RESULT "):
            return json.loads(line[len("BENCH_RESULT "):])
    raise RuntimeError(
        f"bench subprocess {mode} failed (rc={res.returncode}):\n"
        f"{res.stdout[-2000:]}\n{res.stderr[-2000:]}"
    )


#: Regression floors (asserted on real TPU only).  LLM: the BASELINE.md 0.35
#: target itself — drift below target must fail loudly, not hide in a JSON
#: field (round-3 verdict item 7).  FedAvg: 0.125 = just under the confirmed
#: round-3/4 band (0.130-0.137), catching architectural regressions while
#: tolerating tunnel run-to-run noise.
LLM_MFU_FLOOR = 0.35
FEDAVG_MFU_FLOOR = 0.125
#: qsgd8 wire ratio on the ResNet-20 pytree — platform independent (int8 +
#: per-block scales vs f32), so it is asserted on CPU too
CROSSSILO_QSGD8_RATIO_FLOOR = 3.5
#: Peak host memory of the streamed 1M-population rounds, as a multiple of
#: the active cohort's data bytes — platform independent (host-side layer).
#: Budget: 8 resident shards of 4096 clients ≈ 3.3x a 10k cohort, plus the
#: double-buffered in-flight cohorts and npz materialization transients.
POPULATION_RSS_MULTIPLE_FLOOR = 16.0
#: Virtual rounds per second the 10k-client buffered-async soak must sustain
#: (ISSUE 8) — platform independent (host-side fold path; the measured CPU
#: number is ~22/s, so 2.0 catches order-of-magnitude regressions while
#: tolerating loaded-box noise).
ASYNC_VERSIONS_PER_SEC_FLOOR = 2.0
#: Kill-and-recover soak throughput as a fraction of the clean run's
#: versions/s (ISSUE 10) — platform independent.  A mid-run SIGKILL +
#: journal recovery (re-discovery, epoch fence, watchdog re-issue of lost
#: dispatches) must retain at least half the clean throughput, or server
#: restarts are not production-viable.
CHAOS_RECOVERY_RATIO_FLOOR = 0.5
#: Client-kill soak throughput as a fraction of the clean run's versions/s
#: (ISSUE 13) — platform independent.  Mid-run client SIGKILLs + journal
#: resumes (redispatch of the dead slots, replacement construction, EF
#: restore) must retain at least half the clean throughput, or client churn
#: is not survivable at production rates (CPU measures ~0.97: the wall is
#: dominated by real client training, and kills cost one redispatch
#: timeout each).
CLIENT_KILL_RECOVERY_RATIO_FLOOR = 0.5
#: Serving QPS the continuous-batching worker must sustain WHILE an async
#: training run publishes versions (ISSUE 11) — platform independent
#: (host-side serving path; CPU measures hundreds of QPS at the default
#: 4-thread load, so 20 catches order-of-magnitude regressions while
#: tolerating a loaded box running training concurrently).
SERVING_QPS_FLOOR = 20.0
#: qsgd8 wire ratio on the rank-8 LoRA adapter tree (ISSUE 12) — platform
#: independent (int8 + per-block scales vs f32; the q/k/v factors are exact
#: 1024-element blocks), so it is asserted on CPU too.
LORA_QSGD8_RATIO_FLOOR = 3.5
#: Dense-model-vs-compressed-adapter wire ratio (ISSUE 12): the federated
#: LLM scenario exists because the adapter exchange is ~100x cheaper than
#: shipping the model; 50x catches a broken floor without flaking on vocab-
#: dependent model size.
LORA_DENSE_ADAPTER_RATIO_FLOOR = 50.0
#: Concurrent aggregate versions/s of 8 gang-scheduled tenant jobs as a
#: fraction of the 8x-sequential aggregate (ISSUE 14) — platform independent
#: (host-side control plane).  Packing N tenants onto one pool must retain
#: at least half the sequential aggregate throughput; CPU measures >1x
#: (dispatch-wave latency of one tenant hides behind a sibling's folds), so
#: 0.5 catches a serialization regression without flaking on a loaded box.
MULTI_TENANT_THROUGHPUT_RATIO_FLOOR = 0.5
#: Concurrent aggregate versions/s of 4 jobs on disjoint 2-device submeshes
#: as a fraction of the 4x-sequential full-mesh aggregate (ISSUE 19) —
#: measured on the child's forced 8-device CPU platform, so it is asserted
#: everywhere.  A fleet PARTITION must beat time-sharing outright (no job
#: ever waits for a slot), so the floor is 1.0 where the time-sliced
#: multi-tenant floor is 0.5; CPU measures well above it (the 4 jobs'
#: dispatch waves and folds genuinely overlap).
FLEET_THROUGHPUT_RATIO_FLOOR = 1.0
#: Warm start-to-first-round as a fraction of cold (ISSUE 7) — platform
#: independent (the AOT store removes re-tracing everywhere; on CPU the
#: deserialized program's compile additionally rides the persistent
#: compilation cache).  A warm process must reach round 1 in at most half
#: the cold wall clock, with every program served from the store.
AOT_WARM_RATIO_CEILING = 0.5
#: Streaming SecAgg (ISSUE 15) — platform-independent host-side floors.
#: Throughput: versions/s with SecAgg on over off at the 10k simulated
#: cohort; the secure path must keep at least half the plain throughput
#: even with the soak's deliberately cheap proxy local step (real local
#: training pushes the ratio toward 1).
SECAGG_THROUGHPUT_RATIO_FLOOR = 0.5
#: bytes/round of dense+mask (fixed-point u32) over quantize-then-mask
#: (int8 grid + cohort carry bits): 4 over 3 bytes/element at a 10k
#: cohort = 1.33x measured
SECAGG_BYTES_RATIO_FLOOR = 1.25
#: Hierarchical aggregation tree (ISSUE 17) — platform-independent byte
#: accounting, no wall clocks.  Root ingress bytes flat/tree at fanout 8
#: over the qsgd8 wire on both hops: 16 compressed uploads/round collapse
#: to 2 re-encoded partials/round, ~8x counted, 4x floor-guarded (header
#: and control-meta overhead is what eats the slack at tiny models).
HIER_ROOT_BYTES_RATIO_FLOOR = 4.0


def _hierarchy_violations(res) -> list:
    """Floor checks for the hierarchy section (shared by the full bench and
    `--mode hierarchy`)."""
    v = []
    ratio = res.get("root_bytes_ratio")
    if ratio is not None and ratio < HIER_ROOT_BYTES_RATIO_FLOOR:
        v.append(f"hierarchy root ingress bytes flat/tree {ratio} < floor "
                 f"{HIER_ROOT_BYTES_RATIO_FLOOR} (edge folding not paying "
                 "for itself at fanout "
                 f"{res.get('fanout')})")
    if res.get("peak_buffered_root", 0) > 2 or res.get("peak_buffered_edge", 0) > 2:
        v.append(f"hierarchy peak buffered root="
                 f"{res.get('peak_buffered_root')} edge="
                 f"{res.get('peak_buffered_edge')} > 2 (streaming fold not "
                 "engaged on some hop)")
    if res.get("unaccounted", 0) != 0:
        v.append(f"hierarchy left {res['unaccounted']} uploads unaccounted "
                 "(folds + relays + dedups must cover every child upload)")
    if res.get("edge_kills", 0) != 1 or res.get("edge_dedups", 0) < 1:
        v.append(f"hierarchy kill leg: {res.get('edge_kills')} kills / "
                 f"{res.get('edge_dedups')} dedups (expected 1 SIGKILL and "
                 ">= 1 journaled dedup of a re-sent upload)")
    if not res.get("kill_bitwise_clean", False):
        v.append("hierarchy killed-edge final global != clean tree run "
                 "bitwise (journal recovery changed the fold)")
    return v


def _secagg_violations(res) -> list:
    """Floor checks for the secagg section (shared by the full bench and
    `--mode secagg`)."""
    v = []
    ratio = res.get("throughput_ratio")
    if ratio is not None and ratio < SECAGG_THROUGHPUT_RATIO_FLOOR:
        v.append(f"secagg on/off versions/s ratio {ratio} < floor "
                 f"{SECAGG_THROUGHPUT_RATIO_FLOOR}")
    bytes_ratio = res.get("bytes_ratio_dense_vs_qsgd8")
    if bytes_ratio is not None and bytes_ratio < SECAGG_BYTES_RATIO_FLOOR:
        v.append(f"secagg dense+mask/qsgd8+mask bytes ratio {bytes_ratio} "
                 f"< floor {SECAGG_BYTES_RATIO_FLOOR}")
    if res.get("peak_buffered", 0) > 2:
        v.append(f"secagg soak peak buffered {res['peak_buffered']} > 2 "
                 "(streaming masked fold not engaged)")
    if res.get("e2e_peak_buffered", 0) > 2:
        v.append(f"secagg e2e peak buffered {res['e2e_peak_buffered']} > 2")
    if not res.get("bitwise_identity", False):
        v.append("secagg streamed masked sum != exact unmasked sum "
                 "(mod-field integer identity failed)")
    if not res.get("e2e_stream_vs_legacy_bitwise", False):
        v.append("secagg e2e streamed global != buffer-all global bitwise")
    return v


def _federated_lora_violations(res) -> list:
    """Floor checks for the federated_lora section (shared by the full bench
    and `--mode federated_lora`)."""
    v = []
    ratio = res.get("qsgd8_ratio_lora")
    if ratio is not None and ratio < LORA_QSGD8_RATIO_FLOOR:
        v.append(f"federated_lora qsgd8 ratio {ratio} < floor "
                 f"{LORA_QSGD8_RATIO_FLOOR}")
    dense = res.get("dense_vs_adapter_ratio")
    if dense is not None and dense < LORA_DENSE_ADAPTER_RATIO_FLOOR:
        v.append(f"federated_lora dense/adapter wire ratio {dense} < floor "
                 f"{LORA_DENSE_ADAPTER_RATIO_FLOOR}")
    if res.get("peak_buffered_updates", 0) > 2:
        v.append(f"federated_lora peak buffered updates "
                 f"{res['peak_buffered_updates']} > 2 (streaming fold not "
                 "engaged)")
    if not res.get("stream_exact_bitwise", False):
        v.append("federated_lora streaming aggregation != exact (bitwise "
                 "proof at staleness 0 failed)")
    for leg in ("raw", "qsgd8"):
        if not res.get(leg, {}).get("streaming", False):
            v.append(f"federated_lora {leg} leg did not engage the streaming "
                     "accumulator")
    return v


def _multi_tenant_violations(res) -> list:
    """Floor checks for the multi_tenant section (shared by the full bench
    and `--mode multi_tenant`)."""
    v = []
    ratio = res.get("throughput_ratio")
    if ratio is not None and ratio < MULTI_TENANT_THROUGHPUT_RATIO_FLOOR:
        v.append(f"multi_tenant concurrent/sequential aggregate versions/s "
                 f"{ratio} < floor {MULTI_TENANT_THROUGHPUT_RATIO_FLOOR} "
                 "(gang scheduling lost too much throughput)")
    for jid, s in (res.get("jobs_detail") or {}).items():
        if s.get("rounds") != res.get("versions_per_job"):
            v.append(f"multi_tenant job {jid} completed {s.get('rounds')}/"
                     f"{res.get('versions_per_job')} rounds")
    return v


def _fleet_violations(res) -> list:
    """Floor + hard-identity checks for the fleet section (shared by the
    full bench and `--mode fleet`)."""
    v = []
    ratio = res.get("throughput_ratio")
    if ratio is not None and ratio < FLEET_THROUGHPUT_RATIO_FLOOR:
        v.append(f"fleet concurrent/sequential aggregate versions/s {ratio} "
                 f"< floor {FLEET_THROUGHPUT_RATIO_FLOOR} (the submesh "
                 "partition lost to time-sharing)")
    if not res.get("parity_bitwise", False):
        bad = [j for j, ok in (res.get("parity_jobs") or {}).items() if not ok]
        v.append(f"fleet submesh-vs-dedicated parity broken for jobs {bad} "
                 "(a job's final global on its lease must be bitwise the "
                 "same job alone on an identically shaped dedicated mesh)")
    if not res.get("parity_finals_distinct", False):
        v.append("fleet parity jobs produced identical finals (per-job "
                 "recipes must differ or the parity check cannot see a "
                 "cross-tenant leak)")
    for kind in ("metric", "journal", "publish"):
        if not res.get(f"{kind}_bleed_clean", False):
            v.append(f"fleet cross-tenant {kind} bleed detected (every "
                     f"{kind} artifact must be attributable to exactly one "
                     "tenant)")
    for jid, s in (res.get("jobs_detail") or {}).items():
        if s.get("rounds") != res.get("versions_per_job"):
            v.append(f"fleet job {jid} completed {s.get('rounds')}/"
                     f"{res.get('versions_per_job')} rounds")
    return v


def _slo_violations(res) -> list:
    """Checks for the slo section (shared by the full bench and
    `--mode slo`): the watchdog must have actually ticked, and a CLEAN leg
    must record zero breaches — generous thresholds mean any breach is a
    regression (or a broken spec default), never noise."""
    v = []
    slo = res.get("slo") or {}
    if not slo:
        v.append("slo engine never armed (extra.slo_specs did not take)")
        return v
    if slo.get("evaluations", 0) <= 0:
        v.append("slo engine armed but never evaluated (timer wheel tick "
                 "missing)")
    if slo.get("breaches", 0) != 0:
        v.append(f"slo clean leg recorded {slo['breaches']} breach(es) on "
                 f"{slo.get('breached_slos')} (healthy runs must be "
                 "breach-free)")
    if res.get("unaccounted_drops", 0) != 0:
        v.append(f"slo leg lost {res['unaccounted_drops']} drops unaccounted")
    return v


def _mode_violations(mode, result) -> list:
    if mode == "federated_lora":
        return _federated_lora_violations(result)
    if mode == "multi_tenant":
        return _multi_tenant_violations(result)
    if mode == "fleet":
        return _fleet_violations(result)
    if mode == "secagg":
        return _secagg_violations(result)
    if mode == "slo":
        return _slo_violations(result)
    if mode == "hierarchy":
        return _hierarchy_violations(result)
    return []


def main():
    argv = sys.argv[1:]
    if "--mode" in argv and argv[argv.index("--mode") + 1] == "compare":
        # regression sentinel (ISSUE 18, obs/regress.py): judge one result
        # file against the BENCH_*.json trajectory.  Pure stdlib + no
        # subprocess, no retry — comparison is deterministic, and a flaky
        # rerun would only launder a real regression.
        from fedml_tpu.obs import regress

        def _opt(flag, default=None):
            return argv[argv.index(flag) + 1] if flag in argv else default

        candidate = _opt("--candidate")
        if not candidate:
            print("bench.py --mode compare requires --candidate <result.json>",
                  file=sys.stderr)
            sys.exit(2)
        baseline_dir = _opt("--baseline-dir",
                            os.path.dirname(os.path.abspath(__file__)))
        try:
            comparison = regress.compare_candidate(
                candidate, baseline_dir,
                rel_tol=float(_opt("--rel-tol", 0.10)),
                nsigma=float(_opt("--nsigma", 3.0)))
        except ValueError as e:
            print(f"bench.py --mode compare: {e}", file=sys.stderr)
            sys.exit(2)
        print(json.dumps({"metric": "bench_compare",
                          "value": len(comparison["regressions"]),
                          "unit": "regressions",
                          "floor_violations": [
                              f"{r['metric']}: {r['candidate']} vs mean "
                              f"{r['mean']} (slack {r['slack']})"
                              for r in comparison["regressions"]],
                          "detail": {"regression": comparison}}))
        if not comparison["ok"]:
            sys.stdout.flush()
            print("BENCH REGRESSION: " + "; ".join(
                r["metric"] for r in comparison["regressions"]),
                file=sys.stderr)
            sys.exit(3)
        return
    if "--mode" in argv:
        # single-section run (`bench.py --mode federated_lora`): same
        # exit-3 / one-retry floor policy as the full bench
        mode = argv[argv.index("--mode") + 1]
        result = _subprocess_bench(mode)
        violations = _mode_violations(mode, result)
        if violations:
            result = _subprocess_bench(mode)
            violations = _mode_violations(mode, result)
        print(json.dumps({"metric": f"bench_{mode}", "detail": result,
                          "floor_violations": violations}))
        if violations:
            sys.stdout.flush()
            print("BENCH FLOOR VIOLATION: " + "; ".join(violations),
                  file=sys.stderr)
            sys.exit(3)
        return
    if os.environ.get("BENCH_MODE"):
        _run_one(os.environ["BENCH_MODE"])
        return
    # The parent must NOT import jax: initializing the TPU runtime here would
    # hold the process-exclusive device lock and starve both child benches.
    # Device identity/peak come back in the children's results.
    # Static-analysis trajectory (ISSUE 5): the finding count rides the bench
    # JSON so the record shows the codebase staying clean round over round.
    # The lint engine is pure stdlib-ast (no jax), so it is parent-safe.
    from fedml_tpu.analysis.engine import run_lint

    pkg = os.path.join(os.path.dirname(os.path.abspath(__file__)), "fedml_tpu")
    lint_res = run_lint(pkg, baseline=os.path.join(pkg, "analysis", "baseline.json"))
    from fedml_tpu.analysis.engine import default_rules

    # per-rule activity, suppressions included: a clean tree has zero
    # findings by construction (tier-1 gate), so the by-rule trajectory
    # that actually moves round over round is the documented-suppression
    # count — GL004/GL007/GL008 invariant annotations live there
    suppressed_by_rule: dict = {}
    for f in lint_res.suppressed:
        suppressed_by_rule[f.rule] = suppressed_by_rule.get(f.rule, 0) + 1
    lint_section = {
        "findings": len(lint_res.findings),
        "suppressed": len(lint_res.suppressed),
        "baselined": len(lint_res.baselined),
        "by_rule": lint_res.counts_by_rule(),
        "suppressed_by_rule": suppressed_by_rule,
        "rules_run": [r.id for r in default_rules()],
    }
    llm = _subprocess_bench("llm")
    fedavg = _subprocess_bench("fedavg")
    # round-6 A/B: the identical FedAvg recipe with conv epilogues through
    # the fused Pallas kernels.  Soft-fail — a fused-path failure is recorded
    # in the JSON but must not take down the two floor-guarded benches.
    try:
        fedavg_fused = _subprocess_bench("fedavg_fused")
    except Exception as e:  # noqa: BLE001 — the error string IS the record
        fedavg_fused = {"error": str(e)[-2000:]}
    # ISSUE-4: compressed streaming cross-silo rounds (in-proc backend) —
    # bytes-on-wire, compression ratio, and round wall time raw vs qsgd8
    crosssilo = _subprocess_bench("crosssilo")
    # ISSUE-6: 1M-client population round streamed from the sharded store —
    # samples/s/chip at a 10k cohort, gather/scatter seconds, prefetch
    # overlap, and the cohort-bounded host-RSS multiple (floor-guarded)
    population = _subprocess_bench("population")
    # ISSUE-8: buffered-async aggregation — 10k simulated clients against one
    # server, staleness-decayed folds, K-arrival virtual rounds; floor on
    # versions/s + the peak-buffered/unaccounted-drop acceptance bounds
    async_soak = _subprocess_bench("async_soak")
    # ISSUE-10 chaos: the same async shape clean vs killed-and-recovered
    # under seeded chaos — floor on recovered/clean versions/s plus the
    # recovery correctness invariants
    chaos = _subprocess_bench("chaos")
    # ISSUE-11 serving: continuous-batching worker hot-swapping model
    # versions WHILE an async training run publishes them — QPS floor +
    # zero dropped requests across >= 3 hot swaps + final served version
    # == final published version
    serving = _subprocess_bench("serving")
    # ISSUE-12 federated LoRA: adapter deltas over the compressed streaming
    # wire — qsgd8 adapter ratio floor, dense-vs-adapter ~100x, peak
    # buffered <= 2, streaming==exact bitwise at staleness 0
    federated_lora = _subprocess_bench("federated_lora")
    if _federated_lora_violations(federated_lora):
        # same one-retry policy as the other floors
        federated_lora = _subprocess_bench("federated_lora")
    # ISSUE-14 multi-tenant: 8 concurrent gang-scheduled FL jobs vs the
    # 8x-sequential baseline — aggregate versions/s ratio floor + p95
    # round-latency interference
    multi_tenant = _subprocess_bench("multi_tenant")
    if _multi_tenant_violations(multi_tenant):
        # same one-retry policy as the other wall-clock floors
        multi_tenant = _subprocess_bench("multi_tenant")
    # ISSUE-19 fleet: 4 jobs on disjoint 2-device submeshes of one 8-device
    # CPU mesh vs the same 4 jobs sequentially on the full mesh — ratio
    # floor 1.0 (a partition must beat time-sharing), per-job submesh-vs-
    # dedicated bitwise parity, and zero cross-tenant metric/journal/
    # publish bleed
    fleet = _subprocess_bench("fleet")
    if _fleet_violations(fleet):
        # same one-retry policy as the other wall-clock floors (the parity
        # and bleed identities are deterministic, but the ratio is not)
        fleet = _subprocess_bench("fleet")
    # ISSUE-15 streaming SecAgg: masked uploads through the field-domain
    # streaming fold at a 10k simulated cohort — on/off versions/s floor,
    # peak buffered <= 2, streamed==exact integer identity, and the
    # quantize-then-mask vs dense+mask bytes/round ratio
    secagg = _subprocess_bench("secagg")
    if _secagg_violations(secagg):
        # same one-retry policy as the other wall-clock floors
        secagg = _subprocess_bench("secagg")
    # ISSUE-17 hierarchy: flat vs fanout-8 edge tree on the qsgd8 wire —
    # root ingress bytes ratio floor, peak buffered <= 2 on every hop,
    # edge-SIGKILL recovery with the accounting identity closed and the
    # final global bitwise the clean tree run's
    hierarchy = _subprocess_bench("hierarchy")
    if _hierarchy_violations(hierarchy):
        # same one-retry policy as the other floors
        hierarchy = _subprocess_bench("hierarchy")
    # ISSUE-16 SLO watchdog: the async soak with declarative SLOs live on
    # the server's timer wheel — evaluations > 0, zero breaches on a clean
    # leg (generous thresholds: any breach is a regression, not noise)
    slo_bench = _subprocess_bench("slo")
    if _slo_violations(slo_bench):
        # same one-retry policy as the other wall-clock floors
        slo_bench = _subprocess_bench("slo")
    # ISSUE-7 cold_start: two fresh processes share one AOT program store +
    # compilation cache root; the first populates it, the second must
    # deserialize every program (misses == 0) and start in <= 0.5x the time
    import shutil
    import tempfile

    def _aot_pair():
        aot_root = tempfile.mkdtemp(prefix="bench_aot_")
        try:
            cold = _subprocess_bench("aot_cold_start", {"BENCH_AOT_ROOT": aot_root})
            warm = _subprocess_bench("aot_cold_start", {"BENCH_AOT_ROOT": aot_root})
        finally:
            shutil.rmtree(aot_root, ignore_errors=True)
        ratio = round(warm["start_to_first_round_s"]
                      / max(cold["start_to_first_round_s"], 1e-9), 3)
        return cold, warm, ratio

    aot_cold, aot_warm, aot_ratio = _aot_pair()
    if aot_ratio > AOT_WARM_RATIO_CEILING:
        # same one-retry policy as the MFU floors: wall-clock pairs on a
        # loaded box have real variance; a single noisy pair must not fail
        # the round
        aot_cold, aot_warm, aot_ratio = _aot_pair()
    aot = {
        "cold_start_s": aot_cold["start_to_first_round_s"],
        "warm_start_s": aot_warm["start_to_first_round_s"],
        "ratio": aot_ratio,
        "hits": {"cold": aot_cold["hits"], "warm": aot_warm["hits"]},
        "misses": {"cold": aot_cold["misses"], "warm": aot_warm["misses"]},
        "cold": aot_cold,
        "warm": aot_warm,
    }

    on_tpu = "TPU" in str(llm.get("device", ""))
    # one retry per bench before declaring a floor violation: a tunneled chip
    # has real run-to-run variance and a single cold run must not fail a round
    if on_tpu and llm["mfu"] is not None and llm["mfu"] < LLM_MFU_FLOOR:
        llm = _subprocess_bench("llm")
    if on_tpu and fedavg["mfu"] is not None and fedavg["mfu"] < FEDAVG_MFU_FLOOR:
        fedavg = _subprocess_bench("fedavg")
    violations = []
    if on_tpu and llm["mfu"] is not None and llm["mfu"] < LLM_MFU_FLOOR:
        violations.append(f"llm mfu {llm['mfu']} < floor {LLM_MFU_FLOOR}")
    if on_tpu and fedavg["mfu"] is not None and fedavg["mfu"] < FEDAVG_MFU_FLOOR:
        violations.append(f"fedavg mfu {fedavg['mfu']} < floor {FEDAVG_MFU_FLOOR}")
    cs_ratio = crosssilo.get("qsgd8_ratio_resnet20")
    if cs_ratio is not None and cs_ratio < CROSSSILO_QSGD8_RATIO_FLOOR:
        violations.append(
            f"crosssilo qsgd8 ratio {cs_ratio} < floor {CROSSSILO_QSGD8_RATIO_FLOOR}")
    async_vps = async_soak.get("versions_per_sec")
    if async_vps is not None and async_vps < ASYNC_VERSIONS_PER_SEC_FLOOR:
        # same one-retry policy as the other wall-clock floors
        async_soak = _subprocess_bench("async_soak")
        async_vps = async_soak.get("versions_per_sec")
    if async_vps is not None and async_vps < ASYNC_VERSIONS_PER_SEC_FLOOR:
        violations.append(
            f"async soak versions/s {async_vps} < floor {ASYNC_VERSIONS_PER_SEC_FLOOR}")
    if async_soak.get("peak_buffered_updates", 0) > 2:
        violations.append(
            f"async soak peak buffered updates {async_soak['peak_buffered_updates']} "
            "> 2 (streaming fold not engaged)")
    if async_soak.get("unaccounted_drops", 0) != 0:
        violations.append(
            f"async soak lost {async_soak['unaccounted_drops']} drops unaccounted")
    chaos_ratio = chaos.get("recovery_ratio")
    ck_ratio = chaos.get("client_kill_ratio")
    if ((chaos_ratio is not None and chaos_ratio < CHAOS_RECOVERY_RATIO_FLOOR)
            or (ck_ratio is not None
                and ck_ratio < CLIENT_KILL_RECOVERY_RATIO_FLOOR)):
        # same one-retry policy as the other wall-clock floors
        chaos = _subprocess_bench("chaos")
        chaos_ratio = chaos.get("recovery_ratio")
        ck_ratio = chaos.get("client_kill_ratio")
    if chaos_ratio is not None and chaos_ratio < CHAOS_RECOVERY_RATIO_FLOOR:
        violations.append(
            f"chaos recovery ratio {chaos_ratio} < floor "
            f"{CHAOS_RECOVERY_RATIO_FLOOR} (recovered run lost too much throughput)")
    rec = chaos.get("recovered", {})
    if rec and not rec.get("monotone", True):
        violations.append("chaos recovered run version not monotone")
    if rec.get("unaccounted", 0) != 0:
        violations.append(
            f"chaos recovered run lost {rec['unaccounted']} drops unaccounted")
    if rec.get("peak_buffered_updates", 0) > 2:
        violations.append(
            f"chaos recovered run peak buffered {rec['peak_buffered_updates']} > 2")
    # ISSUE-13 client-kill leg: throughput floor + the client-side identity
    if ck_ratio is not None and ck_ratio < CLIENT_KILL_RECOVERY_RATIO_FLOOR:
        violations.append(
            f"client-kill recovery ratio {ck_ratio} < floor "
            f"{CLIENT_KILL_RECOVERY_RATIO_FLOOR} (client churn cost too much "
            "throughput)")
    ck_rec = chaos.get("client_kill_recover", {})
    if ck_rec and ck_rec.get("unaccounted", 0) != 0:
        violations.append(
            f"client-kill run left {ck_rec['unaccounted']} restarts unaccounted")
    if ck_rec and ck_rec.get("kills", 0) != ck_rec.get("resumed_from_journal", 0):
        violations.append(
            f"client-kill run: {ck_rec.get('kills')} kills but only "
            f"{ck_rec.get('resumed_from_journal')} journal resumes (clients "
            "rejoining cold lose their EF residual carry)")
    serving_qps = serving.get("qps")
    if serving_qps is not None and serving_qps < SERVING_QPS_FLOOR:
        # same one-retry policy as the other wall-clock floors
        serving = _subprocess_bench("serving")
        serving_qps = serving.get("qps")
    if serving_qps is not None and serving_qps < SERVING_QPS_FLOOR:
        violations.append(
            f"serving qps {serving_qps} < floor {SERVING_QPS_FLOOR}")
    if serving.get("dropped_requests", 0) != 0:
        violations.append(
            f"serving dropped {serving['dropped_requests']} requests "
            "(hot swaps must drop zero in-flight work)")
    if serving.get("hot_swaps", 0) < 3:
        violations.append(
            f"serving saw only {serving.get('hot_swaps')} hot swaps "
            "(>= 3 required to prove the version-swap gap)")
    if serving.get("served_version_final") != serving.get("versions_published"):
        violations.append(
            f"serving final served version {serving.get('served_version_final')} "
            f"!= final published version {serving.get('versions_published')}")
    violations += _federated_lora_violations(federated_lora)
    violations += _multi_tenant_violations(multi_tenant)
    violations += _fleet_violations(fleet)
    violations += _secagg_violations(secagg)
    violations += _hierarchy_violations(hierarchy)
    violations += _slo_violations(slo_bench)
    pop_rss = population.get("rss_multiple")
    if pop_rss is not None and pop_rss > POPULATION_RSS_MULTIPLE_FLOOR:
        violations.append(
            f"population rss multiple {pop_rss} > ceiling "
            f"{POPULATION_RSS_MULTIPLE_FLOOR} (host memory not cohort-bounded)")
    if aot_ratio > AOT_WARM_RATIO_CEILING:
        violations.append(
            f"aot warm/cold start ratio {aot_ratio} > ceiling "
            f"{AOT_WARM_RATIO_CEILING} (warm start not program-store bound)")
    if aot_warm["misses"] != 0 or aot_warm["hits"] <= 0:
        violations.append(
            f"aot warm run hits={aot_warm['hits']} misses={aot_warm['misses']} "
            "(expected every program served from the store)")

    mfu = llm["mfu"]
    target = 0.35  # BASELINE.md MFU floor
    fused_speedup = None
    if fedavg.get("samples_per_sec_chip") and fedavg_fused.get("samples_per_sec_chip"):
        fused_speedup = round(
            fedavg_fused["samples_per_sec_chip"] / fedavg["samples_per_sec_chip"], 4
        )
    print(json.dumps({
        "metric": "llm_542m_train_step_mfu",
        "value": mfu if mfu is not None else llm["tokens_per_sec_chip"],
        "unit": "MFU" if mfu is not None else "tokens/s/chip (MFU n/a off-TPU)",
        "vs_baseline": round(mfu / target, 3) if mfu is not None else 1.0,
        "floor_violations": violations,
        "detail": {
            "device": llm.get("device"),
            "chip_peak_tflops": llm.get("chip_peak_tflops"),
            "llm": llm,
            "fedavg_cifar10_resnet20": fedavg,
            "fedavg_cifar10_resnet20_fused": fedavg_fused,
            "fedavg_fused_speedup": fused_speedup,
            "crosssilo_comm": crosssilo,
            "population": population,
            "async": async_soak,
            "chaos": chaos,
            "serving": serving,
            "federated_lora": federated_lora,
            "multi_tenant": multi_tenant,
            "fleet": fleet,
            "secagg": secagg,
            "hierarchy": hierarchy,
            "slo": slo_bench,
            "aot": aot,
            "lint": lint_section,
        },
    }))
    if violations:
        sys.stdout.flush()
        print("BENCH FLOOR VIOLATION: " + "; ".join(violations), file=sys.stderr)
        sys.exit(3)


if __name__ == "__main__":
    main()
