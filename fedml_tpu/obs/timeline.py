"""Performance timeline — continuous telemetry recording (ISSUE 18).

PR 16 made *failures* self-explaining; performance was still observed at
two instants only (a live ``/metrics`` scrape, a pile of ``BENCH_*.json``).
This module is the substrate between those instants: a
:class:`TimelineRecorder` samples ``MetricsRegistry.snapshot()`` on the
existing ``cross_silo/runtime.py`` timer wheel — NO new threads — into a
bounded in-memory ring, flushes atomic on-disk segment files with the
flight-bundle envelope (MAGIC + one sorted-keys JSON meta line + JSON
body, ``tempfile.mkstemp`` + fsync + ``os.replace``), and answers the
queries a performance investigation actually asks:

- **range scans** over samples (in-ring or loaded from segments),
- **windowed rates** of any counter series (``rounds/s``, ``versions/s``,
  ``bytes/s`` between any two sampled instants, not just "now"),
- **histogram-delta pNN** — percentile of the *window's* observations
  (last counts minus first counts, bucket-interpolated), which a
  cumulative ``/metrics`` scrape fundamentally cannot answer.

Samples store the *cumulative* scalarized snapshot; every query is a
delta between two samples, so the ring IS a time series of deltas without
the reconstruction fragility of storing increments.

The recorder also owns the **convergence series** (ROADMAP
"rounds-to-accuracy as a tracked metric"): the servers tee each finished
round's ``(round_idx, server_version, test_acc, wall)`` through
:meth:`TimelineRecorder.note_round`, and the first crossing of each
accuracy target becomes ``fedml_convergence_rounds_to_target{target}`` —
throughput × rounds-to-target (the survey's judging criterion) is then
two queries against one artifact.

Gating is absolute: :func:`timeline_from_config` returns ``None`` unless
``extra.perf_timeline`` is set — no ring, no timer, no segment files,
default path bit-identical.
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import tempfile
import threading
import time
from collections import deque
from typing import Any, Optional, Sequence

from ..core.flags import cfg_extra
from . import registry as obsreg

log = logging.getLogger("fedml_tpu.obs.timeline")

__all__ = [
    "TimelineRecorder", "timeline_from_config", "read_segment",
    "list_segments", "load_timeline", "range_scan", "windowed_rate",
    "hist_pnn", "value_series", "rounds_to_target",
]

#: on-disk segment envelope: MAGIC + one sorted-keys JSON meta line + the
#: JSON body.  Bump the magic when the envelope changes — old segments are
#: then rejected as foreign, never misread.
_MAGIC = b"FMLTLN1\n"

#: accuracy targets tracked by default (first-crossing round per target)
_DEFAULT_TARGETS = (0.5, 0.6, 0.7, 0.8, 0.9)

TIMELINE_SAMPLES = obsreg.REGISTRY.counter(
    "fedml_timeline_samples_total",
    "Registry snapshots sampled into the performance-timeline ring.",
)
TIMELINE_SEGMENTS = obsreg.REGISTRY.counter(
    "fedml_timeline_segments_total",
    "Atomic timeline segment files flushed to disk.",
)
CONV_ROUND = obsreg.REGISTRY.gauge(
    "fedml_convergence_round",
    "Latest round index (sync) or server version (async) tee'd into the "
    "convergence series.",
)
CONV_TEST_ACC = obsreg.REGISTRY.gauge(
    "fedml_convergence_test_acc",
    "Latest test accuracy tee'd into the convergence series.",
)
ROUNDS_TO_TARGET = obsreg.REGISTRY.gauge(
    "fedml_convergence_rounds_to_target",
    "First round index whose test accuracy reached the target (the ROADMAP "
    "rounds-to-accuracy metric; unset until the target is crossed).",
    labels=("target",),
)


def _split_snapshot(snapshot: list[dict]) -> tuple[dict, dict, dict]:
    """Flatten a registry snapshot into ``(scalars, hists, buckets)`` —
    counters/gauges as ``{"family{k=v,...}": value}``, histograms as
    ``{key: {"counts": [...], "sum": s, "count": n}}`` with the bucket
    bounds keyed per family (stored once, not per sample)."""
    scalars: dict[str, float] = {}
    hists: dict[str, dict] = {}
    buckets: dict[str, list[float]] = {}
    for fam in snapshot:
        name = fam["name"]
        hist = fam.get("kind") == "histogram"
        if hist and fam.get("buckets"):
            buckets[name] = [float(b) for b in fam["buckets"]]
        for s in fam.get("samples", ()):
            labels = ",".join(f"{k}={v}" for k, v in sorted(s["labels"].items()))
            key = f"{name}{{{labels}}}" if labels else name
            if hist:
                hists[key] = {"counts": [int(c) for c in s["counts"]],
                              "sum": float(s["sum"]), "count": int(s["count"])}
            else:
                scalars[key] = float(s["value"])
    return scalars, hists, buckets


def _family_of(key: str) -> str:
    return key.split("{", 1)[0]


# ---------------------------------------------------------------------------
# pure query functions — they work on ANY sorted sample list (the live ring
# or segments loaded back from disk), which is what lets the dash and tests
# share one implementation with the recorder


def range_scan(samples: Sequence[dict], start_ts: Optional[float] = None,
               end_ts: Optional[float] = None) -> list[dict]:
    """Samples whose timestamp falls in ``[start_ts, end_ts]`` (either
    bound ``None`` = unbounded)."""
    out = []
    for s in samples:
        ts = float(s.get("ts", 0.0))
        if start_ts is not None and ts < start_ts:
            continue
        if end_ts is not None and ts > end_ts:
            continue
        out.append(s)
    return out


def _window(samples: Sequence[dict], window_s: Optional[float],
            now: Optional[float]) -> list[dict]:
    if not samples:
        return []
    if window_s is None or window_s <= 0:
        return list(samples)
    t = now if now is not None else float(samples[-1].get("ts", 0.0))
    return range_scan(samples, start_ts=t - float(window_s), end_ts=t)


def windowed_rate(samples: Sequence[dict], key: str,
                  window_s: Optional[float] = None,
                  now: Optional[float] = None) -> Optional[float]:
    """Per-second rate of a cumulative scalar series over the window:
    ``(last - first) / (t_last - t_first)`` between the window's first and
    last samples carrying the series.  ``None`` without two such samples
    (no data = no rate, never a fabricated zero)."""
    win = [s for s in _window(samples, window_s, now)
           if key in s.get("scalars", {})]
    if len(win) < 2:
        return None
    t0, t1 = float(win[0]["ts"]), float(win[-1]["ts"])
    if t1 <= t0:
        return None
    return (float(win[-1]["scalars"][key]) - float(win[0]["scalars"][key])) / (t1 - t0)


def value_series(samples: Sequence[dict], key: str) -> list[tuple[float, float]]:
    """``[(ts, value)]`` for one scalar series — the dash's curve input."""
    return [(float(s["ts"]), float(s["scalars"][key]))
            for s in samples if key in s.get("scalars", {})]


def hist_pnn(samples: Sequence[dict], key: str, q: float,
             buckets: Sequence[float],
             window_s: Optional[float] = None,
             now: Optional[float] = None) -> Optional[float]:
    """Bucket-interpolated percentile of the observations that landed
    WITHIN the window: per-bucket counts are differenced between the
    window's last and first samples, then walked to the ``q`` quantile
    with linear interpolation inside the bucket (the +Inf bucket reports
    the last finite bound).  ``q`` in (0, 1]."""
    win = [s for s in _window(samples, window_s, now)
           if key in s.get("hists", {})]
    if len(win) < 2 or not buckets:
        return None
    first = win[0]["hists"][key]["counts"]
    last = win[-1]["hists"][key]["counts"]
    delta = [max(0, int(b) - int(a)) for a, b in zip(first, last)]
    total = sum(delta)
    if total == 0:
        return None
    target = float(q) * total
    cumulative = 0
    lo = 0.0
    for bound, c in zip(buckets, delta):
        hi = float(bound)
        if c and cumulative + c >= target:
            if hi == float("inf"):
                return lo
            frac = (target - cumulative) / c
            return lo + frac * (hi - lo)
        cumulative += c
        if hi != float("inf"):
            lo = hi
    return lo


def rounds_to_target(rounds: Sequence[dict],
                     targets: Sequence[float] = _DEFAULT_TARGETS
                     ) -> dict[str, Optional[float]]:
    """First-crossing round per accuracy target over a convergence series
    (``None`` = never crossed) — the offline twin of the live gauge, so a
    loaded timeline answers rounds-to-accuracy without a running server."""
    out: dict[str, Optional[float]] = {f"{t:g}": None for t in targets}
    for r in rounds:
        acc = r.get("test_acc")
        if acc is None:
            continue
        idx = r.get("round_idx")
        idx = r.get("server_version") if idx is None else idx
        if idx is None:
            continue
        for t in targets:
            k = f"{t:g}"
            if out[k] is None and float(acc) >= float(t):
                out[k] = float(idx)
    return out


# ---------------------------------------------------------------------------


class TimelineRecorder:
    """Bounded in-ring performance timeline + atomic on-disk segments."""

    def __init__(self, out_dir: str, *, name: str = "server",
                 capacity: int = 512, interval_s: float = 1.0,
                 registry: Optional[obsreg.MetricsRegistry] = None,
                 runtime=None, targets: Sequence[float] = _DEFAULT_TARGETS,
                 meta: Optional[dict] = None):
        self.out_dir = os.path.abspath(str(out_dir))
        os.makedirs(self.out_dir, exist_ok=True)
        self.name = str(name)
        self.capacity = max(8, int(capacity))
        self.interval_s = max(0.01, float(interval_s))
        self.registry = registry or obsreg.REGISTRY
        self.runtime = runtime
        self.targets = tuple(float(t) for t in targets)
        self.meta = dict(meta or {})
        self._ring: deque = deque(maxlen=self.capacity)
        self._rounds: deque = deque(maxlen=4096)
        self._buckets: dict[str, list[float]] = {}
        # flush a segment every capacity/2 samples: pending stays bounded
        # and a full ring is always covered by at most two segments
        self._flush_every = max(4, self.capacity // 2)
        self._pending_samples: list[dict] = []
        self._pending_rounds: list[dict] = []
        self._crossed: dict[str, float] = {}
        self._lock = threading.Lock()
        self._seq = 0
        self._started = False
        self._closed = False

    # -- timer-wheel lifecycle ------------------------------------------------
    def start(self) -> "TimelineRecorder":
        if self.runtime is None:
            raise ValueError("TimelineRecorder.start needs a ServerRuntime")
        self._started = True
        self.runtime.arm(self, "timeline_tick", self.interval_s, self._tick)
        return self

    def _tick(self) -> None:
        if self._closed:
            return
        try:
            self.sample_now()
        except Exception:
            log.exception("timeline: sample tick failed")
        if not self._closed:
            self.runtime.arm(self, "timeline_tick", self.interval_s, self._tick)

    # -- intake ---------------------------------------------------------------
    def sample_now(self, now: Optional[float] = None) -> dict:
        """Take one registry snapshot into the ring (public so tests and
        harnesses can drive the recorder without a timer; ``now`` pins the
        sample timestamp for deterministic fixtures); returns the sample.
        Flushes a segment when enough samples are pending."""
        scalars, hists, buckets = _split_snapshot(self.registry.snapshot())
        sample = {"ts": round(float(now) if now is not None else time.time(), 6),
                  "scalars": scalars, "hists": hists}
        flush = False
        with self._lock:
            self._buckets.update(buckets)
            self._ring.append(sample)
            self._pending_samples.append(sample)
            flush = len(self._pending_samples) >= self._flush_every
        TIMELINE_SAMPLES.inc()
        if flush:
            self.flush()
        return sample

    def note_round(self, *, round_idx: Optional[int] = None,
                   server_version: Optional[int] = None,
                   test_acc: Optional[float] = None,
                   wall: Optional[float] = None) -> None:
        """Tee one finished round into the convergence series.  Never
        raises into the server's round path."""
        try:
            row = {"wall": round(float(wall if wall is not None else time.time()), 6)}
            if round_idx is not None:
                row["round_idx"] = int(round_idx)
            if server_version is not None:
                row["server_version"] = int(server_version)
            if test_acc is not None:
                row["test_acc"] = float(test_acc)
            with self._lock:
                self._rounds.append(row)
                self._pending_rounds.append(row)
            idx = row.get("round_idx", row.get("server_version"))
            if idx is not None:
                CONV_ROUND.set(float(idx))
            if test_acc is not None:
                CONV_TEST_ACC.set(float(test_acc))
                if idx is not None:
                    for t in self.targets:
                        k = f"{t:g}"
                        if k not in self._crossed and float(test_acc) >= t:
                            self._crossed[k] = float(idx)
                            ROUNDS_TO_TARGET.set(float(idx), target=k)
        except Exception:
            log.exception("timeline: note_round failed")

    # -- queries (delegate to the pure functions over the live ring) ----------
    def samples(self, start_ts: Optional[float] = None,
                end_ts: Optional[float] = None) -> list[dict]:
        with self._lock:
            ring = list(self._ring)
        return range_scan(ring, start_ts, end_ts)

    def rounds(self) -> list[dict]:
        with self._lock:
            return list(self._rounds)

    def latest(self, key: str) -> Optional[float]:
        with self._lock:
            ring = list(self._ring)
        for s in reversed(ring):
            if key in s.get("scalars", {}):
                return float(s["scalars"][key])
        return None

    def rate(self, key: str, window_s: Optional[float] = None,
             now: Optional[float] = None) -> Optional[float]:
        return windowed_rate(self.samples(), key, window_s, now)

    def pnn(self, key: str, q: float, window_s: Optional[float] = None,
            now: Optional[float] = None) -> Optional[float]:
        with self._lock:
            buckets = list(self._buckets.get(_family_of(key), ()))
        return hist_pnn(self.samples(), key, q, buckets, window_s, now)

    def crossed_targets(self) -> dict[str, float]:
        with self._lock:
            return dict(self._crossed)

    # -- segments -------------------------------------------------------------
    def flush(self) -> Optional[str]:
        """Write every pending sample/round as one atomic segment file;
        returns its path (``None`` when nothing is pending)."""
        with self._lock:
            samples, self._pending_samples = self._pending_samples, []
            rounds, self._pending_rounds = self._pending_rounds, []
            buckets = {k: list(v) for k, v in self._buckets.items()}
            self._seq += 1
            seq = self._seq
        if not samples and not rounds:
            with self._lock:
                self._seq -= 1
            return None
        body = {"samples": samples, "rounds": rounds, "buckets": buckets,
                "recorder": dict(self.meta)}
        meta = {
            "format": "fedml-timeline-v1",
            "name": self.name,
            "pid": os.getpid(),
            "seq": seq,
            "ts": round(time.time(), 6),
            "n_samples": len(samples),
            "n_rounds": len(rounds),
        }
        payload = json.dumps(body, sort_keys=True, default=str).encode()
        blob = _MAGIC + json.dumps(meta, sort_keys=True).encode() + b"\n" + payload
        fname = f"{self.name}.{os.getpid()}.{seq:06d}.tseg"
        fname = "".join(c if c.isalnum() or c in "._-" else "_" for c in fname)
        path = os.path.join(self.out_dir, fname)
        fd, tmp = tempfile.mkstemp(dir=self.out_dir, prefix=".tmp_", suffix=".tseg")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(blob)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)  # atomic: readers see a complete segment or none
        except OSError:
            with contextlib.suppress(OSError):
                os.remove(tmp)
            raise
        TIMELINE_SEGMENTS.inc()
        return path

    def close(self) -> None:
        """Final sample + flush, then release the timer.  Idempotent."""
        if self._closed:
            return
        self._closed = True
        try:
            # one end-of-run sample: even a run shorter than one tick
            # interval leaves a queryable timeline behind
            scalars, hists, buckets = _split_snapshot(self.registry.snapshot())
            sample = {"ts": round(time.time(), 6), "scalars": scalars,
                      "hists": hists}
            with self._lock:
                self._buckets.update(buckets)
                self._ring.append(sample)
                self._pending_samples.append(sample)
            TIMELINE_SAMPLES.inc()
        except Exception:
            log.exception("timeline: final sample failed")
        try:
            self.flush()
        except Exception:
            log.exception("timeline: final flush failed")
        if self._started and self.runtime is not None:
            self.runtime.cancel(self)


# ---------------------------------------------------------------------------
# segment IO


def read_segment(path: str) -> dict:
    """Parse one ``.tseg`` segment -> ``{"meta": {...}, "samples": [...],
    "rounds": [...], "buckets": {...}}``.  Raises ``ValueError`` on a
    foreign or torn file (callers skip those)."""
    with open(path, "rb") as f:
        blob = f.read()
    if not blob.startswith(_MAGIC):
        raise ValueError(f"{path}: not a timeline segment (bad magic)")
    rest = blob[len(_MAGIC):]
    nl = rest.find(b"\n")
    if nl < 0:
        raise ValueError(f"{path}: truncated header")
    meta = json.loads(rest[:nl].decode())
    body = json.loads(rest[nl + 1:].decode())
    body["meta"] = meta
    body["path"] = path
    return body


def list_segments(root: str) -> list[str]:
    """Every ``.tseg`` file under ``root`` (recursive), sorted."""
    out = []
    for dirpath, _dirs, files in os.walk(root):
        out.extend(os.path.join(dirpath, f) for f in files
                   if f.endswith(".tseg") and not f.startswith(".tmp_"))
    return sorted(out)


def load_timeline(root: str) -> dict:
    """Merge every readable segment under ``root`` into one timeline:
    samples sorted by ts, rounds sorted by wall, bucket maps unioned,
    torn/foreign files skipped (and counted in ``skipped``)."""
    samples: list[dict] = []
    rounds: list[dict] = []
    buckets: dict[str, list[float]] = {}
    metas: list[dict] = []
    skipped = 0
    for path in list_segments(root):
        try:
            seg = read_segment(path)
        except (ValueError, OSError, json.JSONDecodeError):
            skipped += 1
            continue
        samples.extend(seg.get("samples", ()))
        rounds.extend(seg.get("rounds", ()))
        buckets.update(seg.get("buckets", {}))
        metas.append(seg.get("meta", {}))
    samples.sort(key=lambda s: float(s.get("ts", 0.0)))
    rounds.sort(key=lambda r: float(r.get("wall", 0.0)))
    return {"samples": samples, "rounds": rounds, "buckets": buckets,
            "metas": metas, "skipped": skipped}


def timeline_from_config(cfg, *, name: str, runtime=None,
                         registry: Optional[obsreg.MetricsRegistry] = None,
                         meta: Optional[dict] = None
                         ) -> Optional[TimelineRecorder]:
    """The one gate: ``extra.perf_timeline`` unset/falsy -> ``None`` (no
    ring, no timer, no segments, bit-identical default path)."""
    if cfg is None or not cfg_extra(cfg, "perf_timeline"):
        return None
    out_dir = cfg_extra(cfg, "timeline_dir") or os.path.join(
        os.getcwd(), "perf_timeline")
    try:
        return TimelineRecorder(
            str(out_dir), name=name,
            capacity=int(cfg_extra(cfg, "timeline_capacity")),
            interval_s=float(cfg_extra(cfg, "timeline_interval_s")),
            registry=registry, runtime=runtime,
            meta={"run_id": str(getattr(cfg, "run_id", "")), **(meta or {})})
    except OSError as e:
        log.warning("timeline: recorder dir %s unusable (%s) — running "
                    "without the timeline", out_dir, e)
        return None
