"""Per-program device-time attribution (ISSUE 18).

PERF.md round-6 left attribution as a manual escape hatch — "run
``scripts/profile_trace.py`` if fused MFU < 0.14".  This module turns that
script into a layer the engine invokes itself: a
:class:`ProgramTimeAttributor` opens a programmatic
``jax.profiler.start_trace`` window around rounds ``k..k+n`` (behind
``extra.profile_rounds`` / ``profile_dir``), parses the captured trace,
and splits the window's time into

- **compile** — host-side XLA compilation events,
- **h2d** — data movement (transfers, infeed/outfeed, device copies),
- **device-compute** — everything the chip actually executed,
- **host-gap** — window wall time not covered by any of the above (the
  dispatch/bookkeeping bubble the roofline cannot see).

The engine notes every program that ran inside the window together with
its PR-16 cost-model FLOPs (``fedml_program_flops``), so the attribution
joins analytic cost against measured device time and cross-checks the
live ``fedml_sim_mfu`` gauge: ``mfu_cost_model`` (cost-model FLOPs /
device-compute time / chip peak) landing far from ``sim_mfu_gauge``
means the wall-clock denominator is hiding host time — exactly the
signal the manual workflow existed to surface.

Everything degrades gracefully: no profiler support, an unparseable
trace, or a dead trace dir each leave a warning and a window without
attribution — never an exception into the round path.  Gating is
absolute: :func:`profiler_from_config` returns ``None`` unless
``extra.profile_rounds`` parses.
"""

from __future__ import annotations

import contextlib
import collections
import glob
import gzip
import json
import logging
import os
import tempfile
import time
from typing import Any, Optional

from ..core.flags import cfg_extra
from . import registry as obsreg

log = logging.getLogger("fedml_tpu.obs.profiler")

__all__ = [
    "ProgramTimeAttributor", "profiler_from_config", "parse_profile_rounds",
    "find_trace_file", "load_trace", "aggregate_device_events",
    "split_time_buckets", "bucket_rows",
]

PROFILE_WINDOWS = obsreg.REGISTRY.counter(
    "fedml_profile_windows_total",
    "Programmatic profiler trace windows completed, by outcome (attributed "
    "= trace parsed; unparsed = window closed but no readable trace).",
    labels=("outcome",),
)
PROFILE_DEVICE_SECONDS = obsreg.REGISTRY.gauge(
    "fedml_profile_device_seconds",
    "Window time split by the attributor: compile / h2d / device_compute / "
    "host_gap seconds of the last completed profile window.",
    labels=("category",),
)
PROFILE_MFU = obsreg.REGISTRY.gauge(
    "fedml_profile_mfu",
    "MFU cross-checked from the profile window: cost-model program FLOPs "
    "over measured device-compute time over chip peak (compare against "
    "fedml_sim_mfu, whose denominator is host-inclusive wall time).",
)

#: hlo categories / event-name fragments that are data movement, not compute
_H2D_CATEGORIES = ("copy", "infeed", "outfeed", "host send", "host recv")
_H2D_NAME_FRAGMENTS = ("transferto", "transferfrom", "copy")
_COMPILE_NAME_FRAGMENTS = ("compile", "xlacompile", "pjitcompil")


def parse_profile_rounds(value: Any) -> Optional[tuple[int, int]]:
    """``'n'`` -> rounds ``[0, n)``; ``'k:n'`` -> ``[k, k+n)``; ``None`` /
    unparseable / empty window -> ``None`` (the gate)."""
    if value is None:
        return None
    try:
        text = str(value).strip()
        if not text:
            return None
        if ":" in text:
            k_s, n_s = text.split(":", 1)
            k, n = int(k_s), int(n_s)
        else:
            k, n = 0, int(text)
        if n <= 0 or k < 0:
            return None
        return (k, k + n)
    except (TypeError, ValueError):
        log.warning("profiler: unparseable profile_rounds %r — disabled", value)
        return None


# ---------------------------------------------------------------------------
# trace parsing — the library `scripts/profile_trace.py` now wraps


def find_trace_file(root: str) -> Optional[str]:
    """Newest ``*.trace.json.gz`` under ``root/plugins/profile/*/`` (the
    layout ``jax.profiler`` writes); ``None`` when nothing captured."""
    runs = glob.glob(os.path.join(root, "plugins", "profile", "*", ""))
    if not runs:
        return None
    latest = max(runs, key=os.path.getmtime)
    traces = glob.glob(os.path.join(latest, "*.trace.json.gz"))
    return traces[0] if traces else None


def load_trace(path: str) -> dict:
    with gzip.open(path) as f:
        return json.load(f)


def _device_pids(trace: dict) -> set:
    pids = {e["pid"]: (e.get("args") or {}).get("name", "")
            for e in trace.get("traceEvents", [])
            if e.get("ph") == "M" and e.get("name") == "process_name"}
    return {p for p, n in pids.items() if "TPU" in n or "device" in n.lower()}


def aggregate_device_events(trace: dict) -> dict:
    """Aggregate device-pid ``X`` events by hlo_category and source line:
    ``{key: [duration_ps, flops, bytes, n]}`` per bucket, plus host-side
    compile time — the same aggregation the round-4 script printed, now
    returned as data."""
    dev_pids = _device_pids(trace)
    cat: dict = collections.defaultdict(lambda: [0, 0, 0, 0])
    src: dict = collections.defaultdict(lambda: [0, 0, 0, 0])
    compile_ps = 0
    for e in trace.get("traceEvents", []):
        a = e.get("args") or {}
        if e.get("ph") != "X":
            continue
        if e.get("pid") in dev_pids and "hlo_category" in a:
            c = a["hlo_category"]
            if c == "while":
                continue
            d = int(a.get("device_duration_ps", 0))
            fl = int(a.get("model_flops", 0) or 0)
            by = int(a.get("raw_bytes_accessed", 0) or 0)
            for bucket, key in ((cat, c), (src, a.get("source", "?"))):
                bucket[key][0] += d
                bucket[key][1] += fl
                bucket[key][2] += by
                bucket[key][3] += 1
        elif e.get("pid") not in dev_pids:
            name = str(e.get("name", "")).lower()
            if any(f in name for f in _COMPILE_NAME_FRAGMENTS):
                # host durations are microseconds in the chrome trace format
                compile_ps += int(float(e.get("dur", 0)) * 1e6)
    return {"by_category": dict(cat), "by_source": dict(src),
            "compile_ps": compile_ps}


def split_time_buckets(aggregated: dict, wall_s: float) -> dict:
    """The four-way split: compile / h2d / device_compute / host_gap
    seconds over a window of ``wall_s`` wall seconds."""
    h2d_ps = 0
    compute_ps = 0
    for key, (d, _fl, _by, _n) in aggregated.get("by_category", {}).items():
        k = str(key).lower()
        if any(f in k for f in _H2D_CATEGORIES) or any(
                f in k for f in _H2D_NAME_FRAGMENTS):
            h2d_ps += d
        else:
            compute_ps += d
    compile_s = aggregated.get("compile_ps", 0) / 1e12
    h2d_s = h2d_ps / 1e12
    compute_s = compute_ps / 1e12
    host_gap_s = max(0.0, float(wall_s) - compile_s - h2d_s - compute_s)
    return {"compile_s": round(compile_s, 6), "h2d_s": round(h2d_s, 6),
            "device_compute_s": round(compute_s, 6),
            "host_gap_s": round(host_gap_s, 6)}


def bucket_rows(bucket: dict, top: int) -> list[dict]:
    """Render one aggregation bucket as sorted report rows (achieved
    TFLOP/s and GB/s per key) — shared by the attributor and the script."""
    out = []
    for k, (d, fl, by, n) in sorted(bucket.items(), key=lambda kv: -kv[1][0])[:top]:
        out.append({
            "key": k, "ms": round(d / 1e9, 2), "n": n,
            "tflops": round(fl / (d / 1e12) / 1e12, 2) if d else 0,
            "gbps": round(by / (d / 1e12) / 1e9, 1) if d else 0,
        })
    return out


# ---------------------------------------------------------------------------


class ProgramTimeAttributor:
    """One profile window around rounds ``[start, end)``: trace, parse,
    attribute, cross-check MFU, write the attribution JSON."""

    def __init__(self, out_dir: str, *, window: tuple[int, int],
                 name: str = "sim",
                 registry: Optional[obsreg.MetricsRegistry] = None,
                 peak_flops: Optional[float] = None):
        self.out_dir = os.path.abspath(str(out_dir))
        os.makedirs(self.out_dir, exist_ok=True)
        self.name = str(name)
        self.window = (int(window[0]), int(window[1]))
        self.registry = registry or obsreg.REGISTRY
        self.peak_flops = peak_flops
        self.attribution: Optional[dict] = None
        self.attribution_path: Optional[str] = None
        self._programs: list[dict] = []
        self._active = False
        self._done = False
        self._wall_start = 0.0

    # -- window lifecycle (the engine drives these around round chunks) ------
    def maybe_start(self, round_idx: int) -> bool:
        """Open the trace when ``round_idx`` enters the window.  Returns
        whether the window is active after the call."""
        if self._active:
            return True
        if self._done or not (self.window[0] <= int(round_idx) < self.window[1]):
            return False
        try:
            import jax

            jax.profiler.start_trace(self.out_dir)
        except Exception as e:
            log.warning("profiler: start_trace failed (%s: %s) — window "
                        "disabled", type(e).__name__, e)
            self._done = True
            return False
        self._active = True
        self._wall_start = time.time()
        return True

    def note_program(self, program: str, *, flops: Optional[float] = None,
                     rounds: Optional[int] = None) -> None:
        """Record one program execution inside the window (the join key
        against the cost-model gauges)."""
        if not self._active:
            return
        self._programs.append({
            "program": str(program),
            "flops": float(flops) if flops else None,
            "rounds": int(rounds) if rounds else None,
        })

    def maybe_stop(self, next_round_idx: int) -> Optional[dict]:
        """Close the window once the next round falls past its end;
        returns the attribution (``None`` while still open / unparsed)."""
        if not self._active or int(next_round_idx) < self.window[1]:
            return None
        return self.finalize()

    def finalize(self) -> Optional[dict]:
        """Stop the trace (if open), parse, attribute, export gauges."""
        if not self._active:
            return self.attribution
        self._active = False
        self._done = True
        wall_s = time.time() - self._wall_start
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception as e:
            log.warning("profiler: stop_trace failed (%s: %s)",
                        type(e).__name__, e)
            PROFILE_WINDOWS.inc(outcome="unparsed")
            return None
        self.attribution = self._attribute(wall_s)
        outcome = "attributed" if self.attribution is not None else "unparsed"
        PROFILE_WINDOWS.inc(outcome=outcome)
        return self.attribution

    # -- attribution ----------------------------------------------------------
    def _attribute(self, wall_s: float) -> Optional[dict]:
        trace_file = find_trace_file(self.out_dir)
        if trace_file is None:
            log.warning("profiler: no trace captured under %s", self.out_dir)
            return None
        try:
            aggregated = aggregate_device_events(load_trace(trace_file))
        except Exception as e:
            log.warning("profiler: trace %s unparseable (%s: %s)",
                        trace_file, type(e).__name__, e)
            return None
        buckets = split_time_buckets(aggregated, wall_s)
        for category, seconds in buckets.items():
            PROFILE_DEVICE_SECONDS.set(seconds,
                                       category=category.rsplit("_s", 1)[0])
        compute_s = buckets["device_compute_s"]
        cost_flops = sum(p["flops"] for p in self._programs if p["flops"])
        programs = []
        for p in self._programs:
            row = dict(p)
            if p["flops"] and cost_flops and compute_s:
                share = p["flops"] / cost_flops
                row["share_device_s"] = round(share * compute_s, 6)
            programs.append(row)
        mfu_cost_model = None
        if cost_flops and compute_s and self.peak_flops:
            mfu_cost_model = cost_flops / compute_s / float(self.peak_flops)
            PROFILE_MFU.set(mfu_cost_model)
        trace_flops = sum(v[1] for v in aggregated["by_category"].values())
        mfu_trace = None
        if trace_flops and compute_s and self.peak_flops:
            mfu_trace = trace_flops / compute_s / float(self.peak_flops)
        sim_mfu = None
        fam = self.registry.get("fedml_sim_mfu")
        if fam is not None:
            with contextlib.suppress(Exception):
                sim_mfu = float(fam.value())
        attribution = {
            "window": {"start_round": self.window[0],
                       "end_round": self.window[1],
                       "wall_s": round(wall_s, 6)},
            "buckets": buckets,
            "by_category": bucket_rows(aggregated["by_category"], 8),
            "by_source": bucket_rows(aggregated["by_source"], 12),
            "programs": programs,
            "cost_model_flops": cost_flops or None,
            "trace_model_flops": trace_flops or None,
            "chip_peak_flops": self.peak_flops,
            "mfu_cost_model": round(mfu_cost_model, 6) if mfu_cost_model else None,
            "mfu_trace": round(mfu_trace, 6) if mfu_trace else None,
            "sim_mfu_gauge": round(sim_mfu, 6) if sim_mfu else None,
            "trace_file": trace_file,
        }
        self.attribution_path = self._write(attribution)
        return attribution

    def _write(self, attribution: dict) -> Optional[str]:
        path = os.path.join(
            self.out_dir, f"{self.name}.{os.getpid()}.attribution.json")
        try:
            fd, tmp = tempfile.mkstemp(dir=self.out_dir, prefix=".tmp_",
                                       suffix=".json")
            with os.fdopen(fd, "w") as f:
                json.dump(attribution, f, sort_keys=True, indent=1, default=str)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            return path
        except OSError as e:
            log.warning("profiler: attribution write failed (%s)", e)
            return None


def profiler_from_config(cfg, *, name: str = "sim",
                         peak_flops: Optional[float] = None
                         ) -> Optional[ProgramTimeAttributor]:
    """The one gate: ``extra.profile_rounds`` unset/unparseable ->
    ``None`` (no trace, no window, bit-identical default path)."""
    if cfg is None:
        return None
    window = parse_profile_rounds(cfg_extra(cfg, "profile_rounds"))
    if window is None:
        return None
    out_dir = cfg_extra(cfg, "profile_dir") or os.path.join(
        os.getcwd(), "profile_traces")
    try:
        return ProgramTimeAttributor(str(out_dir), window=window, name=name,
                                     peak_flops=peak_flops)
    except OSError as e:
        log.warning("profiler: dir %s unusable (%s) — running without the "
                    "attributor", out_dir, e)
        return None
