"""OTLP/HTTP JSON export sink — stdlib only, no opentelemetry-sdk.

PR 1 made rounds traceable but the data dead-ended on the local machine
(JSONL trails + a /metrics endpoint).  This module is the egress: it maps
the obs layer's native shapes onto the OpenTelemetry protocol's proto3-JSON
encoding (OTLP/HTTP, ``Content-Type: application/json``), which every
standard collector (otel-collector, Jaeger all-in-one, Grafana Alloy,
vendor OTLP endpoints) accepts on ``/v1/traces`` and ``/v1/metrics``:

- ``Span.to_record()`` dicts -> ``resourceSpans``: trace/span ids
  zero-padded to the protocol's 32/16 hex chars, wall clocks to unix-nano
  strings, leftover record keys to typed attributes;
- ``MetricsRegistry.snapshot()`` -> ``resourceMetrics``: Counter ->
  monotonic cumulative sum, Gauge -> gauge, Histogram -> histogram data
  points with explicit bounds (the +Inf bucket becomes the overflow count).

:class:`OTLPExporter` is a batched background worker over a bounded queue
with exponential-backoff retry on 429/5xx and connection errors.  Its
shipped/dropped/retried counts land back in the SAME registry it exports,
so telemetry loss is itself observable.  ``exporter_from_config`` gates the
whole thing on ``extra.otlp_endpoint`` (or ``FEDML_TPU_OTLP_ENDPOINT``):
unset means no exporter object and no thread — the default path is
untouched.  ``export_jsonl_trail`` backfills a recorded collector trail
(``fedml-tpu obs export``).
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
import time
import urllib.error
import urllib.request
from collections import deque
from typing import Iterable, Optional

from . import registry as obsreg

__all__ = [
    "OTLPExporter", "exporter_from_config", "export_jsonl_trail",
    "span_record_to_otlp", "spans_to_otlp", "metrics_snapshot_to_otlp",
    "trail_metrics_to_otlp", "post_otlp", "otlp_counters",
]

_INF = float("inf")

#: exporter self-telemetry, in the registry the exporter itself ships
OTLP_SHIPPED = obsreg.REGISTRY.counter(
    "fedml_otlp_shipped_total",
    "Spans / metric data points delivered to the OTLP collector.",
    labels=("signal",),
)
OTLP_DROPPED = obsreg.REGISTRY.counter(
    "fedml_otlp_dropped_total",
    "Spans / metric data points lost (bounded queue full, non-retryable "
    "status, or retry budget exhausted).",
    labels=("signal", "reason"),
)
OTLP_RETRIED = obsreg.REGISTRY.counter(
    "fedml_otlp_retried_total",
    "OTLP export requests retried after 429/5xx or a connection failure.",
)


# ---------------------------------------------------------------------------
# shape mapping: obs records -> OTLP proto3-JSON


def _hex_id(value, width: int) -> str:
    """Normalize an id to the OTLP hex width (32 for traces, 16 for spans).
    Native ids are 16-hex ``secrets.token_hex(8)`` — zero-padded on the
    left; foreign/non-hex ids (hand-written trails) hash deterministically
    so parent/child links still line up after conversion."""
    s = str(value if value is not None else "").strip().lower()
    if not s:
        return ""
    if all(c in "0123456789abcdef" for c in s):
        return s[-width:].zfill(width)
    return hashlib.sha256(s.encode()).hexdigest()[:width]


def _any_value(v) -> dict:
    """proto3-JSON AnyValue (int64 is a string in the JSON encoding)."""
    if isinstance(v, bool):
        return {"boolValue": v}
    if isinstance(v, int):
        return {"intValue": str(v)}
    if isinstance(v, float):
        return {"doubleValue": v}
    if isinstance(v, str):
        return {"stringValue": v}
    return {"stringValue": json.dumps(v, default=str)}


def _attrs(d: dict) -> list:
    return [{"key": str(k), "value": _any_value(v)} for k, v in d.items()]


def _num(v, default: float = 0.0) -> float:
    try:
        return float(v)
    except (TypeError, ValueError):
        return default


_SPAN_CORE_KEYS = frozenset({"kind", "name", "trace_id", "span_id", "parent_id",
                             "ts", "dur_s"})


def span_record_to_otlp(rec: dict) -> dict:
    """One ``Span.to_record()``-shaped dict -> one OTLP JSON Span."""
    ts = _num(rec.get("ts"))
    dur = _num(rec.get("dur_s"))
    start_ns = int(ts * 1e9)
    span = {
        "traceId": _hex_id(rec.get("trace_id"), 32),
        "spanId": _hex_id(rec.get("span_id"), 16),
        "name": str(rec.get("name", "")),
        "kind": 1,  # SPAN_KIND_INTERNAL
        "startTimeUnixNano": str(start_ns),
        "endTimeUnixNano": str(start_ns + int(dur * 1e9)),
        "attributes": _attrs({k: v for k, v in rec.items()
                              if k not in _SPAN_CORE_KEYS and v is not None}),
    }
    parent = _hex_id(rec.get("parent_id"), 16)
    if parent:
        span["parentSpanId"] = parent
    return span


def _resource(service_name: str, resource_attributes: Optional[dict]) -> dict:
    return {"attributes": _attrs({"service.name": service_name,
                                  **(resource_attributes or {})})}


def spans_to_otlp(records: Iterable[dict], service_name: str = "fedml-tpu",
                  resource_attributes: Optional[dict] = None,
                  scope: str = "fedml_tpu.obs") -> tuple[dict, int]:
    """Span records -> an ``ExportTraceServiceRequest`` JSON body.  Returns
    (payload, span count); non-span / id-less records are skipped."""
    spans = [span_record_to_otlp(r) for r in records
             if r.get("kind") == "span" and r.get("trace_id") and r.get("span_id")]
    payload = {"resourceSpans": [{
        "resource": _resource(service_name, resource_attributes),
        "scopeSpans": [{"scope": {"name": scope}, "spans": spans}],
    }]}
    return payload, len(spans)


def metrics_snapshot_to_otlp(snapshot: list[dict], service_name: str = "fedml-tpu",
                             resource_attributes: Optional[dict] = None,
                             scope: str = "fedml_tpu.obs.registry",
                             time_unix_nano: Optional[int] = None) -> tuple[dict, int]:
    """``MetricsRegistry.snapshot()`` -> an ``ExportMetricsServiceRequest``
    JSON body.  Counter -> cumulative monotonic sum, Gauge -> gauge,
    Histogram -> histogram with explicit bounds.  Returns (payload, number
    of data points)."""
    now = str(time_unix_nano if time_unix_nano is not None else int(time.time() * 1e9))
    metrics, n_points = [], 0
    for fam in snapshot:
        kind = fam.get("kind")
        if kind == "histogram":
            bounds = [b for b in fam.get("buckets", ()) if b != _INF]
            dps = [{
                "attributes": _attrs(s["labels"]),
                "timeUnixNano": now,
                "count": str(int(s["count"])),
                "sum": float(s["sum"]),
                "bucketCounts": [str(int(c)) for c in s["counts"]],
                "explicitBounds": bounds,
            } for s in fam["samples"]]
            body = {"histogram": {"dataPoints": dps, "aggregationTemporality": 2}}
        else:
            dps = [{"attributes": _attrs(s["labels"]), "timeUnixNano": now,
                    "asDouble": float(s["value"])} for s in fam["samples"]]
            if kind == "counter":
                body = {"sum": {"dataPoints": dps, "aggregationTemporality": 2,
                                "isMonotonic": True}}
            else:  # gauge / untyped
                body = {"gauge": {"dataPoints": dps}}
        metrics.append({"name": fam["name"], "description": fam.get("help", ""),
                        **body})
        n_points += len(dps)
    payload = {"resourceMetrics": [{
        "resource": _resource(service_name, resource_attributes),
        "scopeMetrics": [{"scope": {"name": scope}, "metrics": metrics}],
    }]}
    return payload, n_points


def trail_metrics_to_otlp(records: Iterable[dict], service_name: str = "fedml-tpu",
                          resource_attributes: Optional[dict] = None,
                          scope: str = "fedml_tpu.obs.trail") -> tuple[dict, int]:
    """Collector-trail ``kind: metric`` records (``{"metric": name,
    "value": x, ...}``) -> gauge data points, grouped per metric name —
    the backfill half of ``fedml-tpu obs export``.  Records without a name
    or a numeric value are skipped."""
    by_name: dict[str, list] = {}
    n_points = 0
    for rec in records:
        if rec.get("kind") != "metric":
            continue
        name = rec.get("metric")
        value = rec.get("value")
        if not name or not isinstance(value, (int, float)) or isinstance(value, bool):
            continue
        extra = {k: v for k, v in rec.items()
                 if k not in ("kind", "metric", "value", "ts") and v is not None}
        by_name.setdefault(str(name), []).append({
            "attributes": _attrs(extra),
            "timeUnixNano": str(int(_num(rec.get("ts"), time.time()) * 1e9)),
            "asDouble": float(value),
        })
        n_points += 1
    metrics = [{"name": name, "description": "backfilled from a collector JSONL trail",
                "gauge": {"dataPoints": dps}} for name, dps in sorted(by_name.items())]
    payload = {"resourceMetrics": [{
        "resource": _resource(service_name, resource_attributes),
        "scopeMetrics": [{"scope": {"name": scope}, "metrics": metrics}],
    }]}
    return payload, n_points


# ---------------------------------------------------------------------------
# transport


def post_otlp(url: str, payload: dict, timeout_s: float = 10.0,
              max_retries: int = 4, backoff_base_s: float = 0.25,
              backoff_max_s: float = 10.0, headers: Optional[dict] = None,
              on_retry=None, protocol: str = "json") -> Optional[int]:
    """POST one OTLP body — ``protocol="json"`` (proto3-JSON, the default)
    or ``"protobuf"`` (binary wire format via :mod:`.otlp_proto`, for
    collectors that reject JSON); exponential-backoff retry on 429/5xx and
    connection errors.  Returns the final HTTP status, or None when every
    attempt failed at the connection level."""
    if protocol == "protobuf":
        from . import otlp_proto
        body = otlp_proto.encode_request(payload)
        content_type = "application/x-protobuf"
    else:
        body = json.dumps(payload).encode("utf-8")
        content_type = "application/json"
    delay = backoff_base_s
    status: Optional[int] = None
    for attempt in range(max_retries + 1):
        req = urllib.request.Request(
            url, data=body, method="POST",
            headers={"Content-Type": content_type, **(headers or {})},
        )
        try:
            with urllib.request.urlopen(req, timeout=timeout_s) as resp:
                status = resp.status
        except urllib.error.HTTPError as e:
            status = e.code
        except (OSError, urllib.error.URLError):
            status = None  # connection-level failure: retryable
        if status is not None and 200 <= status < 300:
            return status
        retryable = status is None or status == 429 or status >= 500
        if not retryable or attempt == max_retries:
            return status
        if on_retry is not None:
            try:
                on_retry()
            except Exception:
                pass
        time.sleep(delay)
        delay = min(delay * 2.0, backoff_max_s)
    return status


class OTLPExporter:
    """Batched background OTLP/HTTP exporter over a bounded queue.

    ``enqueue_span(record)`` never blocks the caller: a full queue drops
    the record (counted, reason ``queue_full``).  The daemon worker drains
    up to ``batch_size`` records per request to ``/v1/traces``; a request
    that still fails after the retry budget drops its batch (counted).
    ``export_metrics_now()`` ships the registry snapshot to ``/v1/metrics``
    on the caller's thread; ``close()`` drains the span queue, ships a
    final snapshot, and joins the worker.
    """

    def __init__(self, endpoint: str, registry: Optional[obsreg.MetricsRegistry] = None,
                 service_name: str = "fedml-tpu",
                 resource_attributes: Optional[dict] = None,
                 queue_size: int = 4096, batch_size: int = 256,
                 flush_interval_s: float = 1.0, max_retries: int = 4,
                 backoff_base_s: float = 0.25, backoff_max_s: float = 10.0,
                 timeout_s: float = 5.0, headers: Optional[dict] = None,
                 protocol: str = "json"):
        if protocol not in ("json", "protobuf", "auto"):
            raise ValueError(f"otlp protocol must be json|protobuf|auto, got {protocol!r}")
        self.endpoint = endpoint.rstrip("/")
        self.registry = registry or obsreg.REGISTRY
        self.service_name = service_name
        self.resource_attributes = dict(resource_attributes or {})
        self.protocol = protocol
        # "auto" starts on JSON and falls back to protobuf the first time a
        # collector rejects the encoding (415/400); sticky once flipped
        self._wire = "protobuf" if protocol == "protobuf" else "json"
        self.queue_size = int(queue_size)
        self.batch_size = int(batch_size)
        self.flush_interval_s = float(flush_interval_s)
        self._post_kw = dict(timeout_s=timeout_s, max_retries=max_retries,
                             backoff_base_s=backoff_base_s,
                             backoff_max_s=backoff_max_s, headers=headers)
        self._q: deque = deque()
        self._cv = threading.Condition()
        self._inflight = 0
        self._stop = threading.Event()
        self._closed = False
        self._thread = threading.Thread(target=self._worker,
                                        name="fedml-otlp-export", daemon=True)
        self._thread.start()

    # -- producers ------------------------------------------------------------
    def enqueue_span(self, record: dict) -> bool:
        with self._cv:
            if len(self._q) >= self.queue_size:
                OTLP_DROPPED.inc(signal="traces", reason="queue_full")
                return False
            self._q.append(dict(record))
            self._cv.notify()
        return True

    def tee(self, sender, batch: Iterable[dict]) -> None:
        """``ObsCollector.ingest`` tap: queue every span record of a
        collector batch, stamped with its sender rank."""
        for rec in batch:
            if isinstance(rec, dict) and rec.get("kind") == "span" and rec.get("trace_id"):
                self.enqueue_span({"sender": sender, **rec})

    # -- shipping -------------------------------------------------------------
    def _post(self, url: str, payload: dict) -> Optional[int]:
        status = post_otlp(url, payload, on_retry=OTLP_RETRIED.inc,
                           protocol=self._wire, **self._post_kw)
        if (self.protocol == "auto" and self._wire == "json"
                and status in (400, 415)):
            self._wire = "protobuf"  # graftlint: disable=GL008(monotone one-way flip json->protobuf, idempotent under races: two threads flipping concurrently write the same value, and the worst stale read costs one extra JSON POST that the collector 415s and this branch re-sends)
            status = post_otlp(url, payload, on_retry=OTLP_RETRIED.inc,
                               protocol=self._wire, **self._post_kw)
        return status

    def _send_spans(self, batch: list[dict]) -> None:
        payload, n = spans_to_otlp(batch, service_name=self.service_name,
                                   resource_attributes=self.resource_attributes)
        if not n:
            return
        status = self._post(self.endpoint + "/v1/traces", payload)
        if status is not None and 200 <= status < 300:
            OTLP_SHIPPED.inc(n, signal="traces")
        else:
            reason = "retries_exhausted" if (status is None or status == 429
                                             or status >= 500) else "rejected"
            OTLP_DROPPED.inc(n, signal="traces", reason=reason)

    def export_metrics_now(self, snapshot: Optional[list[dict]] = None) -> bool:
        """Ship a registry snapshot to ``/v1/metrics`` (caller's thread)."""
        payload, n = metrics_snapshot_to_otlp(
            snapshot if snapshot is not None else self.registry.snapshot(),
            service_name=self.service_name,
            resource_attributes=self.resource_attributes,
        )
        status = self._post(self.endpoint + "/v1/metrics", payload)
        ok = status is not None and 200 <= status < 300
        if ok:
            OTLP_SHIPPED.inc(max(n, 1), signal="metrics")
        else:
            reason = "retries_exhausted" if (status is None or status == 429
                                             or status >= 500) else "rejected"
            OTLP_DROPPED.inc(max(n, 1), signal="metrics", reason=reason)
        return ok

    def _worker(self) -> None:
        while True:
            with self._cv:
                if not self._q and not self._stop.is_set():
                    self._cv.wait(self.flush_interval_s)
                batch = [self._q.popleft()
                         for _ in range(min(len(self._q), self.batch_size))]
                if batch:
                    self._inflight += 1
                stopping = self._stop.is_set()
            if batch:
                try:
                    self._send_spans(batch)
                finally:
                    with self._cv:
                        self._inflight -= 1
                        self._cv.notify_all()
            elif stopping:
                return

    def flush(self, timeout: float = 10.0) -> bool:
        """Block until the span queue is drained (or ``timeout``)."""
        deadline = time.monotonic() + timeout
        with self._cv:
            self._cv.notify_all()
            while (self._q or self._inflight) and time.monotonic() < deadline:
                self._cv.wait(0.05)
            return not self._q and not self._inflight

    def close(self, timeout: float = 15.0) -> None:
        """Drain remaining spans, ship a final metrics snapshot, stop the
        worker.  Idempotent; telemetry shutdown must never raise."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        with self._cv:
            self._cv.notify_all()
        self._thread.join(timeout=timeout)
        try:
            self.export_metrics_now()
        except Exception:
            pass


def exporter_from_config(cfg, **kwargs) -> Optional[OTLPExporter]:
    """The gate: an exporter (and its worker thread) exists ONLY when
    ``cfg.extra['otlp_endpoint']`` or ``$FEDML_TPU_OTLP_ENDPOINT`` is set;
    otherwise None and the default path is byte-for-byte unchanged.

    Multi-tenant configs (``extra.mt_job_id`` set by ``tenant_config``)
    stamp the job onto the exporter's OTLP *resource* — without it, every
    tenant's exporter shipped an identical ``service.name=fedml-tpu``
    resource and per-job series collapsed at the collector."""
    from ..core.flags import cfg_extra

    endpoint = cfg_extra(cfg, "otlp_endpoint") or os.environ.get("FEDML_TPU_OTLP_ENDPOINT")
    if not endpoint:
        return None
    kwargs.setdefault("protocol", str(cfg_extra(cfg, "otlp_protocol") or "json"))
    job = cfg_extra(cfg, "mt_job_id")
    if job:
        attrs = dict(kwargs.get("resource_attributes") or {})
        attrs.setdefault("job", str(job))
        attrs.setdefault("service.instance.id", f"job_{job}")
        kwargs["resource_attributes"] = attrs
    return OTLPExporter(str(endpoint), **kwargs)


def export_jsonl_trail(endpoint: str, records: list[dict], *,
                       batch_size: int = 512, timeout_s: float = 10.0,
                       max_retries: int = 4, service_name: str = "fedml-tpu",
                       resource_attributes: Optional[dict] = None) -> dict:
    """Backfill a recorded collector JSONL trail into an OTLP collector:
    span records to ``/v1/traces`` in batches, numeric metric records to
    ``/v1/metrics`` as gauges.  Returns a shipped/failed summary
    (``fedml-tpu obs export`` prints it)."""
    endpoint = endpoint.rstrip("/")
    kw = dict(timeout_s=timeout_s, max_retries=max_retries,
              on_retry=OTLP_RETRIED.inc)
    spans = [r for r in records
             if r.get("kind") == "span" and r.get("trace_id") and r.get("span_id")]
    shipped = failed = requests = 0
    for i in range(0, len(spans), batch_size):
        payload, n = spans_to_otlp(spans[i:i + batch_size],
                                   service_name=service_name,
                                   resource_attributes=resource_attributes)
        status = post_otlp(endpoint + "/v1/traces", payload, **kw)
        requests += 1
        if status is not None and 200 <= status < 300:
            shipped += n
            OTLP_SHIPPED.inc(n, signal="traces")
        else:
            failed += n
            OTLP_DROPPED.inc(n, signal="traces", reason="retries_exhausted")
    m_payload, m_points = trail_metrics_to_otlp(
        records, service_name=service_name, resource_attributes=resource_attributes)
    m_shipped = m_failed = 0
    if m_points:
        status = post_otlp(endpoint + "/v1/metrics", m_payload, **kw)
        requests += 1
        if status is not None and 200 <= status < 300:
            m_shipped = m_points
            OTLP_SHIPPED.inc(m_points, signal="metrics")
        else:
            m_failed = m_points
            OTLP_DROPPED.inc(m_points, signal="metrics", reason="retries_exhausted")
    return {"endpoint": endpoint, "requests": requests,
            "spans_shipped": shipped, "spans_failed": failed,
            "metric_points_shipped": m_shipped, "metric_points_failed": m_failed}


def otlp_counters() -> dict:
    """Exporter self-telemetry totals — ``bench.py`` attaches this so the
    perf trajectory records telemetry overhead."""
    out = {}
    for key, metric in (("shipped", OTLP_SHIPPED), ("dropped", OTLP_DROPPED),
                        ("retried", OTLP_RETRIED)):
        fam = metric._snapshot()
        out[key] = round(sum(s["value"] for s in fam["samples"]), 6)
    return out
